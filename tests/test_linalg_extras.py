"""norm / expm_multiply / svds vs the scipy oracle.

Beyond-reference surface (docs/PARITY.md): the reference exposes none of
these; scipy.sparse.linalg users expect them.
"""

import numpy as np
import pytest
import scipy.sparse as sp
import scipy.sparse.linalg as sla

import sparse_tpu as sparse
import sparse_tpu.linalg as linalg
from .utils.sample import sample_csr


@pytest.mark.parametrize("ord_", [None, "fro", 1, -1, np.inf, -np.inf])
def test_norm_matrix(ord_):
    s = sample_csr(23, 17, density=0.3, seed=60)
    s.data -= 0.5
    A = sparse.csr_array(s)
    got = float(np.asarray(linalg.norm(A, ord=ord_)))
    want = sla.norm(s, ord=ord_)
    assert np.isclose(got, want, rtol=1e-12)


@pytest.mark.parametrize("axis", [0, 1])
@pytest.mark.parametrize("ord_", [None, 1, np.inf])
def test_norm_axis(axis, ord_):
    s = sample_csr(12, 9, density=0.4, seed=61)
    s.data -= 0.5
    A = sparse.csr_array(s)
    got = np.asarray(linalg.norm(A, ord=ord_, axis=axis))
    want = sla.norm(s, ord=ord_ if ord_ is not None else 2, axis=axis)
    np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-14)


@pytest.mark.parametrize("t", [1.0, 0.3, -0.7])
def test_expm_multiply_vector(t):
    s = sample_csr(40, 40, density=0.1, seed=62)
    s.data -= 0.5
    A = sparse.csr_array(s)
    v = np.linspace(-1, 1, 40)
    got = np.asarray(linalg.expm_multiply(A, v, t=t))
    want = sla.expm_multiply(t * s.tocsc(), v)
    np.testing.assert_allclose(got, want, rtol=1e-8, atol=1e-10)


def test_expm_multiply_complex_evolution():
    """The quantum primitive: e^{-iHt} psi stays unit-norm and matches
    scipy for a Hermitian H."""
    s = sample_csr(30, 30, density=0.2, seed=63)
    H = ((s + s.T) / 2).tocsr().astype(np.complex128)
    A = sparse.csr_array(H)
    psi0 = np.zeros(30, dtype=np.complex128)
    psi0[0] = 1.0
    got = np.asarray(linalg.expm_multiply(A, psi0, t=-0.5j))
    want = sla.expm_multiply(-0.5j * H.tocsc(), psi0)
    np.testing.assert_allclose(got, want, rtol=1e-8, atol=1e-10)
    assert abs(np.linalg.norm(got) - 1.0) < 1e-8


def test_expm_multiply_matrix_rhs():
    s = sample_csr(25, 25, density=0.15, seed=64)
    A = sparse.csr_array(s)
    B = np.linspace(0, 1, 25 * 3).reshape(25, 3)
    got = np.asarray(linalg.expm_multiply(A, B))
    want = sla.expm_multiply(s.tocsc(), B)
    np.testing.assert_allclose(got, want, rtol=1e-8, atol=1e-10)


@pytest.mark.parametrize("shape", [(40, 25), (25, 40), (30, 30)])
def test_svds_matches_scipy(shape):
    m, n = shape
    s = sample_csr(m, n, density=0.3, seed=65)
    s.data -= 0.25
    A = sparse.csr_array(s)
    k = 4
    U, sig, Vh = linalg.svds(A, k=k)
    sv_ref = np.sort(sla.svds(s, k=k, return_singular_vectors=False))[::-1]
    np.testing.assert_allclose(sig, sv_ref, rtol=1e-7, atol=1e-9)
    # triplet consistency: A ~ U diag(s) Vh on the recovered subspace
    Un, Vhn = np.asarray(U), np.asarray(Vh)
    recon = Un @ np.diag(sig) @ Vhn
    proj = Un @ (Un.T @ s.toarray())  # A restricted to span(U)
    np.testing.assert_allclose(recon, proj, atol=1e-6)


def test_svds_values_only():
    s = sample_csr(20, 15, density=0.4, seed=66)
    A = sparse.csr_array(s)
    sig = linalg.svds(A, k=3, return_singular_vectors=False)
    sv_ref = np.sort(sla.svds(s, k=3, return_singular_vectors=False))[::-1]
    np.testing.assert_allclose(sig, sv_ref, rtol=1e-7, atol=1e-9)


def test_norm_inf_axis_empty_line():
    """Review r3: an empty column/row must report 0 (implicit zeros), not
    segment_max's -inf fill."""
    s = sp.csr_array(np.array([[1.0, 0.0, -3.0], [2.0, 0.0, 0.0]]))
    A = sparse.csr_array(s)
    np.testing.assert_allclose(
        np.asarray(linalg.norm(A, ord=np.inf, axis=0)), [2.0, 0.0, 3.0]
    )
    s2 = sp.csr_array(np.array([[0.0, 0.0], [5.0, -1.0]]))
    A2 = sparse.csr_array(s2)
    np.testing.assert_allclose(
        np.asarray(linalg.norm(A2, ord=np.inf, axis=1)), [0.0, 5.0]
    )


def test_svds_invalid_k_raises():
    A = sparse.csr_array(sample_csr(5, 1, density=1.0, seed=67))
    with pytest.raises(ValueError):
        linalg.svds(A, k=6)
    with pytest.raises(ValueError):
        linalg.svds(sparse.csr_array(sample_csr(5, 5, 0.5, seed=68)), k=0)


def test_expm_multiply_linear_operator_sign_cancellation():
    """Review r3: the operator-input norm estimate must survive sign
    cancellation (A @ ones == 0 for [[2,-2],[-2,2]])."""
    M = np.array([[2.0, -2.0], [-2.0, 2.0]])
    op = linalg.LinearOperator(
        (2, 2), matvec=lambda x: M @ x, rmatvec=lambda x: M.T @ x,
        dtype=np.float64,
    )
    got = np.asarray(linalg.expm_multiply(op, np.array([1.0, 0.0])))
    import scipy.linalg as sl

    want = sl.expm(M) @ np.array([1.0, 0.0])
    np.testing.assert_allclose(got, want, rtol=1e-8)


def test_onenormest():
    s = sample_csr(30, 30, density=0.2, seed=70)
    s.data -= 0.5
    A = sparse.csr_array(s)
    exact = sla.norm(s, ord=1)
    assert np.isclose(linalg.onenormest(A), exact, rtol=1e-12)
    est, v, w = linalg.onenormest(A, compute_v=True, compute_w=True)
    assert np.isclose(est, exact, rtol=1e-12)
    assert np.isclose(np.abs(np.asarray(w)).sum(), exact, rtol=1e-12)
    # operator input: estimate is a lower bound within 3x on random mats
    op = linalg.LinearOperator(
        (30, 30), matvec=lambda x: s @ np.asarray(x),
        rmatvec=lambda x: s.T @ np.asarray(x), dtype=np.float64,
    )
    est_op = linalg.onenormest(op)
    assert est_op <= exact * (1 + 1e-9) and est_op >= exact / 3


def test_svds_rank_deficient():
    """Review r3: k past rank(A) must report exact zeros (rank cutoff +
    dense fallback when the basis would span the space), never
    unconverged Ritz junk; U stays orthonormal on the live columns."""
    rng = np.random.default_rng(71)
    L = rng.normal(size=(20, 3))
    Rm = rng.normal(size=(3, 8))
    dense = L @ Rm  # rank 3
    A = sparse.csr_array(sp.csr_array(dense))
    U, s, Vh = linalg.svds(A, k=5)
    sv_ref = np.linalg.svd(dense, compute_uv=False)[:5]
    np.testing.assert_allclose(s, sv_ref, rtol=1e-9, atol=1e-9)
    assert np.all(s[3:] == 0.0)
    Un = np.asarray(U)[:, :3]
    np.testing.assert_allclose(Un.T @ Un, np.eye(3), atol=1e-9)
    # wide orientation of the same matrix
    A2 = sparse.csr_array(sp.csr_array(dense.T))
    _, s2, _ = linalg.svds(A2, k=5)
    np.testing.assert_allclose(s2, sv_ref, rtol=1e-9, atol=1e-9)


def test_onenormest_certificate_operator():
    """Review r3: the (v, w) certificate must satisfy est == ||A v||_1
    even for operator inputs whose heaviest column is not column 0."""
    M = np.diag([1.0, 100.0, 3.0])
    op = linalg.LinearOperator(
        (3, 3), matvec=lambda x: M @ x, rmatvec=lambda x: M.T @ x,
        dtype=np.float64,
    )
    est, v, w = linalg.onenormest(op, compute_v=True, compute_w=True)
    assert np.isclose(est, np.abs(np.asarray(w)).sum())
    assert np.isclose(est, 100.0)
    np.testing.assert_allclose(np.asarray(M @ np.asarray(v)), np.asarray(w))


def test_matrix_power():
    s = sample_csr(15, 15, density=0.2, seed=72)
    A = sparse.csr_array(s)
    for p in (0, 1, 2, 5):
        want = np.linalg.matrix_power(s.toarray(), p)
        got = np.asarray(linalg.matrix_power(A, p).toarray())
        np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-9)
    with pytest.raises(ValueError):
        linalg.matrix_power(A, -1)
    with pytest.raises(ValueError):
        linalg.matrix_power(sparse.csr_array(sample_csr(3, 4, 0.5, seed=73)), 2)


def test_expm_multiply_time_grid():
    """scipy's linspace form: one pass yields the whole trajectory."""
    s = sample_csr(20, 20, density=0.15, seed=74)
    s.data -= 0.5
    A = sparse.csr_array(s)
    v = np.linspace(-1, 1, 20)
    got = np.asarray(linalg.expm_multiply(A, v, start=0.0, stop=1.0, num=5))
    want = sla.expm_multiply(s.tocsc(), v, start=0.0, stop=1.0, num=5)
    np.testing.assert_allclose(got, want, rtol=1e-7, atol=1e-9)


def test_matrix_power_edges():
    """Review r3: non-integer powers raise; power 1 returns a copy."""
    s = sample_csr(8, 8, density=0.3, seed=75)
    A = sparse.csr_array(s)
    with pytest.raises(TypeError):
        linalg.matrix_power(A, 2.5)
    P1 = linalg.matrix_power(A, 1)
    assert P1 is not A
    np.testing.assert_allclose(np.asarray(P1.toarray()), s.toarray())


def test_expm_grid_rejects_t():
    A = sparse.csr_array(sample_csr(5, 5, 0.4, seed=76))
    with pytest.raises(ValueError):
        linalg.expm_multiply(A, np.ones(5), t=2.0, start=0.0, stop=1.0, num=3)
