"""Distributed odd-even block sort (SORT_BY_KEY analog) on the CPU mesh."""

import numpy as np
import pytest
import scipy.sparse as sp

from sparse_tpu.parallel.sort import coo_to_csr_distributed, dist_sort_host


@pytest.mark.parametrize("num_shards", [1, 2, 3, 8])
@pytest.mark.parametrize("n", [0, 1, 7, 100, 1000])
def test_dist_sort_random(num_shards, n):
    rng = np.random.default_rng(n + num_shards)
    keys = rng.integers(0, 10_000, size=n).astype(np.int64)
    payload = rng.standard_normal(n)
    sk, (spay,) = dist_sort_host(keys, (payload,), num_shards)
    order = np.argsort(keys, kind="stable")
    np.testing.assert_array_equal(sk, keys[order])
    # same multiset of (key, payload) pairs, keys sorted
    got = sorted(zip(sk.tolist(), spay.tolist()))
    want = sorted(zip(keys.tolist(), payload.tolist()))
    assert got == want


@pytest.mark.parametrize("num_shards", [2, 8])
def test_dist_sort_with_duplicates(num_shards):
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 10, size=500).astype(np.int64)
    payload = np.arange(500, dtype=np.float64)
    sk, (spay,) = dist_sort_host(keys, (payload,), num_shards)
    np.testing.assert_array_equal(sk, np.sort(keys))
    assert set(spay.tolist()) == set(payload.tolist())


@pytest.mark.parametrize("num_shards", [1, 3, 8])
def test_coo_to_csr_distributed(num_shards):
    rng = np.random.default_rng(1)
    m, n, nnz = 40, 37, 300
    rows = rng.integers(0, m, nnz)
    cols = rng.integers(0, n, nnz)
    vals = rng.standard_normal(nnz)
    A = coo_to_csr_distributed(rows, cols, vals, (m, n), num_shards)
    want = sp.coo_matrix((vals, (rows, cols)), shape=(m, n)).tocsr().toarray()
    np.testing.assert_allclose(np.asarray(A.toarray()), want, rtol=1e-12)


def test_coo_to_csr_distributed_empty():
    A = coo_to_csr_distributed(
        np.zeros(0, np.int64), np.zeros(0, np.int64), np.zeros(0), (5, 4), 8
    )
    assert A.nnz == 0
    assert A.shape == (5, 4)
