"""Distributed odd-even block sort (SORT_BY_KEY analog) on the CPU mesh."""

import numpy as np
import pytest
import scipy.sparse as sp

from sparse_tpu.parallel.sort import coo_to_csr_distributed, dist_sort_host


@pytest.mark.parametrize("num_shards", [1, 2, 3, 8])
@pytest.mark.parametrize("n", [0, 1, 7, 100, 1000])
def test_dist_sort_random(num_shards, n):
    rng = np.random.default_rng(n + num_shards)
    keys = rng.integers(0, 10_000, size=n).astype(np.int64)
    payload = rng.standard_normal(n)
    sk, (spay,) = dist_sort_host(keys, (payload,), num_shards)
    order = np.argsort(keys, kind="stable")
    np.testing.assert_array_equal(sk, keys[order])
    # same multiset of (key, payload) pairs, keys sorted
    got = sorted(zip(sk.tolist(), spay.tolist()))
    want = sorted(zip(keys.tolist(), payload.tolist()))
    assert got == want


@pytest.mark.parametrize("num_shards", [2, 8])
def test_dist_sort_with_duplicates(num_shards):
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 10, size=500).astype(np.int64)
    payload = np.arange(500, dtype=np.float64)
    sk, (spay,) = dist_sort_host(keys, (payload,), num_shards)
    np.testing.assert_array_equal(sk, np.sort(keys))
    assert set(spay.tolist()) == set(payload.tolist())


@pytest.mark.parametrize("num_shards", [1, 3, 8])
def test_coo_to_csr_distributed(num_shards):
    rng = np.random.default_rng(1)
    m, n, nnz = 40, 37, 300
    rows = rng.integers(0, m, nnz)
    cols = rng.integers(0, n, nnz)
    vals = rng.standard_normal(nnz)
    A = coo_to_csr_distributed(rows, cols, vals, (m, n), num_shards)
    want = sp.coo_matrix((vals, (rows, cols)), shape=(m, n)).tocsr().toarray()
    np.testing.assert_allclose(np.asarray(A.toarray()), want, rtol=1e-12)


def test_coo_to_csr_distributed_empty():
    A = coo_to_csr_distributed(
        np.zeros(0, np.int64), np.zeros(0, np.int64), np.zeros(0), (5, 4), 8
    )
    assert A.nnz == 0
    assert A.shape == (5, 4)


@pytest.mark.parametrize("num_shards", [2, 3, 8])
@pytest.mark.parametrize("n", [64, 1000])
def test_dist_sort_sample_unique(num_shards, n):
    """Samplesort path (ragged_all_to_all): unique keys stay on the fast
    two-exchange pipeline; result must match the serial oracle exactly."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sparse_tpu.parallel.mesh import get_mesh
    from sparse_tpu.parallel.sort import dist_sort_sample

    rng = np.random.default_rng(n * num_shards)
    mesh = get_mesh(num_shards)
    L = (n + num_shards - 1) // num_shards
    total = num_shards * L
    keys = rng.permutation(total).astype(np.int64)  # unique
    payload = keys.astype(np.float64) * 2.0
    sharding = NamedSharding(mesh, P("shards"))
    sk, (sp_,) = dist_sort_sample(
        jax.device_put(keys, sharding),
        (jax.device_put(payload, sharding),),
        mesh=mesh,
    )
    sk = np.asarray(sk)
    sp_ = np.asarray(sp_)
    np.testing.assert_array_equal(sk, np.sort(keys))
    np.testing.assert_allclose(sp_, np.sort(keys) * 2.0)


@pytest.mark.parametrize("num_shards", [2, 8])
def test_dist_sort_sample_duplicate_fallback(num_shards):
    """All-equal keys overflow the samplesort bucket bound; the wrapper must
    fall back to the odd-even sort and keep key->payload association."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sparse_tpu.parallel.mesh import get_mesh
    from sparse_tpu.parallel.sort import dist_sort_sample

    mesh = get_mesh(num_shards)
    total = num_shards * 32
    keys = np.full(total, 7, dtype=np.int64)
    payload = np.arange(total, dtype=np.float64)
    sharding = NamedSharding(mesh, P("shards"))
    sk, (sp_,) = dist_sort_sample(
        jax.device_put(keys, sharding),
        (jax.device_put(payload, sharding),),
        mesh=mesh,
    )
    np.testing.assert_array_equal(np.asarray(sk), keys)
    assert sorted(np.asarray(sp_).tolist()) == payload.tolist()


@pytest.mark.parametrize("num_shards", [2, 8])
def test_coo_to_csr_distributed_big_shape(num_shards):
    """m*n > 2**31: the pair path (two stable distributed passes, int32
    keys) must match scipy without x64 — same guarantee as the
    single-device lexsort_rc big-shape path."""
    import scipy.sparse as sp

    BIG = 60_000
    rng = np.random.default_rng(3)
    nnz = 300
    rows = rng.integers(0, BIG, nnz)
    cols = rng.integers(0, BIG, nnz)
    rows[:40] = rows[40:80]  # duplicates (must sum)
    cols[:40] = cols[40:80]
    vals = rng.random(nnz)
    A = coo_to_csr_distributed(rows, cols, vals, (BIG, BIG), num_shards)
    want = sp.coo_matrix((vals, (rows, cols)), shape=(BIG, BIG)).tocsr()
    want.sum_duplicates()
    got = A.tocoo()
    w = want.tocoo()
    np.testing.assert_array_equal(np.asarray(got.row), w.row)
    np.testing.assert_array_equal(np.asarray(got.col), w.col)
    np.testing.assert_allclose(np.asarray(got.data), w.data, rtol=1e-12)
