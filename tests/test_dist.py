"""Distributed-layer tests on the virtual 8-device CPU mesh.

Mirrors the reference's strategy (SURVEY §4): the same scipy-oracle
correctness checks, run under multi-shard resource shapes so the full
partitioning/halo/collective machinery is exercised (the CI-configs analog of
.github/workflows/ci.yml:73-80).
"""

import numpy as np
import pytest
import scipy.sparse as sp

import sparse_tpu
from sparse_tpu.parallel.dist import dist_cg, shard_csr
from sparse_tpu.parallel.mesh import get_mesh

from .utils.sample import sample_csr


def laplacian_1d(n, dtype=np.float64):
    return sp.diags(
        [-1.0, 2.0, -1.0], [-1, 0, 1], shape=(n, n), format="csr"
    ).astype(dtype)


def laplacian_2d(n, dtype=np.float64):
    l1 = sp.diags([-1.0, 2.0, -1.0], [-1, 0, 1], shape=(n, n))
    eye = sp.identity(n)
    return (sp.kron(l1, eye) + sp.kron(eye, l1)).tocsr().astype(dtype)


MESH_SIZES = [1, 2, 3, 8]


@pytest.mark.parametrize("num_shards", MESH_SIZES)
@pytest.mark.parametrize("balanced", [False, True])
def test_dist_spmv_banded(num_shards, balanced):
    s = laplacian_1d(101)
    A = sparse_tpu.csr_array(s)
    mesh = get_mesh(num_shards)
    D = shard_csr(A, mesh=mesh, balanced=balanced)
    assert D.mode == "halo"
    x = np.random.default_rng(0).standard_normal(101)
    np.testing.assert_allclose(D.dot(x), s @ x, rtol=1e-12)


@pytest.mark.parametrize("num_shards", [2, 8])
@pytest.mark.parametrize("layout", ["ell", "csr"])
def test_dist_spmv_random(num_shards, layout):
    s = sample_csr(73, 61, density=0.15, seed=3, dtype=np.float64)
    A = sparse_tpu.csr_array(s)
    D = shard_csr(A, mesh=get_mesh(num_shards), layout=layout)
    x = np.random.default_rng(1).standard_normal(61)
    np.testing.assert_allclose(D.dot(x), s @ x, rtol=1e-10, atol=1e-12)


@pytest.mark.parametrize("num_shards", [2, 8])
def test_dist_spmv_gather_fallback(num_shards):
    # a dense-ish matrix whose windows span everything -> all_gather mode
    rng = np.random.default_rng(7)
    d = rng.standard_normal((40, 40))
    d[np.abs(d) < 0.5] = 0.0
    s = sp.csr_matrix(d)
    A = sparse_tpu.csr_array(s)
    D = shard_csr(A, mesh=get_mesh(num_shards), halo_max_ratio=0.25)
    assert D.mode == "gather"
    x = rng.standard_normal(40)
    np.testing.assert_allclose(D.dot(x), s @ x, rtol=1e-10, atol=1e-12)


def test_dist_spmv_more_shards_than_rows():
    # the "more shards than rows" edge the reference defends (coo.py:283-290)
    s = laplacian_1d(5)
    A = sparse_tpu.csr_array(s)
    D = shard_csr(A, mesh=get_mesh(8))
    x = np.arange(5.0)
    np.testing.assert_allclose(D.dot(x), s @ x, rtol=1e-12)


@pytest.mark.parametrize("num_shards", [1, 8])
def test_dist_cg_poisson(num_shards):
    s = laplacian_2d(12)  # 144x144, SPD
    A = sparse_tpu.csr_array(s)
    D = shard_csr(A, mesh=get_mesh(num_shards))
    rng = np.random.default_rng(0)
    xtrue = rng.standard_normal(s.shape[0])
    b = s @ xtrue
    xp, iters, converged = dist_cg(D, b, tol=1e-8, maxiter=2000)
    x = D.unpad_vector(xp)
    np.testing.assert_allclose(x, xtrue, rtol=1e-6, atol=1e-7)
    assert iters < 2000
    assert converged


def test_dist_matches_single_chip():
    s = laplacian_2d(8)
    A = sparse_tpu.csr_array(s)
    D = shard_csr(A, mesh=get_mesh(8))
    x = np.random.default_rng(4).standard_normal(s.shape[0])
    np.testing.assert_allclose(D.dot(x), np.asarray(A @ x), rtol=1e-12)


def test_precise_windows_asymmetric_halo(monkeypatch):
    """settings.precise_windows keeps left/right halos separate: an upper
    bidiagonal matrix needs no left halo (LEGATE_SPARSE_PRECISE_IMAGES
    analog, partition.py:152-160)."""
    import scipy.sparse as sp

    from sparse_tpu.config import settings

    n = 64
    s = sp.diags([np.full(n, 2.0), np.full(n - 1, -1.0)], [0, 1], format="csr")
    x = np.random.default_rng(3).standard_normal(n)
    monkeypatch.setattr(settings, "precise_windows", True)
    D = shard_csr(sparse_tpu.csr_array(s), mesh=get_mesh(8), balanced=False)
    assert D.HL == 0 and D.HR >= 1
    np.testing.assert_allclose(D.dot(x), s @ x, rtol=1e-12)
    monkeypatch.setattr(settings, "precise_windows", False)
    D2 = shard_csr(sparse_tpu.csr_array(s), mesh=get_mesh(8), balanced=False)
    assert D2.HL == D2.HR
    np.testing.assert_allclose(D2.dot(x), s @ x, rtol=1e-12)


def test_force_serial_sort(monkeypatch):
    """settings.force_serial pins the distributed sort to one shard
    (reference coo.py:242)."""
    from sparse_tpu.config import settings
    from sparse_tpu.parallel.sort import dist_sort_host

    monkeypatch.setattr(settings, "force_serial", True)
    rng = np.random.default_rng(5)
    keys = rng.integers(0, 50, size=101)
    payload = rng.standard_normal(101)
    sk, (spay,) = dist_sort_host(keys, (payload,))
    order = np.argsort(keys, kind="stable")
    np.testing.assert_array_equal(sk, keys[order])
    np.testing.assert_allclose(spay, payload[order])
