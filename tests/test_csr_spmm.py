"""SpMM (sparse x dense, dense x sparse) oracle tests vs scipy.

Reference analog: ``tests/integration/test_csr_spmm.py`` — fixture files x
dtype cross, plus the rmatmul (dense @ CSR) k-split path and the balanced
variant.
"""

import numpy as np
import pytest
import scipy.io as sci_io

import sparse_tpu as sparse
from .utils.common import test_mtx_files, types
from .utils.sample import sample_csr, sample_dense


@pytest.mark.parametrize("filename", test_mtx_files)
@pytest.mark.parametrize("b_type", types)
def test_csr_spmm(filename, b_type):
    arr = sparse.io.mmread(filename).tocsr().astype(b_type)
    s = sci_io.mmread(filename).tocsr().astype(b_type)
    B = sample_dense(arr.shape[1], 9, dtype=b_type, seed=60)
    assert np.allclose(np.asarray(arr @ B), s @ B, atol=1e-5)


@pytest.mark.parametrize("filename", test_mtx_files)
@pytest.mark.parametrize("idim", [1, 4, 33])
def test_csr_spmm_rmatmul(filename, idim):
    arr = sparse.io.mmread(filename).tocsr()
    s = sci_io.mmread(filename).tocsr()
    C = sample_dense(idim, arr.shape[0], seed=61)
    assert np.allclose(np.asarray(C @ arr), C @ s, atol=1e-5)


@pytest.mark.parametrize("b_type", [np.float32, np.complex128])
@pytest.mark.parametrize("c_type", types)
def test_csr_spmm_rmatmul_types(b_type, c_type):
    sa = sample_csr(21, 27, density=0.25, dtype=b_type, seed=62).tocsr()
    C = sample_dense(6, 21, dtype=c_type, seed=63)
    got = np.asarray(C @ sparse.csr_array(sa))
    exp = C @ sa
    assert got.dtype == exp.dtype
    assert np.allclose(got, exp, atol=1e-5)


def test_csr_rmatmul_balanced():
    """rmatmul after balance() (reference test_csr_spmm.py:79)."""
    sa = sample_csr(33, 19, density=0.2, seed=64).tocsr()
    arr = sparse.csr_array(sa)
    arr.balance()
    C = sample_dense(5, 33, seed=65)
    assert np.allclose(np.asarray(C @ arr), C @ sa, atol=1e-6)


def test_csr_spmm_result_dtype_promotion():
    sa = sample_csr(11, 13, dtype=np.float32, seed=66).tocsr()
    B = sample_dense(13, 4, dtype=np.float64, seed=67)
    got = np.asarray(sparse.csr_array(sa) @ B)
    assert got.dtype == np.float64
    assert np.allclose(got, sa @ B, atol=1e-6)
