"""DIA SpMV kernels (XLA + Pallas-interpret) and the CSR banded fast path."""

import numpy as np
import pytest
import scipy.sparse as sp

import sparse_tpu
from sparse_tpu.config import settings
from sparse_tpu.kernels.dia_spmv import dia_spmv_pallas
from sparse_tpu.ops.dia_spmv import dia_spmv_xla

CASES = [
    (50, 50, [-5, -1, 0, 1, 5]),
    (40, 60, [-3, 0, 2, 10]),
    (60, 40, [-7, 0, 1]),
    (7, 7, [0]),
    (300, 300, [-17, -1, 0, 1, 17]),
]


@pytest.mark.parametrize("m,n,offs", CASES)
def test_dia_spmv_xla(m, n, offs):
    rng = np.random.default_rng(m + n)
    data = rng.standard_normal((len(offs), n))
    s = sp.dia_matrix((data, offs), shape=(m, n))
    x = rng.standard_normal(n)
    got = np.asarray(dia_spmv_xla(data, tuple(offs), x, (m, n)))
    np.testing.assert_allclose(got, s @ x, rtol=1e-12, atol=1e-12)


@pytest.mark.parametrize("m,n,offs", CASES)
def test_dia_spmv_pallas_interpret(m, n, offs):
    rng = np.random.default_rng(m)
    data = rng.standard_normal((len(offs), n))
    s = sp.dia_matrix((data, offs), shape=(m, n))
    x = rng.standard_normal(n)
    got = np.asarray(
        dia_spmv_pallas(data, tuple(offs), x, (m, n), interpret=True)
    )
    np.testing.assert_allclose(got, s @ x, rtol=1e-12, atol=1e-12)


@pytest.mark.parametrize("m,n,offs", CASES)
def test_dia_spmv_packed_interpret(m, n, offs):
    from sparse_tpu.kernels.dia_spmv import dia_spmv_pallas_v2

    rng = np.random.default_rng(m * 3 + n)
    data = rng.standard_normal((len(offs), n)).astype(np.float32)
    s = sp.dia_matrix((data, offs), shape=(m, n))
    x = rng.standard_normal(n).astype(np.float32)
    got = np.asarray(
        dia_spmv_pallas_v2(data, tuple(offs), x, (m, n), tile=1024, interpret=True)
    )
    np.testing.assert_allclose(got, s @ x, rtol=1e-5, atol=1e-5)


def test_dia_packed_multi_tile_interpret():
    from sparse_tpu.kernels.dia_spmv import dia_spmv_pallas_v2

    m = 2500  # three 1024-tiles with a ragged tail
    offs = (-70, -1, 0, 1, 70)
    rng = np.random.default_rng(7)
    data = rng.standard_normal((len(offs), m)).astype(np.float32)
    s = sp.dia_matrix((data, offs), shape=(m, m))
    x = rng.standard_normal(m).astype(np.float32)
    got = np.asarray(
        dia_spmv_pallas_v2(data, offs, x, (m, m), tile=1024, interpret=True)
    )
    np.testing.assert_allclose(got, s @ x, rtol=1e-4, atol=1e-4)


def test_dia_packed_wide_matrix_interpret():
    # n >> m_pad + B: packing must truncate, not let update-slice clamp
    from sparse_tpu.kernels.dia_spmv import dia_spmv_pallas_v2

    m, n, offs = 100, 2000, (0, 5)
    rng = np.random.default_rng(11)
    data = rng.standard_normal((2, n)).astype(np.float32)
    s = sp.dia_matrix((data, offs), shape=(m, n))
    x = rng.standard_normal(n).astype(np.float32)
    got = np.asarray(
        dia_spmv_pallas_v2(data, offs, x, (m, n), tile=1024, interpret=True)
    )
    np.testing.assert_allclose(got, s @ x, rtol=1e-5, atol=1e-5)


def test_dia_array_dot_uses_dia_path():
    offs = [-2, 0, 3]
    data = np.random.default_rng(0).standard_normal((3, 30))
    s = sp.dia_matrix((data, offs), shape=(30, 30))
    A = sparse_tpu.dia_array((data, offs), shape=(30, 30))
    x = np.random.default_rng(1).standard_normal(30)
    np.testing.assert_allclose(np.asarray(A @ x), s @ x, rtol=1e-12)


def test_csr_banded_autodetect():
    s = sp.diags([1.0, -2.0, 1.0], [-1, 0, 1], shape=(64, 64), format="csr")
    A = sparse_tpu.csr_array(s)
    assert A._maybe_dia() is not None  # detected as banded
    x = np.random.default_rng(2).standard_normal(64)
    np.testing.assert_allclose(np.asarray(A @ x), s @ x, rtol=1e-12)


def test_csr_unbanded_rejects_dia():
    from .utils.sample import sample_csr

    s = sample_csr(80, 80, density=0.3, seed=1)
    A = sparse_tpu.csr_array(s)
    assert A._maybe_dia() is None  # ~everything is a distinct diagonal
    x = np.random.default_rng(3).standard_normal(80)
    np.testing.assert_allclose(np.asarray(A @ x), s @ x, rtol=1e-10)


def test_with_data_invalidates_dia_cache():
    s = sp.diags([1.0, -2.0, 1.0], [-1, 0, 1], shape=(32, 32), format="csr")
    A = sparse_tpu.csr_array(s)
    _ = A._maybe_dia()
    B = A * 2.0
    x = np.random.default_rng(4).standard_normal(32)
    np.testing.assert_allclose(np.asarray(B @ x), 2.0 * (s @ x), rtol=1e-12)


def test_dia_transpose_nonsquare_dot():
    # transpose leaves wider data planes; must fall back to CSR, not crash
    A = sparse_tpu.dia_array((np.ones((1, 60)), [0]), shape=(40, 60))
    At = A.T
    got = np.asarray(At @ np.ones(40))
    want = sp.dia_matrix((np.ones((1, 60)), [0]), shape=(40, 60)).T @ np.ones(40)
    np.testing.assert_allclose(got, want)


def test_dia_pallas_wide_matrix():
    m, n, offs = 100, 390, (0, 5)
    rng = np.random.default_rng(9)
    data = rng.standard_normal((2, n))
    s = sp.dia_matrix((data, offs), shape=(m, n))
    x = rng.standard_normal(n)
    got = np.asarray(dia_spmv_pallas(data, offs, x, (m, n), interpret=True))
    np.testing.assert_allclose(got, s @ x, rtol=1e-12, atol=1e-12)


def test_spmv_mode_ell_overrides_dia():
    s = sp.diags([1.0, -2.0, 1.0], [-1, 0, 1], shape=(32, 32), format="csr")
    A = sparse_tpu.csr_array(s)
    x = np.random.default_rng(5).standard_normal(32)
    old = settings.spmv_mode
    try:
        settings.spmv_mode = "ell"
        np.testing.assert_allclose(np.asarray(A @ x), s @ x, rtol=1e-12)
        settings.spmv_mode = "segment"
        np.testing.assert_allclose(np.asarray(A @ x), s @ x, rtol=1e-12)
        settings.spmv_mode = "auto"
        np.testing.assert_allclose(np.asarray(A @ x), s @ x, rtol=1e-12)
    finally:
        settings.spmv_mode = old


def test_csr_duplicate_entries_dia_path_sums():
    # non-canonical CSR with duplicate (i, j): banded fast path must sum
    indptr = np.array([0, 2, 3])
    indices = np.array([0, 0, 1])
    data = np.array([1.0, 2.0, 5.0])
    A = sparse_tpu.csr_array.from_parts(data, indices, indptr, (2, 2))
    assert A._maybe_dia() is not None
    got = np.asarray(A @ np.array([1.0, 1.0]))
    np.testing.assert_allclose(got, [3.0, 5.0])


def test_spmv_mode_pallas_prepared_cache():
    """spmv_mode='pallas' routes through the cached PreparedDia operator
    (interpret mode off-TPU) for both dia_array and banded csr_array."""
    offs = [-2, 0, 3]
    rng = np.random.default_rng(21)
    data = rng.standard_normal((3, 40)).astype(np.float32)
    s = sp.dia_matrix((data, offs), shape=(40, 40))
    x = rng.standard_normal(40).astype(np.float32)
    old = settings.spmv_mode
    try:
        settings.spmv_mode = "pallas"
        A = sparse_tpu.dia_array((data, offs), shape=(40, 40))
        np.testing.assert_allclose(np.asarray(A @ x), s @ x, rtol=1e-4, atol=1e-5)
        # PreparedDia now lives in the library-wide plan cache (weak-ref
        # keyed under the legacy attr name), not as an object attribute
        from sparse_tpu import plan_cache

        assert plan_cache.lookup(A, "_prepared") is not None
        np.testing.assert_allclose(np.asarray(A @ x), s @ x, rtol=1e-4, atol=1e-5)
        C = sparse_tpu.csr_array(s.tocsr())
        np.testing.assert_allclose(np.asarray(C @ x), s @ x, rtol=1e-4, atol=1e-5)
        assert plan_cache.lookup(C, "_dia_prepared") is not None
        # mutation produces a fresh object -> fresh cache
        C2 = C * 2.0
        np.testing.assert_allclose(np.asarray(C2 @ x), 2 * (s @ x), rtol=1e-4, atol=1e-5)
    finally:
        settings.spmv_mode = old


def test_spmv_chain_matches_repeated_apply():
    """_spmv_chain (the autotuner/bench timing primitive) must be an HONEST
    dependency chain: k compiled iterations == k explicit SpMV+update steps."""
    import jax.numpy as jnp

    from sparse_tpu.kernels.dia_spmv import (
        _spmv_chain, dia_pack, dia_pad_x, dia_plan, dia_spmv_packed,
    )

    offs = (-2, 0, 1)
    m = 40
    rng = np.random.default_rng(3)
    data = (0.1 * rng.standard_normal((3, m))).astype(np.float32)
    plan = dia_plan(offs, (m, m), tile=1024)
    pf = dia_pack(jnp.asarray(data), plan)
    xp0 = dia_pad_x(jnp.asarray(rng.standard_normal(m).astype(np.float32)), plan)
    got = np.asarray(_spmv_chain(pf, xp0, plan, 3, interpret=True))

    xp = xp0
    import jax

    for _ in range(3):
        y = dia_spmv_packed(pf, xp, plan, interpret=True)
        xp = jax.lax.dynamic_update_slice(xp, y.astype(xp.dtype), (plan.B,))
    np.testing.assert_allclose(got, np.asarray(xp), rtol=1e-5, atol=1e-6)


def test_autotune_off_tpu_returns_default_without_caching():
    from sparse_tpu.kernels import dia_spmv as K

    data = np.ones((3, 64), dtype=np.float32)
    K._TILE_CACHE.clear()
    tile, band = K.autotune_dia_tile(data, (-1, 0, 1), (64, 64))
    assert tile == 65536 and band == {}  # no probing off-TPU
    # the GATE result must not be memoized as if a probe ran (ADVICE r5):
    # flipping pallas_autotune on later in the session — or moving to a
    # TPU backend — must still probe this geometry
    assert ((-1, 0, 1), (64, 64), "float32") not in K._TILE_CACHE
    # PreparedDia with tile=None resolves through the same default off-TPU
    p = K.PreparedDia(data, (-1, 0, 1), (64, 64))
    assert p.plan.TM >= 1024


def test_autotune_probe_failure_returns_default_without_crash(monkeypatch):
    """On a backend where the chain/kernel cannot run, every candidate
    drops out of the race and the default tile comes back — no exception
    escapes (the wedge-safety contract of the one-attempt design)."""
    from sparse_tpu.kernels import dia_spmv as K

    K._TILE_CACHE.clear()
    # the retirement flag is process-global by design; isolate it so this
    # deliberately-failing probe can't leak host-clock-only behavior into
    # later tests
    monkeypatch.setattr(K, "_CHAIN_RETIRED", [False])
    monkeypatch.setattr(K.jax, "default_backend", lambda: "tpu")
    data = np.ones((3, 4096), dtype=np.float32)
    tile, band = K.autotune_dia_tile(
        data, (-1, 0, 1), (4096, 4096), chain=2, reps=1, budget_s=5
    )
    assert isinstance(tile, int) and tile in (16384, 32768, 65536, 131072)
    assert ((-1, 0, 1), (4096, 4096), "float32") in K._TILE_CACHE
