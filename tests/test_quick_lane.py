"""Quick-lane integrity: the committed manifest floor must hold.

Wires ``scripts/check_quick_lane.py`` into the suite (ISSUE 3 satellite)
so tier-1 catches a quick-lane file going missing/unmarked or its test
count silently dropping. The check is pure-ast static analysis — no
subprocess, no collection, milliseconds.
"""

import importlib.util
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_checker():
    spec = importlib.util.spec_from_file_location(
        "check_quick_lane",
        os.path.join(REPO, "scripts", "check_quick_lane.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_quick_lane_intact():
    mod = _load_checker()
    assert mod.check() == []


def test_this_file_is_in_the_lane():
    """The guard itself must ride the lane it guards."""
    mod = _load_checker()
    assert "test_quick_lane.py" in mod.quick_files()


def test_static_counter_sees_this_function():
    mod = _load_checker()
    n = mod.count_tests(os.path.abspath(__file__))
    assert n >= 3  # the three tests in this module


def test_manifest_matches_conftest():
    import json

    mod = _load_checker()
    manifest = json.load(open(mod.MANIFEST))
    assert set(manifest["files"]) == mod.quick_files()
    assert manifest["total"] == sum(manifest["files"].values())
