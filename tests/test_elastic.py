"""Elastic mesh (ISSUE 20): slice-loss survival, live re-plan, and
zero-loss ticket migration.

The load-bearing contracts:

* **Re-plan** — ``session.remesh(mesh)`` quiesces, re-targets the
  :class:`FleetPolicy` and serves on the new topology: shrink, grow
  and swap (same fingerprint, different devices) all land in a
  consistent ``session_stats()`` view.
* **Zero-loss migration** — a forged slice loss
  (``shrink:mesh:to=4``) mid-traffic requeues every in-flight lane
  with its best iterate as ``x0``; every ticket still reaches a
  terminal state and the solutions match a clean session.
* **Flap guard** — a topology that will not hold still latches after
  ``SPARSE_TPU_REMESH_RETRIES`` transitions: the policy pins the
  single-device strategy and keeps serving degraded.
* **mesh=1 collapse** — remeshing onto one device disables the fleet
  tier but never the session.
* **Ordering** — the transition is visible in telemetry in the only
  legal order: requeue -> admission hold -> ``fleet.remesh`` ->
  re-dispatch.
* **No stale identity** — ``session_stats()['mesh']`` and the
  per-device occupancy family re-resolve after the transition; the
  old mesh's higher-numbered devices leave no ghost series.
* **Default-off invariance** — with no mesh fault and no ``remesh()``
  call, a remesh-enabled session is byte-identical to a
  ``SPARSE_TPU_REMESH=0`` one: same program keys, same jaxprs, same
  dispatch count.
* **Mesh-keyed replay** — a manifest holding two fingerprints replays
  exactly the matching subset on restart.

Runs on the conftest-forced 8-device virtual CPU mesh
(``--xla_force_host_platform_device_count=8``).
"""

import numpy as np
import pytest
import scipy.sparse as sp

import jax

from sparse_tpu import fleet, plan_cache, telemetry, vault
from sparse_tpu.batch import SolveSession
from sparse_tpu.batch.operator import SparsityPattern
from sparse_tpu.config import settings
from sparse_tpu.fleet.elastic import MeshMonitor, mesh_identity
from sparse_tpu.parallel.mesh import mesh_fingerprint
from sparse_tpu.resilience import faults
from sparse_tpu.telemetry import _metrics


@pytest.fixture(autouse=True)
def _clean_state(tmp_path):
    """Scratch telemetry sink, no faults, vault off, cold plan cache,
    and the elastic knobs restored (tests flip them)."""
    faults.clear()
    old_vault = settings.vault
    old_tel = settings.telemetry
    old_remesh = settings.remesh
    old_retries = settings.remesh_retries
    settings.vault = ""
    telemetry.configure(str(tmp_path / "records.jsonl"))
    telemetry.reset()
    plan_cache.clear()
    yield
    faults.clear()
    settings.vault = old_vault
    settings.telemetry = old_tel
    settings.remesh = old_remesh
    settings.remesh_retries = old_retries
    telemetry.configure(None)
    telemetry.reset()
    plan_cache.clear()


def _traffic(B=16, n=96, seed=0, dtype=np.float64):
    rng = np.random.default_rng(seed)
    e = np.ones(n)
    mats = []
    for _ in range(B):
        A = sp.diags(
            [-e[:-1], 3.0 * e, -e[:-1]], [-1, 0, 1], format="csr"
        ).astype(dtype)
        A.setdiag((3.0 + rng.random(n)).astype(dtype))
        A.sort_indices()
        mats.append(A.tocsr())
    rhs = rng.standard_normal((B, n)).astype(dtype)
    return mats, rhs


def _mesh(S):
    return fleet.fleet_mesh(S)


def _session(**kw):
    kw.setdefault("batch_max", 16)
    kw.setdefault("fleet", "auto")
    kw.setdefault("fleet_mesh", _mesh(8))
    kw.setdefault("fleet_min_b", 4)
    return SolveSession("cg", **kw)


def _check(mats, X, rhs, tol=1e-8):
    for A, x, b in zip(mats, X, rhs):
        assert np.linalg.norm(A @ x - b) < tol


# ---------------------------------------------------------------------------
# explicit re-plan: shrink, grow, swap
# ---------------------------------------------------------------------------
def test_shrink_then_grow_replan():
    mats, rhs = _traffic()
    ses = _session()
    X0, _, _ = ses.solve_many(mats, rhs, tol=1e-10)
    _check(mats, X0, rhs)

    res = ses.remesh(_mesh(4))
    assert res["outcome"] == "ok"
    assert res["old"] == mesh_fingerprint(_mesh(8))
    assert res["new"] == mesh_fingerprint(_mesh(4))
    assert res["devices"] == 4 and res["reason"] == "manual"
    st = ses.session_stats()
    assert st["mesh"]["devices"] == 4
    assert st["mesh"]["fingerprint"] == mesh_fingerprint(_mesh(4))
    X1, _, _ = ses.solve_many(mats, rhs, tol=1e-10)
    _check(mats, X1, rhs)
    assert np.max(np.abs(X1 - X0)) < 1e-12

    # grow back: the same verb, the same session
    res = ses.remesh(_mesh(8))
    assert res["outcome"] == "ok" and res["devices"] == 8
    assert ses.session_stats()["mesh"]["devices"] == 8
    X2, _, _ = ses.solve_many(mats, rhs, tol=1e-10)
    _check(mats, X2, rhs)
    # a repeated remesh onto the current topology is a no-op
    assert ses.remesh(_mesh(8))["outcome"] == "noop"


def test_swap_same_fingerprint_replans():
    mats, rhs = _traffic()
    ses = _session()
    snap0 = plan_cache.snapshot()
    ses.solve_many(mats, rhs, tol=1e-10)
    cold_misses = plan_cache.delta(snap0)["misses"]
    assert cold_misses >= 1

    # same count, reversed devices: fingerprint identical, identity not
    mon = MeshMonitor(_mesh(8), retries=8)
    swapped = mon._swapped()
    assert mesh_fingerprint(swapped) == mesh_fingerprint(_mesh(8))
    assert mesh_identity(swapped) != mesh_identity(_mesh(8))

    res = ses.remesh(swapped)
    assert res["outcome"] == "ok"
    assert res["old"] == res["new"]  # a swap keeps the fingerprint
    # cached executables compiled against the dead mesh were dropped:
    # serving on the replacement slice rebuilds as cold as the first
    snap1 = plan_cache.snapshot()
    X, _, _ = ses.solve_many(mats, rhs, tol=1e-10)
    assert plan_cache.delta(snap1)["misses"] == cold_misses
    _check(mats, X, rhs)


# ---------------------------------------------------------------------------
# zero-loss migration under a forged slice loss
# ---------------------------------------------------------------------------
def test_forged_shrink_migrates_with_x0_carry():
    settings.telemetry = True
    mats, rhs = _traffic()
    clean = _session()
    Xc, _, _ = clean.solve_many(mats, rhs, tol=1e-10)

    ses = _session()
    tickets = [
        ses.submit(A, b, tol=1e-10) for A, b in zip(mats, rhs)
    ]
    faults.configure("shrink:mesh:to=4")
    try:
        ses.drain()
    finally:
        faults.clear()
    assert all(t.done for t in tickets), "a ticket was lost in migration"
    X = np.stack([t.result()[0] for t in tickets])
    _check(mats, X, rhs)
    assert np.max(np.abs(X - Xc)) < 1e-8
    # the transition really happened, as a migration not a failure
    st = ses.session_stats()
    assert st["mesh"]["devices"] == 4
    assert st["tickets"]["queue_depth_drift"] == 0
    rq = [
        e for e in telemetry.events()
        if e["kind"] == "batch.requeue" and e.get("action") == "remesh"
    ]
    assert rq and rq[0]["lanes"] > 0
    rm = [e for e in telemetry.events() if e["kind"] == "fleet.remesh"]
    assert rm and rm[0]["reason"] == "fault"
    assert rm[0]["requeued"] == rq[0]["lanes"]

    # recovery drill: after faults.clear(), remesh() with no argument
    # re-resolves the construction-time world
    rec = ses.remesh()
    assert rec["outcome"] == "ok" and rec["devices"] == 8


def test_admission_hold_release_ordering():
    settings.telemetry = True
    mats, rhs = _traffic()
    ses = _session()
    faults.configure("shrink:mesh:to=4")
    try:
        X, _, _ = ses.solve_many(mats, rhs, tol=1e-10)
    finally:
        faults.clear()
    _check(mats, X, rhs)
    evs = telemetry.events()
    kinds = [
        (e["kind"], e.get("action") or e.get("reason"))
        for e in evs
    ]
    i_rq = kinds.index(("batch.requeue", "remesh"))
    i_adm = kinds.index(("batch.admission", "remesh"))
    i_rm = next(
        i for i, e in enumerate(evs) if e["kind"] == "fleet.remesh"
    )
    dispatches_after = [
        i for i, e in enumerate(evs)
        if e["kind"] == "batch.dispatch" and i > i_rm
    ]
    # requeue -> admission hold -> transition -> re-dispatch
    assert i_rq < i_adm < i_rm
    assert dispatches_after, "migrated lanes never re-dispatched"


# ---------------------------------------------------------------------------
# flap guard: latch + single pin
# ---------------------------------------------------------------------------
def test_flap_guard_latches_and_pins_single():
    settings.telemetry = True
    settings.remesh_retries = 1
    mats, rhs = _traffic()
    ses = _session()
    ses.solve_many(mats, rhs, tol=1e-10)

    assert ses.remesh(_mesh(4))["outcome"] == "ok"  # budget: 1 allowed
    res = ses.remesh(_mesh(8))  # the second transition latches
    assert res["outcome"] == "latched"
    st = ses.session_stats()
    assert st["elastic"] == {"remeshes": 2, "retries": 1, "latched": True}
    assert st["mesh"]["pinned"] == "remesh flap guard"
    assert not ses.fleet.enabled
    failed = [
        e for e in telemetry.events()
        if e["kind"] == "fleet.remesh_failed"
    ]
    assert failed and failed[0]["reason"] == "flap_guard"

    # latched is terminal for the monitor: further verbs refuse fast
    assert ses.remesh(_mesh(8))["outcome"] == "latched"
    # ... and the session still serves, degraded but correct
    X, _, _ = ses.solve_many(mats, rhs, tol=1e-10)
    _check(mats, X, rhs)


def test_flap_fault_respects_budget():
    settings.telemetry = True
    settings.remesh_retries = 2
    mats, rhs = _traffic()
    ses = _session()
    faults.configure("flap:mesh:n=6")
    try:
        for _ in range(4):
            ses.solve_many(mats, rhs, tol=1e-10)
    finally:
        faults.clear()
    st = ses.session_stats()
    # the guard bounded the chase regardless of how long the flap ran
    assert st["elastic"]["remeshes"] <= settings.remesh_retries + 1
    X, _, _ = ses.solve_many(mats, rhs, tol=1e-10)
    _check(mats, X, rhs)


# ---------------------------------------------------------------------------
# mesh=1 collapse
# ---------------------------------------------------------------------------
def test_remesh_to_one_device_collapses_to_classic():
    mats, rhs = _traffic()
    ses = _session()
    X0, _, _ = ses.solve_many(mats, rhs, tol=1e-10)
    res = ses.remesh(_mesh(1))
    assert res["outcome"] == "ok" and res["devices"] == 1
    assert not ses.fleet.enabled  # one device: fleet tier disabled
    X1, _, _ = ses.solve_many(mats, rhs, tol=1e-10)
    _check(mats, X1, rhs)
    assert np.max(np.abs(X1 - X0)) < 1e-12


def test_remesh_on_fleet_off_session_is_disabled():
    ses = SolveSession("cg", fleet=False)
    assert ses.remesh(_mesh(4)) == {"outcome": "disabled"}
    assert "elastic" not in ses.session_stats()


# ---------------------------------------------------------------------------
# stale identity: stats and gauges re-resolve (ISSUE 20 satellite)
# ---------------------------------------------------------------------------
def test_no_stale_mesh_identity_after_shrink():
    settings.telemetry = True
    mats, rhs = _traffic()
    ses = _session()
    ses.solve_many(mats, rhs, tol=1e-10)
    assert len(ses.session_stats()["device_occupancy"]) == 8
    assert len(_metrics.family("fleet.device_occupancy")) == 8

    ses.remesh(_mesh(4))
    # the transition REMOVES the per-device family outright — a zeroed
    # ghost for devices 4..7 would still trip occupancy alerting
    assert ses.session_stats()["device_occupancy"] == []
    assert _metrics.family("fleet.device_occupancy") == []
    st = ses.session_stats()
    assert st["mesh"]["devices"] == 4
    assert st["mesh"]["fingerprint"] == mesh_fingerprint(_mesh(4))

    ses.solve_many(mats, rhs, tol=1e-10)
    occ = ses.session_stats()["device_occupancy"]
    assert len(occ) == 4  # no ghost devices from the 8-mesh era
    assert len(_metrics.family("fleet.device_occupancy")) == 4


# ---------------------------------------------------------------------------
# default-off invariance: no fault + no remesh() = byte-identical
# ---------------------------------------------------------------------------
def test_default_off_invariance_pin():
    mats, rhs = _traffic()
    pat = SparsityPattern.from_csr(mats[0])
    runs = {}
    for enabled in (True, False):
        plan_cache.clear()
        settings.remesh = enabled
        ses = _session()
        assert (ses._elastic is not None) is enabled
        snap = plan_cache.snapshot()
        X, iters, r2 = ses.solve_many(mats, rhs, tol=1e-10)
        plan = ses.fleet.decide(pat, 16, "cg")
        B, n = 16, pat.shape[0]
        args = (
            np.zeros((B, pat.nnz)), np.zeros((B, n)),
            np.zeros((B, n)), np.zeros(B), 100,
        )
        jx = jax.make_jaxpr(
            ses._build_program(pat, B, np.dtype(np.float64), plan=plan)
        )(*args)
        runs[enabled] = (
            X, iters, plan_cache.delta(snap), ses.dispatches, str(jx)
        )
    X1, it1, d1, n1, j1 = runs[True]
    X0, it0, d0, n0, j0 = runs[False]
    assert np.array_equal(X1, X0) and np.array_equal(it1, it0)
    assert d1 == d0 and n1 == n0
    assert j1 == j0  # the monitor perturbs nothing compiled


# ---------------------------------------------------------------------------
# mesh-keyed manifest: two fingerprints, matching subset replays
# ---------------------------------------------------------------------------
def test_manifest_two_fingerprints_replays_matching_subset(tmp_path):
    settings.telemetry = True
    settings.vault = str(tmp_path / "vault")
    mats, rhs = _traffic()
    ses = _session()
    ses.solve_many(mats, rhs, tol=1e-10)  # vaulted under cpu:8
    assert ses.remesh(_mesh(4))["outcome"] == "ok"
    ses.solve_many(mats, rhs, tol=1e-10)  # vaulted under cpu:4

    fps = [e.get("mesh") for e in vault.manifest_entries()]
    assert set(fps) == {
        mesh_fingerprint(_mesh(8)), mesh_fingerprint(_mesh(4))
    }
    n4 = fps.count(mesh_fingerprint(_mesh(4)))

    # a 4-mesh restart replays exactly the 4-mesh subset, serves warm
    plan_cache.clear()
    telemetry.reset()
    s2 = _session(
        fleet_mesh=_mesh(4), warm_start=True, warm_async=False
    )
    assert s2.warm_replayed == n4
    rp = [e for e in telemetry.events() if e["kind"] == "vault.replay"]
    assert rp and rp[0]["mesh_skipped"] == len(fps) - n4
    snap = plan_cache.snapshot()
    X, _, _ = s2.solve_many(mats, rhs, tol=1e-10)
    assert plan_cache.delta(snap)["misses"] == 0
    _check(mats, X, rhs)

    # a live remesh onto the OTHER vaulted topology is also warm: the
    # transition's replay pulls the 8-mesh subset back in
    rec = s2.remesh(_mesh(8))
    assert rec["outcome"] == "ok"
    assert rec["replayed"] == len(fps) - n4
    snap = plan_cache.snapshot()
    s2.solve_many(mats, rhs, tol=1e-10)
    assert plan_cache.delta(snap)["misses"] == 0


# ---------------------------------------------------------------------------
# monitor unit surface
# ---------------------------------------------------------------------------
def test_monitor_resolve_and_guard_unit():
    mon = MeshMonitor(_mesh(8), retries=2)
    assert mon.describe() == {"remeshes": 0, "retries": 2, "latched": False}
    # clean world: resolve is mesh0, changed is None
    assert mesh_identity(mon.resolve()) == mesh_identity(_mesh(8))
    pol = fleet.FleetPolicy("auto", mesh=_mesh(8), min_b=2)
    assert mon.changed(pol) is None
    # forged shrink: resolve offers the submesh, changed names it
    faults.configure("shrink:mesh:to=4")
    try:
        tgt = mon.changed(pol)
        assert tgt is not None
        assert mesh_fingerprint(tgt) == mesh_fingerprint(_mesh(4))
        # a policy already serving the forged world sees no change
        pol4 = fleet.FleetPolicy("auto", mesh=tgt, min_b=2)
        assert mon.changed(pol4) is None
    finally:
        faults.clear()
    assert mon.changed(pol) is None  # cleared: the world healed
    # guard: `retries` transitions pass, the next latches
    assert not mon.guard() and not mon.guard()
    assert mon.guard() and mon.latched
    assert mon.describe()["latched"]
