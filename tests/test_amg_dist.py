"""Distributed AMG end-to-end: hierarchy built with mesh SpGEMM, solved
with a distributed V-cycle-preconditioned CG (VERDICT r1 #3 done-criterion).

Runs the example as a subprocess on the virtual 8-device CPU mesh — the
same driver a user runs — and checks convergence and hierarchy shape
against the single-device expectations.
"""

import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_amg_dist_end_to_end():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "amg.py"),
         "-n", "16", "-dist", "-tpu"],
        capture_output=True,
        text=True,
        timeout=420,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = proc.stdout
    m = re.search(r"levels: (\d+)\s+sizes: \[([0-9, ]+)\]", out)
    assert m, out
    sizes = [int(v) for v in m.group(2).split(",")]
    assert sizes[0] == 256  # 16x16 fine grid
    assert len(sizes) >= 2 and sizes[-1] < sizes[0]
    m = re.search(r"Iterations: (\d+)\s+residual: ([0-9.e+-]+)", out)
    assert m, out
    iters, resid = int(m.group(1)), float(m.group(2))
    assert resid < 1e-7
    assert 0 < iters < 100  # V-cycle preconditioning, not plain CG
