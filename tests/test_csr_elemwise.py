"""Elementwise CSR arithmetic oracle tests vs scipy.

Reference analog: ``tests/integration/test_csr_elemwise.py`` — sparse*sparse,
sparse*dense, sparse+sparse over the fixture files with a dtype axis, plus
scalar mul, subtract, power, neg and dense broadcast.
"""

import numpy as np
import pytest
import scipy.io as sci_io
import scipy.sparse as scpy

import sparse_tpu as sparse
from .utils.common import test_mtx_files, types
from .utils.sample import sample_csr, sample_dense


@pytest.mark.parametrize("filename", test_mtx_files)
@pytest.mark.parametrize("b_type", types)
def test_csr_elemwise_mul(filename, b_type):
    arr = sparse.io.mmread(filename)
    s = sci_io.mmread(filename)
    rolled = np.roll(np.asarray(arr.todense()), 1)
    res = arr.tocsr().astype(b_type) * sparse.csr_array(rolled).astype(b_type)
    res_sci = s.tocsr().astype(b_type).multiply(
        scpy.csr_matrix(np.roll(np.asarray(s.todense()), 1)).astype(b_type)
    )
    assert np.allclose(np.asarray(res.todense()), res_sci.todense(), atol=1e-6)


@pytest.mark.parametrize("filename", test_mtx_files)
@pytest.mark.parametrize("b_type", types)
def test_csr_dense_elemwise_mul(filename, b_type):
    arr = sparse.io.mmread(filename).tocsr().astype(b_type)
    s = sci_io.mmread(filename).tocsr().astype(b_type)
    c = sample_dense(*arr.shape, dtype=b_type, seed=81)
    res = arr * c
    res_sci = s.multiply(c)
    assert np.allclose(np.asarray(res.todense()), res_sci.todense(), atol=1e-6)


@pytest.mark.parametrize("filename", test_mtx_files)
@pytest.mark.parametrize("b_type", types)
def test_csr_elemwise_add(filename, b_type):
    arr = sparse.io.mmread(filename)
    s = sci_io.mmread(filename)
    rolled = np.roll(np.asarray(arr.todense()), 1)
    res = arr.tocsr().astype(b_type) + sparse.csr_array(rolled).astype(b_type)
    res_sci = s.tocsr().astype(b_type) + scpy.csr_matrix(
        np.roll(np.asarray(s.todense()), 1)
    ).astype(b_type)
    assert np.allclose(np.asarray(res.todense()), res_sci.todense(), atol=1e-6)


@pytest.mark.parametrize("filename", test_mtx_files)
def test_csr_mul_scalar(filename):
    arr = sparse.io.mmread(filename).tocsr()
    s = sci_io.mmread(filename).tocsr()
    assert np.allclose(np.asarray((arr * 3.0).todense()), (s * 3.0).todense())
    assert np.allclose(np.asarray((3.0 * arr).todense()), (s * 3.0).todense())
    assert np.allclose(np.asarray((arr / 2.0).todense()), (s / 2.0).todense())


@pytest.mark.parametrize("filename", test_mtx_files)
def test_csr_subtract(filename):
    arr = sparse.io.mmread(filename).tocsr()
    s = sci_io.mmread(filename).tocsr()
    rolled = np.roll(np.asarray(arr.todense()), 1)
    res = arr - sparse.csr_array(rolled)
    res_sci = s - scpy.csr_matrix(np.roll(np.asarray(s.todense()), 1))
    assert np.allclose(np.asarray(res.todense()), res_sci.todense(), atol=1e-6)


def test_csr_power():
    sa = sample_csr(15, 12, density=0.3, seed=82).tocsr()
    got = sparse.csr_array(sa).power(2)
    exp = sa.power(2)
    assert np.allclose(np.asarray(got.todense()), exp.todense())


def test_csr_neg_abs_conj():
    sa = sample_csr(15, 12, density=0.3, dtype=np.complex128, seed=83).tocsr()
    arr = sparse.csr_array(sa)
    assert np.allclose(np.asarray((-arr).todense()), (-sa).todense())
    assert np.allclose(np.asarray(abs(arr).todense()), abs(sa).todense())
    assert np.allclose(np.asarray(arr.conj().todense()), sa.conj().todense())


def test_mult_dense_broadcast():
    """Row-vector broadcast multiply (reference test_csr_elemwise.py:98)."""
    sa = sample_csr(14, 10, density=0.4, seed=84).tocsr()
    arr = sparse.csr_array(sa)
    row = sample_dense(1, 10, seed=85)
    got = arr * row
    exp = sa.multiply(row)
    assert np.allclose(np.asarray(got.todense()), exp.todense(), atol=1e-6)


@pytest.mark.parametrize("axis", [None, 0, 1])
def test_csr_sum_mean(axis):
    sa = sample_csr(17, 13, density=0.3, seed=86).tocsr()
    arr = sparse.csr_array(sa)
    assert np.allclose(np.asarray(arr.sum(axis=axis)), np.asarray(sa.sum(axis=axis)).squeeze())
    assert np.allclose(np.asarray(arr.mean(axis=axis)), np.asarray(sa.mean(axis=axis)).squeeze())


@pytest.mark.parametrize("k", [-2, -1, 0, 1, 2])
def test_csr_diagonal_k(k):
    sa = sample_csr(12, 15, density=0.4, seed=87).tocsr()
    got = sparse.csr_array(sa).diagonal(k=k)
    assert np.allclose(np.asarray(got), sa.diagonal(k=k))


def test_zero_preserving_ufuncs():
    sa = sample_csr(11, 9, density=0.4, seed=88).tocsr()
    arr = sparse.csr_array(sa)
    assert np.allclose(np.asarray(arr.sqrt().todense()), np.sqrt(sa.todense()))
    assert np.allclose(np.asarray(arr.sin().todense()), np.sin(np.asarray(sa.todense())))
    assert np.allclose(np.asarray(arr.expm1().todense()), np.expm1(np.asarray(sa.todense())))


def test_multiply_broadcast_vectors_stay_sparse():
    """Column/row-vector multiply must not materialize the [m, n]
    broadcast (the AMG smoothed prolongator scales rows of a 262k^2
    operator; a dense broadcast there is 512 GB)."""
    import numpy as np
    import scipy.sparse as sp

    import sparse_tpu as sparse

    rng = np.random.default_rng(0)
    S = sp.random(40, 23, 0.3, random_state=rng, format="csr")
    A = sparse.csr_array(S)
    col = rng.standard_normal((40, 1))
    row = rng.standard_normal((1, 23))
    vec = rng.standard_normal(23)
    for other in (col, row, vec, np.full((1, 1), 2.5)):
        want = S.multiply(other).toarray()
        got = A.multiply(other).toarray()
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-12)
    # full dense operand still works, wrong shapes still raise
    D = rng.standard_normal((40, 23))
    np.testing.assert_allclose(
        np.asarray(A.multiply(D).toarray()), S.multiply(D).toarray(), rtol=1e-12
    )
    try:
        A.multiply(np.ones((3, 2)))
    except ValueError:
        pass
    else:
        raise AssertionError("expected ValueError for inconsistent shapes")
