"""Distributed quantum evolution: solve_ivp over a mesh-sharded Hamiltonian.

The BASELINE.md quantum workload at scale: the Hamiltonian is a DistCSR
(complex), the state vector a padded mesh-sharded array, and the RK step's
norms/dots become GSPMD psums — so the same solve_ivp drives single-chip
and mesh runs.
"""

import networkx as nx
import numpy as np
import pytest

import sparse_tpu.integrate as integrate
from sparse_tpu import quantum
from sparse_tpu.parallel.dist import shard_csr
from sparse_tpu.parallel.mesh import get_mesh


@pytest.mark.parametrize("num_shards", [2, 8])
def test_quantum_evolution_distributed_matches_single(num_shards):
    g = nx.cycle_graph(7)
    driver = quantum.HamiltonianDriver(graph=g, dtype=np.complex128)
    H = driver.hamiltonian
    n = H.shape[0]
    y0 = np.zeros(n, dtype=np.complex128)
    y0[0] = 1.0

    def rhs_single(t, y):
        return -1j * (H @ y)

    sol = integrate.solve_ivp(rhs_single, (0.0, 0.5), y0, method="RK45",
                              rtol=1e-8, atol=1e-10)
    y_ref = np.asarray(sol.y[:, -1])

    mesh = get_mesh(num_shards)
    D = shard_csr(H, mesh=mesh, balanced=True)
    y0p = D.pad_vector(y0)

    def rhs_dist(t, yp):
        return -1j * D.spmv_padded(yp)

    sol_d = integrate.solve_ivp(rhs_dist, (0.0, 0.5), y0p, method="RK45",
                                rtol=1e-8, atol=1e-10)
    y_dist = D.unpad_vector(np.asarray(sol_d.y[:, -1]))
    assert np.allclose(y_dist, y_ref, atol=1e-6)
    # unitary evolution: norm preserved
    assert abs(np.linalg.norm(y_dist) - 1.0) < 1e-6


@pytest.mark.slow
def test_quantum_build_at_1e5_states_distributed():
    """VERDICT r2 #10: the distributed Hamiltonian build (mesh samplesort
    group sorts + distributed COO->CSR) at >=1e5 independent sets —
    cycle_graph(25) has L_25 = 167,761 of them — matches the single-host
    build exactly, and the mesh RK path evolves the result."""
    g = nx.cycle_graph(25)
    dist = quantum.HamiltonianDriver(graph=g, dtype=np.complex128,
                                     dist_shards=8)
    assert dist.nstates >= 100_000
    single = quantum.HamiltonianDriver(graph=g, dtype=np.complex128)
    Hd, Hs = dist.hamiltonian, single.hamiltonian
    assert np.array_equal(np.asarray(Hd.indptr), np.asarray(Hs.indptr))
    assert np.array_equal(np.asarray(Hd.indices), np.asarray(Hs.indices))
    assert np.allclose(np.asarray(Hd.data), np.asarray(Hs.data))

    # short mesh evolution: the BASELINE.md quantum workload shape at scale
    mesh = get_mesh(8)
    D = shard_csr(Hd, mesh=mesh, balanced=True)
    y0 = np.zeros(dist.nstates, dtype=np.complex128)
    y0[-1] = 1.0
    y0p = D.pad_vector(y0)

    def rhs(t, yp):
        return -1j * D.spmv_padded(yp)

    sol = integrate.solve_ivp(rhs, (0.0, 0.02), y0p, method="RK45",
                              rtol=1e-6, atol=1e-9)
    y = D.unpad_vector(np.asarray(sol.y[:, -1]))
    assert abs(np.linalg.norm(y) - 1.0) < 1e-6
