"""CSC format surface oracle tests vs scipy.

Reference analog: ``tests/integration/test_csc.py``.
"""

import numpy as np
import pytest
import scipy.io as sci_io

import sparse_tpu as sparse
from .utils.common import test_mtx_files, types
from .utils.sample import sample_csr, sample_dense, sample_vec


@pytest.mark.parametrize("filename", test_mtx_files)
def test_csc_from_dense(filename):
    s = sci_io.mmread(filename)
    arr = sparse.csc_array(np.asarray(s.todense()))
    assert np.allclose(np.asarray(arr.todense()), s.todense())


@pytest.mark.parametrize("filename", test_mtx_files)
def test_csc_to_coo(filename):
    arr = sparse.io.mmread(filename).tocsc()
    s = sci_io.mmread(filename).tocsc()
    assert np.allclose(np.asarray(arr.tocoo().todense()), s.tocoo().todense())


@pytest.mark.parametrize("filename", test_mtx_files)
def test_csc_to_csr(filename):
    arr = sparse.io.mmread(filename).tocsc()
    s = sci_io.mmread(filename).tocsc()
    assert np.allclose(np.asarray(arr.tocsr().todense()), s.tocsr().todense())


@pytest.mark.parametrize("filename", test_mtx_files)
def test_csc_elemwise_mul(filename):
    arr = sparse.io.mmread(filename).tocsc()
    s = sci_io.mmread(filename).tocsc()
    rolled = np.roll(np.asarray(arr.todense()), 1)
    res = arr * sparse.csc_array(rolled)
    res_sci = s.multiply(np.roll(np.asarray(s.todense()), 1))
    assert np.allclose(np.asarray(res.todense()), np.asarray(res_sci.todense()), atol=1e-6)


@pytest.mark.parametrize("filename", test_mtx_files)
def test_csc_elemwise_add(filename):
    arr = sparse.io.mmread(filename).tocsc()
    s = sci_io.mmread(filename).tocsc()
    rolled = np.roll(np.asarray(arr.todense()), 1)
    res = arr + sparse.csc_array(rolled)
    import scipy.sparse as scpy

    res_sci = s + scpy.csc_matrix(np.roll(np.asarray(s.todense()), 1))
    assert np.allclose(np.asarray(res.todense()), np.asarray(res_sci.todense()), atol=1e-6)


@pytest.mark.parametrize("filename", test_mtx_files)
def test_csc_transpose(filename):
    arr = sparse.io.mmread(filename).tocsc().T
    s = sci_io.mmread(filename).tocsc().T
    assert np.allclose(np.asarray(arr.todense()), np.asarray(s.todense()))


def test_csc_conj():
    sa = sample_csr(9, 11, density=0.3, dtype=np.complex128, seed=91).tocsc()
    got = sparse.csc_array(sa).conj()
    assert np.allclose(np.asarray(got.todense()), sa.conj().todense())


@pytest.mark.parametrize("b_type", [np.float32, np.complex128])
@pytest.mark.parametrize("c_type", types)
def test_csc_spmm(b_type, c_type):
    sa = sample_csr(18, 22, density=0.25, dtype=b_type, seed=92).tocsc()
    B = sample_dense(22, 7, dtype=c_type, seed=93)
    got = np.asarray(sparse.csc_array(sa) @ B)
    exp = sa @ B
    assert got.dtype == exp.dtype
    assert np.allclose(got, exp, atol=1e-5)


@pytest.mark.parametrize("vec_type", types)
def test_csc_dot(vec_type):
    sa = sample_csr(18, 22, density=0.25, seed=94).tocsc()
    v = sample_vec(22, dtype=vec_type, seed=95)
    assert np.allclose(np.asarray(sparse.csc_array(sa) @ v), sa @ v, atol=1e-5)


@pytest.mark.parametrize("filename", test_mtx_files)
def test_csc_todense(filename):
    arr = sparse.io.mmread(filename).tocsc()
    s = sci_io.mmread(filename).tocsc()
    assert np.allclose(np.asarray(arr.todense()), np.asarray(s.todense()))


@pytest.mark.parametrize("axis", [None, 0, 1])
def test_csc_sum(axis):
    sa = sample_csr(13, 17, density=0.3, seed=96).tocsc()
    got = np.asarray(sparse.csc_array(sa).sum(axis=axis))
    exp = np.asarray(sa.sum(axis=axis)).squeeze()
    assert np.allclose(got, exp)
