"""Mixed precision as the fast path (ISSUE 15): policy, IR solver, keys.

The load-bearing contracts:

* **Accuracy** — the ``ir`` solver (reduced-precision inner Krylov
  sweeps under the f64 iterative-refinement outer loop) reaches the
  f64 answer at its absolute tolerance; ``scripts/f64_oracle.py``'s
  per-size table is pinned HERE (the oracle-fixture satellite), not
  just pasted into BENCH_NOTES.md. The divergence safeguard returns
  the best iterate, reported unconverged, when refinement cannot
  contract.
* **Kernels** — the SELL/DIA formulations accept a storage dtype
  distinct from the accumulation dtype (``acc_dtype``): bf16/f32
  value planes, wide products/reductions; ``None`` stays
  byte-identical. The fused Pallas CG's recurrence scalars carry the
  same split.
* **Policy/keys** — SPARSE_TPU_DTYPE / per-session / per-ticket
  resolution, ``.P<policy>``-suffixed program keys with 'exact'
  byte-identical to the historic keys and numerics, vault manifest
  round-trip at zero serving misses, and the promote_dtype rung
  (anomalous reduced buckets escalate to 'exact' through the requeue
  machinery, ahead of solver escalation).
* **Frozen lanes** — converged lanes stay bit-stable under the IR
  outer loop while neighbors keep refining.

Runs on the conftest-forced 8-device virtual CPU mesh.
"""

import importlib.util
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sp

import sparse_tpu
from sparse_tpu import linalg, mixed, plan_cache, telemetry, vault
from sparse_tpu.batch import SolveSession, SparsityPattern
from sparse_tpu.batch.krylov import batched_ir
from sparse_tpu.batch.operator import BatchedCSR
from sparse_tpu.config import settings
from sparse_tpu.resilience import faults
from sparse_tpu.telemetry import _cost, _metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state(tmp_path):
    faults.clear()
    old_vault = settings.vault
    old_tel = settings.telemetry
    old_policy = settings.dtype_policy
    settings.vault = ""
    telemetry.configure(str(tmp_path / "records.jsonl"))
    telemetry.reset()
    plan_cache.clear()
    yield
    faults.clear()
    settings.vault = old_vault
    settings.telemetry = old_tel
    settings.dtype_policy = old_policy
    telemetry.configure(None)


def _tridiag(n=64, seed=0, diag=3.0, dtype=np.float64):
    rng = np.random.default_rng(seed)
    e = np.ones(n)
    A = sp.diags([-e[:-1], diag * e, -e[:-1]], [-1, 0, 1], format="csr")
    A = A.copy()
    A.setdiag(diag + rng.random(n))
    A = A.tocsr().astype(dtype)
    A.sort_indices()
    return A


def _pattern(A):
    return SparsityPattern(A.indptr, A.indices, A.shape)


# ---------------------------------------------------------------------------
# policy resolution and key suffixes
# ---------------------------------------------------------------------------
def test_canonical_policy_spellings():
    for s in ("", "off", "none", "exact", None, "0", "false"):
        assert mixed.canonical_policy(s) == "exact"
    assert mixed.canonical_policy("f32ir") == "f32ir"
    assert mixed.canonical_policy("BF16IR") == "bf16ir"
    assert mixed.canonical_policy("auto") == "auto"
    with pytest.raises(ValueError):
        mixed.canonical_policy("auto", allow_auto=False)
    with pytest.raises(ValueError):
        mixed.canonical_policy("f16")


def test_key_suffix_backcompat():
    assert mixed.key_suffix("exact") == ""
    assert mixed.key_suffix(None) == ""
    assert mixed.key_suffix("f32ir") == ".Pf32ir"
    assert mixed.key_suffix("bf16ir") == ".Pbf16ir"


def test_inner_dtypes_split():
    s, c = mixed.inner_dtypes("f32ir")
    assert s == np.float32 and c == np.float32
    s, c = mixed.inner_dtypes("bf16ir")
    assert s == jnp.bfloat16 and c == np.float32
    assert mixed.outer_dtype() == np.float64


def test_policy_auto_and_env():
    A = _tridiag(16)
    pat = _pattern(A)
    pol = mixed.DtypePolicy("auto")
    assert pol.decide(pat, "cg", 4, np.float64) == "f32ir"
    assert pol.decide(pat, "bicgstab", 4, np.float64) == "f32ir"
    # gmres has no fused IR loop; f32 requests stay exact under auto
    assert pol.decide(pat, "gmres", 4, np.float64) == "exact"
    assert pol.decide(pat, "cg", 4, np.float32) == "exact"
    settings.dtype_policy = "f32ir"
    try:
        pol2 = mixed.DtypePolicy()
        assert pol2.mode == "f32ir"
        assert pol2.decide(pat, "cg", 4, np.float64,
                           override="exact") == "exact"
    finally:
        settings.dtype_policy = ""
    with pytest.raises(ValueError):
        mixed.DtypePolicy("bogus")


def test_policy_degrades_complex_and_gmres():
    A = _tridiag(16)
    pat = _pattern(A)
    pol = mixed.DtypePolicy("f32ir")
    assert pol.decide(pat, "cg", 4, np.complex128) == "exact"
    assert pol.decide(pat, "gmres", 4, np.float64) == "exact"
    assert pol.decide(pat, "cg", 4, np.float64) == "f32ir"


def test_promote_pins_group_and_counts():
    A = _tridiag(16)
    pat = _pattern(A)
    pol = mixed.DtypePolicy("f32ir")
    assert pol.decide(pat, "cg", 4, np.float64) == "f32ir"
    before = float(
        _metrics.counter("mixed.promotions", reason="unit").value
    )
    pol.promote(pat, "cg", 4, np.float64, reason="unit")
    assert pol.decide(pat, "cg", 4, np.float64) == "exact"
    # other buckets of the same pattern are untouched
    assert pol.decide(pat, "cg", 8, np.float64) == "f32ir"
    after = float(
        _metrics.counter("mixed.promotions", reason="unit").value
    )
    assert after - before == 1
    assert pol.describe()["promoted_groups"] == 1


def test_ir_knobs_scale_with_n():
    pol = mixed.DtypePolicy("f32ir")
    small = pol.ir_knobs("f32ir", 64, 25)
    big = pol.ir_knobs("f32ir", 100_000, 25)
    assert small["inner_iters"] >= 200
    assert big["inner_iters"] == 4000  # capped
    assert big["max_outer"] >= 1 and big["eta"] > 0


# ---------------------------------------------------------------------------
# the ir solver: accuracy, parity, safeguards
# ---------------------------------------------------------------------------
def test_ir_matches_exact_cg():
    A = _tridiag(96, seed=1)
    b = np.random.default_rng(2).standard_normal(96)
    x64, _ = linalg.cg(sparse_tpu.csr_array(A), b, tol=1e-10, maxiter=4000)
    x, info = mixed.ir_solve(A, b, tol=1e-10, policy="f32ir")
    assert np.asarray(info.converged).all()
    assert np.linalg.norm(A @ np.asarray(x) - b) <= 1e-10
    assert np.allclose(np.asarray(x), np.asarray(x64), atol=1e-9)


def test_ir_f32_request_reaches_beyond_f32():
    """The point of the outer f64 loop: an f32-stored operator still
    solves to an absolute residual plain f32 CG cannot reach."""
    A = _tridiag(96, seed=3, dtype=np.float32)
    b = np.random.default_rng(4).standard_normal(96).astype(np.float32)
    x, info = mixed.ir_solve(A, b, tol=1e-11, policy="f32ir")
    assert np.asarray(info.converged).all()
    r = A.astype(np.float64) @ np.asarray(x, dtype=np.float64) - b.astype(
        np.float64
    )
    assert np.linalg.norm(r) <= 1e-11


def test_ir_bf16_storage_converges_well_conditioned():
    A = _tridiag(64, seed=5)
    b = np.random.default_rng(6).standard_normal(64)
    x, info = mixed.ir_solve(A, b, tol=1e-9, policy="bf16ir")
    assert np.asarray(info.converged).all()
    assert np.linalg.norm(A @ np.asarray(x) - b) <= 1e-9
    assert info.outer >= 2  # bf16 storage genuinely needs refinement


def test_batched_ir_lanes_and_outer_counter():
    A = _tridiag(48, seed=7)
    pat = _pattern(A)
    B = 3
    vals = np.stack([A.data * (1.0 + 0.01 * i) for i in range(B)])
    op = BatchedCSR(pat, vals)
    rhs = np.random.default_rng(8).standard_normal((B, 48))
    before = float(_metrics.counter("mixed.ir_outer_iters").value)
    X, info = batched_ir(op, rhs, tol=1e-9)
    after = float(_metrics.counter("mixed.ir_outer_iters").value)
    assert np.asarray(info.converged).all()
    assert after > before
    for i in range(B):
        Ai = sp.csr_matrix((vals[i], A.indices, A.indptr), shape=A.shape)
        assert np.linalg.norm(Ai @ np.asarray(X[i]) - rhs[i]) <= 1e-9


def test_linalg_ir_entry_point():
    A = _tridiag(48, seed=9)
    b = np.ones(48)
    x, iters = linalg.ir(sparse_tpu.csr_array(A), b, tol=1e-9)
    assert isinstance(iters, int) and iters > 0
    assert np.linalg.norm(A @ np.asarray(x) - b) <= 1e-9
    assert "ir" in linalg.__all__ and "batched_ir" in linalg.__all__


def test_ir_rejects_complex_and_exact():
    A = _tridiag(16).astype(np.complex128)
    with pytest.raises(ValueError):
        mixed.ir_solve(A, np.ones(16, complex), policy="f32ir")
    with pytest.raises(ValueError):
        mixed.ir_solve(_tridiag(16), np.ones(16), policy="exact")


def test_ir_divergence_safeguard_returns_best():
    """A deliberately WRONG low-precision operator (2x the true one)
    cannot contract — the safeguard must freeze at the best iterate,
    finite and unconverged, instead of diverging."""
    from sparse_tpu.ops.spmv import csr_spmv_segment
    from sparse_tpu.utils import asjnp

    A = _tridiag(32, seed=10)
    indptr, indices = asjnp(A.indptr), asjnp(A.indices)

    def mk(vals):
        def mv(X):
            return jax.vmap(
                lambda v: csr_spmv_segment(indptr, indices, vals, v, 32)
            )(X)

        return mv

    mvw = mk(asjnp(A.data))
    mvl = mk(jnp.asarray(2.0 * A.data, dtype=jnp.float32))  # WRONG operator

    b = np.random.default_rng(11).standard_normal(32)
    x, info = mixed.ir_solve((mvw, mvl), b, tol=1e-12, policy="f32ir",
                             max_outer=10)
    r = np.linalg.norm(A @ np.asarray(x) - b)
    assert np.isfinite(r)
    assert not np.asarray(info.converged).all()
    # best iterate beats the trivial x=0 start (one half-step correction)
    assert r < np.linalg.norm(b)


def test_frozen_lane_bit_stability_under_ir():
    """Lane 0 (loose tol) freezes while lane 1 refines; its bits must
    not depend on how long lane 1 keeps the outer loop alive."""
    A = _tridiag(40, seed=12)
    op = BatchedCSR(_pattern(A), np.stack([A.data, A.data]))
    rng = np.random.default_rng(13)
    b0 = rng.standard_normal(40)
    b1 = rng.standard_normal(40)
    b1_alt = rng.standard_normal(40)
    tols = np.asarray([1e-3, 1e-12])
    X_a, _ = batched_ir(op, np.stack([b0, b1]), tol=tols)
    X_b, _ = batched_ir(op, np.stack([b0, b1_alt]), tol=tols)
    assert np.array_equal(np.asarray(X_a[0]), np.asarray(X_b[0]))


# ---------------------------------------------------------------------------
# the f64_oracle fixture (satellite: the table pinned in CI)
# ---------------------------------------------------------------------------
def test_f64_oracle_table_pinned():
    spec = importlib.util.spec_from_file_location(
        "f64_oracle", os.path.join(REPO, "scripts", "f64_oracle.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    row = mod.run(24)  # small grid: the same columns, seconds not minutes
    # plain f32 plateaus orders of magnitude above f64...
    assert row["rel_resid_f32"] > 100 * row["rel_resid_f64"]
    # ...while the IR solver matches the f64 target it was driven to
    assert row["f32ir_converged"]
    assert row["rel_resid_f32ir"] <= max(row["rel_resid_f64"] * 1.01, 1e-12)
    assert row["bf16ir_converged"]
    assert row["rel_resid_bf16ir"] <= max(row["rel_resid_f64"] * 1.01, 1e-12)
    assert row["f32ir_outer"] >= 1 and row["f32ir_inner_iters"] > 0


# ---------------------------------------------------------------------------
# kernel storage/accumulation splits
# ---------------------------------------------------------------------------
def test_sell_spmv_acc_dtype_widening():
    from sparse_tpu.ops import spmv as spmv_ops

    A = _tridiag(64, seed=14)
    pat = _pattern(A)
    pack = pat.sell_pack()
    x = np.random.default_rng(15).standard_normal(64)
    y64 = A @ x
    vals_bf = pack.pack_values(
        jnp.asarray(A.data, dtype=jnp.float32)[None].astype(jnp.bfloat16)
    )
    y = spmv_ops.csr_spmv_sell_batched(
        pack.idx_slabs, vals_bf, pack.pos,
        jnp.asarray(x, dtype=jnp.float32)[None], pack.plan.zero_rows,
        acc_dtype=jnp.float32,
    )
    assert y.dtype == jnp.float32
    rel = np.abs(np.asarray(y[0]) - y64).max() / np.abs(y64).max()
    assert rel < 2e-2  # bf16 storage error, not accumulation error


def test_segment_spmv_acc_dtype():
    from sparse_tpu.ops.spmv import csr_spmv_segment
    from sparse_tpu.utils import asjnp

    A = _tridiag(48, seed=16)
    x = np.random.default_rng(17).standard_normal(48)
    vals_bf = jnp.asarray(A.data, dtype=jnp.float32).astype(jnp.bfloat16)
    y = csr_spmv_segment(
        asjnp(A.indptr), asjnp(A.indices), vals_bf,
        jnp.asarray(x, dtype=jnp.float32), 48, acc_dtype=jnp.float32,
    )
    assert y.dtype == jnp.float32
    rel = np.abs(np.asarray(y) - A @ x).max() / np.abs(A @ x).max()
    assert rel < 2e-2
    # default path unchanged: no acc_dtype => result_type behavior
    y64 = csr_spmv_segment(
        asjnp(A.indptr), asjnp(A.indices), asjnp(A.data), asjnp(x), 48
    )
    assert y64.dtype == jnp.float64


def test_dia_spmv_acc_dtype():
    from sparse_tpu.ops.dia_spmv import dia_spmv_xla

    n = 32
    e = np.ones(n)
    data = np.stack([-e, 3.0 * e, -e])
    offsets = (-1, 0, 1)
    x = np.random.default_rng(18).standard_normal(n)
    y64 = np.asarray(dia_spmv_xla(jnp.asarray(data), offsets,
                                  jnp.asarray(x), (n, n)))
    y = dia_spmv_xla(
        jnp.asarray(data, dtype=jnp.float32).astype(jnp.bfloat16), offsets,
        jnp.asarray(x, dtype=jnp.float32), (n, n),
        acc_dtype=jnp.float32,
    )
    assert y.dtype == jnp.float32
    assert np.abs(np.asarray(y) - y64).max() / np.abs(y64).max() < 2e-2


def test_cg_dia_fused_acc_dtype_noop_is_identical():
    """acc_dtype=None vs acc_dtype=<the vector dtype> must be the SAME
    program numerically (the no-op convert contract)."""
    from sparse_tpu.kernels.cg_dia import cg_dia_fused

    n = 64
    e = np.ones(n)
    data = jnp.asarray(np.stack([-e, 3.0 * e, -e]))
    b = jnp.asarray(np.random.default_rng(19).standard_normal(n))
    x1, r1, rho1 = cg_dia_fused(data, (-1, 0, 1), b, None, n, iters=20,
                                interpret=True)
    x2, r2, rho2 = cg_dia_fused(data, (-1, 0, 1), b, None, n, iters=20,
                                interpret=True, acc_dtype=jnp.float64)
    assert np.array_equal(np.asarray(x1), np.asarray(x2))
    assert float(rho1) == float(rho2)


def test_cg_dia_fused_wide_scalars_for_f32():
    """f32 vectors with f64 recurrence scalars: the dot partials carry
    f64 and the iterates stay close to the all-f64 run."""
    from sparse_tpu.kernels.cg_dia import cg_dia_fused

    n = 64
    e = np.ones(n)
    data64 = jnp.asarray(np.stack([-e, 3.0 * e, -e]))
    b64 = jnp.asarray(np.random.default_rng(20).standard_normal(n))
    x64, _, _ = cg_dia_fused(data64, (-1, 0, 1), b64, None, n, iters=30,
                             interpret=True)
    x32, _, rho32 = cg_dia_fused(
        data64.astype(jnp.float32), (-1, 0, 1), b64.astype(jnp.float32),
        None, n, iters=30, interpret=True, acc_dtype=jnp.float64,
    )
    assert rho32.dtype == jnp.float64
    assert np.abs(np.asarray(x32) - np.asarray(x64)).max() < 1e-4


# ---------------------------------------------------------------------------
# serving integration: keys, invariance, promote rung, vault
# ---------------------------------------------------------------------------
def test_session_program_keys_and_per_ticket_override():
    A = _tridiag(32, seed=21)
    b = np.ones(32)
    _cost.reset()
    ses = SolveSession("cg", warm_start=False, dtype_policy="f32ir")
    t1 = ses.submit(A, b, tol=1e-9, maxiter=2000)
    t2 = ses.submit(A, b, tol=1e-9, maxiter=2000, dtype_policy="exact")
    ses.flush()
    for t in (t1, t2):
        x, _i, r2 = t.result()
        assert np.sqrt(r2) <= 1e-9 * 1.01
    keys = set(_cost.programs())
    assert "batch.cg.B1.<f8.Pf32ir" in keys
    assert "batch.cg.B1.<f8" in keys  # the exact override: historic key


def test_exact_policy_is_bit_identical_to_default():
    A = _tridiag(32, seed=22)
    b = np.random.default_rng(23).standard_normal(32)
    _cost.reset()
    ses_d = SolveSession("cg", warm_start=False)
    td = ses_d.submit(A, b, tol=1e-9, maxiter=2000)
    ses_d.flush()
    ses_e = SolveSession("cg", warm_start=False, dtype_policy="exact")
    te = ses_e.submit(A, b, tol=1e-9, maxiter=2000)
    ses_e.flush()
    xd, id_, rd = td.result()
    xe, ie, re_ = te.result()
    assert np.array_equal(np.asarray(xd), np.asarray(xe))
    assert id_ == ie and rd == re_
    # one shared historic key — no .P suffix anywhere
    assert set(_cost.programs()) == {"batch.cg.B1.<f8"}


def test_ir_bucket_program_solves_and_counts_outer():
    A = _tridiag(48, seed=24)
    mats = [A.copy() for _ in range(4)]
    for i, m in enumerate(mats):
        m.setdiag(m.diagonal() + 0.01 * i)
    rhs = np.random.default_rng(25).standard_normal((4, 48))
    before = float(_metrics.counter("mixed.ir_outer_iters").value)
    ses = SolveSession("cg", warm_start=False, dtype_policy="f32ir")
    X, iters, r2 = ses.solve_many(mats, rhs, tol=1e-9, maxiter=4000)
    after = float(_metrics.counter("mixed.ir_outer_iters").value)
    assert after > before
    for i, m in enumerate(mats):
        assert np.linalg.norm(m @ X[i] - rhs[i]) <= 1e-9 * 1.5


def test_promote_dtype_rung_end_to_end():
    """Injected corruption in the inner f32 sweep: the promote rung
    requeues at exact (same solver), the ticket converges, and the
    group is pinned so later dispatches are exact."""
    A = _tridiag(64, seed=26)
    b = np.random.default_rng(27).standard_normal(64)
    settings.telemetry = True
    faults.configure("nonfinite:matvec:p=1,n=6,seed=3")

    def promos():
        # the divergence safeguard reports a NaN-corrupted lane as
        # unconverged-with-finite-best-residual, so either reason is a
        # correct classification of the injected anomaly
        return sum(
            float(_metrics.counter("mixed.promotions", reason=r).value)
            for r in ("nonfinite", "unconverged")
        )

    before = promos()
    try:
        ses = SolveSession("cg", warm_start=False, dtype_policy="f32ir")
        t = ses.submit(A, b, tol=1e-9, maxiter=4000)
        ses.flush()
        x, _i, _r = t.result()
    finally:
        faults.clear()
    assert t.converged and t.promoted
    assert np.linalg.norm(A @ np.asarray(x) - b) <= 1e-9 * 1.5
    assert promos() - before == 1
    kinds = [e.get("kind") for e in telemetry.events()]
    assert "mixed.promote" in kinds
    actions = [e.get("action") for e in telemetry.events()
               if e.get("kind") == "batch.requeue"]
    assert "promote_dtype" in actions
    # the group is pinned: the next dispatch resolves exact
    pat = ses.pattern_of(A)
    assert ses.dtype_policy.decide(pat, "cg", 1, np.float64) == "exact"


def test_ticket_event_carries_dtype_policy_label():
    A = _tridiag(32, seed=28)
    settings.telemetry = True
    ses = SolveSession("cg", warm_start=False, dtype_policy="f32ir")
    t = ses.submit(A, np.ones(32), tol=1e-9, maxiter=2000)
    ses.flush()
    t.result()
    ev = [e for e in telemetry.events() if e.get("kind") == "batch.ticket"]
    assert ev and ev[-1]["dtype_policy"] == "f32ir"
    assert ev[-1]["promoted"] is False
    # exact tickets keep the historic event shape (no dtype_policy key)
    telemetry.reset()
    ses2 = SolveSession("cg", warm_start=False)
    t2 = ses2.submit(A, np.ones(32), tol=1e-9, maxiter=2000)
    ses2.flush()
    t2.result()
    ev2 = [e for e in telemetry.events() if e.get("kind") == "batch.ticket"]
    assert ev2 and "dtype_policy" not in ev2[-1]


def test_vault_manifest_precision_keyed_warm_restart(tmp_path):
    A = _tridiag(48, seed=29)
    b = np.random.default_rng(30).standard_normal(48)
    settings.vault = str(tmp_path / "vault")
    ses = SolveSession("cg", warm_start=False, dtype_policy="f32ir")
    t = ses.submit(A, b, tol=1e-9, maxiter=4000)
    ses.flush()
    t.result()
    entries = vault.manifest_entries()
    assert any(e.get("dtype_policy") == "f32ir" for e in entries)
    plan_cache.clear()
    ses2 = SolveSession("cg", warm_start=True, warm_async=False,
                        dtype_policy="f32ir")
    assert ses2.warm_replayed >= 1
    snap = plan_cache.snapshot()
    t2 = ses2.submit(A, b, tol=1e-9, maxiter=4000)
    ses2.flush()
    x2, _i, _r = t2.result()
    assert plan_cache.delta(snap)["misses"] == 0
    assert np.linalg.norm(A @ np.asarray(x2) - b) <= 1e-9 * 1.5


def test_session_stats_dtype_policy_block():
    ses = SolveSession("cg", warm_start=False, dtype_policy="f32ir")
    blk = ses.session_stats()["dtype_policy"]
    assert blk["mode"] == "f32ir" and blk["enabled"]
    ses2 = SolveSession("cg", warm_start=False)
    assert ses2.session_stats()["dtype_policy"]["mode"] == "exact"


def test_schema_kind_registered_and_validates():
    from sparse_tpu.telemetry import _schema

    assert "mixed.promote" in _schema.KINDS
    ev = {"kind": "mixed.promote", "ts": 1.0, "reason": "nonfinite",
          "lanes": 2}
    assert _schema.validate(ev) == []
    assert _schema.validate({"kind": "mixed.promote", "ts": 1.0})
