"""DIA format surface oracle tests vs scipy.

Reference analog: ``tests/integration/test_dia.py``.
"""

import numpy as np
import pytest
import scipy.sparse as scpy

import sparse_tpu as sparse
from .utils.sample import sample_csr, sample_vec


def test_dia_to_csr():
    s = scpy.diags([1.0, 2.0, 3.0], [-1, 0, 1], shape=(6, 6)).todia()
    arr = sparse.dia_array(s)
    assert np.allclose(np.asarray(arr.tocsr().todense()), s.tocsr().todense())


def test_spdiags_roundtrip():
    data = np.arange(12.0).reshape(3, 4)
    offsets = np.array([0, -1, 2])
    got = sparse.spdiags(data, offsets, 4, 4)
    exp = scpy.spdiags(data, offsets, 4, 4)
    assert np.allclose(np.asarray(got.todense()), exp.todense())


@pytest.mark.parametrize("m,n,k", [(5, 5, 0), (4, 6, 1), (6, 4, -1)])
def test_eye_dia(m, n, k):
    got = sparse.eye(m, n, k=k, format="dia")
    exp = scpy.eye(m, n, k=k, format="dia")
    assert got.format == "dia"
    assert np.allclose(np.asarray(got.todense()), exp.todense())


@pytest.mark.parametrize("m,n,k", [(5, 5, 0), (5, 8, 2), (8, 5, -2)])
def test_dia_diagonal(m, n, k):
    s = sample_csr(m, n, density=0.5, seed=101).todia()
    arr = sparse.dia_array(s)
    assert np.allclose(np.asarray(arr.diagonal(k=k)), s.diagonal(k=k))


@pytest.mark.parametrize("m,n", [(5, 5), (4, 7), (7, 4)])
def test_dia_to_coo(m, n):
    s = sample_csr(m, n, density=0.5, seed=102).todia()
    arr = sparse.dia_array(s)
    assert np.allclose(np.asarray(arr.tocoo().todense()), s.tocoo().todense())


def test_dia_spmv_matches_scipy():
    s = scpy.diags(
        [np.full(63, -1.0), np.full(64, 2.0), np.full(63, -1.0)],
        [-1, 0, 1],
    ).todia()
    arr = sparse.dia_array(s)
    v = sample_vec(64, seed=103)
    assert np.allclose(np.asarray(arr @ v), s @ v)


def test_dia_transpose():
    s = sample_csr(6, 9, density=0.4, seed=104).todia()
    arr = sparse.dia_array(s)
    assert np.allclose(np.asarray(arr.T.todense()), s.T.todense())


def test_dia_sum_scalar_mul():
    s = sample_csr(7, 7, density=0.4, seed=105).todia()
    arr = sparse.dia_array(s)
    assert np.allclose(float(np.asarray(arr.sum())), s.sum())
    assert np.allclose(np.asarray((arr * 2.0).todense()), (s * 2).todense())
