"""Vault persistent plan-cache tier (ISSUE 9): crash-safe artifacts,
corruption quarantine, warm restart, disk-fault chaos.

The load-bearing contracts:

* **Corruption never escapes** — every corrupt/truncated/stale/
  mistyped artifact (and every injected ``io:*`` fault) loads as a
  clean miss: quarantined, counted, rebuilt. No exception reaches the
  caller; the rebuilt layout is identical to a cold pack.
* **Round-trip parity** — a disk-loaded ``PreparedCSR`` /
  ``PreparedDia`` / SELL pattern pack computes exactly what the fresh
  pack computes, across f32/f64/c64.
* **Warm restart** — a new "process" (cleared in-process tier) replays
  the manifest and serves at zero plan-cache misses.
* **Inert when off / invisible to traces** — ``SPARSE_TPU_VAULT``
  unset writes nothing; vault on vs off never changes a traced program
  (jaxpr string equality).
"""

import json
import os
import subprocess
import sys
import time

import jax
import numpy as np
import pytest
import scipy.sparse as sp

import sparse_tpu
from sparse_tpu import plan_cache, telemetry, vault
from sparse_tpu.batch import SolveSession
from sparse_tpu.batch.operator import SparsityPattern
from sparse_tpu.config import settings
from sparse_tpu.resilience import faults
from sparse_tpu.vault import _codecs, _manifest, _store

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state(tmp_path):
    """Each test gets a scratch vault + sink, a cold in-process tier,
    and ends with the vault disabled again."""
    faults.clear()
    old_vault = settings.vault
    old_tel = settings.telemetry
    settings.vault = str(tmp_path / "vault")
    telemetry.configure(str(tmp_path / "records.jsonl"))
    telemetry.reset()
    plan_cache.clear()
    yield
    faults.clear()
    settings.vault = old_vault
    settings.telemetry = old_tel
    telemetry.configure(None)
    telemetry.reset()
    plan_cache.clear()


def _spd(n=48, seed=0):
    rng = np.random.default_rng(seed)
    e = np.ones(n)
    A = sp.diags([-e[:-1], 3.0 * e, -e[:-1]], [-1, 0, 1], format="csr")
    A = A.copy()
    A.setdiag(3.0 + rng.random(n))
    A.sort_indices()
    return A


def _skewed(n=120, seed=0, dtype=np.float64):
    """A matrix the SELL path takes (one heavy row defeats the ELL gate)."""
    rng = np.random.default_rng(seed)
    A = sp.random(n, n, density=0.05, format="lil", random_state=seed)
    A[0, : n // 2] = 1.0
    A = A.tocsr().astype(dtype)
    A.setdiag(np.abs(A.diagonal()) + n)
    A.sort_indices()
    return A.tocsr()


def _quarantine_files():
    try:
        return sorted(os.listdir(vault.quarantine_dir()))
    except OSError:
        return []


# ---------------------------------------------------------------------------
# raw store
# ---------------------------------------------------------------------------
class TestStore:
    def test_roundtrip(self):
        arrays = {"a": np.arange(6, dtype=np.int64),
                  "b": np.ones((2, 3), dtype=np.float32)}
        assert vault.store("pattern", "k1", {"dtype": "structure"}, arrays)
        out = vault.load("pattern", "k1")
        assert out is not None
        meta, loaded = out
        assert meta["dtype"] == "structure"
        np.testing.assert_array_equal(loaded["a"], arrays["a"])
        np.testing.assert_array_equal(loaded["b"], arrays["b"])

    def test_missing_is_clean_miss(self):
        st0 = vault.stats()
        assert vault.load("pattern", "nope") is None
        st = vault.stats()
        assert st["misses"] == st0["misses"] + 1
        assert st["quarantined"] == st0["quarantined"]

    def test_disabled_writes_nothing(self, tmp_path):
        settings.vault = ""
        assert not vault.enabled()
        assert not vault.store("pattern", "k", {}, {"a": np.zeros(1)})
        assert vault.load("pattern", "k") is None
        A = _skewed(60)
        SparsityPattern.from_csr(A).sell_pack()
        assert not (tmp_path / "vault").exists()

    def test_plan_cache_off_bypasses_vault(self, monkeypatch):
        monkeypatch.setattr(settings, "plan_cache", False)
        st0 = vault.stats()
        SparsityPattern.from_csr(_spd(40)).sell_pack()
        st = vault.stats()
        assert st["writes"] == st0["writes"]
        assert st["hits"] == st0["hits"]

    def test_atomic_no_tmp_left_behind(self):
        vault.store("pattern", "k", {}, {"a": np.zeros(4)})
        tmp_dir = os.path.join(vault.vault_dir(), "tmp")
        assert os.listdir(tmp_dir) == []


# ---------------------------------------------------------------------------
# corruption matrix: every bad artifact = miss + quarantine, never a raise
# ---------------------------------------------------------------------------
def _stored_artifact():
    arrays = {"a": np.arange(128, dtype=np.float64)}
    assert vault.store("pattern", "kc", {"dtype": "structure"}, arrays)
    return vault.artifact_path("pattern", "kc")


def _truncate(path):
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[: len(blob) // 2])


def _bitflip(path):
    blob = bytearray(open(path, "rb").read())
    blob[-10] ^= 0x20
    open(path, "wb").write(bytes(blob))


def _flip_header_byte(path):
    blob = bytearray(open(path, "rb").read())
    blob[len(_store.MAGIC) + 2] ^= 0xFF
    open(path, "wb").write(bytes(blob))


def _patch_header(path, **kv):
    blob = open(path, "rb").read()
    nl = blob.index(b"\n", len(_store.MAGIC))
    hdr = json.loads(blob[len(_store.MAGIC):nl].decode())
    hdr.update(kv)
    open(path, "wb").write(
        _store.MAGIC + json.dumps(hdr, sort_keys=True).encode()
        + b"\n" + blob[nl + 1:]
    )


def _bad_magic(path):
    blob = open(path, "rb").read()
    open(path, "wb").write(b"NOTAVAULT!" + blob[10:])


def _empty(path):
    open(path, "wb").close()


@pytest.mark.parametrize("corrupt,reason", [
    (_truncate, "truncated"),
    (_bitflip, "checksum"),
    (_flip_header_byte, "bad-header"),
    (lambda p: _patch_header(p, format=_store.FORMAT + 1), "stale-format"),
    (lambda p: _patch_header(p, jax="0.0.0"), "stale-jax"),
    (lambda p: _patch_header(p, key="other"), "key-mismatch"),
    (_bad_magic, "bad-magic"),
    (_empty, "bad-magic"),
])
def test_corruption_matrix(corrupt, reason):
    path = _stored_artifact()
    corrupt(path)
    st0 = vault.stats()
    assert vault.load("pattern", "kc") is None  # clean miss, no raise
    st = vault.stats()
    assert st["verify_failed"] == st0["verify_failed"] + 1
    assert st["quarantined"] == st0["quarantined"] + 1
    assert not os.path.exists(path)  # moved aside, never re-read
    qf = _quarantine_files()
    assert len(qf) == 1 and reason in qf[0]


def test_wrong_dtype_expect_quarantines():
    path = _stored_artifact()
    st0 = vault.stats()
    assert vault.load("pattern", "kc", expect={"dtype": "float32"}) is None
    st = vault.stats()
    assert st["quarantined"] == st0["quarantined"] + 1
    assert not os.path.exists(path)
    assert any("expect-dtype" in f for f in _quarantine_files())


def test_quarantine_emits_event_and_is_bounded():
    settings.telemetry = True
    for i in range(_store.QUARANTINE_KEEP + 4):
        arrays = {"a": np.arange(4)}
        vault.store("pattern", f"q{i}", {"dtype": "structure"}, arrays)
        _bitflip(vault.artifact_path("pattern", f"q{i}"))
        assert vault.load("pattern", f"q{i}") is None
    assert len(_quarantine_files()) <= _store.QUARANTINE_KEEP
    kinds = [e["kind"] for e in telemetry.events()]
    assert "vault.quarantine" in kinds
    from sparse_tpu.telemetry import _schema

    for ev in telemetry.events():
        if ev["kind"].startswith("vault."):
            assert _schema.validate(ev) == []


# ---------------------------------------------------------------------------
# codec round trips
# ---------------------------------------------------------------------------
class TestRoundTrip:
    def test_sell_pattern_pack(self):
        A = _skewed(100)
        pat = SparsityPattern.from_csr(A)
        p0 = pat.sell_pack()
        assert vault.stats()["writes"] >= 1
        plan_cache.clear()
        snap = plan_cache.snapshot()
        pat2 = SparsityPattern.from_csr(A)
        p1 = pat2.sell_pack()
        d = plan_cache.delta(snap)
        assert d["disk_hits"] == 1 and d["misses"] == 0
        assert p1.plan == p0.plan
        np.testing.assert_array_equal(np.asarray(p1.pos), np.asarray(p0.pos))
        for a, b in zip(p1.idx_slabs, p0.idx_slabs):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(p1.srcs, p0.srcs):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.parametrize("dtype", [np.float32, np.float64, np.complex64])
    def test_prepared_csr_matvec_parity(self, dtype, monkeypatch):
        monkeypatch.setattr(settings, "spmv_mode", "sell")
        S = _skewed(90, dtype=np.float64)
        S = S.astype(dtype)
        if np.issubdtype(dtype, np.complexfloating):
            S = S + 1j * S
        rng = np.random.default_rng(3)
        x = rng.standard_normal(90).astype(
            np.float32 if dtype == np.complex64 else dtype
        )
        y0 = np.asarray(sparse_tpu.csr_array(S) @ x)
        plan_cache.clear()
        snap = plan_cache.snapshot()
        y1 = np.asarray(sparse_tpu.csr_array(S) @ x)
        assert plan_cache.delta(snap)["disk_hits"] >= 1
        np.testing.assert_array_equal(y0, y1)  # bit-identical layouts

    @pytest.mark.parametrize("dtype", [np.float32, np.float64])
    def test_prepared_dia_matvec_parity(self, dtype, monkeypatch):
        monkeypatch.setattr(settings, "spmv_mode", "pallas")
        D = _spd(200).astype(dtype)
        x = np.random.default_rng(4).standard_normal(200).astype(dtype)
        y0 = np.asarray(sparse_tpu.csr_array(D) @ x)
        plan_cache.clear()
        snap = plan_cache.snapshot()
        y1 = np.asarray(sparse_tpu.csr_array(D) @ x)
        assert plan_cache.delta(snap)["disk_hits"] >= 1
        np.testing.assert_array_equal(y0, y1)

    def test_prepared_dia_c64_codec_parity(self):
        """Complex plane round trip at the codec level (the Pallas DIA
        kernel itself is exercised by the f32/f64 matvec parities)."""
        from sparse_tpu.kernels.dia_spmv import PreparedDia

        rng = np.random.default_rng(5)
        data = (rng.standard_normal((3, 64))
                + 1j * rng.standard_normal((3, 64))).astype(np.complex64)
        prep = PreparedDia(data, (-1, 0, 1), (64, 64))
        key = _codecs.prepared_dia_key(data, (-1, 0, 1), (64, 64))
        assert vault.deposit("prepared_dia", key, prep)
        prep2 = vault.fetch("prepared_dia", key)
        assert prep2 is not None
        assert prep2.plan == prep.plan
        np.testing.assert_array_equal(
            np.asarray(prep2.planes), np.asarray(prep.planes)
        )

    def test_dia_tile_choice_persists(self):
        """The stored DiaPlan carries the (autotuned) row tile: a disk
        hit reuses it without re-probing."""
        from sparse_tpu.kernels.dia_spmv import PreparedDia, dia_plan

        data = np.ones((3, 64), dtype=np.float32)
        prep = PreparedDia(data, (-1, 0, 1), (64, 64), tile=131072)
        key = _codecs.prepared_dia_key(data, (-1, 0, 1), (64, 64))
        assert vault.deposit("prepared_dia", key, prep)
        prep2 = vault.fetch("prepared_dia", key)
        assert prep2.plan == dia_plan((-1, 0, 1), (64, 64), tile=131072)

    def test_content_key_separates_settings(self, monkeypatch):
        """A different SELL geometry is a different artifact — the disk
        tier can never serve a pack built under other settings."""
        pat = SparsityPattern.from_csr(_skewed(80))
        k1 = _codecs.sell_pattern_key(pat)
        monkeypatch.setattr(settings, "sell_chunk", settings.sell_chunk * 2)
        assert _codecs.sell_pattern_key(pat) != k1


# ---------------------------------------------------------------------------
# manifest
# ---------------------------------------------------------------------------
class TestManifest:
    def test_missing_and_empty_are_clean(self):
        assert vault.manifest_entries() == []
        os.makedirs(vault.vault_dir(), exist_ok=True)
        open(_manifest.path(), "w").close()
        st0 = vault.stats()
        assert vault.manifest_entries() == []
        assert vault.stats()["quarantined"] == st0["quarantined"]

    def test_corrupt_manifest_quarantines(self):
        os.makedirs(vault.vault_dir(), exist_ok=True)
        with open(_manifest.path(), "w") as f:
            f.write('{"format": 1, "entries": "garbage"')
        st0 = vault.stats()
        assert vault.manifest_entries() == []
        assert vault.stats()["quarantined"] == st0["quarantined"] + 1
        assert not os.path.exists(_manifest.path())

    def test_checksum_guards_entries(self):
        pat = SparsityPattern.from_csr(_spd(40))
        vault.note_program(pat, solver="cg", bucket=4, dtype="<f8")
        assert len(vault.manifest_entries()) == 1
        doc = json.load(open(_manifest.path()))
        doc["entries"][0]["solver"] = "gmres"  # tamper without re-checksum
        json.dump(doc, open(_manifest.path(), "w"))
        assert vault.manifest_entries() == []  # quarantined

    def test_note_dedupes_and_bounds(self):
        pat = SparsityPattern.from_csr(_spd(40))
        for _ in range(3):
            vault.note_program(pat, solver="cg", bucket=4, dtype="<f8")
        assert len(vault.manifest_entries()) == 1
        for i in range(_manifest.MANIFEST_KEEP + 10):
            vault.note_program(pat, solver="cg", bucket=4,
                               dtype=f"d{i}")
        ents = vault.manifest_entries()
        assert len(ents) == _manifest.MANIFEST_KEEP
        assert ents[-1]["dtype"] == f"d{_manifest.MANIFEST_KEEP + 9}"


# ---------------------------------------------------------------------------
# warm restart
# ---------------------------------------------------------------------------
def _traffic(n=64, B=4, seed=9):
    rng = np.random.default_rng(seed)
    mats = []
    for _ in range(B):
        M = _spd(n, seed=seed)
        M.setdiag(3.0 + rng.random(n))
        M.sort_indices()
        mats.append(M.tocsr())
    return mats, rng.standard_normal((B, n))


class TestWarmRestart:
    def test_replay_serves_at_zero_misses(self):
        mats, rhs = _traffic()
        ses = SolveSession("cg", warm_start=False)
        X0, _, _ = ses.solve_many(mats, rhs, tol=1e-10)
        assert len(vault.manifest_entries()) >= 1
        plan_cache.clear()  # "the process died"
        ses2 = SolveSession("cg")  # warm_start defaults on: vault enabled
        assert ses2.warm_replayed >= 1
        snap = plan_cache.snapshot()
        X1, _, _ = ses2.solve_many(mats, rhs, tol=1e-10)
        d = plan_cache.delta(snap)
        assert d["misses"] == 0 and d["hits"] >= 1
        np.testing.assert_allclose(X0, X1, atol=1e-12)

    def test_replay_emits_event_and_counts(self):
        settings.telemetry = True
        mats, rhs = _traffic()
        SolveSession("cg", warm_start=False).solve_many(mats, rhs, tol=1e-10)
        plan_cache.clear()
        telemetry.reset()
        ses = SolveSession("cg", warm_start=True)
        assert ses.warm_replayed >= 1
        evs = [e for e in telemetry.events() if e["kind"] == "vault.replay"]
        assert evs and evs[0]["programs"] >= 1

    def test_warm_start_false_skips(self):
        mats, rhs = _traffic()
        SolveSession("cg", warm_start=False).solve_many(mats, rhs, tol=1e-10)
        plan_cache.clear()
        ses = SolveSession("cg", warm_start=False)
        assert ses.warm_replayed == 0

    def test_corrupt_manifest_degrades_to_cold(self):
        mats, rhs = _traffic()
        SolveSession("cg", warm_start=False).solve_many(mats, rhs, tol=1e-10)
        with open(_manifest.path(), "w") as f:
            f.write("not json at all")
        plan_cache.clear()
        ses = SolveSession("cg", warm_start=True)  # must not raise
        assert ses.warm_replayed == 0
        X, _, _ = ses.solve_many(mats, rhs, tol=1e-10)
        r = max(np.linalg.norm(m @ x - b)
                for m, x, b in zip(mats, X, rhs))
        assert r <= 1e-4

    def test_compile_cache_env_gate(self, tmp_path, monkeypatch):
        target = str(tmp_path / "xla_cache")
        old = jax.config.jax_compilation_cache_dir
        monkeypatch.setattr(settings, "compile_cache", target)
        try:
            SolveSession("cg", warm_start=False)
            assert jax.config.jax_compilation_cache_dir == target
        finally:
            jax.config.update("jax_compilation_cache_dir", old)


# ---------------------------------------------------------------------------
# io fault injection (the chaos grammar, unit-level)
# ---------------------------------------------------------------------------
class TestIoFaults:
    def test_enospc_write_fails_cleanly(self):
        faults.configure("enospc:io:p=1,n=1")
        st0 = vault.stats()
        pack = SparsityPattern.from_csr(_skewed(70)).sell_pack()
        assert pack is not None  # the pack itself must survive
        st = vault.stats()
        assert st["write_failed"] == st0["write_failed"] + 1
        tmp_dir = os.path.join(vault.vault_dir(), "tmp")
        assert not os.path.isdir(tmp_dir) or os.listdir(tmp_dir) == []

    def test_truncate_on_write_quarantines_on_read(self):
        faults.configure("truncate:io:p=1,n=1")
        p0 = SparsityPattern.from_csr(_skewed(72)).sell_pack()
        faults.clear()
        st0 = vault.stats()
        plan_cache.clear()
        p1 = SparsityPattern.from_csr(_skewed(72)).sell_pack()
        st = vault.stats()
        assert st["quarantined"] == st0["quarantined"] + 1
        assert p1.plan == p0.plan

    def test_bitflip_on_read_quarantines(self):
        p0 = SparsityPattern.from_csr(_skewed(74)).sell_pack()
        faults.configure("bitflip:io:p=1,seed=3,n=1")
        st0 = vault.stats()
        plan_cache.clear()
        p1 = SparsityPattern.from_csr(_skewed(74)).sell_pack()
        faults.clear()
        st = vault.stats()
        assert st["quarantined"] == st0["quarantined"] + 1
        assert p1.plan == p0.plan

    def test_stale_write_quarantines_on_read(self):
        faults.configure("stale:io:p=1,n=1")
        SparsityPattern.from_csr(_skewed(76)).sell_pack()
        faults.clear()
        st0 = vault.stats()
        plan_cache.clear()
        SparsityPattern.from_csr(_skewed(76)).sell_pack()
        st = vault.stats()
        assert st["quarantined"] == st0["quarantined"] + 1
        assert any("stale-format" in f for f in _quarantine_files())

    def test_io_fires_are_counted_and_seeded(self):
        faults.configure("bitflip:io:p=1,seed=7")
        a1 = faults.io_actions("read")
        faults.configure("bitflip:io:p=1,seed=7")
        a2 = faults.io_actions("read")
        assert a1 == a2 and a1[0][0] == "bitflip"
        assert faults.io_actions("write") == []  # read-only fault

    def test_bad_io_spec_rejected(self):
        with pytest.raises(faults.FaultSpecError):
            faults.parse_spec("bitflip:io2")
        with pytest.raises(faults.FaultSpecError):
            faults.parse_spec("drop:io")


# ---------------------------------------------------------------------------
# GC
# ---------------------------------------------------------------------------
class TestGC:
    def test_cap_evicts_oldest(self):
        for i in range(6):
            vault.store("pattern", f"g{i}", {"dtype": "structure"},
                        {"a": np.zeros(64 * 1024 // 8)})  # ~64 KB payload
            t = time.time() - 1000 + i
            os.utime(vault.artifact_path("pattern", f"g{i}"), (t, t))
        st0 = vault.stats()
        evicted = vault.gc(cap_mb=0.2)  # ~3 artifacts fit
        assert evicted >= 2
        assert vault.stats()["evictions"] == st0["evictions"] + evicted
        left = sorted(os.listdir(os.path.join(
            vault.vault_dir(), "objects", "pattern")))
        assert f"g5{_store.SUFFIX}" in left  # newest survives
        assert f"g0{_store.SUFFIX}" not in left  # oldest went first

    def test_store_triggers_sweep(self, monkeypatch):
        monkeypatch.setattr(settings, "vault_cap_mb", 1)
        payload = {"a": np.zeros(600 * 1024 // 8)}  # ~600 KB each
        st0 = vault.stats()
        for i in range(3):
            vault.store("pattern", f"s{i}", {"dtype": "structure"}, payload)
        assert vault.stats()["evictions"] > st0["evictions"]

    def test_gc_script_matches_library_policy(self, tmp_path):
        for i in range(4):
            vault.store("pattern", f"c{i}", {"dtype": "structure"},
                        {"a": np.zeros(64 * 1024 // 8)})
            t = time.time() - 100 + i
            os.utime(vault.artifact_path("pattern", f"c{i}"), (t, t))
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "vault_gc.py"),
             "--dir", vault.vault_dir(), "--cap-mb", "0.15"],
            capture_output=True, text=True, timeout=60,
        )
        assert r.returncode == 0, r.stderr
        assert "evicted" in r.stdout
        left = sorted(os.listdir(os.path.join(
            vault.vault_dir(), "objects", "pattern")))
        assert f"c3{_store.SUFFIX}" in left


# ---------------------------------------------------------------------------
# concurrency: per-process tmp names, atomic replace
# ---------------------------------------------------------------------------
_WRITER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
from sparse_tpu.config import settings
from sparse_tpu import vault
settings.vault = sys.argv[1]
fill = float(sys.argv[2])
for i in range(25):
    vault.store("pattern", "shared",
                {"dtype": "structure", "writer": fill},
                {"a": np.full(2048, fill)})
print("WROTE")
"""


def test_concurrent_writers_never_tear():
    """Two processes hammering ONE key while this process loads: every
    load is either a verified artifact from one writer or a miss —
    never an exception, never a quarantine (no torn reads)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WRITER, vault.vault_dir(), str(fill)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )
        for fill in (1.0, 2.0)
    ]
    st0 = vault.stats()
    deadline = time.time() + 120
    seen = 0
    try:
        while any(p.poll() is None for p in procs):
            out = vault.load("pattern", "shared")
            if out is not None:
                meta, arrays = out
                fill = float(meta["writer"])
                assert fill in (1.0, 2.0)
                np.testing.assert_array_equal(
                    arrays["a"], np.full(2048, fill)
                )
                seen += 1
            assert time.time() < deadline, "writers hung"
            time.sleep(0.01)
    finally:
        for p in procs:
            p.wait(timeout=120)
    for p in procs:
        assert "WROTE" in p.stdout.read(), p.stderr.read()
    # final read sees one of the two writers, intact
    meta, arrays = vault.load("pattern", "shared")
    np.testing.assert_array_equal(
        arrays["a"], np.full(2048, float(meta["writer"]))
    )
    assert vault.stats()["quarantined"] == st0["quarantined"]


# ---------------------------------------------------------------------------
# trace invisibility
# ---------------------------------------------------------------------------
def test_vault_never_changes_traced_programs():
    """The disk tier is host-side only: the bucket program a session
    builds is jaxpr-identical with the vault on and off."""
    mats, rhs = _traffic()
    pat = SparsityPattern.from_csr(mats[0])
    pat.sell_pack()
    ses = SolveSession("cg", warm_start=False)
    prog_on = ses._build_program(pat, 4, np.dtype(np.float64))
    args = (
        np.zeros((4, pat.nnz)), np.zeros((4, 64)), np.zeros((4, 64)),
        np.zeros(4), 10,
    )
    jaxpr_on = str(jax.make_jaxpr(prog_on)(*args))
    settings.vault = ""
    plan_cache.clear()
    pat2 = SparsityPattern.from_csr(mats[0])
    pat2.sell_pack()
    prog_off = SolveSession(
        "cg", warm_start=False
    )._build_program(pat2, 4, np.dtype(np.float64))
    assert str(jax.make_jaxpr(prog_off)(*args)) == jaxpr_on


def test_store_load_raw_bytes_shapes():
    """npz payloads preserve dtype/shape exactly (incl. complex)."""
    arrays = {
        "f32": np.linspace(0, 1, 7, dtype=np.float32),
        "f64": np.linspace(0, 1, 7, dtype=np.float64),
        "c64": (np.arange(5) + 1j * np.arange(5)).astype(np.complex64),
        "i32": np.arange(12, dtype=np.int32).reshape(3, 4),
    }
    vault.store("pattern", "raw", {"dtype": "structure"}, arrays)
    _meta, loaded = vault.load("pattern", "raw")
    for k, a in arrays.items():
        assert loaded[k].dtype == a.dtype
        np.testing.assert_array_equal(loaded[k], a)
