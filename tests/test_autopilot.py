"""Autopilot: the online policy tuner (ISSUE 16).

The load-bearing contracts:

* **Convergence** — the bounded epsilon-greedy / successive-halving
  scheduler pins the measurably better arm (synthetic two-arm race and
  a real end-to-end serving session).
* **Persistence** — a converged decision deposits an
  ``autopilot_policy`` vault artifact; a fresh tuner over the same
  (pattern, bucket, SLO class, mesh, grid) restores it on first touch
  (``autopilot.restore``) and serves tuned with zero trials.
* **SLO guard** — a trial observation over ``slo_factor x slo_ms``
  kills its arm immediately (``autopilot.abort``).
* **Drift** — incumbent observations worse than ``drift x`` the pinned
  score strike the watchdog-visible ``autopilot.drift_strikes``
  counter; a :func:`drift_rule` alert transition re-opens exploration
  through the process-global hook (``autopilot.reopen``).
* **Default off** — ``SPARSE_TPU_AUTOPILOT=''`` leaves the session
  tuner-less: program keys, results and manifests byte-identical to
  pre-autopilot behavior. The storage-dtype compounding arm keys as a
  ``.W`` suffix and converges end to end.
"""

import numpy as np
import pytest
import scipy.sparse as sp

from sparse_tpu import autopilot, plan_cache, telemetry, vault
from sparse_tpu.batch import SolveSession, SparsityPattern
from sparse_tpu.config import settings
from sparse_tpu.resilience import faults
from sparse_tpu.telemetry import _cost, _metrics, _watchdog


@pytest.fixture(autouse=True)
def _clean_state(tmp_path):
    faults.clear()
    old = (settings.vault, settings.telemetry, settings.autopilot,
           settings.precond_dtype, settings.dtype_policy)
    settings.vault = ""
    settings.autopilot = ""
    settings.precond_dtype = ""
    telemetry.configure(str(tmp_path / "records.jsonl"))
    telemetry.reset()
    plan_cache.clear()
    yield
    faults.clear()
    (settings.vault, settings.telemetry, settings.autopilot,
     settings.precond_dtype, settings.dtype_policy) = old
    telemetry.configure(None)


def _tridiag(n=32, seed=0, diag=4.0):
    rng = np.random.default_rng(seed)
    e = np.ones(n)
    A = sp.diags([-e[:-1], diag * e, -e[:-1]], [-1, 0, 1], format="csr")
    A.setdiag(diag + rng.random(n))
    A = A.tocsr()
    A.sort_indices()
    return A


def _pattern(A):
    return SparsityPattern(A.indptr, A.indices, A.shape)


def _drive(ap, pattern, scores, bucket=4, dtype=np.float64, slo_ms=None,
           rounds=40):
    """Drive assign/observe with synthetic per-arm latencies until the
    group converges (or ``rounds`` runs out). ``scores`` maps arm_id ->
    milliseconds."""
    for _ in range(rounds):
        spec, token = ap.assign(pattern, "cg", bucket, dtype,
                                slo_ms=slo_ms)
        if token is None:
            break
        ap.observe(token, solve_ms=scores[autopilot.arm_id(spec)],
                   lanes=1)
        if ap.decision_for(pattern, "cg", bucket, dtype,
                           slo_ms=slo_ms) is not None:
            break
    return ap.decision_for(pattern, "cg", bucket, dtype, slo_ms=slo_ms)


# ---------------------------------------------------------------------------
# scheduler mechanics (synthetic observations — no solves)
# ---------------------------------------------------------------------------
def test_two_arm_convergence_picks_the_faster_arm():
    settings.telemetry = True
    ap = autopilot.Autopilot(
        grid=({}, {"precond": "jacobi"}), epsilon=1.0, trials=2,
    )
    pat = _pattern(_tridiag(seed=1))
    dec = _drive(ap, pat, {"static": 5.0, "precond=jacobi": 1.0})
    assert dec is not None
    assert dec.spec == {"precond": "jacobi"}
    assert dec.score == pytest.approx(1.0)
    kinds = [e.get("kind") for e in telemetry.events()]
    assert "autopilot.trial" in kinds and "autopilot.converge" in kinds
    # pinned traffic now serves the decision (token kind 'pinned')
    spec, token = ap.assign(pat, "cg", 4, np.float64)
    assert spec == {"precond": "jacobi"} and token[1] == "pinned"


def test_epsilon_bounds_exploration_to_the_declared_fraction():
    ap = autopilot.Autopilot(
        grid=({}, {"precond": "jacobi"}), epsilon=0.25, trials=2,
    )
    pat = _pattern(_tridiag(seed=2))
    kinds = []
    for _ in range(8):
        _spec, token = ap.assign(pat, "cg", 4, np.float64)
        kinds.append(token[1])
    # period = 4: exactly one trial per 4 dispatches while exploring
    assert kinds.count("trial") == 2
    assert kinds.count("incumbent") == 6


def test_slo_guard_aborts_a_budget_blowing_arm():
    settings.telemetry = True
    ap = autopilot.Autopilot(
        grid=({}, {"precond": "jacobi"}, {"precond": "bjacobi"}),
        epsilon=1.0, trials=2, slo_factor=1.5,
    )
    pat = _pattern(_tridiag(seed=3))
    # bjacobi blows the 10ms SLO budget (> 1.5 x 10); the others race on
    dec = _drive(
        ap, pat,
        {"static": 5.0, "precond=jacobi": 2.0, "precond=bjacobi": 100.0},
        slo_ms=10.0,
    )
    assert dec is not None and dec.spec == {"precond": "jacobi"}
    aborts = [e for e in telemetry.events()
              if e.get("kind") == "autopilot.abort"]
    assert aborts and aborts[0]["reason"] == "slo_guard"
    assert aborts[0]["arm"] == "precond=bjacobi"


def test_unconverged_trials_never_win():
    ap = autopilot.Autopilot(
        grid=({}, {"precond": "jacobi"}), epsilon=1.0, trials=2,
    )
    pat = _pattern(_tridiag(seed=4))
    for _ in range(40):
        spec, token = ap.assign(pat, "cg", 4, np.float64)
        fast_but_wrong = spec == {"precond": "jacobi"}
        ap.observe(token, solve_ms=0.1 if fast_but_wrong else 5.0,
                   converged=0.5 if fast_but_wrong else 1.0)
        dec = ap.decision_for(pat, "cg", 4, np.float64)
        if dec is not None:
            break
    assert dec is not None and dec.spec == {}


def test_drift_strikes_and_watchdog_reopen():
    settings.telemetry = True
    ap = autopilot.Autopilot(
        grid=({}, {"precond": "jacobi"}), epsilon=1.0, trials=2, drift=2.0,
    )
    pat = _pattern(_tridiag(seed=5))
    dec = _drive(ap, pat, {"static": 5.0, "precond=jacobi": 1.0})
    assert dec is not None
    wd = _watchdog.Watchdog([autopilot.drift_rule()], interval_s=0.01)
    wd.evaluate()  # priming tick (windowed delta)
    assert wd.evaluate() == []  # no strikes yet: quiet
    # pinned traffic regresses past drift x the decision score
    for _ in range(3):
        _spec, token = ap.assign(pat, "cg", 4, np.float64)
        assert token[1] == "pinned"
        ap.observe(token, solve_ms=50.0)
    transitions = wd.evaluate()
    assert any(t["rule"] == "autopilot_drift" for t in transitions)
    # the alert hook re-opened exploration in every live autopilot
    assert ap.decision_for(pat, "cg", 4, np.float64) is None
    reopens = [e for e in telemetry.events()
               if e.get("kind") == "autopilot.reopen"]
    assert reopens and reopens[-1]["reason"].startswith("watchdog:")
    # and the group converges again from fresh measurements
    dec2 = _drive(ap, pat, {"static": 5.0, "precond=jacobi": 1.0})
    assert dec2 is not None and dec2.spec == {"precond": "jacobi"}


def test_vault_persistence_round_trip(tmp_path):
    settings.vault = str(tmp_path / "vault")
    settings.telemetry = True
    pat = _pattern(_tridiag(seed=6))
    ap = autopilot.Autopilot(
        grid=({}, {"precond": "jacobi"}), epsilon=1.0, trials=2,
    )
    dec = _drive(ap, pat, {"static": 5.0, "precond=jacobi": 1.0})
    assert dec is not None and not dec.restored
    # a fresh tuner (the restarted process) restores on first touch:
    # tuned from the first request, zero trials
    ap2 = autopilot.Autopilot(
        grid=({}, {"precond": "jacobi"}), epsilon=1.0, trials=2,
    )
    spec, token = ap2.assign(pat, "cg", 4, np.float64)
    assert spec == {"precond": "jacobi"} and token[1] == "pinned"
    dec2 = ap2.decision_for(pat, "cg", 4, np.float64)
    assert dec2.restored and dec2.spec == dec.spec
    assert [e for e in telemetry.events()
            if e.get("kind") == "autopilot.restore"]
    # a different grid is a different vault key: no stale restore
    ap3 = autopilot.Autopilot(
        grid=({}, {"precond": "bjacobi"}), epsilon=1.0, trials=2,
    )
    _spec, token3 = ap3.assign(pat, "cg", 4, np.float64)
    assert token3 is None or token3[1] != "pinned"


def test_grid_validation_rejects_typos():
    with pytest.raises(ValueError):
        autopilot.Autopilot(grid=({"precnd": "jacobi"},))
    with pytest.raises(ValueError):
        autopilot.Autopilot(grid=({"precond": "jacoby"},))
    with pytest.raises(ValueError):
        autopilot.Autopilot(grid=())


def test_slo_class_boundaries():
    assert autopilot.slo_class(None) == "none"
    assert autopilot.slo_class(50) == "tight"
    assert autopilot.slo_class(500) == "standard"
    assert autopilot.slo_class(5000) == "relaxed"


# ---------------------------------------------------------------------------
# serving integration
# ---------------------------------------------------------------------------
def test_default_off_is_bit_identical():
    """No tuner object, historic program keys, identical results."""
    A = _tridiag(32, seed=7)
    b = np.random.default_rng(8).standard_normal(32)
    _cost.reset()
    ses = SolveSession("cg", warm_start=False)
    assert ses.autopilot is None
    assert "autopilot" not in ses.session_stats()
    t = ses.submit(A, b, tol=1e-9, maxiter=2000)
    ses.flush()
    x, i, r = t.result()
    ses2 = SolveSession("cg", warm_start=False, autopilot=False)
    t2 = ses2.submit(A, b, tol=1e-9, maxiter=2000)
    ses2.flush()
    x2, i2, r2 = t2.result()
    assert np.array_equal(np.asarray(x), np.asarray(x2))
    assert i == i2 and r == r2
    # one shared historic key — no autopilot, no .W anywhere
    assert set(_cost.programs()) == {"batch.cg.B1.<f8"}


def test_session_end_to_end_convergence_and_stats():
    A = _tridiag(32, seed=9)
    rng = np.random.default_rng(10)
    bs = [rng.random(32) for _ in range(4)]
    ap = autopilot.Autopilot(
        grid=({}, {"precond": "jacobi"}), epsilon=1.0, trials=1,
    )
    ses = SolveSession("cg", warm_start=False, autopilot=ap)
    for _ in range(12):
        tks = [ses.submit(A, b, tol=1e-9, maxiter=2000) for b in bs]
        ses.flush()
        for t, b in zip(tks, bs):
            x, _i, _r = t.result()
            assert np.linalg.norm(A @ np.asarray(x) - b) <= 1e-7
    blk = ses.session_stats()["autopilot"]
    assert blk["arms"] == ["static", "precond=jacobi"]
    groups = list(blk["groups"].values())
    assert groups and groups[0]["phase"] == "converged"
    assert groups[0]["trials"] >= 2


def test_storage_precond_dtype_keys_and_converges():
    """The compounding arm (ISSUE 16): reduced-width factors under the
    f32 IR loop — '.W' program key, converged f64-accurate results."""
    A = _tridiag(48, seed=11)
    rng = np.random.default_rng(12)
    bs = [rng.random(48) for _ in range(4)]
    _cost.reset()
    ses = SolveSession("cg", warm_start=False)
    tks = [ses.submit(A, b, tol=1e-8, maxiter=4000, precond="bjacobi",
                      dtype_policy="f32ir", precond_dtype="storage")
           for b in bs]
    ses.flush()
    for t, b in zip(tks, bs):
        x, _i, _r = t.result()
        assert t.converged
        assert np.linalg.norm(A @ np.asarray(x) - b) <= 1e-6
    assert "batch.cg.B4.<f8.Mbjacobi.Pf32ir.Wstorage" in set(
        _cost.programs()
    )


def test_storage_precond_dtype_degrades_outside_reduced_buckets():
    """'storage' without a reduced dtype policy (or without stored
    factors) falls back to 'compute' with a breadcrumb — the key stays
    historic."""
    A = _tridiag(32, seed=13)
    settings.telemetry = True
    _cost.reset()
    ses = SolveSession("cg", warm_start=False)
    t = ses.submit(A, np.ones(32), tol=1e-9, maxiter=2000,
                   precond="jacobi", precond_dtype="storage")
    ses.flush()
    t.result()
    keys = set(_cost.programs())
    assert "batch.cg.B1.<f8.Mjacobi" in keys
    assert not any(".W" in k for k in keys)
    fb = [e for e in telemetry.events()
          if e.get("kind") == "coverage.fallback"
          and e.get("op") == "precond.storage"]
    assert fb and fb[0]["to"] == "compute"


def test_manifest_records_precond_dtype_and_replays(tmp_path):
    settings.vault = str(tmp_path / "vault")
    A = _tridiag(48, seed=14)
    b = np.random.default_rng(15).standard_normal(48)
    ses = SolveSession("cg", warm_start=False)
    t = ses.submit(A, b, tol=1e-8, maxiter=4000, precond="bjacobi",
                   dtype_policy="f32ir", precond_dtype="storage")
    ses.flush()
    t.result()
    entries = vault.manifest_entries()
    assert any(e.get("precond_dtype") == "storage" for e in entries)
    plan_cache.clear()
    ses2 = SolveSession("cg", warm_start=True, warm_async=False)
    assert ses2.warm_replayed >= 1
    snap = plan_cache.snapshot()
    t2 = ses2.submit(A, b, tol=1e-8, maxiter=4000, precond="bjacobi",
                     dtype_policy="f32ir", precond_dtype="storage")
    ses2.flush()
    t2.result()
    assert plan_cache.delta(snap)["misses"] == 0


def test_schema_kinds_registered():
    from sparse_tpu.telemetry import _schema

    for kind in ("autopilot.trial", "autopilot.abort",
                 "autopilot.converge", "autopilot.reopen",
                 "autopilot.restore"):
        assert kind in _schema.KINDS
    assert _schema.validate(
        {"kind": "autopilot.reopen", "ts": 1.0, "group": "g",
         "reason": "drift"}
    ) == []
    assert _schema.validate({"kind": "autopilot.reopen", "ts": 1.0})
