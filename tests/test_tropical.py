"""Tropical (max, +)-style semiring SpMV tests.

Reference analog: the MIS tournament kernel (``sparse/csr.py:366`` tropical
spmv) that powers AMG aggregation — each output row takes the lexicographic
maximum over its neighbors' 3-tuples.
"""

import numpy as np

import sparse_tpu as sparse
from .utils.sample import sample_csr


def _oracle(s, x):
    """Row-wise lexicographic max over neighbor tuples."""
    m = s.shape[0]
    out = np.zeros((m, x.shape[1]), dtype=x.dtype)
    s = s.tocsr()
    for i in range(m):
        cols = s.indices[s.indptr[i] : s.indptr[i + 1]]
        if len(cols) == 0:
            continue
        cand = [tuple(x[j]) for j in cols]
        out[i] = max(cand)
    return out


def test_tropical_spmv_matches_oracle():
    s = sample_csr(20, 20, density=0.25, seed=110).tocsr()
    rng = np.random.default_rng(4)
    x = rng.integers(0, 8, size=(20, 3)).astype(np.float64)
    got = np.asarray(sparse.csr_array(s).tropical_spmv(x))
    assert np.allclose(got, _oracle(s, x))


def test_tropical_spmv_tie_breaking():
    """Ties on the first component must resolve by the second, then third."""
    import scipy.sparse as sp

    s = sp.csr_matrix(np.array([[1.0, 1.0, 1.0], [0, 1.0, 1.0], [0, 0, 1.0]]))
    x = np.array([[2.0, 5.0, 0.0], [2.0, 5.0, 1.0], [2.0, 4.0, 9.0]])
    got = np.asarray(sparse.csr_array(s).tropical_spmv(x))
    assert np.allclose(got, _oracle(s, x))


def test_tropical_spmv_empty_rows():
    import scipy.sparse as sp

    s = sp.csr_matrix(
        (np.ones(2), np.array([0, 2]), np.array([0, 1, 1, 2])), shape=(3, 3)
    )
    x = np.arange(9.0).reshape(3, 3)
    got = np.asarray(sparse.csr_array(s).tropical_spmv(x))
    exp = _oracle(s, x)
    assert np.allclose(got, exp)
