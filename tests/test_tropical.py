"""Tropical (max, +)-style semiring SpMV tests.

Reference analog: the MIS tournament kernel (``sparse/csr.py:366`` tropical
spmv) that powers AMG aggregation — each output row takes the lexicographic
maximum over its neighbors' 3-tuples.
"""

import numpy as np

import sparse_tpu as sparse
from .utils.sample import sample_csr


def _oracle(s, x):
    """Row-wise lexicographic max over neighbor tuples."""
    m = s.shape[0]
    out = np.zeros((m, x.shape[1]), dtype=x.dtype)
    s = s.tocsr()
    for i in range(m):
        cols = s.indices[s.indptr[i] : s.indptr[i + 1]]
        if len(cols) == 0:
            continue
        cand = [tuple(x[j]) for j in cols]
        out[i] = max(cand)
    return out


def test_tropical_spmv_matches_oracle():
    s = sample_csr(20, 20, density=0.25, seed=110).tocsr()
    rng = np.random.default_rng(4)
    x = rng.integers(0, 8, size=(20, 3)).astype(np.float64)
    got = np.asarray(sparse.csr_array(s).tropical_spmv(x))
    assert np.allclose(got, _oracle(s, x))


def test_tropical_spmv_tie_breaking():
    """Ties on the first component must resolve by the second, then third."""
    import scipy.sparse as sp

    s = sp.csr_matrix(np.array([[1.0, 1.0, 1.0], [0, 1.0, 1.0], [0, 0, 1.0]]))
    x = np.array([[2.0, 5.0, 0.0], [2.0, 5.0, 1.0], [2.0, 4.0, 9.0]])
    got = np.asarray(sparse.csr_array(s).tropical_spmv(x))
    assert np.allclose(got, _oracle(s, x))


def test_tropical_spmv_empty_rows():
    import scipy.sparse as sp

    s = sp.csr_matrix(
        (np.ones(2), np.array([0, 2]), np.array([0, 1, 1, 2])), shape=(3, 3)
    )
    x = np.arange(9.0).reshape(3, 3)
    got = np.asarray(sparse.csr_array(s).tropical_spmv(x))
    exp = _oracle(s, x)
    assert np.allclose(got, exp)


def _host_mis(C, k=1, invalid=None, seed=0):
    """The examples/amg.py host tournament loop — oracle for the device
    while_loop form."""
    N = C.shape[0]
    rng = np.random.default_rng(seed)
    rv = rng.integers(0, np.iinfo(np.int32).max, size=N, dtype=np.int32)
    x = np.stack([np.ones(N, np.int32), rv, np.arange(N, dtype=np.int32)], axis=1)
    if invalid is not None:
        x[invalid, 0] = -1
    C = C.tocsr()
    while np.any(x[:, 0] == 1):
        z = np.array(C.tropical_spmv(x))
        for _ in range(1, k):
            z = np.array(C.tropical_spmv(z))
        mis_node = (x[:, 0] == 1) & (z[:, 2] == np.arange(N))
        x[mis_node, 0] = 2
        non_mis = (x[:, 0] == 1) & (z[:, 0] == 2)
        x[non_mis, 0] = 0
    return x[:, 0]


def _sym_graph(n, density, seed):
    """Symmetric pattern with self-loops — the MIS strength-graph shape."""
    s = sample_csr(n, n, density=density, seed=seed).tocsr()
    import scipy.sparse as sp

    a = sp.csr_matrix(
        (np.asarray(s.data), np.asarray(s.indices), np.asarray(s.indptr)),
        shape=s.shape,
    )
    a = a + a.T + sp.identity(n)
    a.data[:] = 1.0
    return sparse.csr_matrix(
        (a.tocsr().data, a.tocsr().indices, a.tocsr().indptr), shape=a.shape
    )


def test_mis_tropical_matches_host_loop():
    for seed in (0, 3):
        for k in (1, 2):
            C = _sym_graph(40, 0.1, 200 + seed)
            flags_dev = np.asarray(C.mis_tropical(k=k, seed=seed))
            flags_host = _host_mis(C, k=k, seed=seed)
            np.testing.assert_array_equal(flags_dev, flags_host)
            # it IS an independent set (k=1): no two MIS nodes adjacent
            if k == 1:
                import scipy.sparse as sp

                mis = np.nonzero(flags_dev == 2)[0]
                a = sp.csr_matrix(
                    (np.asarray(C.data), np.asarray(C.indices), np.asarray(C.indptr)),
                    shape=C.shape,
                )
                sub = a[np.ix_(mis, mis)].toarray()
                np.fill_diagonal(sub, 0)
                assert not sub.any()


def test_mis_tropical_invalid_nodes():
    C = _sym_graph(30, 0.12, 7)
    invalid = np.zeros(30, bool)
    invalid[:10] = True
    flags = np.asarray(C.mis_tropical(k=1, invalid=invalid))
    assert (flags[:10] == -1).all()
    np.testing.assert_array_equal(flags, _host_mis(C, k=1, invalid=invalid))


def test_mis_aggregate_cols_matches_host():
    C = _sym_graph(50, 0.08, 11)
    flags = C.mis_tropical(k=2)
    col_dev, n_coarse = C.mis_aggregate_cols(flags)
    # host form (examples/amg.py:mis_aggregate fallback)
    flags_np = np.asarray(flags)
    mis = np.nonzero(flags_np == 2)[0]
    x = np.zeros((50, 2), dtype=np.int32)
    x[mis, 0] = 2
    x[mis, 1] = np.arange(mis.size, dtype=np.int32)
    y = np.array(C.tropical_spmv(x))
    y[:, 0] += x[:, 0]
    z = np.array(C.tropical_spmv(y))
    np.testing.assert_array_equal(np.asarray(col_dev), z[:, 1])
    assert int(n_coarse) == mis.size


def test_mis_tropical_stall_fails_fast():
    """A strength graph without self-loops can never elect a winner
    (z[:,2]==i needs i in its own neighborhood): the device loop must
    exit on the first no-progress round and raise, not spin."""
    import pytest

    # 2-cycle without diagonal: each node's only neighbor is the other
    C = sparse.csr_matrix(
        (np.ones(2), np.array([1, 0]), np.array([0, 1, 2])), shape=(2, 2)
    )
    with pytest.raises(RuntimeError, match="no progress"):
        C.mis_tropical(k=1)
