"""sparse_tpu.ingest — streaming matrix ingestion data plane (ISSUE 18).

Pins the subsystem's contract pillars:

* **sort parity** — the mesh-sharded samplesort COO->CSR
  (:func:`ingest_coo_to_csr`) matches the scipy host oracle bit-for-bit
  on indices and to fp tolerance on summed duplicate values, in f32 and
  f64, on both the single-device fast path and the distributed path;
* **fingerprinting** — :func:`structure_key` is permutation/value
  invariant, equals ``SparsityPattern.fingerprint[2]`` exactly, and the
  dedup path is observable: a structural re-arrival reports
  ``dedup=True`` and its first solve costs ZERO new plan-cache misses
  (the PR's acceptance criterion);
* **balance()** — nnz-balanced row bounds beat uniform row splits on a
  skewed profile and are always a valid monotone partition;
* **background onboarding** — `SolveSession.ingest` returns a
  future-style ticket, an onboard racing the first solve of the same
  structure converges on ONE canonical pattern object, and the
  admission bound rejects/blocks at ``max_depth``;
* **streaming IO** — :func:`sparse_tpu.io.read_coo_host` (chunked
  :func:`stream_coo`) matches ``scipy.io.mmread`` on every testdata
  file plus symmetric-expansion and pattern-only bodies, at chunk sizes
  that force multi-chunk parses;
* **telemetry** — the four ``ingest.*`` event kinds are registered in
  the schema and every event a live run emits validates against it;
* **loadgen** — the ``ingest`` trace clause round-trips through
  parse/describe, and ``build_report`` rolls onboarding latency
  percentiles separately from the solve latencies.
"""

import json

import numpy as np
import pytest
import scipy.io as sci_io
import scipy.sparse as sp

import sparse_tpu as sparse
from sparse_tpu import plan_cache, telemetry
from sparse_tpu.batch import SolveSession
from sparse_tpu.config import settings
from sparse_tpu.ingest import (
    FingerprintIndex,
    IngestAdmissionError,
    Onboarder,
    balance,
    balance_stats,
    ingest_coo_to_csr,
    structure_key,
)
from sparse_tpu.ingest.fingerprint import canonicalize_coo
from sparse_tpu.loadgen import ArrivalTrace, build_report

from .utils.common import test_mtx_files


@pytest.fixture
def tel(tmp_path, monkeypatch):
    telemetry.reset()
    monkeypatch.setattr(settings, "telemetry", True)
    telemetry.configure(str(tmp_path / "records.jsonl"))
    yield tmp_path / "records.jsonl"
    telemetry.configure(None)
    telemetry.reset()


def _random_coo(n=40, k=160, seed=0, dtype=np.float64, dups=True):
    """Unsorted COO with duplicate coordinates (when ``dups``)."""
    rng = np.random.default_rng(seed)
    rows = rng.integers(0, n, size=k)
    cols = rng.integers(0, n, size=k)
    if dups:  # force at least a few exact duplicates
        rows[: k // 8] = rows[k // 2 : k // 2 + k // 8]
        cols[: k // 8] = cols[k // 2 : k // 2 + k // 8]
    vals = rng.standard_normal(k).astype(dtype)
    return rows, cols, vals, (n, n)


def _spd_coo(n=24, seed=0):
    """Diagonally-dominant symmetric COO (CG-solvable)."""
    rng = np.random.default_rng(seed)
    k = 2 * n
    r = rng.integers(0, n, size=k)
    c = rng.integers(0, n, size=k)
    v = 0.1 * rng.standard_normal(k)
    d = np.arange(n)
    rows = np.concatenate([d, r, c])
    cols = np.concatenate([d, c, r])
    vals = np.concatenate([np.full(n, float(n)), v, v])
    return rows, cols, vals, (n, n)


# ---------------------------------------------------------------------------
# samplesort COO -> CSR parity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
@pytest.mark.parametrize("num_shards", [1, 4])
def test_sort_parity_vs_host_oracle(dtype, num_shards):
    rows, cols, vals, shape = _random_coo(seed=3, dtype=dtype)
    got = ingest_coo_to_csr(rows, cols, vals, shape, num_shards=num_shards)
    ref = sp.coo_matrix((vals, (rows, cols)), shape=shape).tocsr()
    ref.sum_duplicates()
    ref.sort_indices()
    assert got.dtype == np.dtype(dtype)
    np.testing.assert_array_equal(np.asarray(got.indptr), ref.indptr)
    np.testing.assert_array_equal(np.asarray(got.indices), ref.indices)
    tol = 1e-5 if dtype == np.float32 else 1e-12
    np.testing.assert_allclose(np.asarray(got.data), ref.data, atol=tol)


def test_sort_empty_and_validation():
    got = ingest_coo_to_csr(
        np.zeros(0, np.int64), np.zeros(0, np.int64), np.zeros(0), (5, 7)
    )
    assert got.shape == (5, 7) and got.nnz == 0
    with pytest.raises(ValueError):
        ingest_coo_to_csr(np.array([0, 1]), np.array([0]), np.array([1.0]),
                          (2, 2))


# ---------------------------------------------------------------------------
# fingerprinting + dedup
# ---------------------------------------------------------------------------


def test_structure_key_permutation_and_value_invariant():
    rows, cols, vals, shape = _random_coo(seed=5)
    k1 = structure_key(rows, cols, shape)
    perm = np.random.default_rng(0).permutation(rows.shape[0])
    k2 = structure_key(rows[perm], cols[perm], shape)
    assert k1 == k2  # order never matters
    # values never matter — and the key matches the live pattern's
    csr = ingest_coo_to_csr(rows, cols, vals, shape)
    from sparse_tpu.batch.operator import SparsityPattern

    pat = SparsityPattern.from_csr(csr)
    assert pat.fingerprint[2] == k1
    # different structure -> different key
    k3 = structure_key(rows, (cols + 1) % shape[1], shape)
    assert k3 != k1


def test_canonicalize_dedups_by_sum():
    rows = np.array([1, 0, 1, 1])
    cols = np.array([2, 0, 2, 0])
    vals = np.array([1.5, 2.0, 2.5, -1.0])
    crows, ccols, cvals = canonicalize_coo(rows, cols, vals, (3, 3))
    np.testing.assert_array_equal(crows, [0, 1, 1])
    np.testing.assert_array_equal(ccols, [0, 0, 2])
    np.testing.assert_allclose(cvals, [2.0, -1.0, 4.0])
    with pytest.raises(ValueError):
        canonicalize_coo(np.array([3]), np.array([0]), None, (3, 3))


def test_fingerprint_index_note_and_lookup():
    idx = FingerprintIndex(autoload=False)
    assert idx.lookup("abc") is None
    idx.note("abc", "p123")
    assert idx.lookup("abc") == "p123"
    assert len(idx) == 1
    assert idx.snapshot() == {"abc": "p123"}


# ---------------------------------------------------------------------------
# balance(): nnz-balanced row resharding
# ---------------------------------------------------------------------------


def test_balance_beats_uniform_on_skew():
    # front-loaded profile: first rows hold almost all the nnz
    counts = np.zeros(64, np.int64)
    counts[:8] = 120
    counts[8:] = 2
    indptr = np.concatenate([[0], np.cumsum(counts)])
    bounds = balance(indptr, 8)
    assert bounds[0] == 0 and bounds[-1] == 64
    assert np.all(np.diff(bounds) >= 0)
    st = balance_stats(indptr, 8)
    assert st["balanced_imbalance"] < st["uniform_imbalance"]
    # uniform row splits put 8x the ideal nnz on shard 0; balanced
    # bounds stay within one heavy row of the ideal
    assert st["uniform_imbalance"] > 7.0
    assert st["balanced_imbalance"] < 2.0


def test_balance_uniform_profile_is_even():
    indptr = np.arange(0, 33 * 4, 4)  # 32 rows x 4 nnz
    bounds = balance(indptr, 4)
    np.testing.assert_array_equal(bounds, [0, 8, 16, 24, 32])


# ---------------------------------------------------------------------------
# background onboarding through SolveSession.ingest
# ---------------------------------------------------------------------------


def test_ingest_cold_then_dedup_zero_plan_misses():
    src = _spd_coo(n=24, seed=11)
    sess = SolveSession(solver="cg")
    try:
        out = sess.ingest(src, wait=True, timeout=180.0).result()
        assert out["state"] == "ready" and out["dedup"] is False
        pat = out["pattern"]
        assert pat.fingerprint in sess._patterns
        assert "ingest" in sess.session_stats()

        # structural re-arrival (same pattern, new values): dedup hit,
        # and its first solve costs zero new plan-cache compiles
        rows, cols, vals, shape = src
        src2 = (rows, cols, vals * 1.5, shape)
        snap = plan_cache.snapshot()
        out2 = sess.ingest(src2, wait=True, timeout=60.0).result()
        assert out2["dedup"] is True
        assert out2["pattern"] is pat  # the SAME canonical object
        b = np.ones(shape[0])
        tk = sess.submit(out2["csr"], b, tol=1e-9)
        sess.drain()
        x = np.asarray(tk.result()[0])
        A = sp.csr_matrix(
            (np.asarray(out2["csr"].data), np.asarray(out2["csr"].indices),
             np.asarray(out2["csr"].indptr)), shape=shape,
        )
        np.testing.assert_allclose(A @ x, b, atol=1e-6)
        assert plan_cache.delta(snap)["misses"] == 0
    finally:
        sess._onboarder.close()


def test_onboard_races_first_solve_converges():
    rows, cols, vals, shape = _spd_coo(n=20, seed=13)
    A = sp.csr_matrix(
        sp.coo_matrix((vals, (rows, cols)), shape=shape)
    )
    A.sum_duplicates()
    A.sort_indices()
    sess = SolveSession(solver="cg")
    try:
        t = sess.ingest((rows, cols, vals, shape))  # background
        b = np.ones(shape[0])
        tk = sess.submit(sparse.csr_array(A), b, tol=1e-9)
        sess.flush()
        x = np.asarray(tk.result()[0])
        np.testing.assert_allclose(A @ x, b, atol=1e-6)
        assert t.wait(timeout=180.0)
        out = t.result()
        # both sides raced _patterns.setdefault: ONE canonical pattern
        fp = out["pattern"].fingerprint
        assert sess._patterns[fp] is out["pattern"]
        assert sum(1 for k in sess._patterns if k == fp) == 1
    finally:
        sess._onboarder.close()


class _Blocker:
    """tocoo() blocks until released — pins the worker mid-item."""

    def __init__(self):
        import threading

        self.release = threading.Event()

    def tocoo(self):
        self.release.wait(30.0)
        c = sp.coo_matrix(np.eye(3))
        return c


def test_admission_bound_rejects_at_depth():
    import time

    sess = SolveSession(solver="cg")
    onb = Onboarder(sess, max_depth=1, admission="reject", retries=0)
    try:
        blocker = _Blocker()
        t1 = onb.submit(blocker)
        deadline = time.monotonic() + 10.0
        while onb.stats()["active"] != 1:  # worker picked up the blocker
            assert time.monotonic() < deadline
            time.sleep(0.005)
        t2 = onb.submit(_spd_coo(n=6, seed=1))  # fills the queue
        with pytest.raises(IngestAdmissionError):
            onb.submit(_spd_coo(n=7, seed=2))
        assert onb.stats()["queued"] == 1
        blocker.release.set()
        assert t1.wait(timeout=180.0) and t2.wait(timeout=180.0)
        assert t1.state == "ready" and t2.state == "ready"
    finally:
        onb.close()
        if sess._onboarder is not None:
            sess._onboarder.close()


def test_failed_arrival_retries_then_raises():
    sess = SolveSession(solver="cg")
    onb = Onboarder(sess, retries=1)
    try:
        t = onb.submit(object())  # not ingestable
        assert t.wait(timeout=30.0)
        assert t.state == "failed"
        with pytest.raises(Exception, match="failed after 2 attempts"):
            t.result()
        assert onb.stats()["failed"] == 1
        assert onb.stats()["retries"] == 1
    finally:
        onb.close()
        if sess._onboarder is not None:
            sess._onboarder.close()


# ---------------------------------------------------------------------------
# streaming MatrixMarket IO vs the scipy oracle
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("filename", test_mtx_files)
@pytest.mark.parametrize("chunk_nnz", [3, 1 << 20])
def test_read_coo_host_parity(filename, chunk_nnz):
    rows, cols, vals, shape = sparse.io.read_coo_host(
        filename, chunk_nnz=chunk_nnz
    )
    ref = sci_io.mmread(filename)
    got = sp.coo_matrix((vals, (rows, cols)), shape=shape)
    assert got.shape == ref.shape
    assert np.allclose(got.toarray(), ref.toarray())


def test_stream_coo_symmetric_and_pattern(tmp_path):
    p1 = tmp_path / "sym.mtx"
    p1.write_text(
        "%%MatrixMarket matrix coordinate real symmetric\n"
        "% comment line\n"
        "3 3 4\n1 1 2.0\n2 1 -1.0\n3 2 0.5\n3 3 4.0\n"
    )
    rows, cols, vals, shape = sparse.io.read_coo_host(str(p1), chunk_nnz=2)
    got = sp.coo_matrix((vals, (rows, cols)), shape=shape).toarray()
    ref = sci_io.mmread(str(p1)).toarray()
    assert np.allclose(got, ref)

    p2 = tmp_path / "pat.mtx"
    p2.write_text(
        "%%MatrixMarket matrix coordinate pattern general\n"
        "2 4 3\n1 2\n2 1\n2 4\n"
    )
    rows, cols, vals, shape = sparse.io.read_coo_host(str(p2), chunk_nnz=2)
    got = sp.coo_matrix((vals, (rows, cols)), shape=shape).toarray()
    ref = sci_io.mmread(str(p2)).toarray()
    assert np.allclose(got, ref)


def test_stream_coo_rejects_bad_bodies(tmp_path):
    p = tmp_path / "short.mtx"
    p.write_text(
        "%%MatrixMarket matrix coordinate real general\n"
        "2 2 3\n1 1 1.0\n2 2 2.0\n"
    )
    with pytest.raises(ValueError, match="expected 3"):
        list(sparse.io.stream_coo(str(p)))
    p2 = tmp_path / "arr.mtx"
    p2.write_text("%%MatrixMarket matrix array real general\n1 1\n1.0\n")
    with pytest.raises(ValueError, match="coordinate"):
        list(sparse.io.stream_coo(str(p2)))
    # read_coo_host falls back to the dense decoder for array files
    rows, cols, vals, shape = sparse.io.read_coo_host(str(p2))
    assert shape == (1, 1) and vals[0] == 1.0


def test_ingest_from_mtx_path(tmp_path, tel):
    rows, cols, vals, shape = _spd_coo(n=10, seed=3)
    A = sp.coo_matrix((vals, (rows, cols)), shape=shape)
    A.sum_duplicates()
    path = tmp_path / "arrival.mtx"
    sci_io.mmwrite(str(path), A)
    sess = SolveSession(solver="cg")
    try:
        out = sess.ingest(str(path), wait=True, timeout=180.0).result()
        assert out["state"] == "ready"
        got = sp.csr_matrix(
            (np.asarray(out["csr"].data), np.asarray(out["csr"].indices),
             np.asarray(out["csr"].indptr)), shape=shape,
        )
        assert np.allclose(got.toarray(), A.toarray())
    finally:
        sess._onboarder.close()
    # every emitted ingest.* event validates against the schema
    from sparse_tpu.telemetry import _schema

    for kind in ("ingest.arrive", "ingest.sort", "ingest.dedup",
                 "ingest.onboard"):
        assert kind in _schema.KINDS
    events = [json.loads(ln) for ln in tel.read_text().splitlines()]
    ingest_events = [e for e in events if e["kind"].startswith("ingest.")]
    kinds = {e["kind"] for e in ingest_events}
    assert {"ingest.arrive", "ingest.sort", "ingest.dedup",
            "ingest.onboard"} <= kinds
    for e in ingest_events:
        _schema.validate(e)


# ---------------------------------------------------------------------------
# loadgen: the ingest arrival clause + onboard report rollup
# ---------------------------------------------------------------------------


def test_trace_ingest_clause_roundtrip():
    spec = "poisson:rate=8,duration=1,seed=2;ingest:rate=3,duration=1,seed=5,size=32"
    tr = ArrivalTrace.parse(spec)
    kinds = [a.kind for a in tr.arrivals]
    assert "ingest" in kinds and "solve" in kinds
    for a in tr.arrivals:
        if a.kind == "ingest":
            assert a.size == 32 and a.tenant == "ingest"
    # describe() -> parse() is a fixed point
    again = ArrivalTrace.parse(tr.describe())
    assert again.describe() == tr.describe()
    assert [(a.t, a.kind, a.size) for a in again.arrivals] == [
        (a.t, a.kind, a.size) for a in tr.arrivals
    ]
    with pytest.raises(Exception):
        ArrivalTrace.parse("ingest:rate=1,duration=1,size=1")  # size < 2


def test_build_report_onboard_rollup():
    tr = ArrivalTrace.parse(
        "poisson:rate=10,duration=1,seed=0;ingest:rate=2,duration=1,seed=1"
    )
    n_solve = sum(1 for a in tr.arrivals if a.kind == "solve")
    outcomes = [("", 0.010, True, False)] * n_solve
    onboard = [(250.0, True, False), (40.0, True, True),
               (None, False, False)]
    rep = build_report(tr, outcomes, wall_s=1.0, slo_ms=100.0,
                       onboard=onboard, onboard_rejected=1)
    assert rep.onboard["arrivals"] == 4
    assert rep.onboard["completed"] == 2
    assert rep.onboard["failed"] == 2
    assert rep.onboard["dedup_hits"] == 1
    assert rep.onboard["latency_ms"]["max"] == 250.0
    assert rep.onboard["latency_ms"]["p50"] in (40.0, 250.0)
    # onboarding never leaks into the solve rollup
    assert rep.completed == n_solve
    assert rep.slo_misses == 0
    # offered counts solve arrivals only
    assert rep.offered_rps == round(n_solve / 1.0, 3)
    assert "ingest" not in rep.tenants
    d = rep.as_dict()
    assert d["onboard"]["latency_ms"]["p95"] == 250.0
    # no ingest clause -> empty rollup
    tr2 = ArrivalTrace.parse("poisson:rate=5,duration=1,seed=0")
    rep2 = build_report(tr2, [("", 0.01, True, False)], wall_s=1.0)
    assert rep2.onboard == {}
