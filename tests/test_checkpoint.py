"""Checkpoint/resume subsystem (SURVEY §5 aux category; absent in the
reference — a failed long solve there restarts from zero)."""

import numpy as np
import pytest
import scipy.sparse as sp

import sparse_tpu as sparse
from sparse_tpu.checkpoint import (
    CheckpointManager, checkpointed_cg, checkpointed_solve_ivp,
)
from .utils.sample import sample_vec


def _spd(n, seed=0):
    rng = np.random.default_rng(seed)
    S = sp.random(n, n, 0.05, random_state=rng)
    return ((S + S.T) * 0.5 + sp.diags(np.linspace(2, 5, n))).tocsr()


def test_manager_atomic_roundtrip(tmp_path):
    p = tmp_path / "ck.npz"
    m = CheckpointManager(p)
    assert m.load() == (None, None)
    m.save(7, x=np.arange(4.0), rho=np.float64(0.5))
    step, state = m.load()
    assert step == 7
    np.testing.assert_array_equal(state["x"], np.arange(4.0))
    m.save(9, x=np.ones(4))  # overwrite is atomic
    step, state = m.load()
    assert step == 9 and state["x"].sum() == 4
    m.delete()
    assert m.load() == (None, None)


def test_checkpointed_cg_resumes_exactly(tmp_path):
    n = 400
    S = _spd(n)
    A = sparse.csr_array(S)
    b = np.asarray(S @ sample_vec(n, seed=1))
    # uninterrupted reference
    x_ref, it_ref = checkpointed_cg(A, b, tmp_path / "ref.npz", tol=1e-10,
                                    chunk=40)
    r = np.linalg.norm(S @ np.asarray(x_ref) - b) / np.linalg.norm(b)
    assert r <= 1e-8
    # interrupted run: small maxiter leaves a checkpoint behind
    p = tmp_path / "ck.npz"
    x_part, it_part = checkpointed_cg(A, b, p, tol=1e-10, chunk=40,
                                      maxiter=80)
    assert p.exists() and it_part <= 80 < it_ref
    # resume completes and the checkpoint is consumed
    x_res, it_res = checkpointed_cg(A, b, p, tol=1e-10, chunk=40)
    assert not p.exists()
    r = np.linalg.norm(S @ np.asarray(x_res) - b) / np.linalg.norm(b)
    assert r <= 1e-8
    # resumed trajectory is the SAME recurrence: the reported total
    # (checkpointed + resumed sweeps) matches the uninterrupted count
    assert abs(it_res - it_ref) <= 40  # within one chunk boundary
    assert it_res >= it_part


def test_load_tolerates_truncated_and_corrupt_npz(tmp_path):
    """ISSUE 5 satellite: load() is called mid-recovery — a torn/corrupt
    file must read as 'no checkpoint' (with a warning), never raise."""
    p = tmp_path / "ck.npz"
    m = CheckpointManager(p)
    m.save(3, x=np.arange(32.0))
    # truncate the zip mid-payload (external damage the atomic rename
    # can't prevent)
    raw = p.read_bytes()
    p.write_bytes(raw[: len(raw) // 2])
    with pytest.warns(UserWarning, match="corrupt/truncated"):
        assert m.load() == (None, None)
    # outright garbage (not a zip at all)
    p.write_bytes(b"this is not an npz")
    with pytest.warns(UserWarning, match="corrupt/truncated"):
        assert m.load() == (None, None)
    # a valid npz MISSING the step counter is corrupt too
    np.savez(p, x=np.arange(4.0))
    with pytest.warns(UserWarning, match="corrupt/truncated"):
        assert m.load() == (None, None)
    # recovery proceeds: a fresh save over the damaged file works
    m.save(4, x=np.ones(8))
    step, state = m.load()
    assert step == 4 and state["x"].sum() == 8


def test_load_corrupt_emits_telemetry_event(tmp_path):
    from sparse_tpu import telemetry
    from sparse_tpu.config import settings

    p = tmp_path / "ck.npz"
    m = CheckpointManager(p)
    m.save(1, x=np.zeros(4))
    p.write_bytes(b"garbage")
    old = settings.telemetry
    telemetry.configure(str(tmp_path / "records.jsonl"))
    settings.telemetry = True
    try:
        with pytest.warns(UserWarning):
            m.load()
        (ev,) = telemetry.events("checkpoint.corrupt")
        assert ev["path"].endswith("ck.npz")
        assert not telemetry.schema.validate(ev)
    finally:
        settings.telemetry = old
        telemetry.configure(None)
        telemetry.reset()


def test_checkpointed_cg_keep_on_success(tmp_path):
    n = 120
    S = _spd(n, seed=2)
    A = sparse.csr_array(S)
    b = np.asarray(S @ sample_vec(n, seed=3))
    p = tmp_path / "keep.npz"
    checkpointed_cg(A, b, p, tol=1e-10, chunk=500, keep_on_success=True)
    assert p.exists()


def test_checkpointed_solve_ivp_resume(tmp_path):
    import jax.numpy as jnp

    def decay(t, y):
        return -0.7 * y

    p = tmp_path / "ivp.npz"
    y0 = np.array([1.0, 2.0])
    # run with a tiny max_step so many steps occur, checkpointing often
    sol = checkpointed_solve_ivp(decay, (0, 2.0), y0, p, method="RK45",
                                 checkpoint_every=5, max_step=0.01)
    assert sol.status == 0 and sol.resumed_from is None
    assert not p.exists()  # consumed on success
    # simulate a crash: pre-seed a checkpoint mid-interval, then resume
    CheckpointManager(p).save(123, t=np.float64(1.0),
                              y=y0 * np.exp(-0.7 * 1.0))
    sol2 = checkpointed_solve_ivp(decay, (0, 2.0), y0, p, method="RK45",
                                  checkpoint_every=10)
    assert sol2.resumed_from == 1.0
    np.testing.assert_allclose(
        np.asarray(sol2.y)[:, -1], y0 * np.exp(-0.7 * 2.0), rtol=1e-4
    )
