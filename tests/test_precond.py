"""Precond subsystem (ISSUE 14): pattern-shared batched preconditioners.

The load-bearing contracts:

* **Factor correctness** — point/block Jacobi match direct diagonal /
  block solves (ragged last block included); the fixed-sweep Chow-Patel
  ILU(0) reproduces the exact reference factorization at high sweep
  counts; IC(0) factors satisfy ``L L^T = A`` on the pattern.
* **B=1 parity with a non-identity M** — the batched preconditioned
  paths (`batch/krylov.py` cg/gmres ``M=``) reproduce the unbatched
  preconditioned ``linalg.cg``/``gmres`` at machine eps for
  f32/f64/c128, and frozen converged lanes stay bit-stable under a
  non-identity M (the satellite coverage gap).
* **Policy/keys** — SPARSE_TPU_PRECOND / per-session / per-ticket
  resolution, precond-suffixed program keys, exactly ONE symbolic
  factorization per (pattern, bucket), vault round-trip + quarantine of
  the ``ilu_symbolic`` artifact, and a precond-keyed warm restart at
  zero plan-cache misses — including the mesh/fleet path.
* **Resilience** — the recovery ladder's drop-preconditioner rung:
  ``nonfinite:precond`` injection classifies as ``nonfinite_m`` and
  drops M without a solver escalation; a stalling preconditioned solve
  sheds M before escalating.
* **GMRES warm-up** — a non-identity M warms eagerly before the first
  compiled cycle (aligned with cg), pinned by call accounting and the
  host-sync count.

Runs on the conftest-forced 8-device virtual CPU mesh.
"""

import numpy as np
import pytest
import scipy.sparse as sp

import sparse_tpu
from sparse_tpu import linalg, plan_cache, precond, telemetry, utils, vault
from sparse_tpu.batch import BatchedCSR, SolveSession, SparsityPattern
from sparse_tpu.batch.krylov import batched_bicgstab, batched_cg, batched_gmres
from sparse_tpu.config import settings
from sparse_tpu.precond import ilu as pilu
from sparse_tpu.resilience import faults
from sparse_tpu.resilience.policy import RecoveryPolicy, solve_with_recovery
from sparse_tpu.telemetry import _cost, _metrics


@pytest.fixture(autouse=True)
def _clean_state(tmp_path):
    faults.clear()
    old_vault = settings.vault
    old_tel = settings.telemetry
    old_precond = settings.precond
    settings.vault = ""
    telemetry.configure(str(tmp_path / "records.jsonl"))
    telemetry.reset()
    plan_cache.clear()
    yield
    faults.clear()
    settings.vault = old_vault
    settings.telemetry = old_tel
    settings.precond = old_precond
    telemetry.configure(None)
    telemetry.reset()
    plan_cache.clear()


def _spd(n=32, seed=3, density=0.15, dtype=np.float64):
    """Random SPD CSR with a full structural diagonal."""
    A = sp.random(n, n, density=density, random_state=seed, format="csr")
    A = A + A.T + sp.eye(n) * (np.abs(A).sum(axis=1).max() + 1.0)
    A = A.tocsr().astype(dtype)
    A.sort_indices()
    return A


def _vardiag(n=48, seed=0, dtype=np.float64, spread=3.0):
    """SPD tridiagonal with a wildly varying diagonal — the shape
    Jacobi-family preconditioners visibly help."""
    rng = np.random.default_rng(seed)
    e = np.ones(n)
    A = sp.diags([-e[:-1], 2.0 * e, -e[:-1]], [-1, 0, 1], format="csr")
    A = A.copy()
    A.setdiag(2.0 + np.exp(rng.normal(0, spread, n)))
    A = A.tocsr().astype(dtype)
    A.sort_indices()
    return A


def _pattern(A):
    return SparsityPattern(A.indptr, A.indices, A.shape)


# ---------------------------------------------------------------------------
# factor correctness
# ---------------------------------------------------------------------------
def test_jacobi_apply_is_diag_scaling():
    A = _spd(24, seed=1)
    pat = _pattern(A)
    vals = np.asarray(A.data)[None, :]
    M = precond.make_factory(pat, "jacobi")(vals, None)
    r = np.random.default_rng(0).standard_normal((1, 24))
    np.testing.assert_allclose(
        np.asarray(M(r))[0], r[0] / A.diagonal(), rtol=1e-12
    )


@pytest.mark.parametrize("n", [24, 26])  # 26: ragged last block at bs=4
def test_bjacobi_apply_matches_block_solve(n):
    A = _spd(n, seed=2)
    pat = _pattern(A)
    vals = np.asarray(A.data)[None, :]
    M = precond.make_factory(pat, "bjacobi")(vals, None)
    r = np.random.default_rng(1).standard_normal((1, n))
    z = np.asarray(M(r))[0]
    bs = settings.precond_block
    ref = np.zeros(n)
    for k in range(0, n, bs):
        hi = min(k + bs, n)
        blk = A[k:hi, k:hi].toarray()
        ref[k:hi] = np.linalg.solve(blk, r[0][k:hi])
    np.testing.assert_allclose(z, ref, rtol=1e-10, atol=1e-10)


def test_ilu0_factor_matches_reference():
    A = _spd(28, seed=5)
    pat = _pattern(A)
    sym = pilu.ilu0_symbolic(pat, "ilu0")
    F = np.asarray(
        pilu.factorize(sym, np.asarray(A.data)[None, :], sweeps=40)
    )[0]
    Fref = pilu.ilu0_reference(A.indptr, A.indices, A.data)
    np.testing.assert_allclose(F, Fref, rtol=1e-12, atol=1e-12)


def test_ic0_factor_llt_matches_on_pattern():
    A = _spd(24, seed=6)
    pat = _pattern(A)
    sym = pilu.ilu0_symbolic(pat, "ic0")
    assert sym.symmetric
    F = np.asarray(
        pilu.factorize(sym, np.asarray(A.data)[None, :], sweeps=40)
    )[0]
    n = A.shape[0]
    rows = np.repeat(np.arange(n), np.diff(A.indptr))
    cols = A.indices
    L = np.zeros((n, n))
    low = rows >= cols
    L[rows[low], cols[low]] = F[low]
    R = L @ L.T
    for i, j in zip(rows, cols):
        assert abs(R[i, j] - A[i, j]) < 1e-10


def test_ic0_asymmetric_pattern_falls_back_to_jacobi():
    # structurally asymmetric: one extra strict-upper entry whose
    # transpose slot is absent
    A = _spd(16, seed=7).tolil()
    dense = A.toarray()
    i, j = next(
        (i, j) for i in range(16) for j in range(16)
        if i < j and dense[i, j] == 0 and dense[j, i] == 0
    )
    A[i, j] = 0.1
    A = A.tocsr()
    A.sort_indices()
    pat = _pattern(A)
    pol = precond.PrecondPolicy("ic0")
    kind = pol.decide(pat, "cg", 1, np.float64)
    assert kind == "jacobi"


@pytest.mark.parametrize("kind", ["ilu0", "ic0", "cheby", "neumann"])
def test_kinds_reduce_or_match_cg_iterations(kind):
    A = _vardiag(32, seed=4, spread=2.0)
    pat = _pattern(A)
    vals = np.asarray(A.data)[None, :]
    op = BatchedCSR(pat, vals)
    b = np.random.default_rng(2).standard_normal((1, 32))
    _, info0 = batched_cg(op, b, tol=1e-9, maxiter=2000, conv_test_iters=5)
    # small sweep counts keep the unrolled apply graph (and its compile
    # on the 1-core CI host) cheap; correctness is sweep-independent
    pol = precond.PrecondPolicy(kind, sweeps=2, tri_sweeps=2, degree=3)
    Mv = pol.factory(pat, kind)(op.values, op.matvec)
    X, info = batched_cg(op, b, tol=1e-9, maxiter=2000, conv_test_iters=5,
                         M=Mv)
    assert bool(np.asarray(info.converged)[0])
    assert int(np.asarray(info.iters)[0]) <= int(np.asarray(info0.iters)[0])
    r = b[0] - np.asarray(A @ np.asarray(X)[0])
    assert np.linalg.norm(r) < 1e-8 * 10


# ---------------------------------------------------------------------------
# B=1 parity with a non-identity M (the satellite coverage gap)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.complex128])
def test_b1_cg_parity_with_M(dtype):
    A = _vardiag(32, seed=11, spread=2.0).astype(dtype)
    if np.dtype(dtype).kind == "c":
        A = (A + 0j).tocsr()
    pat = _pattern(A)
    vals = np.asarray(A.data)[None, :]
    b = np.random.default_rng(3).standard_normal(32).astype(dtype)
    tol = 1e-5 if dtype == np.float32 else 1e-11
    Mv = precond.make_factory(pat, "jacobi")(vals, None)
    Xb, info = batched_cg(BatchedCSR(pat, vals), b[None, :], tol=tol,
                          maxiter=1500, M=Mv)
    Mu = precond.make_M(sparse_tpu.csr_array(A), "jacobi")
    xu, iu = linalg.cg(sparse_tpu.csr_array(A), b, tol=tol, maxiter=1500,
                       M=Mu)
    assert int(np.asarray(info.iters)[0]) == iu
    np.testing.assert_allclose(
        np.asarray(Xb)[0], np.asarray(xu),
        rtol=1e-4 if dtype == np.float32 else 1e-11,
        atol=1e-5 if dtype == np.float32 else 1e-11,
    )
    assert bool(np.asarray(info.converged)[0])


@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.complex128])
def test_b1_gmres_parity_with_M(dtype):
    A = _vardiag(32, seed=12, spread=2.0).astype(dtype)
    if np.dtype(dtype).kind == "c":
        A = (A + 0j).tocsr()
    pat = _pattern(A)
    vals = np.asarray(A.data)[None, :]
    b = np.random.default_rng(4).standard_normal(32).astype(dtype)
    tol = 1e-5 if dtype == np.float32 else 1e-10
    Mv = precond.make_factory(pat, "jacobi")(vals, None)
    Xb, info = batched_gmres(BatchedCSR(pat, vals), b[None, :], tol=tol,
                             restart=8, M=Mv)
    Mu = precond.make_M(sparse_tpu.csr_array(A), "jacobi")
    xu, iu = linalg.gmres(sparse_tpu.csr_array(A), b, tol=tol, restart=8,
                          M=Mu)
    assert int(np.asarray(info.iters)[0]) == iu
    np.testing.assert_allclose(
        np.asarray(Xb)[0], np.asarray(xu),
        rtol=1e-4 if dtype == np.float32 else 1e-9,
        atol=1e-4 if dtype == np.float32 else 1e-9,
    )


def test_b1_bicgstab_preconditioned_converges_faster():
    A = _vardiag(40, seed=13)
    pat = _pattern(A)
    vals = np.asarray(A.data)[None, :]
    op = BatchedCSR(pat, vals)
    b = np.random.default_rng(5).standard_normal((1, 40))
    _, info0 = batched_bicgstab(op, b, tol=1e-9, maxiter=2000,
                                conv_test_iters=1)
    Mv = precond.make_factory(pat, "jacobi")(vals, None)
    X, info = batched_bicgstab(op, b, tol=1e-9, maxiter=2000,
                               conv_test_iters=1, M=Mv)
    assert bool(np.asarray(info.converged)[0])
    assert int(np.asarray(info.iters)[0]) < int(np.asarray(info0.iters)[0])
    r = b[0] - np.asarray(A @ np.asarray(X)[0])
    assert np.linalg.norm(r) < 1e-8


def test_frozen_lane_bit_stable_under_M():
    """A lane that converges early (loose tol) must freeze bit-stable
    while its preconditioned neighbors keep iterating."""
    A = _vardiag(32, seed=14, spread=2.0)
    pat = _pattern(A)
    B = 3
    vals = np.repeat(np.asarray(A.data)[None, :], B, axis=0)
    rng = np.random.default_rng(6)
    rhs = rng.standard_normal((B, 32))
    op = BatchedCSR(pat, vals)
    Mv = precond.make_factory(pat, "jacobi")(vals, op.matvec)
    tols = np.array([1e-2, 1e-10, 1e-10])
    X, info = batched_cg(op, rhs, tol=tols, maxiter=1500, M=Mv,
                         conv_test_iters=5)
    # solo B=1 solve of the loose lane at the same tol: bit-stable freeze
    op1 = BatchedCSR(pat, vals[:1])
    Mv1 = precond.make_factory(pat, "jacobi")(vals[:1], op1.matvec)
    X1, info1 = batched_cg(op1, rhs[:1], tol=1e-2, maxiter=1500, M=Mv1,
                           conv_test_iters=5)
    assert int(np.asarray(info.iters)[0]) == int(np.asarray(info1.iters)[0])
    np.testing.assert_array_equal(np.asarray(X)[0], np.asarray(X1)[0])
    assert np.asarray(info.converged).all()


# ---------------------------------------------------------------------------
# gmres warm-up alignment (satellite)
# ---------------------------------------------------------------------------
def test_gmres_warms_noniidentity_M_eagerly():
    n = 40
    A = _vardiag(n, seed=15)
    b = np.random.default_rng(7).standard_normal(n)
    dinv = 1.0 / A.diagonal()
    calls = {"eager": 0, "traced": 0}

    def mv(r):
        if utils.in_trace():
            calls["traced"] += 1
        else:
            calls["eager"] += 1
        import jax.numpy as jnp

        return r * jnp.asarray(dinv)

    M = linalg.LinearOperator((n, n), matvec=mv, dtype=np.dtype(np.float64))
    linalg.HOST_SYNCS = 0
    x, iters = linalg.gmres(sparse_tpu.csr_array(A), b, tol=1e-9, M=M,
                            restart=20)
    # warmed exactly once, eagerly, BEFORE the first compiled cycle —
    # every later apply is a trace-time call inside the jitted cycle,
    # never a per-iteration host call
    assert calls["eager"] == 1
    assert calls["traced"] >= 1
    cycles = max(-(-iters // 20), 1)
    # one packed fetch per restart cycle (+1 final): M adds NO syncs
    assert linalg.HOST_SYNCS <= cycles + 1
    r = b - np.asarray(A @ np.asarray(x))
    assert np.linalg.norm(r) <= 1e-9 * np.linalg.norm(b) * 10


# ---------------------------------------------------------------------------
# policy resolution, program keys, build cadence
# ---------------------------------------------------------------------------
def test_canonical_kind_round_trip():
    assert precond.canonical_kind("") == "none"
    assert precond.canonical_kind("off") == "none"
    assert precond.canonical_kind(None) == "none"
    assert precond.canonical_kind("BJACOBI") == "bjacobi"
    assert precond.canonical_kind("auto") == "auto"
    with pytest.raises(ValueError):
        precond.canonical_kind("ilu7")
    with pytest.raises(ValueError):
        precond.canonical_kind("auto", allow_auto=False)


def test_key_suffix_backcompat():
    assert precond.key_suffix("none") == ""
    assert precond.key_suffix(None) == ""
    assert precond.key_suffix("ilu0") == ".Milu0"


def test_policy_auto_and_env():
    A = _spd(16, seed=8)
    pat = _pattern(A)
    pol = precond.PrecondPolicy("auto")
    assert pol.decide(pat, "cg", 4, np.float64) == "bjacobi"
    assert pol.decide(pat, "gmres", 4, np.float64) == "jacobi"
    # env resolution + per-call override
    settings.precond = "jacobi"
    pol2 = precond.PrecondPolicy()
    assert pol2.mode == "jacobi"
    assert pol2.decide(pat, "cg", 4, np.float64, override="off") == "none"
    settings.precond = ""
    with pytest.raises(ValueError):
        precond.PrecondPolicy("bogus")


def test_session_program_keys_and_per_ticket_override():
    A = _vardiag(32, seed=16, spread=2.0)
    b = np.random.default_rng(8).standard_normal(32)
    _cost.reset()
    ses = SolveSession("cg", warm_start=False, precond="bjacobi")
    t1 = ses.submit(A, b, tol=1e-8, maxiter=2000)
    t2 = ses.submit(A, b, tol=1e-8, maxiter=2000, precond="off")
    t3 = ses.submit(A, b, tol=1e-8, maxiter=2000, precond="jacobi")
    ses.flush()
    for t in (t1, t2, t3):
        x, iters, r2 = t.result()
        assert np.sqrt(r2) <= 1e-8 * 1.01
    keys = set(_cost.programs())
    assert "batch.cg.B1.<f8.Mbjacobi" in keys
    assert "batch.cg.B1.<f8" in keys  # the 'off' override: historic key
    assert "batch.cg.B1.<f8.Mjacobi" in keys
    # the preconditioned lanes actually solved with fewer iterations
    assert t1.result()[1] < t2.result()[1]


def test_one_symbolic_build_per_pattern_and_bucket():
    A = _vardiag(32, seed=17, spread=2.0)
    mats = [A.copy() for _ in range(4)]
    for i, m in enumerate(mats):
        m.setdiag(m.diagonal() + 0.01 * i)
    rhs = np.random.default_rng(9).standard_normal((4, 32))
    before = int(_metrics.counter("precond.builds", kind="ilu0").value)
    ses = SolveSession("cg", warm_start=False, precond="ilu0")
    ses.precond.sweeps = 2
    ses.precond.tri_sweeps = 2
    ses.solve_many(mats, rhs, tol=1e-8, maxiter=2000)
    snap = plan_cache.snapshot()
    ses.solve_many(mats, rhs, tol=1e-8, maxiter=2000)  # warm flush
    d = plan_cache.delta(snap)
    after = int(_metrics.counter("precond.builds", kind="ilu0").value)
    assert after - before == 1  # ONE symbolic factorization, ever
    assert d["misses"] == 0  # warm flush: program + maps all hit


def test_precond_apply_and_build_events():
    settings.telemetry = True
    A = _vardiag(32, seed=18)
    ses = SolveSession("cg", warm_start=False, precond="jacobi")
    t = ses.submit(A, np.ones(32), tol=1e-8, maxiter=2000)
    ses.flush()
    t.result()
    kinds = [e.get("kind") for e in telemetry.events()]
    assert "precond.apply" in kinds
    builds = [e for e in telemetry.events()
              if e.get("kind") == "precond.build"]
    assert builds and builds[0]["precond"] == "jacobi"
    # schema: both kinds validate
    for e in telemetry.events():
        if e.get("kind", "").startswith("precond."):
            assert not telemetry.schema.validate(e)


def test_requeue_fallback_drops_preconditioner():
    """The session's drop rung: the fallback bucket runs without M
    (its program key carries no .M suffix)."""
    A = _vardiag(32, seed=19)
    _cost.reset()
    # never-converging lane: absurd tol with tiny maxiter forces the
    # requeue into the gmres fallback bucket
    ses = SolveSession("cg", warm_start=False, precond="jacobi",
                       fallback_solver="gmres")
    t = ses.submit(A, np.ones(32), tol=1e-30, maxiter=3)
    ses.flush()
    keys = set(_cost.programs())
    assert "batch.cg.B1.<f8.Mjacobi" in keys
    fb = [k for k in keys if k.startswith("batch.gmres.B1")]
    assert fb and all(".M" not in k for k in fb)


# ---------------------------------------------------------------------------
# vault: artifacts, quarantine, warm restart (single + fleet)
# ---------------------------------------------------------------------------
def test_ilu_symbolic_vault_round_trip_and_quarantine(tmp_path):
    settings.vault = str(tmp_path / "vault")
    A = _spd(24, seed=20)
    S1 = SparsityPattern(A.indptr, A.indices, A.shape)
    sym = pilu.ilu0_symbolic(S1, "ilu0")
    vals = np.asarray(A.data)[None, :]
    F1 = np.asarray(pilu.factorize(sym, vals, sweeps=20))
    # fresh object, same content: in-process miss -> verified disk hit
    snap = plan_cache.snapshot()
    S2 = SparsityPattern(A.indptr, A.indices, A.shape)
    sym2 = pilu.ilu0_symbolic(S2, "ilu0")
    d = plan_cache.delta(snap)
    assert d["disk_hits"] == 1 and d["misses"] == 0
    np.testing.assert_array_equal(
        np.asarray(pilu.factorize(sym2, vals, sweeps=20)), F1
    )
    # corrupted read: quarantine + rebuild to identical factors
    plan_cache.clear()
    vault.reset_stats()
    faults.configure("bitflip:io:p=1,n=1,seed=3")
    try:
        S3 = SparsityPattern(A.indptr, A.indices, A.shape)
        sym3 = pilu.ilu0_symbolic(S3, "ilu0")
    finally:
        faults.clear()
    assert vault.stats()["quarantined"] >= 1
    np.testing.assert_array_equal(
        np.asarray(pilu.factorize(sym3, vals, sweeps=20)), F1
    )


def test_warm_restart_replays_precond_keyed_program(tmp_path):
    settings.vault = str(tmp_path / "vault")
    A = _vardiag(32, seed=21, spread=2.0)
    mats = [A.copy() for _ in range(4)]
    rhs = np.random.default_rng(10).standard_normal((4, 32))
    ses = SolveSession("cg", warm_start=False, precond="bjacobi")
    X, _, _ = ses.solve_many(mats, rhs, tol=1e-9, maxiter=2000)
    ents = vault.manifest_entries()
    assert any(e.get("precond") == "bjacobi" for e in ents)
    # the restart: in-process tier gone, vault retained
    plan_cache.clear()
    ses2 = SolveSession("cg", warm_start=True, warm_async=False,
                        precond="bjacobi")
    assert ses2.warm_replayed >= 1
    snap = plan_cache.snapshot()
    X2, _, _ = ses2.solve_many(mats, rhs, tol=1e-9, maxiter=2000)
    d = plan_cache.delta(snap)
    assert d["misses"] == 0  # zero-build warm serving window
    np.testing.assert_array_equal(X, X2)


def test_fleet_precond_parity_and_mesh_manifest(tmp_path):
    """Batch-sharded preconditioned programs: bit-identical lanes vs
    single-device, and the manifest entry carries BOTH the mesh
    fingerprint and the precond kind (the mesh/fleet warm path)."""
    settings.vault = str(tmp_path / "vault")
    A = _vardiag(48, seed=22, spread=2.0)
    rng = np.random.default_rng(11)
    mats = []
    for _ in range(8):
        m = A.copy()
        m.setdiag(A.diagonal() + 0.1 * rng.random(48))
        m.sort_indices()
        mats.append(m.tocsr())
    rhs = rng.standard_normal((8, 48))
    ses_f = SolveSession("cg", warm_start=False, fleet="batch",
                         precond="bjacobi")
    Xf, itf, _ = ses_f.solve_many(mats, rhs, tol=1e-10, maxiter=2500)
    ses_s = SolveSession("cg", warm_start=False, fleet=False,
                         precond="bjacobi")
    Xs, its, _ = ses_s.solve_many(mats, rhs, tol=1e-10, maxiter=2500)
    np.testing.assert_array_equal(Xf, Xs)  # bit-identical lanes
    assert (itf == its).all()
    ents = vault.manifest_entries()
    mesh_ent = [e for e in ents if e.get("mesh")]
    assert mesh_ent and mesh_ent[-1].get("precond") == "bjacobi"
    # same-topology restart replays the mesh+precond-keyed program
    plan_cache.clear()
    ses3 = SolveSession("cg", warm_start=True, warm_async=False,
                        fleet="batch", precond="bjacobi")
    assert ses3.warm_replayed >= 1
    snap = plan_cache.snapshot()
    X3, _, _ = ses3.solve_many(mats, rhs, tol=1e-10, maxiter=2500)
    assert plan_cache.delta(snap)["misses"] == 0
    np.testing.assert_array_equal(Xf, X3)


# ---------------------------------------------------------------------------
# resilience: the drop-preconditioner rung
# ---------------------------------------------------------------------------
def test_recovery_drops_M_on_nonfinite_m():
    settings.telemetry = True
    A = sparse_tpu.csr_array(_spd(32, seed=23))
    b = np.random.default_rng(12).standard_normal(32)
    faults.configure("nonfinite:precond:p=1")
    try:
        M = precond.make_M(A, "jacobi")
        x, info = solve_with_recovery(A, b, solver="cg", tol=1e-8, M=M)
    finally:
        faults.clear()
    assert info.converged and info.recovered
    evs = list(telemetry.events())
    assert any(e.get("kind") == "fault.injected"
               and e.get("site") == "precond" for e in evs)
    retries = [e for e in evs if e.get("kind") == "solver.retry"]
    assert any(e.get("action") == "drop_precond"
               and e.get("reason") == "nonfinite_m" for e in retries)
    # the rung never spent a solver escalation
    assert info.solver == "cg"


def test_recovery_stagnation_drop_rung_before_escalation():
    settings.telemetry = True
    n = 48
    A = sparse_tpu.csr_array(_spd(n, seed=24))
    b = np.random.default_rng(13).standard_normal(n)
    # a degenerate (finite) M that zeroes every search direction: CG
    # makes NO progress preconditioned, so the ladder must classify
    # stagnation and shed M — the plain re-solve then converges
    def badmv(r):
        import jax.numpy as jnp

        return jnp.zeros_like(r)

    M = linalg.LinearOperator((n, n), matvec=badmv,
                              dtype=np.dtype(np.float64))
    x, info = solve_with_recovery(
        A, b, solver="cg", tol=1e-9, maxiter=40, M=M,
        policy=RecoveryPolicy(max_attempts=6, restart_first=1),
    )
    retries = [e for e in telemetry.events()
               if e.get("kind") == "solver.retry"]
    actions = [e.get("action") for e in retries]
    assert "drop_precond" in actions
    # the drop rung fires BEFORE any solver escalation
    if "escalate" in actions:
        assert actions.index("drop_precond") < actions.index("escalate")


# ---------------------------------------------------------------------------
# multigrid V-cycle as M for the row-shard lane (satellite)
# ---------------------------------------------------------------------------
def _gmg_2d(g):
    """Two-level GMG on the 2-D Poisson grid model: 5-point fine
    operator, bilinear transfer as a 1-D kron."""
    from sparse_tpu.models.poisson import laplacian_2d_csr_host

    a = laplacian_2d_csr_host(g)
    A0 = sp.csr_matrix(
        (np.asarray(a.data), np.asarray(a.indices), np.asarray(a.indptr)),
        shape=a.shape,
    )
    gc = g // 2
    i = np.arange(gc)
    rows = np.concatenate([2 * i, np.maximum(2 * i - 1, 0),
                           np.minimum(2 * i + 1, g - 1)])
    cols = np.concatenate([i, i, i])
    vals = np.concatenate([np.ones(gc), np.full(gc, 0.5),
                           np.full(gc, 0.5)])
    P1 = sp.coo_matrix((vals, (rows, cols)), shape=(g, gc)).tocsr()
    P = sp.kron(P1, P1).tocsr()
    R = (P.T * 0.25).tocsr()
    A1 = (R @ A0 @ P).tocsr()
    return A0, A1, R, P


def test_vcycle_operator_preconditions_dist_cg_on_gmg_grid():
    from sparse_tpu.parallel.dist import dist_cg
    from sparse_tpu.parallel.mesh import get_mesh
    from sparse_tpu.parallel.multigrid import (
        make_dist_vcycle,
        shard_hierarchy,
        vcycle_operator,
    )

    g = 16
    A0, A1, R, P = _gmg_2d(g)
    mesh = get_mesh(4)
    ops, _ = shard_hierarchy(
        [sparse_tpu.csr_array(A0), sparse_tpu.csr_array(A1)],
        [(sparse_tpu.csr_array(R), sparse_tpu.csr_array(P))], mesh,
    )
    weights = []
    for Ad, lvA in ((ops[0][0], A0), (ops[1][0], A1)):
        D = np.asarray(lvA.diagonal())
        weights.append((2.0 / 3.0) / (Ad.pad_out_vector(D - 1.0) + 1.0))
    cycle = make_dist_vcycle(ops, weights,
                             coarse_apply=lambda rp: weights[-1] * rp)
    A0d = ops[0][0]
    Mop = vcycle_operator(cycle, A0d.m_pad, dtype=np.float64)
    b = np.ones(g * g)
    _, it_plain, conv_p = dist_cg(A0d, b, tol=1e-8, maxiter=600,
                                  conv_test_iters=5)
    xp, it_pre, conv_m = dist_cg(A0d, b, tol=1e-8, maxiter=600,
                                 conv_test_iters=5, M=Mop)
    assert conv_p and conv_m
    x = A0d.unpad_vector(xp)
    assert np.linalg.norm(np.asarray(A0 @ x) - b) < 1e-5
    assert it_pre < it_plain  # the LinearOperator form actually helps


def test_row_program_make_M_hook():
    from sparse_tpu.fleet import build_row_program, fleet_mesh
    from sparse_tpu.parallel.multigrid import vcycle_operator

    g = 8
    A0, _, _, _ = _gmg_2d(g)
    pat = SparsityPattern(A0.indptr, A0.indices, A0.shape)
    mesh = fleet_mesh(4)
    made = {}

    def make_M(D):
        # a padded Jacobi smoother through the LinearOperator wrapper —
        # the same promotion path a V-cycle hook uses
        Dw = 1.0 / (D.pad_out_vector(np.asarray(A0.diagonal()) - 1.0) + 1.0)
        made["m_pad"] = D.m_pad
        return vcycle_operator(lambda rp: Dw * rp, D.m_pad)

    run = build_row_program(pat, np.float64, mesh, make_M=make_M)
    b = np.ones(g * g)
    X, iters, resid2, conv = run(
        np.asarray(A0.data)[None, :], b[None, :],
        np.zeros((1, g * g)), np.asarray([1e-9]), 2000,
    )
    assert made["m_pad"] > 0
    assert bool(conv[0])
    assert np.linalg.norm(np.asarray(A0 @ X[0]) - b) < 1e-7
