"""Axon v7 (ISSUE 19): continuous telemetry — time-series history
store, SLO error-budget burn engine, per-tenant usage metering.

Pins the PR's contracts:

* **zero overhead when off** — the default leaves no sampler, touches
  no filesystem, and program keys / jaxprs / host-sync counts are
  byte-identical with the sampler live;
* **segment store** — rotation past the size target, byte-capped GC
  that never evicts the active segment, verify-then-load (alien header
  quarantined, torn tail keeps the valid prefix), and the restart join
  (a later sampler's segments read back joined with a prior one's, in
  time order);
* **downsampling** — the 10x rollup's [min, max, mean, last] matches a
  brute-force oracle over the same raw stream;
* **burn math** — the engine reproduces hand-computed fixtures through
  its injectable count reader and clock, including the min-across-pair
  multi-window read and the idle-tenant omission;
* **usage metering** — tenant-tagged solves and ingest arrivals land in
  the ``usage.*`` families, ``session_stats()['usage']`` and
  ``usage_stats()`` attribute them to the right tenant;
* **satellites** — ingest tickets resolve through the terminal
  ``ingest.ticket`` event + latency histogram; ``axon_dash.py --once``
  renders committed segments stdlib-only; sampler per-scrape cost stays
  under the 2% duty-cycle budget.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest
import scipy.sparse as sp

import sparse_tpu  # noqa: F401 - jax config side effects
from sparse_tpu import telemetry
from sparse_tpu.batch import SolveSession
from sparse_tpu.config import settings
from sparse_tpu.telemetry import _budget, _history, _metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def tel(tmp_path, monkeypatch):
    """Telemetry on with an isolated sink; history singleton isolated."""
    telemetry.reset()
    _history.stop()
    monkeypatch.setattr(settings, "telemetry", True)
    telemetry.configure(str(tmp_path / "records.jsonl"))
    yield tmp_path
    telemetry.configure(None)
    _history.stop()
    telemetry.reset()


def _tridiag(n=48, seed=0):
    rng = np.random.default_rng(seed)
    e = np.ones(n)
    A = sp.diags([-e[:-1], 3.0 * e, -e[:-1]], [-1, 0, 1], format="csr")
    A.setdiag(3.0 + rng.random(n))
    A.sort_indices()
    return A.tocsr()


def _sampler(tmp_path, name="hist", **kw):
    kw.setdefault("interval_s", 1.0)
    kw.setdefault("cap_mb", 1)
    root = str(tmp_path / name)
    os.makedirs(root, exist_ok=True)
    return _history.Sampler(root, **kw), root


# -- zero overhead when off ---------------------------------------------------


def test_off_by_default_no_sampler_no_files(tel, tmp_path, monkeypatch):
    monkeypatch.setattr(settings, "history", "")
    assert not _history.enabled()
    assert _history.maybe_start() is None
    assert _history.current() is None
    ses = SolveSession("cg")  # the serving-path auto-enable hook
    A = _tridiag()
    ses.submit(A, np.ones(A.shape[0]), tol=1e-8)
    ses.drain()
    assert _history.current() is None
    assert _history.state() == {"enabled": False, "running": False}
    assert _history.window() == []


def test_off_is_byte_identical(tel, tmp_path, monkeypatch):
    """The acceptance pin: the sampler live (its own daemon thread, its
    own directory) leaves dispatch programs (jaxpr) and host-sync
    counts exactly as the off path produces them."""
    import jax

    monkeypatch.setattr(settings, "history", "")
    A = _tridiag()
    rhs = np.random.default_rng(3).standard_normal((2, A.shape[0]))

    def jaxpr_and_syncs():
        ses = SolveSession("cg")
        pat = ses.pattern_of(A)
        dt = np.dtype(np.result_type(A.data.dtype, rhs.dtype))
        prog = ses._build_program(pat, 2, dt)
        args = (
            np.zeros((2, pat.nnz), dt), np.zeros((2, A.shape[0]), dt),
            np.zeros((2, A.shape[0]), dt), np.zeros(2), 10,
        )
        import re

        jx = re.sub(r"0x[0-9a-f]+", "0x", str(jax.make_jaxpr(prog)(*args)))
        base = _metrics.counter(
            "telemetry.counts", name="host_sync.int"
        ).value
        ses.solve_many([A, A], rhs, tol=1e-8)
        syncs = _metrics.counter(
            "telemetry.counts", name="host_sync.int"
        ).value - base
        return jx, syncs

    jx_off, syncs_off = jaxpr_and_syncs()
    _history.start(root=str(tmp_path / "hist_on"), interval_s=0.05)
    try:
        jx_on, syncs_on = jaxpr_and_syncs()
    finally:
        _history.stop()
    assert jx_off == jx_on
    assert syncs_off == syncs_on


# -- segment store ------------------------------------------------------------


def test_rotation_and_byte_capped_gc(tel, tmp_path):
    smp, root = _sampler(tmp_path, segment_max_bytes=2048)
    smp.cap_bytes = 8192  # tiny budget so GC must evict
    flat = {f"series.{i}": float(i) for i in range(16)}
    for k in range(200):
        smp.observe(1000.0 + k, dict(flat, tick=float(k)))
    smp.stop()
    segs = sorted(
        f for f in os.listdir(root)
        if f.startswith("seg-") and f.endswith(".jsonl")
    )
    assert smp.rotations >= 2 and len(segs) >= 1
    assert smp.gc_evicted >= 1
    # the active segment survived every GC pass: the newest committed
    # file holds the newest points
    pts = _history.read_segments(root, res=0)
    assert pts and pts[-1]["s"]["tick"] == 199.0
    total = sum(os.path.getsize(os.path.join(root, f)) for f in segs)
    assert total <= smp.cap_bytes + smp.segment_max_bytes


def test_verify_then_load_quarantine_and_torn_tail(tel, tmp_path):
    smp, root = _sampler(tmp_path)
    for k in range(5):
        smp.observe(1000.0 + k, {"a": float(k)})
    smp.stop()
    (seg,) = [f for f in os.listdir(root) if f.startswith("seg-")]
    # torn tail: a half-written trailing line keeps the intact prefix
    with open(os.path.join(root, seg), "a") as f:
        f.write('{"t": 1005.0, "r": 0, "s": {"a"')
    # alien header: quarantined, not parsed, not fatal
    alien = os.path.join(root, "seg-0000000000000-9999.jsonl")
    with open(alien, "w") as f:
        f.write('{"kind": "not-history", "format": 99}\n')
    base_q = _metrics.counter("history.quarantined").value
    pts = _history.read_segments(root, res=0)
    assert [p["s"]["a"] for p in pts] == [0.0, 1.0, 2.0, 3.0, 4.0]
    assert not os.path.exists(alien)
    assert os.path.exists(os.path.join(root, "quarantine",
                                       os.path.basename(alien)))
    assert _metrics.counter("history.quarantined").value == base_q + 1
    assert _metrics.counter("history.truncated").value >= 1


def test_restart_join_across_samplers(tel, tmp_path):
    """A later sampler on the same root reads back joined with the
    prior one's segments, in time order — the cross-restart contract
    ``axon_report --history`` builds on."""
    smp1, root = _sampler(tmp_path)
    for k in range(3):
        smp1.observe(1000.0 + k, {"x": float(k)})
    smp1.stop()
    time.sleep(0.01)  # distinct epoch-ms in the next segment name
    smp2 = _history.Sampler(root, interval_s=1.0, cap_mb=1)
    for k in range(3):
        smp2.observe(2000.0 + k, {"x": 100.0 + k})
    smp2.stop()
    pts = _history.read_segments(root, res=0)
    assert [p["s"]["x"] for p in pts] == [0.0, 1.0, 2.0, 100.0, 101.0,
                                          102.0]
    assert all(p["session"] for p in pts)
    assert [p["t"] for p in pts] == sorted(p["t"] for p in pts)


def test_rollup_matches_brute_force_oracle(tel, tmp_path):
    smp, root = _sampler(tmp_path)  # interval 1.0 -> 10x bucket = 10 s
    rng = np.random.default_rng(7)
    t0 = 10_000.0  # bucket-aligned
    vals = rng.standard_normal(40).round(6)
    for k, v in enumerate(vals):
        smp.observe(t0 + k, {"m": float(v)})
    smp.stop()  # flushes the open buckets
    rolls = {p["t"]: p["s"]["m"]
             for p in _history.read_segments(root, res=10)}
    assert len(rolls) == 4
    for b in range(4):
        chunk = vals[b * 10:(b + 1) * 10]
        got = rolls[t0 + b * 10]
        assert got[0] == pytest.approx(float(chunk.min()))
        assert got[1] == pytest.approx(float(chunk.max()))
        assert got[2] == pytest.approx(float(chunk.mean()), abs=1e-8)
        assert got[3] == pytest.approx(float(chunk[-1]))


def test_sampler_scrape_cost_under_duty_cycle(tel):
    """The <2% overhead acceptance, measured deterministically: one
    scrape of a populated registry must cost well under 2% of the
    default 1 s interval (i.e. < 20 ms)."""
    for i in range(60):
        _metrics.counter("overhead.c", idx=str(i)).inc(i)
        _metrics.histogram("overhead.h", idx=str(i)).observe(0.1 * i)
    flat = _history.flatten(_metrics.snapshot())
    assert len(flat) >= 120
    import tempfile

    root = tempfile.mkdtemp(prefix="hist_cost_")
    smp = _history.Sampler(root, interval_s=1.0, cap_mb=1)
    n = 50
    t0 = time.perf_counter()
    for _ in range(n):
        smp._sample_once()
    per_sample = (time.perf_counter() - t0) / n
    smp.stop()
    assert per_sample < 0.02 * smp.interval_s, (
        f"scrape cost {per_sample * 1e3:.2f} ms exceeds the 2% duty "
        f"cycle of the {smp.interval_s} s interval"
    )


# -- burn math ----------------------------------------------------------------


def test_burn_math_matches_hand_fixtures():
    counts = {"": (0.0, 0.0)}
    eng = _budget.Engine(objective=0.99, read_counts=lambda: dict(counts))
    eng.sample(now=0.0)
    # 100 tickets, 1 miss over 60 s: rate 0.01 == budget rate -> burn 1
    counts[""] = (1.0, 100.0)
    eng.sample(now=60.0)
    assert eng.burn(60.0, now=60.0)[""] == pytest.approx(1.0)
    # all-miss traffic saturates at 1/budget_rate = 100 (the window is
    # kept strictly inside the 60 s sample gap: the base is the newest
    # sample strictly OLDER than the cutoff)
    counts[""] = (11.0, 110.0)
    eng.sample(now=120.0)
    assert eng.burn(59.0, now=120.0)[""] == pytest.approx(100.0)
    # the long window averages both phases: 11 misses / 110 tickets
    assert eng.burn(1e6, now=120.0)[""] == pytest.approx(10.0)
    # clean traffic reads zero burn
    counts[""] = (11.0, 210.0)
    eng.sample(now=180.0)
    assert eng.burn(59.0, now=180.0)[""] == pytest.approx(0.0)


def test_worst_burn_min_across_pair_and_idle_omission():
    counts = {"": (0.0, 0.0), "acme": (0.0, 0.0), "idle": (0.0, 5.0)}
    eng = _budget.Engine(objective=0.99, read_counts=lambda: dict(counts))
    eng.sample(now=0.0)
    # acme burns hot in the short window only; aggregate stays clean
    counts[""] = (10.0, 1000.0)
    counts["acme"] = (10.0, 10.0)
    eng.sample(now=30.0)
    burns = eng.burn(60.0, now=30.0)
    assert "idle" not in burns  # no traffic in window: omitted
    assert burns["acme"] == pytest.approx(100.0)
    worst, who = eng.worst_burn((60.0, 3600.0), now=30.0)
    assert who == "acme" and worst == pytest.approx(100.0)
    # a tenant present in only one of the windows can't page the pair
    assert eng.worst_burn((0.0, 3600.0), now=30.0)[1] != "idle"


def test_burn_rule_fires_and_emits_event(tel):
    counts = {"": (0.0, 0.0)}
    eng = _budget.Engine(objective=0.99, read_counts=lambda: dict(counts))
    rule = _budget.fast_burn_rule(windows=(60.0, 300.0), engine=eng)
    assert rule.name == "slo_fast_burn" and rule.severity == "page"
    eng.sample(now=0.0)
    counts[""] = (50.0, 50.0)  # every ticket missed
    # the rule's own tick takes the second sample (real clock): every
    # window's base falls back to the t=0 priming sample
    v = rule.value()
    assert v == pytest.approx(100.0) and v > rule.trigger
    evs = telemetry.events("budget.burn")
    assert evs and evs[-1]["rule"] == "slo_fast_burn"
    assert evs[-1]["burn"] == pytest.approx(100.0)


# -- usage metering -----------------------------------------------------------


def test_tenant_attribution_solves(tel):
    A = _tridiag()
    b = np.ones(A.shape[0])
    ses = SolveSession("cg", slo_ms=10_000.0)
    ses.submit(A, b, tol=1e-8, tenant="acme")
    ses.submit(A, b, tol=1e-8, tenant="acme")
    ses.submit(A, b, tol=1e-8, tenant="zeta")
    ses.submit(A, b, tol=1e-8)  # untagged -> the '-' bucket
    ses.drain()
    usage = _budget.usage_stats()
    assert usage["acme"]["tickets"] == 2
    assert usage["zeta"]["tickets"] == 1
    assert usage["-"]["tickets"] >= 1
    assert usage["acme"].get("device_ms", 0.0) >= 0.0
    stats = ses.session_stats()
    assert stats["usage"]["acme"]["tickets"] == 2
    # tenant-labeled latency series exist only for tagged tickets
    fam = _metrics.family("batch.ticket_latency")
    tenants = {m.labels.get("tenant") for m in fam}
    assert "acme" in tenants and "zeta" in tenants


def test_ingest_ticket_event_and_metering(tel):
    A = _tridiag(64, seed=5)
    coo = A.tocoo()
    ses = SolveSession("cg")
    try:
        t = ses.ingest(
            (coo.row, coo.col, coo.data, A.shape), wait=True,
            timeout=600.0, tenant="acme",
        )
        assert t.state == "ready"
    finally:
        if ses._onboarder is not None:
            ses._onboarder.close()
    evs = telemetry.events("ingest.ticket")
    assert evs and evs[-1]["state"] == "ready"
    assert evs[-1]["tenant"] == "acme"
    assert evs[-1]["latency_ms"] >= 0.0
    fam = _metrics.family("ingest.ticket_latency")
    assert any(
        m.labels.get("state") == "ready"
        and m.labels.get("tenant") == "acme" and m.count >= 1
        for m in fam
    )
    assert _budget.usage_stats()["acme"]["ingest"] >= 1


# -- tooling ------------------------------------------------------------------


def test_axon_dash_once_renders_segments(tel, tmp_path):
    smp, root = _sampler(tmp_path)
    for k in range(12):
        smp.observe(1000.0 + k, {"batch.dispatches": float(k),
                                 "usage.tickets{tenant=a}": float(k)})
    smp.stop()
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "axon_dash.py"),
         "--once", "--root", root, "--window", "1e9"],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    assert "batch.dispatches" in out.stdout
    assert "last=11" in out.stdout


def test_axon_report_history_joins_segments(tel, tmp_path):
    smp, root = _sampler(tmp_path)
    for k in range(10):
        smp.observe(1000.0 + k, {"batch.slo_misses": float(k // 5),
                                 "batch.dispatches": float(k)})
    smp.stop()
    out_json = str(tmp_path / "history_summary.json")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "axon_report.py"),
         "--history", root, "--json", out_json],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    assert "incident window" in out.stdout
    with open(out_json) as f:
        h = json.load(f)
    assert h["points"] >= 10
    assert h.get("incident", {}).get("misses", 0) >= 1
