"""csgraph oracle tests vs scipy.sparse.csgraph (beyond the reference —
it has no graph module; this generalizes its tropical-SpMV MIS design
into the full scipy.sparse.csgraph relaxation surface)."""

import numpy as np
import pytest
import scipy.sparse as sp
import scipy.sparse.csgraph as scs

import sparse_tpu as sparse
from sparse_tpu import csgraph as cg


def _rand_graph(n=25, density=0.2, seed=0, directed=True, negative=False):
    rng = np.random.default_rng(seed)
    G = sp.random(n, n, density, random_state=rng, format="csr")
    G.setdiag(0)
    G.eliminate_zeros()
    G.data = rng.uniform(0.5, 2.0, G.nnz)
    if negative:
        G.data[rng.random(G.nnz) < 0.2] *= -0.2
    if not directed:
        G = G.maximum(G.T)
    return G


def _validate_pred(dist, pred, G, src, directed):
    """Predecessor arrays need not match scipy's tie choice; check they
    encode genuine shortest paths."""
    D = G.toarray()
    if not directed:
        D = np.where((D > 0) & ((D < D.T) | (D.T == 0)), D, D.T)
    n = D.shape[0]
    for v in range(n):
        p = pred[v]
        if v == src:
            assert p == -9999
        elif np.isfinite(dist[v]):
            assert p >= 0
            w = D[p, v]
            assert w != 0
            assert np.isclose(dist[p] + w, dist[v], atol=1e-5)


@pytest.mark.parametrize("directed", [True, False])
def test_bellman_ford_matches_scipy(directed):
    G = _rand_graph(directed=directed)
    A = sparse.csr_array(G)
    d = cg.bellman_ford(A, directed=directed)
    d_sci = scs.bellman_ford(G, directed=directed)
    np.testing.assert_allclose(d, d_sci, atol=1e-5)


def test_bellman_ford_negative_edges_and_cycle():
    # seed 7: negative edges present but no negative cycle (scipy-checked)
    G = _rand_graph(seed=7, negative=True)
    d = cg.bellman_ford(sparse.csr_array(G), directed=True)
    d_sci = scs.bellman_ford(G, directed=True)
    np.testing.assert_allclose(d, d_sci, atol=1e-5)
    # a genuine negative cycle raises
    C = sp.csr_matrix(np.array([[0, 1.0, 0], [0, 0, 1.0], [-3.0, 0, 0]]))
    with pytest.raises(cg.NegativeCycleError):
        cg.bellman_ford(sparse.csr_array(C), directed=True)


def test_dijkstra_and_predecessors():
    G = _rand_graph(seed=2)
    A = sparse.csr_array(G)
    d, p = cg.dijkstra(A, indices=0, return_predecessors=True)
    d_sci = scs.dijkstra(G, indices=0)
    np.testing.assert_allclose(d, d_sci, atol=1e-5)
    _validate_pred(d, p, G, 0, directed=True)
    with pytest.raises(ValueError):
        cg.dijkstra(sparse.csr_array(
            sp.csr_matrix(np.array([[0, -1.0], [0, 0]]))
        ))


def test_floyd_warshall_matches_scipy():
    G = _rand_graph(n=18, seed=3)
    D = cg.floyd_warshall(sparse.csr_array(G))
    D_sci = scs.floyd_warshall(G.toarray())
    np.testing.assert_allclose(D, D_sci, atol=1e-5)


def test_shortest_path_dispatch():
    G = _rand_graph(n=15, seed=4)
    A = sparse.csr_array(G)
    for method in ("auto", "FW", "BF", "D", "J"):
        D = cg.shortest_path(A, method=method)
        D_sci = scs.shortest_path(G, method="FW")
        np.testing.assert_allclose(D, D_sci, atol=1e-5)
    d0 = cg.shortest_path(A, indices=0)
    np.testing.assert_allclose(d0, scs.shortest_path(G, indices=0)[0]
                               if scs.shortest_path(G, indices=0).ndim == 2
                               else scs.shortest_path(G, indices=0),
                               atol=1e-5)


@pytest.mark.parametrize("directed", [True, False])
def test_connected_components(directed):
    rng = np.random.default_rng(5)
    blocks = [sp.random(6, 6, 0.6, random_state=rng) + sp.identity(6)
              for _ in range(3)]
    G = sp.block_diag(blocks, format="csr")
    n, labels = cg.connected_components(
        sparse.csr_array(G), directed=directed, connection="weak"
    )
    n_sci, lab_sci = scs.connected_components(G, directed=directed,
                                              connection="weak")
    assert n == n_sci
    # same partition up to relabeling
    for a in range(n):
        members = labels == a
        assert len(np.unique(lab_sci[members])) == 1


def test_breadth_first_order_levels_and_tree():
    G = _rand_graph(n=20, seed=6, directed=False)
    A = sparse.csr_array(G)
    nodes, pred = cg.breadth_first_order(A, 0, directed=False)
    nodes_sci = scs.breadth_first_order(G, 0, directed=False,
                                        return_predecessors=False)
    assert set(np.asarray(nodes).tolist()) == set(nodes_sci.tolist())
    # hop distance of each node's predecessor is one less
    d = cg.bellman_ford(A, directed=False, indices=0, unweighted=True)
    for v in nodes[1:]:
        assert d[pred[v]] == d[v] - 1
    T = cg.breadth_first_tree(A, 0, directed=False)
    assert T.nnz == len(nodes) - 1


def test_depth_first_order_matches_scipy():
    G = _rand_graph(n=15, seed=7, directed=False)
    nodes, pred = cg.depth_first_order(sparse.csr_array(G), 0,
                                       directed=False)
    nodes_sci = scs.depth_first_order(G, 0, directed=False,
                                      return_predecessors=False)
    assert set(nodes.tolist()) == set(nodes_sci.tolist())
    assert nodes[0] == 0


def test_minimum_spanning_tree_weight_matches_scipy():
    G = _rand_graph(n=20, seed=8, directed=False)
    T = cg.minimum_spanning_tree(sparse.csr_array(G))
    T_sci = scs.minimum_spanning_tree(G)
    assert np.isclose(np.asarray(T.todense()).sum(), T_sci.toarray().sum(),
                      atol=1e-6)


def test_reverse_cuthill_mckee_reduces_bandwidth():
    rng = np.random.default_rng(9)
    P = rng.permutation(30)
    band = sp.diags([np.ones(29), np.ones(30), np.ones(29)], [-1, 0, 1],
                    format="csr")
    scrambled = band[P][:, P].tocsr()
    perm = cg.reverse_cuthill_mckee(sparse.csr_array(scrambled))
    R = scrambled[perm][:, perm].tocoo()
    bw = np.abs(R.row - R.col).max()
    orig = np.abs(scrambled.tocoo().row - scrambled.tocoo().col).max()
    assert bw <= 2 and bw < orig


def test_structural_rank_and_laplacian():
    G = _rand_graph(n=12, seed=10)
    assert cg.structural_rank(sparse.csr_array(G)) == scs.structural_rank(G)
    A = sparse.csr_array(_rand_graph(n=10, seed=11, directed=False))
    L = cg.laplacian(A)
    L_sci = scs.laplacian(_rand_graph(n=10, seed=11, directed=False))
    np.testing.assert_allclose(np.asarray(L.todense()), L_sci.toarray(),
                               atol=1e-6)
    Ln, d = cg.laplacian(A, normed=True, return_diag=True)
    Ln_sci, d_sci = scs.laplacian(
        _rand_graph(n=10, seed=11, directed=False), normed=True,
        return_diag=True,
    )
    np.testing.assert_allclose(np.asarray(Ln.todense()), Ln_sci.toarray(),
                               atol=1e-6)
    np.testing.assert_allclose(d, d_sci, atol=1e-6)


def test_dense_round_trip():
    D = np.array([[0, 1.5, 0], [0, 0, 2.0], [np.nan, 0, 0]])
    A = cg.csgraph_from_dense(D)
    assert A.nnz == 2
    out = cg.csgraph_to_dense(A, null_value=-1)
    assert out[0, 1] == 1.5 and out[1, 2] == 2.0 and out[0, 0] == -1


def test_maximum_bipartite_matching():
    G = _rand_graph(n=15, seed=12)
    ours = cg.maximum_bipartite_matching(sparse.csr_array(G), perm_type="row")
    sci = scs.maximum_bipartite_matching(G.astype(bool).astype(float),
                                         perm_type="row")
    # matchings may differ; cardinality must agree
    assert (ours >= 0).sum() == (sci >= 0).sum()
    colm = cg.maximum_bipartite_matching(sparse.csr_array(G),
                                         perm_type="column")
    assert (colm >= 0).sum() == (ours >= 0).sum()


def test_construct_dist_matrix_round_trip():
    G = _rand_graph(n=12, seed=13)
    A = sparse.csr_array(G)
    D, P = cg.floyd_warshall(A, return_predecessors=True)
    D2 = cg.construct_dist_matrix(A, P)
    np.testing.assert_allclose(D2, D, atol=1e-5)


def test_masked_round_trip():
    D = np.array([[0, 2.0], [np.inf, 0]])
    M = cg.csgraph_masked_from_dense(D)
    assert M.mask[0, 0] and M.mask[1, 0] and not M.mask[0, 1]
    A = cg.csgraph_from_masked(M)
    assert A.nnz == 1
    back = cg.csgraph_to_masked(A)
    assert back[0, 1] == 2.0 and back.mask[0, 0]


def test_dijkstra_min_only_scalar_and_sources():
    G = _rand_graph(n=14, seed=14)
    A = sparse.csr_array(G)
    # scalar index + min_only must still return length-n arrays
    d = cg.dijkstra(A, indices=0, min_only=True)
    assert d.shape == (14,)
    d, p, s = cg.dijkstra(A, indices=[0, 3], min_only=True,
                          return_predecessors=True)
    d_sci, p_sci, s_sci = scs.dijkstra(G, indices=[0, 3], min_only=True,
                                       return_predecessors=True)
    np.testing.assert_allclose(d, d_sci, atol=1e-5)
    np.testing.assert_array_equal(np.isin(s, [0, 3, -9999]),
                                  np.isin(s_sci, [0, 3, -9999]))


def test_laplacian_form_not_implemented():
    A = sparse.csr_array(_rand_graph(n=6, seed=15, directed=False))
    with pytest.raises(NotImplementedError):
        cg.laplacian(A, form="lo")


def test_dijkstra_unweighted_ignores_negative_and_limit_preds():
    C = sp.csr_matrix(np.array([[0, -1.0, 0], [0, 0, 2.0], [0, 0, 0]]))
    A = sparse.csr_array(C)
    d = cg.dijkstra(A, indices=0, unweighted=True)
    np.testing.assert_allclose(d, [0, 1, 2])
    G = _rand_graph(n=12, seed=16)
    d, p = cg.dijkstra(sparse.csr_array(G), indices=0, limit=2.0,
                       return_predecessors=True)
    assert np.all(p[~np.isfinite(d)] == -9999)  # no stale pruned paths


def test_csgraph_accepts_array_like():
    D = [[0, 1.0, 0], [0, 0, 1.0], [0, 0, 0]]
    d = cg.dijkstra(D, indices=0)
    np.testing.assert_allclose(d, [0, 1, 2])
    T = cg.breadth_first_tree(D, 0)
    assert T.nnz == 2
    L = cg.laplacian(sp.csr_matrix(np.array([[0, 1.0], [1.0, 0]])))
    np.testing.assert_allclose(np.asarray(L.todense()),
                               [[1, -1], [-1, 1]])


@pytest.mark.parametrize("directed", [True, False])
@pytest.mark.parametrize("K", [1, 3, 8])
def test_yen_matches_scipy(directed, K):
    G = _rand_graph(n=14, density=0.3, seed=3, directed=directed)
    want = scs.yen(G, 0, 9, K, directed=directed)
    got = cg.yen(sparse.csr_array(G), 0, 9, K, directed=directed)
    assert got.shape == want.shape
    np.testing.assert_allclose(np.sort(got), np.sort(want), atol=1e-10)


def test_yen_predecessors_encode_real_paths():
    G = _rand_graph(n=12, density=0.35, seed=5)
    D = G.toarray()
    costs, preds = cg.yen(sparse.csr_array(G), 0, 7, 4,
                          return_predecessors=True)
    assert preds.shape[0] == costs.shape[0]
    seen = set()
    for k in range(len(costs)):
        # walk each path back from the sink; its edge-weight sum must
        # equal the reported cost and the path must be loopless+unique
        path, cur = [7], 7
        while cur != 0:
            cur = int(preds[k, cur])
            assert cur >= 0
            path.append(cur)
        path = path[::-1]
        assert len(set(path)) == len(path)
        assert tuple(path) not in seen
        seen.add(tuple(path))
        total = sum(D[path[j], path[j + 1]] for j in range(len(path) - 1))
        np.testing.assert_allclose(total, costs[k], atol=1e-10)


def test_yen_no_path_and_negative():
    G = sp.csr_matrix(np.array([[0.0, 1, 0], [0, 0, 0], [0, 0, 0]]))
    assert cg.yen(sparse.csr_array(G), 2, 0, 3).shape == (0,)
    Gn = sp.csr_matrix(np.array([[0.0, -1], [0, 0]]))
    with pytest.raises(ValueError):
        cg.yen(sparse.csr_array(Gn), 0, 1, 1)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_maximum_flow_matches_scipy(seed):
    rng = np.random.default_rng(seed)
    n = 12
    G = sp.random(n, n, 0.3, random_state=rng, format="csr")
    G.setdiag(0)
    G.eliminate_zeros()
    G.data = rng.integers(1, 20, G.nnz).astype(np.int32)
    G = sp.csr_matrix(G)
    want = scs.maximum_flow(G, 0, n - 1)
    got = cg.maximum_flow(sparse.csr_array(np.asarray(G.toarray())), 0, n - 1)
    assert got.flow_value == want.flow_value
    F = got.flow.toarray().astype(np.int64)
    # antisymmetric net flows, capacity-feasible, conservation at
    # interior vertices, and the source's net outflow equals the value
    assert np.array_equal(F, -F.T)
    assert np.all(F <= G.toarray())
    net = F.sum(axis=1)
    assert got.flow_value == net[0] == -net[n - 1]
    assert np.all(net[1:-1] == 0)


def test_maximum_flow_validation():
    G = sparse.csr_array(np.array([[0.0, 2.5], [0, 0]]))
    with pytest.raises(ValueError):
        cg.maximum_flow(G, 0, 1)  # non-integer dtype
    Gi = sparse.csr_array(np.array([[0, 2], [0, 0]], dtype=np.int32))
    with pytest.raises(ValueError):
        cg.maximum_flow(Gi, 0, 0)  # source == sink
    r = cg.maximum_flow(Gi, 0, 1)
    assert r.flow_value == 2 and "2" in repr(r)


@pytest.mark.parametrize("maximize", [False, True])
@pytest.mark.parametrize("shape", [(8, 8), (6, 10), (10, 6)])
def test_min_weight_full_bipartite_matching(shape, maximize):
    rng = np.random.default_rng(hash(shape) % 2**32)
    m, n = shape
    # dense enough that a full matching almost surely exists
    B = sp.random(m, n, 0.7, random_state=rng, format="csr")
    B.data = rng.uniform(-3.0, 5.0, B.nnz)
    try:
        wr, wc = scs.min_weight_full_bipartite_matching(B, maximize=maximize)
    except ValueError:
        with pytest.raises(ValueError):
            cg.min_weight_full_bipartite_matching(
                sparse.csr_array(B), maximize=maximize)
        return
    gr, gc = cg.min_weight_full_bipartite_matching(
        sparse.csr_array(B), maximize=maximize)
    D = B.toarray()
    np.testing.assert_allclose(D[gr, gc].sum(), D[wr, wc].sum(), atol=1e-9)
    assert len(set(gr.tolist())) == len(gr)
    assert len(set(gc.tolist())) == len(gc)


def test_min_weight_matching_infeasible_and_types():
    with pytest.raises(TypeError):
        cg.min_weight_full_bipartite_matching(np.ones((3, 3)))
    # an isolated row can never be matched
    B = sp.csr_matrix(np.array([[1.0, 0], [0, 0]]))
    B.eliminate_zeros()
    with pytest.raises(ValueError):
        cg.min_weight_full_bipartite_matching(sparse.csr_array(B))


def test_linalg_legacy_namespaces():
    from sparse_tpu import linalg as tl

    assert tl.isolve.cg is tl.cg
    assert tl.dsolve.spsolve is tl.spsolve
    assert tl.eigen.eigsh is tl.eigsh
    assert tl.interface.LinearOperator is tl.LinearOperator
    assert tl.matfuncs.expm is tl.expm


def test_linalg_legacy_from_import():
    # the scipy-style from-import form must resolve too
    from sparse_tpu.linalg.isolve import cg as cg_fn
    from sparse_tpu import linalg as tl

    assert cg_fn is tl.cg


def test_dijkstra_high_diameter_fallback():
    """Path graph (hop diameter = n): must complete fast via the host
    heap fallback, matching scipy (VERDICT r3 #8)."""
    import time

    import scipy.sparse as sp
    from scipy.sparse.csgraph import dijkstra as scipy_dijkstra

    n = 20_000
    G = sp.diags([np.ones(n - 1)], [1], format="csr")
    A = sparse.csr_array(G)
    t0 = time.perf_counter()
    with pytest.warns(UserWarning, match="host binary-heap"):
        d = cg.dijkstra(A, indices=0, directed=True)
    assert time.perf_counter() - t0 < 30
    np.testing.assert_allclose(d, scipy_dijkstra(G, indices=0))


def test_dijkstra_low_diameter_stays_on_device():
    """Mesh-like graph: converges within the sweep bound, no fallback
    warning, distances match scipy."""
    import warnings

    import scipy.sparse as sp
    from scipy.sparse.csgraph import dijkstra as scipy_dijkstra

    g = sp.diags([np.ones(19), np.ones(19)], [1, -1])
    G = (sp.kronsum(g, g) * 0.5).tocsr()
    A = sparse.csr_array(G)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        d = cg.dijkstra(A, indices=3)
    np.testing.assert_allclose(d, scipy_dijkstra(G, indices=3))
