"""Layout-construction performance discipline: no per-row host loops.

VERDICT r1 #4: sharding a 1M-row Laplacian must be vectorized
(searchsorted + scatter) — seconds, not minutes. The wall-clock bound here
is deliberately loose (CI machines vary); the real guard is the scaling
assert: 4x the rows must cost < 20x the time (a per-row-Python-loop
implementation fails that by orders of magnitude).
"""

import time

import numpy as np

from sparse_tpu.models.poisson import laplacian_2d_csr_host
from sparse_tpu.parallel.dist import shard_csr
from sparse_tpu.parallel.mesh import get_mesh


def _time_shard(A, mesh):
    t0 = time.perf_counter()
    D = shard_csr(A, mesh=mesh, balanced=True)
    dt = time.perf_counter() - t0
    return D, dt


def test_shard_csr_1m_rows_vectorized():
    mesh = get_mesh(8)
    small = laplacian_2d_csr_host(500, dtype=np.float32)  # 250k rows
    big = laplacian_2d_csr_host(1000, dtype=np.float32)  # 1M rows
    _time_shard(small, mesh)  # warm jax dispatch paths
    _, dt_small = _time_shard(small, mesh)
    D, dt_big = _time_shard(big, mesh)
    _, dt_big2 = _time_shard(big, mesh)
    dt_big = min(dt_big, dt_big2)  # shield against suite-wide memory churn
    assert D.m_pad >= 1_000_000
    assert dt_big < 10.0, f"1M-row shard_csr took {dt_big:.2f}s"
    # loose scaling guard: a per-row Python loop is ~1000x off, while
    # allocator effects (the 4x-larger arrays are mmap'd fresh each call,
    # the small ones recycled) can legitimately cost tens of x
    assert dt_big < 100 * max(dt_small, 0.05), (
        f"superlinear layout construction: {dt_small:.3f}s -> {dt_big:.3f}s"
    )
    # spot-check the layout is correct at this scale: one SpMV vs host
    import scipy.sparse as sp

    x = np.random.default_rng(0).standard_normal(big.shape[0]).astype(np.float32)
    y = D.dot(x)
    oracle = sp.csr_matrix(
        (np.asarray(big.data), np.asarray(big.indices), np.asarray(big.indptr)),
        shape=big.shape,
    )
    assert np.allclose(y, oracle @ x, atol=1e-3)
