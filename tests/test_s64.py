"""S=64 virtual-mesh validation (VERDICT r2 #2/#4).

The conftest pins this process to an 8-device CPU mesh (XLA's device count
is fixed at backend init), so each S=64 scenario runs its payload in a
SUBPROCESS with its own ``--xla_force_host_platform_device_count=64``.
Mirrors the reference's CI strategy of re-running the same code under many
resource shapes (``/root/reference/.github/workflows/ci.yml:73-80``) —
scaled up to the mesh size the distributed design actually targets.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_payload(code: str, ndev: int = 64, timeout: int = 1200) -> dict:
    """Run ``code`` under an ndev-device CPU mesh; parse its last JSON line."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={ndev}"
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, (
        f"payload rc={proc.returncode}\n--- stderr ---\n{proc.stderr[-4000:]}"
    )
    return json.loads(proc.stdout.strip().splitlines()[-1])


GALERKIN_PAYLOAD = r"""
import json
import numpy as np
import scipy.sparse as sp
import sparse_tpu
from sparse_tpu.models.poisson import laplacian_2d_csr_host
from sparse_tpu.parallel import dist_spgemm
from sparse_tpu.parallel.mesh import get_mesh
from sparse_tpu.parallel import spgemm as dspg

grid = 1024
N = grid * grid
A = laplacian_2d_csr_host(grid)  # 1024^2 Poisson, ~5.2M nnz
# pair-aggregation prolongator: coarse id = fine id // 2
P = sparse_tpu.csr_array.from_parts(
    np.ones(N), (np.arange(N) // 2).astype(np.int64),
    np.arange(N + 1, dtype=np.int64), (N, N // 2),
)
R = P.T.tocsr()
mesh = get_mesh(64)
stats = {}
AP = dist_spgemm(A, P, mesh=mesh)
stats["AP"] = dict(dspg.LAST_STATS)
RAP = dist_spgemm(R, AP, mesh=mesh)
stats["RAP"] = dict(dspg.LAST_STATS)

# correctness vs scipy on the full-size sparse result
As = sp.csr_matrix(
    (np.asarray(A.data), np.asarray(A.indices), np.asarray(A.indptr)), (N, N)
)
Ps = sp.csr_matrix(
    (np.asarray(P.data), np.asarray(P.indices), np.asarray(P.indptr)),
    (N, N // 2),
)
ref = (Ps.T @ As @ Ps).tocsr()
ref.sum_duplicates()
ref.sort_indices()
got = sp.csr_matrix(
    (np.asarray(RAP.data), np.asarray(RAP.indices), np.asarray(RAP.indptr)),
    RAP.shape,
)
got.sum_duplicates()
got.sort_indices()
ok = (
    got.shape == ref.shape
    and np.array_equal(got.indptr, ref.indptr)
    and np.array_equal(got.indices, ref.indices)
    and np.allclose(got.data, ref.data)
)
print(json.dumps({"ok": bool(ok), "stats": stats}))
"""


@pytest.mark.slow
def test_s64_galerkin_image_memory():
    """64-shard Galerkin R@A@P on the 1024^2 Poisson: correct vs scipy AND
    per-device B memory < 2*nnz(B)/S — the image gather keeps per-chip
    footprint ∝ nnz/S, never ∝ nnz (reference image partition,
    csr.py:1447-1465)."""
    rec = run_payload(GALERKIN_PAYLOAD)
    assert rec["ok"], "distributed Galerkin product diverged from scipy"
    for name, st in rec["stats"].items():
        per_dev_entries = st["bnnz_pad"]
        bound = 2 * st["nnz_B"] / st["S"]
        assert per_dev_entries < bound, (
            f"{name}: per-device B entries {per_dev_entries} >= "
            f"2*nnz(B)/S = {bound} (S={st['S']}, nnz_B={st['nnz_B']})"
        )
