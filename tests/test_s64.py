"""S=64 virtual-mesh validation (VERDICT r2 #2/#4).

The conftest pins this process to an 8-device CPU mesh (XLA's device count
is fixed at backend init), so each S=64 scenario runs its payload in a
SUBPROCESS with its own ``--xla_force_host_platform_device_count=64``.
Mirrors the reference's CI strategy of re-running the same code under many
resource shapes (``/root/reference/.github/workflows/ci.yml:73-80``) —
scaled up to the mesh size the distributed design actually targets.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_payload(code: str, ndev: int = 64, timeout: int = 1200) -> dict:
    """Run ``code`` under an ndev-device CPU mesh; parse its last JSON line."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={ndev}"
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, (
        f"payload rc={proc.returncode}\n--- stderr ---\n{proc.stderr[-4000:]}"
    )
    return json.loads(proc.stdout.strip().splitlines()[-1])


GALERKIN_PAYLOAD = r"""
import json
import numpy as np
import scipy.sparse as sp
import sparse_tpu
from sparse_tpu.models.poisson import laplacian_2d_csr_host
from sparse_tpu.parallel import dist_spgemm
from sparse_tpu.parallel.mesh import get_mesh
from sparse_tpu.parallel import spgemm as dspg

grid = 1024
N = grid * grid
A = laplacian_2d_csr_host(grid)  # 1024^2 Poisson, ~5.2M nnz
# pair-aggregation prolongator: coarse id = fine id // 2
P = sparse_tpu.csr_array.from_parts(
    np.ones(N), (np.arange(N) // 2).astype(np.int64),
    np.arange(N + 1, dtype=np.int64), (N, N // 2),
)
R = P.T.tocsr()
mesh = get_mesh(64)
stats = {}
AP = dist_spgemm(A, P, mesh=mesh)
stats["AP"] = dict(dspg.LAST_STATS)
RAP = dist_spgemm(R, AP, mesh=mesh)
stats["RAP"] = dict(dspg.LAST_STATS)

# correctness vs scipy on the full-size sparse result
As = sp.csr_matrix(
    (np.asarray(A.data), np.asarray(A.indices), np.asarray(A.indptr)), (N, N)
)
Ps = sp.csr_matrix(
    (np.asarray(P.data), np.asarray(P.indices), np.asarray(P.indptr)),
    (N, N // 2),
)
ref = (Ps.T @ As @ Ps).tocsr()
ref.sum_duplicates()
ref.sort_indices()
got = sp.csr_matrix(
    (np.asarray(RAP.data), np.asarray(RAP.indices), np.asarray(RAP.indptr)),
    RAP.shape,
)
got.sum_duplicates()
got.sort_indices()
ok = (
    got.shape == ref.shape
    and np.array_equal(got.indptr, ref.indptr)
    and np.array_equal(got.indices, ref.indices)
    and np.allclose(got.data, ref.data)
)
print(json.dumps({"ok": bool(ok), "stats": stats}))
"""


@pytest.mark.slow
def test_s64_galerkin_image_memory():
    """64-shard Galerkin R@A@P on the 1024^2 Poisson: correct vs scipy AND
    per-device B memory < 2*nnz(B)/S — the image gather keeps per-chip
    footprint ∝ nnz/S, never ∝ nnz (reference image partition,
    csr.py:1447-1465)."""
    rec = run_payload(GALERKIN_PAYLOAD)
    assert rec["ok"], "distributed Galerkin product diverged from scipy"
    for name, st in rec["stats"].items():
        per_dev_entries = st["bnnz_pad"]
        bound = 2 * st["nnz_B"] / st["S"]
        assert per_dev_entries < bound, (
            f"{name}: per-device B entries {per_dev_entries} >= "
            f"2*nnz(B)/S = {bound} (S={st['S']}, nnz_B={st['nnz_B']})"
        )


DRYRUN_PAYLOAD = r"""
import json
import __graft_entry__ as g
g.dryrun_multichip(64)
print(json.dumps({"ok": True}))
"""


@pytest.mark.slow
def test_s64_dryrun_multichip():
    """The driver's full multi-chip dryrun (dist CG with halo exchange,
    col-split SpMV, k-split rSpMM, mesh SpGEMM, 2-level V-cycle) compiles
    and executes at S=64, not just the 8-device default."""
    rec = run_payload(DRYRUN_PAYLOAD)
    assert rec["ok"]


HALO_PAYLOAD = r"""
import json
import numpy as np
from sparse_tpu.models.poisson import laplacian_2d_csr_host
from sparse_tpu.parallel.dist import comm_stats, dist_cg, shard_csr
from sparse_tpu.parallel.mesh import get_mesh

grid = 320  # N = 102400 rows, n/S = 1600, band = 320
A = laplacian_2d_csr_host(grid, dtype=np.float32)
D = shard_csr(A, mesh=get_mesh(64), balanced=True)
st = comm_stats(D)
# the halo-SpMV CG actually runs at this width
rng = np.random.default_rng(0)
xp, iters, _ = dist_cg(D, rng.standard_normal(A.shape[0]).astype(np.float32),
                       tol=1e-3, maxiter=8, conv_test_iters=4)
ok = bool(np.all(np.isfinite(np.asarray(xp))))
print(json.dumps({"ok": ok, "stats": st, "band": grid,
                  "rows_per_shard": A.shape[0] // st["S"]}))
"""


@pytest.mark.slow
def test_s64_halo_tracks_band_not_rows():
    """At S=64 the x halo stays proportional to the matrix BAND, not to
    n/S — the MinMaxImage locality property (reference partition.py:139-214)
    that makes weak scaling possible. comm_stats records the
    per-CG-iteration collective bytes so regressions are visible without
    hardware."""
    rec = run_payload(HALO_PAYLOAD)
    assert rec["ok"]
    st = rec["stats"]
    band = rec["band"]
    assert st["mode"] == "halo", "banded operator must keep the halo path"
    # HL+HR covers both sides: 2*band plus bounded split drift, and far
    # below the per-shard row count (the replication-avoidance criterion)
    assert st["halo_entries_per_spmv"] <= 3 * band
    assert st["halo_entries_per_spmv"] < rec["rows_per_shard"]
    assert st["cg_iter_collective_bytes_per_shard"] < 4 * 3 * band + 64


@pytest.mark.slow
def test_s64_amg_full_hierarchy():
    """The FULL AMG pipeline at S=64 (VERDICT r3 #2): device-MIS
    aggregation hierarchy with >=4 levels, sharded fine levels, replicated
    tail crossover, V-cycle-preconditioned dist CG — converges, and the
    fine level keeps halo-bounded per-iteration collectives (comm
    accounting parsed from the example's disclosure lines)."""
    import re

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=64"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "amg.py"),
         "-n", "128", "-dist", "-maxiter", "60"],
        capture_output=True,
        text=True,
        timeout=1500,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    out = proc.stdout
    m = re.search(r"levels: (\d+)\s+sizes: \[([0-9, ]+)\]", out)
    assert m, out
    sizes = [int(v) for v in m.group(2).split(",")]
    assert len(sizes) >= 4 and sizes[0] == 128 * 128
    m = re.search(r"dist tail crossover: level (\d+) of (\d+)", out)
    assert m, out
    c, L = int(m.group(1)), int(m.group(2))
    assert 0 < c < L, "hierarchy must split into sharded levels + tail"
    m = re.search(r"dist comm stats: (\{.*\})", out)
    assert m, out
    st = json.loads(m.group(1))
    assert st["S"] == 64
    # per-iteration collective volume bounded by the (unstructured) fine
    # operator's halo, far below the all-gather footprint n/S * (S-1)
    n_over_s = sizes[0] // 64
    if st["mode"] == "halo":
        assert st["halo_entries_per_spmv"] < 4 * n_over_s
    m = re.search(r"Iterations: (\d+)\s+residual: ([0-9.e+-]+)", out)
    assert m, out
    iters, resid = int(m.group(1)), float(m.group(2))
    assert resid < 1e-6
    assert 0 < iters < 60
