"""Hardware-evidence log (bench.py results/axon pipeline, VERDICT r3 #4).

The reference ships verbatim machine output under results/summit/; here the
analogous artifacts are results/axon/records.jsonl (machine-readable) plus
*.out files (verbatim example stdout). These tests pin the record round-trip
and the freshest-TPU-record selection that backs the session-log fallback.
"""

import json

import bench


def _redirect(monkeypatch, tmp_path):
    monkeypatch.setattr(bench, "RESULTS_DIR", str(tmp_path))
    monkeypatch.setattr(bench, "RECORDS_PATH", str(tmp_path / "records.jsonl"))


def test_log_and_freshest_roundtrip(tmp_path, monkeypatch):
    _redirect(monkeypatch, tmp_path)
    assert bench._freshest_session_record() is None
    bench._log_hw_record(
        {"metric": "cg_iters_per_s_pde6000_tpu_fused", "value": 210.0}
    )
    rec = bench._freshest_session_record()
    assert rec is not None
    assert rec["value"] == 210.0
    assert isinstance(rec["ts"], float) and "iso" in rec


def test_freshest_picks_newest_tpu_line(tmp_path, monkeypatch):
    _redirect(monkeypatch, tmp_path)
    with open(bench.RECORDS_PATH, "w") as f:
        # cpu lines and malformed lines must be skipped, newest ts wins
        f.write(json.dumps({"metric": "cg_iters_per_s_pde512_cpu",
                            "value": 574.0, "ts": 9e9}) + "\n")
        f.write("not json\n")
        f.write(json.dumps({"metric": "cg_iters_per_s_pde6000_tpu_fused",
                            "value": 200.0, "ts": 100.0}) + "\n")
        f.write(json.dumps({"metric": "cg_iters_per_s_pde6000_tpu_fused",
                            "value": 214.0, "ts": 200.0}) + "\n")
    rec = bench._freshest_session_record()
    assert rec["value"] == 214.0 and rec["ts"] == 200.0


def test_session_record_embeds_plan_cache_stats(tmp_path, monkeypatch):
    """Every bench.session record carries the always-on plan-cache
    counters (ISSUE 3 satellite): rounds attribute cache behavior —
    prepare reuse, batched-bucket compiles — without a separate probe."""
    import time

    _redirect(monkeypatch, tmp_path)
    bench._log_session_record({"metric": "x"}, "ok", time.monotonic())
    line = open(bench.RECORDS_PATH).read().splitlines()[-1]
    rec = json.loads(line)
    assert rec["kind"] == "bench.session" and rec["status"] == "ok"
    pc = rec["plan_cache"]
    for key in ("hits", "misses", "evictions", "size", "hit_rate"):
        assert key in pc


def test_log_hw_text_writes_out_file(tmp_path, monkeypatch):
    _redirect(monkeypatch, tmp_path)
    bench._log_hw_text("gmg_n_2000", "Iterations / sec: 97.1\n")
    outs = list(tmp_path.glob("*_gmg_n_2000.out"))
    assert len(outs) == 1
    assert "97.1" in outs[0].read_text()


def test_probe_timeouts_recorded_in_session_record(tmp_path, monkeypatch):
    """ISSUE 6 satellite: a watchdog-killed probe is a structured
    artifact — a ``timeouts`` entry in the bench.session record and,
    with telemetry on, one schema-valid ``bench.probe_timeout`` event —
    not a bare stderr line."""
    import time

    from sparse_tpu import telemetry
    from sparse_tpu.config import settings

    _redirect(monkeypatch, tmp_path)
    monkeypatch.setattr(bench, "PROBE_TIMEOUTS", [])
    monkeypatch.setenv("SPARSE_TPU_TELEMETRY", "1")
    monkeypatch.setattr(settings, "telemetry", True)
    telemetry.reset()
    telemetry.configure(str(tmp_path / "tel.jsonl"))
    try:
        bench._note_probe_timeout("tpu", 120.0)
        bench._note_probe_timeout("worker:tpu", 333.3)
        bench._log_session_record({"metric": "x"}, "dead", time.monotonic())
        rec = json.loads(open(bench.RECORDS_PATH).read().splitlines()[-1])
        assert [t["probe"] for t in rec["timeouts"]] == ["tpu", "worker:tpu"]
        assert rec["timeouts"][0]["timeout_s"] == 120.0
        assert all("t_wall" in t for t in rec["timeouts"])
        evs = telemetry.events("bench.probe_timeout")
        assert [e["probe"] for e in evs] == ["tpu", "worker:tpu"]
        assert all(not telemetry.schema.validate(e) for e in evs)
    finally:
        telemetry.configure(None)
        telemetry.reset()


def test_no_timeouts_yields_empty_field(tmp_path, monkeypatch):
    import time

    _redirect(monkeypatch, tmp_path)
    monkeypatch.setattr(bench, "PROBE_TIMEOUTS", [])
    bench._log_session_record({"metric": "x"}, "ok", time.monotonic())
    rec = json.loads(open(bench.RECORDS_PATH).read().splitlines()[-1])
    assert rec["timeouts"] == []
