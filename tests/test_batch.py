"""Batched solve subsystem (sparse_tpu.batch): operators, masked Krylov
batches, bucketing, and the SolveSession microbatcher.

The load-bearing contract is batch-of-1 parity: the masked batched
solvers use the unbatched solvers' recurrences and convergence-test
points, so ``B=1`` must reproduce ``linalg.cg``/``bicgstab``/``gmres``
(f32/f64, and c64/c128 through the stacked-real transfer shim) — plus
the masked-exit edge cases (already-converged lane, never-converging
lane hitting maxiter) and the plan-cache accounting the bench row
asserts (one pattern pack per pattern, one program per bucket).
"""

import numpy as np
import pytest
import scipy.sparse as sp

import sparse_tpu
from sparse_tpu import linalg, plan_cache, utils
from sparse_tpu.batch import (
    BatchedCSR,
    BatchedDIA,
    SolveSession,
    SparsityPattern,
    batched_bicgstab,
    batched_cg,
    batched_gmres,
    bucket_batch,
    make_batched_operator,
    pad_lanes,
    pad_pattern,
    pow2_ceil,
)
from sparse_tpu.config import settings


def _tridiag_stack(n=48, B=4, dtype=np.float64, seed=0):
    """B SPD systems over one tridiagonal pattern, varied diagonals."""
    rng = np.random.default_rng(seed)
    e = np.ones(n)
    base = sp.diags([-e[:-1], 3.0 * e, -e[:-1]], [-1, 0, 1], format="csr")
    mats = []
    for _ in range(B):
        A = base.copy()
        A.setdiag(3.0 + rng.random(n))
        A.sort_indices()
        mats.append(A.tocsr().astype(dtype))
    rhs = rng.standard_normal((B, n)).astype(dtype)
    return mats, rhs


def _skewed(n=60, seed=3):
    """Skewed general pattern with an empty row and a wide row."""
    rng = np.random.default_rng(seed)
    rows = np.concatenate([
        np.zeros(8, np.int64), np.arange(2, n - 3, 2),
        np.full(6, n - 2, np.int64),
    ])
    cols = rng.integers(0, n, rows.shape[0])
    G = sp.coo_matrix(
        (rng.random(rows.shape[0]), (rows, cols)), shape=(n, n)
    ).tocsr()
    A = (G + G.T) * 0.5
    A = A + sp.diags(np.asarray(np.abs(A).sum(axis=1)).ravel() + 1.0)
    A = A.tocsr()
    A.sort_indices()
    return A


# ---------------------------------------------------------------------------
# operators
# ---------------------------------------------------------------------------
def test_batched_csr_spmv_matches_lanes():
    mats, _ = _tridiag_stack(B=3)
    bc = BatchedCSR.from_stack(mats)
    rng = np.random.default_rng(1)
    X = rng.standard_normal((3, mats[0].shape[0]))
    Y = np.asarray(bc.matvec(X))
    for i in range(3):
        np.testing.assert_allclose(Y[i], mats[i] @ X[i], rtol=1e-12)


@pytest.mark.parametrize("mode", ["segment", "sell", "pallas", "auto"])
def test_batched_csr_modes_agree(monkeypatch, mode):
    """Every spmv_mode produces the same batched SpMV on a skewed
    pattern (the pallas row dispatches the batch-grid kernel in
    interpret mode off-TPU, failing over like PreparedCSR)."""
    monkeypatch.setattr(settings, "spmv_mode", mode)
    A = _skewed()
    mats = []
    for i in range(3):
        m = A.copy()
        m.data = m.data * (1.0 + i)
        mats.append(m)
    bc = BatchedCSR.from_stack(mats)
    bc = BatchedCSR(bc.pattern, np.stack(
        [m.data for m in mats]).astype(np.float32))
    rng = np.random.default_rng(2)
    X = rng.standard_normal((3, A.shape[0])).astype(np.float32)
    Y = np.asarray(bc.matvec(X))
    for i in range(3):
        np.testing.assert_allclose(
            Y[i], (mats[i] @ X[i]).astype(np.float32), rtol=2e-5, atol=1e-6
        )


def test_batched_csr_spmm_and_dense_stack():
    mats, _ = _tridiag_stack(B=2, n=20)
    bc = BatchedCSR.from_stack(mats)
    rng = np.random.default_rng(4)
    X = rng.standard_normal((2, 20, 3))
    Y = np.asarray(bc.matmat(X))
    for i in range(2):
        np.testing.assert_allclose(Y[i], mats[i] @ X[i], rtol=1e-12)
    dense = make_batched_operator(
        np.stack([m.toarray() for m in mats])
    )
    Yd = np.asarray(dense.matmat(X))
    np.testing.assert_allclose(Yd, Y, rtol=1e-12)


def test_batched_dia_matches_csr_path():
    mats, _ = _tridiag_stack(B=3, n=32)
    bc = BatchedCSR.from_stack(mats)
    bd = bc.todia()
    assert isinstance(bd, BatchedDIA)
    assert len(bd.offsets) == 3
    rng = np.random.default_rng(5)
    X = rng.standard_normal((3, 32))
    np.testing.assert_allclose(
        np.asarray(bd.matvec(X)), np.asarray(bc.matvec(X)), rtol=1e-12
    )
    # a genuinely non-banded pattern refuses the DIA view
    with pytest.raises(ValueError):
        BatchedCSR.from_stack([_skewed()]).todia(max_diags=4)


def test_pattern_mismatch_rejected():
    mats, _ = _tridiag_stack(B=2, n=16)
    other = sp.eye(16, format="csr")
    with pytest.raises(ValueError):
        BatchedCSR.from_stack([mats[0], other])


def test_pattern_pack_cached_once():
    """One pattern object => one SELL pack, shared by every batch over
    it (the batched form of the prepare/execute contract)."""
    mats, _ = _tridiag_stack(B=2, n=24)
    pattern = SparsityPattern.from_csr(mats[0])
    vals = np.stack([m.data for m in mats])
    before = plan_cache.snapshot()
    bc1 = BatchedCSR(pattern, vals)
    bc2 = BatchedCSR(pattern, vals * 2.0)
    X = np.random.default_rng(0).standard_normal((2, 24))
    bc1.matvec(X)
    bc2.matvec(X)
    bc1.matvec(X)
    d = plan_cache.delta(before)
    assert d["misses"] == 1  # the pattern pack; everything else hits
    assert d["hits"] >= 2


def test_lane_view_roundtrip():
    mats, _ = _tridiag_stack(B=2, n=16)
    bc = BatchedCSR.from_stack(mats)
    lane = bc.lane(1)
    assert isinstance(lane, sparse_tpu.csr_array)
    np.testing.assert_allclose(lane.toarray(), mats[1].toarray())


def test_block_operator_interop():
    """make_linear_operator over a batch = the block-diagonal system:
    the unbatched solver surface keeps working."""
    mats, rhs = _tridiag_stack(B=3, n=24)
    bc = BatchedCSR.from_stack(mats)
    L = linalg.make_linear_operator(bc)
    assert L.shape == (72, 72)
    x, iters = linalg.cg(L, rhs.reshape(-1), tol=1e-10, maxiter=300)
    X = np.asarray(x).reshape(3, 24)
    for i in range(3):
        np.testing.assert_allclose(
            mats[i] @ X[i], rhs[i], rtol=1e-8, atol=1e-8
        )


# ---------------------------------------------------------------------------
# batch-of-1 parity (the satellite contract)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_b1_cg_parity(dtype):
    mats, rhs = _tridiag_stack(B=1, dtype=dtype, seed=7)
    tol = 1e-6 if dtype == np.float32 else 1e-12
    Xb, info = batched_cg(
        BatchedCSR.from_stack(mats), rhs, tol=tol, maxiter=400
    )
    xu, iu = linalg.cg(sparse_tpu.csr_array(mats[0]), rhs[0], tol=tol,
                       maxiter=400)
    assert int(np.asarray(info.iters)[0]) == iu
    # same recurrences, different SpMV kernel (batched SELL vs DIA):
    # f32 agreement is eps-accumulation bounded, f64 essentially exact
    np.testing.assert_allclose(
        np.asarray(Xb)[0], np.asarray(xu),
        rtol=1e-4 if dtype == np.float32 else 1e-12,
        atol=1e-5 if dtype == np.float32 else 1e-12,
    )
    assert bool(np.asarray(info.converged)[0])


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_b1_bicgstab_parity(dtype):
    mats, rhs = _tridiag_stack(B=1, dtype=dtype, seed=8)
    tol = 1e-5 if dtype == np.float32 else 1e-12
    Xb, info = batched_bicgstab(
        BatchedCSR.from_stack(mats), rhs, tol=tol, maxiter=400
    )
    xu, iu = linalg.bicgstab(
        sparse_tpu.csr_array(mats[0]), rhs[0], tol=tol, maxiter=400
    )
    assert int(np.asarray(info.iters)[0]) == iu
    np.testing.assert_allclose(
        np.asarray(Xb)[0], np.asarray(xu),
        rtol=1e-4 if dtype == np.float32 else 1e-11, atol=1e-11,
    )


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_b1_gmres_parity(dtype):
    mats, rhs = _tridiag_stack(B=1, dtype=dtype, seed=9)
    tol = 1e-5 if dtype == np.float32 else 1e-10
    Xb, info = batched_gmres(BatchedCSR.from_stack(mats), rhs, tol=tol)
    xu, iu = linalg.gmres(sparse_tpu.csr_array(mats[0]), rhs[0], tol=tol)
    assert int(np.asarray(info.iters)[0]) == iu
    np.testing.assert_allclose(
        np.asarray(Xb)[0], np.asarray(xu),
        rtol=1e-4 if dtype == np.float32 else 1e-9, atol=1e-9,
    )


def _hermitian_stack(n=32, seed=10):
    rng = np.random.default_rng(seed)
    hop = rng.random(n - 1) + 1j * rng.random(n - 1)
    H = sp.diags(
        [np.conj(hop), np.full(n, 4.0 + 0j), hop], [-1, 0, 1]
    ).tocsr()
    H.sort_indices()
    zb = rng.standard_normal(n) + 1j * rng.standard_normal(n)
    return H, zb


def test_b1_cg_complex_via_stacked_shim(monkeypatch):
    """c64/c128 batch-of-1 parity with the TRANSFER-RESTRICTED path
    forced: complex host inputs ride utils.asjnp's stacked-real shim
    into the batched solver, exactly like the unbatched solvers."""
    monkeypatch.setattr(utils, "_TRANSFER_RESTRICTED", True)
    H, zb = _hermitian_stack()
    Xb, info = batched_cg(
        BatchedCSR.from_stack([H]), zb[None, :], tol=1e-10, maxiter=400
    )
    xu, iu = linalg.cg(sparse_tpu.csr_array(H), zb, tol=1e-10, maxiter=400)
    assert int(np.asarray(info.iters)[0]) == iu
    np.testing.assert_allclose(
        utils.tohost(Xb)[0], utils.tohost(xu), rtol=1e-10, atol=1e-12
    )


def test_b1_gmres_complex():
    H, zb = _hermitian_stack(seed=11)
    Xb, info = batched_gmres(
        BatchedCSR.from_stack([H]), zb[None, :], tol=1e-9
    )
    xu, iu = linalg.gmres(sparse_tpu.csr_array(H), zb, tol=1e-9)
    assert int(np.asarray(info.iters)[0]) == iu
    np.testing.assert_allclose(
        np.asarray(Xb)[0], np.asarray(xu), rtol=1e-7, atol=1e-9
    )


# ---------------------------------------------------------------------------
# masked-exit edge cases
# ---------------------------------------------------------------------------
def test_masked_lanes_match_unbatched_iters():
    """Mixed batch: an already-converged lane (b = 0), a normal lane, a
    never-converging lane (impossible tol) — per-lane iteration counts
    equal the three separate unbatched solves, converged lanes freeze."""
    mats, rhs = _tridiag_stack(B=3, seed=12)
    rhs = rhs.copy()
    rhs[0] = 0.0  # already converged at entry
    tols = np.array([1e-10, 1e-10, 1e-300])
    Xb, info = batched_cg(
        BatchedCSR.from_stack(mats), rhs, tol=tols, maxiter=40,
        conv_test_iters=5,
    )
    iters_b = np.asarray(info.iters)
    conv_b = np.asarray(info.converged)
    for i in range(3):
        xu, iu = linalg.cg(
            sparse_tpu.csr_array(mats[i]), rhs[i], tol=float(tols[i]),
            maxiter=40, conv_test_iters=5,
        )
        assert iters_b[i] == iu
        np.testing.assert_allclose(
            np.asarray(Xb)[i], np.asarray(xu), rtol=1e-10, atol=1e-12
        )
    # the impossible lane hit maxiter and is flagged unconverged
    assert iters_b[2] == 40 and not conv_b[2]
    assert conv_b[0] and conv_b[1]


def test_converged_lane_result_is_frozen():
    """A lane that converges early must return the SAME iterate whether
    its batch-mates keep running or not."""
    mats, rhs = _tridiag_stack(B=2, seed=13)
    tols = np.array([1e-8, 1e-300])  # lane 1 runs to maxiter
    X2, info2 = batched_cg(
        BatchedCSR.from_stack(mats), rhs, tol=tols, maxiter=60,
        conv_test_iters=5,
    )
    X1, info1 = batched_cg(
        BatchedCSR.from_stack(mats[:1]), rhs[:1], tol=1e-8, maxiter=60,
        conv_test_iters=5,
    )
    assert np.asarray(info2.iters)[0] == np.asarray(info1.iters)[0]
    np.testing.assert_array_equal(np.asarray(X2)[0], np.asarray(X1)[0])


def test_bicgstab_maxiter_lane():
    mats, rhs = _tridiag_stack(B=2, seed=14)
    tols = np.array([1e-8, 1e-300])
    _X, info = batched_bicgstab(
        BatchedCSR.from_stack(mats), rhs, tol=tols, maxiter=60,
        conv_test_iters=4,
    )
    iters = np.asarray(info.iters)
    conv = np.asarray(info.converged)
    assert iters[1] == 60 and not conv[1]
    assert conv[0] and iters[0] < 60


# ---------------------------------------------------------------------------
# bucketing
# ---------------------------------------------------------------------------
def test_pow2_bucketing(monkeypatch):
    assert [pow2_ceil(v) for v in (0, 1, 2, 3, 5, 8, 9)] == \
        [1, 1, 2, 4, 8, 8, 16]
    monkeypatch.setattr(settings, "batch_max", 16)
    assert bucket_batch(5) == 8
    assert bucket_batch(5, policy="exact") == 5
    assert bucket_batch(100) == 16  # clamped to batch_max
    with pytest.raises(ValueError):
        bucket_batch(3, policy="fibonacci")


def test_pad_lanes_converge_instantly():
    mats, rhs = _tridiag_stack(B=3, seed=15)
    vals = np.stack([m.data for m in mats])
    tols = np.full(3, 1e-10)
    v, r, t, x0, nreal = pad_lanes(vals, rhs, tols, 4)
    assert v.shape[0] == r.shape[0] == t.shape[0] == 4 and nreal == 3
    pattern = SparsityPattern.from_csr(mats[0])
    _X, info = batched_cg(
        BatchedCSR(pattern, v), r, tol=t, maxiter=100, conv_test_iters=5
    )
    iters = np.asarray(info.iters)
    # the pad lane (zero rhs, huge tol) froze at the first test point
    assert iters[3] == 5 and bool(np.asarray(info.converged)[3])


def test_pad_pattern_exact_for_krylov():
    """Shape/nnz pow2 padding is exact: the padded solve restricted to
    the real rows equals the unpadded solve (empty pad rows and zero
    entries contribute nothing to any inner product)."""
    A = _tridiag_stack(B=1, n=27, seed=16)[0][0]
    b = np.random.default_rng(17).standard_normal(27)
    pattern = SparsityPattern.from_csr(A)
    padded, pad_values, pad_rhs = pad_pattern(pattern)
    assert padded.shape == (32, 32)
    assert padded.nnz == pow2_ceil(pattern.nnz)
    Xp, infop = batched_cg(
        BatchedCSR(padded, pad_values(A.data[None, :])),
        pad_rhs(b[None, :]), tol=1e-10, maxiter=200,
    )
    xu, iu = linalg.cg(sparse_tpu.csr_array(A), b, tol=1e-10, maxiter=200)
    assert int(np.asarray(infop.iters)[0]) == iu
    np.testing.assert_allclose(
        np.asarray(Xp)[0, :27], np.asarray(xu), rtol=1e-10, atol=1e-12
    )
    np.testing.assert_allclose(np.asarray(Xp)[0, 27:], 0.0, atol=1e-12)


# ---------------------------------------------------------------------------
# SolveSession
# ---------------------------------------------------------------------------
def test_session_scatter_and_correctness():
    mats, rhs = _tridiag_stack(B=5, seed=18)
    ses = SolveSession("cg", batch_max=8)
    tickets = [
        ses.submit(mats[i], rhs[i], tol=1e-10, maxiter=200)
        for i in range(5)
    ]
    assert ses.pending == 5 and not tickets[0].done
    assert ses.flush() == 1  # one bucket: same pattern, one chunk
    assert ses.pending == 0
    for i, t in enumerate(tickets):
        x, iters, resid2 = t.result()
        assert t.done and t.converged
        np.testing.assert_allclose(mats[i] @ x, rhs[i], rtol=1e-7,
                                   atol=1e-7)
        assert iters > 0 and resid2 < 1e-18


def test_session_one_miss_per_bucket():
    """The bench-row contract: a bucket costs exactly one plan-cache
    miss ever; same-bucket redispatches hit the compiled program."""
    mats, rhs = _tridiag_stack(B=4, seed=19)
    ses = SolveSession("cg", batch_max=4)
    pattern = ses.pattern_of(mats[0])
    pattern.sell_pack()  # pattern warm (its own, separate entry)
    before = plan_cache.snapshot()
    ses.solve_many(mats, rhs, tol=1e-8, maxiter=100)
    d = plan_cache.delta(before)
    assert d["misses"] == 1  # the bucket program, nothing else
    before = plan_cache.snapshot()
    ses.solve_many(mats, rhs, tol=1e-8, maxiter=100)
    d2 = plan_cache.delta(before)
    assert d2["misses"] == 0 and d2["hits"] >= 1


def test_session_buckets_split_and_pad(monkeypatch):
    """7 requests under batch_max=4 -> two dispatches; the 3-lane tail
    pads to its pow2 bucket of 4."""
    mats, rhs = _tridiag_stack(B=7, seed=20)
    ses = SolveSession("cg", batch_max=4)
    tickets = [
        ses.submit(mats[i], rhs[i], tol=1e-8, maxiter=100)
        for i in range(7)
    ]
    assert ses.flush() == 2
    for i, t in enumerate(tickets):
        x, _it, _r2 = t.result()
        np.testing.assert_allclose(mats[i] @ x, rhs[i], rtol=1e-6,
                                   atol=1e-6)


def test_session_auto_flush_and_mixed_tols():
    mats, rhs = _tridiag_stack(B=2, seed=21)
    ses = SolveSession("cg", auto_flush=2)
    t0 = ses.submit(mats[0], rhs[0], tol=1e-4, maxiter=100)
    assert not t0.done
    t1 = ses.submit(mats[1], rhs[1], tol=1e-12, maxiter=400)
    # auto_flush fired on the second submit: both lanes dispatched (the
    # pipelined fast path launches without waiting, so retirement is
    # only guaranteed once a result is demanded — not at submit return)
    assert all(not q for q in ses._pending.values())
    t0.result(), t1.result()
    assert t0.done and t1.done
    _x0, it0, r0 = t0.result()
    _x1, it1, r1 = t1.result()
    assert r0 < 1e-8 and r1 < 1e-22  # tol^2 per lane
    assert it1 >= it0  # the tighter lane iterated at least as long


@pytest.mark.parametrize("solver", ["bicgstab", "gmres"])
def test_session_other_solvers(solver):
    mats, rhs = _tridiag_stack(B=3, seed=22)
    ses = SolveSession(solver, batch_max=4)
    X, iters, _r2 = ses.solve_many(mats, rhs, tol=1e-9, maxiter=300)
    for i in range(3):
        np.testing.assert_allclose(mats[i] @ X[i], rhs[i], rtol=1e-6,
                                   atol=1e-6)
        assert iters[i] > 0


def test_session_telemetry_dispatch_event(monkeypatch, tmp_path):
    """With telemetry on, each dispatch emits a schema-valid
    batch.dispatch event carrying batch/bucket/padding/queue stats."""
    from sparse_tpu import telemetry

    monkeypatch.setattr(settings, "telemetry", True)
    telemetry.configure(str(tmp_path / "t.jsonl"))
    telemetry.reset()
    try:
        mats, rhs = _tridiag_stack(B=3, seed=23)
        ses = SolveSession("cg", batch_max=4)
        ses.solve_many(mats, rhs, tol=1e-8, maxiter=100)
        evs = telemetry.events("batch.dispatch")
        assert len(evs) == 1
        ev = evs[0]
        assert telemetry.schema.validate(ev) == []
        assert ev["batch"] == 3 and ev["bucket"] == 4
        assert ev["pad_waste"] == 1
        assert ev["queue_ms_max"] >= 0 and ev["iters_max"] > 0
        # the public krylov entry points log batch.solve events (the
        # session's jitted bucket programs use the raw loops instead)
        _X, _info = batched_cg(
            BatchedCSR.from_stack(mats), rhs, tol=1e-8, maxiter=100
        )
        solves = telemetry.events("batch.solve")
        assert solves and telemetry.schema.validate(solves[0]) == []
        assert solves[0]["B"] == 3
    finally:
        telemetry.configure(None)
        telemetry.reset()


def test_session_rejects_bad_shapes():
    mats, rhs = _tridiag_stack(B=1, n=16)
    ses = SolveSession("cg")
    with pytest.raises(ValueError):
        ses.submit(mats[0], rhs[0][:-1])
    with pytest.raises(ValueError):
        SolveSession("sor")


# -- Axon v3: serving levels (SLO, ticket latency, live session view) --------


def test_slo_miss_counter_and_ticket_latency_histogram():
    from sparse_tpu.telemetry import _metrics as M

    mats, rhs = _tridiag_stack(B=2)
    misses0 = M.counter("batch.slo_misses").value
    s = SolveSession("cg", slo_ms=0.0)  # every ticket misses a 0ms SLO
    h0 = M.histogram("batch.ticket_latency", solver="cg").count
    X, iters, resid2 = s.solve_many(mats, rhs, tol=1e-8)
    assert M.counter("batch.slo_misses").value == misses0 + 2
    assert M.histogram("batch.ticket_latency", solver="cg").count == h0 + 2
    st = s.session_stats()
    assert st["tickets"]["done"] == 2 and st["tickets"]["slo_miss"] == 2
    assert st["slo_ms"] == 0.0 and st["tickets"]["pending"] == 0

    # no objective -> nothing counted
    s2 = SolveSession("cg")
    s2.solve_many(mats, rhs, tol=1e-8)
    assert M.counter("batch.slo_misses").value == misses0 + 2
    assert s2.session_stats()["tickets"]["slo_miss"] == 0


def test_sessions_stats_tracks_live_sessions_weakly():
    import gc

    from sparse_tpu.batch import service

    mats, rhs = _tridiag_stack(B=1)
    s = SolveSession("bicgstab")
    s.submit(mats[0], rhs[0], tol=1e-8)
    stats = service.sessions_stats()
    mine = [
        st for st in stats
        if st["solver"] == "bicgstab" and st["tickets"]["pending"] == 1
    ]
    assert mine, "a live session must appear in the serving view"
    s.flush()
    del s
    gc.collect()
    assert not [
        st for st in service.sessions_stats()
        if st["solver"] == "bicgstab" and st["tickets"]["pending"] == 1
    ]
