"""COO format surface oracle tests vs scipy.

Reference analog: ``tests/integration/test_coo.py``.
"""

import numpy as np
import pytest
import scipy.io as sci_io
import scipy.sparse as scpy

import sparse_tpu as sparse
from .utils.common import test_mtx_files, types
from .utils.sample import sample_csr, sample_vec


@pytest.mark.parametrize("filename", test_mtx_files)
@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_coo_from_scipy(filename, dtype):
    s = sci_io.mmread(filename).astype(dtype)
    arr = sparse.coo_array(s)
    assert arr.dtype == dtype
    assert np.allclose(np.asarray(arr.todense()), s.todense())


def test_coo_from_arrays():
    row = np.array([0, 3, 1, 0])
    col = np.array([0, 3, 1, 2])
    data = np.array([4.0, 5.0, 7.0, 9.0])
    arr = sparse.coo_array((data, (row, col)), shape=(4, 4))
    exp = scpy.coo_matrix((data, (row, col)), shape=(4, 4))
    assert np.allclose(np.asarray(arr.todense()), exp.todense())


def test_coo_duplicates_sum():
    """Duplicate (i, j) entries must sum on conversion (the dist_sort
    duplicate-key regression surface)."""
    row = np.array([0, 0, 1, 1, 0])
    col = np.array([1, 1, 2, 2, 1])
    data = np.array([1.0, 2.0, 3.0, 4.0, 5.0])
    arr = sparse.coo_array((data, (row, col)), shape=(3, 3)).tocsr()
    exp = scpy.coo_matrix((data, (row, col)), shape=(3, 3)).tocsr()
    assert np.allclose(np.asarray(arr.todense()), exp.todense())


@pytest.mark.parametrize("filename", test_mtx_files)
def test_coo_transpose(filename):
    arr = sparse.io.mmread(filename).T
    s = sci_io.mmread(filename).T
    assert np.allclose(np.asarray(arr.todense()), np.asarray(s.todense()))


@pytest.mark.parametrize("filename", test_mtx_files)
def test_coo_matmul(filename):
    arr = sparse.io.mmread(filename)
    s = sci_io.mmread(filename).tocsr()
    B = np.random.default_rng(1).random((arr.shape[1], 6))
    assert np.allclose(np.asarray(arr @ B), s @ B, atol=1e-6)


@pytest.mark.parametrize("filename", test_mtx_files)
def test_coo_mul(filename):
    arr = sparse.io.mmread(filename)
    s = sci_io.mmread(filename)
    res = arr * 2.5
    assert np.allclose(np.asarray(res.todense()), (s * 2.5).todense())


@pytest.mark.parametrize("vec_type", types)
def test_coo_dot(vec_type):
    sa = sample_csr(15, 21, density=0.3, seed=97).tocoo()
    v = sample_vec(21, dtype=vec_type, seed=98)
    arr = sparse.coo_array(sa)
    assert np.allclose(np.asarray(arr @ v), sa.tocsr() @ v, atol=1e-5)


def test_coo_row_col_attributes():
    sa = sample_csr(8, 9, density=0.4, seed=99).tocoo()
    arr = sparse.coo_array(sa)
    got = scpy.coo_matrix(
        (np.asarray(arr.data), (np.asarray(arr.row), np.asarray(arr.col))),
        shape=arr.shape,
    )
    assert np.allclose(got.todense(), sa.todense())


def test_coo_tocsc_roundtrip():
    sa = sample_csr(12, 10, density=0.3, seed=100).tocoo()
    arr = sparse.coo_array(sa)
    assert np.allclose(np.asarray(arr.tocsc().todense()), sa.tocsc().todense())
    assert np.allclose(np.asarray(arr.todia().todense()), sa.todia().todense())
