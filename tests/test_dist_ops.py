"""Distributed op layer vs the scipy oracle on the virtual CPU mesh.

Reference analog: the resource-shape axis of the reference CI (SURVEY §4):
the same correctness checks under 1/2/8 shards exercise the full
partitioning + collective machinery — SpMM row-split (csr.py:1151), rSpMM
k-split + reduction (csr.py:1209), column-split SpMV (csr.py:869-927), and
the distributed SpGEMM algorithms (csr.py:1390-1728).
"""

import numpy as np
import pytest
import scipy.sparse as sp

import sparse_tpu as sparse
from sparse_tpu.parallel import (
    dist_spgemm,
    dist_spgemm_2d,
    shard_csr,
    shard_csr_cols,
)
from sparse_tpu.parallel.mesh import get_mesh, get_mesh_2d

SHARDS = [1, 2, 8]


def _rand_csr(m, n, density=0.15, seed=0):
    return sp.random(m, n, density=density, random_state=seed, format="csr")


@pytest.mark.parametrize("num_shards", SHARDS)
@pytest.mark.parametrize("layout", ["ell", "csr"])
def test_dist_spmm(num_shards, layout):
    s = _rand_csr(60, 50, seed=1)
    D = shard_csr(sparse.csr_array(s), mesh=get_mesh(num_shards), layout=layout)
    B = np.random.default_rng(2).standard_normal((50, 7))
    assert np.allclose(D.dot(B), s @ B)


@pytest.mark.parametrize("num_shards", SHARDS)
@pytest.mark.parametrize("layout", ["ell", "csr"])
def test_dist_rspmm(num_shards, layout):
    s = _rand_csr(40, 33, seed=3)
    D = shard_csr(sparse.csr_array(s), mesh=get_mesh(num_shards), layout=layout)
    B = np.random.default_rng(4).standard_normal((5, 40))
    assert np.allclose(D.rdot(B), B @ s)
    v = np.random.default_rng(5).standard_normal(40)
    assert np.allclose(D.rdot(v), v @ s)


@pytest.mark.parametrize("num_shards", SHARDS)
def test_dist_spmv_colsplit(num_shards):
    s = _rand_csr(45, 52, seed=6)
    D = shard_csr_cols(sparse.csr_array(s), mesh=get_mesh(num_shards))
    x = np.random.default_rng(7).standard_normal(52)
    assert np.allclose(D.dot(x), s @ x)


@pytest.mark.parametrize("num_shards", SHARDS)
def test_dist_spmv_colsplit_square_banded(num_shards):
    """Banded square case — the PDE/solver shape."""
    s = sp.diags(
        [np.full(63, -1.0), np.full(64, 2.0), np.full(63, -1.0)],
        [-1, 0, 1],
        format="csr",
    )
    D = shard_csr_cols(sparse.csr_array(s), mesh=get_mesh(num_shards))
    x = np.random.default_rng(8).standard_normal(64)
    assert np.allclose(D.dot(x), s @ x)


@pytest.mark.parametrize("num_shards", SHARDS)
def test_dist_spgemm(num_shards):
    a = _rand_csr(37, 29, seed=9)
    b = _rand_csr(29, 41, seed=10)
    C = dist_spgemm(
        sparse.csr_array(a), sparse.csr_array(b), mesh=get_mesh(num_shards)
    )
    assert np.allclose(np.asarray(C.toarray()), (a @ b).toarray())


def test_dist_spgemm_empty_rows():
    """Shards spanning empty row blocks must stitch correctly."""
    a = sp.csr_matrix((8, 6))
    a[0, 1] = 2.0
    a[7, 5] = 3.0
    b = _rand_csr(6, 5, density=0.4, seed=11)
    C = dist_spgemm(sparse.csr_array(a), sparse.csr_array(b), mesh=get_mesh(8))
    assert np.allclose(np.asarray(C.toarray()), (a @ b).toarray())


@pytest.mark.parametrize("nprocs", [1, 2, 8])
def test_dist_spgemm_2d(nprocs):
    a = _rand_csr(30, 26, seed=12)
    b = _rand_csr(26, 34, seed=13)
    C = dist_spgemm_2d(
        sparse.csr_array(a), sparse.csr_array(b), mesh2d=get_mesh_2d(nprocs)
    )
    assert np.allclose(np.asarray(C.toarray()), (a @ b).toarray())


def test_dist_spgemm_galerkin():
    """The AMG Galerkin triple product R @ A @ P across the mesh matches
    the single-device product (the north-star structure, BASELINE.md)."""
    n = 64
    A = sp.diags(
        [np.full(n - 1, -1.0), np.full(n, 2.0), np.full(n - 1, -1.0)],
        [-1, 0, 1],
        format="csr",
    )
    # simple aggregation P: pair neighboring points
    P = sp.csr_matrix(
        (np.ones(n), (np.arange(n), np.arange(n) // 2)), shape=(n, n // 2)
    )
    R = P.T.tocsr()
    mesh = get_mesh(8)
    Ad = sparse.csr_array(A)
    Pd = sparse.csr_array(P)
    Rd = sparse.csr_array(R)
    AP = dist_spgemm(Ad, Pd, mesh=mesh)
    RAP = dist_spgemm(Rd, AP, mesh=mesh)
    ref = (R @ A @ P).toarray()
    assert np.allclose(np.asarray(RAP.toarray()), ref)


@pytest.mark.parametrize("nprocs", [2, 8])
def test_dist_spgemm_2d_as_dist(nprocs):
    """The device-side shuffle materializes a row-sharded DistCSR whose
    mesh SpMV matches scipy — no host lexsort anywhere in the path
    (reference 3-phase shuffle, csr.py:1592-1728)."""
    from sparse_tpu.parallel import spgemm as dspg

    a = _rand_csr(44, 31, seed=21)
    b = _rand_csr(31, 38, seed=22)
    D = dist_spgemm_2d(
        sparse.csr_array(a), sparse.csr_array(b),
        mesh2d=get_mesh_2d(nprocs), as_dist=True,
    )
    # host saw only O(S*gy) counts (the send matrix), never the nnz
    assert dspg.LAST_STATS["host_counts"] <= nprocs * 8 * 2
    x = np.arange(38, dtype=np.float64) / 38.0
    y = D.unpad_vector(D.spmv_padded(D.pad_vector(x)))
    np.testing.assert_allclose(y, (a @ b) @ x, rtol=1e-9, atol=1e-12)


def test_dist_spgemm_2d_banded_dist_stays_local():
    """On a banded product the 2-D shuffle output keeps halo mode (windowed
    x gather), proving locality survives the device-side pipeline."""
    n = 96
    a = sp.diags(
        [np.full(n - 1, -1.0), np.full(n, 2.0), np.full(n - 1, -1.0)],
        [-1, 0, 1], format="csr",
    ).tocsr()
    D = dist_spgemm_2d(
        sparse.csr_array(a), sparse.csr_array(a),
        mesh2d=get_mesh_2d(8), as_dist=True,
    )
    assert D.mode == "halo", "banded product must keep the windowed-x path"
    x = np.sin(np.arange(n))
    y = D.unpad_vector(D.spmv_padded(D.pad_vector(x)))
    np.testing.assert_allclose(y, (a @ a) @ x, rtol=1e-9, atol=1e-12)
