"""Library-wide operator plan cache: counters, eviction, solver reuse.

The acceptance instrument of ISSUE 2's prepare/execute split: one miss at
prepare, hits for every subsequent matvec of a solve (>= 98% over a
50-iteration CG), entries dying with their operator, LRU bounded, and a
disable switch that changes performance only — never results.
"""

import gc

import numpy as np
import pytest
import scipy.sparse as sp

import sparse_tpu
from sparse_tpu import linalg, plan_cache
from sparse_tpu.config import settings


class _Obj:
    """A trivially weakref-able cache key."""


def _delta(before, after):
    return {k: after[k] - before[k] for k in ("hits", "misses", "evictions")}


def test_get_counts_hits_and_misses():
    o = _Obj()
    before = plan_cache.stats()
    assert plan_cache.get(o, "k", lambda: "plan") == "plan"
    assert plan_cache.get(o, "k", lambda: "NEW") == "plan"  # cached wins
    assert plan_cache.lookup(o, "k") == "plan"
    assert plan_cache.lookup(o, "other") is None
    d = _delta(before, plan_cache.stats())
    assert d["hits"] == 2 and d["misses"] == 2


def test_weakref_eviction():
    o = _Obj()
    plan_cache.get(o, "k", lambda: "plan")
    before = plan_cache.stats()
    del o
    gc.collect()
    after = plan_cache.stats()
    assert after["evictions"] >= before["evictions"] + 1


def test_invalidate_and_capacity_lru(monkeypatch):
    monkeypatch.setattr(settings, "plan_cache_capacity", 4)
    objs = [_Obj() for _ in range(6)]
    for i, o in enumerate(objs):
        plan_cache.get(o, "k", lambda i=i: i)
    assert plan_cache.stats()["size"] <= 4
    # the oldest entries were LRU-evicted; the newest are still hits
    before = plan_cache.stats()
    assert plan_cache.lookup(objs[-1], "k") == 5
    assert plan_cache.lookup(objs[0], "k") is None
    d = _delta(before, plan_cache.stats())
    assert d["hits"] == 1 and d["misses"] == 1
    plan_cache.invalidate(objs[-1], "k")
    assert plan_cache.lookup(objs[-1], "k") is None


def test_no_eager_pack_when_cache_disabled(monkeypatch):
    """Regression (ISSUE 3 satellite): make_linear_operator's auto-warm
    used to pack a SELL plan even with SPARSE_TPU_PLAN_CACHE=0 — a full
    pack built and immediately discarded, charged to every one-shot
    solve. With the cache off the warm must skip; execute-time packing
    (an actual matvec) still works."""
    from sparse_tpu.kernels import sell_spmv as ks

    monkeypatch.setattr(settings, "plan_cache", False)
    monkeypatch.setattr(settings, "spmv_mode", "sell")
    packs = []
    real = ks.sell_pack
    monkeypatch.setattr(
        ks, "sell_pack", lambda *a, **k: packs.append(1) or real(*a, **k)
    )
    s = _skewed_spd(120, seed=9)
    A = sparse_tpu.csr_array(s)
    linalg.make_linear_operator(A)  # the auto-warm wrap
    assert packs == []  # no pack: nowhere to cache it
    y = A @ np.ones(120)  # eager matvec: packs (uncached) and executes
    assert len(packs) == 1
    np.testing.assert_allclose(np.asarray(y), s @ np.ones(120), rtol=1e-10)
    # with the cache ON the warm packs exactly once and the matvec reuses
    monkeypatch.setattr(settings, "plan_cache", True)
    packs.clear()
    A2 = sparse_tpu.csr_array(s)
    linalg.make_linear_operator(A2)
    assert len(packs) == 1
    A2 @ np.ones(120)
    assert len(packs) == 1


def test_disabled_cache_builds_every_time(monkeypatch):
    monkeypatch.setattr(settings, "plan_cache", False)
    o = _Obj()
    calls = []
    for _ in range(3):
        plan_cache.get(o, "k", lambda: calls.append(1))
    assert len(calls) == 3
    assert plan_cache.lookup(o, "k") is None


def _skewed_spd(m=400, seed=5):
    rng = np.random.default_rng(seed)
    deg = np.minimum((rng.pareto(1.1, m) * 4 + 1).astype(int), m // 4)
    rows = np.repeat(np.arange(m), deg)
    cols = rng.integers(0, m, rows.shape[0])
    G = sp.coo_matrix(
        (rng.random(rows.shape[0]), (rows, cols)), shape=(m, m)
    ).tocsr()
    A = (G + G.T) * 0.5
    return (A + sp.diags(np.asarray(np.abs(A).sum(axis=1)).ravel() + 1.0)).tocsr()


def test_cg_100_iters_hit_rate(monkeypatch):
    """The headline contract: a long host-loop CG solve prepares once and
    reuses the plan for every matvec — exactly 1 miss (at prepare), hits
    for the rest. 50 eager per-iteration matvecs (via callback) pin the
    same asymptote 100 did at half the dispatch cost."""
    monkeypatch.setattr(settings, "spmv_mode", "sell")
    s = _skewed_spd()
    A = sparse_tpu.csr_array(s)
    b = np.random.default_rng(0).standard_normal(s.shape[0])
    plan_cache.reset_stats()
    x, iters = linalg.cg(
        A, b, maxiter=50, tol=1e-30, conv_test_iters=200,
        callback=lambda _x: None,
    )
    assert iters == 50
    st = plan_cache.stats()
    assert st["misses"] == 1
    assert st["hit_rate"] >= 0.98
    # and the solve is still a solve
    np.testing.assert_allclose(np.asarray(A @ x), b, rtol=1e-4, atol=1e-5)


def test_device_loop_cg_uses_prepared_plan(monkeypatch):
    """The compiled-loop path: make_linear_operator warms the plan at wrap
    time, so the traced while_loop embeds the packed operator (lookup hits
    from inside the trace) and converges identically both cache states."""
    s = _skewed_spd(200, seed=6)
    b = np.random.default_rng(1).standard_normal(200)
    monkeypatch.setattr(settings, "spmv_mode", "sell")
    sols = {}
    for cache_on in (True, False):
        monkeypatch.setattr(settings, "plan_cache", cache_on)
        A = sparse_tpu.csr_array(s)
        x, _ = linalg.cg(A, b, maxiter=60, tol=1e-12)
        sols[cache_on] = np.asarray(x)
        if cache_on:
            assert plan_cache.lookup(A, "sell") is not None
    np.testing.assert_allclose(sols[True], sols[False], rtol=1e-6, atol=1e-8)


def test_solvers_share_one_plan(monkeypatch):
    """Different solvers over the same operator object share the plan:
    exactly one sell pack, everything after is hits."""
    monkeypatch.setattr(settings, "spmv_mode", "sell")
    s = _skewed_spd(150, seed=7)
    A = sparse_tpu.csr_array(s)
    b = np.random.default_rng(2).standard_normal(150)
    plan_cache.reset_stats()
    linalg.cg(A, b, maxiter=10, tol=1e-30)
    linalg.bicgstab(A, b, maxiter=5, tol=1e-30)
    linalg.gmres(A, b, maxiter=1, restart=5, tol=1e-30)
    st = plan_cache.stats()
    assert st["misses"] <= 2  # one sell pack (+ at most one trace-cold lookup)
    assert st["hits"] >= 3


def test_dist_spmv_plans_ride_the_cache():
    """DistCSR's compiled shard_map programs are plan-cache entries: eager
    local-shard matvecs account hits, and the plan dies with the layout."""
    from sparse_tpu.parallel.dist import shard_csr

    e = np.ones(64)
    A = sparse_tpu.diags([-e[:-1], 2 * e, -e[:-1]], [-1, 0, 1]).tocsr()
    D = shard_csr(A)
    x = np.random.default_rng(3).standard_normal(64)
    plan_cache.reset_stats()
    y1 = D.dot(x)
    y2 = D.dot(x)
    np.testing.assert_allclose(y1, y2)
    st = plan_cache.stats()
    assert st["hits"] >= 1
    assert plan_cache.lookup(D, "dist.spmv") is not None


def test_telemetry_counter_mirror(monkeypatch, tmp_path):
    """With telemetry on, cache activity mirrors into summary()['counts']
    under plan_cache.hit / plan_cache.miss (docs/telemetry.md)."""
    from sparse_tpu import telemetry

    monkeypatch.setattr(settings, "telemetry", True)
    telemetry.configure(str(tmp_path / "t.jsonl"))
    telemetry.reset()
    try:
        o = _Obj()
        plan_cache.get(o, "k", lambda: "plan")
        plan_cache.get(o, "k", lambda: "plan")
        counts = telemetry.summary()["counts"]
        assert counts.get("plan_cache.miss", 0) >= 1
        assert counts.get("plan_cache.hit", 0) >= 1
    finally:
        telemetry.configure(None)
        telemetry.reset()


def test_unweakrefable_keys_never_cached():
    """Objects without weakref support build every time (id-reuse safety)."""
    import weakref

    class NoRef:
        __slots__ = ("x",)

    o = NoRef()
    with pytest.raises(TypeError):
        weakref.ref(o)
    built = []
    for _ in range(2):
        plan_cache.get(o, "k", lambda: built.append(1) or "p")
    assert len(built) == 2
