"""No-x64 test lane (VERDICT r2 #7): real TPUs run WITHOUT x64.

The conftest enables x64 globally for exact scipy-oracle comparisons, so
these scenarios run in SUBPROCESSES with x64 disabled and
``-W error::UserWarning`` — any int64-truncation warning (the silent
downcast hazard of the real-TPU config) fails the lane, not just wrong
results. Covers the marked subset VERDICT names: conversions, sort,
solvers, dist.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PRELUDE = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.pop("JAX_ENABLE_X64", None)
import jax
jax.config.update("jax_platforms", "cpu")
assert not jax.config.jax_enable_x64
import json
import numpy as np
import scipy.sparse as sp
import sparse_tpu as sparse
"""


def run_nox64(code: str, ndev: int = 8, timeout: int = 900) -> dict:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("JAX_ENABLE_X64", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={ndev}"
    proc = subprocess.run(
        [sys.executable, "-W", "error::UserWarning", "-c", PRELUDE + code],
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, (
        f"no-x64 payload rc={proc.returncode}\n--- stderr ---\n"
        f"{proc.stderr[-4000:]}"
    )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_nox64_conversions_and_sort():
    """COO->CSR (device sort path), CSR<->CSC<->dense round trips in f32."""
    rec = run_nox64(r"""
rng = np.random.default_rng(0)
As = sp.random(60, 45, density=0.2, random_state=1, format="coo").astype(np.float32)
C = sparse.coo_array((As.data.copy(), (As.row.copy(), As.col.copy())), shape=As.shape)
csr = C.tocsr()
csc = csr.tocsc()
back = csc.tocsr()
dense_ok = bool(np.allclose(np.asarray(csr.toarray()), As.toarray()))
rt_ok = bool(np.allclose(np.asarray(back.toarray()), As.toarray()))
print(json.dumps({"ok": dense_ok and rt_ok}))
""")
    assert rec["ok"]


def test_nox64_spgemm_and_elemwise():
    rec = run_nox64(r"""
a = sp.random(40, 30, density=0.2, random_state=2, format="csr").astype(np.float32)
b = sp.random(30, 35, density=0.2, random_state=3, format="csr").astype(np.float32)
A = sparse.csr_array(a)
B = sparse.csr_array(b)
prod_ok = bool(np.allclose(np.asarray((A @ B).toarray()), (a @ b).toarray(), atol=1e-5))
c = sp.random(40, 30, density=0.2, random_state=4, format="csr").astype(np.float32)
Cm = sparse.csr_array(c)
add_ok = bool(np.allclose(np.asarray((A + Cm).toarray()), (a + c).toarray(), atol=1e-6))
mul_ok = bool(np.allclose(np.asarray(A.multiply(Cm).toarray()), (a.multiply(c)).toarray(), atol=1e-6))
print(json.dumps({"ok": prod_ok and add_ok and mul_ok}))
""")
    assert rec["ok"]


def test_nox64_solvers():
    """cg / gmres / lsqr / eigsh in f32 without x64."""
    rec = run_nox64(r"""
import sparse_tpu.linalg as linalg
n = 64
s = sp.diags([np.full(n - 1, -1.0), np.full(n, 2.1), np.full(n - 1, -1.0)],
             [-1, 0, 1], format="csr").astype(np.float32)
A = sparse.csr_array(s)
b = np.ones(n, dtype=np.float32)
x, iters = linalg.cg(A, b, tol=1e-4)
cg_ok = bool(np.linalg.norm(np.asarray(A @ x) - b) < 1e-2)
xg, _ = linalg.gmres(A, b, tol=1e-5)
gm_ok = bool(np.linalg.norm(np.asarray(A @ xg) - b) < 1e-2)
xl = linalg.lsqr(A, b)[0]
ls_ok = bool(np.linalg.norm(np.asarray(A @ xl) - b) < 1e-2)
w = linalg.eigsh(A, k=3, tol=1e-4, return_eigenvectors=False)
dense_w = np.linalg.eigvalsh(s.toarray().astype(np.float64))
ei_ok = bool(np.allclose(np.sort(np.abs(np.asarray(w, dtype=np.float64))),
                         np.sort(np.abs(dense_w))[-3:], rtol=1e-3))
print(json.dumps({"ok": cg_ok and gm_ok and ls_ok and ei_ok,
                  "parts": [cg_ok, gm_ok, ls_ok, ei_ok]}))
""")
    assert rec["ok"], rec


def test_nox64_dist():
    """Distributed CG (halo SpMV) + image-gather SpGEMM + 2-D shuffle on
    the 8-device mesh without x64 — the exact real-TPU configuration of
    the multi-chip dryrun."""
    rec = run_nox64(r"""
from sparse_tpu.models.poisson import laplacian_2d_csr_host
from sparse_tpu.parallel import dist_spgemm, dist_spgemm_2d
from sparse_tpu.parallel.dist import dist_cg, shard_csr
from sparse_tpu.parallel.mesh import get_mesh, get_mesh_2d

A = laplacian_2d_csr_host(24, dtype=np.float32)  # 576 rows
D = shard_csr(A, mesh=get_mesh(8), balanced=True)
rng = np.random.default_rng(0)
b = rng.standard_normal(A.shape[0]).astype(np.float32)
xp, iters, conv = dist_cg(D, b, tol=1e-4, maxiter=600, conv_test_iters=25)
x = D.unpad_vector(xp)
As = sp.csr_matrix((np.asarray(A.data), np.asarray(A.indices), np.asarray(A.indptr)), A.shape)
cg_ok = bool(np.linalg.norm(As @ x - b) < 1e-2 * np.linalg.norm(b))
C1 = dist_spgemm(A, A, mesh=get_mesh(8))
g1_ok = bool(np.allclose(np.asarray(C1.toarray()), (As @ As).toarray(), atol=1e-3))
C2 = dist_spgemm_2d(A, A, mesh2d=get_mesh_2d(8))
g2_ok = bool(np.allclose(np.asarray(C2.toarray()), (As @ As).toarray(), atol=1e-3))
print(json.dumps({"ok": cg_ok and g1_ok and g2_ok,
                  "parts": [cg_ok, g1_ok, g2_ok]}))
""")
    assert rec["ok"], rec
