"""Pallas ELL SpMV vs the XLA path and scipy.

Reference analog: the GPU kernel-parity axis of the reference tests — the
cuSPARSE spmv variant must agree with the CPU variant; here the Pallas
windowed-DMA kernel must agree with the XLA gather kernel.
"""

import numpy as np
import pytest
import scipy.sparse as sp

import sparse_tpu
from sparse_tpu.kernels.ell_spmv import ell_band, ell_spmv_pallas
from sparse_tpu.ops.conv import csr_to_ell


def _banded(n, offs):
    mats = [np.full(n - abs(o), 1.0 + i) for i, o in enumerate(offs)]
    return sp.diags(mats, offs, format="csr")


@pytest.mark.parametrize("n", [64, 700, 1500])
def test_ell_pallas_banded(n):
    s = _banded(n, [-3, -1, 0, 1])
    A = sparse_tpu.csr_array(s)
    k = int(np.diff(np.asarray(A.indptr)).max())
    idx, val = csr_to_ell(A.indptr, A.indices, A.data, n, k)
    band = ell_band(idx, val)
    assert band == 3
    x = np.random.default_rng(0).standard_normal(n).astype(np.float32)
    y = ell_spmv_pallas(idx, val.astype(np.float32), x, band=band)
    np.testing.assert_allclose(
        np.asarray(y), (s @ x).astype(np.float32), rtol=1e-4, atol=1e-5
    )


def test_ell_pallas_dispatch(monkeypatch):
    """spmv_mode='pallas' routes banded non-DIA-profiled ELL matrices
    through the Pallas kernel and matches the segment path."""
    from sparse_tpu.config import settings

    n = 256
    s = _banded(n, [-2, 0, 5])
    x = np.random.default_rng(1).standard_normal(n)
    monkeypatch.setattr(settings, "spmv_mode", "segment")
    y_seg = np.asarray(sparse_tpu.csr_array(s) @ x)
    monkeypatch.setattr(settings, "spmv_mode", "pallas")
    monkeypatch.setattr(settings, "dia_max_diags", 0)  # force the ELL route
    A = sparse_tpu.csr_array(s)
    y_pal = np.asarray(A @ x)
    assert A._ell_band_cache == 5
    np.testing.assert_allclose(y_pal, y_seg, rtol=1e-12)


def test_ell_pallas_wide_band_falls_back(monkeypatch):
    """Band beyond pallas_max_band must use the XLA path (still correct)."""
    from sparse_tpu.config import settings

    n = 128
    s = _banded(n, [-(n - 1), 0])  # corner-to-corner band
    x = np.random.default_rng(2).standard_normal(n)
    monkeypatch.setattr(settings, "spmv_mode", "pallas")
    monkeypatch.setattr(settings, "dia_max_diags", 0)
    monkeypatch.setattr(settings, "pallas_max_band", 16)
    y = np.asarray(sparse_tpu.csr_array(s) @ x)
    np.testing.assert_allclose(y, s @ x, rtol=1e-12)
