"""cdist tests vs scipy (reference: tests/integration/test_spatial.py)."""

import numpy as np
import pytest
import scipy.spatial.distance as sd

from sparse_tpu import spatial


@pytest.mark.parametrize("m,n,k", [(10, 7, 3), (33, 33, 8), (1, 5, 2)])
def test_cdist_euclidean(m, n, k):
    rng = np.random.default_rng(0)
    XA = rng.standard_normal((m, k))
    XB = rng.standard_normal((n, k))
    np.testing.assert_allclose(
        np.asarray(spatial.cdist(XA, XB)), sd.cdist(XA, XB), rtol=1e-10, atol=1e-12
    )


def test_cdist_sqeuclidean_cityblock():
    rng = np.random.default_rng(1)
    XA = rng.standard_normal((9, 4))
    XB = rng.standard_normal((6, 4))
    np.testing.assert_allclose(
        np.asarray(spatial.cdist(XA, XB, "sqeuclidean")),
        sd.cdist(XA, XB, "sqeuclidean"),
        rtol=1e-10,
        atol=1e-12,
    )
    np.testing.assert_allclose(
        np.asarray(spatial.cdist(XA, XB, "cityblock")),
        sd.cdist(XA, XB, "cityblock"),
        rtol=1e-10,
        atol=1e-12,
    )


def test_cdist_errors():
    with pytest.raises(ValueError):
        spatial.cdist(np.zeros((3, 2)), np.zeros((3, 4)))
    with pytest.raises(ValueError):
        spatial.cdist(np.zeros(3), np.zeros((3, 4)))
    with pytest.raises(ValueError):
        spatial.cdist(np.zeros((3, 2)), np.zeros((3, 2)), metric="cosine")
