"""MINRES / LSMR / TFQMR / QMR oracle tests.

Beyond the reference's solver menu (its linalg.py stops at lsqr/eigsh);
these close the scipy.sparse.linalg drop-in gap. Each solver follows the
repo's device-resident design (one lax.while_loop, no host syncs inside),
so the tests check converged residuals against direct/scipy solutions.
"""

import numpy as np
import pytest
import scipy.sparse as sp
import scipy.sparse.linalg as sla

import sparse_tpu as sparse
import sparse_tpu.linalg as linalg
from .utils.sample import sample_vec


def _sym_indefinite(n, seed=0):
    rng = np.random.default_rng(seed)
    S = sp.random(n, n, 0.1, random_state=rng)
    # symmetric, eigenvalues pushed to both signs -> indefinite (CG would fail)
    S = (S + S.T) * 0.5 + sp.diags(np.linspace(-2.0, 3.0, n))
    return S.tocsr()


def _nonsym(n, seed=1):
    rng = np.random.default_rng(seed)
    return (sp.random(n, n, 0.1, random_state=rng) + n * sp.identity(n)).tocsr()


def test_minres_symmetric_indefinite():
    n = 80
    S = _sym_indefinite(n)
    A = sparse.csr_array(S)
    xtrue = sample_vec(n, seed=2)
    b = np.asarray(S @ xtrue)
    x, iters = linalg.minres(A, b, tol=1e-9, maxiter=4 * n)
    assert iters > 0
    r = np.asarray(S @ np.asarray(x)) - b
    assert np.linalg.norm(r) <= 1e-5 * np.linalg.norm(b)


def test_minres_shift():
    n = 60
    S = _sym_indefinite(n, seed=3)
    A = sparse.csr_array(S)
    b = sample_vec(n, seed=4)
    shift = 0.37
    x, _ = linalg.minres(A, b, shift=shift, tol=1e-9, maxiter=6 * n)
    r = np.asarray((S - shift * sp.identity(n)) @ np.asarray(x)) - b
    assert np.linalg.norm(r) <= 1e-5 * np.linalg.norm(b)


def test_minres_zero_rhs():
    n = 30
    A = sparse.csr_array(_sym_indefinite(n, seed=5))
    x, iters = linalg.minres(A, np.zeros(n), tol=1e-8)
    assert iters == 0
    assert np.allclose(np.asarray(x), 0)


def test_lsmr_least_squares_matches_scipy():
    m, n = 100, 60
    rng = np.random.default_rng(6)
    R = (sp.random(m, n, 0.2, random_state=rng) + 2 * sp.eye(m, n)).tocsr()
    A = sparse.csr_array(R)
    b = sample_vec(m, seed=7)
    x, istop, itn, normr, normar, norma, conda, normx = linalg.lsmr(
        A, b, atol=1e-10, btol=1e-10
    )
    assert istop in (1, 2)
    assert itn > 0
    x_sci = sla.lsmr(R, b, atol=1e-10, btol=1e-10)[0]
    assert np.allclose(np.asarray(x), x_sci, atol=1e-5)
    # the returned norm estimates describe the converged state
    rvec = b - np.asarray(R @ np.asarray(x))
    assert abs(normr - np.linalg.norm(rvec)) <= 1e-3 * max(1.0, normr)


def test_lsmr_damped():
    m, n = 60, 60
    rng = np.random.default_rng(8)
    R = (sp.random(m, n, 0.15, random_state=rng) + sp.identity(n)).tocsr()
    A = sparse.csr_array(R)
    b = sample_vec(m, seed=9)
    damp = 1.5
    x = np.asarray(linalg.lsmr(A, b, damp=damp, atol=1e-10, btol=1e-10)[0])
    x_sci = sla.lsmr(R, b, damp=damp, atol=1e-10, btol=1e-10)[0]
    assert np.allclose(x, x_sci, atol=1e-5)


def test_tfqmr_nonsymmetric():
    n = 80
    N = _nonsym(n)
    A = sparse.csr_array(N)
    xtrue = sample_vec(n, seed=10)
    b = np.asarray(N @ xtrue)
    x, iters = linalg.tfqmr(A, b, tol=1e-10)
    assert iters > 0
    assert np.allclose(np.asarray(A @ x), b, atol=1e-5)


def test_qmr_nonsymmetric():
    n = 80
    N = _nonsym(n, seed=11)
    A = sparse.csr_array(N)
    xtrue = sample_vec(n, seed=12)
    b = np.asarray(N @ xtrue)
    x, iters = linalg.qmr(A, b, tol=1e-10)
    assert iters > 0
    assert np.allclose(np.asarray(A @ x), b, atol=1e-5)


@pytest.mark.parametrize("solver", ["tfqmr", "qmr"])
def test_transpose_free_solvers_match_direct(solver):
    n = 50
    N = _nonsym(n, seed=13)
    A = sparse.csr_array(N)
    b = np.asarray(N @ sample_vec(n, seed=14))
    x_sci = sla.spsolve(N.tocsc(), b)
    x = np.asarray(getattr(linalg, solver)(A, b, tol=1e-12)[0])
    assert np.allclose(x, x_sci, atol=1e-5)


def test_minres_warm_start_and_preconditioner():
    n = 80
    S = _sym_indefinite(n, seed=20)
    A = sparse.csr_array(S)
    b = np.asarray(S @ sample_vec(n, seed=21))
    # warm start at the (near-)solution must converge immediately, not
    # grind against a target scaled by the tiny ||r0|| (r3 review fix)
    x_direct = sla.spsolve(S.tocsc(), b)
    x, iters = linalg.minres(A, b, x0=x_direct, tol=1e-6)
    assert iters <= 1
    # Jacobi preconditioner (SPD M)
    Sspd = (S + 10 * sp.identity(n)).tocsr()
    M = sparse.diags([1.0 / Sspd.diagonal()], [0]).tocsr()
    xp, itp = linalg.minres(sparse.csr_array(Sspd), b, M=M, tol=1e-9)
    r = np.asarray(Sspd @ np.asarray(xp)) - b
    assert np.linalg.norm(r) <= 1e-5 * np.linalg.norm(b)


def test_lsmr_x0_warm_start():
    m, n = 80, 50
    rng = np.random.default_rng(22)
    R = (sp.random(m, n, 0.2, random_state=rng) + 2 * sp.eye(m, n)).tocsr()
    A = sparse.csr_array(R)
    b = sample_vec(m, seed=23)
    x_cold = sla.lsmr(R, b, atol=1e-10, btol=1e-10)[0]
    out = linalg.lsmr(A, b, x0=x_cold, atol=1e-8, btol=1e-8)
    assert out[2] <= 2  # itn: starts at the solution
    np.testing.assert_allclose(np.asarray(out[0]), x_cold, atol=1e-5)


def test_tfqmr_qmr_preconditioned():
    n = 80
    N = _nonsym(n, seed=24)
    A = sparse.csr_array(N)
    b = np.asarray(N @ sample_vec(n, seed=25))
    Minv = sparse.diags([1.0 / N.diagonal()], [0]).tocsr()
    x, it = linalg.tfqmr(A, b, M=Minv, tol=1e-10)
    assert np.allclose(np.asarray(A @ x), b, atol=1e-5)
    x, it = linalg.qmr(A, b, M1=Minv, tol=1e-10)
    assert np.allclose(np.asarray(A @ x), b, atol=1e-5)


def test_minres_indefinite_preconditioner_raises():
    n = 40
    S = _sym_indefinite(n, seed=26)
    A = sparse.csr_array(S)
    b = sample_vec(n, seed=27)
    Mneg = sparse.diags([-np.ones(n)], [0]).tocsr()  # b.(-I)b < 0 always
    with pytest.raises(ValueError, match="indefinite"):
        linalg.minres(A, b, M=Mneg, tol=1e-8)


def test_solvers_callback_runs_per_iteration():
    n = 50
    S = _nonsym(n, seed=28)
    A = sparse.csr_array(S)
    b = np.asarray(S @ sample_vec(n, seed=29))
    for solver, kw in ((linalg.tfqmr, {}), (linalg.qmr, {}),
                       (linalg.minres, {})):
        mat = A
        if solver is linalg.minres:
            Ssym = ((S + S.T) * 0.5 + n * sp.identity(n)).tocsr()
            mat = sparse.csr_array(Ssym)
            b2 = np.asarray(Ssym @ sample_vec(n, seed=29))
        else:
            b2 = b
        hist = []
        x, iters = solver(mat, b2, tol=1e-6, callback=lambda xk: hist.append(np.asarray(xk)), **kw)
        assert len(hist) == iters and iters > 0
        # the recorded iterates converge toward the returned solution
        assert np.allclose(hist[-1], np.asarray(x))
