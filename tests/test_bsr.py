"""BSR format vs the scipy oracle.

Beyond the reference's class surface (its coverage layer lists tobsr as a
gap): dense [R, C] blocks at block-sparse positions — the MXU-native
sparse layout (SpMV = one batched einsum matmul).
"""

import numpy as np
import pytest
import scipy.sparse as sp

import sparse_tpu as sparse
from .utils.sample import sample_csr


def _block_matrix(mb=5, nb=4, R=2, C=3, density=0.4, seed=90):
    """Random block-structured matrix as (scipy_bsr, dense)."""
    rng = np.random.default_rng(seed)
    mask = rng.random((mb, nb)) < density
    dense = np.zeros((mb * R, nb * C))
    for i in range(mb):
        for j in range(nb):
            if mask[i, j]:
                dense[i * R : (i + 1) * R, j * C : (j + 1) * C] = rng.normal(
                    size=(R, C)
                )
    return sp.bsr_array(dense, blocksize=(R, C)), dense


@pytest.mark.parametrize("blocksize", [(1, 1), (2, 3), (5, 2)])
def test_tobsr_roundtrip(blocksize):
    R, C = blocksize
    s = sample_csr(5 * R * 2, 4 * C, density=0.3, seed=91)
    s.data -= 0.4
    A = sparse.csr_array(s)
    B = A.tobsr(blocksize=blocksize)
    assert B.blocksize == blocksize
    ref = s.tobsr(blocksize=blocksize)
    np.testing.assert_allclose(B.toarray(), ref.toarray())
    assert int(B.data.shape[0]) == ref.data.shape[0]  # same block count
    np.testing.assert_allclose(
        np.asarray(B.tocsr().toarray()), s.toarray()
    )


def test_bsr_spmv_spmm():
    ref, dense = _block_matrix()
    B = sparse.bsr_array(
        (np.asarray(ref.data), ref.indices.copy(), ref.indptr.copy()),
        shape=ref.shape,
    )
    x = np.linspace(-1, 1, dense.shape[1])
    np.testing.assert_allclose(np.asarray(B @ x), dense @ x, rtol=1e-12)
    X = np.arange(dense.shape[1] * 3, dtype=np.float64).reshape(-1, 3)
    np.testing.assert_allclose(np.asarray(B @ X), dense @ X, rtol=1e-12)
    with pytest.raises(ValueError):
        B @ np.ones(3)


def test_bsr_transpose_and_conversions():
    ref, dense = _block_matrix(seed=92)
    B = sparse.bsr_array(
        (np.asarray(ref.data), ref.indices.copy(), ref.indptr.copy()),
        shape=ref.shape,
    )
    np.testing.assert_allclose(B.T.toarray(), dense.T)
    assert B.T.blocksize == (B.blocksize[1], B.blocksize[0])
    np.testing.assert_allclose(np.asarray(B.tocsc().toarray()), dense)
    np.testing.assert_allclose(np.asarray(B.tocoo().toarray()), dense)
    # stored-zero semantics: nnz counts stored values, count_nonzero real
    assert B.nnz == B.data.size
    assert B.count_nonzero() == np.count_nonzero(dense)


def test_bsr_unary_and_scalar_ops():
    ref, dense = _block_matrix(seed=93)
    B = sparse.bsr_array(
        (np.asarray(ref.data), ref.indices.copy(), ref.indptr.copy()),
        shape=ref.shape,
    )
    np.testing.assert_allclose((-B).toarray(), -dense)
    np.testing.assert_allclose(abs(B).toarray(), np.abs(dense))
    assert B.astype(np.float32).dtype == np.float32
    np.testing.assert_allclose(
        np.asarray((B + B.tocsr()).toarray()), 2 * dense
    )
    assert sparse.issparse(B)
    assert B.asformat("bsr") is B


def test_tobsr_bad_blocksize():
    A = sparse.csr_array(sample_csr(6, 6, density=0.5, seed=94))
    with pytest.raises(ValueError):
        A.tobsr(blocksize=(4, 2))
    with pytest.raises(ValueError):
        A.tobsr(blocksize=(0, 2))


def test_blocksize_estimation():
    """Review r3: blocksize=None estimates the block structure like scipy
    instead of silently defaulting to worst-case (1, 1)."""
    ref, dense = _block_matrix(mb=6, nb=6, R=3, C=3, density=0.5, seed=95)
    B = sparse.csr_array(sp.csr_array(dense)).tobsr()
    assert B.blocksize == (3, 3)
    np.testing.assert_allclose(B.toarray(), dense)
    # no block structure -> (1, 1)
    s = sample_csr(12, 12, density=0.08, seed=96)
    assert sparse.csr_array(s).tobsr().blocksize == (1, 1)


def test_bsr_triple_blocksize_validation():
    """Review r3: a blocksize argument that contradicts the data blocks
    must raise, matching scipy."""
    ref, _ = _block_matrix(seed=97)
    with pytest.raises(ValueError):
        sparse.bsr_array(
            (np.asarray(ref.data), ref.indices.copy(), ref.indptr.copy()),
            shape=ref.shape, blocksize=(1, 1),
        )
