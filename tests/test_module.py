"""Module-level constructor/predicate oracle tests vs scipy.

Reference analog: ``tests/integration/test_module.py`` (kron, diagonal, sum
over formats) plus the constructor surface (diags/spdiags/eye/identity/
random/rand) from ``sparse/module.py``.
"""

import numpy as np
import pytest
import scipy.io as sci_io
import scipy.sparse as scpy

import sparse_tpu as sparse
from .utils.common import test_mtx_files
from .utils.sample import sample_csr


@pytest.mark.parametrize("filename", test_mtx_files)
@pytest.mark.parametrize("format", ["csr", "csc", "coo"])
def test_kron(filename, format):
    arr = sparse.io.mmread(filename).asformat(format)
    s = sci_io.mmread(filename).asformat(format)
    rolled = np.roll(np.asarray(arr.todense()), 1)
    res = sparse.kron(arr, sparse.coo_array(rolled), format=format)
    res_sci = scpy.kron(s, np.roll(np.asarray(s.todense()), 1), format=format)
    assert res.format == format
    assert np.allclose(np.asarray(res.todense()), np.asarray(res_sci.todense()))


@pytest.mark.parametrize("filename", test_mtx_files)
@pytest.mark.parametrize("k", [-1, 0, 2])
@pytest.mark.parametrize("format", ["coo", "csr", "csc"])
def test_diagonal(filename, k, format):
    arr = sparse.io.mmread(filename).asformat(format)
    s = sci_io.mmread(filename).asformat(format)
    assert np.allclose(np.asarray(arr.diagonal(k=k)), s.todia().diagonal(k=k))


@pytest.mark.parametrize("filename", test_mtx_files)
@pytest.mark.parametrize("format", ["coo", "csr", "csc"])
@pytest.mark.parametrize("axis", [None, 0, 1])
def test_sum(filename, format, axis):
    arr = sparse.io.mmread(filename).asformat(format)
    s = sci_io.mmread(filename).asformat(format)
    got = np.asarray(arr.sum(axis=axis))
    exp = np.asarray(s.sum(axis=axis)).squeeze()
    assert np.allclose(got, exp)


@pytest.mark.parametrize("offsets", [0, [0], [-1, 0, 2]])
@pytest.mark.parametrize("format", [None, "csr", "dia"])
def test_diags(offsets, format):
    n = 9
    if isinstance(offsets, list):
        diagonals = [np.arange(1.0, n + 1)[: n - abs(o)] for o in offsets]
    else:  # scalar offset: scipy requires the bare 1-D diagonal
        diagonals = np.arange(1.0, n + 1)
    got = sparse.diags(diagonals, offsets, format=format)
    exp = scpy.diags(diagonals, offsets, format=format)
    assert np.allclose(np.asarray(got.todense()), exp.todense())


def test_spdiags():
    data = np.array([[1, 2, 3, 4.0], [1, 2, 3, 4], [1, 2, 3, 4]])
    diags_offsets = np.array([0, -1, 2])
    got = sparse.spdiags(data, diags_offsets, 4, 4)
    exp = scpy.spdiags(data, diags_offsets, 4, 4)
    assert np.allclose(np.asarray(got.todense()), exp.todense())


@pytest.mark.parametrize("m,n,k", [(5, 5, 0), (5, 7, 0), (7, 5, -2), (5, 7, 3)])
def test_eye(m, n, k):
    got = sparse.eye(m, n, k=k)
    exp = scpy.eye(m, n, k=k)
    assert np.allclose(np.asarray(got.todense()), exp.todense())


def test_identity():
    got = sparse.identity(6, dtype=np.float32)
    assert got.dtype == np.float32
    assert np.allclose(np.asarray(got.todense()), np.eye(6))


@pytest.mark.parametrize("format", ["coo", "csr", "csc"])
def test_random(format):
    a = sparse.random(30, 20, density=0.2, format=format, random_state=7)
    assert a.shape == (30, 20)
    assert a.format == format
    dense = np.asarray(a.todense())
    frac = np.count_nonzero(dense) / dense.size
    assert 0.05 < frac <= 0.3


def test_rand():
    a = sparse.rand(10, 10, density=0.5, random_state=3)
    dense = np.asarray(a.todense())
    assert np.all(dense >= 0)


def test_predicates():
    c = sparse.csr_array(sample_csr(4, 4, seed=89))
    assert sparse.issparse(c)
    assert sparse.isspmatrix(c)
    assert sparse.isspmatrix_csr(c)
    assert not sparse.isspmatrix_csc(c)
    assert sparse.isspmatrix_csc(c.tocsc())
    assert sparse.isspmatrix_coo(c.tocoo())
    assert sparse.isspmatrix_dia(sparse.eye(4, format="dia"))
    assert not sparse.issparse(np.zeros((3, 3)))


def test_csr_matrix_alias():
    """scipy-compat aliases exist and build the same objects."""
    s = sample_csr(5, 5, seed=90)
    assert isinstance(sparse.csr_matrix(s), sparse.csr_array)
    assert isinstance(sparse.csc_matrix(s.tocsc()), sparse.csc_array)
    assert isinstance(sparse.coo_matrix(s.tocoo()), sparse.coo_array)
