"""DOK and LIL host staging formats vs the scipy oracle.

Beyond the reference's class surface (its coverage layer lists
todok/tolil as gaps): incremental construction formats converted once for
device compute.
"""

import numpy as np
import pytest
import scipy.sparse as sp

import sparse_tpu as sparse
from .utils.sample import sample_csr


def _pair(m=7, n=5, density=0.3, seed=80):
    s = sample_csr(m, n, density=density, seed=seed)
    s.data -= 0.4
    return sparse.csr_array(s), s


def test_dok_roundtrip_and_indexing():
    A, s = _pair()
    D = A.todok()
    Ds = s.todok()
    assert D.nnz == Ds.nnz
    np.testing.assert_allclose(D.toarray(), s.toarray())
    # scalar reads incl. implicit zeros and negative indices
    for i in range(s.shape[0]):
        for j in range(s.shape[1]):
            assert np.isclose(D[i, j], s.toarray()[i, j])
    assert np.isclose(D[-1, -1], s.toarray()[-1, -1])
    # mutation: set, overwrite, delete-via-zero
    D[0, 0] = 3.5
    D[0, 1] = 0.0
    ref = s.toarray()
    ref[0, 0] = 3.5
    ref[0, 1] = 0.0
    np.testing.assert_allclose(D.toarray(), ref)
    np.testing.assert_allclose(np.asarray(D.tocsr().toarray()), ref)
    with pytest.raises(IndexError):
        D[99, 0]


def test_dok_incremental_build():
    D = sparse.dok_array((4, 6), dtype=np.float64)
    ref = np.zeros((4, 6))
    rng = np.random.default_rng(81)
    for _ in range(30):
        i, j = rng.integers(0, 4), rng.integers(0, 6)
        v = float(rng.normal())
        D[i, j] = v
        ref[i, j] = v
    np.testing.assert_allclose(D.toarray(), ref)
    C = D.tocsr()
    np.testing.assert_allclose(np.asarray(C.toarray()), ref)
    # dict surface
    assert set(D.keys()) == {tuple(map(int, k)) for k in zip(*np.nonzero(ref))}
    assert (0, 0) in D or ref[0, 0] == 0


def test_lil_roundtrip_and_rows():
    A, s = _pair(seed=82)
    L = A.tolil()
    Ls = s.tolil()
    assert L.nnz == Ls.nnz
    np.testing.assert_allclose(L.toarray(), s.toarray())
    # row read/write
    np.testing.assert_allclose(L[2], s.toarray()[2])
    newrow = np.zeros(s.shape[1])
    newrow[::2] = 2.0
    L[2] = newrow
    ref = s.toarray()
    ref[2] = newrow
    np.testing.assert_allclose(L.toarray(), ref)
    np.testing.assert_allclose(np.asarray(L.tocsr().toarray()), ref)
    # scalar set keeps columns sorted
    L[0, 4] = 9.0
    L[0, 1] = 9.0
    ref[0, 4] = 9.0
    ref[0, 1] = 9.0
    np.testing.assert_allclose(L.toarray(), ref)
    assert L.rows[0] == sorted(L.rows[0])


def test_dok_lil_math_delegates():
    A, s = _pair(m=6, n=6, seed=83)
    x = np.arange(6, dtype=np.float64)
    for fmt in ("todok", "tolil"):
        F = getattr(A, fmt)()
        np.testing.assert_allclose(np.asarray(F @ x), s @ x)
        np.testing.assert_allclose(
            np.asarray((F + A).toarray()), (s + s).toarray()
        )
        np.testing.assert_allclose(
            np.asarray(F.multiply(F).toarray()), s.multiply(s).toarray()
        )
        assert np.isclose(float(np.asarray(F.sum())), s.sum())
        assert sparse.issparse(F)


def test_asformat_dok_lil():
    A, s = _pair(seed=84)
    assert A.asformat("dok").format == "dok"
    assert A.asformat("lil").format == "lil"
    np.testing.assert_allclose(
        np.asarray(A.asformat("dok").tocsc().toarray()), s.toarray()
    )
    # transpose round trips
    np.testing.assert_allclose(A.todok().T.toarray(), s.toarray().T)
    np.testing.assert_allclose(A.tolil().T.toarray(), s.toarray().T)


def test_dok_sums_duplicate_coo():
    """Review r3: a duplicate-holding COO must SUM into DOK like tocsr."""
    C = sparse.coo_array(
        (np.array([2.0, 3.0]), (np.array([1, 1]), np.array([1, 1]))),
        shape=(3, 3),
    )
    D = C.todok()
    assert np.isclose(D[1, 1], 5.0)


def test_shape_override_validation():
    dense = np.arange(9.0).reshape(3, 3)
    with pytest.raises(ValueError):
        sparse.dok_array(dense, shape=(2, 2))
    with pytest.raises(ValueError):
        sparse.lil_array(dense, shape=(2, 2))
    # growing is fine
    L = sparse.lil_array(dense, shape=(5, 3))
    assert L.shape == (5, 3) and L.nnz == 8
    D = sparse.dok_array(dense, shape=(5, 4))
    assert D.shape == (5, 4) and D.nnz == 8


def test_dok_lil_generic_unary_ops():
    """Review r3: neg/abs/conj/astype run through the SparseArray hooks."""
    s = sp.csr_array(np.array([[1.0, -2.0], [0.0, 3.0]]))
    A = sparse.csr_array(s)
    for fmt in ("todok", "tolil"):
        F = getattr(A, fmt)()
        np.testing.assert_allclose((-F).toarray(), -s.toarray())
        np.testing.assert_allclose(abs(F).toarray(), np.abs(s.toarray()))
        assert F.astype(np.float32).dtype == np.float32
        np.testing.assert_allclose(
            F.astype(np.float32).toarray(), s.toarray().astype(np.float32)
        )
        np.testing.assert_allclose((F - F).toarray() if hasattr(F - F, "toarray") else np.asarray((F - F).toarray()), np.zeros((2, 2)))
