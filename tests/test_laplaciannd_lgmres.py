"""LaplacianNd / lgmres / gcrotmk / ARPACK-alias oracle tests
(scipy.sparse.linalg drop-in surface, round 3)."""

import numpy as np
import pytest
import scipy.sparse as sp
import scipy.sparse.linalg as sla

import sparse_tpu as sparse
import sparse_tpu.linalg as linalg
from .utils.sample import sample_vec


@pytest.mark.parametrize("bc", ["dirichlet", "neumann", "periodic"])
@pytest.mark.parametrize("grid", [(7,), (3, 4), (2, 3, 4)])
def test_laplaciannd_matches_scipy(bc, grid):
    L = linalg.LaplacianNd(grid, boundary_conditions=bc)
    Ls = sla.LaplacianNd(grid, boundary_conditions=bc)
    ref = Ls.toarray().astype(np.float64)
    # assembled matrix
    np.testing.assert_allclose(
        np.asarray(L.tosparse().todense()), ref, atol=1e-12
    )
    np.testing.assert_allclose(L.toarray(), Ls.toarray(), atol=0)
    # matvec (the fused stencil path) vs assembly
    n = int(np.prod(grid))
    import zlib
    v = sample_vec(n, seed=zlib.crc32(repr((bc, grid)).encode()) % 1000)
    np.testing.assert_allclose(
        np.asarray(L.matvec(v)), ref @ v, rtol=1e-5, atol=1e-5
    )
    # analytic eigenvalues vs scipy's
    np.testing.assert_allclose(
        L.eigenvalues(), Ls.eigenvalues(), atol=1e-10
    )
    np.testing.assert_allclose(
        L.eigenvalues(3), Ls.eigenvalues(3), atol=1e-10
    )
    # eigenvectors satisfy the eigen-equation for the matching values
    m = 3
    lam = L.eigenvalues(m)
    V = L.eigenvectors(m)
    R = ref @ V - V * lam[None, :]
    assert np.abs(R).max() <= 1e-8


def test_laplaciannd_rejects_bad_bc():
    with pytest.raises(ValueError):
        linalg.LaplacianNd((4, 4), boundary_conditions="robin")


def _nonsym(n, seed):
    rng = np.random.default_rng(seed)
    return (sp.random(n, n, 0.1, random_state=rng)
            + n * sp.identity(n)).tocsr()


@pytest.mark.parametrize("solver", ["lgmres", "gcrotmk"])
def test_augmented_krylov_solvers(solver):
    n = 120
    S = _nonsym(n, seed=40)
    A = sparse.csr_array(S)
    b = np.asarray(S @ sample_vec(n, seed=41))
    fn = getattr(linalg, solver)
    x, info = fn(A, b, tol=1e-10, inner_m=15) if solver == "lgmres" else fn(
        A, b, tol=1e-10, m=15, k=5
    )
    assert info == 0
    assert np.allclose(np.asarray(A @ x), b, atol=1e-5)
    x_sci = sla.spsolve(S.tocsc(), b)
    assert np.allclose(np.asarray(x), x_sci, atol=1e-4)


def test_lgmres_beats_plain_restart_on_stagnating_system():
    """The augmentation must help where tight restarts stagnate: a
    strongly nonnormal system with small restart length."""
    n = 100
    rng = np.random.default_rng(42)
    S = (sp.diags(np.linspace(1, 2, n))
         + sp.diags(np.full(n - 1, 1.0), 1)).tocsr()
    A = sparse.csr_array(S)
    b = np.asarray(S @ rng.standard_normal(n))
    x, info = linalg.lgmres(A, b, tol=1e-8, inner_m=5, outer_k=3,
                            maxiter=200)
    assert info == 0
    assert np.allclose(np.asarray(A @ x), b, atol=1e-4)


def test_gcrotmk_truncate_validation_and_callback():
    n = 60
    S = _nonsym(n, seed=43)
    A = sparse.csr_array(S)
    b = np.asarray(S @ sample_vec(n, seed=44))
    with pytest.raises(ValueError):
        linalg.gcrotmk(A, b, truncate="newest")
    hist = []
    x, info = linalg.gcrotmk(A, b, tol=1e-8, m=10, k=4,
                             callback=lambda xk: hist.append(1))
    assert info == 0 and len(hist) >= 1


def test_arpack_aliases_and_use_solver():
    e = linalg.ArpackNoConvergence("no conv", eigenvalues=[1.0])
    assert isinstance(e, linalg.ArpackError)
    assert e.eigenvalues == [1.0] and e.eigenvectors == []
    assert issubclass(linalg.MatrixRankWarning, UserWarning)
    linalg.use_solver(useUmfpack=False)  # accepted no-op


def test_laplaciannd_size_one_axes_and_m_zero():
    """Size-1 axes: matvec, tosparse and the analytic eigenpairs must
    agree with each other (scipy's own toarray/eigenvalues DISAGREE for
    neumann/periodic size-1 axes — documented deviation; its eigenvalues
    match ours, its matrix does not)."""
    for bc in ("dirichlet", "neumann", "periodic"):
        L = linalg.LaplacianNd((1, 4), boundary_conditions=bc)
        dense = np.asarray(L.tosparse().todense())
        v = sample_vec(4, seed=50)
        np.testing.assert_allclose(
            np.asarray(L.matvec(v)), dense @ v, rtol=1e-5, atol=1e-6
        )
        # internal eigen-consistency of the assembled matrix
        np.testing.assert_allclose(
            np.sort(np.linalg.eigvalsh(dense)), L.eigenvalues(),
            atol=1e-8,
        )
        # scipy's analytic eigenvalues agree with ours
        ref = sla.LaplacianNd((1, 4), boundary_conditions=bc)
        np.testing.assert_allclose(
            L.eigenvalues(), ref.eigenvalues(), atol=1e-10
        )
    L = linalg.LaplacianNd((5, 5))
    assert L.eigenvalues(0).shape == (0,)
    assert L.eigenvectors(0).shape == (25, 0)


def test_lgmres_small_system_default_inner_m():
    """inner_m (default 30) must clamp to n on small systems (r3 review:
    the wide-AZ block crashed QR+solve)."""
    n = 12
    S = _nonsym(n, seed=45)
    A = sparse.csr_array(S)
    b = np.asarray(S @ sample_vec(n, seed=46))
    x, info = linalg.lgmres(A, b, tol=1e-10)
    assert info == 0
    assert np.allclose(np.asarray(A @ x), b, atol=1e-5)
    x, info = linalg.gcrotmk(A, b, tol=1e-10)  # default m=20 > n
    assert info == 0
    assert np.allclose(np.asarray(A @ x), b, atol=1e-5)


def test_lgmres_outer_k_zero_is_plain_restart():
    n = 40
    S = _nonsym(n, seed=47)
    A = sparse.csr_array(S)
    b = np.asarray(S @ sample_vec(n, seed=48))
    x, info = linalg.lgmres(A, b, tol=1e-9, inner_m=10, outer_k=0,
                            maxiter=100)
    assert info == 0
    assert np.allclose(np.asarray(A @ x), b, atol=1e-5)


def test_gcrotmk_truncate_smallest_converges():
    n = 90
    S = _nonsym(n, seed=49)
    A = sparse.csr_array(S)
    b = np.asarray(S @ sample_vec(n, seed=50))
    x, info = linalg.gcrotmk(A, b, tol=1e-9, m=8, k=3,
                             truncate="smallest")
    assert info == 0
    assert np.allclose(np.asarray(A @ x), b, atol=1e-5)
