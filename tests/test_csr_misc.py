"""CSR misc surface: balance, transpose, diagonal.

Reference analog: ``tests/integration/test_csr_misc.py``.
"""

import numpy as np
import pytest
import scipy.io as sci_io

import sparse_tpu as sparse
from .utils.common import test_mtx_files


@pytest.mark.parametrize("filename", test_mtx_files)
def test_balance_row_partitions(filename):
    arr = sparse.io.mmread(filename).tocsr()
    arr.balance()
    s = sci_io.mmread(filename).tocsr()
    vec = np.random.default_rng(3).random(arr.shape[1])
    assert np.allclose(np.asarray(arr @ vec), s @ vec)
    mat = np.random.default_rng(4).random((arr.shape[1], 2))
    assert np.allclose(np.asarray(arr @ mat), s @ mat)


@pytest.mark.parametrize("filename", test_mtx_files)
def test_csr_transpose(filename):
    arr = sparse.io.mmread(filename).tocsr().T
    s = sci_io.mmread(filename).tocsr().T
    assert np.allclose(np.asarray(arr.todense()), np.asarray(s.todense()))
    # transpose of the transpose round-trips
    assert np.allclose(
        np.asarray(arr.T.todense()), np.asarray(s.T.todense())
    )


@pytest.mark.parametrize("filename", test_mtx_files)
def test_csr_diagonal_default(filename):
    arr = sparse.io.mmread(filename).tocsr()
    s = sci_io.mmread(filename).tocsr()
    assert np.allclose(np.asarray(arr.diagonal()), s.diagonal())
