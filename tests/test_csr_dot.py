"""SpMV/SpMM correctness vs the scipy oracle.

Reference analog: ``tests/integration/test_csr_dot.py:29-46`` (incl. the
col-split spmv_domain_part axis) and ``test_csr_spmm.py``.
"""

import numpy as np
import pytest
import scipy.io as sci_io

import sparse_tpu as sparse
from .utils.common import test_mtx_files, types
from .utils.sample import sample_csr, sample_dense, sample_vec


@pytest.mark.parametrize("filename", test_mtx_files)
def test_csr_dot_vec_mtx(filename):
    arr = sparse.io.mmread(filename).tocsr()
    s = sci_io.mmread(filename).tocsr()
    vec = np.random.default_rng(0).random((arr.shape[1],))
    assert np.allclose(np.asarray(arr @ vec), s @ vec)


@pytest.mark.parametrize("dtype", types)
def test_csr_dot_vec_dtype(dtype):
    s = sample_csr(31, 17, dtype=dtype, seed=3)
    arr = sparse.csr_array(s)
    vec = sample_vec(17, dtype=dtype, seed=7)
    assert np.allclose(np.asarray(arr @ vec), s @ vec, atol=1e-5)


@pytest.mark.parametrize("filename", test_mtx_files)
def test_csr_dot_vec_domain_part(filename):
    """The reference's spmv_domain_part=True axis
    (tests/integration/test_csr_dot.py:27-35): the contraction-split kernel
    must match scipy."""
    arr = sparse.io.mmread(filename).tocsr()
    s = sci_io.mmread(filename).tocsr()
    vec = np.random.default_rng(0).random((arr.shape[1],))
    got = arr.dot(vec, spmv_domain_part=True)
    assert np.allclose(np.asarray(got), s @ vec)


@pytest.mark.parametrize("dtype", types)
def test_csr_dot_domain_part_dtype(dtype):
    s = sample_csr(31, 17, dtype=dtype, seed=3)
    arr = sparse.csr_array(s)
    vec = sample_vec(17, dtype=dtype, seed=7)
    got = arr.dot(vec, spmv_domain_part=True)
    assert np.allclose(np.asarray(got), s @ vec, atol=1e-5)


@pytest.mark.parametrize("dtype", types)
def test_csr_spmm(dtype):
    s = sample_csr(19, 23, dtype=dtype, seed=5)
    arr = sparse.csr_array(s)
    B = sample_dense(23, 11, dtype=dtype, seed=8)
    assert np.allclose(np.asarray(arr @ B), s @ B, atol=1e-5)


def test_csr_rdot():
    s = sample_csr(13, 9, seed=1)
    arr = sparse.csr_array(s)
    B = sample_dense(7, 13, seed=2)
    assert np.allclose(np.asarray(B @ arr), B @ s)
    v = sample_vec(13, seed=4)
    assert np.allclose(np.asarray(v @ arr), v @ s)


def test_csr_dot_ell_vs_segment(monkeypatch):
    """The padded-row fast path must agree with the segment path exactly."""
    from sparse_tpu.config import settings

    s = sample_csr(40, 40, density=0.2, seed=11)
    vec = sample_vec(40, seed=12)
    monkeypatch.setattr(settings, "spmv_mode", "segment")
    y_seg = np.asarray(sparse.csr_array(s) @ vec)
    monkeypatch.setattr(settings, "spmv_mode", "ell")
    y_ell = np.asarray(sparse.csr_array(s) @ vec)
    assert np.allclose(y_seg, y_ell)
    assert np.allclose(y_seg, s @ vec)


def test_csc_dot():
    s = sample_csr(21, 15, seed=9).tocsc()
    arr = sparse.csc_array(s)
    vec = sample_vec(15, seed=10)
    assert np.allclose(np.asarray(arr @ vec), s @ vec)
    B = sample_dense(15, 6, seed=13)
    assert np.allclose(np.asarray(arr @ B), s @ B)
    C = sample_dense(5, 21, seed=14)
    assert np.allclose(np.asarray(C @ arr), C @ s)


def test_empty_rows():
    """More shards than rows / empty-row discipline (SURVEY §4)."""
    import scipy.sparse as sp

    s = sp.csr_matrix(
        (np.array([1.0, 2.0]), np.array([1, 3]), np.array([0, 0, 2, 2, 2, 2])),
        shape=(5, 4),
    )
    arr = sparse.csr_array(s)
    vec = np.arange(4, dtype=np.float64)
    assert np.allclose(np.asarray(arr @ vec), s @ vec)
