"""Structural collective cost models (VERDICT r4 weak-scaling depth work).

``sort_comm_stats`` / ``spgemm2d_comm_stats`` predict the alltoallv-shaped
traffic of the samplesort and the 2-D SpGEMM shuffle from the algorithm
alone. These tests pin the models to the device implementations on the
virtual 8-device mesh: conservation laws, exact agreement with the on-device
send accounting, and the weak-scaling shape (per-shard bytes tracking the
workload, not the mesh size).
"""

import numpy as np
import pytest

import sparse_tpu
from sparse_tpu.parallel.mesh import get_mesh, get_mesh_2d
from sparse_tpu.parallel.sort import _sample_phase1, dist_sort_sample, sort_comm_stats
from sparse_tpu.parallel.spgemm import (
    LAST_STATS,
    dist_spgemm_2d,
    spgemm2d_comm_stats,
)

pytestmark = pytest.mark.quick


def _random_csr(m, n, density, seed):
    rng = np.random.default_rng(seed)
    nnz = max(int(m * n * density), 1)
    rows = rng.integers(0, m, nnz)
    cols = rng.integers(0, n, nnz)
    vals = rng.standard_normal(nnz).astype(np.float32)
    order = np.lexsort((cols, rows))
    rows, cols, vals = rows[order], cols[order], vals[order]
    keep = np.concatenate([[True], (rows[1:] != rows[:-1]) | (cols[1:] != cols[:-1])])
    rows, cols, vals = rows[keep], cols[keep], vals[keep]
    indptr = np.zeros(m + 1, np.int64)
    np.add.at(indptr, rows + 1, 1)
    return sparse_tpu.csr_array.from_parts(vals, cols, np.cumsum(indptr), (m, n))


def test_sort_model_conservation_and_phase1_agreement():
    S = 8
    rng = np.random.default_rng(7)
    n = 128 * S
    keys = rng.integers(0, 1 << 16, n).astype(np.int64)
    stats = sort_comm_stats(keys, S, payloads=(np.ones(n, np.float32),))
    assert stats["S"] == S and stats["L"] == n // S
    assert stats["sample_allgather_bytes_per_shard"] == S * S * 8
    assert stats["host_sync_bytes"] == S * S * 4

    # the model's bucketing arithmetic must MATCH the device phase-1 run
    mesh = get_mesh(S)
    import jax.numpy as jnp

    phase1 = _sample_phase1(mesh, mesh.axis_names[0], S, 0)
    out = phase1(jnp.asarray(keys))
    send_dev = np.asarray(out[1])  # [S, S]
    assert send_dev.sum() == n
    # rebuild the model's send matrix the same way the function does
    L = n // S
    ks = np.sort(keys.reshape(S, L), axis=1, kind="stable")
    pos = np.clip([(j + 1) * L // (S + 1) for j in range(S)], 0, L - 1)
    splitters = np.sort(ks[:, pos].reshape(-1), kind="stable")[np.arange(1, S) * S]
    send_model = np.empty((S, S), np.int64)
    for s in range(S):
        b = np.searchsorted(ks[s], splitters, side="left")
        send_model[s] = np.diff(np.concatenate([[0], b, [L]]))
    np.testing.assert_array_equal(send_model, send_dev)
    off = send_model.sum(axis=1) - np.diag(send_model)
    assert stats["bucket_entries_sent_max"] == off.max()
    # uniform random keys: no capacity fallback, and the real sort agrees
    assert not stats["fallback_odd_even"]
    ks_out, _ = dist_sort_sample(jnp.asarray(keys), (), mesh=mesh)
    np.testing.assert_array_equal(np.asarray(ks_out), np.sort(keys, kind="stable"))


def test_sort_model_duplicate_flood_predicts_fallback():
    S = 8
    n = 64 * S
    keys = np.zeros(n, np.int64)  # every key identical: one bucket gets all
    stats = sort_comm_stats(keys, S)
    assert stats["fallback_odd_even"]


def test_sort_model_weak_scaling_shape():
    """Constant per-shard load: exchange bytes/shard must stay ~flat in S
    (the alltoallv weak-scaling signature), sample volume grows as S^2."""
    rng = np.random.default_rng(11)
    L = 256
    per_shard = []
    for S in (2, 4, 8, 16):
        keys = rng.integers(0, 1 << 20, L * S).astype(np.int64)
        st = sort_comm_stats(keys, S)
        per_shard.append(st["exchange_bytes_per_shard_max"])
        assert st["sample_allgather_bytes_per_shard"] == S * S * 8
    # max per-shard exchange is bounded by the 2L capacity both ways
    assert max(per_shard) <= 2 * (2 * L) * 8


def test_spgemm2d_model_exact_vs_device():
    gx, gy = 4, 2
    A = _random_csr(96, 64, 0.06, 1)
    B = _random_csr(64, 80, 0.06, 2)
    stats = spgemm2d_comm_stats(A, B, (gx, gy))
    Cref = (A @ B).tocsr()
    assert stats["c_nnz"] == Cref.nnz
    assert stats["tile_nnz_max"] <= Cref.nnz
    assert stats["shuffle_entries_sent_max"] <= stats["tile_nnz_max"]

    mesh2d = get_mesh_2d(gx * gy)
    assert mesh2d.devices.shape == (gx, gy)
    C = dist_spgemm_2d(A, B, mesh2d=mesh2d)
    assert C.nnz == Cref.nnz
    # the model's capacity bucket must equal the one the device run sized
    assert stats["exchange_cap_entries"] == LAST_STATS["cap"]


def test_spgemm2d_model_weak_scaling_shape():
    """Replication bytes per device shrink as the grid grows (each device
    holds 1/gx of A + 1/gy of B) — the 2-D layout's defining property."""
    A = _random_csr(128, 128, 0.08, 3)
    r11 = spgemm2d_comm_stats(A, A, (1, 1))["replicate_bytes_per_device"]
    r22 = spgemm2d_comm_stats(A, A, (2, 2))["replicate_bytes_per_device"]
    r42 = spgemm2d_comm_stats(A, A, (4, 2))["replicate_bytes_per_device"]
    assert r22 < r11 and r42 < r22
    # a (1,1) grid shuffles nothing
    assert spgemm2d_comm_stats(A, A, (1, 1))["shuffle_entries_sent_max"] == 0


def test_sort_model_s64_stays_capacity_bounded():
    """S=64 at constant L: the host-only model needs no mesh, so the
    64-shard weak-scaling claim is test-pinned directly — per-shard
    exchange stays under the 2L capacity bound and uniform keys never
    trip the odd-even fallback."""
    rng = np.random.default_rng(64)
    L = 4096
    st = sort_comm_stats(rng.integers(0, 1 << 24, L * 64).astype(np.int64), 64)
    assert not st["fallback_odd_even"]
    assert st["bucket_entries_sent_max"] <= 2 * L
    assert st["restore_entries_sent_max"] <= 2 * L
