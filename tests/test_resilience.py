"""Bastion resilience subsystem (ISSUE 5): fault injector, failover
registry, recovery policy engine, resilient SolveSession.

The two load-bearing contracts:

* **Zero overhead when off** — with ``SPARSE_TPU_FAULTS`` unset the
  injection machinery must change NOTHING: no operator wrapper, jaxpr
  byte-identical, bitwise-identical solver results, no extra host syncs.
* **Bounded, observable recovery** — under seeded injection every
  solver (and a ``SolveSession`` batch) converges through the retry
  ladder, emitting the ``fault.injected -> solver.retry ->
  solver.recovered`` chains the chaos gate asserts.
"""

import importlib.util
import os
import time

import jax
import numpy as np
import pytest
import scipy.sparse as sp

import sparse_tpu
from sparse_tpu import linalg, telemetry
from sparse_tpu.batch import (
    SolveSession,
    TicketDeadlineError,
    TicketFailedError,
    TicketState,
)
from sparse_tpu.config import settings
from sparse_tpu.resilience import (
    FaultSpecError,
    Preempted,
    RecoveryPolicy,
    failover,
    faults,
    solve_with_recovery,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state(tmp_path):
    """Every test starts and ends fault-free with a scratch telemetry
    sink (never the committed session log)."""
    faults.clear()
    failover.clear()
    old_tel = settings.telemetry
    telemetry.configure(str(tmp_path / "records.jsonl"))
    telemetry.reset()
    yield
    faults.clear()
    failover.clear()
    settings.telemetry = old_tel
    telemetry.configure(None)
    telemetry.reset()


def _spd(n=48, seed=0):
    rng = np.random.default_rng(seed)
    e = np.ones(n)
    A = sp.diags([-e[:-1], 3.0 * e, -e[:-1]], [-1, 0, 1], format="csr")
    A = A.copy()
    A.setdiag(3.0 + rng.random(n))
    A.sort_indices()
    return A


def _stack(n=48, B=4, seed=0):
    rng = np.random.default_rng(seed)
    mats = []
    for _ in range(B):
        A = _spd(n)
        A.setdiag(3.0 + rng.random(n))
        mats.append(A.tocsr())
    return mats, rng.standard_normal((B, n))


# ---------------------------------------------------------------------------
# fault spec grammar
# ---------------------------------------------------------------------------
def test_spec_parse_basic():
    (c,) = faults.parse_spec("nonfinite:matvec:p=0.01,seed=7")
    assert c.fault == "nonfinite" and c.site == "matvec"
    assert c.p == 0.01 and c.seed == 7 and c.n is None


def test_spec_parse_defaults_and_multi():
    cs = faults.parse_spec(
        " fail:pallas:kernel=sell_spmv,n=1 ; drop:dispatch ;"
        " preempt:chunk:p=0.5 ;"
    )
    assert [c.site for c in cs] == ["pallas", "dispatch", "chunk"]
    assert cs[0].kernel == "sell_spmv" and cs[0].n == 1 and cs[0].p == 1.0
    assert cs[1].fault == "drop" and cs[1].seed == 0
    assert cs[2].p == 0.5


@pytest.mark.parametrize("bad", [
    "nonfinite",  # no site
    "nonfinite:pallas",  # fault/site mismatch
    "nan:matvec",  # unknown fault
    "nonfinite:matvec:p=nope",  # bad value
    "nonfinite:matvec:p",  # not key=value
    "nonfinite:matvec:p=2",  # p outside [0, 1]
])
def test_spec_parse_errors(bad):
    with pytest.raises(FaultSpecError):
        faults.parse_spec(bad)


def test_spec_env_roundtrip(monkeypatch):
    monkeypatch.setenv("SPARSE_TPU_FAULTS", "inf:matvec:p=0.25,seed=9")
    faults.reload_from_env()
    assert faults.ACTIVE and faults.targets("matvec")
    monkeypatch.delenv("SPARSE_TPU_FAULTS")
    faults.reload_from_env()
    assert not faults.ACTIVE


# ---------------------------------------------------------------------------
# injector behavior
# ---------------------------------------------------------------------------
def test_corrupt_array_deterministic_and_pure():
    a = np.ones(64)
    faults.configure("nonfinite:matvec:p=0.5,seed=42")
    outs1 = [faults.corrupt_array(a) for _ in range(8)]
    faults.configure("nonfinite:matvec:p=0.5,seed=42")
    outs2 = [faults.corrupt_array(a) for _ in range(8)]
    for o1, o2 in zip(outs1, outs2):
        np.testing.assert_array_equal(o1, o2)
    assert np.isfinite(a).all(), "input must never be mutated"
    assert any(np.isnan(o).any() for o in outs1)


def test_corrupt_kinds_and_budget():
    a = np.ones(16)
    faults.configure("inf:matvec:p=1,n=1")
    o1 = faults.corrupt_array(a)
    o2 = faults.corrupt_array(a)
    assert np.isinf(o1).any() and np.isfinite(o2).all()  # n=1 budget
    faults.configure("bitflip:matvec:p=1,scale=1e6")
    o3 = faults.corrupt_array(a)
    assert o3.max() == pytest.approx(1e6)


def test_injection_events_and_counters():
    settings.telemetry = True
    before = telemetry.metrics.counter("faults.injected").value
    faults.configure("nonfinite:matvec:p=1,seed=0")
    faults.corrupt_array(np.ones(4))
    evs = telemetry.events("fault.injected")
    assert evs and evs[-1]["site"] == "matvec"
    assert evs[-1]["fault"] == "nonfinite"
    assert not telemetry.schema.validate(evs[-1])
    assert telemetry.metrics.counter("faults.injected").value == before + 1


def test_suspended_context():
    faults.configure("nonfinite:matvec:p=1")
    with faults.suspended():
        assert np.isfinite(faults.corrupt_array(np.ones(4))).all()
    assert np.isnan(faults.corrupt_array(np.ones(4))).any()


def test_preempt_draws_and_raises():
    faults.configure("preempt:chunk:p=1,n=2")
    with pytest.raises(Preempted):
        faults.check_preempt("test.site")
    with pytest.raises(Preempted):
        faults.check_preempt("test.site")
    faults.check_preempt("test.site")  # budget exhausted: no raise


# ---------------------------------------------------------------------------
# zero overhead / zero code-path change when off
# ---------------------------------------------------------------------------
def test_zero_overhead_when_off():
    S = _spd()
    A = sparse_tpu.csr_array(S)
    b = np.random.default_rng(1).standard_normal(S.shape[0])

    def jaxpr_of():
        op = linalg.make_linear_operator(A)
        assert not getattr(op, "_fault_wrapped", False)
        return str(jax.make_jaxpr(op.matvec)(b))

    # baseline BEFORE any injector has ever been configured this test
    x_ref, it_ref = linalg.cg(A, b, tol=1e-10)
    jaxpr_ref = jaxpr_of()
    linalg.HOST_SYNCS = 0
    linalg.gmres(A, b, tol=1e-10)
    syncs_ref = linalg.HOST_SYNCS

    # configure + clear an injector: traces and results must be
    # BYTE-identical afterwards — no residue of the machinery
    faults.configure("nonfinite:matvec:p=1;fail:pallas;drop:dispatch")
    faults.clear()
    assert jaxpr_of() == jaxpr_ref
    x_after, it_after = linalg.cg(A, b, tol=1e-10)
    assert it_after == it_ref
    np.testing.assert_array_equal(np.asarray(x_ref), np.asarray(x_after))
    linalg.HOST_SYNCS = 0
    linalg.gmres(A, b, tol=1e-10)
    assert linalg.HOST_SYNCS == syncs_ref


def test_wrapper_installed_only_when_active():
    A = sparse_tpu.csr_array(_spd())
    assert not getattr(
        linalg.make_linear_operator(A), "_fault_wrapped", False
    )
    faults.configure("nonfinite:matvec:p=0")
    op = linalg.make_linear_operator(A)
    assert getattr(op, "_fault_wrapped", False)
    # no double wrap through repeated make_linear_operator
    assert linalg.make_linear_operator(op) is op
    faults.clear()
    assert not getattr(
        linalg.make_linear_operator(A), "_fault_wrapped", False
    )


# ---------------------------------------------------------------------------
# recovery policy engine
# ---------------------------------------------------------------------------
def test_recovery_clean_solve_no_retry_events():
    settings.telemetry = True
    S = _spd()
    A = sparse_tpu.csr_array(S)
    b = np.random.default_rng(2).standard_normal(S.shape[0])
    x, info = solve_with_recovery(A, b, solver="cg", tol=1e-10)
    assert info.converged and info.attempts == 1 and not info.recovered
    assert np.linalg.norm(S @ np.asarray(x) - b) <= 1e-9
    assert not telemetry.events("solver.retry")
    assert not telemetry.events("solver.recovered")


@pytest.mark.parametrize("solver", ["cg", "bicgstab", "gmres"])
def test_recovery_under_nan_injection(solver):
    settings.telemetry = True
    S = _spd(64)
    A = sparse_tpu.csr_array(S)
    b = np.random.default_rng(1).standard_normal(64)
    faults.configure("nonfinite:matvec:p=0.01,seed=7")
    x, info = solve_with_recovery(
        A, b, solver=solver, tol=1e-8,
        policy=RecoveryPolicy(max_attempts=10),
    )
    faults.clear()
    assert info.converged, info.history
    target = 1e-8 * max(np.linalg.norm(b), 1.0) if solver == "gmres" else 1e-8
    assert np.linalg.norm(S @ np.asarray(x) - b) <= 10 * target
    assert telemetry.events("fault.injected")
    if info.recovered:
        chain = [e["kind"] for e in telemetry.events()]
        assert chain.index("fault.injected") < chain.index("solver.retry")
        assert telemetry.events("solver.recovered")


def test_recovery_stagnation_restarts_from_iterate():
    settings.telemetry = True
    S = _spd(96, seed=5)
    A = sparse_tpu.csr_array(S)
    b = np.random.default_rng(3).standard_normal(96)
    # maxiter far below what one attempt needs: progress accumulates
    # across restarts from the best iterate (never punished by
    # escalation), so the ladder converges where one attempt cannot
    x, info = solve_with_recovery(
        A, b, solver="cg", tol=1e-9, maxiter=12,
        policy=RecoveryPolicy(max_attempts=12),
    )
    assert info.converged and info.attempts > 1 and info.recovered
    assert info.solver == "cg", "improving restarts must not escalate"
    assert np.linalg.norm(S @ np.asarray(x) - b) <= 1e-8
    retries = telemetry.events("solver.retry")
    assert retries and all(r["reason"] == "stagnation" for r in retries)
    assert all(r["action"] == "restart" for r in retries)


def test_recovery_bicgstab_breakdown_escalates_to_gmres():
    settings.telemetry = True
    # the classic omega-breakdown shape: skew system, one iteration
    # makes t . s == 0 while ||r|| > 0 — silently where-guarded in the
    # recurrence, detected by the health monitor's breakdown tap
    A = sparse_tpu.csr_array(sp.csr_matrix(np.array([[0., 1.], [-1., 0.]])))
    b = np.array([1., 0.])
    x, info = solve_with_recovery(
        A, b, solver="bicgstab", tol=1e-10,
        policy=RecoveryPolicy(max_attempts=4),
    )
    assert info.converged and info.solver == "gmres"
    reasons = {e["reason"] for e in telemetry.events("solver.anomaly")}
    assert "breakdown" in reasons
    (retry,) = [
        e for e in telemetry.events("solver.retry")
        if e["reason"] == "breakdown"
    ]
    assert retry["action"] == "escalate" and retry["solver"] == "gmres"


def test_recovery_nonfinite_rolls_back_to_checkpoint(tmp_path):
    from sparse_tpu.checkpoint import CheckpointManager

    settings.telemetry = True
    S = _spd(48)
    A = sparse_tpu.csr_array(S)
    b = np.random.default_rng(4).standard_normal(48)
    x_good = sp.linalg.spsolve(S.tocsc(), b)
    mgr = CheckpointManager(tmp_path / "ck.npz")
    mgr.save(1, x=x_good)  # a near-perfect iterate from "before the crash"
    faults.configure("nonfinite:matvec:p=1,n=1,seed=0")  # poison attempt 1
    x, info = solve_with_recovery(
        A, b, solver="cg", tol=1e-8, checkpoint=mgr,
        policy=RecoveryPolicy(max_attempts=4),
    )
    assert info.converged and info.recovered
    (retry,) = [
        e for e in telemetry.events("solver.retry")
        if e["reason"] == "nonfinite"
    ]
    assert retry["action"] == "rollback"
    # rolling back to the solved state means the retry converges at the
    # FIRST conv-test point (one 25-iteration chunk) — nothing like a
    # from-scratch solve, which needs several chunks at this tol
    assert info.history[-1]["iters"] <= 25


def test_recovery_deadline_gives_up():
    settings.telemetry = True
    S = _spd()
    A = sparse_tpu.csr_array(S)
    b = np.random.default_rng(5).standard_normal(S.shape[0])
    x, info = solve_with_recovery(
        A, b, solver="cg", tol=1e-12, maxiter=2,
        policy=RecoveryPolicy(max_attempts=10, deadline_s=0.0),
    )
    assert not info.converged and info.gave_up_reason == "deadline"
    (ev,) = telemetry.events("solver.giveup")
    assert ev["reason"] == "deadline"


def test_recovery_attempt_budget_gives_up():
    S = _spd()
    A = sparse_tpu.csr_array(S)
    b = np.random.default_rng(6).standard_normal(S.shape[0])
    faults.configure("nonfinite:matvec:p=1,seed=0")  # unrecoverable
    x, info = solve_with_recovery(
        A, b, solver="cg", tol=1e-10, policy=RecoveryPolicy(max_attempts=3),
    )
    assert not info.converged and info.gave_up_reason == "attempts"
    assert info.attempts == 3


def test_recovery_preempted_checkpointed_solve(tmp_path):
    from sparse_tpu.checkpoint import checkpointed_cg

    S = _spd(64)
    A = sparse_tpu.csr_array(S)
    b = np.random.default_rng(7).standard_normal(64)
    faults.configure("preempt:chunk:p=1,n=2,seed=0")
    p = tmp_path / "ck.npz"
    done = None
    for _ in range(5):
        try:
            done = checkpointed_cg(A, b, p, tol=1e-10, chunk=15)
            break
        except Preempted:
            continue
    assert done is not None
    x, iters = done
    assert np.linalg.norm(S @ np.asarray(x) - b) <= 1e-8


# ---------------------------------------------------------------------------
# fused-CG nonfinite exit (ISSUE 5 satellite regression)
# ---------------------------------------------------------------------------
def test_fused_cg_nonfinite_rho_is_not_convergence(monkeypatch):
    monkeypatch.setattr(settings, "fused_cg", "force")
    settings.telemetry = True
    n = 64
    e = np.ones(n, np.float32)
    A = sparse_tpu.dia_array(
        (np.stack([-e, 3 * e, -e]), np.array([-1, 0, 1])), shape=(n, n)
    )
    b_bad = np.ones(n, np.float32)
    b_bad[3] = np.nan
    out = linalg._try_fused_cg(A, b_bad.copy(), None, 1e-6, n * 10, 25)
    assert out is not None
    _x, _iters, rho_f, info = out
    assert info == -1 and not np.isfinite(rho_f)
    # through the public cg(): the health report must show a nonfinite
    # anomaly and converged=False — distinguishable from convergence
    telemetry.reset()
    linalg.cg(A, b_bad.copy(), tol=1e-6)
    rep = telemetry.last_solve_report()
    assert rep["converged"] is False
    assert any(a["reason"] == "nonfinite" for a in rep["anomalies"])
    # clean solve: info == 0 and the report says converged
    telemetry.reset()
    out = linalg._try_fused_cg(
        A, np.ones(n, np.float32), None, 1e-6, n * 10, 25
    )
    assert out[3] == 0
    linalg.cg(A, np.ones(n, np.float32), tol=1e-6)
    assert telemetry.last_solve_report()["converged"] is True


# ---------------------------------------------------------------------------
# failover registry
# ---------------------------------------------------------------------------
def test_registry_mark_reinstate_cycle():
    settings.telemetry = True

    class Obj:
        pass

    o = Obj()
    assert not failover.failed("k1", o)
    failover.mark_failed("k1", o, error="boom")
    assert failover.failed("k1", o)
    (ev,) = telemetry.events("kernel.failover")
    assert ev["kernel"] == "k1" and not telemetry.schema.validate(ev)
    assert failover.probe("k1", o, lambda: None)
    assert not failover.failed("k1", o)
    (rev,) = telemetry.events("kernel.reinstate")
    assert rev["kernel"] == "k1"
    # failed probe leaves the latch
    failover.mark_failed("k1", o, error="boom2")
    assert not failover.probe(
        "k1", o, lambda: (_ for _ in ()).throw(RuntimeError("still down"))
    )
    assert failover.failed("k1", o)


def test_injected_pallas_failure_sell(monkeypatch):
    from sparse_tpu.kernels.sell_spmv import PreparedCSR

    settings.telemetry = True
    monkeypatch.setattr(settings, "spmv_mode", "pallas")
    G = _spd(32).astype(np.float32)
    prep = PreparedCSR(G.indptr, G.indices, G.data, G.shape)
    x = np.random.default_rng(0).standard_normal(32).astype(np.float32)
    faults.configure("fail:pallas:kernel=sell_spmv,n=1")
    with pytest.warns(UserWarning, match="failing over"):
        y = np.asarray(prep(x))
    np.testing.assert_allclose(y, G @ x, rtol=1e-5, atol=1e-5)
    assert failover.failed(prep.KERNEL, prep)
    assert telemetry.events("fault.injected")
    (ev,) = telemetry.events("kernel.failover")
    assert ev["kernel"] == "sell_spmv" and "injected" in ev["error"].lower()
    # probe-based reinstate: injection cleared, the real kernel works
    faults.clear()
    assert prep.probe_pallas(x)
    assert not failover.failed(prep.KERNEL, prep)
    assert telemetry.events("kernel.reinstate")


def test_injected_pallas_failure_dia():
    from sparse_tpu.kernels.dia_spmv import DIA_KERNEL, cached_prepared_spmv

    settings.telemetry = True
    n = 32
    e = np.ones(n, np.float32)
    data = np.stack([-e, 3 * e, -e])
    offsets = (-1, 0, 1)

    class Holder:
        pass

    h = Holder()
    x = np.linspace(0, 1, n, dtype=np.float32)
    faults.configure("fail:pallas:kernel=dia_spmv,n=1")
    with pytest.warns(UserWarning, match="failing over"):
        out = cached_prepared_spmv(h, "dia", data, offsets, (n, n), x)
    assert out is None  # caller takes the XLA formulation
    assert failover.failed(DIA_KERNEL, h)
    (ev,) = telemetry.events("kernel.failover")
    assert ev["kernel"] == "dia_spmv"


def test_injected_pallas_failure_batched(monkeypatch):
    from sparse_tpu.batch import BatchedCSR

    settings.telemetry = True
    monkeypatch.setattr(settings, "spmv_mode", "pallas")
    mats, _ = _stack(n=32, B=3)
    bc = BatchedCSR.from_stack([m.astype(np.float32) for m in mats])
    X = np.random.default_rng(1).standard_normal((3, 32)).astype(np.float32)
    faults.configure("fail:pallas:kernel=sell_spmv_batched,n=1")
    with pytest.warns(UserWarning, match="failing over"):
        Y = np.asarray(bc.matvec(X))
    for i in range(3):
        np.testing.assert_allclose(
            Y[i], mats[i] @ X[i], rtol=1e-4, atol=1e-4
        )
    # latched on the PATTERN: with_values siblings share the latch
    assert failover.failed(bc.KERNEL, bc.pattern)
    sib = bc.with_values(bc.values)
    assert failover.failed(sib.KERNEL, sib.pattern)


# ---------------------------------------------------------------------------
# resilient SolveSession
# ---------------------------------------------------------------------------
def test_ticket_states_and_failed_bucket_isolation():
    settings.telemetry = True
    mats, rhs = _stack()
    s = SolveSession("cg")
    t_ok = s.submit(mats[0], rhs[0], tol=1e-10)
    assert t_ok.state is TicketState.PENDING and not t_ok.done
    skew = sp.csr_matrix(np.array([[2., 1.], [1., 2.]]))
    t_bad = s.submit(skew, np.array([1., 0.]))
    orig = s._dispatch

    def poisoned(reqs, dt, **kw):
        if reqs[0].pattern.shape[0] == 2:
            raise RuntimeError("bucket program exploded")
        return orig(reqs, dt, **kw)

    s._dispatch = poisoned
    s.flush()  # must NOT raise: one failed bucket cannot strand the rest
    assert t_ok.state is TicketState.DONE and t_ok.converged
    assert t_bad.state is TicketState.FAILED
    with pytest.raises(TicketFailedError, match="exploded"):
        t_bad.result()
    # the session stays usable after a failed bucket
    t2 = s.submit(skew, np.array([1., 0.]), tol=1e-12)
    s._dispatch = orig
    s.flush()
    assert t2.converged


def test_ticket_deadline():
    settings.telemetry = True
    mats, rhs = _stack()
    s = SolveSession("cg")
    t_late = s.submit(mats[0], rhs[0], deadline_s=0.0)
    t_fine = s.submit(mats[1], rhs[1], tol=1e-10)
    time.sleep(0.005)
    s.flush()
    assert t_late.state is TicketState.FAILED
    with pytest.raises(TicketDeadlineError):
        t_late.result()
    assert t_fine.converged
    (ev,) = telemetry.events("batch.deadline")
    assert ev["lanes"] == 1 and not telemetry.schema.validate(ev)


def test_requeue_unconverged_lane_into_fallback_bucket():
    settings.telemetry = True
    mats, rhs = _stack()
    s = SolveSession("cg")
    # a starved maxiter can't converge under cg; the requeue bucket
    # (gmres, fresh budget, promoted dtype) must finish the lane
    t = s.submit(mats[0], rhs[0], tol=1e-9, maxiter=3)
    s.flush()
    x, iters, resid2 = t.result()
    assert t.converged and t.solver == "gmres" and t.requeued
    assert np.linalg.norm(mats[0] @ x - rhs[0]) <= 1e-8
    (ev,) = telemetry.events("batch.requeue")
    assert ev["lanes"] == 1 and ev["from_solver"] == "cg"
    assert not telemetry.schema.validate(ev)


def test_requeue_disabled_keeps_first_result():
    mats, rhs = _stack()
    s = SolveSession("cg", requeue=False)
    t = s.submit(mats[0], rhs[0], tol=1e-9, maxiter=3)
    s.flush()
    assert not t.converged and t.state is TicketState.DONE


def test_degraded_mode_per_lane_solve():
    settings.telemetry = True
    mats, rhs = _stack()
    s = SolveSession("cg")
    t = s.submit(mats[0], rhs[0], tol=1e-10)

    def broken(*a, **k):
        raise RuntimeError("pallas/plan-cache unavailable")

    s._build_program = broken
    s.flush()
    x, iters, resid2 = t.result()
    assert t.converged
    assert np.linalg.norm(mats[0] @ x - rhs[0]) <= 1e-8
    (ev,) = telemetry.events("batch.degraded")
    assert "unavailable" in ev["reason"]
    assert not telemetry.schema.validate(ev)


def test_injected_dispatch_drop_retries_then_succeeds():
    settings.telemetry = True
    mats, rhs = _stack()
    faults.configure("drop:dispatch:p=1,n=1")  # first dispatch only
    s = SolveSession("cg")
    t = s.submit(mats[0], rhs[0], tol=1e-10)
    s.flush()
    assert t.converged  # retried within flush
    assert telemetry.events("fault.injected")


def test_injected_dispatch_drop_exhausts_to_failed():
    mats, rhs = _stack()
    faults.configure("drop:dispatch:p=1")  # every dispatch drops
    s = SolveSession("cg", requeue=False)
    t = s.submit(mats[0], rhs[0])
    s.flush()
    assert t.state is TicketState.FAILED
    with pytest.raises(TicketFailedError):
        t.result()


def test_session_batch_recovers_under_matvec_injection():
    settings.telemetry = True
    mats, rhs = _stack(n=64, B=4, seed=3)
    faults.configure("nonfinite:matvec:p=0.01,seed=7")
    s = SolveSession("cg")
    X, iters, resid2 = s.solve_many(mats, rhs, tol=1e-8)
    faults.clear()
    for m, x, b in zip(mats, X, rhs):
        assert np.linalg.norm(m @ x - b) <= 1e-7
    assert telemetry.events("batch.dispatch")


def test_b1_parity_under_recovery_features():
    """The resilient session (requeue on, deadlines available) must keep
    the B=1 == unbatched contract (same iteration count, machine-eps
    iterates — the test_batch.py parity tolerance) when nothing fails."""
    mats, rhs = _stack(B=1)
    s = SolveSession("cg")
    X, iters, resid2 = s.solve_many(mats, rhs[:1], tol=1e-10)
    A1 = sparse_tpu.csr_array(mats[0])
    x_ref, it_ref = linalg.cg(A1, rhs[0], tol=1e-10)
    assert int(iters[0]) == int(it_ref)
    np.testing.assert_allclose(X[0], np.asarray(x_ref), rtol=1e-12)


# ---------------------------------------------------------------------------
# chaos gate (the acceptance scenario, via the CI script)
# ---------------------------------------------------------------------------
def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_chaos_check_quick_scenario():
    chaos = _load_script("chaos_check")
    assert chaos.main([]) == 0


@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_chaos_sweep(seed):
    """Seeded chaos sweep: heavier corruption, every solver still
    recovers or gives up CLEANLY (finite outputs, bounded attempts)."""
    settings.telemetry = True
    S = _spd(96, seed=seed)
    A = sparse_tpu.csr_array(S)
    b = np.random.default_rng(seed).standard_normal(96)
    faults.configure(
        f"nonfinite:matvec:p=0.02,seed={seed};"
        f"preempt:chunk:p=0.05,seed={seed}"
    )
    x, info = solve_with_recovery(
        A, b, solver="cg", tol=1e-8,
        policy=RecoveryPolicy(max_attempts=15),
    )
    faults.clear()
    assert info.attempts <= 15
    if info.converged:
        assert np.linalg.norm(S @ np.asarray(x) - b) <= 1e-6
    else:
        assert info.gave_up_reason in ("attempts", "deadline")
        assert telemetry.events("solver.giveup")


# -- Axon v3: request-scoped ticket tracing through the session --------------


def test_ticket_id_traceable_across_requeue_chain():
    """The ISSUE 6 acceptance chain: a flush that triggers a requeue
    yields ONE ticket id traceable across ``batch.dispatch`` →
    ``batch.requeue`` → the terminal ``batch.ticket`` event, in both the
    JSONL records and the exported Perfetto trace."""
    settings.telemetry = True
    mats, rhs = _stack()
    s = SolveSession("cg")
    t = s.submit(mats[0], rhs[0], tol=1e-9, maxiter=3)
    tid = t.id
    assert tid.startswith("tk-")
    s.flush()
    assert t.converged and t.requeued

    evs = telemetry.events()
    chain = [
        e["kind"] for e in evs
        if tid in (e.get("tickets") or ()) or e.get("ticket") == tid
    ]
    # both dispatches (original + requeue bucket) carry the id, the
    # requeue event names it explicitly, and the terminal event ends it
    assert chain.count("batch.dispatch") == 2
    assert "batch.requeue" in chain and chain[-1] == "batch.ticket"
    (term,) = [e for e in evs if e.get("kind") == "batch.ticket"]
    assert term["ticket"] == tid and term["state"] == "done"
    assert term["requeued"] is True and term["solver"] == "gmres"
    assert term["latency_ms"] > 0
    # the phase breakdown tiles the latency (disjoint phases across the
    # two dispatches — the requeue accounting must not double count)
    phases = term["phases"]
    assert set(phases) == {
        "queue_ms", "pack_ms", "compile_ms", "solve_ms", "readback_ms"
    }
    assert sum(phases.values()) <= term["latency_ms"] * 1.05
    assert not telemetry.schema.validate(term)

    # the same chain renders in the Perfetto export: a tickets lane with
    # one end-to-end slice and its nested phase slices
    trace = telemetry.to_chrome_trace(evs)
    lanes = {
        m["args"]["name"]: m["pid"]
        for m in trace["traceEvents"]
        if m.get("ph") == "M" and m.get("name") == "process_name"
    }
    ticket_lane = [k for k in lanes if k.endswith("tickets")]
    assert ticket_lane
    slices = [
        e for e in trace["traceEvents"]
        if e.get("cat") == "ticket" and tid in e.get("name", "")
    ]
    assert len(slices) == 1
    assert slices[0]["dur"] == pytest.approx(
        term["latency_ms"] * 1e3, rel=0.01
    )
    phase_names = [
        e["name"] for e in trace["traceEvents"]
        if e.get("cat") == "ticket.phase" and e["pid"] == slices[0]["pid"]
        and e["tid"] == slices[0]["tid"]
    ]
    assert phase_names == [
        "queue", "pack", "compile", "solve", "readback"
    ]


def test_solve_with_recovery_threads_ticket_through_ladder():
    settings.telemetry = True
    A = _spd()
    b = np.ones(A.shape[0])
    tid = telemetry.new_ticket_id()
    x, info = solve_with_recovery(
        sparse_tpu.csr_array(A), b, solver="cg", tol=1e-8, ticket=tid
    )
    assert info.converged
    tagged = [
        e for e in telemetry.events() if tid in (e.get("tickets") or ())
    ]
    assert tagged, "recovery-ladder events must carry the ticket id"
    kinds = {e["kind"] for e in tagged}
    assert "solver.solve" in kinds or "solver.recovered" in kinds
