"""BDF stiff-ODE solver oracle tests vs scipy.integrate (beyond the
reference — its integrate.py is explicit-RK only)."""

import numpy as np
import pytest
import scipy.integrate as si
import scipy.sparse as sp

import jax.numpy as jnp

import sparse_tpu as sparse
from sparse_tpu.integrate import solve_ivp


def _rober(t, y):
    y1, y2, y3 = y[0], y[1], y[2]
    return jnp.stack([
        -0.04 * y1 + 1e4 * y2 * y3,
        0.04 * y1 - 1e4 * y2 * y3 - 3e7 * y2 ** 2,
        3e7 * y2 ** 2,
    ])


def _rober_np(t, y):
    y1, y2, y3 = y
    return [-0.04 * y1 + 1e4 * y2 * y3,
            0.04 * y1 - 1e4 * y2 * y3 - 3e7 * y2 ** 2,
            3e7 * y2 ** 2]


def test_bdf_robertson_matches_scipy():
    sol = solve_ivp(_rober, (0, 100.0), np.array([1.0, 0, 0]),
                    method="BDF", rtol=1e-6, atol=1e-9)
    ref = si.solve_ivp(_rober_np, (0, 100.0), [1.0, 0, 0], method="BDF",
                       rtol=1e-6, atol=1e-9)
    assert sol.status == 0
    np.testing.assert_allclose(np.asarray(sol.y)[:, -1], ref.y[:, -1],
                               rtol=1e-6)
    # stiffness sanity: an explicit method at the same tolerance needs
    # far more RHS evaluations than BDF on this problem. A tenth of the
    # span suffices — RK45's step size is pinned by the fast transient,
    # so its nfev scales ~linearly with span — and spares the runner
    # the other 90 stiff time units.
    rk = solve_ivp(_rober, (0, 10.0), np.array([1.0, 0, 0]),
                   method="RK45", rtol=1e-6, atol=1e-9)
    assert sol.nfev < rk.nfev / 2


def test_bdf_linear_sparse_jacobian():
    n = 48
    A = sp.diags([np.full(n - 1, 50.0), np.full(n, -100.0),
                  np.full(n - 1, 50.0)], [-1, 0, 1]).tocsr()
    As = sparse.csr_array(A)
    y0 = np.sin(np.linspace(0, np.pi, n))
    sol = solve_ivp(lambda t, y: As @ y, (0, 1.0), y0, method="BDF",
                    jac=As, rtol=1e-8, atol=1e-10)
    ref = si.solve_ivp(lambda t, y: A @ y, (0, 1.0), y0, method="BDF",
                       jac=A, rtol=1e-8, atol=1e-10)
    assert sol.status == 0
    err = (np.linalg.norm(np.asarray(sol.y)[:, -1] - ref.y[:, -1])
           / np.linalg.norm(ref.y[:, -1]))
    assert err < 1e-6
    assert sol.njev <= 1  # constant jacobian: no re-evaluations


def test_bdf_callable_jacobian_and_dense_output():
    def f(t, y):
        return jnp.stack([y[1], -y[0] - 1e3 * y[1] * (y[0] ** 2 - 1)])

    def jac(t, y):
        y0, y1 = float(y[0]), float(y[1])
        return np.array([
            [0.0, 1.0],
            [-1.0 - 2e3 * y0 * y1, -1e3 * (y0 ** 2 - 1)],
        ])

    def f_np(t, y):
        return [y[1], -y[0] - 1e3 * y[1] * (y[0] ** 2 - 1)]

    sol = solve_ivp(f, (0, 20.0), np.array([2.0, 0.0]), method="BDF",
                    jac=jac, dense_output=True, rtol=1e-7, atol=1e-9)
    ref = si.solve_ivp(f_np, (0, 20.0), [2.0, 0.0], method="BDF",
                       rtol=1e-7, atol=1e-9, dense_output=True)
    assert sol.status == 0 and sol.njev > 1
    ts = np.linspace(0.5, 19.5, 9)
    np.testing.assert_allclose(np.asarray(sol.sol(ts)), ref.sol(ts),
                               rtol=1e-3, atol=1e-5)


def test_bdf_events_and_t_eval():
    def decay(t, y):
        return -y

    def hit_half(t, y):
        return float(y[0]) - 0.5

    hit_half.terminal = True
    sol = solve_ivp(decay, (0, 10.0), np.array([1.0]), method="BDF",
                    events=hit_half, rtol=1e-8, atol=1e-10)
    assert sol.status == 1
    np.testing.assert_allclose(sol.t_events[0][0], np.log(2), rtol=1e-5)
    sol2 = solve_ivp(decay, (0, 2.0), np.array([1.0]), method="BDF",
                     t_eval=np.linspace(0, 2, 5), rtol=1e-8, atol=1e-10)
    np.testing.assert_allclose(np.asarray(sol2.y)[0],
                               np.exp(-np.linspace(0, 2, 5)), rtol=1e-5)


def test_bdf_complex_linear():
    """Schrodinger-like evolution y' = -iHy (the quantum workload's
    shape) — BDF must carry complex state and factors."""
    n = 16
    rng = np.random.default_rng(0)
    H = sp.random(n, n, 0.3, random_state=rng)
    H = ((H + H.T) * 0.5).tocsr()
    Hc = sparse.csr_array((-1j) * H.astype(np.complex128))

    sol = solve_ivp(lambda t, y: Hc @ y, (0, 1.0),
                    (rng.standard_normal(n) + 0j), method="BDF",
                    jac=Hc, rtol=1e-8, atol=1e-10)
    ref = si.solve_ivp(lambda t, y: -1j * (H @ y), (0, 1.0),
                       np.asarray(sol.y)[:, 0], method="BDF",
                       rtol=1e-8, atol=1e-10)
    assert sol.status == 0
    np.testing.assert_allclose(np.asarray(sol.y)[:, -1], ref.y[:, -1],
                               rtol=1e-5, atol=1e-7)


# ---------------------------------------------------------------------------
# Radau IIA(5)
# ---------------------------------------------------------------------------
def test_radau_robertson_matches_scipy():
    sol = solve_ivp(_rober, (0, 100.0), np.array([1.0, 0, 0]),
                    method="Radau", rtol=1e-6, atol=1e-9)
    ref = si.solve_ivp(_rober_np, (0, 100.0), [1.0, 0, 0], method="Radau",
                       rtol=1e-6, atol=1e-9)
    assert sol.status == 0
    np.testing.assert_allclose(np.asarray(sol.y)[:, -1], ref.y[:, -1],
                               rtol=1e-5)


def test_radau_sparse_jacobian_and_dense_output():
    n = 40
    A = sp.diags([np.full(n - 1, 40.0), np.full(n, -80.0),
                  np.full(n - 1, 40.0)], [-1, 0, 1]).tocsr()
    As = sparse.csr_array(A)
    y0 = np.sin(np.linspace(0, np.pi, n))
    sol = solve_ivp(lambda t, y: As @ y, (0, 1.0), y0, method="Radau",
                    jac=As, rtol=1e-8, atol=1e-10, dense_output=True)
    ref = si.solve_ivp(lambda t, y: A @ y, (0, 1.0), y0, method="Radau",
                       jac=A, rtol=1e-8, atol=1e-10, dense_output=True)
    assert sol.status == 0
    ts = np.linspace(0.1, 0.9, 5)
    np.testing.assert_allclose(np.asarray(sol.sol(ts)), ref.sol(ts),
                               rtol=1e-5, atol=1e-8)


def test_radau_events():
    def decay(t, y):
        return -y

    def hit_half(t, y):
        return float(y[0]) - 0.5

    hit_half.terminal = True
    sol = solve_ivp(decay, (0, 10.0), np.array([1.0]), method="Radau",
                    events=hit_half, rtol=1e-8, atol=1e-10)
    assert sol.status == 1
    np.testing.assert_allclose(sol.t_events[0][0], np.log(2), rtol=1e-5)
