"""Format conversion round-trips vs scipy.

Reference analog: ``tests/integration/test_csr_conversion.py`` and test_coo/
test_csc/test_dia conversion coverage.
"""

import numpy as np
import pytest
import scipy.io as sci_io
import scipy.sparse as sp

import sparse_tpu as sparse
from .utils.common import test_mtx_files
from .utils.sample import sample_csr, sample_dense


@pytest.mark.parametrize("filename", test_mtx_files)
def test_mtx_roundtrip_formats(filename):
    s = sci_io.mmread(filename)
    ours = sparse.io.mmread(filename)
    dense = s.toarray()
    assert np.allclose(np.asarray(ours.toarray()), dense)
    assert np.allclose(np.asarray(ours.tocsr().toarray()), dense)
    assert np.allclose(np.asarray(ours.tocsc().toarray()), dense)
    assert np.allclose(np.asarray(ours.tocsr().tocoo().toarray()), dense)
    assert np.allclose(np.asarray(ours.tocsc().tocsr().toarray()), dense)
    assert np.allclose(np.asarray(ours.tocsr().tocsc().toarray()), dense)


def test_dense_roundtrip():
    d = sample_dense(12, 17, seed=3)
    d[d < 0.5] = 0.0
    arr = sparse.csr_array(d)
    s = sp.csr_matrix(d)
    assert arr.nnz == s.nnz
    assert np.allclose(np.asarray(arr.toarray()), d)
    assert np.allclose(np.asarray(sparse.csc_array(d).toarray()), d)
    assert np.allclose(np.asarray(sparse.coo_array(d).toarray()), d)


def test_coo_duplicates_sum():
    rows = np.array([0, 0, 1, 2, 0])
    cols = np.array([1, 1, 2, 0, 1])
    vals = np.array([1.0, 2.0, 3.0, 4.0, 5.0])
    ours = sparse.coo_array((vals, (rows, cols)), shape=(3, 3)).tocsr()
    ref = sp.coo_matrix((vals, (rows, cols)), shape=(3, 3)).tocsr()
    assert np.allclose(np.asarray(ours.toarray()), ref.toarray())
    assert ours.nnz == ref.nnz


def test_transpose():
    s = sample_csr(11, 7, seed=5)
    arr = sparse.csr_array(s)
    assert np.allclose(np.asarray(arr.T.toarray()), s.T.toarray())
    assert arr.T.format == "csc"
    assert np.allclose(np.asarray(arr.T.T.toarray()), s.toarray())


def test_dia_conversions():
    s = sp.diags(
        [np.full(9, -1.0), np.full(10, 2.0), np.full(9, -1.0)], [-1, 0, 1]
    )
    ours = sparse.diags(
        [np.full(9, -1.0), np.full(10, 2.0), np.full(9, -1.0)], [-1, 0, 1]
    )
    assert ours.format == "dia"
    dense = s.toarray()
    assert np.allclose(np.asarray(ours.toarray()), dense)
    assert np.allclose(np.asarray(ours.tocsr().toarray()), dense)
    assert np.allclose(np.asarray(ours.tocsc().toarray()), dense)
    assert np.allclose(np.asarray(ours.T.toarray()), dense.T)
    assert np.allclose(np.asarray(ours.tocsc().T.toarray()), dense.T)


def test_empty_matrix():
    arr = sparse.csr_array((4, 5))
    assert arr.nnz == 0
    assert np.allclose(np.asarray(arr.toarray()), np.zeros((4, 5)))
    assert np.allclose(np.asarray(arr @ np.ones(5)), np.zeros(4))
    assert np.allclose(np.asarray(arr.tocsc().toarray()), np.zeros((4, 5)))
    assert np.allclose(np.asarray(arr.tocoo().toarray()), np.zeros((4, 5)))
