"""sparse_tpu.telemetry — structured observability subsystem.

Pins the three contract pillars: (a) disabled mode records NOTHING and
keeps the instrumented hot paths on their uninstrumented traces, (b)
enabled mode emits schema-valid JSONL events for solver iterations,
autotune decisions and distributed comm volumes, (c) trace safety —
spans no-op under jit and the compiled-loop taps never leak tracers.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sp

import sparse_tpu
from sparse_tpu import linalg, telemetry
from sparse_tpu.config import settings


@pytest.fixture
def tel(tmp_path, monkeypatch):
    """Telemetry enabled with an isolated sink; fully reset afterwards."""
    telemetry.reset()
    monkeypatch.setattr(settings, "telemetry", True)
    telemetry.configure(str(tmp_path / "records.jsonl"))
    yield tmp_path / "records.jsonl"
    telemetry.configure(None)
    telemetry.reset()


def _laplacian(n=48):
    e = np.ones(n)
    S = sp.diags([-e[:-1], 2.0 * e + 0.5, -e[:-1]], [-1, 0, 1]).tocsr()
    return sparse_tpu.csr_array(S), np.ones(n)


# -- (a) disabled mode -------------------------------------------------------


def test_disabled_records_nothing(tmp_path):
    telemetry.reset()
    telemetry.configure(str(tmp_path / "never.jsonl"))
    try:
        assert not telemetry.enabled()
        assert telemetry.record("solver.iter", solver="cg", iter=1) is None
        telemetry.count("x")
        telemetry.add_bytes("comm.spmv.total", 100)
        with telemetry.span("nope"):
            pass
        A, b = _laplacian()
        linalg.cg(A, b, tol=1e-8)
        assert telemetry.events() == []
        s = telemetry.summary()
        assert s["enabled"] is False and s["events"] == 0
        assert s["counts"] == {} and s["bytes_by_kind"] == {}
        # the sink is never even created on the disabled path
        assert not (tmp_path / "never.jsonl").exists()
    finally:
        telemetry.configure(None)
        telemetry.reset()


def test_disabled_span_is_shared_noop():
    from sparse_tpu.telemetry._spans import _NULL

    assert telemetry.span("a") is telemetry.span("b") is _NULL


# -- (b) enabled mode: solver events, schema-valid JSONL ---------------------


def test_cg_device_loop_emits_per_iteration_events(tel):
    A, b = _laplacian()
    x, iters = linalg.cg(A, b, tol=1e-10)
    evs = telemetry.events("solver.iter")
    assert len(evs) >= iters >= 1
    cg_evs = [e for e in evs if e["solver"] == "cg"]
    assert [e["iter"] for e in cg_evs][: iters] == list(range(1, iters + 1))
    assert all(e["resid2"] >= 0 for e in cg_evs)
    solves = telemetry.events("solver.solve")
    assert solves and solves[-1]["solver"] == "cg"
    assert solves[-1]["iters"] == iters
    # the solution itself is unchanged by instrumentation
    np.testing.assert_allclose(
        np.asarray(A.todense()) @ np.asarray(x), b, atol=1e-4
    )


def test_gmres_and_bicgstab_emit_events(tel):
    A, b = _laplacian()
    linalg.gmres(A, b, tol=1e-8)
    linalg.bicgstab(A, b, tol=1e-8)
    solvers = {e["solver"] for e in telemetry.events("solver.iter")}
    assert {"gmres", "bicgstab"} <= solvers
    solved = {e["solver"] for e in telemetry.events("solver.solve")}
    assert {"gmres", "bicgstab"} <= solved


def test_cg_host_loop_callback_path_events(tel):
    A, b = _laplacian()
    seen = []
    x, iters = linalg.cg(A, b, tol=1e-10, callback=lambda xk: seen.append(1))
    host_evs = [
        e for e in telemetry.events("solver.iter") if e.get("path") == "host"
    ]
    assert len(host_evs) == iters == len(seen)


def test_fused_cg_chunk_events(tel, monkeypatch):
    # force-mode fused CG (interpret off-TPU) reports per-chunk events
    # reusing its existing rho fetch; the kernel path is f32-only
    monkeypatch.setattr(settings, "fused_cg", "force")
    n = 256
    e = np.ones(n, dtype=np.float32)
    S = sp.diags([-e[:-1], 4.0 * e, -e[:-1]], [-1, 0, 1]).tocsr()
    A = sparse_tpu.csr_array(S.astype(np.float32)).todia()
    b = np.ones(n, dtype=np.float32)
    x, iters = linalg.cg(A, b, tol=1e-5, conv_test_iters=10)
    fused_evs = [
        e for e in telemetry.events("solver.iter") if e.get("path") == "fused"
    ]
    assert fused_evs, "fused path produced no chunk events"
    assert fused_evs[-1]["iter"] == iters
    solves = telemetry.events("solver.solve")
    assert solves[-1]["path"] == "fused"


def test_jsonl_sink_schema_valid(tel):
    A, b = _laplacian()
    linalg.cg(A, b, tol=1e-8)
    linalg.gmres(A, b, tol=1e-8)
    path = str(tel)
    problems = telemetry.schema.validate_jsonl(path)
    assert problems == []
    lines = [json.loads(ln) for ln in open(path) if ln.strip()]
    assert lines and all("kind" in ev and "ts" in ev for ev in lines)


def test_schema_validator_catches_bad_events():
    assert telemetry.schema.validate({"kind": "solver.iter", "ts": 1.0}) != []
    assert telemetry.schema.validate({"ts": 1.0}) != []
    assert telemetry.schema.validate({"kind": "span", "ts": 0}) != []
    assert (
        telemetry.schema.validate(
            {"kind": "comm.spmv", "ts": 1.0, "bytes": -4, "mode": "halo", "S": 2}
        )
        != []
    )
    # unknown kinds are forward-compatible: base fields suffice
    assert telemetry.schema.validate({"kind": "custom.thing", "ts": 1.0}) == []


# -- (b) autotune + kernel events -------------------------------------------


def test_autotune_gate_emits_event_and_never_poisons_cache(tel, monkeypatch):
    from sparse_tpu.kernels import dia_spmv as K

    monkeypatch.setattr(settings, "pallas_autotune", False)
    offsets = (-1, 0, 1)
    shape = (4096, 4096)
    key = (offsets, shape, "float32")
    K._TILE_CACHE.pop(key, None)
    data = jnp.ones((3, 4096), dtype=jnp.float32)
    tile, band = K.autotune_dia_tile(data, offsets, shape)
    assert tile == 65536 and band == {}
    # ADVICE r5: the gate result must NOT be memoized as a probe result —
    # flipping the setting on later in the session must still probe
    assert key not in K._TILE_CACHE
    evs = telemetry.events("autotune.result")
    assert evs and evs[-1]["probed"] is False
    assert evs[-1]["tile"] == 65536
    assert evs[-1]["reason"] == "autotune-disabled"


def test_autotune_backend_gate_reason(tel):
    from sparse_tpu.kernels import dia_spmv as K

    # pallas_autotune defaults True; off-TPU the backend gates
    offsets = (0,)
    shape = (2048, 2048)
    K._TILE_CACHE.pop((offsets, shape, "float32"), None)
    K.autotune_dia_tile(jnp.ones((1, 2048), jnp.float32), offsets, shape)
    evs = telemetry.events("autotune.result")
    assert evs and evs[-1]["reason"] == "backend-not-tpu"
    assert (offsets, shape, "float32") not in K._TILE_CACHE


# -- (b) distributed comm volumes -------------------------------------------


def test_shard_csr_records_spmv_comm_model(tel):
    from sparse_tpu.parallel.dist import shard_csr

    A, b = _laplacian(64)
    D = shard_csr(A)
    evs = telemetry.events("comm.spmv")
    assert evs, "shard_csr emitted no comm model event"
    ev = evs[-1]
    assert ev["S"] == D.S and ev["mode"] == D.mode
    assert ev["bytes"] >= 0
    # eager SpMV dispatches accumulate the structural per-call volume
    before = telemetry.counters().get("comm.spmv.calls", 0)
    D.spmv_padded(D.pad_vector(b))
    assert telemetry.counters().get("comm.spmv.calls", 0) == before + 1


def test_dist_cg_records_whole_solve_comm_volume(tel):
    from sparse_tpu.parallel.dist import comm_stats, dist_cg, shard_csr

    n = 128
    e = np.ones(n)
    S = sp.diags([-e[:-1], 4.0 * e, -e[:-1]], [-1, 0, 1]).tocsr()
    D = shard_csr(sparse_tpu.csr_array(S))
    b = np.ones(n)
    xp, iters, converged = dist_cg(D, b, tol=1e-8)
    assert converged
    evs = telemetry.events("comm.cg")
    assert evs
    ev = evs[-1]
    assert ev["iters"] == iters and ev["S"] == D.S
    cs = comm_stats(D)
    assert ev["bytes"] == int(
        cs["cg_iter_collective_bytes_per_shard"]
    ) * iters * D.S
    assert any(
        e["solver"] == "dist_cg" for e in telemetry.events("solver.solve")
    )


def test_dist_sort_sample_records_exchange_volume(tel):
    from sparse_tpu.parallel.sort import dist_sort_host

    keys = np.random.default_rng(5).permutation(1 << 10).astype(np.int64)
    sk, _ = dist_sort_host(keys)
    np.testing.assert_array_equal(sk, np.sort(keys))
    evs = telemetry.events("comm.sort")
    assert evs
    assert evs[-1]["S"] >= 1 and evs[-1]["bytes"] >= 0


# -- (c) trace safety --------------------------------------------------------


def test_span_noops_inside_jit_no_tracer_leak(tel):
    durs_before = telemetry.summary()["spans"]

    @jax.jit
    def f(x):
        # span must detect the active trace and degrade to the shared
        # no-op — never timing tracer ops, never calling block_until_ready
        with telemetry.span("inside.jit", sync=x):
            return x * 2.0

    out = f(jnp.ones(8))
    np.testing.assert_allclose(np.asarray(out), 2.0)
    assert "inside.jit" not in telemetry.summary()["spans"]
    assert durs_before == {} or True  # no exception is the contract


def test_span_records_outside_trace(tel):
    x = jnp.ones(16)
    with telemetry.span("outer.op", sync=x, n=16):
        y = x + 1
    s = telemetry.summary()["spans"]
    assert "outer.op" in s and s["outer.op"]["n"] == 1
    assert s["outer.op"]["p95_s"] >= 0
    evs = telemetry.events("span")
    assert evs[-1]["name"] == "outer.op" and evs[-1]["n"] == 16


def test_instrumentation_does_not_change_outer_jit_behavior(tel):
    # cg under an OUTER jit is unsupported either way (its host sync
    # points concretize tracers — seed behavior); the telemetry contract
    # is that instrumentation neither fixes nor changes that: the same
    # error class surfaces, and no half-recorded tracer values leak into
    # the event stream
    A, b = _laplacian(32)
    Ad = jnp.asarray(np.asarray(A.todense()))

    @jax.jit
    def solve(bb):
        x, _ = linalg.cg(Ad, bb, tol=1e-8, maxiter=40, conv_test_iters=5)
        return x

    with pytest.raises(jax.errors.ConcretizationTypeError):
        solve(jnp.asarray(b))
    for ev in telemetry.events("solver.iter"):
        assert isinstance(ev["iter"], int)
        assert isinstance(ev.get("resid2", ev.get("resid", 0.0)), float)


# -- recorder mechanics ------------------------------------------------------


def test_ring_is_bounded(tel, monkeypatch):
    monkeypatch.setattr(settings, "telemetry_ring", 32)
    telemetry.reset()
    for i in range(100):
        telemetry.record("custom.tick", i=i)
    evs = telemetry.events("custom.tick")
    assert len(evs) == 32
    assert evs[-1]["i"] == 99  # newest survive


def test_sink_failure_is_nonfatal(tmp_path, monkeypatch):
    telemetry.reset()
    monkeypatch.setattr(settings, "telemetry", True)
    telemetry.configure(str(tmp_path / "no" / "such" / "dir" / "x.jsonl"))
    try:
        # make the directory uncreatable by occupying the parent as a file
        (tmp_path / "no").write_text("a file, not a dir")
        with pytest.warns(UserWarning, match="unwritable"):
            telemetry.record("custom.tick", i=1)
        # ring still records after the sink is dropped
        telemetry.record("custom.tick", i=2)
        assert len(telemetry.events("custom.tick")) == 2
    finally:
        telemetry.configure(None)
        telemetry.reset()


def test_summary_aggregates(tel):
    telemetry.count("k", 3)
    telemetry.add_bytes("comm.spmv.total", 256)
    for d in (0.001, 0.002, 0.003):
        telemetry.add_span("lat", d)
    s = telemetry.summary()
    assert s["counts"]["k"] == 3
    assert s["bytes_by_kind"]["comm.spmv.total"] == 256
    assert s["spans"]["lat"]["n"] == 3
    assert s["spans"]["lat"]["p50_s"] == pytest.approx(0.002)
    assert s["spans"]["lat"]["max_s"] == pytest.approx(0.003)


def test_provenance_scopes_counted(tel):
    A, b = _laplacian()
    linalg.cg(A, b, tol=1e-8)
    counts = telemetry.counters()
    assert counts.get("sparse_tpu.cg", 0) >= 1
    assert counts.get("host_sync.int", 0) >= 1
