"""sparse_tpu.telemetry — structured observability subsystem.

Pins the three contract pillars: (a) disabled mode records NOTHING and
keeps the instrumented hot paths on their uninstrumented traces, (b)
enabled mode emits schema-valid JSONL events for solver iterations,
autotune decisions and distributed comm volumes, (c) trace safety —
spans no-op under jit and the compiled-loop taps never leak tracers.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sp

import sparse_tpu
from sparse_tpu import linalg, telemetry
from sparse_tpu.config import settings


@pytest.fixture
def tel(tmp_path, monkeypatch):
    """Telemetry enabled with an isolated sink; fully reset afterwards."""
    telemetry.reset()
    monkeypatch.setattr(settings, "telemetry", True)
    telemetry.configure(str(tmp_path / "records.jsonl"))
    yield tmp_path / "records.jsonl"
    telemetry.configure(None)
    telemetry.reset()


def _laplacian(n=48):
    e = np.ones(n)
    S = sp.diags([-e[:-1], 2.0 * e + 0.5, -e[:-1]], [-1, 0, 1]).tocsr()
    return sparse_tpu.csr_array(S), np.ones(n)


# -- (a) disabled mode -------------------------------------------------------


def test_disabled_records_nothing(tmp_path):
    telemetry.reset()
    telemetry.configure(str(tmp_path / "never.jsonl"))
    try:
        assert not telemetry.enabled()
        assert telemetry.record("solver.iter", solver="cg", iter=1) is None
        telemetry.count("x")
        telemetry.add_bytes("comm.spmv.total", 100)
        with telemetry.span("nope"):
            pass
        A, b = _laplacian()
        linalg.cg(A, b, tol=1e-8)
        assert telemetry.events() == []
        s = telemetry.summary()
        assert s["enabled"] is False and s["events"] == 0
        assert s["counts"] == {} and s["bytes_by_kind"] == {}
        # the sink is never even created on the disabled path
        assert not (tmp_path / "never.jsonl").exists()
    finally:
        telemetry.configure(None)
        telemetry.reset()


def test_disabled_span_is_shared_noop():
    from sparse_tpu.telemetry._spans import _NULL

    assert telemetry.span("a") is telemetry.span("b") is _NULL


# -- (b) enabled mode: solver events, schema-valid JSONL ---------------------


def test_cg_device_loop_emits_per_iteration_events(tel):
    A, b = _laplacian()
    x, iters = linalg.cg(A, b, tol=1e-10)
    evs = telemetry.events("solver.iter")
    assert len(evs) >= iters >= 1
    cg_evs = [e for e in evs if e["solver"] == "cg"]
    assert [e["iter"] for e in cg_evs][: iters] == list(range(1, iters + 1))
    assert all(e["resid2"] >= 0 for e in cg_evs)
    solves = telemetry.events("solver.solve")
    assert solves and solves[-1]["solver"] == "cg"
    assert solves[-1]["iters"] == iters
    # the solution itself is unchanged by instrumentation
    np.testing.assert_allclose(
        np.asarray(A.todense()) @ np.asarray(x), b, atol=1e-4
    )


def test_gmres_and_bicgstab_emit_events(tel):
    A, b = _laplacian()
    linalg.gmres(A, b, tol=1e-8)
    linalg.bicgstab(A, b, tol=1e-8)
    solvers = {e["solver"] for e in telemetry.events("solver.iter")}
    assert {"gmres", "bicgstab"} <= solvers
    solved = {e["solver"] for e in telemetry.events("solver.solve")}
    assert {"gmres", "bicgstab"} <= solved


def test_cg_host_loop_callback_path_events(tel):
    A, b = _laplacian()
    seen = []
    x, iters = linalg.cg(A, b, tol=1e-10, callback=lambda xk: seen.append(1))
    host_evs = [
        e for e in telemetry.events("solver.iter") if e.get("path") == "host"
    ]
    assert len(host_evs) == iters == len(seen)


def test_fused_cg_chunk_events(tel, monkeypatch):
    # force-mode fused CG (interpret off-TPU) reports per-chunk events
    # reusing its existing rho fetch; the kernel path is f32-only
    monkeypatch.setattr(settings, "fused_cg", "force")
    n = 256
    e = np.ones(n, dtype=np.float32)
    S = sp.diags([-e[:-1], 4.0 * e, -e[:-1]], [-1, 0, 1]).tocsr()
    A = sparse_tpu.csr_array(S.astype(np.float32)).todia()
    b = np.ones(n, dtype=np.float32)
    x, iters = linalg.cg(A, b, tol=1e-5, conv_test_iters=10)
    fused_evs = [
        e for e in telemetry.events("solver.iter") if e.get("path") == "fused"
    ]
    assert fused_evs, "fused path produced no chunk events"
    assert fused_evs[-1]["iter"] == iters
    solves = telemetry.events("solver.solve")
    assert solves[-1]["path"] == "fused"


def test_jsonl_sink_schema_valid(tel):
    A, b = _laplacian()
    linalg.cg(A, b, tol=1e-8)
    linalg.gmres(A, b, tol=1e-8)
    path = str(tel)
    problems = telemetry.schema.validate_jsonl(path)
    assert problems == []
    lines = [json.loads(ln) for ln in open(path) if ln.strip()]
    assert lines and all("kind" in ev and "ts" in ev for ev in lines)


def test_schema_validator_catches_bad_events():
    assert telemetry.schema.validate({"kind": "solver.iter", "ts": 1.0}) != []
    assert telemetry.schema.validate({"ts": 1.0}) != []
    assert telemetry.schema.validate({"kind": "span", "ts": 0}) != []
    assert (
        telemetry.schema.validate(
            {"kind": "comm.spmv", "ts": 1.0, "bytes": -4, "mode": "halo", "S": 2}
        )
        != []
    )
    # unknown kinds are forward-compatible: base fields suffice
    assert telemetry.schema.validate({"kind": "custom.thing", "ts": 1.0}) == []


# -- (b) autotune + kernel events -------------------------------------------


def test_autotune_gate_emits_event_and_never_poisons_cache(tel, monkeypatch):
    from sparse_tpu.kernels import dia_spmv as K

    monkeypatch.setattr(settings, "pallas_autotune", False)
    offsets = (-1, 0, 1)
    shape = (4096, 4096)
    key = (offsets, shape, "float32")
    K._TILE_CACHE.pop(key, None)
    data = jnp.ones((3, 4096), dtype=jnp.float32)
    tile, band = K.autotune_dia_tile(data, offsets, shape)
    assert tile == 65536 and band == {}
    # ADVICE r5: the gate result must NOT be memoized as a probe result —
    # flipping the setting on later in the session must still probe
    assert key not in K._TILE_CACHE
    evs = telemetry.events("autotune.result")
    assert evs and evs[-1]["probed"] is False
    assert evs[-1]["tile"] == 65536
    assert evs[-1]["reason"] == "autotune-disabled"


def test_autotune_backend_gate_reason(tel):
    from sparse_tpu.kernels import dia_spmv as K

    # pallas_autotune defaults True; off-TPU the backend gates
    offsets = (0,)
    shape = (2048, 2048)
    K._TILE_CACHE.pop((offsets, shape, "float32"), None)
    K.autotune_dia_tile(jnp.ones((1, 2048), jnp.float32), offsets, shape)
    evs = telemetry.events("autotune.result")
    assert evs and evs[-1]["reason"] == "backend-not-tpu"
    assert (offsets, shape, "float32") not in K._TILE_CACHE


# -- (b) distributed comm volumes -------------------------------------------


def test_shard_csr_records_spmv_comm_model(tel):
    from sparse_tpu.parallel.dist import shard_csr

    A, b = _laplacian(64)
    D = shard_csr(A)
    evs = telemetry.events("comm.spmv")
    assert evs, "shard_csr emitted no comm model event"
    ev = evs[-1]
    assert ev["S"] == D.S and ev["mode"] == D.mode
    assert ev["bytes"] >= 0
    # eager SpMV dispatches accumulate the structural per-call volume
    before = telemetry.counters().get("comm.spmv.calls", 0)
    D.spmv_padded(D.pad_vector(b))
    assert telemetry.counters().get("comm.spmv.calls", 0) == before + 1


def test_dist_cg_records_whole_solve_comm_volume(tel):
    from sparse_tpu.parallel.dist import comm_stats, dist_cg, shard_csr

    n = 128
    e = np.ones(n)
    S = sp.diags([-e[:-1], 4.0 * e, -e[:-1]], [-1, 0, 1]).tocsr()
    D = shard_csr(sparse_tpu.csr_array(S))
    b = np.ones(n)
    xp, iters, converged = dist_cg(D, b, tol=1e-8)
    assert converged
    evs = telemetry.events("comm.cg")
    assert evs
    ev = evs[-1]
    assert ev["iters"] == iters and ev["S"] == D.S
    cs = comm_stats(D)
    assert ev["bytes"] == int(
        cs["cg_iter_collective_bytes_per_shard"]
    ) * iters * D.S
    assert any(
        e["solver"] == "dist_cg" for e in telemetry.events("solver.solve")
    )


def test_dist_sort_sample_records_exchange_volume(tel):
    from sparse_tpu.parallel.sort import dist_sort_host

    keys = np.random.default_rng(5).permutation(1 << 10).astype(np.int64)
    sk, _ = dist_sort_host(keys)
    np.testing.assert_array_equal(sk, np.sort(keys))
    evs = telemetry.events("comm.sort")
    assert evs
    assert evs[-1]["S"] >= 1 and evs[-1]["bytes"] >= 0


# -- (c) trace safety --------------------------------------------------------


def test_span_noops_inside_jit_no_tracer_leak(tel):
    durs_before = telemetry.summary()["spans"]

    @jax.jit
    def f(x):
        # span must detect the active trace and degrade to the shared
        # no-op — never timing tracer ops, never calling block_until_ready
        with telemetry.span("inside.jit", sync=x):
            return x * 2.0

    out = f(jnp.ones(8))
    np.testing.assert_allclose(np.asarray(out), 2.0)
    assert "inside.jit" not in telemetry.summary()["spans"]
    assert durs_before == {} or True  # no exception is the contract


def test_span_records_outside_trace(tel):
    x = jnp.ones(16)
    with telemetry.span("outer.op", sync=x, n=16):
        y = x + 1
    s = telemetry.summary()["spans"]
    assert "outer.op" in s and s["outer.op"]["n"] == 1
    assert s["outer.op"]["p95_s"] >= 0
    evs = telemetry.events("span")
    assert evs[-1]["name"] == "outer.op" and evs[-1]["n"] == 16


def test_instrumentation_does_not_change_outer_jit_behavior(tel):
    # cg under an OUTER jit is unsupported either way (its host sync
    # points concretize tracers — seed behavior); the telemetry contract
    # is that instrumentation neither fixes nor changes that: the same
    # error class surfaces, and no half-recorded tracer values leak into
    # the event stream
    A, b = _laplacian(32)
    Ad = jnp.asarray(np.asarray(A.todense()))

    @jax.jit
    def solve(bb):
        x, _ = linalg.cg(Ad, bb, tol=1e-8, maxiter=40, conv_test_iters=5)
        return x

    with pytest.raises(jax.errors.ConcretizationTypeError):
        solve(jnp.asarray(b))
    for ev in telemetry.events("solver.iter"):
        assert isinstance(ev["iter"], int)
        assert isinstance(ev.get("resid2", ev.get("resid", 0.0)), float)


# -- recorder mechanics ------------------------------------------------------


def test_ring_is_bounded(tel, monkeypatch):
    monkeypatch.setattr(settings, "telemetry_ring", 32)
    telemetry.reset()
    for i in range(100):
        telemetry.record("custom.tick", i=i)
    evs = telemetry.events("custom.tick")
    assert len(evs) == 32
    assert evs[-1]["i"] == 99  # newest survive


def test_sink_failure_is_nonfatal(tmp_path, monkeypatch):
    telemetry.reset()
    monkeypatch.setattr(settings, "telemetry", True)
    telemetry.configure(str(tmp_path / "no" / "such" / "dir" / "x.jsonl"))
    try:
        # make the directory uncreatable by occupying the parent as a file
        (tmp_path / "no").write_text("a file, not a dir")
        with pytest.warns(UserWarning, match="unwritable"):
            telemetry.record("custom.tick", i=1)
        # ring still records after the sink is dropped
        telemetry.record("custom.tick", i=2)
        assert len(telemetry.events("custom.tick")) == 2
    finally:
        telemetry.configure(None)
        telemetry.reset()


def test_summary_aggregates(tel):
    telemetry.count("k", 3)
    telemetry.add_bytes("comm.spmv.total", 256)
    for d in (0.001, 0.002, 0.003):
        telemetry.add_span("lat", d)
    s = telemetry.summary()
    assert s["counts"]["k"] == 3
    assert s["bytes_by_kind"]["comm.spmv.total"] == 256
    assert s["spans"]["lat"]["n"] == 3
    assert s["spans"]["lat"]["p50_s"] == pytest.approx(0.002)
    assert s["spans"]["lat"]["max_s"] == pytest.approx(0.003)


def test_provenance_scopes_counted(tel):
    A, b = _laplacian()
    linalg.cg(A, b, tol=1e-8)
    counts = telemetry.counters()
    assert counts.get("sparse_tpu.cg", 0) >= 1
    assert counts.get("host_sync.int", 0) >= 1


def test_ring_overflow_counts_dropped(tel, monkeypatch):
    # overflow used to be silent (the deque just evicts); now the drop
    # count is surfaced in summary() and rides the bench.session embed
    monkeypatch.setattr(settings, "telemetry_ring", 32)
    telemetry.reset()
    for i in range(100):
        telemetry.record("custom.tick", i=i)
    s = telemetry.summary()
    assert s["events"] == 32
    assert s["dropped"] == 68 == telemetry.dropped()
    telemetry.reset()
    assert telemetry.summary()["dropped"] == 0


def test_span_exception_records_error_and_timing(tel):
    # a span exiting on an exception keeps the timing, tags the event
    # with the exception type, and still attempts the best-effort sync
    with pytest.raises(ValueError):
        with telemetry.span("boom.op", sync=jnp.ones(4), n=4):
            raise ValueError("inner failure")
    ev = telemetry.events("span")[-1]
    assert ev["name"] == "boom.op"
    assert ev["error"] == "ValueError"
    assert ev["dur_s"] >= 0 and ev["n"] == 4
    assert telemetry.summary()["spans"]["boom.op"]["n"] == 1


# -- metrics registry (telemetry/_metrics.py) --------------------------------


def test_metrics_counter_gauge_histogram_semantics():
    from sparse_tpu.telemetry import _metrics as M

    c = M.counter("test.sem.counter", case="a")
    v0 = c.value
    c.inc()
    c.inc(2)
    # get-or-create: same name+labels is the same object; different
    # labels are a different series
    assert M.counter("test.sem.counter", case="a") is c
    assert M.counter("test.sem.counter", case="b") is not c
    assert c.value == v0 + 3

    g = M.gauge("test.sem.gauge")
    g.set(4.5)
    assert g.value == 4.5
    g.inc()
    g.dec(2)
    assert g.value == pytest.approx(3.5)
    lazy = M.gauge("test.sem.lazy", fn=lambda: 7)
    assert lazy.value == 7

    h = M.histogram("test.sem.hist")
    h.reset()
    obs = [1e-9, 0.25, 3.0, 1e12, float("inf")]
    for v in obs:
        h.observe(v)
    h.observe(float("nan"))  # ignored, never poisons sum/count
    assert h.count == len(obs)
    buckets = h.buckets()
    # cumulative and complete: monotone, +Inf bucket holds everything
    accs = [acc for _b, acc in buckets]
    assert accs == sorted(accs) and accs[-1] == len(obs)
    # each finite observation lands at (or below) its own power of two
    import math

    assert buckets[-1][0] == math.inf


def test_metrics_text_prometheus_exposition(tel):
    from sparse_tpu import plan_cache

    class Obj:
        pass

    o = Obj()
    plan_cache.get(o, "test.kind", lambda: "plan")  # miss (build)
    plan_cache.get(o, "test.kind", lambda: "plan")  # hit
    txt = telemetry.metrics_text()
    assert "# TYPE sparse_tpu_plan_cache_hits_total counter" in txt
    assert "# TYPE sparse_tpu_plan_cache_size gauge" in txt
    # acceptance surface: plan_cache hit/miss and solver anomaly counts
    hits = [
        ln for ln in txt.splitlines()
        if ln.startswith("sparse_tpu_plan_cache_hits_total ")
    ]
    misses = [
        ln for ln in txt.splitlines()
        if ln.startswith("sparse_tpu_plan_cache_misses_total ")
    ]
    assert hits and float(hits[0].split()[-1]) >= 1
    assert misses and float(misses[0].split()[-1]) >= 1
    assert "sparse_tpu_solver_anomalies_total" in txt
    # sample lines are Prometheus-shaped: sanitized name, numeric value
    for ln in txt.splitlines():
        if not ln or ln.startswith("#"):
            continue
        name = ln.split("{")[0].split()[0]
        assert name.replace("_", "a").replace(":", "a").isalnum(), ln
        float(ln.rsplit(None, 1)[1].replace("+Inf", "inf"))
    # the registry numbers match the stats() readback
    assert plan_cache.stats()["hits"] == float(hits[0].split()[-1])


def test_metrics_disabled_path_allocates_nothing(monkeypatch):
    telemetry.reset()
    monkeypatch.setattr(settings, "telemetry", False)
    from sparse_tpu.telemetry import _metrics as M

    before = len(M._REGISTRY)
    telemetry.count("never.counted", 3)
    telemetry.add_bytes("never.bytes", 10)
    # the disabled path returns before touching the registry: no new
    # series, nothing to read back
    assert len(M._REGISTRY) == before
    assert telemetry.counters() == {}
    assert telemetry.bytes_by_kind() == {}


def test_batch_service_levels_on_registry(tel):
    from sparse_tpu.batch.service import SolveSession
    from sparse_tpu.telemetry import _metrics as M

    e = np.ones(12)
    S = sp.diags([-e[:-1], 4.0 * e, -e[:-1]], [-1, 0, 1]).tocsr()
    depth = M.gauge("batch.queue_depth")
    occ = M.histogram("batch.bucket_occupancy")
    n_obs = occ.count
    sess = SolveSession("cg", batch_max=4)
    for _ in range(3):
        sess.submit(sparse_tpu.csr_array(S), np.ones(12), tol=1e-8)
    assert depth.value >= 3
    sess.flush()
    assert depth.value == 0
    assert occ.count == n_obs + 1  # one bucket dispatched, one ratio


# -- trace export (telemetry/_trace.py) --------------------------------------


def test_trace_export_synthetic_session(tel, tmp_path):
    with telemetry.span("solve.outer", n=8):
        with telemetry.span("solve.inner"):
            pass
    telemetry.record(
        "solver.iter", solver="cg", path="host", iter=1, resid2=2.0
    )
    telemetry.record("comm.spmv", bytes=128, mode="halo", S=2)
    out = tmp_path / "trace.json"
    telemetry.export_trace(str(out))
    t = json.load(open(out))
    evs = t["traceEvents"]
    assert isinstance(evs, list) and evs
    for e in evs:
        assert {"ph", "pid", "tid", "name"} <= set(e)
        if e["ph"] != "M":
            assert isinstance(e["ts"], (int, float))
    # spans become complete slices; the inner span nests inside the outer
    spans = {e["name"]: e for e in evs if e["ph"] == "X"}
    assert set(spans) == {"solve.outer", "solve.inner"}
    outer, inner = spans["solve.outer"], spans["solve.inner"]
    assert outer["tid"] == inner["tid"]  # same family track => nesting
    assert outer["ts"] <= inner["ts"] + 1e-3
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    # solver iterations also feed a resid2 counter track
    counters = [e for e in evs if e["ph"] == "C"]
    assert counters and counters[0]["args"]["resid2"] == 2.0
    # subsystem lanes are named processes
    pnames = {
        e["args"]["name"] for e in evs
        if e["ph"] == "M" and e["name"] == "process_name"
    }
    assert {"sparse_tpu/solver", "sparse_tpu/comm", "sparse_tpu/spans"} <= pnames


def test_trace_export_from_jsonl_source(tel, tmp_path):
    A, b = _laplacian()
    linalg.cg(A, b, tol=1e-8)
    out = tmp_path / "trace.json"
    telemetry.export_trace(str(out), source=str(tel))
    t = json.load(open(out))
    iters = [
        e for e in t["traceEvents"]
        if e["ph"] == "i" and e["name"] == "solver.iter"
    ]
    assert iters, "logged solver iterations must appear on the timeline"


# -- solver health monitor (telemetry/_health.py) ----------------------------


def test_health_nan_detected_in_tiny_cg(tel):
    n = 8
    e = np.ones(n)
    S = sp.diags([-e[:-1], 2.0 * e, -e[:-1]], [-1, 0, 1]).tocsr()
    S.data[0] = np.nan  # forced NaN: first matvec poisons the residual
    linalg.cg(sparse_tpu.csr_array(S), np.ones(n), tol=1e-10, maxiter=20)
    evs = telemetry.events("solver.anomaly")
    assert evs and evs[0]["solver"] == "cg"
    assert evs[0]["reason"] == "nonfinite"
    rep = telemetry.last_solve_report()
    assert rep is not None and rep["solver"] == "cg"
    assert any(a["reason"] == "nonfinite" for a in rep["anomalies"])
    assert rep["iters"] is not None  # solver.solve finalized the report
    # one event per (reason, lane) per solve — never one per iteration
    assert len([e for e in evs if e["reason"] == "nonfinite"]) == 1


def test_health_stagnation_detected_in_tiny_cg(tel):
    from sparse_tpu.telemetry import _health

    # singular diagonal with b in the null direction: the residual is
    # bit-invariant across iterations — the textbook stall
    n = 8
    d = np.ones(n)
    d[-1] = 0.0
    A = sparse_tpu.csr_array(sp.diags([d], [0]).tocsr())
    b = np.zeros(n)
    b[-1] = 1.0
    linalg.cg(
        A, b, tol=1e-12, maxiter=_health.STALL_WINDOW + 10,
        conv_test_iters=1000,
    )
    reasons = {e["reason"] for e in telemetry.events("solver.anomaly")}
    assert "stagnation" in reasons
    rep = telemetry.last_solve_report()
    assert any(a["reason"] == "stagnation" for a in rep["anomalies"])


def test_health_divergence_detector_direct(tel):
    h = telemetry.health
    h.reset()
    h.observe("cg", 1, 1.0)
    h.observe("cg", 2, 1e12)  # 1e12 > best * DIVERGENCE_FACTOR
    rep = telemetry.last_solve_report()
    assert any(a["reason"] == "divergence" for a in rep["anomalies"])
    evs = telemetry.events("solver.anomaly")
    assert evs[-1]["reason"] == "divergence" and evs[-1]["iter"] == 2


def test_health_batched_lane_anomaly(tel):
    from sparse_tpu.batch.krylov import batched_cg
    from sparse_tpu.batch.operator import BatchedCSR, SparsityPattern

    n = 16
    e = np.ones(n)
    S = sp.diags([-e[:-1], 3.0 * e, -e[:-1]], [-1, 0, 1]).tocsr()
    pat = SparsityPattern.from_csr(sparse_tpu.csr_array(S))
    op = BatchedCSR(pat, np.stack([S.data] * 3))
    b = np.ones((3, n))
    b[1, 0] = np.nan  # poison exactly one lane
    X, info = batched_cg(op, b, tol=1e-8, maxiter=30)
    evs = telemetry.events("solver.anomaly")
    nan_evs = [e for e in evs if e["reason"] == "nonfinite"]
    assert nan_evs and all(e.get("lane") == 1 for e in nan_evs)
    rep = telemetry.last_solve_report()
    assert rep["lanes"] == 3
    assert any(
        a["reason"] == "nonfinite" and a.get("lane") == 1
        for a in rep["anomalies"]
    )
    # healthy lanes converged and stayed clean
    conv = np.asarray(info.converged)
    assert bool(conv[0]) and bool(conv[2]) and not bool(conv[1])


def test_health_clean_solve_reports_no_anomalies(tel):
    A, b = _laplacian()
    x, iters = linalg.cg(A, b, tol=1e-10)
    rep = telemetry.last_solve_report()
    assert rep["solver"] == "cg" and rep["iters"] == iters
    assert rep["anomalies"] == []
    assert len(rep["resid_history"]) >= min(iters, 1)
    assert telemetry.events("solver.anomaly") == []


def test_health_zero_overhead_when_disabled(monkeypatch):
    telemetry.reset()
    monkeypatch.setattr(settings, "telemetry", False)
    telemetry.health.observe("cg", 1, float("nan"))
    telemetry.health.end_solve("cg", 5)
    assert telemetry.last_solve_report() is None


# -- Axon v3: request-scoped trace context (telemetry/_context.py) -----------


def test_ticket_ids_unique_and_scoped(tel):
    ids = {telemetry.new_ticket_id() for _ in range(64)}
    assert len(ids) == 64
    assert all(i.startswith("tk-") for i in ids)
    assert telemetry.current_tickets() == ()
    with telemetry.ticket_scope("tk-a", "tk-b"):
        assert telemetry.current_tickets() == ("tk-a", "tk-b")
        # REPLACE semantics: a nested scope (the requeue dispatch) owns
        # the context, and the outer set comes back on exit
        with telemetry.ticket_scope("tk-c"):
            assert telemetry.current_tickets() == ("tk-c",)
        assert telemetry.current_tickets() == ("tk-a", "tk-b")
    assert telemetry.current_tickets() == ()


def test_events_inside_scope_carry_tickets(tel):
    telemetry.record("span", name="outside", dur_s=0.0)
    with telemetry.ticket_scope("tk-x"):
        telemetry.record("span", name="inside", dur_s=0.0)
        # explicit ticket fields are authoritative — never overwritten
        telemetry.record("batch.requeue", solver="gmres", lanes=1,
                         tickets=["tk-explicit"])
        telemetry.record("batch.ticket", ticket="tk-own", state="done")
    by_kind = {}
    for e in telemetry.events():
        by_kind.setdefault(e["kind"], []).append(e)
    spans = {e["name"]: e for e in by_kind["span"]}
    assert "tickets" not in spans["outside"]
    assert spans["inside"]["tickets"] == ["tk-x"]
    assert by_kind["batch.requeue"][0]["tickets"] == ["tk-explicit"]
    assert "tickets" not in by_kind["batch.ticket"][0]


def test_ticket_scope_zero_cost_when_disabled(monkeypatch):
    telemetry.reset()
    monkeypatch.setattr(settings, "telemetry", False)
    with telemetry.ticket_scope("tk-z"):
        assert telemetry.record("span", name="n", dur_s=0.0) is None
    assert telemetry.events() == []


# -- Axon v3: Prometheus exposition conformance (_metrics.metrics_text) ------


def test_metrics_text_escapes_label_values(tel):
    from sparse_tpu.telemetry import _metrics as M

    try:
        M.counter(
            "test.escape.counter",
            prog='back\\slash "quoted"\nnewline',
        ).inc()
        txt = telemetry.metrics_text()
        (line,) = [
            ln for ln in txt.splitlines()
            if ln.startswith("sparse_tpu_test_escape_counter_total{")
        ]
        # the raw control characters never reach the exposition...
        assert "\n" not in line  # splitlines guarantees it; belt+braces
        assert '\\\\' in line and '\\"' in line and "\\n" in line
        assert line.endswith("} 1")
        # ...and a conformant parser recovers the original value
        val = line[line.index('{') + 1:line.rindex('}')]
        assert val == 'prog="back\\\\slash \\"quoted\\"\\nnewline"'
    finally:
        M.remove("test.escape.counter")


def test_metrics_text_help_type_and_histogram_series(tel):
    from sparse_tpu.telemetry import _metrics as M

    try:
        M.counter("test.fmt.counter", help="counts things").inc(2)
        M.gauge("test.fmt.gauge", help="level\nwith newline").set(1.5)
        h = M.histogram("test.fmt.hist", solver="cg")
        for v in (0.001, 0.5, 3.0):
            h.observe(v)
        txt = telemetry.metrics_text()
        lines = txt.splitlines()
        # every family leads with HELP then TYPE, in that order
        for i, ln in enumerate(lines):
            if ln.startswith("# TYPE "):
                assert lines[i - 1].startswith(
                    "# HELP " + ln.split()[2] + " "
                ), ln
        assert "# HELP sparse_tpu_test_fmt_counter_total counts things" \
            in lines
        # newline in HELP text is escaped per the format spec
        assert ("# HELP sparse_tpu_test_fmt_gauge level\\nwith newline"
                in lines)
        assert "# TYPE sparse_tpu_test_fmt_hist histogram" in lines
        # the three conventional histogram series, cumulative buckets,
        # +Inf bucket == _count, le label present on every _bucket line
        bucket = [
            ln for ln in lines
            if ln.startswith("sparse_tpu_test_fmt_hist_bucket")
        ]
        assert bucket and all('le="' in ln for ln in bucket)
        assert 'solver="cg"' in bucket[0]
        counts = [float(ln.rsplit(None, 1)[1]) for ln in bucket]
        assert counts == sorted(counts)
        inf_line = [ln for ln in bucket if 'le="+Inf"' in ln]
        assert len(inf_line) == 1 and counts[-1] == 3.0
        (cnt,) = [
            ln for ln in lines
            if ln.startswith("sparse_tpu_test_fmt_hist_count")
        ]
        (tot,) = [
            ln for ln in lines
            if ln.startswith("sparse_tpu_test_fmt_hist_sum")
        ]
        assert float(cnt.rsplit(None, 1)[1]) == 3.0
        assert float(tot.rsplit(None, 1)[1]) == pytest.approx(3.501)
    finally:
        for name in ("test.fmt.counter", "test.fmt.gauge",
                     "test.fmt.hist"):
            M.remove(name)


# -- Axon v3: live serving exporter (telemetry/_serve.py) --------------------


def _scrape(url, timeout=5):
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read()


def test_serve_endpoints_scrape_and_shutdown(tel):
    import urllib.error

    assert telemetry.serving() is None
    srv = telemetry.serve(port=0)
    try:
        assert srv.port > 0
        # serve() is idempotent while running
        assert telemetry.serve(port=0) is srv
        assert telemetry.serving() is srv

        code, ctype, body = _scrape(srv.url + "/metrics")
        assert code == 200 and ctype.startswith("text/plain")
        assert "version=0.0.4" in ctype
        text = body.decode()
        assert "# TYPE sparse_tpu_plan_cache_hits_total counter" in text
        assert "# HELP " in text

        code, ctype, body = _scrape(srv.url + "/healthz")
        assert code == 200 and ctype.startswith("application/json")
        hz = json.loads(body)
        assert hz["status"] in ("ok", "degraded")
        for key in ("last_solve_anomalies", "failover_latches", "faults",
                    "uptime_s"):
            assert key in hz
        assert hz["faults"]["active"] is False

        code, ctype, body = _scrape(srv.url + "/session")
        sess = json.loads(body)
        for key in ("queue_depth", "dispatches", "sessions", "programs",
                    "cold_start_s", "slo_misses"):
            assert key in sess

        with pytest.raises(urllib.error.HTTPError) as ei:
            _scrape(srv.url + "/nope")
        assert ei.value.code == 404
    finally:
        srv.stop()
    assert telemetry.serving() is None
    # a stopped exporter's port is actually released (clean shutdown)
    with pytest.raises(Exception):
        _scrape(srv.url + "/metrics", timeout=1)


def test_serve_healthz_reflects_failover_latch(tel):
    from sparse_tpu.resilience import failover
    from sparse_tpu.telemetry import _serve

    failover.clear()
    try:
        failover.mark_failed("dia_spmv", error="lowering boom")
        hz = _serve._healthz()
        assert hz["status"] == "degraded"
        assert hz["failover_latches"]["dia_spmv"]["kernel_wide"] is True
        assert "lowering boom" in hz["failover_latches"]["dia_spmv"]["error"]
    finally:
        failover.clear()
    assert _serve._healthz()["status"] in ("ok", "degraded")


# -- Axon v3: compile-time cost attribution (telemetry/_cost.py) -------------


def test_cost_attribute_captures_compile_and_emits_event(tel):
    from sparse_tpu.telemetry import _cost

    @jax.jit
    def prog(x):
        return (x * 2.0).sum()

    x = jnp.ones(64)
    before = _cost.total_compile_s()
    wrapped, info = _cost.attribute(
        "test.prog.unit", prog, (x,), pack_s=0.001,
        solver="cg", bucket=4, dtype="<f8",
    )
    assert info["program"] == "test.prog.unit"
    assert info["compile_s"] >= 0 and info["pack_s"] == 0.001
    # the wrapped program computes the same thing through the AOT path
    assert float(wrapped(x)) == float(prog(x))
    assert "test.prog.unit" in _cost.programs()
    assert _cost.total_compile_s() > before
    (ev,) = telemetry.events("plan_cache.compile")
    assert ev["program"] == "test.prog.unit" and ev["solver"] == "cg"
    assert not telemetry.schema.validate(ev)
    # per-program gauges landed in the exposition
    txt = telemetry.metrics_text()
    assert "sparse_tpu_plan_cache_program_compile_s" in txt
    assert 'program="test.prog.unit"' in txt
    # cold-start budget includes both compile and pack shares
    assert _cost.total_compile_s() - before == pytest.approx(
        info["compile_s"] + 0.001, abs=1e-9
    )


def test_cost_attribute_non_aot_callable_degrades(tel):
    from sparse_tpu.telemetry import _cost

    def plain(x):  # no .lower: the GMRES host-driven closure shape
        return x + 1

    wrapped, info = _cost.attribute("test.prog.plain", plain, (1,))
    assert wrapped is plain and "compile_s" not in info
    assert _cost.programs()["test.prog.plain"]["program"] == \
        "test.prog.plain"


def test_cost_program_wrapper_falls_back_on_arg_drift(tel):
    from sparse_tpu.telemetry._cost import _Program

    calls = {"fn": 0}

    def fn(x):
        calls["fn"] += 1
        return x * 2

    class Rejecting:
        def __call__(self, x):
            raise TypeError("layout drift")

    p = _Program(fn, Rejecting())
    assert p(3) == 6 and calls["fn"] == 1
    assert p.compiled is None  # permanently reverted to the jit path
    assert p(4) == 8 and calls["fn"] == 2


# -- Axon v3: health-monitor dedup across sequential solves ------------------


def test_health_anomaly_dedup_across_sequential_solves(tel):
    """One ``solver.anomaly`` per (reason, lane) per solve — a session
    running several solves gets one event per solve, not one total and
    not one per iteration; the metrics counter stays cumulative."""
    from sparse_tpu.telemetry import _metrics as M

    n = 8
    e = np.ones(n)
    S = sp.diags([-e[:-1], 2.0 * e, -e[:-1]], [-1, 0, 1]).tocsr()
    S.data[0] = np.nan
    A = sparse_tpu.csr_array(S)
    b = np.ones(n)
    c0 = M.counter("solver.anomalies.by_reason",
                   reason="nonfinite").value
    for _ in range(3):
        linalg.cg(A, b, tol=1e-10, maxiter=20)
    evs = [
        e for e in telemetry.events("solver.anomaly")
        if e["reason"] == "nonfinite"
    ]
    assert len(evs) == 3
    # each solve's report was finalized separately: the LAST report has
    # exactly one nonfinite anomaly, not three accumulated
    rep = telemetry.last_solve_report()
    assert len([
        a for a in rep["anomalies"] if a["reason"] == "nonfinite"
    ]) == 1
    assert M.counter("solver.anomalies.by_reason",
                     reason="nonfinite").value == c0 + 3


# -- Axon v5: the SLO watchdog (telemetry/_watchdog.py) ----------------------


def _box_rule(name="box", trigger=10.0, **kw):
    """A rule whose value is a mutable box — deterministic tick fodder."""
    from sparse_tpu.telemetry import _watchdog

    box = {"v": 0.0}
    rule = _watchdog.Rule(name, lambda: box["v"], trigger, **kw)
    return box, rule


def test_watchdog_fires_and_clears_with_hysteresis(tel):
    from sparse_tpu.telemetry import _metrics as M
    from sparse_tpu.telemetry import _watchdog

    box, rule = _box_rule(trigger=10.0, clear=5.0, severity="page")
    wd = _watchdog.Watchdog(rules=[rule])
    c0 = M.counter("watchdog.alerts", rule="box", severity="page").value
    assert wd.evaluate(now=0.0) == []  # 0 <= trigger: ok
    box["v"] = 11.0
    trans = wd.evaluate(now=1.0)
    assert [t["event"] for t in trans] == ["alert"]
    assert wd.active() == ["box"]
    assert M.counter(
        "watchdog.alerts", rule="box", severity="page"
    ).value == c0 + 1
    # hysteresis: back under the trigger but above clear stays firing
    box["v"] = 7.0
    assert wd.evaluate(now=2.0) == []
    assert wd.active() == ["box"]
    box["v"] = 4.0
    trans = wd.evaluate(now=3.0)
    assert [t["event"] for t in trans] == ["clear"]
    assert wd.active() == []
    kinds = [e["kind"] for e in telemetry.events()
             if e["kind"].startswith("watchdog.")]
    assert kinds == ["watchdog.alert", "watchdog.clear"]
    alert = telemetry.events("watchdog.alert")[0]
    assert telemetry.schema.validate(alert) == []
    assert alert["rule"] == "box" and alert["severity"] == "page"
    clear = telemetry.events("watchdog.clear")[0]
    assert telemetry.schema.validate(clear) == []
    assert clear["active_s"] == pytest.approx(2.0)


def test_watchdog_for_ticks_and_cooldown():
    from sparse_tpu.telemetry import _watchdog

    box, rule = _box_rule(trigger=1.0, for_ticks=2, cooldown_s=10.0)
    wd = _watchdog.Watchdog(rules=[rule])
    box["v"] = 5.0
    assert wd.evaluate(now=0.0) == []  # 1st breach tick: armed only
    assert wd.evaluate(now=1.0) != []  # 2nd consecutive: alert
    box["v"] = 0.0
    assert wd.evaluate(now=2.0) != []  # clear
    # cooldown: the condition returns immediately but re-alerting is
    # suppressed until 10s past the clear
    box["v"] = 5.0
    assert wd.evaluate(now=3.0) == []
    assert wd.evaluate(now=4.0) == []
    assert wd.active() == []
    trans = wd.evaluate(now=13.0)  # cooldown expired (clear was at t=2)
    assert [t["event"] for t in trans] == ["alert"]
    # a flapping value never re-arms mid-streak
    box["v"] = 0.0
    wd.evaluate(now=14.0)


def test_watchdog_slo_miss_rate_rule_windows():
    from sparse_tpu.telemetry import _metrics as M
    from sparse_tpu.telemetry import _watchdog

    rule = _watchdog.slo_miss_rate_rule(trigger=0.5, clear=0.1)
    wd = _watchdog.Watchdog(rules=[rule])
    wd.evaluate()  # priming tick: snapshots taken, no value yet
    assert wd.active() == []
    # a window where 3 of 4 resolved tickets missed the SLO
    h = M.histogram("batch.ticket_latency", solver="wdtest")
    for _ in range(4):
        h.observe(0.05)
    M.counter("batch.slo_misses").inc(3)
    wd.evaluate()
    assert wd.active() == ["slo_miss_rate"]
    # idle window (denominator unmoved): no state change either way
    wd.evaluate()
    assert wd.active() == ["slo_miss_rate"]
    # a clean window clears
    for _ in range(10):
        h.observe(0.001)
    wd.evaluate()
    assert wd.active() == []


def test_watchdog_default_rules_construct_and_tick():
    from sparse_tpu.telemetry import _watchdog

    wd = _watchdog.Watchdog()  # the stock rule set
    names = {r.name for st in [wd._states] for r in
             [s.rule for s in st.values()]}
    assert {"slo_fast_burn", "slo_slow_burn", "anomaly_rate",
            "queue_depth", "device_occupancy", "vault_quarantine",
            "mesh_change", "failover_latched"} <= names
    wd.evaluate()
    wd.evaluate()  # two ticks: windowed rules produce values, no crash
    st = wd.state()
    assert st["enabled"] and st["ticks"] == 2
    assert isinstance(st["rules"], list) and len(st["rules"]) == len(names)


def test_watchdog_thread_start_stop():
    from sparse_tpu.telemetry import _watchdog

    box, rule = _box_rule(trigger=1e18)
    wd = _watchdog.Watchdog(rules=[rule], interval_s=0.02)
    wd.start()
    try:
        deadline = time.time() + 5.0
        while wd.ticks < 2 and time.time() < deadline:
            time.sleep(0.01)
        assert wd.ticks >= 2
        assert wd.state()["running"]
    finally:
        wd.stop()
    assert not wd.state()["running"]


def test_watchdog_singleton_and_alerts_endpoint_round_trip(tel):
    """/alerts serves the process watchdog's state; /healthz summarizes
    the firing set and reports degraded (the ISSUE 11 serve surface)."""
    import json as _json
    import urllib.request

    from sparse_tpu.telemetry import _watchdog

    telemetry.stop_watchdog()
    telemetry.stop_serving()
    box = {"v": 100.0}
    wd = telemetry.watchdog(rules=[
        _watchdog.Rule("rt", lambda: box["v"], 10.0, severity="page"),
    ])
    assert telemetry.watchdog(rules=[]) is wd  # get-or-create
    wd.evaluate()
    try:
        server = telemetry.serve(port=0)
        body = urllib.request.urlopen(
            server.url + "/alerts", timeout=5
        ).read()
        alerts = _json.loads(body)
        assert alerts["enabled"] and alerts["active"] == ["rt"]
        (row,) = alerts["rules"]
        assert row["state"] == "firing" and row["value"] == 100.0
        hz = _json.loads(urllib.request.urlopen(
            server.url + "/healthz", timeout=5
        ).read())
        assert hz["alerts"]["active"] == ["rt"]
        assert hz["status"] == "degraded"
        # clearing the rule restores ok on both surfaces
        box["v"] = 0.0
        wd.evaluate()
        alerts = _json.loads(urllib.request.urlopen(
            server.url + "/alerts", timeout=5
        ).read())
        assert alerts["active"] == []
        hz = _json.loads(urllib.request.urlopen(
            server.url + "/healthz", timeout=5
        ).read())
        assert hz["alerts"]["active"] == [] and hz["status"] == "ok"
    finally:
        telemetry.stop_serving()
        telemetry.stop_watchdog()
    assert telemetry.watchdog_state()["enabled"] is False


def test_alerts_endpoint_without_watchdog_is_disabled_stub(tel):
    import json as _json
    import urllib.request

    telemetry.stop_watchdog()
    telemetry.stop_serving()
    try:
        server = telemetry.serve(port=0)
        alerts = _json.loads(urllib.request.urlopen(
            server.url + "/alerts", timeout=5
        ).read())
        assert alerts == {"enabled": False, "running": False,
                          "active": [], "rules": []}
    finally:
        telemetry.stop_serving()


def test_serve_busy_port_falls_back_to_ephemeral():
    """ISSUE 11 satellite: a taken port must not raise — the exporter
    binds an ephemeral port and reports it on the handle."""
    import socket

    telemetry.stop_serving()
    blocker = socket.socket()
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    busy = blocker.getsockname()[1]
    try:
        server = telemetry.serve(port=busy)
        assert server.port != busy and server.port > 0
        assert server.fallback and server.requested_port == busy
        import urllib.request

        body = urllib.request.urlopen(server.url + "/", timeout=5).read()
        assert b"/alerts" in body
    finally:
        telemetry.stop_serving()
        blocker.close()


def test_metrics_family_readback():
    from sparse_tpu.telemetry import _metrics as M

    M.histogram("wd.fam.test", a="1").observe(1.0)
    M.histogram("wd.fam.test", a="2").observe(2.0)
    fam = M.family("wd.fam.test")
    assert len(fam) == 2
    assert sum(h.count for h in fam) == 2
    M.remove("wd.fam.test")
    assert M.family("wd.fam.test") == []
