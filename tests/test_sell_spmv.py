"""SELL-C-sigma prepared SpMV: pack correctness, mode parity, fallbacks.

The prepared general-matrix path of ISSUE 2: every ``spmv_mode`` must agree
with the dense/scipy oracle on the awkward shapes (empty rows, zero-nnz,
duplicate columns, dtype axis, power-law row-length skew), with the plan
cache enabled and disabled, and the Pallas row-block kernel (interpret mode
off-TPU) must match the XLA slab formulation exactly.
"""

import gc

import numpy as np
import pytest
import scipy.sparse as sp

import sparse_tpu
from sparse_tpu import plan_cache
from sparse_tpu.config import Settings, settings
from sparse_tpu.kernels.sell_spmv import PreparedCSR, sell_pack

from .utils.sample import sample_csr, sample_vec

MODES = ("segment", "ell", "sell", "pallas", "auto")


def powerlaw_csr(m=300, seed=5, dtype=np.float64):
    """Pathological power-law row-length profile (plus one near-dense row):
    the shape where ELL's global-max padding explodes."""
    rng = np.random.default_rng(seed)
    deg = np.minimum((rng.pareto(1.0, m) * 3 + 1).astype(int), m - 1)
    deg[0] = m - 1  # one near-dense row pins the global max
    rows = np.repeat(np.arange(m), deg)
    cols = rng.integers(0, m, rows.shape[0])
    vals = rng.standard_normal(rows.shape[0])
    if np.issubdtype(dtype, np.complexfloating):
        vals = vals + 1j * rng.standard_normal(rows.shape[0])
    return sp.coo_matrix((vals.astype(dtype), (rows, cols)), shape=(m, m)).tocsr()


def _cases():
    """(label, scipy_csr) pairs for the parity sweep."""
    out = [
        ("random_f64", sample_csr(37, 29, density=0.25, seed=1)),
        ("random_f32", sample_csr(23, 31, dtype=np.float32, seed=2)),
        ("c64", sample_csr(19, 19, dtype=np.complex64, seed=3)),
        ("powerlaw", powerlaw_csr(120, seed=4)),
        ("zero_nnz", sp.csr_matrix((7, 5), dtype=np.float64)),
        (
            "empty_rows",
            sp.csr_matrix(
                (np.array([1.0, 2.0]), np.array([1, 3]),
                 np.array([0, 0, 2, 2, 2, 2])),
                shape=(5, 4),
            ),
        ),
        (
            # duplicate column ids within a row (from_parts skips the
            # COO-dedup canonicalization) must sum, not drop
            "dup_cols",
            sp.csr_matrix(
                (np.array([1.0, 2.0, 4.0]), np.array([1, 1, 0]),
                 np.array([0, 2, 3, 3])),
                shape=(3, 3),
            ),
        ),
    ]
    return out


@pytest.mark.parametrize("mode", MODES)
@pytest.mark.parametrize("cache_on", [True, False], ids=["cache", "nocache"])
def test_spmv_mode_parity(mode, cache_on, monkeypatch):
    """Every mode x every awkward shape x cache on/off == dense reference."""
    monkeypatch.setattr(settings, "spmv_mode", mode)
    monkeypatch.setattr(settings, "plan_cache", cache_on)
    for label, s in _cases():
        A = sparse_tpu.csr_array.from_parts(
            s.data, s.indices, s.indptr, s.shape
        )
        rng = np.random.default_rng(11)
        x = rng.standard_normal(s.shape[1])
        if np.issubdtype(s.dtype, np.complexfloating):
            x = (x + 1j * rng.standard_normal(s.shape[1])).astype(s.dtype)
        dense = s.toarray()
        for rep in range(2):  # second call exercises the cached plan
            got = np.asarray(A @ x)
            np.testing.assert_allclose(
                got, dense @ x, rtol=2e-5, atol=2e-5,
                err_msg=f"{label} mode={mode} cache={cache_on} rep={rep}",
            )
        B = rng.standard_normal((s.shape[1], 4))
        np.testing.assert_allclose(
            np.asarray(A @ B), dense @ B, rtol=2e-5, atol=2e-5,
            err_msg=f"{label} spmm mode={mode} cache={cache_on}",
        )


@pytest.mark.parametrize("C,sigma,max_slabs", [(4, 0, 16), (8, 32, 16), (8, 64, 3), (16, 1000, 16)])
def test_sell_pack_geometry(C, sigma, max_slabs):
    """Pack invariants across chunk/window/slab-budget settings: exact SpMV,
    every nonzero stored once, pad bounded by the quantization guarantee."""
    s = powerlaw_csr(130, seed=9)
    plan, slabs, pos = sell_pack(
        s.indptr, s.indices, s.data, s.shape, C=C, sigma=sigma,
        max_slabs=max_slabs,
    )
    assert len(plan.slab_meta) <= max(max_slabs, 33)  # pow2 fallback bound
    total_vals = sum(int((np.asarray(vt) != 0).sum()) for _, vt in slabs)
    assert total_vals == int((s.data != 0).sum())
    x = np.random.default_rng(0).standard_normal(s.shape[1])
    from sparse_tpu.ops.spmv import csr_spmv_sell

    got = np.asarray(csr_spmv_sell(slabs, pos, np.asarray(x), plan.zero_rows))
    np.testing.assert_allclose(got, s @ x, rtol=1e-10, atol=1e-10)


def test_sell_beats_ell_padding_on_skew():
    """The point of the format: on the power-law profile the SELL stored
    slots stay near nnz while ELL's global-max padding is >10x."""
    s = powerlaw_csr(300, seed=5)
    plan, _, _ = sell_pack(s.indptr, s.indices, s.data, s.shape)
    kmax = int(np.diff(s.indptr).max())
    ell_slots = s.shape[0] * kmax
    assert plan.pad_ratio < 3.0
    assert ell_slots / max(s.nnz, 1) > 10 * plan.pad_ratio


def test_sell_pallas_interpret_matches_xla():
    """The Pallas row-block kernel (interpret off-TPU) == XLA slab path."""
    s = powerlaw_csr(90, seed=6).astype(np.float32)
    prep = PreparedCSR(s.indptr, s.indices, s.data, s.shape)
    x = np.random.default_rng(1).standard_normal(s.shape[1]).astype(np.float32)
    y_xla = np.asarray(prep.matvec_xla(x))
    y_pal = np.asarray(prep.matvec_pallas(x))
    np.testing.assert_allclose(y_pal, y_xla, rtol=1e-6, atol=1e-6)


def test_auto_mode_routes_skewed_to_sell(monkeypatch):
    """'auto' folds the SELL option in: a skewed profile packs a SELL plan,
    a tight (banded-free, bounded-degree) profile keeps the ELL path."""
    monkeypatch.setattr(settings, "spmv_mode", "auto")
    skewed = sparse_tpu.csr_array(powerlaw_csr(100, seed=8))
    x = np.random.default_rng(2).standard_normal(100)
    skewed @ x
    assert plan_cache.lookup(skewed, "sell") is not None

    tight = sparse_tpu.csr_array(sample_csr(40, 40, density=0.2, seed=3))
    tight @ np.random.default_rng(3).standard_normal(40)
    assert tight._ell is not None
    assert plan_cache.lookup(tight, "sell") is None


def test_sell_mode_env_roundtrip(monkeypatch):
    """SPARSE_TPU_SPMV_MODE round-trips through config for the new mode."""
    monkeypatch.setenv("SPARSE_TPU_SPMV_MODE", "sell")
    assert Settings().spmv_mode == "sell"
    monkeypatch.delenv("SPARSE_TPU_SPMV_MODE")
    assert Settings().spmv_mode == "auto"
    monkeypatch.setenv("SPARSE_TPU_PLAN_CACHE", "0")
    assert Settings().plan_cache is False


def test_prepare_api(monkeypatch):
    """csr_array.prepare() warms the mode's plan eagerly and returns self."""
    monkeypatch.setattr(settings, "spmv_mode", "sell")
    A = sparse_tpu.csr_array(powerlaw_csr(80, seed=10))
    assert A.prepare() is A
    assert plan_cache.lookup(A, "sell") is not None
    # explicit mode override does not disturb the ambient setting
    monkeypatch.setattr(settings, "spmv_mode", "segment")
    B = sparse_tpu.csr_array(powerlaw_csr(80, seed=11))
    B.prepare(mode="sell")
    assert settings.spmv_mode == "segment"
    assert plan_cache.lookup(B, "sell") is not None


def test_in_trace_cold_start_degrades_then_warm(monkeypatch):
    """First use inside a trace cannot pack (host syncs) and must still be
    correct; an eager warm then serves the compiled path the plan."""
    import jax

    monkeypatch.setattr(settings, "spmv_mode", "sell")
    s = powerlaw_csr(60, seed=12)
    A = sparse_tpu.csr_array(s)
    x = np.random.default_rng(4).standard_normal(60)
    y_cold = np.asarray(jax.jit(A._spmv)(np.asarray(x)))
    np.testing.assert_allclose(y_cold, s @ x, rtol=1e-10)
    assert plan_cache.lookup(A, "sell") is None  # no cache write in-trace
    A.prepare()
    y_warm = np.asarray(jax.jit(A._spmv)(np.asarray(x)))
    np.testing.assert_allclose(y_warm, s @ x, rtol=1e-10)


def test_dia_detection_fallback_emits_coverage_event(monkeypatch, tmp_path):
    """The (formerly silent) banded-detection degradation now records a
    coverage.fallback telemetry event and still returns a correct matvec."""
    import jax

    from sparse_tpu import telemetry

    offs = [-1, 0, 1]
    e = np.ones(32)
    s = sp.diags([e[:-1], 2 * e, e[:-1]], offs, format="csr")
    A = sparse_tpu.csr_array(s)

    def boom(offs_dev):
        raise jax.errors.JaxRuntimeError("UNIMPLEMENTED: transfer failed")

    monkeypatch.setattr(sparse_tpu.csr_array, "_fetch_offsets", staticmethod(boom))
    monkeypatch.setattr(settings, "telemetry", True)
    telemetry.configure(str(tmp_path / "t.jsonl"))
    telemetry.reset()
    try:
        with pytest.warns(UserWarning, match="detection"):
            y = np.asarray(A @ np.ones(32))
        np.testing.assert_allclose(y, s @ np.ones(32))
        evs = telemetry.events("coverage.fallback")
        assert len(evs) == 1
        assert evs[0]["op"] == "csr._maybe_dia"
        assert telemetry.schema.validate(evs[0]) == []
    finally:
        telemetry.configure(None)
        telemetry.reset()


def test_sell_plan_dies_with_matrix(monkeypatch):
    """_with_data / fresh objects never inherit a stale plan; collected
    matrices evict their plans (weak-ref keyed cache)."""
    monkeypatch.setattr(settings, "spmv_mode", "sell")
    s = powerlaw_csr(50, seed=13)
    A = sparse_tpu.csr_array(s)
    x = np.random.default_rng(5).standard_normal(50)
    A @ x
    A2 = A * 2.0  # fresh object -> fresh (cold) plan
    assert plan_cache.lookup(A2, "sell") is None
    np.testing.assert_allclose(np.asarray(A2 @ x), 2 * (s @ x), rtol=1e-10)
    before = plan_cache.stats()["size"]
    del A, A2
    gc.collect()
    assert plan_cache.stats()["size"] < before
