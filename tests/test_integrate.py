"""solve_ivp tests against scipy ground truth.

Reference analog: the reference tests integrate via the quantum demo; here we
compare directly with scipy.integrate.solve_ivp on classic systems (the
SURVEY §4 oracle pattern).
"""

import numpy as np
import pytest
import scipy.integrate as si

from sparse_tpu import integrate

METHODS = ["RK23", "RK45", "DOP853"]


def exp_decay(t, y):
    return -0.5 * y


def lotka(t, y):
    a, b, c, d = 1.5, 1.0, 3.0, 1.0
    return np.array([a * y[0] - b * y[0] * y[1], -c * y[1] + d * y[0] * y[1]])


@pytest.mark.parametrize("method", METHODS)
def test_exp_decay_vs_scipy(method):
    ref = si.solve_ivp(exp_decay, (0, 10), [2.0, 4.0], method=method, rtol=1e-8, atol=1e-10)
    out = integrate.solve_ivp(
        exp_decay, (0, 10), [2.0, 4.0], method=method, rtol=1e-8, atol=1e-10
    )
    assert out.success
    np.testing.assert_allclose(
        np.asarray(out.y)[:, -1], ref.y[:, -1], rtol=1e-7
    )
    np.testing.assert_allclose(
        np.asarray(out.y)[:, -1], 2 * np.exp(-5) * np.array([1.0, 2.0]), rtol=1e-6
    )


@pytest.mark.parametrize("method", METHODS)
def test_lotka_volterra_t_eval(method):
    # 1e-7 keeps both integrators on the same step-control regime at a
    # fraction of the step count 1e-9 forces out of the low-order RK23
    # (~5x fewer RHS evals); the assertion margin scales with it.
    t_eval = np.linspace(0, 10, 31)
    ref = si.solve_ivp(
        lotka, (0, 10), [10.0, 5.0], method=method, t_eval=t_eval, rtol=1e-7, atol=1e-9
    )
    out = integrate.solve_ivp(
        lotka, (0, 10), [10.0, 5.0], method=method, t_eval=t_eval, rtol=1e-7, atol=1e-9
    )
    np.testing.assert_allclose(out.t, ref.t)
    np.testing.assert_allclose(np.asarray(out.y), ref.y, rtol=2e-4, atol=1e-6)


@pytest.mark.parametrize("method", METHODS)
def test_dense_output(method):
    out = integrate.solve_ivp(
        exp_decay, (0, 5), [1.0], method=method, dense_output=True, rtol=1e-9, atol=1e-11
    )
    tq = np.linspace(0, 5, 17)
    yq = np.asarray(out.sol(tq))
    np.testing.assert_allclose(yq[0], np.exp(-0.5 * tq), rtol=1e-6)


@pytest.mark.parametrize("method", METHODS)
def test_complex_oscillator(method):
    # dy/dt = -i y  -> y = exp(-i t): the quantum-evolution shape (SURVEY §3.5)
    out = integrate.solve_ivp(
        lambda t, y: -1j * y,
        (0, 2 * np.pi),
        np.array([1.0 + 0j]),
        method=method,
        rtol=1e-9,
        atol=1e-11,
    )
    np.testing.assert_allclose(np.asarray(out.y)[0, -1], 1.0 + 0j, atol=1e-5)


def test_event_terminal():
    def hit_ground(t, y):
        return y[0]

    hit_ground.terminal = True
    hit_ground.direction = -1

    def cannon(t, y):
        return np.array([y[1], -9.8])

    out = integrate.solve_ivp(
        cannon, (0, 100), [0.0, 10.0], events=hit_ground, rtol=1e-9, atol=1e-11
    )
    assert out.status == 1
    # ballistic flight time 2*v/g
    np.testing.assert_allclose(out.t_events[0][0], 2 * 10.0 / 9.8, rtol=1e-6)
    ref = si.solve_ivp(
        cannon, (0, 100), [0.0, 10.0], events=hit_ground, rtol=1e-9, atol=1e-11
    )
    np.testing.assert_allclose(out.t_events[0], ref.t_events[0], rtol=1e-6)


def test_backward_integration():
    out = integrate.solve_ivp(exp_decay, (10, 0), [2 * np.exp(-5)], rtol=1e-9, atol=1e-11)
    assert out.success
    np.testing.assert_allclose(np.asarray(out.y)[0, -1], 2.0, rtol=1e-6)


def test_sparse_matvec_rhs():
    """ODE whose RHS is a sparse SpMV — the quantum-evolution composition."""
    import sparse_tpu

    H = sparse_tpu.diags(
        [np.full(9, 1.0), np.full(10, -2.0), np.full(9, 1.0)], [-1, 0, 1]
    ).tocsr()
    y0 = np.zeros(10)
    y0[5] = 1.0

    out = integrate.solve_ivp(
        lambda t, y: H @ y, (0, 1), y0, method="RK45", rtol=1e-9, atol=1e-11
    )
    import scipy.sparse as sp

    Hs = sp.diags([np.full(9, 1.0), np.full(10, -2.0), np.full(9, 1.0)], [-1, 0, 1]).tocsr()
    ref = si.solve_ivp(lambda t, y: Hs @ y, (0, 1), y0, method="RK45", rtol=1e-9, atol=1e-11)
    np.testing.assert_allclose(np.asarray(out.y)[:, -1], ref.y[:, -1], rtol=1e-6, atol=1e-9)


def test_args_passing():
    out = integrate.solve_ivp(
        lambda t, y, k: -k * y, (0, 1), [1.0], args=(2.0,), rtol=1e-9, atol=1e-11
    )
    np.testing.assert_allclose(np.asarray(out.y)[0, -1], np.exp(-2.0), rtol=1e-6)


def test_args_unhashable():
    """args containing ndarrays / sparse matrices (the common
    solve_ivp(f, span, y0, args=(A,)) pattern) must not break the
    step-core cache — identity-keyed fallback, not TypeError."""
    import sparse_tpu

    K = np.array([[0.0, 1.0], [-1.0, 0.0]])
    out = integrate.solve_ivp(
        lambda t, y, M: M @ y, (0, 1), [1.0, 0.0], args=(K,), rtol=1e-9, atol=1e-11
    )
    np.testing.assert_allclose(
        np.asarray(out.y)[:, -1], [np.cos(1.0), -np.sin(1.0)], rtol=1e-6
    )
    # unhashable args are NOT step-core cached: in-place mutation of the
    # arg between solves must be honored, not served from a stale trace
    K *= 2.0  # rotation at double rate
    out2 = integrate.solve_ivp(
        lambda t, y, M: M @ y, (0, 1), [1.0, 0.0], args=(K,), rtol=1e-9, atol=1e-11
    )
    np.testing.assert_allclose(
        np.asarray(out2.y)[:, -1], [np.cos(2.0), -np.sin(2.0)], rtol=1e-6
    )
    # sparse-matrix arg (hashes by identity, so it must be excluded from
    # the cache by TYPE, not by hashability; list-args variant)
    A = sparse_tpu.diags([[-1.0, -1.0]], [0]).tocsr()
    rhs = lambda t, y, M: M @ y  # noqa: E731 — shared fn, distinct args
    out3 = integrate.solve_ivp(
        rhs, (0, 1), [1.0, 1.0], args=[A], rtol=1e-9, atol=1e-11
    )
    np.testing.assert_allclose(
        np.asarray(out3.y)[:, -1], [np.exp(-1.0)] * 2, rtol=1e-6
    )
    # mutate the SAME matrix object in place: the solve must see the new
    # values, not a cached trace with the old ones baked in
    A.data = A.data * 2.0
    out4 = integrate.solve_ivp(
        rhs, (0, 1), [1.0, 1.0], args=[A], rtol=1e-9, atol=1e-11
    )
    np.testing.assert_allclose(
        np.asarray(out4.y)[:, -1], [np.exp(-2.0)] * 2, rtol=1e-6
    )
