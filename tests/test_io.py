"""MatrixMarket IO vs the scipy oracle (reference: tests/integration/test_io.py)."""

import numpy as np
import pytest
import scipy.io as sci_io

import sparse_tpu as sparse
from .utils.common import test_mtx_files


@pytest.mark.parametrize("filename", test_mtx_files)
def test_mmread(filename):
    ours = sparse.io.mmread(filename)
    ref = sci_io.mmread(filename)
    assert ours.shape == ref.shape
    assert np.allclose(np.asarray(ours.toarray()), ref.toarray())


def test_mmwrite_roundtrip(tmp_path):
    from .utils.sample import sample_csr

    s = sample_csr(13, 11, seed=21)
    ours = sparse.csr_array(s)
    path = tmp_path / "out.mtx"
    sparse.io.mmwrite(str(path), ours)
    back = sci_io.mmread(str(path))
    assert np.allclose(back.toarray(), s.toarray())
    ours_back = sparse.io.mmread(str(path))
    assert np.allclose(np.asarray(ours_back.toarray()), s.toarray())


def test_mmwrite_complex_roundtrip(tmp_path):
    from .utils.sample import sample_csr

    s = sample_csr(7, 9, dtype=np.complex128, seed=22)
    path = tmp_path / "out.mtx"
    sparse.io.mmwrite(str(path), sparse.csr_array(s))
    back = sci_io.mmread(str(path))
    assert np.allclose(back.toarray(), s.toarray())


def test_mmread_array_skew_symmetric(tmp_path):
    """Array-format skew-symmetric files store only the STRICT lower
    triangle (diagonal implicitly zero) — r2 code-review regression."""
    path = tmp_path / "skew.mtx"
    path.write_text(
        "%%MatrixMarket matrix array real skew-symmetric\n3 3\n1.0\n2.0\n3.0\n"
    )
    got = np.asarray(sparse.io.mmread(str(path)).todense())
    exp = np.array([[0.0, -1.0, -2.0], [1.0, 0.0, -3.0], [2.0, 3.0, 0.0]])
    assert np.allclose(got, exp)
    s = sci_io.mmread(str(path))
    assert np.allclose(got, np.asarray(s))


def test_mmread_array_symmetric(tmp_path):
    path = tmp_path / "sym.mtx"
    path.write_text(
        "%%MatrixMarket matrix array real symmetric\n3 3\n"
        "1.0\n2.0\n3.0\n4.0\n5.0\n6.0\n"
    )
    got = np.asarray(sparse.io.mmread(str(path)).todense())
    s = sci_io.mmread(str(path))
    assert np.allclose(got, np.asarray(s))
