"""GMRES and LSQR oracle tests.

Reference analogs: ``tests/integration/test_gmres_solve.py:25`` (nonsymmetric
system, residual check) and ``test_lsqr_solve.py:23`` (least-squares on a
rectangular system vs the scipy solution).
"""

import numpy as np
import pytest
import scipy.sparse as sp
import scipy.sparse.linalg as sla

import sparse_tpu as sparse
import sparse_tpu.linalg as linalg
from .utils.common import real_types
from .utils.sample import sample_csr, sample_vec


@pytest.mark.parametrize("dtype", real_types)
def test_gmres_solve(dtype):
    n = 80
    s = sample_csr(n, n, density=0.1, dtype=dtype, seed=22)
    s = (s + n * sp.identity(n, dtype=dtype)).tocsr()
    A = sparse.csr_array(s)
    y = np.asarray(s @ sample_vec(n, dtype=dtype, seed=23))
    x_pred, iters = linalg.gmres(A, y, tol=1e-8)
    assert iters > 0
    assert np.allclose(np.asarray(A @ x_pred), y, atol=1e-4)


def test_gmres_restarted_matches_scipy_solution():
    n = 60
    s = sample_csr(n, n, density=0.15, seed=24)
    s = (s + n * sp.identity(n)).tocsr()
    A = sparse.csr_array(s)
    y = np.asarray(s @ sample_vec(n, seed=25))
    x_pred, _ = linalg.gmres(A, y, tol=1e-10, restart=10)
    x_sci = sla.spsolve(s.tocsc(), y)
    assert np.allclose(np.asarray(x_pred), x_sci, atol=1e-6)


def test_gmres_exact_x0_zero_iters():
    n = 40
    s = (sample_csr(n, n, density=0.2, seed=26) + n * sp.identity(n)).tocsr()
    A = sparse.csr_array(s)
    x = sample_vec(n, seed=27)
    y = np.asarray(s @ x)
    x_sci = sla.spsolve(s.tocsc(), y)
    x_pred, iters = linalg.gmres(A, y, x0=x_sci, tol=1e-8)
    assert iters == 0
    assert np.allclose(np.asarray(x_pred), x_sci)


def test_lsqr_square():
    n = 60
    s = (sample_csr(n, n, density=0.15, seed=28) + n * sp.identity(n)).tocsr()
    A = sparse.csr_array(s)
    y = np.asarray(s @ sample_vec(n, seed=29))
    x, istop, itn, r1norm = linalg.lsqr(A, y)[:4]
    assert istop in (1, 2)
    assert itn > 0
    assert np.allclose(np.asarray(A @ x), y, atol=1e-4)


def test_lsqr_rectangular_least_squares():
    """Overdetermined system: match scipy.sparse.linalg.lsqr's minimizer."""
    m, n = 90, 40
    s = sample_csr(m, n, density=0.2, seed=30).tocsr()
    b = sample_vec(m, seed=31)
    A = sparse.csr_array(s)
    x = np.asarray(linalg.lsqr(A, b, atol=1e-12, btol=1e-12)[0])
    x_sci = sla.lsqr(s, b, atol=1e-12, btol=1e-12)[0]
    assert np.allclose(x, x_sci, atol=1e-5)


def test_lsqr_returns_scipy_ten_tuple():
    """Full 10-tuple signature parity with scipy (ADVICE r1: positional
    unpacking of scipy-ported code must not break)."""
    m, n = 50, 30
    s = sample_csr(m, n, density=0.2, seed=32).tocsr()
    b = sample_vec(m, seed=33)
    out = linalg.lsqr(sparse.csr_array(s), b)
    assert len(out) == 10
    x, istop, itn, r1norm, r2norm, anorm, acond, arnorm, xnorm, var = out
    ref = sla.lsqr(s, b)
    assert np.allclose(np.asarray(x), ref[0], atol=1e-5)
    assert abs(r1norm - ref[3]) < 1e-4 * max(1.0, ref[3])
    assert np.asarray(var).shape == (n,)


def test_lsqr_damped():
    m, n = 70, 35
    s = sample_csr(m, n, density=0.2, seed=34).tocsr()
    b = sample_vec(m, seed=35)
    damp = 0.5
    x = np.asarray(linalg.lsqr(sparse.csr_array(s), b, damp=damp, atol=1e-12, btol=1e-12)[0])
    x_sci = sla.lsqr(s, b, damp=damp, atol=1e-12, btol=1e-12)[0]
    assert np.allclose(x, x_sci, atol=1e-5)


def test_gmres_one_sync_per_cycle():
    """VERDICT r2 #5: the Arnoldi cycle (Gram-Schmidt, Givens recurrences,
    triangular solve) is device-resident — the driver makes exactly ONE
    host fetch per restart cycle, counted by the linalg.HOST_SYNCS hook."""
    n = 80
    restart = 10
    s = (sample_csr(n, n, density=0.1, seed=40) + n * sp.identity(n)).tocsr()
    A = sparse.csr_array(s)
    y = np.asarray(s @ sample_vec(n, seed=41))
    linalg.HOST_SYNCS = 0
    x, iters = linalg.gmres(A, y, restart=restart, tol=1e-10)
    assert iters > 0
    cycles_with_work = -(-iters // restart)  # ceil
    # one sync per executed cycle, +1 for the final converged-on-entry call
    assert linalg.HOST_SYNCS <= cycles_with_work + 1
    assert np.allclose(np.asarray(A @ x), y, atol=1e-6)


def test_lsqr_single_sync():
    """The whole LSQR solve (bidiagonalization + Paige-Saunders scalar
    recurrences) runs in one lax.while_loop with ONE host sync."""
    m, n = 80, 50
    s = sample_csr(m, n, density=0.2, seed=42)
    A = sparse.csr_array(s)
    y = np.asarray(sample_vec(m, seed=43))
    linalg.HOST_SYNCS = 0
    x, istop, itn = linalg.lsqr(A, y)[:3]
    assert itn > 0
    assert linalg.HOST_SYNCS == 1
    ref = sla.lsqr(s, y)[0]
    assert np.allclose(np.asarray(x), ref, atol=1e-5)


def test_lanczos_one_sync_per_cycle():
    """eigsh's Lanczos factorization fetches the (alphas, betas) pair once
    per ncv-step cycle instead of 2 scalars per step."""
    n = 60
    s = sample_csr(n, n, density=0.2, seed=44)
    s = (s + s.T + n * sp.identity(n)).tocsr()
    A = sparse.csr_array(s)
    linalg.HOST_SYNCS = 0
    w, _ = linalg.eigsh(A, k=4)
    # every sync is one full cycle; a 60-dim problem converges in a handful
    assert 0 < linalg.HOST_SYNCS <= 25
    ref = np.sort(sla.eigsh(s, k=4, which="LM")[0])
    assert np.allclose(np.sort(np.asarray(w)), ref, rtol=1e-5, atol=1e-8)


def test_gmres_complex_operator_real_rhs():
    """Review r3: a real b with a complex A must promote the Krylov basis —
    a real basis would silently solve against Re(A) only."""
    n = 40
    rng = np.random.default_rng(50)
    s = sample_csr(n, n, density=0.15, seed=51).astype(np.complex128)
    s.data = s.data * np.exp(1j * rng.uniform(0, 2 * np.pi, s.nnz))
    s = (s + n * sp.identity(n)).tocsr()
    A = sparse.csr_array(s)
    b = np.ones(n)  # REAL rhs
    x, iters = linalg.gmres(A, b, tol=1e-10)
    assert iters > 0
    assert np.iscomplexobj(np.asarray(x))
    assert np.linalg.norm(np.asarray(A @ x) - b) < 1e-6


def test_lsqr_complex_operator_real_rhs():
    n = 30
    rng = np.random.default_rng(52)
    s = sample_csr(n, n, density=0.2, seed=53).astype(np.complex128)
    s.data = s.data * np.exp(1j * rng.uniform(0, 2 * np.pi, s.nnz))
    s = (s + n * sp.identity(n)).tocsr()
    A = sparse.csr_array(s)
    b = np.ones(n)  # REAL rhs
    x, istop, itn = linalg.lsqr(A, b, atol=1e-10, btol=1e-10)[:3]
    assert itn > 0
    assert np.linalg.norm(np.asarray(A @ x) - b) < 1e-5
