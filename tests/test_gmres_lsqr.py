"""GMRES and LSQR oracle tests.

Reference analogs: ``tests/integration/test_gmres_solve.py:25`` (nonsymmetric
system, residual check) and ``test_lsqr_solve.py:23`` (least-squares on a
rectangular system vs the scipy solution).
"""

import numpy as np
import pytest
import scipy.sparse as sp
import scipy.sparse.linalg as sla

import sparse_tpu as sparse
import sparse_tpu.linalg as linalg
from .utils.common import real_types
from .utils.sample import sample_csr, sample_vec


@pytest.mark.parametrize("dtype", real_types)
def test_gmres_solve(dtype):
    n = 80
    s = sample_csr(n, n, density=0.1, dtype=dtype, seed=22)
    s = (s + n * sp.identity(n, dtype=dtype)).tocsr()
    A = sparse.csr_array(s)
    y = np.asarray(s @ sample_vec(n, dtype=dtype, seed=23))
    x_pred, iters = linalg.gmres(A, y, tol=1e-8)
    assert iters > 0
    assert np.allclose(np.asarray(A @ x_pred), y, atol=1e-4)


def test_gmres_restarted_matches_scipy_solution():
    n = 60
    s = sample_csr(n, n, density=0.15, seed=24)
    s = (s + n * sp.identity(n)).tocsr()
    A = sparse.csr_array(s)
    y = np.asarray(s @ sample_vec(n, seed=25))
    x_pred, _ = linalg.gmres(A, y, tol=1e-10, restart=10)
    x_sci = sla.spsolve(s.tocsc(), y)
    assert np.allclose(np.asarray(x_pred), x_sci, atol=1e-6)


def test_gmres_exact_x0_zero_iters():
    n = 40
    s = (sample_csr(n, n, density=0.2, seed=26) + n * sp.identity(n)).tocsr()
    A = sparse.csr_array(s)
    x = sample_vec(n, seed=27)
    y = np.asarray(s @ x)
    x_sci = sla.spsolve(s.tocsc(), y)
    x_pred, iters = linalg.gmres(A, y, x0=x_sci, tol=1e-8)
    assert iters == 0
    assert np.allclose(np.asarray(x_pred), x_sci)


def test_lsqr_square():
    n = 60
    s = (sample_csr(n, n, density=0.15, seed=28) + n * sp.identity(n)).tocsr()
    A = sparse.csr_array(s)
    y = np.asarray(s @ sample_vec(n, seed=29))
    x, istop, itn, r1norm = linalg.lsqr(A, y)[:4]
    assert istop in (1, 2)
    assert itn > 0
    assert np.allclose(np.asarray(A @ x), y, atol=1e-4)


def test_lsqr_rectangular_least_squares():
    """Overdetermined system: match scipy.sparse.linalg.lsqr's minimizer."""
    m, n = 90, 40
    s = sample_csr(m, n, density=0.2, seed=30).tocsr()
    b = sample_vec(m, seed=31)
    A = sparse.csr_array(s)
    x = np.asarray(linalg.lsqr(A, b, atol=1e-12, btol=1e-12)[0])
    x_sci = sla.lsqr(s, b, atol=1e-12, btol=1e-12)[0]
    assert np.allclose(x, x_sci, atol=1e-5)


def test_lsqr_returns_scipy_ten_tuple():
    """Full 10-tuple signature parity with scipy (ADVICE r1: positional
    unpacking of scipy-ported code must not break)."""
    m, n = 50, 30
    s = sample_csr(m, n, density=0.2, seed=32).tocsr()
    b = sample_vec(m, seed=33)
    out = linalg.lsqr(sparse.csr_array(s), b)
    assert len(out) == 10
    x, istop, itn, r1norm, r2norm, anorm, acond, arnorm, xnorm, var = out
    ref = sla.lsqr(s, b)
    assert np.allclose(np.asarray(x), ref[0], atol=1e-5)
    assert abs(r1norm - ref[3]) < 1e-4 * max(1.0, ref[3])
    assert np.asarray(var).shape == (n,)


def test_lsqr_damped():
    m, n = 70, 35
    s = sample_csr(m, n, density=0.2, seed=34).tocsr()
    b = sample_vec(m, seed=35)
    damp = 0.5
    x = np.asarray(linalg.lsqr(sparse.csr_array(s), b, damp=damp, atol=1e-12, btol=1e-12)[0])
    x_sci = sla.lsqr(s, b, damp=damp, atol=1e-12, btol=1e-12)[0]
    assert np.allclose(x, x_sci, atol=1e-5)
