"""Whole-array/axis reductions + canonicalization surface vs scipy.

Mirrors scipy's `_minmax_mixin` semantics (implicit zeros participate in
max/min/argmax/argmin; first occurrence wins ties) — the reference inherits
this surface from scipy via its coverage layer (coverage.py:226-276).
"""

import numpy as np
import pytest
import scipy.sparse as sp

import sparse_tpu


def _pair(m, n, density, seed, fmt="csr"):
    As = sp.random(m, n, density=density, random_state=seed, format="csr")
    As.data = np.round(As.data * 10 - 5)  # negatives + explicit zeros
    A = sparse_tpu.csr_array.from_parts(
        As.data.copy(), As.indices.copy(), As.indptr.copy(), (m, n)
    )
    return A.asformat(fmt), As


CASES = [(1, 1, 0.0, 0), (3, 5, 0.2, 1), (7, 4, 0.5, 2), (6, 6, 0.9, 3),
         (8, 3, 1.0, 4), (2, 9, 0.1, 5)]


@pytest.mark.parametrize("m,n,density,seed", CASES)
@pytest.mark.parametrize("name", ["max", "min"])
@pytest.mark.parametrize("axis", [None, 0, 1])
def test_min_max(m, n, density, seed, name, axis):
    A, As = _pair(m, n, density, seed)
    want = getattr(As, name)(axis=axis)
    got = getattr(A, name)(axis=axis)
    if axis is None:
        assert np.isclose(got, want)
    else:
        w = want.toarray().ravel() if sp.issparse(want) else np.asarray(want).ravel()
        np.testing.assert_allclose(np.asarray(got).ravel(), w)


@pytest.mark.parametrize("m,n,density,seed", CASES)
@pytest.mark.parametrize("name", ["argmax", "argmin"])
@pytest.mark.parametrize("axis", [None, 0, 1])
def test_argmin_argmax(m, n, density, seed, name, axis):
    A, As = _pair(m, n, density, seed)
    want = np.asarray(getattr(As, name)(axis=axis)).ravel()
    got = np.asarray(getattr(A, name)(axis=axis)).ravel()
    np.testing.assert_array_equal(got, want)


def test_nan_variants():
    data = np.array([[np.nan, -2.0, 0.0], [0.0, 5.0, np.nan]])
    As = sp.csr_matrix(data)
    A = sparse_tpu.csr_array.from_parts(
        As.data.copy(), As.indices.copy(), As.indptr.copy(), As.shape
    )
    assert np.isclose(A.nanmax(), np.nanmax(data))
    assert np.isclose(A.nanmin(), np.nanmin(data))
    np.testing.assert_allclose(np.asarray(A.nanmax(axis=1)), np.nanmax(data, axis=1))
    np.testing.assert_allclose(np.asarray(A.nanmin(axis=0)), np.nanmin(data, axis=0))


def _from_scipy(As):
    return sparse_tpu.csr_array.from_parts(
        As.data.copy(), As.indices.copy(), As.indptr.copy(), As.shape
    )


def _nan_cases():
    # stored NaNs with/without implicit zeros — the cases where stored-vs-
    # implicit bookkeeping diverges (review r2 findings)
    yield sp.csr_matrix(np.array([[-5.0, np.nan]]))  # fully stored
    yield sp.csr_matrix(
        (np.array([-5.0, np.nan]), np.array([0, 1]), np.array([0, 2])),
        shape=(1, 3),
    )  # + implicit
    yield sp.csr_matrix(np.array([[np.nan, np.nan]]))  # all-NaN full
    yield sp.csr_matrix(
        (np.array([np.nan]), np.array([0]), np.array([0, 1])), shape=(1, 2)
    )  # all-NaN + implicit
    yield sp.csr_matrix(
        (np.array([0.0, -3.0]), np.array([0, 1]), np.array([0, 2])),
        shape=(1, 3),
    )  # explicit zero before implicit
    yield sp.csr_matrix(
        (np.array([-3.0, 0.0]), np.array([1, 2]), np.array([0, 2])),
        shape=(1, 3),
    )  # implicit zero before explicit


@pytest.mark.parametrize("case", range(6))
def test_nan_and_zero_edge_semantics(case):
    import warnings as _w

    As = list(_nan_cases())[case]
    A = _from_scipy(As)
    with _w.catch_warnings():
        _w.simplefilter("ignore")  # scipy warns on all-NaN slices
        for name in ["nanmax", "nanmin", "argmax", "argmin", "max", "min"]:
            want = getattr(As, name)()
            got = getattr(A, name)()
            np.testing.assert_equal(float(got), float(want), err_msg=name)
        for name in ["nanmax", "argmax", "argmin"]:
            for ax in (0, 1):
                want = getattr(As, name)(axis=ax)
                w = (
                    want.toarray().ravel()
                    if sp.issparse(want)
                    else np.asarray(want).ravel()
                )
                got = np.asarray(getattr(A, name)(axis=ax)).ravel()
                np.testing.assert_equal(
                    got.astype(float), w.astype(float),
                    err_msg=f"{name} axis={ax}",
                )


@pytest.mark.parametrize("offset", [-2, -1, 0, 1, 3])
def test_trace(offset):
    A, As = _pair(6, 7, 0.5, 11)
    assert np.isclose(A.trace(offset=offset), As.toarray().trace(offset=offset))


@pytest.mark.parametrize("fmt", ["csr", "csc", "coo"])
def test_nonzero(fmt):
    A, As = _pair(5, 6, 0.4, 12, fmt=fmt)
    gr, gc = A.nonzero()
    wr, wc = As.nonzero()
    np.testing.assert_array_equal(gr, wr)
    np.testing.assert_array_equal(gc, wc)


@pytest.mark.parametrize("m,n,density,seed", CASES[1:4])
def test_maximum_minimum_sparse(m, n, density, seed):
    A, As = _pair(m, n, density, seed)
    B, Bs = _pair(m, n, 0.3, seed + 100)
    np.testing.assert_allclose(
        np.asarray(A.maximum(B).todense()), As.maximum(Bs).toarray()
    )
    np.testing.assert_allclose(
        np.asarray(A.minimum(B).todense()), As.minimum(Bs).toarray()
    )


def test_maximum_minimum_scalar():
    A, As = _pair(4, 4, 0.5, 20)
    np.testing.assert_allclose(
        np.asarray(A.maximum(-2.0).todense()), As.maximum(-2.0).toarray()
    )
    np.testing.assert_allclose(
        np.asarray(A.minimum(3.0).todense()), As.minimum(3.0).toarray()
    )
    with pytest.raises(NotImplementedError):
        A.maximum(1.0)  # densifying case: loud, not silent


def test_sum_duplicates_coo_inplace():
    r = np.array([2, 0, 2, 0]); c = np.array([1, 3, 1, 3])
    v = np.array([1.0, 2.0, 4.0, 8.0])
    A = sparse_tpu.coo_array((v, (r, c)), shape=(3, 4))
    assert not A.has_canonical_format
    A.sum_duplicates()
    assert A.has_canonical_format and A.nnz == 2
    np.testing.assert_array_equal(np.asarray(A.row), [0, 2])
    np.testing.assert_array_equal(np.asarray(A.col), [3, 1])
    np.testing.assert_allclose(np.asarray(A.data), [10.0, 5.0])


def test_eliminate_zeros_inplace():
    A, As = _pair(5, 5, 0.8, 30)
    As.eliminate_zeros()
    A.eliminate_zeros()
    assert A.nnz == As.nnz
    np.testing.assert_allclose(np.asarray(A.todense()), As.toarray())


def test_check_format():
    A, _ = _pair(4, 5, 0.5, 40)
    A.check_format()  # canonical arrays pass
    bad = sparse_tpu.csr_array.from_parts(
        np.ones(2), np.array([4, 1]), np.array([0, 2, 2, 2, 2]), (4, 5)
    )
    with pytest.raises(ValueError):
        bad.check_format()


def test_canonicalization_noops():
    A, _ = _pair(4, 5, 0.5, 41)
    assert A.has_sorted_indices and A.has_canonical_format
    A.sort_indices(); A.prune(); A.sum_duplicates()  # all no-ops, no error
    B = A.sorted_indices()
    np.testing.assert_allclose(np.asarray(B.todense()), np.asarray(A.todense()))


@pytest.mark.parametrize("k", [-2, 0, 1])
@pytest.mark.parametrize("fmt", ["csr", "csc", "coo"])
def test_setdiag(k, fmt):
    A, As = _pair(5, 6, 0.4, 50, fmt=fmt)
    As = As.tolil()  # scipy warns on csr setdiag; lil is its canonical path
    A.setdiag(7.5, k=k)
    As.setdiag(7.5, k=k)
    np.testing.assert_allclose(np.asarray(A.todense()), As.toarray())
    vals = np.arange(3, dtype=float) + 1
    A.setdiag(vals, k=k)
    As.setdiag(vals, k=k)
    np.testing.assert_allclose(np.asarray(A.todense()), As.toarray())
    assert A.format == fmt


@pytest.mark.parametrize("order", ["C", "F"])
def test_reshape(order):
    A, As = _pair(6, 4, 0.5, 60)
    got = A.reshape((8, 3), order=order)
    want = As.reshape((8, 3), order=order)
    np.testing.assert_allclose(np.asarray(got.todense()), want.toarray())
    assert got.format == "csr"


def test_resize():
    A, As = _pair(6, 6, 0.5, 70)
    dense = As.toarray()
    A.resize((4, 9))
    np.testing.assert_allclose(
        np.asarray(A.todense()), np.pad(dense[:4, :], ((0, 0), (0, 3)))
    )
    assert A.shape == (4, 9)


def test_argmax_nan_extreme_ignores_stored_zero():
    # probed scipy rule: NaN extreme + implicit zeros -> FIRST IMPLICIT
    # position, even when a stored zero sits earlier
    As = sp.csr_matrix(
        (np.array([0.0, np.nan]), np.array([0, 1]), np.array([0, 2])),
        shape=(1, 3),
    )
    A = _from_scipy(As)
    assert A.argmax() == As.argmax() == 2
    assert A.argmin() == As.argmin() == 2
    np.testing.assert_array_equal(
        np.asarray(A.argmax(axis=1)).ravel(), np.asarray(As.argmax(axis=1)).ravel()
    )


def test_reductions_on_noncanonical_coo():
    # duplicates must SUM before any reduction (scipy canonicalizes first)
    A = sparse_tpu.coo_array(
        (np.array([1.0, 2.0]), (np.array([0, 0]), np.array([0, 0]))),
        shape=(1, 2),
    )
    assert A.max() == 3.0  # not 2.0
    assert A.min() == 0.0  # implicit zero at (0, 1) still visible
    assert A.argmax() == 0
    r, c = A.nonzero()
    np.testing.assert_array_equal(r, [0])
    np.testing.assert_array_equal(c, [0])


def test_maximum_nan_scalar_raises():
    A, _ = _pair(3, 3, 0.5, 80)
    with pytest.raises(NotImplementedError):
        A.maximum(np.nan)
    with pytest.raises(NotImplementedError):
        A.minimum(np.nan)


def test_swapaxes_out_of_bounds():
    A = sparse_tpu.random(3, 4, 0.5, random_state=0, format="csr")
    with pytest.raises(ValueError):
        sparse_tpu.swapaxes(A, 0, 2)
    with pytest.raises(ValueError):
        sparse_tpu.permute_dims(A, (0, 2))


@pytest.mark.parametrize(
    "dtype,dense",
    [
        (np.uint32, [[5, 0]]),          # stored 0: -0 wraps to key 0 in uint
        (np.int8, [[-128, -1]]),        # int8 min negates to itself
        (np.uint8, [[200, 3, 0]]),
    ],
)
def test_argmin_extreme_dtypes(dtype, dense):
    """ADVICE r2: the argmin/argmax sort key stays in the NATIVE dtype with
    no negation — negating wraps unsigned values and the signed minimum
    (and a float64 key would lose int64 exactness past 2**53)."""
    As = sp.csr_array(np.asarray(dense, dtype=dtype))
    A = sparse_tpu.csr_array.from_parts(
        As.data.copy(), As.indices.copy(), As.indptr.copy(), As.shape
    )
    for axis in (None, 0, 1):
        want = np.asarray(As.argmin(axis=axis)).ravel()
        got = np.asarray(A.argmin(axis=axis)).ravel()
        np.testing.assert_array_equal(got, want)
        want = np.asarray(As.argmax(axis=axis)).ravel()
        got = np.asarray(A.argmax(axis=axis)).ravel()
        np.testing.assert_array_equal(got, want)


def test_reduction_out_param_raises():
    """scipy raises ValueError for out= on sparse reductions; so do we."""
    A, _ = _pair(3, 3, 0.5, 7)
    buf = np.zeros(3)
    for name in ("max", "min", "nanmax", "nanmin", "argmax", "argmin"):
        with pytest.raises(ValueError):
            getattr(A, name)(axis=1, out=buf)


def test_argminmax_inf_nan_collision():
    """NaN must beat a stored inf for argmax (and -inf for argmin) — the
    NaN key is separate from the value key, never folded in as np.inf."""
    As = sp.csr_array(np.array([[np.inf, np.nan], [-np.inf, np.nan]]))
    A = sparse_tpu.csr_array.from_parts(
        As.data.copy(), As.indices.copy(), As.indptr.copy(), As.shape
    )
    np.testing.assert_array_equal(
        np.asarray(A.argmax(axis=1)).ravel(),
        np.asarray(As.argmax(axis=1)).ravel(),
    )
    np.testing.assert_array_equal(
        np.asarray(A.argmin(axis=1)).ravel(),
        np.asarray(As.argmin(axis=1)).ravel(),
    )


def test_argminmax_int64_exact_past_2_53():
    """The value key stays in the native dtype: 2**53 and 2**53+1 collide in
    float64 but must still argsort exactly."""
    big = 2**53
    dense = np.array([[big, big + 1], [-big - 1, -big]], dtype=np.int64)
    As = sp.csr_array(dense)
    A = sparse_tpu.csr_array.from_parts(
        As.data.copy(), As.indices.copy(), As.indptr.copy(), As.shape
    )
    for axis in (0, 1):
        np.testing.assert_array_equal(
            np.asarray(A.argmax(axis=axis)).ravel(),
            np.asarray(dense.argmax(axis=axis)).ravel(),
        )
        np.testing.assert_array_equal(
            np.asarray(A.argmin(axis=axis)).ravel(),
            np.asarray(dense.argmin(axis=axis)).ravel(),
        )
