"""SDDMM oracle tests: vals_out = A_vals * (C @ D) at A's sparsity.

Reference analog: ``tests/integration/test_csr_sddmm.py`` (kdim axis +
balanced variant) and ``test_csc.py:141-163`` (CSC variant).
"""

import numpy as np
import pytest
import scipy.io as sci_io

import sparse_tpu as sparse
from .utils.common import test_mtx_files
from .utils.sample import sample_csr, sample_dense


def _oracle(s, C, D):
    s = s.tocsr()
    out = s.copy()
    full = C @ D
    rows = np.repeat(np.arange(s.shape[0]), np.diff(s.indptr))
    out.data = s.data * np.asarray(full)[rows, s.indices]
    return out


@pytest.mark.parametrize("filename", test_mtx_files)
@pytest.mark.parametrize("kdim", [2, 8, 17])
def test_csr_sddmm(filename, kdim):
    arr = sparse.io.mmread(filename).tocsr()
    s = sci_io.mmread(filename).tocsr()
    m, n = arr.shape
    C = sample_dense(m, kdim, seed=70)
    D = sample_dense(kdim, n, seed=71)
    got = arr.sddmm(C, D)
    exp = _oracle(s, C, D)
    assert np.allclose(np.asarray(got.todense()), exp.todense(), atol=1e-6)


def test_csr_sddmm_balanced():
    sa = sample_csr(29, 23, density=0.2, seed=72).tocsr()
    arr = sparse.csr_array(sa)
    arr.balance()
    C = sample_dense(29, 5, seed=73)
    D = sample_dense(5, 23, seed=74)
    got = arr.sddmm(C, D)
    exp = _oracle(sa, C, D)
    assert np.allclose(np.asarray(got.todense()), exp.todense(), atol=1e-6)


@pytest.mark.parametrize("kdim", [3, 11])
def test_csc_sddmm(kdim):
    sa = sample_csr(19, 31, density=0.2, seed=75).tocsc()
    arr = sparse.csc_array(sa)
    m, n = arr.shape
    C = sample_dense(m, kdim, seed=76)
    D = sample_dense(kdim, n, seed=77)
    got = arr.sddmm(C, D)
    exp = _oracle(sa, C, D)
    assert np.allclose(np.asarray(got.todense()), exp.todense(), atol=1e-6)


def test_sddmm_complex():
    sa = sample_csr(13, 17, density=0.3, dtype=np.complex128, seed=78).tocsr()
    C = sample_dense(13, 4, dtype=np.complex128, seed=79)
    D = sample_dense(4, 17, dtype=np.complex128, seed=80)
    got = sparse.csr_array(sa).sddmm(C, D)
    exp = _oracle(sa, C, D)
    assert np.allclose(np.asarray(got.todense()), exp.todense(), atol=1e-6)
