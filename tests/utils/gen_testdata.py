"""Generate the MatrixMarket fixture set in testdata/.

Plays the role of the reference's testdata/ (test.mtx, GlossGT.mtx,
Ragusa18.mtx, cage4.mtx, karate.mtx — SURVEY §4) with freshly generated
matrices covering the same axes: small general real, rectangular, symmetric
pattern graph, integer-valued, banded. Run once; outputs are committed.
"""

import numpy as np
import scipy.io
import scipy.sparse as sp


def main(outdir="testdata"):
    rng = np.random.default_rng(42)

    # small square general real (analog of test.mtx)
    a = sp.random(10, 10, density=0.3, random_state=rng, format="coo")
    scipy.io.mmwrite(f"{outdir}/small.mtx", a)

    # rectangular real (analog of Ragusa18: nonsquare, weighted)
    b = sp.random(23, 14, density=0.2, random_state=rng, format="coo")
    scipy.io.mmwrite(f"{outdir}/rect.mtx", b)

    # symmetric pattern graph (analog of karate.mtx)
    g = sp.random(34, 34, density=0.12, random_state=rng, format="coo")
    g = ((g + g.T) > 0).astype(np.int64)
    g.setdiag(0)
    g.eliminate_zeros()
    scipy.io.mmwrite(f"{outdir}/graph.mtx", sp.coo_matrix(g), field="pattern", symmetry="symmetric")

    # small structured matrix with integer entries (analog of cage4-ish)
    c = sp.random(9, 9, density=0.35, random_state=rng, format="coo")
    c.data = np.round(c.data * 10).astype(np.float64) + 1
    scipy.io.mmwrite(f"{outdir}/ints.mtx", c, field="integer")

    # banded SPD 5-pt Laplacian-ish (the solver fixture)
    n = 16
    lap = sp.diags(
        [-1.0, -1.0, 4.0, -1.0, -1.0],
        [-4, -1, 0, 1, 4],
        shape=(n, n),
        format="coo",
    )
    scipy.io.mmwrite(f"{outdir}/banded.mtx", lap)


if __name__ == "__main__":
    main()
