"""Seeded random sparse matrix generator.

Reference analog: ``tests/integration/utils/sample.py:25-43``.
"""

import numpy as np
import scipy.sparse as sp


def sample_csr(m, n, density=0.3, dtype=np.float64, seed=0):
    """Random scipy CSR with the given density; complex dtypes get imag parts."""
    rng = np.random.default_rng(seed)
    a = sp.random(m, n, density=density, random_state=rng, format="csr")
    data = a.data
    if np.issubdtype(dtype, np.complexfloating):
        data = data + 1j * rng.random(data.shape[0])
    a = sp.csr_matrix((data.astype(dtype), a.indices, a.indptr), shape=(m, n))
    return a


def sample_dense(m, n, dtype=np.float64, seed=0):
    rng = np.random.default_rng(seed)
    d = rng.random((m, n))
    if np.issubdtype(dtype, np.complexfloating):
        d = d + 1j * rng.random((m, n))
    return d.astype(dtype)


def sample_vec(n, dtype=np.float64, seed=0):
    return sample_dense(n, 1, dtype, seed)[:, 0]
