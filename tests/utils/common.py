"""Shared test fixtures.

Reference analog: ``tests/integration/utils/common.py:24-34`` — the fixture
matrix list and the dtype axis {f32, f64, c64, c128}.
"""

import os

import numpy as np

TESTDATA = os.path.join(os.path.dirname(__file__), "..", "..", "testdata")

test_mtx_files = [
    os.path.join(TESTDATA, f)
    for f in ["small.mtx", "rect.mtx", "graph.mtx", "ints.mtx", "banded.mtx"]
]

types = [np.float32, np.float64, np.complex64, np.complex128]
real_types = [np.float32, np.float64]
