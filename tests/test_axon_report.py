"""Axon offline tooling: axon_report analyzer/compare, axon_trace CLI,
trim_records round-trip (ISSUE 4).

The report and trace scripts are the operator's view of a session log;
these tests pin (a) the smoke contract — the committed
``results/axon/records.jsonl`` always analyzes and always exports valid
Chrome-trace JSON, (b) the regression gate — ``--compare`` exits
nonzero on a >=20% span-latency regression and zero otherwise, and
(c) the trim round-trip — a trimmed log still validates and exports.

axon_report is pure-stdlib (no jax init), so everything here except the
trim/trace checks runs in milliseconds.
"""

import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RECORDS = os.path.join(REPO, "results", "axon", "records.jsonl")


def _load(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write_records(path, span_durs, ts0=1700000000.0):
    """A synthetic session: one span family plus a solver rollup, the
    minimum surface the comparison gate needs."""
    lines = []
    ts = ts0
    for d in span_durs:
        ts += 1.0
        lines.append({
            "kind": "span", "ts": ts, "name": "bench.step", "dur_s": d,
        })
    lines.append({
        "kind": "solver.solve", "ts": ts + 1, "solver": "cg",
        "iters": 10, "path": "device", "n": 32,
    })
    with open(path, "w") as f:
        for rec in lines:
            f.write(json.dumps(rec) + "\n")
    return path


# -- the committed-log smoke (quick-lane CI satellite) ------------------------


def test_report_smoke_on_committed_log():
    rep = _load("axon_report").build_report(RECORDS)
    assert rep["events_total"] > 0
    assert "solver.iter" in rep["events_by_kind"]
    assert rep["solvers"].get("cg", {}).get("solves", 0) >= 1
    assert rep["metrics"], "the comparison surface must not be empty"


def test_report_cli_smoke_exits_zero(capsys):
    assert _load("axon_report").main([RECORDS, "--quiet"]) == 0


def test_report_joins_bench_evidence():
    bench = os.path.join(REPO, "BENCH_r05.json")
    if not os.path.exists(bench):
        pytest.skip("no BENCH_r05.json in this checkout")
    rep = _load("axon_report").build_report(RECORDS, [bench])
    assert any(r["source"] == "BENCH_r05.json" for r in rep["bench"])
    assert any(k.startswith("bench.") for k in rep["metrics"])


# -- the regression gate ------------------------------------------------------


def test_compare_flags_span_latency_regression(tmp_path):
    mod = _load("axon_report")
    base_rec = _write_records(str(tmp_path / "base.jsonl"), [0.010] * 8)
    base_json = str(tmp_path / "base.json")
    assert mod.main([base_rec, "--quiet", "--json", base_json]) == 0
    # inject a 30% span-latency regression (>= the 20% default gate)
    slow_rec = _write_records(str(tmp_path / "slow.jsonl"), [0.013] * 8)
    rc = mod.main([slow_rec, "--quiet", "--compare", base_json])
    assert rc == 1
    regs = mod.compare(
        mod.build_report(slow_rec), json.load(open(base_json))
    )
    assert any(r["metric"] == "span.bench.step.p50_s" for r in regs)


def test_compare_passes_within_threshold_and_on_improvement(tmp_path):
    mod = _load("axon_report")
    base_rec = _write_records(str(tmp_path / "base.jsonl"), [0.010] * 8)
    base_json = str(tmp_path / "base.json")
    mod.main([base_rec, "--quiet", "--json", base_json])
    same_rec = _write_records(str(tmp_path / "same.jsonl"), [0.011] * 8)
    assert mod.main([same_rec, "--quiet", "--compare", base_json]) == 0
    fast_rec = _write_records(str(tmp_path / "fast.jsonl"), [0.004] * 8)
    assert mod.main([fast_rec, "--quiet", "--compare", base_json]) == 0
    # a tighter threshold flags the 10% move the default ignores
    assert mod.main(
        [same_rec, "--quiet", "--compare", base_json, "--threshold", "0.05"]
    ) == 1


def test_compare_missing_inputs_exit_2(tmp_path):
    mod = _load("axon_report")
    assert mod.main([str(tmp_path / "nope.jsonl")]) == 2
    rec = _write_records(str(tmp_path / "r.jsonl"), [0.01])
    assert mod.main([rec, "--compare", str(tmp_path / "nope.json")]) == 2


# -- trace CLI + schema -------------------------------------------------------


def test_trace_cli_produces_valid_chrome_trace(tmp_path):
    out = str(tmp_path / "trace.json")
    assert _load("axon_trace").main([RECORDS, out]) == 0
    trace = json.load(open(out))
    evs = trace["traceEvents"]
    assert isinstance(evs, list) and evs
    for e in evs:
        assert e["ph"] in ("X", "i", "C", "M")
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert isinstance(e["name"], str) and e["name"]
        if e["ph"] == "X":
            assert e["dur"] >= 0
        if e["ph"] != "M":
            assert isinstance(e["ts"], (int, float))
    # the committed log's solver iterations land in the solver lane
    assert any(
        e["ph"] == "i" and e["name"] == "solver.iter" for e in evs
    )


def test_trace_cli_missing_input_exits_2(tmp_path):
    assert _load("axon_trace").main([str(tmp_path / "nope.jsonl")]) == 2


# -- trim round-trip ----------------------------------------------------------


def test_trim_keeps_log_exportable(tmp_path):
    """Prepend a stale session, trim, and require the survivor to still
    schema-validate and export (the ISSUE 4 trim satellite)."""
    trim = _load("trim_records")
    committed = open(RECORDS).read()
    stale = [
        {"kind": "solver.iter", "ts": 1000.0, "solver": "cg", "iter": 1},
        {"kind": "bench.session", "ts": 1010.0, "status": "cpu",
         "budget_spent_s": 5.0},
    ]
    target = tmp_path / "records.jsonl"
    with open(target, "w") as f:
        for rec in stale:
            f.write(json.dumps(rec) + "\n")
        f.write(committed)
    dropped = trim.trim(str(target), dry_run=False)
    assert dropped >= len(stale)

    from sparse_tpu import telemetry

    assert telemetry.schema.validate_jsonl(str(target)) == []
    from sparse_tpu.telemetry import _trace

    events = _trace.read_events_jsonl(str(target))
    assert events
    trace = _trace.to_chrome_trace(events)
    assert trace["traceEvents"]
