"""Axon offline tooling: axon_report analyzer/compare, axon_trace CLI,
trim_records round-trip (ISSUE 4).

The report and trace scripts are the operator's view of a session log;
these tests pin (a) the smoke contract — the committed
``results/axon/records.jsonl`` always analyzes and always exports valid
Chrome-trace JSON, (b) the regression gate — ``--compare`` exits
nonzero on a >=20% span-latency regression and zero otherwise, and
(c) the trim round-trip — a trimmed log still validates and exports.

axon_report is pure-stdlib (no jax init), so everything here except the
trim/trace checks runs in milliseconds.
"""

import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RECORDS = os.path.join(REPO, "results", "axon", "records.jsonl")


def _load(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write_records(path, span_durs, ts0=1700000000.0):
    """A synthetic session: one span family plus a solver rollup, the
    minimum surface the comparison gate needs."""
    lines = []
    ts = ts0
    for d in span_durs:
        ts += 1.0
        lines.append({
            "kind": "span", "ts": ts, "name": "bench.step", "dur_s": d,
        })
    lines.append({
        "kind": "solver.solve", "ts": ts + 1, "solver": "cg",
        "iters": 10, "path": "device", "n": 32,
    })
    with open(path, "w") as f:
        for rec in lines:
            f.write(json.dumps(rec) + "\n")
    return path


# -- the committed-log smoke (quick-lane CI satellite) ------------------------


def test_report_smoke_on_committed_log():
    rep = _load("axon_report").build_report(RECORDS)
    assert rep["events_total"] > 0
    assert "solver.iter" in rep["events_by_kind"]
    assert rep["solvers"].get("cg", {}).get("solves", 0) >= 1
    assert rep["metrics"], "the comparison surface must not be empty"


def test_report_cli_smoke_exits_zero(capsys):
    assert _load("axon_report").main([RECORDS, "--quiet"]) == 0


def test_report_joins_bench_evidence():
    bench = os.path.join(REPO, "BENCH_r05.json")
    if not os.path.exists(bench):
        pytest.skip("no BENCH_r05.json in this checkout")
    rep = _load("axon_report").build_report(RECORDS, [bench])
    assert any(r["source"] == "BENCH_r05.json" for r in rep["bench"])
    assert any(k.startswith("bench.") for k in rep["metrics"])


# -- the regression gate ------------------------------------------------------


def test_compare_flags_span_latency_regression(tmp_path):
    mod = _load("axon_report")
    base_rec = _write_records(str(tmp_path / "base.jsonl"), [0.010] * 8)
    base_json = str(tmp_path / "base.json")
    assert mod.main([base_rec, "--quiet", "--json", base_json]) == 0
    # inject a 30% span-latency regression (>= the 20% default gate)
    slow_rec = _write_records(str(tmp_path / "slow.jsonl"), [0.013] * 8)
    rc = mod.main([slow_rec, "--quiet", "--compare", base_json])
    assert rc == 1
    regs = mod.compare(
        mod.build_report(slow_rec), json.load(open(base_json))
    )
    assert any(r["metric"] == "span.bench.step.p50_s" for r in regs)


def test_compare_passes_within_threshold_and_on_improvement(tmp_path):
    mod = _load("axon_report")
    base_rec = _write_records(str(tmp_path / "base.jsonl"), [0.010] * 8)
    base_json = str(tmp_path / "base.json")
    mod.main([base_rec, "--quiet", "--json", base_json])
    same_rec = _write_records(str(tmp_path / "same.jsonl"), [0.011] * 8)
    assert mod.main([same_rec, "--quiet", "--compare", base_json]) == 0
    fast_rec = _write_records(str(tmp_path / "fast.jsonl"), [0.004] * 8)
    assert mod.main([fast_rec, "--quiet", "--compare", base_json]) == 0
    # a tighter threshold flags the 10% move the default ignores
    assert mod.main(
        [same_rec, "--quiet", "--compare", base_json, "--threshold", "0.05"]
    ) == 1


def test_compare_missing_inputs_exit_2(tmp_path):
    mod = _load("axon_report")
    assert mod.main([str(tmp_path / "nope.jsonl")]) == 2
    rec = _write_records(str(tmp_path / "r.jsonl"), [0.01])
    assert mod.main([rec, "--compare", str(tmp_path / "nope.json")]) == 2


# -- trace CLI + schema -------------------------------------------------------


def test_trace_cli_produces_valid_chrome_trace(tmp_path):
    out = str(tmp_path / "trace.json")
    assert _load("axon_trace").main([RECORDS, out]) == 0
    trace = json.load(open(out))
    evs = trace["traceEvents"]
    assert isinstance(evs, list) and evs
    for e in evs:
        assert e["ph"] in ("X", "i", "C", "M")
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert isinstance(e["name"], str) and e["name"]
        if e["ph"] == "X":
            assert e["dur"] >= 0
        if e["ph"] != "M":
            assert isinstance(e["ts"], (int, float))
    # the committed log's solver iterations land in the solver lane
    assert any(
        e["ph"] == "i" and e["name"] == "solver.iter" for e in evs
    )


def test_trace_cli_missing_input_exits_2(tmp_path):
    assert _load("axon_trace").main([str(tmp_path / "nope.jsonl")]) == 2


# -- trim round-trip ----------------------------------------------------------


def test_trim_keeps_log_exportable(tmp_path):
    """Prepend a stale session, trim, and require the survivor to still
    schema-validate and export (the ISSUE 4 trim satellite)."""
    trim = _load("trim_records")
    committed = open(RECORDS).read()
    stale = [
        {"kind": "solver.iter", "ts": 1000.0, "solver": "cg", "iter": 1},
        {"kind": "bench.session", "ts": 1010.0, "status": "cpu",
         "budget_spent_s": 5.0},
    ]
    target = tmp_path / "records.jsonl"
    with open(target, "w") as f:
        for rec in stale:
            f.write(json.dumps(rec) + "\n")
        f.write(committed)
    dropped = trim.trim(str(target), dry_run=False)
    assert dropped >= len(stale)

    from sparse_tpu import telemetry

    assert telemetry.schema.validate_jsonl(str(target)) == []
    from sparse_tpu.telemetry import _trace

    events = _trace.read_events_jsonl(str(target))
    assert events
    trace = _trace.to_chrome_trace(events)
    assert trace["traceEvents"]


# -- Axon v3: lanes round-trip, ticket rollups, roofline, serve smoke ---------


def _write_v3_records(path, ts0=1700000000.0):
    """A synthetic serving session: two tickets (one requeued, one SLO
    miss), one attributed program, plus one event per batch/resilience
    kind — the lane and rollup surfaces ISSUE 6 pins."""
    ts = ts0
    lines = [
        {"kind": "plan_cache.compile", "ts": ts,
         "program": "batch.cg.B4.<f8", "solver": "cg", "bucket": 4,
         "dtype": "<f8", "n": 64, "nnz": 190, "compile_s": 0.25,
         "pack_s": 0.05, "flops": 2.0e6, "bytes": 1.0e6,
         "peak_bytes": 3_000_000},
        {"kind": "batch.dispatch", "ts": ts + 1.0, "solver": "cg",
         "batch": 2, "bucket": 4, "pad": 2, "program": "batch.cg.B4.<f8",
         "solve_ms": 10.0, "tickets": ["tk-1", "tk-2"]},
        {"kind": "batch.requeue", "ts": ts + 1.1, "solver": "gmres",
         "lanes": 1, "from_solver": "cg", "tickets": ["tk-2"]},
        {"kind": "batch.dispatch", "ts": ts + 1.5, "solver": "gmres",
         "batch": 1, "bucket": 1, "pad": 0, "program": "batch.cg.B4.<f8",
         "solve_ms": 10.0, "tickets": ["tk-2"]},
        {"kind": "batch.ticket", "ts": ts + 1.2, "ticket": "tk-1",
         "state": "done", "solver": "cg", "latency_ms": 12.0,
         "requeued": False, "slo_ms": 50.0, "slo_miss": False,
         "phases": {"queue_ms": 1.0, "pack_ms": 0.5, "compile_ms": 2.0,
                    "solve_ms": 8.0, "readback_ms": 0.5}},
        {"kind": "batch.ticket", "ts": ts + 1.6, "ticket": "tk-2",
         "state": "done", "solver": "gmres", "latency_ms": 80.0,
         "requeued": True, "slo_ms": 50.0, "slo_miss": True,
         "phases": {"queue_ms": 30.0, "pack_ms": 1.0, "compile_ms": 20.0,
                    "solve_ms": 28.0, "readback_ms": 1.0}},
        {"kind": "fault.injected", "ts": ts + 2.0, "fault": "nonfinite",
         "site": "matvec"},
        {"kind": "solver.retry", "ts": ts + 2.1, "solver": "cg",
         "attempt": 1, "action": "restart", "reason": "stagnation"},
        {"kind": "kernel.reinstate", "ts": ts + 2.2, "kernel": "dia_spmv"},
        {"kind": "bench.probe_timeout", "ts": ts + 3.0, "probe": "tpu",
         "timeout_s": 120.0},
    ]
    with open(path, "w") as f:
        for rec in lines:
            f.write(json.dumps(rec) + "\n")
    return path


def test_v3_kinds_schema_valid_and_lanes_round_trip(tmp_path):
    """Satellite: batch.* and resilience.* kinds get their own process
    lanes (never the "other" catch-all), the new ticket/compile kinds
    included, via a full JSONL -> trace CLI round-trip."""
    rec = _write_v3_records(str(tmp_path / "v3.jsonl"))
    from sparse_tpu import telemetry

    assert telemetry.schema.validate_jsonl(rec) == []

    out = str(tmp_path / "v3-trace.json")
    assert _load("axon_trace").main([rec, out]) == 0
    trace = json.load(open(out))
    evs = trace["traceEvents"]
    lane_name = {
        m["pid"]: m["args"]["name"].split("/")[-1]
        for m in evs if m.get("ph") == "M" and m["name"] == "process_name"
    }
    lane_of = {
        e["name"]: lane_name[e["pid"]] for e in evs if e.get("ph") == "i"
    }
    assert lane_of["batch.dispatch"] == "batch"
    assert lane_of["batch.requeue"] == "batch"
    assert lane_of["fault.injected"] == "resilience"
    assert lane_of["solver.retry"] == "solver"
    assert lane_of["kernel.reinstate"] == "kernels"
    assert lane_of["plan_cache.compile"] == "plan_cache"
    assert lane_of["bench.probe_timeout"] == "bench"
    assert "other" not in lane_of.values()
    # each ticket renders one end-to-end slice + its phase slices on the
    # tickets lane; the requeued ticket's phases tile its latency
    tickets = [e for e in evs if e.get("cat") == "ticket"]
    assert {e["name"] for e in tickets} == {"ticket tk-1", "ticket tk-2"}
    assert all(lane_name[e["pid"]] == "tickets" for e in tickets)
    (tk2,) = [e for e in tickets if e["name"] == "ticket tk-2"]
    assert tk2["dur"] == pytest.approx(80.0 * 1e3)
    phases = [
        e for e in evs if e.get("cat") == "ticket.phase"
        and e["tid"] == tk2["tid"]
    ]
    assert [p["name"] for p in phases] == [
        "queue", "pack", "compile", "solve", "readback"
    ]
    assert sum(p["dur"] for p in phases) <= tk2["dur"]


def test_report_ticket_percentiles_slo_and_roofline(tmp_path):
    rec = _write_v3_records(str(tmp_path / "v3.jsonl"))
    mod = _load("axon_report")
    rep = mod.build_report(rec, peak_gflops=100.0, peak_gbs=50.0)

    tk = rep["tickets"]
    assert tk["n"] == 2 and tk["requeued"] == 1 and tk["slo_misses"] == 1
    assert tk["states"] == {"done": 2}
    # nearest-rank on two samples: the upper median
    assert tk["latency_ms"]["p50"] == 80.0
    assert tk["latency_ms"]["p99"] == 80.0
    assert tk["latency_ms"]["mean"] == pytest.approx(46.0)
    assert tk["phase_ms_mean"]["solve"] == pytest.approx(18.0)

    # roofline join: 2 dispatches x 2MFLOP over 20ms of solve time
    prog = rep["programs"]["batch.cg.B4.<f8"]
    assert prog["solves"] == 2 and prog["solve_ms_total"] == 20.0
    assert prog["achieved_gflops"] == pytest.approx(0.2)
    assert prog["pct_peak_gflops"] == pytest.approx(0.2, rel=0.01)
    assert prog["achieved_gbs"] == pytest.approx(0.1)
    assert prog["flops_per_byte"] == pytest.approx(2.0)
    assert rep["cold_start_s"] == pytest.approx(0.3)

    # ...and the comparable metrics surface carries all of it
    m = rep["metrics"]
    assert m["tickets.latency_ms.p99"]["v"] == tk["latency_ms"]["p99"]
    assert m["tickets.slo_misses"]["v"] == 1
    assert m["cold_start_s"]["v"] == pytest.approx(0.3)
    assert m["program.batch.cg.B4.<f8.achieved_gflops"]["hib"] is True

    # the CLI renders the new sections without error
    out_json = str(tmp_path / "rep.json")
    assert mod.main(
        [rec, "--json", out_json, "--peak-gflops", "100",
         "--peak-gbs", "50", "--quiet"]
    ) == 0
    assert json.load(open(out_json))["tickets"]["n"] == 2


def test_report_without_serving_events_omits_ticket_metrics(tmp_path):
    rec = _write_records(str(tmp_path / "plain.jsonl"), [0.01] * 4)
    rep = _load("axon_report").build_report(rec)
    assert rep["tickets"]["n"] == 0
    assert rep["programs"] == {} and rep["cold_start_s"] == 0
    assert not any(k.startswith("tickets.") for k in rep["metrics"])


def test_axon_serve_once_smoke(capsys):
    """Quick-lane smoke (ISSUE 6 satellite): start the exporter on an
    ephemeral port, scrape /metrics + /healthz + /session, shut down
    cleanly — all through the CLI's --once path."""
    assert _load("axon_serve").main(["--once"]) == 0
    out = capsys.readouterr().out
    assert "listening on http://127.0.0.1:" in out
    assert "/metrics: " in out and "series" in out
    assert "/healthz: " in out and "status" in out
    assert "/session: " in out and "queue_depth" in out

    from sparse_tpu import telemetry

    assert telemetry.serving() is None  # --once left nothing running


def test_axon_serve_bad_usage_exits_2(capsys):
    mod = _load("axon_serve")
    assert mod.main(["--port", "nope"]) == 2
    assert mod.main(["--bogus"]) == 2


# -- Axon v5: load/alerts rollups, sustained_cg lift, informational compare ---


def _write_v5_records(path, ts0=1700000000.0):
    """A synthetic Axon v5 session: one loadgen run, one watchdog
    alert->clear chain plus one unresolved alert, and a bench.session
    embedding a sustained_cg row."""
    ts = ts0
    lines = [
        {"kind": "loadgen.trace", "ts": ts,
         "trace": "poisson:rate=150,duration=1.5,seed=23",
         "arrivals": 220, "completed": 218, "failed": 2, "wall_s": 1.62,
         "offered_rps": 146.7, "achieved_rps": 134.6, "p50_ms": 18.0,
         "p95_ms": 42.0, "p99_ms": 88.0, "slo_ms": 250.0,
         "slo_miss_rate": 0.009, "fairness": 0.98, "dispatches": 40,
         "tenants": {"a": {"completed": 109, "achieved_rps": 67.3,
                           "weight": 1.0},
                     "b": {"completed": 109, "achieved_rps": 67.3,
                           "weight": 1.0}}},
        {"kind": "watchdog.alert", "ts": ts + 0.5, "rule": "slo_miss_rate",
         "severity": "page", "value": 0.8, "trigger": 0.5, "op": ">"},
        {"kind": "watchdog.clear", "ts": ts + 1.0, "rule": "slo_miss_rate",
         "value": 0.0, "active_s": 0.5},
        {"kind": "watchdog.alert", "ts": ts + 1.2, "rule": "queue_depth",
         "severity": "warn", "value": 900.0, "trigger": 512.0, "op": ">"},
        {"kind": "bench.session", "ts": ts + 2.0, "status": "cpu",
         "record": {"metric": "cg_iters_per_s_pde512_cpu", "value": 100.0,
                    "unit": "iters/s",
                    "sustained_cg": {"offered_rps": 146.7,
                                     "achieved_rps": 134.6,
                                     "p50_ms": 18.0, "p95_ms": 42.0,
                                     "p99_ms": 88.0, "slo_ms": 250.0,
                                     "slo_miss_rate": 0.009,
                                     "p95_under_slo": True}}},
    ]
    with open(path, "w") as f:
        for rec in lines:
            f.write(json.dumps(rec) + "\n")
    return path


def test_v5_kinds_schema_valid(tmp_path):
    rec = _write_v5_records(str(tmp_path / "v5.jsonl"))
    from sparse_tpu import telemetry

    assert telemetry.schema.validate_jsonl(rec) == []


def test_report_load_alerts_and_sustained_rollups(tmp_path):
    rec = _write_v5_records(str(tmp_path / "v5.jsonl"))
    mod = _load("axon_report")
    rep = mod.build_report(rec)

    load = rep["load"]
    assert load["runs"] == 1
    assert load["last"]["achieved_rps"] == 134.6
    assert load["last"]["tenants"]["a"]["completed"] == 109

    al = rep["alerts"]
    assert al["fired"] == 2 and al["cleared"] == 1
    assert al["by_rule"]["slo_miss_rate"]["last"] == "clear"
    assert al["unresolved"] == ["queue_depth"]

    assert rep["sustained_row"]["p95_under_slo"] is True

    m = rep["metrics"]
    assert m["load.achieved_rps"] == {"v": 134.6, "hib": True}
    assert m["load.p95_ms"]["hib"] is False
    assert m["load.fairness"]["hib"] is True
    assert m["alerts.fired"] == {"v": 2, "hib": False}
    assert m["sustained_cg.achieved_rps"] == {"v": 134.6, "hib": True}
    assert m["sustained_cg.p95_ms"] == {"v": 42.0, "hib": False}
    assert m["sustained_cg.slo_miss_rate"]["hib"] is False

    # the CLI renders the new sections and writes them to --json
    out_json = str(tmp_path / "v5.json")
    assert mod.main([rec, "--json", out_json, "--quiet"]) == 0
    dumped = json.load(open(out_json))
    assert dumped["load"]["runs"] == 1
    assert dumped["alerts"]["unresolved"] == ["queue_depth"]


def test_compare_treats_one_sided_metrics_as_informational(tmp_path, capsys):
    """ISSUE 11 satellite: a metric missing from the baseline (a new
    bench row like sustained_cg) is LISTED, never a regression — and a
    vanished metric is surfaced the same way."""
    mod = _load("axon_report")
    base_rec = _write_records(str(tmp_path / "base.jsonl"), [0.010] * 8)
    base_json = str(tmp_path / "base.json")
    assert mod.main([base_rec, "--quiet", "--json", base_json]) == 0
    # the current run gains sustained_cg/load metrics the baseline
    # predates (plus all the v5 kinds)
    cur = _write_v5_records(str(tmp_path / "cur.jsonl"))
    capsys.readouterr()
    rc = mod.main([cur, "--compare", base_json])
    out = capsys.readouterr()
    assert rc == 0, "new-only metrics must not gate"
    assert "informational" in out.out
    assert "sustained_cg.achieved_rps" in out.out or "..." in out.out
    # ...and the reverse direction (baseline has rows this run lost)
    cur_json = str(tmp_path / "cur.json")
    assert mod.main([cur, "--quiet", "--json", cur_json]) == 0
    capsys.readouterr()
    rc = mod.main([base_rec, "--compare", cur_json])
    out = capsys.readouterr()
    assert rc == 0
    assert "missing from this run (informational)" in out.out

    info = mod.informational(
        mod.build_report(cur), json.load(open(base_json))
    )
    assert "sustained_cg.achieved_rps" in info["new"]
    assert "span.bench.step.p50_s" in info["vanished"]


def test_axon_serve_once_prints_bound_port_on_busy_port(capsys):
    """ISSUE 11 satellite: a taken port falls back to an ephemeral bind
    and the CLI prints the port that actually answers."""
    import socket

    blocker = socket.socket()
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    busy = blocker.getsockname()[1]
    try:
        assert _load("axon_serve").main(["--once", "--port", str(busy)]) == 0
    finally:
        blocker.close()
    out = capsys.readouterr().out
    assert f"(requested {busy} busy)" in out
    bound = [
        ln for ln in out.splitlines()
        if ln.startswith("axon_serve: bound port ")
    ]
    assert bound and str(busy) != bound[0].split()[3]
    assert "/alerts: " in out
