"""SpGEMM oracle tests vs scipy.

Reference analog: ``tests/integration/test_csr_spgemm.py`` — CSR@CSR and
CSR@CSC products over the fixture files with a dtype cross axis.
"""

import numpy as np
import pytest
import scipy.io as sci_io

import sparse_tpu as sparse
from .utils.common import test_mtx_files, types
from .utils.sample import sample_csr


@pytest.mark.parametrize("filename", test_mtx_files)
@pytest.mark.parametrize("b_type", types)
def test_csr_csr_csr_spgemm(filename, b_type):
    arr = sparse.io.mmread(filename)
    s = sci_io.mmread(filename).tocsr()
    # A @ A for square fixtures, A @ A^T for the rectangular one
    other = arr.tocsr() if arr.shape[0] == arr.shape[1] else arr.T.tocsr()
    s_other = s if s.shape[0] == s.shape[1] else s.T.tocsr()
    res = arr.tocsr().astype(b_type) @ other.astype(b_type)
    res_sci = s.astype(b_type) @ s_other.astype(b_type)
    assert np.allclose(np.asarray(res.todense()), res_sci.todense(), atol=1e-5)


@pytest.mark.parametrize("b_type", [np.float32, np.complex128])
@pytest.mark.parametrize("c_type", types)
def test_csr_spgemm_mixed_dtypes(b_type, c_type):
    sa = sample_csr(23, 17, density=0.3, dtype=b_type, seed=50)
    sb = sample_csr(17, 29, density=0.3, dtype=c_type, seed=51)
    res = sparse.csr_array(sa) @ sparse.csr_array(sb)
    res_sci = sa @ sb
    assert res.dtype == res_sci.dtype
    assert np.allclose(np.asarray(res.todense()), res_sci.todense(), atol=1e-5)


@pytest.mark.parametrize("filename", test_mtx_files)
def test_csr_csr_csc_spgemm(filename):
    arr = sparse.io.mmread(filename)
    s = sci_io.mmread(filename)
    other = arr if arr.shape[0] == arr.shape[1] else arr.T
    s_other = s if s.shape[0] == s.shape[1] else s.T
    res = arr.tocsr() @ other.tocsc()
    res_sci = s.tocsr() @ s_other.tocsc()
    assert np.allclose(np.asarray(res.todense()), res_sci.todense(), atol=1e-5)


def test_spgemm_rectangular_chain():
    """Galerkin-style triple product R @ A @ P (the AMG hot path)."""
    A = sample_csr(40, 40, density=0.15, seed=52)
    P = sample_csr(40, 12, density=0.3, seed=53)
    R = P.T.tocsr()
    got = sparse.csr_array(R) @ (sparse.csr_array(A) @ sparse.csr_array(P))
    exp = R @ (A @ P)
    assert np.allclose(np.asarray(got.todense()), exp.todense(), atol=1e-6)


def test_spgemm_empty_result():
    import scipy.sparse as sp

    a = sp.csr_matrix((5, 7))
    b = sp.csr_matrix((7, 3))
    got = sparse.csr_array(a) @ sparse.csr_array(b)
    assert got.shape == (5, 3)
    assert got.nnz == 0
