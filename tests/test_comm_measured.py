"""Axon v4 mesh observability (ISSUE 7): measured collective accounting
(``sparse_tpu/parallel/comm.py``), per-process event identity, and the
multi-host telemetry merge.

Pins the PR's acceptance surface: (a) the S=8 CPU dryrun parity —
measured ``comm.measured`` bytes for halo- AND gather-mode ``dist_cg``
agree with the analytic ``comm_stats`` model within 10%, with the
per-SpMV accounting agreeing EXACTLY; (b) always-on
``comm.collective_bytes{op,site}`` metrics accumulate without telemetry
enabled; (c) the recorder stamps every event with process identity and
leads each sink file with a ``session.start`` clock base; (d)
``scripts/axon_merge.py`` round-trips two per-process logs into one
clock-aligned session that ``axon_trace`` renders with per-process lanes
(never "other") and ``axon_report --compare`` accepts.
"""

import importlib.util
import json
import os

import numpy as np
import pytest
import scipy.sparse as sp

import sparse_tpu
from sparse_tpu import telemetry
from sparse_tpu.config import settings
from sparse_tpu.parallel import comm
from sparse_tpu.telemetry import _metrics, _recorder

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXDIR = os.path.join(REPO, "testdata", "axon_two_proc")


def _load(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def tel(tmp_path, monkeypatch):
    """Telemetry enabled with an isolated sink; fully reset afterwards."""
    telemetry.reset()
    monkeypatch.setattr(settings, "telemetry", True)
    telemetry.configure(str(tmp_path / "records.jsonl"))
    yield tmp_path / "records.jsonl"
    telemetry.configure(None)
    telemetry.reset()


def _band_csr(n=1024, offs=(-8, -4, -1, 0, 1, 4, 8)):
    """SPD band matrix whose halo (16 entries) dwarfs the per-iteration
    scalar psums — the shape where the 10% reconciliation is meaningful."""
    A = sp.diags([np.ones(n - abs(k)) for k in offs], offs).tocsr()
    return (A + sp.diags(np.full(n, 20.0))).astype(np.float32)


def _bytes_metric(site):
    vals = 0
    with _metrics._LOCK:
        items = [
            m for (nm, _), m in _metrics._REGISTRY.items()
            if nm == comm.BYTES_METRIC and m.labels.get("site") == site
        ]
    for m in items:
        vals += int(m.value)
    return vals


# -- (a) SiteLedger semantics -------------------------------------------------


def test_ledger_idempotent_notes_and_commit_math():
    led = comm.SiteLedger("test.site")
    led.note("ppermute", "a", 100)
    led.note("ppermute", "a", 120)  # re-trace overwrites, never doubles
    led.note("all_gather", "b", 50, exact=False)
    assert led.bytes_per_shard() == 170
    assert not led.exact
    per = led.per_op()
    assert per["ppermute"] == {"calls": 1, "bytes": 120}
    assert per["all_gather"] == {"calls": 1, "bytes": 50}
    before = _bytes_metric("test.site")
    led.commit(executions=3, shards=4)
    assert _bytes_metric("test.site") - before == 170 * 3 * 4
    assert comm.sites()["test.site"]["bytes_per_shard"] == 170


# -- (b) S=8 dryrun parity: the acceptance criterion --------------------------


@pytest.mark.parametrize("mode,kwargs", [
    ("halo", {}),
    ("gather", {"halo_max_ratio": 0.0}),
])
def test_dist_cg_measured_matches_model_within_10pct(tel, mode, kwargs):
    from sparse_tpu.parallel.dist import comm_stats, dist_cg, shard_csr

    A = _band_csr()
    D = shard_csr(sparse_tpu.csr_array(A), **kwargs)
    assert D.mode == mode
    b = np.ones(A.shape[0], np.float32)
    _, iters, _ = dist_cg(D, b, tol=1e-30, maxiter=25, conv_test_iters=5)
    assert iters == 25
    cs = comm_stats(D, 5)
    led = D._comm_ledger
    # per-SpMV: trace-derived bytes equal the structural model EXACTLY
    assert led.bytes_per_shard() == cs["spmv_collective_bytes_per_shard"]
    evs = telemetry.events("comm.measured")
    ev = [e for e in evs if e.get("site") == "dist.cg"][-1]
    assert ev["S"] == D.S and ev["executions"] == iters + 1
    assert ev["exact"] is True
    # whole-solve reconciliation within the 10% gate (residue: GSPMD
    # scalar psums on the model side, the initial-residual SpMV on the
    # measured side)
    assert abs(ev["divergence_pct"]) <= 10.0
    assert ev["bytes"] == led.bytes_per_shard() * (iters + 1) * D.S
    assert ev["solve_s"] > 0 and ev["gbs_per_shard"] >= 0


def test_dist_cg_halo_vs_gather_measured_ordering(tel):
    """The gather fallback must measure as strictly more traffic than the
    halo path on the same operator — the regression the accounting is
    for (a banded matrix silently flipping to gather)."""
    from sparse_tpu.parallel.dist import dist_cg, shard_csr

    A = _band_csr(512)
    b = np.ones(512, np.float32)
    Dh = shard_csr(sparse_tpu.csr_array(A))
    dist_cg(Dh, b, tol=1e-30, maxiter=5, conv_test_iters=5)
    Dg = shard_csr(sparse_tpu.csr_array(A), halo_max_ratio=0.0)
    dist_cg(Dg, b, tol=1e-30, maxiter=5, conv_test_iters=5)
    assert (
        Dg._comm_ledger.bytes_per_shard()
        > 10 * Dh._comm_ledger.bytes_per_shard()
    )


# -- (c) always-on metrics (no telemetry) -------------------------------------


def test_eager_spmv_commits_always_on_metrics():
    from sparse_tpu.parallel.dist import shard_csr

    assert not telemetry.enabled()
    A = _band_csr(512)
    D = shard_csr(sparse_tpu.csr_array(A))
    x = np.ones(512, np.float32)
    D.dot(x)  # first call traces AND commits one execution
    base = _bytes_metric("dist.spmv")
    per_exec = D._comm_ledger.bytes_per_shard() * D.S
    assert per_exec > 0
    D.dot(x)
    D.dot(x)
    assert _bytes_metric("dist.spmv") - base == 2 * per_exec


def test_col_split_psum_scatter_accounted():
    from sparse_tpu.parallel.dist import shard_csr_cols

    A = _band_csr(512)
    Dc = shard_csr_cols(sparse_tpu.csr_array(A))
    v = np.ones(512, np.float32)
    assert np.all(np.isfinite(Dc.dot(v)))
    led = Dc._comm_ledger
    it = np.dtype(np.float32).itemsize
    S = Dc.S
    expect = (S * Dc.R * it) * (S - 1) // S  # ring reduce-scatter of y_full
    assert led.entries == {("psum_scatter", "y"): expect}


def test_samplesort_sites_accounted(tel):
    from sparse_tpu.parallel.sort import dist_sort_host

    keys = np.random.default_rng(5).permutation(1 << 10).astype(np.int64)
    sk, _ = dist_sort_host(keys)
    np.testing.assert_array_equal(sk, np.sort(keys))
    st = comm.sites()
    assert st.get("sort.sample1", {}).get("bytes_per_shard", 0) > 0
    assert st.get("sort.sample2", {}).get("bytes_per_shard", 0) > 0
    evs = [
        e for e in telemetry.events("comm.measured")
        if e.get("site") == "sort.sample"
    ]
    # capacity-shaped accounting (dense-slot emulation on the CPU mesh is
    # exact wire volume; the native ragged path marks exact=False)
    assert evs and evs[-1]["bytes"] > 0
    assert evs[-1]["model_bytes"] > 0


def test_hierarchy_comm_per_cycle_sums_ledgers():
    from sparse_tpu.parallel.mesh import get_mesh
    from sparse_tpu.parallel.multigrid import (
        hierarchy_comm_per_cycle,
        shard_hierarchy,
    )

    nf, nc = 256, 64
    Af = sparse_tpu.csr_array(_band_csr(nf))
    cols = (np.arange(nc) * 4).astype(np.int64)
    R = sparse_tpu.csr_array.from_parts(
        np.ones(nc, np.float32), cols, np.arange(nc + 1, dtype=np.int64),
        (nc, nf),
    )
    P = R.T.tocsr()
    Ac = R @ Af @ P
    ops, _ = shard_hierarchy([Af, Ac], [(R, P)], get_mesh(8))
    # untraced hierarchy: nothing to sum yet
    assert hierarchy_comm_per_cycle(ops)["bytes_per_shard_per_cycle"] == 0
    for Ad, Rd, Pd in ops:
        for op in (Ad, Rd, Pd):
            if op is not None:
                op.dot(np.ones(op.shape[1], np.float32))
    stats = hierarchy_comm_per_cycle(ops)
    expect = [
        sum(
            (op._comm_ledger.bytes_per_shard() if op is not None and
             getattr(op, "_comm_ledger", None) is not None else 0) * k
            for op, k in ((Ad, 3), (Rd, 1), (Pd, 1))
        )
        for Ad, Rd, Pd in ops
    ]
    assert stats["levels_bytes_per_shard"] == expect
    assert stats["bytes_per_shard_per_cycle"] == sum(expect) > 0
    assert stats["exact"] is True


# -- (d) per-process identity -------------------------------------------------


def test_events_carry_identity_and_session_start(tel):
    telemetry.record("solver.solve", solver="cg", iters=1, path="host")
    ident = telemetry.process_identity()
    ev = telemetry.events("solver.solve")[-1]
    assert ev["pi"] == ident["pi"] and ev["pid"] == ident["pid"]
    assert isinstance(ev["tm"], float) and ev["tm"] >= 0.0
    lines = [json.loads(ln) for ln in open(telemetry.sink_path())]
    assert lines[0]["kind"] == "session.start"
    assert lines[0]["epoch"] > 0 and lines[0]["mono"] >= 0
    assert lines[0]["pid"] == ident["pid"]
    assert lines[0]["session"] == telemetry.session_info()["session"]
    from sparse_tpu.telemetry import schema

    assert schema.validate_jsonl(telemetry.sink_path()) == []


def test_multi_controller_sink_splits_per_pid(tmp_path):
    telemetry.reset()
    os.environ["SPARSE_TPU_PROCESS_COUNT"] = "2"
    os.environ["SPARSE_TPU_PROCESS_INDEX"] = "1"
    _recorder.reset_identity()
    settings.telemetry = True
    telemetry.configure(str(tmp_path / "records.jsonl"))
    try:
        telemetry.record("span", name="x", dur_s=0.01)
        path = telemetry.sink_path()
        assert path.endswith(f"records.{os.getpid()}.jsonl")
        assert os.path.exists(path)
        assert not os.path.exists(tmp_path / "records.jsonl")
        first = json.loads(open(path).readline())
        assert first["kind"] == "session.start" and first["pi"] == 1
        assert first["procs"] == 2
    finally:
        settings.telemetry = False
        telemetry.configure(None)
        os.environ.pop("SPARSE_TPU_PROCESS_COUNT", None)
        os.environ.pop("SPARSE_TPU_PROCESS_INDEX", None)
        _recorder.reset_identity()
        telemetry.reset()


# -- (e) the merge round-trip -------------------------------------------------


def _fixture_paths():
    return [
        os.path.join(FIXDIR, "records.1001.jsonl"),
        os.path.join(FIXDIR, "records.1002.jsonl"),
    ]


def test_axon_merge_roundtrip_two_process_fixture(tmp_path):
    m = _load("axon_merge")
    out = str(tmp_path / "merged.jsonl")
    summary = m.merge_files(_fixture_paths(), out, align="session")
    assert summary["processes"] == 2
    recs = [json.loads(ln) for ln in open(out)]
    assert len(recs) == summary["events"]
    ts = [r["ts"] for r in recs]
    assert ts == sorted(ts)
    # session alignment: both session.start records land on one origin
    starts = [r for r in recs if r["kind"] == "session.start"]
    assert len(starts) == 2 and starts[0]["ts"] == starts[1]["ts"]
    # every event attributed — the trace must never need an "other" lane
    assert all("pi" in r for r in recs)
    from sparse_tpu.telemetry import _trace

    trace = _trace.to_chrome_trace(recs)
    names = [
        e["args"]["name"] for e in trace["traceEvents"]
        if e.get("name") == "process_name"
    ]
    assert any(n.startswith("sparse_tpu/p0/") for n in names)
    assert any(n.startswith("sparse_tpu/p1/") for n in names)
    assert any(n.endswith("/comm") for n in names)  # per-device comm lanes
    assert not any("other" in n for n in names)


def test_axon_merge_wall_alignment_preserves_skew(tmp_path):
    m = _load("axon_merge")
    out = str(tmp_path / "merged_wall.jsonl")
    m.merge_files(_fixture_paths(), out, align="wall")
    recs = [json.loads(ln) for ln in open(out)]
    starts = sorted(
        (r for r in recs if r["kind"] == "session.start"),
        key=lambda r: r["ts"],
    )
    # the fixture's controllers start 3.2s apart on the wall clock
    assert starts[1]["ts"] - starts[0]["ts"] == pytest.approx(3.2)


def test_axon_merge_cli_and_report_compare_roundtrip(tmp_path):
    """The quick-lane smoke (ISSUE 7 CI satellite): merge the committed
    two-process fixture, then axon_report --json on the merged log and
    --compare against its own report must both exit 0."""
    m = _load("axon_merge")
    out = str(tmp_path / "merged.jsonl")
    assert m.main([os.path.join(FIXDIR, "records.*.jsonl"), "-o", out]) == 0
    rep_path = str(tmp_path / "report.json")
    r = _load("axon_report")
    assert r.main([out, "--quiet", "--json", rep_path]) == 0
    assert (
        r.main([out, "--quiet", "--compare", rep_path, "--threshold", "0.2"])
        == 0
    )
    rep = json.load(open(rep_path))
    assert rep["comm"]["dist.cg"]["events"] == 2
    assert "comm.dist.cg.abs_divergence_pct" in rep["metrics"]


def test_report_comm_rollup_ici_roofline(tmp_path):
    r = _load("axon_report")
    path = str(tmp_path / "records.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({
            "kind": "comm.measured", "ts": 1.0, "site": "dist.cg",
            "bytes": 8_000_000, "bytes_per_shard": 1_000_000,
            "executions": 26, "S": 8, "exact": True,
            "model_bytes": 8_400_000, "solve_s": 0.01,
        }) + "\n")
    rep = r.build_report(path, peak_ici_gbs=100.0)
    site = rep["comm"]["dist.cg"]
    assert site["divergence_pct"] == pytest.approx(-4.762, abs=1e-3)
    assert site["achieved_gbs_per_shard"] == pytest.approx(0.1)
    assert site["pct_peak_ici"] == pytest.approx(0.1)
    assert rep["metrics"]["comm.dist.cg.abs_divergence_pct"]["v"] == pytest.approx(4.762, abs=1e-3)
    assert rep["metrics"]["comm.dist.cg.achieved_gbs_per_shard"]["hib"]


# -- (f) trim keeps per-process logs mergeable -------------------------------


def test_trim_keeps_latest_session_start(tmp_path):
    t = _load("trim_records")
    path = str(tmp_path / "records.4242.jsonl")
    old_session = {"kind": "session.start", "ts": 100.0, "epoch": 100.0,
                   "mono": 1.0, "pi": 0, "pid": 4242}
    with open(path, "w") as f:
        f.write(json.dumps(old_session) + "\n")
        f.write(json.dumps({"kind": "span", "ts": 101.0, "name": "old",
                            "dur_s": 0.1}) + "\n")
        f.write(json.dumps({"kind": "bench.session", "ts": 5000.0,
                            "status": "ok", "budget_spent_s": 10.0}) + "\n")
        f.write(json.dumps({"kind": "span", "ts": 5001.0, "name": "new",
                            "dur_s": 0.1}) + "\n")
    dropped = t.trim(path)
    kept = [json.loads(ln) for ln in open(path)]
    assert dropped == 1  # the old span went; the old session.start stayed
    assert any(r.get("kind") == "session.start" for r in kept)
    assert not any(r.get("name") == "old" for r in kept)


def test_trim_all_globs_per_process_files(tmp_path, monkeypatch):
    t = _load("trim_records")
    monkeypatch.setattr(t, "AXON_DIR", str(tmp_path))
    for pid in (1, 2):
        with open(tmp_path / f"records.{pid}.jsonl", "w") as f:
            f.write(json.dumps({"kind": "span", "ts": 1.0, "name": "x",
                                "dur_s": 0.1}) + "\n")
    # no bench.session anchor in either file: both kept whole, no crash
    assert t.trim_all() == 0
    for pid in (1, 2):
        assert (tmp_path / f"records.{pid}.jsonl").exists()


# -- (g) serving identity -----------------------------------------------------


def test_serve_exposes_process_identity(tel):
    import urllib.request

    server = telemetry.serve(port=0)
    try:
        with urllib.request.urlopen(server.url + "/healthz", timeout=5) as r:
            h = json.loads(r.read())
        ident = telemetry.process_identity()
        assert h["process"]["pi"] == ident["pi"]
        assert h["process"]["pid"] == ident["pid"]
        assert h["process"]["session_epoch"] > 0
        assert "sink" in h["process"]
        with urllib.request.urlopen(server.url + "/metrics", timeout=5) as r:
            text = r.read().decode()
        assert "sparse_tpu_process_info{" in text
        assert f'pid="{ident["pid"]}"' in text
        assert "sparse_tpu_process_devices" in text
    finally:
        telemetry.stop_serving()
