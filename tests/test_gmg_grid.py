"""Structured-grid GMG (sparse_tpu/models/gmg_grid.py) oracle tests.

Every grid-space op is pinned EXACTLY (f64 atol 1e-12) to the explicit
sparse-matrix formulation it replaces — the restriction/prolongation
matrices and Galerkin SpGEMM products of examples/gmg.py — so the stencil
pipeline is provably the same linear algebra, just without general sparse
formats. Reference analog: examples/gmg.py:287-381 (gmg.py:303-380 in the
reference repo).
"""

import numpy as np
import pytest
import scipy.sparse as sp

import jax
import jax.numpy as jnp

from sparse_tpu.models import gmg_grid as gg


def poisson_sp(N):
    diag_a = np.full(N * N - 1, -1.0)
    diag_a[N - 1 :: N] = 0.0
    diag_g = -np.ones(N * (N - 1))
    diag_c = 4.0 * np.ones(N * N)
    return sp.diags(
        [diag_g, diag_a, diag_c, diag_a, diag_g], [-N, -1, 0, 1, N]
    ).tocsr()


def R_mat(fine_n, gridop):
    """Explicit restriction matrix (examples/gmg.py:injection_operator /
    linear_operator, scipy form)."""
    coarse_n = fine_n // 2
    coarse_dim = coarse_n * coarse_n
    fine_dim = fine_n * fine_n
    ij = np.arange(coarse_dim)
    ci, cj = ij // coarse_n, ij % coarse_n
    if gridop == "injection":
        cols = 2 * ci * fine_n + 2 * cj
        return sp.csr_matrix(
            (np.ones(coarse_dim), cols, np.arange(coarse_dim + 1)),
            shape=(coarse_dim, fine_dim),
        )
    rows_l, cols_l, vals_l = [], [], []
    weights = {(-1, -1): 1, (-1, 0): 2, (-1, 1): 1,
               (0, -1): 2, (0, 0): 4, (0, 1): 2,
               (1, -1): 1, (1, 0): 2, (1, 1): 1}
    for (di, dj), w in weights.items():
        fi = 2 * ci + di
        fj = 2 * cj + dj
        ok = (fi >= 0) & (fi < fine_n) & (fj >= 0) & (fj < fine_n)
        rows_l.append(ij[ok])
        cols_l.append((fi * fine_n + fj)[ok])
        vals_l.append(np.full(int(ok.sum()), w / 16.0))
    return sp.coo_matrix(
        (np.concatenate(vals_l), (np.concatenate(rows_l), np.concatenate(cols_l))),
        shape=(coarse_dim, fine_dim),
    ).tocsr()


def stencil_to_dense(stc, cn):
    out = np.zeros((cn * cn, cn * cn))
    for (di, dj), C in stc.items():
        C = np.broadcast_to(np.asarray(C), (cn, cn))  # scalar or plane form
        for i in range(cn):
            for j in range(cn):
                ii, jj = i + di, j + dj
                if 0 <= ii < cn and 0 <= jj < cn:
                    out[i * cn + j, ii * cn + jj] += C[i, j]
    return out


@pytest.mark.parametrize("n", [8, 9, 13])
@pytest.mark.parametrize("gridop", ["linear", "injection"])
def test_grid_ops_match_matrices(n, gridop):
    cn = n // 2
    A = poisson_sp(n)
    R = R_mat(n, gridop)
    P = R.T.tocsr()
    st = gg.poisson_stencil(n, jnp.float64)
    x = np.random.default_rng(1).random((n, n))
    z = np.random.default_rng(2).random((cn, cn))

    np.testing.assert_allclose(
        np.asarray(gg.stencil_apply(st, jnp.asarray(x))),
        (A @ x.reshape(-1)).reshape(n, n), atol=1e-12,
    )
    np.testing.assert_allclose(
        np.asarray(gg.restrict_grid(jnp.asarray(x), cn, gridop)),
        (R @ x.reshape(-1)).reshape(cn, cn), atol=1e-12,
    )
    np.testing.assert_allclose(
        np.asarray(gg.prolong_grid(jnp.asarray(z), n, cn, gridop)),
        (P @ z.reshape(-1)).reshape(n, n), atol=1e-12,
    )
    stc = gg.galerkin_stencil(st, n, cn, gridop)
    np.testing.assert_allclose(
        stencil_to_dense(stc, cn), (R @ A @ P).toarray(), atol=1e-12
    )


def test_galerkin_recursion_matches_spgemm_chain():
    """Three coarsening steps: the probed stencils equal the R A P chain."""
    n = 33
    A = poisson_sp(n)
    st = gg.poisson_stencil(n, jnp.float64)
    for _ in range(3):
        cn = n // 2
        R = R_mat(n, "linear")
        Ac = (R @ A @ R.T).tocsr()
        st = gg.galerkin_stencil(st, n, cn, "linear")
        np.testing.assert_allclose(
            stencil_to_dense(st, cn), Ac.toarray(), atol=1e-12
        )
        A, n = Ac, cn


def test_omega_matches_host_power_iteration():
    """The jitted fori_loop rho equals the examples/gmg.py host loop
    (same seed, same iteration count, same Rayleigh quotient)."""
    n = 16
    A = poisson_sp(n)
    D_inv = 1.0 / A.diagonal()
    rng = np.random.default_rng(0)
    x1 = rng.random(n * n)
    for _ in range(15):
        x1 = D_inv * (A @ x1)
        x1 = x1 / np.linalg.norm(x1)
    rho_host = float(np.dot(x1, D_inv * (A @ x1)))

    st = gg.poisson_stencil(n, jnp.float64)
    rho_grid = gg._rho(st, 1.0 / st[(0, 0)], n, seed=0, iters=15)
    np.testing.assert_allclose(rho_grid, rho_host, rtol=1e-10)


def test_vcycle_equals_matrix_form():
    """One V-cycle output == the same recursion done with explicit
    scipy matrices and the same smoother weights."""
    n, levels, gridop = 13, 3, "linear"
    hier = gg.build_hierarchy(n, levels, gridop, dtype=jnp.float64)

    mats = []
    A = poisson_sp(n)
    fn = n
    for lvl in range(levels):
        w = np.asarray(hier[lvl][1]).reshape(-1)  # omega * D^-1, flat
        mats.append((A, w, fn))
        if lvl < levels - 1:
            R = R_mat(fn, gridop)
            A = (R @ A @ R.T).tocsr()
            fn = fn // 2

    def cycle_ref(r, lvl):
        A, w, fn = mats[lvl]
        if lvl == levels - 1:
            return w * r
        x = w * r
        fine_r = r - A @ x
        R = R_mat(fn, gridop)
        coarse_x = cycle_ref(R @ fine_r, lvl + 1)
        x = x + R.T @ coarse_x
        return x + w * (r - A @ x)

    r = np.random.default_rng(3).random(n * n)
    got = np.asarray(jax.jit(gg.make_vcycle(hier, gridop))(jnp.asarray(r)))
    np.testing.assert_allclose(got, cycle_ref(r, 0), atol=1e-10)


def test_pcg_with_grid_vcycle_converges():
    """linalg.cg + the grid V-cycle preconditioner solves the Poisson
    problem in far fewer iterations than plain CG (the GMG benchmark
    composition, examples/gmg.py:main)."""
    from sparse_tpu import linalg

    n = 64
    hier = gg.build_hierarchy(n, 4, "linear", dtype=jnp.float64)
    vc = gg.make_vcycle(hier, "linear")
    st = hier[0][0]

    A_op = linalg.LinearOperator(
        (n * n, n * n), dtype=np.float64,
        matvec=lambda v: gg.stencil_apply(st, v.reshape(n, n)).reshape(-1),
    )
    M = linalg.LinearOperator((n * n, n * n), dtype=np.float64, matvec=vc)
    b = np.random.default_rng(0).random(n * n)
    x, iters = linalg.cg(A_op, b, tol=1e-8, maxiter=300, M=M)
    A = poisson_sp(n)
    assert np.linalg.norm(A @ np.asarray(x) - b) < 1e-6
    _, iters_plain = linalg.cg(A_op, b, tol=1e-8, maxiter=2000)
    assert iters < iters_plain / 3, (iters, iters_plain)


def test_sharded_grid_hierarchy_matches_single_device():
    """GSPMD-distributed form (VERDICT: distributed is first-class): the
    SAME vcycle/cg code over a row-sharded hierarchy must produce the
    single-device iterates — XLA inserts the stencil halo collectives
    from the sharding annotations alone."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sparse_tpu import linalg
    from sparse_tpu.parallel.mesh import get_mesh

    n = 64
    mesh = get_mesh(8)
    hier = gg.build_hierarchy(n, 3, "linear", dtype=jnp.float64)
    vc = gg.make_vcycle(hier, "linear")
    r = np.random.default_rng(7).random(n * n)
    want = np.asarray(jax.jit(vc)(jnp.asarray(r)))

    hs, vec_sharding = gg.shard_hierarchy_grid(hier, mesh, replicate_below=1024)
    vc_s = jax.jit(gg.make_vcycle(hs, "linear"))
    rs = jax.device_put(jnp.asarray(r), vec_sharding)
    assert vec_sharding.spec == P("shards"), vec_sharding
    got = vc_s(rs)
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-11)
    # the compiled program must be genuinely distributed: some
    # collective moves the stencil halos / transfer rows
    txt = vc_s.lower(rs).compile().as_text()
    assert ("collective-permute" in txt) or ("all-gather" in txt), (
        "no collective in the sharded V-cycle program"
    )

    # end-to-end: the full PCG over the sharded hierarchy converges to
    # the same answer as the single-device run
    st_s = hs[0][0]
    mv = jax.jit(
        lambda v: gg.stencil_apply(st_s, v.reshape(n, n)).reshape(-1)
    )
    A_op = linalg.LinearOperator((n * n, n * n), dtype=np.float64, matvec=mv)
    M = linalg.LinearOperator(
        (n * n, n * n), dtype=np.float64, matvec=gg.make_vcycle(hs, "linear")
    )
    b = np.random.default_rng(8).random(n * n)
    bs = jax.device_put(jnp.asarray(b), vec_sharding)
    x, iters = linalg.cg(A_op, bs, tol=1e-9, maxiter=200, M=M)
    A = poisson_sp(n)
    assert np.linalg.norm(A @ np.asarray(x) - b) < 1e-6
    assert iters < 60


def test_sharded_grid_hierarchy_odd_sizes_replicate():
    """Non-divisible levels must REPLICATE, not crash: n=33 hierarchy on
    8 devices (33 % 8 != 0 at every level) runs end to end."""
    from jax.sharding import PartitionSpec as P

    from sparse_tpu.parallel.mesh import get_mesh

    mesh = get_mesh(8)
    hier = gg.build_hierarchy(33, 3, "linear", dtype=jnp.float64)
    hs, vec_sharding = gg.shard_hierarchy_grid(hier, mesh)
    assert vec_sharding.spec == P(), "unshardable level 0 must replicate"
    r = np.random.default_rng(9).random(33 * 33)
    rs = jax.device_put(jnp.asarray(r), vec_sharding)
    got = jax.jit(gg.make_vcycle(hs, "linear"))(rs)
    want = jax.jit(gg.make_vcycle(hier, "linear"))(jnp.asarray(r))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-11)
