"""Regression tests for review findings (solver edge cases, layout caps)."""

import numpy as np
import pytest
import scipy.sparse as sp

import sparse_tpu
from sparse_tpu import linalg

from .utils.sample import sample_csr


def spd(n, seed=0):
    a = sample_csr(n, n, density=0.3, seed=seed)
    s = (a + a.T).toarray() + n * np.eye(n)
    return s


def test_lsqr_damp_identity():
    # min ||x - b||^2 + ||x||^2 has solution b/2
    A = sparse_tpu.identity(5)
    b = np.arange(1.0, 6.0)
    x, *_ = linalg.lsqr(A, b, damp=1.0)
    np.testing.assert_allclose(np.asarray(x), b / 2, rtol=1e-6)


def test_lsqr_damp_matches_scipy():
    s = sample_csr(20, 12, density=0.4, seed=5)
    b = np.random.default_rng(0).standard_normal(20)
    x_ref = sp.linalg.lsqr(s, b, damp=0.7, atol=1e-12, btol=1e-12)[0]
    x, *_ = linalg.lsqr(sparse_tpu.csr_array(s), b, damp=0.7, atol=1e-12, btol=1e-12)
    np.testing.assert_allclose(np.asarray(x), x_ref, rtol=1e-5, atol=1e-8)


@pytest.mark.parametrize("solver", [linalg.cg, linalg.bicg, linalg.bicgstab, linalg.cgs])
def test_zero_rhs_returns_zeros(solver):
    A = sparse_tpu.csr_array(spd(8))
    x, _ = solver(A, np.zeros(8), maxiter=100)
    assert np.all(np.isfinite(np.asarray(x)))
    np.testing.assert_allclose(np.asarray(x), 0.0)


def test_gmres_zero_rhs():
    A = sparse_tpu.csr_array(spd(8))
    x, iters = linalg.gmres(A, np.zeros(8))
    np.testing.assert_allclose(np.asarray(x), 0.0)
    assert np.all(np.isfinite(np.asarray(x)))


def test_gmres_complex():
    rng = np.random.default_rng(3)
    n = 12
    d = rng.standard_normal((n, n)) + 1j * rng.standard_normal((n, n))
    d = d + n * np.eye(n)  # well conditioned
    d[np.abs(d) < 0.8] = 0
    d += n * np.eye(n)
    A = sparse_tpu.csr_array(d)
    xtrue = rng.standard_normal(n) + 1j * rng.standard_normal(n)
    b = d @ xtrue
    x, _ = linalg.gmres(A, b, tol=1e-10, restart=n, maxiter=50)
    np.testing.assert_allclose(np.asarray(x), xtrue, rtol=1e-6, atol=1e-8)


def test_linear_operator_transpose_of_sparse():
    s = sample_csr(9, 7, density=0.4, seed=2)
    op = linalg.aslinearoperator(sparse_tpu.csr_array(s))
    x = np.random.default_rng(1).standard_normal(9)
    np.testing.assert_allclose(np.asarray(op.T.matvec(x)), s.T @ x, rtol=1e-12)


def test_linear_operator_transpose_complex():
    s = sample_csr(6, 5, density=0.5, seed=2, dtype=np.complex128)
    op = linalg.aslinearoperator(sparse_tpu.csr_array(s))
    x = np.random.default_rng(1).standard_normal(6)
    np.testing.assert_allclose(
        np.asarray(op.T.matvec(x)), s.T.toarray() @ x, rtol=1e-12
    )
    np.testing.assert_allclose(
        np.asarray(op.H.matvec(x)), s.conj().T.toarray() @ x, rtol=1e-12
    )


def test_wide_ell_spmv_fori_path():
    # force the ELL path on a matrix wider than ELL_UNROLL_MAX
    from sparse_tpu.config import settings
    from sparse_tpu.ops.spmv import ELL_UNROLL_MAX

    n = ELL_UNROLL_MAX + 17
    d = np.random.default_rng(0).standard_normal((8, n))
    A = sparse_tpu.csr_array(d)
    old = settings.spmv_mode
    settings.spmv_mode = "ell"
    try:
        x = np.random.default_rng(1).standard_normal(n)
        np.testing.assert_allclose(np.asarray(A @ x), d @ x, rtol=1e-10)
        B = np.random.default_rng(2).standard_normal((n, 4))
        np.testing.assert_allclose(np.asarray(A @ B), d @ B, rtol=1e-10)
    finally:
        settings.spmv_mode = old


def test_random_large_path_covers_high_rows():
    A = sparse_tpu.random(10000, 10000, density=1e-5, random_state=0)
    assert A.nnz == 1000
    # the fixed sampler must reach the top of the index space
    assert np.asarray(A.row).max() > 5000


def test_wide_dim_requires_x64_message():
    # fused m*n keys are gone everywhere (pair sorts); only a single
    # DIMENSION beyond int32 still needs x64 (kron of huge factors)
    import jax

    from sparse_tpu.ops.coords import require_x64_index

    assert not require_x64_index(60000)
    if jax.config.jax_enable_x64:
        assert require_x64_index(2**31 + 1)
    else:
        with pytest.raises(ValueError, match="x64"):
            require_x64_index(2**31 + 1)


# ---------------------------------------------------------------------------
# Big-shape (m*n > 2**31) paths must work WITHOUT x64: every single-device
# sort/dedup works on (row, col) pairs (ops.coords.lexsort_rc), so only a
# single dimension overflowing int32 ever requires int64 indices. This is
# what lets examples/gmg.py build 4500^2-grid hierarchies in pure int32.
# ---------------------------------------------------------------------------

BIG = 60_000  # BIG*BIG = 3.6e9 > 2**31


def _big_coo(seed=0, nnz=200):
    rng = np.random.default_rng(seed)
    rows = rng.integers(0, BIG, nnz)
    cols = rng.integers(0, BIG, nnz)
    vals = rng.random(nnz)
    return rows, cols, vals


def test_big_shape_coo_tocsr_matches_scipy():
    rows, cols, vals = _big_coo()
    ours = sparse_tpu.coo_array((vals, (rows, cols)), shape=(BIG, BIG)).tocsr()
    ref = sp.coo_matrix((vals, (rows, cols)), shape=(BIG, BIG)).tocsr()
    got = ours.tocoo()
    want = ref.tocoo()
    want.sum_duplicates()
    np.testing.assert_array_equal(np.asarray(got.row), want.row)
    np.testing.assert_array_equal(np.asarray(got.col), want.col)
    np.testing.assert_allclose(np.asarray(got.data), want.data, rtol=1e-12)


def test_big_shape_transpose_roundtrip():
    rows, cols, vals = _big_coo(seed=1)
    A = sparse_tpu.coo_array((vals, (rows, cols)), shape=(BIG, BIG)).tocsr()
    At = A.T.tocsr()  # CSR -> (zero-copy CSC) -> sort-based CSR
    ref = sp.coo_matrix((vals, (rows, cols)), shape=(BIG, BIG)).tocsr().T.tocsr()
    got = At.tocoo()
    want = ref.tocoo()
    want.sum_duplicates()
    np.testing.assert_array_equal(np.asarray(got.row), want.row)
    np.testing.assert_array_equal(np.asarray(got.col), want.col)
    np.testing.assert_allclose(np.asarray(got.data), want.data, rtol=1e-12)


def test_big_shape_add_and_mult_match_scipy():
    ra, ca, va = _big_coo(seed=2)
    rb, cb, vb = _big_coo(seed=3)
    # force some structural overlap so mult has nonempty intersection
    rb[:50], cb[:50] = ra[:50], ca[:50]
    A = sparse_tpu.coo_array((va, (ra, ca)), shape=(BIG, BIG)).tocsr()
    B = sparse_tpu.coo_array((vb, (rb, cb)), shape=(BIG, BIG)).tocsr()
    As = sp.coo_matrix((va, (ra, ca)), shape=(BIG, BIG)).tocsr()
    Bs = sp.coo_matrix((vb, (rb, cb)), shape=(BIG, BIG)).tocsr()
    for got, want in (((A + B), (As + Bs)), ((A * B), (As.multiply(Bs)))):
        g = got.tocoo()
        w = sp.coo_matrix(want)
        w.sum_duplicates()
        np.testing.assert_array_equal(np.asarray(g.row), w.row)
        np.testing.assert_array_equal(np.asarray(g.col), w.col)
        np.testing.assert_allclose(np.asarray(g.data), w.data, rtol=1e-12)


def test_big_shape_spgemm_matches_scipy():
    ra, ca, va = _big_coo(seed=4)
    rb, cb, vb = _big_coo(seed=5)
    rb[:100] = ca[:100]  # make A's columns hit B's rows
    A = sparse_tpu.coo_array((va, (ra, ca)), shape=(BIG, BIG)).tocsr()
    B = sparse_tpu.coo_array((vb, (rb, cb)), shape=(BIG, BIG)).tocsr()
    C = (A @ B).tocoo()
    Cs = (
        sp.coo_matrix((va, (ra, ca)), shape=(BIG, BIG)).tocsr()
        @ sp.coo_matrix((vb, (rb, cb)), shape=(BIG, BIG)).tocsr()
    ).tocoo()
    Cs.sum_duplicates()
    np.testing.assert_array_equal(np.asarray(C.row), Cs.row)
    np.testing.assert_array_equal(np.asarray(C.col), Cs.col)
    np.testing.assert_allclose(np.asarray(C.data), Cs.data, rtol=1e-10)


def test_big_shape_diags_spmv():
    # diags at a >2**31-key shape, then SpMV — the gmg.py WeightedJacobi path
    d = np.arange(BIG, dtype=np.float64) + 1.0
    D = sparse_tpu.diags([d], [0], shape=(BIG, BIG), format="csr")
    x = np.ones(BIG)
    y = np.asarray(D @ x)
    np.testing.assert_allclose(y, d, rtol=1e-12)


def test_big_shape_kron_small_factors():
    # kron whose OUTPUT shape crosses 2**31 keys but whose dims fit int32
    A = sp.random(300, 300, density=0.001, random_state=6, format="coo")
    B = sp.random(200, 200, density=0.001, random_state=7, format="coo")
    got = sparse_tpu.kron(
        sparse_tpu.coo_array((A.data, (A.row, A.col)), shape=A.shape),
        sparse_tpu.coo_array((B.data, (B.row, B.col)), shape=B.shape),
        format="csr",
    ).tocoo()
    want = sp.kron(A, B, format="csr").tocoo()
    want.sum_duplicates()
    np.testing.assert_array_equal(np.asarray(got.row), want.row)
    np.testing.assert_array_equal(np.asarray(got.col), want.col)
    np.testing.assert_allclose(np.asarray(got.data), want.data, rtol=1e-12)


def test_segment_searchsorted_pow2_segments():
    # regression: the binary-search trip count was one short for power-of-
    # two data lengths, returning lo below the true lower bound (dropped
    # intersection entries in A.multiply(B) with 2^k-nnz operands)
    import jax.numpy as jnp

    from sparse_tpu.ops.coords import segment_searchsorted

    rng = np.random.default_rng(0)
    for nb in [1, 2, 4, 8, 16, 32, 3, 7, 33]:
        vals = np.sort(rng.integers(0, 50, nb))
        starts = rng.integers(0, nb + 1, 64)
        ends = np.array([rng.integers(s, nb + 1) for s in starts])
        qs = rng.integers(-1, 51, 64)
        want = np.array(
            [s + np.searchsorted(vals[s:e], q) for s, e, q in zip(starts, ends, qs)]
        )
        got = np.asarray(
            segment_searchsorted(
                jnp.asarray(vals), jnp.asarray(starts), jnp.asarray(ends), jnp.asarray(qs)
            )
        )
        np.testing.assert_array_equal(got, want)


def test_mult_two_nnz_single_row():
    # the exact power-of-two scenario from the off-by-one: 1x2 operands
    A = sparse_tpu.coo_array(
        (np.array([1.0, 2.0]), (np.array([0, 0]), np.array([0, 1]))), shape=(1, 2)
    ).tocsr()
    B = sparse_tpu.coo_array(
        (np.array([3.0, 4.0]), (np.array([0, 0]), np.array([0, 1]))), shape=(1, 2)
    ).tocsr()
    got = np.asarray((A * B).todense())
    np.testing.assert_allclose(got, np.array([[3.0, 8.0]]))


def test_big_shape_paths_without_x64_subprocess():
    """The no-x64 contract the suite itself cannot test (conftest enables
    x64 globally): big-shape conversion + distributed conversion must work
    with jax_enable_x64 = False — int32 pair sorts end to end."""
    import os
    import subprocess
    import sys

    script = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
import jax
jax.config.update("jax_platforms", "cpu")
assert not jax.config.jax_enable_x64
import numpy as np, scipy.sparse as sp
import sparse_tpu
from sparse_tpu.parallel.sort import coo_to_csr_distributed

BIG = 60_000
rng = np.random.default_rng(0)
nnz = 200
rows = rng.integers(0, BIG, nnz)
cols = rng.integers(0, BIG, nnz)
rows[:30] = rows[30:60]; cols[:30] = cols[30:60]  # duplicates
vals = rng.integers(1, 100, nnz).astype(np.float32)  # f32-exact values

want = sp.coo_matrix((vals, (rows, cols)), shape=(BIG, BIG)).tocsr()
want.sum_duplicates()
w = want.tocoo()

for A in (
    sparse_tpu.coo_array((vals, (rows, cols)), shape=(BIG, BIG)).tocsr(),
    coo_to_csr_distributed(rows, cols, vals, (BIG, BIG), 8),
):
    got = A.tocoo()
    np.testing.assert_array_equal(np.asarray(got.row), w.row)
    np.testing.assert_array_equal(np.asarray(got.col), w.col)
    np.testing.assert_allclose(np.asarray(got.data), w.data)
print("NO_X64_OK")
"""
    env = dict(os.environ)
    proc = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=420,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "NO_X64_OK" in proc.stdout


def test_layout_detection_inside_trace_falls_back_not_raises():
    """A csr first applied INSIDE a jit trace (multigrid transfer
    operators) must not host-sync in _maybe_dia/_maybe_ell — the
    resulting TracerArrayConversionError silently demoted CG to its
    host loop (tunnel-fatal). The guard skips detection without
    poisoning the cache, so a later eager call still detects."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import scipy.sparse as sp

    import sparse_tpu as sparse

    S = sp.random(64, 64, 0.1, random_state=np.random.default_rng(0), format="csr")
    S.setdiag(3.0)
    A = sparse.csr_array(S)
    x = jnp.ones(64, dtype=jnp.float32)
    y = jax.jit(lambda v: A @ v)(x)  # must trace cleanly, no fallback
    np.testing.assert_allclose(np.asarray(y), S @ np.ones(64), rtol=1e-5)
    assert A._dia is False or A._dia is None  # cache not poisoned by the trace
    A @ np.ones(64)  # eager use afterwards still allowed to detect+cache


def test_cg_with_traceable_preconditioner_stays_on_device_loop(monkeypatch):
    """Preconditioned CG whose M is first seen inside the loop must run
    the compiled device loop (the eager warm call primes layout
    caches), not the host fallback."""
    import numpy as np
    import scipy.sparse as sp

    import sparse_tpu as sparse
    from sparse_tpu import linalg

    rng = np.random.default_rng(1)
    n = 128
    S = sp.diags([np.full(n - 1, -1.0), np.full(n, 2.0), np.full(n - 1, -1.0)],
                 [-1, 0, 1]).tocsr()
    A = sparse.csr_array(S)
    Mmat = sparse.csr_array(sp.diags([1.0 / S.diagonal()], [0]).tocsr())
    M = linalg.LinearOperator((n, n), matvec=lambda r: Mmat @ r, dtype=np.float64)
    b = rng.standard_normal(n)
    called = {"host": 0}
    orig = linalg._cg_host_loop
    monkeypatch.setattr(
        linalg, "_cg_host_loop",
        lambda *a, **k: called.__setitem__("host", called["host"] + 1) or orig(*a, **k),
    )
    x, iters = linalg.cg(A, b, tol=1e-6, maxiter=200, M=M)
    assert called["host"] == 0, "preconditioned CG fell back to the host loop"
    resid = np.linalg.norm(np.asarray(A @ x) - b)
    assert resid < 1e-4


def test_host_scope_and_commit_helpers():
    """host_scope keeps eager analysis on the CPU backend; on a CPU
    target commit_to_exec_device is an identity (no copies)."""
    import jax
    import jax.numpy as jnp

    from sparse_tpu.utils import commit_to_exec_device, host_scope, in_trace

    with host_scope():
        a = jnp.arange(8) * 2
    assert next(iter(a.sharding.device_set)).platform == "cpu"
    arrs = (jnp.arange(4), jnp.ones(3))
    out = commit_to_exec_device(arrs)
    assert out[0] is arrs[0] and out[1] is arrs[1]  # cpu target: no-op
    assert not in_trace()
    flags = []
    jax.jit(lambda x: (flags.append(in_trace()), x)[1])(1.0)
    assert flags == [True]
