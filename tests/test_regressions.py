"""Regression tests for review findings (solver edge cases, layout caps)."""

import numpy as np
import pytest
import scipy.sparse as sp

import sparse_tpu
from sparse_tpu import linalg

from .utils.sample import sample_csr


def spd(n, seed=0):
    a = sample_csr(n, n, density=0.3, seed=seed)
    s = (a + a.T).toarray() + n * np.eye(n)
    return s


def test_lsqr_damp_identity():
    # min ||x - b||^2 + ||x||^2 has solution b/2
    A = sparse_tpu.identity(5)
    b = np.arange(1.0, 6.0)
    x, *_ = linalg.lsqr(A, b, damp=1.0)
    np.testing.assert_allclose(np.asarray(x), b / 2, rtol=1e-6)


def test_lsqr_damp_matches_scipy():
    s = sample_csr(20, 12, density=0.4, seed=5)
    b = np.random.default_rng(0).standard_normal(20)
    x_ref = sp.linalg.lsqr(s, b, damp=0.7, atol=1e-12, btol=1e-12)[0]
    x, *_ = linalg.lsqr(sparse_tpu.csr_array(s), b, damp=0.7, atol=1e-12, btol=1e-12)
    np.testing.assert_allclose(np.asarray(x), x_ref, rtol=1e-5, atol=1e-8)


@pytest.mark.parametrize("solver", [linalg.cg, linalg.bicg, linalg.bicgstab, linalg.cgs])
def test_zero_rhs_returns_zeros(solver):
    A = sparse_tpu.csr_array(spd(8))
    x, _ = solver(A, np.zeros(8), maxiter=100)
    assert np.all(np.isfinite(np.asarray(x)))
    np.testing.assert_allclose(np.asarray(x), 0.0)


def test_gmres_zero_rhs():
    A = sparse_tpu.csr_array(spd(8))
    x, iters = linalg.gmres(A, np.zeros(8))
    np.testing.assert_allclose(np.asarray(x), 0.0)
    assert np.all(np.isfinite(np.asarray(x)))


def test_gmres_complex():
    rng = np.random.default_rng(3)
    n = 12
    d = rng.standard_normal((n, n)) + 1j * rng.standard_normal((n, n))
    d = d + n * np.eye(n)  # well conditioned
    d[np.abs(d) < 0.8] = 0
    d += n * np.eye(n)
    A = sparse_tpu.csr_array(d)
    xtrue = rng.standard_normal(n) + 1j * rng.standard_normal(n)
    b = d @ xtrue
    x, _ = linalg.gmres(A, b, tol=1e-10, restart=n, maxiter=50)
    np.testing.assert_allclose(np.asarray(x), xtrue, rtol=1e-6, atol=1e-8)


def test_linear_operator_transpose_of_sparse():
    s = sample_csr(9, 7, density=0.4, seed=2)
    op = linalg.aslinearoperator(sparse_tpu.csr_array(s))
    x = np.random.default_rng(1).standard_normal(9)
    np.testing.assert_allclose(np.asarray(op.T.matvec(x)), s.T @ x, rtol=1e-12)


def test_linear_operator_transpose_complex():
    s = sample_csr(6, 5, density=0.5, seed=2, dtype=np.complex128)
    op = linalg.aslinearoperator(sparse_tpu.csr_array(s))
    x = np.random.default_rng(1).standard_normal(6)
    np.testing.assert_allclose(
        np.asarray(op.T.matvec(x)), s.T.toarray() @ x, rtol=1e-12
    )
    np.testing.assert_allclose(
        np.asarray(op.H.matvec(x)), s.conj().T.toarray() @ x, rtol=1e-12
    )


def test_wide_ell_spmv_fori_path():
    # force the ELL path on a matrix wider than ELL_UNROLL_MAX
    from sparse_tpu.config import settings
    from sparse_tpu.ops.spmv import ELL_UNROLL_MAX

    n = ELL_UNROLL_MAX + 17
    d = np.random.default_rng(0).standard_normal((8, n))
    A = sparse_tpu.csr_array(d)
    old = settings.spmv_mode
    settings.spmv_mode = "ell"
    try:
        x = np.random.default_rng(1).standard_normal(n)
        np.testing.assert_allclose(np.asarray(A @ x), d @ x, rtol=1e-10)
        B = np.random.default_rng(2).standard_normal((n, 4))
        np.testing.assert_allclose(np.asarray(A @ B), d @ B, rtol=1e-10)
    finally:
        settings.spmv_mode = old


def test_random_large_path_covers_high_rows():
    A = sparse_tpu.random(10000, 10000, density=1e-5, random_state=0)
    assert A.nnz == 1000
    # the fixed sampler must reach the top of the index space
    assert np.asarray(A.row).max() > 5000


def test_wide_shape_requires_x64_message():
    import jax

    from sparse_tpu.ops.coords import require_x64_keys

    if jax.config.jax_enable_x64:
        assert require_x64_keys((60000, 60000))
    else:
        with pytest.raises(ValueError, match="x64"):
            require_x64_keys((60000, 60000))
