"""lobpcg and eigs oracle tests (scipy.sparse.linalg drop-in surface
beyond the reference's symmetric-only eigsh)."""

import numpy as np
import pytest
import scipy.sparse as sp
import scipy.sparse.linalg as sla

import sparse_tpu as sparse
import sparse_tpu.linalg as linalg


def _spd(n, seed=0):
    rng = np.random.default_rng(seed)
    S = sp.random(n, n, 0.05, random_state=rng)
    return ((S + S.T) * 0.5 + sp.diags(np.linspace(1, 10, n))).tocsr()


def test_lobpcg_largest_matches_eigsh():
    n, m = 200, 4
    S = _spd(n)
    A = sparse.csr_array(S)
    rng = np.random.default_rng(1)
    X = rng.standard_normal((n, m))
    lam, V = linalg.lobpcg(A, X, tol=1e-6, maxiter=120)
    w_ref = np.sort(sla.eigsh(S, k=m, which="LA")[0])[::-1]
    np.testing.assert_allclose(np.sort(lam)[::-1], w_ref, rtol=1e-4)
    # eigen-residuals
    R = S @ V - V * lam[None, :]
    assert np.linalg.norm(R, axis=0).max() <= 1e-3 * np.abs(lam).max()


def test_lobpcg_smallest():
    n, m = 150, 3
    S = _spd(n, seed=2)
    A = sparse.csr_array(S)
    rng = np.random.default_rng(3)
    lam, V = linalg.lobpcg(A, rng.standard_normal((n, m)), largest=False,
                           tol=1e-6, maxiter=200)
    w_ref = np.sort(sla.eigsh(S, k=m, which="SA")[0])
    np.testing.assert_allclose(np.sort(lam), w_ref, rtol=1e-3)


def test_lobpcg_rejects_generalized_and_fat_blocks():
    A = sparse.csr_array(_spd(50))
    X = np.ones((50, 2))
    with pytest.raises(NotImplementedError):
        linalg.lobpcg(A, X, B=A)
    with pytest.raises(ValueError):
        linalg.lobpcg(A, np.ones((50, 20)))


def _nonsym(n, seed=4):
    rng = np.random.default_rng(seed)
    return (sp.random(n, n, 0.08, random_state=rng)
            + sp.diags(np.linspace(1, 5, n))).tocsr()


def test_eigs_largest_magnitude():
    n, k = 160, 4
    S = _nonsym(n)
    A = sparse.csr_array(S)
    vals, vecs = linalg.eigs(A, k=k, which="LM")
    ref = sla.eigs(S.astype(np.complex128), k=k, which="LM")[0]
    np.testing.assert_allclose(
        np.sort(np.abs(vals)), np.sort(np.abs(ref)), rtol=1e-3
    )
    # residuals ||A v - lambda v||
    for i in range(k):
        v = vecs[:, i]
        r = S @ v - vals[i] * v
        assert np.linalg.norm(r) <= 1e-2 * max(1.0, abs(vals[i]))


def test_eigs_values_only_and_which_lr():
    n, k = 120, 3
    S = _nonsym(n, seed=5)
    A = sparse.csr_array(S)
    vals = linalg.eigs(A, k=k, which="LR", return_eigenvectors=False)
    ref = sla.eigs(S.astype(np.complex128), k=k, which="LR",
                   return_eigenvectors=False)
    np.testing.assert_allclose(
        np.sort(vals.real), np.sort(ref.real), rtol=1e-3
    )


def test_eigs_large_magnitude_spectrum():
    """Ritz selection must not rely on exact value matching between two
    LAPACK code paths (r3 review: set-membership of round(.,12) failed
    at |lambda| ~ 1e6)."""
    n, k = 100, 3
    S = (_nonsym(n, seed=6) * 1e6).tocsr()
    A = sparse.csr_array(S)
    vals = linalg.eigs(A, k=k, which="LM", return_eigenvectors=False)
    ref = sla.eigs(S.astype(np.complex128), k=k, which="LM",
                   return_eigenvectors=False)
    np.testing.assert_allclose(
        np.sort(np.abs(vals)), np.sort(np.abs(ref)), rtol=1e-3
    )
