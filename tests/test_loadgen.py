"""sparse_tpu.loadgen — deterministic traffic generation + load reports
(ISSUE 11).

Pins the contract pillars: (a) seeded determinism — the same spec +
seed produces the identical arrival schedule (virtual clock, no
wall-clock randomness in-library) and the deterministic report fields
match run to run; (b) the spec grammar fails loudly on typos; (c) the
runner drives a real ``SolveSession`` through its actual ticket path
(tenant labels included) and the report's accounting adds up; (d) the
weighted fairness index behaves (equal shares = 1, starvation < 1,
weights normalize); (e) the tenant satellite changes NOTHING on the
dispatch path — program keys and jaxprs are identical with and without
a tenant label, and the default metric series names are unchanged.
"""

import json

import jax
import numpy as np
import pytest
import scipy.sparse as sp

from sparse_tpu import loadgen, telemetry
from sparse_tpu.batch import SolveSession
from sparse_tpu.config import settings
from sparse_tpu.loadgen import ArrivalTrace, LoadSpecError


@pytest.fixture
def tel(tmp_path, monkeypatch):
    telemetry.reset()
    monkeypatch.setattr(settings, "telemetry", True)
    telemetry.configure(str(tmp_path / "records.jsonl"))
    yield tmp_path / "records.jsonl"
    telemetry.configure(None)
    telemetry.reset()


def _tridiag(n, seed=0):
    rng = np.random.default_rng(seed)
    e = np.ones(n)
    A = sp.diags([-e[:-1], 3.0 * e, -e[:-1]], [-1, 0, 1], format="csr")
    A = A.copy()
    A.setdiag(3.0 + rng.random(n))
    A.sort_indices()
    return A


def _systems(B=4, n=48):
    rng = np.random.default_rng(7)
    mats = [_tridiag(n, seed=s) for s in range(B)]
    rhs = rng.standard_normal((B, n))
    return list(zip(mats, rhs))


# -- (a) seeded determinism ---------------------------------------------------


def test_poisson_trace_deterministic():
    a = ArrivalTrace.poisson(rate=200.0, duration=1.0, seed=42)
    b = ArrivalTrace.poisson(rate=200.0, duration=1.0, seed=42)
    assert np.array_equal(a.arrival_times(), b.arrival_times())
    assert len(a.arrivals) > 100  # ~200 expected
    assert all(0 < t.t < 1.0 for t in a.arrivals)
    c = ArrivalTrace.poisson(rate=200.0, duration=1.0, seed=43)
    assert not np.array_equal(a.arrival_times(), c.arrival_times())


def test_bursty_trace_deterministic_and_denser_in_bursts():
    kw = dict(rate=20.0, burst_rate=800.0, period=0.5, duty=0.2,
              duration=2.0, seed=5)
    a, b = ArrivalTrace.bursty(**kw), ArrivalTrace.bursty(**kw)
    assert np.array_equal(a.arrival_times(), b.arrival_times())
    # burst windows are the first 20% of each 0.5s period
    ts = a.arrival_times()
    in_burst = sum(1 for t in ts if (t % 0.5) < 0.1)
    assert in_burst > len(ts) * 0.7  # bursts dominate at 40x the rate


def test_uniform_trace_is_evenly_spaced():
    t = ArrivalTrace.uniform(rate=10.0, duration=1.0)
    gaps = np.diff(t.arrival_times())
    assert np.allclose(gaps, 0.1)
    assert len(t.arrivals) == 9  # k/10 for k=1..9 strictly inside [0,1)


def test_merge_is_sorted_and_keeps_tenants_weights():
    a = ArrivalTrace.poisson(rate=50.0, duration=0.5, seed=1, tenant="a")
    b = ArrivalTrace.uniform(rate=40.0, duration=0.5, tenant="b",
                             weight=2.0)
    m = a + b
    ts = m.arrival_times()
    assert np.all(np.diff(ts) >= 0)
    assert m.tenants() == ["a", "b"]
    assert m.weights == {"a": 1.0, "b": 2.0}
    assert m.counts()["b"] == len(b.arrivals)
    assert m.duration == 0.5


# -- (b) the spec grammar -----------------------------------------------------


def test_parse_round_trips_through_describe():
    spec = ("poisson:rate=100,duration=0.5,seed=3,tenant=a;"
            "burst:rate=10,burst_rate=200,period=0.2,duty=0.25,"
            "duration=0.5,seed=4,tenant=b,weight=2;"
            "closed:concurrency=2,requests=6,tenant=c")
    t = ArrivalTrace.parse(spec)
    assert t.tenants() == ["a", "b", "c"]
    assert t.weights["b"] == 2.0
    assert t.closed[0].concurrency == 2 and t.closed[0].requests == 6
    t2 = ArrivalTrace.parse(t.describe())
    assert np.array_equal(t.arrival_times(), t2.arrival_times())
    assert [a.tenant for a in t.arrivals] == [a.tenant for a in t2.arrivals]
    assert t2.closed == t.closed


def test_parse_rejects_bad_specs():
    with pytest.raises(LoadSpecError):
        ArrivalTrace.parse("gaussian:rate=10,duration=1")  # unknown pattern
    with pytest.raises(LoadSpecError):
        ArrivalTrace.parse("poisson:rate=10,duration=1,bogus=3")
    with pytest.raises(LoadSpecError):
        ArrivalTrace.parse("poisson:rate=-5,duration=1")
    with pytest.raises(LoadSpecError):
        ArrivalTrace.parse("poisson:rate")  # not key=value
    with pytest.raises(LoadSpecError):
        ArrivalTrace.parse("")  # empty
    with pytest.raises(LoadSpecError):
        ArrivalTrace.bursty(rate=1, burst_rate=10, period=0.5, duty=1.5,
                            duration=1)


# -- (d) fairness index -------------------------------------------------------


def test_fairness_index_equal_and_starved():
    assert loadgen.fairness_index({"a": 10, "b": 10}) == pytest.approx(1.0)
    j = loadgen.fairness_index({"a": 10, "b": 0})
    assert j == pytest.approx(0.5)
    assert loadgen.fairness_index({}) == 1.0
    assert loadgen.fairness_index({"a": 0, "b": 0}) == 1.0


def test_build_report_fairness_respects_weights():
    """A tenant with weight 2 completing 2x the requests IS fair."""
    tr = (ArrivalTrace.uniform(rate=10, duration=1, tenant="a")
          + ArrivalTrace.uniform(rate=20, duration=1, tenant="b",
                                 weight=2.0))
    outcomes = (
        [("a", 0.01, True, False)] * 10 + [("b", 0.01, True, False)] * 20
    )
    rep = loadgen.build_report(tr, outcomes, wall_s=1.0)
    assert rep.fairness == pytest.approx(1.0)
    assert rep.tenants["b"]["weight"] == 2.0
    # the same completions under equal weights are unfair
    tr2 = (ArrivalTrace.uniform(rate=10, duration=1, tenant="a")
           + ArrivalTrace.uniform(rate=20, duration=1, tenant="b"))
    rep2 = loadgen.build_report(tr2, outcomes, wall_s=1.0)
    assert rep2.fairness < 0.95


def test_build_report_is_pure_and_deterministic():
    tr = ArrivalTrace.uniform(rate=10, duration=1, tenant="x")
    outcomes = [("x", 0.002 * (i + 1), True, False) for i in range(9)]
    r1 = loadgen.build_report(tr, outcomes, wall_s=0.5, slo_ms=10.0)
    r2 = loadgen.build_report(tr, outcomes, wall_s=0.5, slo_ms=10.0)
    assert r1.as_dict() == r2.as_dict()
    assert r1.arrivals == 9 and r1.completed == 9
    assert r1.offered_rps == pytest.approx(9.0)  # 9 arrivals / 1 virtual s
    assert r1.achieved_rps == pytest.approx(18.0)  # 9 / 0.5 wall s
    # latencies 2..18 ms; misses are the 12/14/16/18 ms tickets
    assert r1.slo_misses == 4
    assert r1.slo_miss_rate == pytest.approx(4 / 9)
    assert r1.latency_ms["max"] == pytest.approx(18.0)
    json.dumps(r1.as_dict())  # JSON-friendly by contract


# -- (c) the runner against a real session -----------------------------------


def test_run_load_open_loop_smoke():
    ses = SolveSession("cg", slo_ms=5000.0)
    trace = ArrivalTrace.poisson(rate=120.0, duration=0.25, seed=9)
    rep = loadgen.run_load(ses, trace, _systems(), tol=1e-8)
    assert rep.arrivals == len(trace.arrivals)
    assert rep.completed == rep.arrivals and rep.failed == 0
    assert rep.achieved_rps > 0
    assert rep.latency_ms["p95"] >= rep.latency_ms["p50"] > 0
    assert rep.dispatches >= 1
    assert rep.queue_depth, "queue-depth time series must be sampled"
    assert rep.slo_miss_rate == 0.0  # 5s SLO is unmissable here
    assert ses.pending == 0


def test_run_load_closed_loop_completes_budget():
    ses = SolveSession("cg")
    trace = ArrivalTrace.closed_loop(concurrency=3, requests=8,
                                     tenant="cl")
    rep = loadgen.run_load(ses, trace, _systems(), tol=1e-8)
    assert rep.arrivals == 8 and rep.completed == 8
    assert rep.tenants["cl"]["completed"] == 8
    # closed-loop offered == achieved by construction
    assert rep.offered_rps == pytest.approx(rep.achieved_rps)


def test_run_load_two_tenants_counts_and_fairness():
    ses = SolveSession("cg")
    trace = (
        ArrivalTrace.poisson(rate=80.0, duration=0.25, seed=1, tenant="a")
        + ArrivalTrace.poisson(rate=80.0, duration=0.25, seed=2,
                               tenant="b")
    )
    rep = loadgen.run_load(ses, trace, _systems(), tol=1e-8)
    want = trace.counts()
    assert rep.tenants["a"]["completed"] == want["a"]
    assert rep.tenants["b"]["completed"] == want["b"]
    assert rep.fairness > 0.8  # near-equal seeded rates


def test_run_load_emits_schema_valid_trace_event(tel):
    ses = SolveSession("cg", slo_ms=1000.0)
    trace = ArrivalTrace.uniform(rate=40.0, duration=0.2, tenant="t")
    rep = loadgen.run_load(ses, trace, _systems(), tol=1e-8)
    evs = telemetry.events("loadgen.trace")
    assert len(evs) == 1
    ev = evs[0]
    assert telemetry.schema.validate(ev) == []
    assert ev["trace"] == trace.describe()
    assert ev["arrivals"] == rep.arrivals
    assert ev["achieved_rps"] == rep.achieved_rps
    assert ev["fairness"] == rep.fairness
    assert ev["tenants"]["t"]["completed"] == rep.completed
    # the per-ticket terminal events carry the tenant label
    tks = telemetry.events("batch.ticket")
    assert tks and all(e.get("tenant") == "t" for e in tks)


def test_run_load_input_validation():
    ses = SolveSession("cg")
    trace = ArrivalTrace.uniform(rate=10, duration=0.1)
    with pytest.raises(ValueError):
        loadgen.run_load(ses, trace, [])
    with pytest.raises(ValueError):
        loadgen.run_load(ses, trace, _systems(), time_scale=0.0)


# -- (e) tenant satellite: zero dispatch-path change --------------------------


def test_tenant_label_never_touches_program_or_default_series():
    from sparse_tpu.telemetry import _metrics

    systems = _systems(B=2)
    ses = SolveSession("cg")
    t_plain = ses.submit(*systems[0], tol=1e-8)
    t_tagged = ses.submit(*systems[1], tol=1e-8, tenant="acme")
    ses.flush()
    assert t_plain.tenant is None and t_tagged.tenant == "acme"
    assert t_plain.result() is not None and t_tagged.result() is not None
    # default tickets keep the pre-existing {solver} series; tagged ones
    # get their own {solver, tenant} series — existing names unchanged
    fams = [m.labels for m in _metrics.family("batch.ticket_latency")]
    assert {"solver": "cg"} in fams
    assert {"solver": "cg", "tenant": "acme"} in fams

    # the tenant never reaches the compiled program: same key, same jaxpr
    pat = ses.pattern_of(systems[0][0])
    B, n = 2, pat.shape[0]
    args = (
        np.zeros((B, pat.nnz)), np.zeros((B, n)), np.zeros((B, n)),
        np.zeros(B), 50,
    )
    j = str(jax.make_jaxpr(ses._build_program(pat, B, np.dtype(np.float64)))(
        *args
    ))
    ses2 = SolveSession("cg")
    j2 = str(
        jax.make_jaxpr(ses2._build_program(pat, B, np.dtype(np.float64)))(
            *args
        )
    )
    assert j == j2
