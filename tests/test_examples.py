"""Example scripts as system tests (SURVEY §4: the reference's test runner
executes ``examples/`` alongside the integration suite).

Each example runs as a subprocess on the virtual CPU mesh with tiny sizes —
the exact command a user runs, not an import of its internals. The parent
conftest already scrubbed the TPU-tunnel trigger from the environment, so
these cannot block on a wedged tunnel.
"""

import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, *args, timeout=420, devices=8):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={devices}"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script), *args],
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, f"{script} rc={proc.returncode}\n{proc.stderr[-2000:]}"
    return proc.stdout


def test_pde_example():
    out = _run("pde.py", "-nx", "32", "-ny", "32", "-max_iter", "60")
    m = re.search(r"Iterations: (\d+)\s+residual: ([0-9.e+-]+)", out)
    assert m, out
    assert float(m.group(2)) < 1e-2


def test_gmg_example():
    # default dispatch = the structured-grid pipeline (models/gmg_grid.py)
    out = _run("gmg.py", "-n", "16", "-levels", "2", "-maxiter", "40")
    m = re.search(r"Iterations: (\d+)\s+residual: ([0-9.e+-]+)", out)
    assert m, out
    assert float(m.group(2)) < 1e-5


def test_gmg_example_generic_path():
    # --no-grid keeps the generic sparse-matrix hierarchy (GMG class,
    # SpGEMM Galerkin products) exercised end-to-end
    out = _run("gmg.py", "-n", "16", "-levels", "2", "-maxiter", "40", "--no-grid")
    m = re.search(r"Iterations: (\d+)\s+residual: ([0-9.e+-]+)", out)
    assert m, out
    assert float(m.group(2)) < 1e-5


def test_spectral_norm_example():
    out = _run("spectral_norm.py")
    # dense vs sparse estimates printed and equal to a few digits
    nums = re.findall(r"([0-9]+\.[0-9]+)", out)
    assert len(nums) >= 2, out
    assert abs(float(nums[0]) - float(nums[1])) < 1e-2 * max(float(nums[0]), 1.0)


def test_quantum_evolution_example():
    out = _run("quantum_evolution.py", "-nodes", "8", "-t", "0.2")
    m = re.search(r"norm drift: ([0-9.e+-]+)", out)
    assert m, out
    assert float(m.group(1)) < 1e-3


def test_dot_microbenchmark_example():
    out = _run("dot_microbenchmark.py", "-n", "200", "-i", "3")
    assert re.search(r"Iterations / sec: [0-9.]+", out), out


def test_spgemm_microbenchmark_example():
    out = _run("spgemm_microbenchmark.py", "-n", "200", "-i", "2")
    assert re.search(r"Iterations / sec: [0-9.]+", out), out


def test_weak_scaling_example():
    out = _run("weak_scaling.py", "-n", "24", "-shards", "1,2", "-iters", "4")
    m = re.search(r'\{"weak_scaling":', out)
    assert m, out


def test_pyamg_adapter_example():
    pytest.importorskip("pyamg")
    _run("pyamg_sparse_tpu_test.py")


def test_gmg_dist_example():
    """Distributed GMG, generic machinery (--no-grid): Galerkin products
    via mesh SpGEMM, DistCSR V-cycle CG on the 8-device mesh."""
    out = _run("gmg.py", "-n", "32", "-levels", "3", "-maxiter", "60", "-dist",
               "--no-grid")
    m = re.search(r"Iterations: (\d+)\s+residual: ([0-9.e+-]+)", out)
    assert m, out
    assert float(m.group(2)) < 1e-6


def test_heat_implicit_example():
    out = _run("heat_implicit.py", "-n", "12", "-t", "0.2", "-explicit",
               devices=1)
    m = re.search(r"BDF:\s+status=0", out)
    assert m, out
    m = re.search(r"measured ([0-9.e+-]+) vs exp\(-lam1\*t\) ([0-9.e+-]+)",
                  out)
    assert m, out
    a, b = float(m.group(1)), float(m.group(2))
    assert abs(a - b) <= 0.02 * max(abs(b), 1e-3)  # relative
    m = re.search(r"stiffness ratio nfev: ([0-9.]+)x", out)
    assert m and float(m.group(1)) > 1.5, out


def test_gmg_stencil_transfer_operators_match_matrices():
    """The TPU-first conv forms of R (stride-2 conv) and P = R.T
    (input-dilated conv) must be exactly the linear maps of the
    assembled matrices, on even and odd grids, for both gridops."""
    import importlib.util
    import sys as _sys

    import jax.numpy as jnp
    import numpy as np

    here = os.path.join(os.path.dirname(os.path.dirname(__file__)), "examples")
    _sys.path.insert(0, here)
    old_argv = _sys.argv
    _sys.argv = ["gmg.py", "-n", "8", "--precision", "f32"]
    try:
        spec = importlib.util.spec_from_file_location(
            "gmg_stencil_mod", os.path.join(here, "gmg.py")
        )
        m = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(m)
    finally:
        _sys.argv = old_argv
        _sys.path.remove(here)
    rng = np.random.default_rng(0)
    for fine_n in (8, 9, 13):
        dim = fine_n * fine_n
        for gridop, op in (
            ("injection", m.injection_operator), ("linear", m.linear_operator)
        ):
            R, cdim = op(dim)
            cn = int(np.sqrt(cdim))
            r = rng.standard_normal(dim).astype(np.float32)
            xc = rng.standard_normal(cdim).astype(np.float32)
            np.testing.assert_allclose(
                np.asarray(m._restrict_stencil(jnp.asarray(r), fine_n, cn, gridop)),
                np.asarray(R @ r), atol=1e-5,
            )
            np.testing.assert_allclose(
                np.asarray(m._prolong_stencil(jnp.asarray(xc), fine_n, cn, gridop)),
                np.asarray(R.T.tocsr() @ xc), atol=1e-5,
            )


def test_amg_example_single_device():
    # single-device AMG path: device-MIS aggregation hierarchy + the
    # best-of-2 timed solve block
    out = _run("amg.py", "-n", "32", "-maxiter", "60")
    m = re.search(r"Iterations: (\d+)\s+residual: ([0-9.e+-]+)", out)
    assert m, out
    assert float(m.group(2)) < 1e-6


def test_gmg_dist_grid_example():
    """Distributed GMG, grid pipeline: the -dist default — row-sharded
    stencil hierarchy, XLA-inserted halo collectives."""
    out = _run("gmg.py", "-n", "32", "-levels", "3", "-maxiter", "60", "-dist")
    m = re.search(r"Iterations: (\d+)\s+residual: ([0-9.e+-]+)", out)
    assert m, out
    assert float(m.group(2)) < 1e-6
