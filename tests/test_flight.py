"""Axon v6 (ISSUE 12): incident flight recorder, alert-triggered
postmortem bundles, measured device-time profiling, and the doctor.

Pins the PR's contracts:

* **watchdog hook** — alert transitions reach registered hooks; the
  flight path is rate-limited (one bundle per window), count-bounded
  (oldest pruned), and OFF by default (no filesystem touch without
  ``SPARSE_TPU_FLIGHT`` or an explicit recorder);
* **bundle contents under the multi-process sink split** — a bundle
  captured by (simulated) process 1 carries THAT process's identity
  block and ring tail;
* **sampled device profiling** — ``profile_every`` feeds the always-on
  ``batch.program_device_ms{program}`` histogram and the
  ``batch.dispatch`` event's ``device_ms``/``host_ms`` split, while the
  OFF path leaves dispatch programs (jaxpr) and host-sync counts
  byte-identical and emits no extra fields;
* **doctor diagnosis** — the rule+chain signatures name the right
  probable cause, stdlib-only;
* **satellites** — span-sync-error counter, incident retention in
  trim_records, axon_report ``--trend``.
"""

import importlib.util
import json
import os

import numpy as np
import pytest
import scipy.sparse as sp

import sparse_tpu  # noqa: F401 - jax config side effects
from sparse_tpu import telemetry
from sparse_tpu.batch import SolveSession
from sparse_tpu.config import settings
from sparse_tpu.telemetry import _flight, _metrics, _recorder, _watchdog

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def tel(tmp_path, monkeypatch):
    """Telemetry on with an isolated sink; flight singleton isolated."""
    telemetry.reset()
    _flight.stop_flight()
    monkeypatch.setattr(settings, "telemetry", True)
    telemetry.configure(str(tmp_path / "records.jsonl"))
    yield tmp_path
    telemetry.configure(None)
    _flight.stop_flight()
    telemetry.reset()


def _tridiag(n=48, seed=0):
    rng = np.random.default_rng(seed)
    e = np.ones(n)
    A = sp.diags([-e[:-1], 3.0 * e, -e[:-1]], [-1, 0, 1], format="csr")
    A.setdiag(3.0 + rng.random(n))
    A.sort_indices()
    return A.tocsr()


# -- watchdog alert hooks -----------------------------------------------------


def test_alert_hook_receives_transitions(tel):
    got = []
    _watchdog.add_alert_hook(got.append)
    try:
        wd = _watchdog.Watchdog(
            rules=[_watchdog.Rule("hook_t", lambda: 1.0, 0.5)]
        )
        wd.evaluate()
    finally:
        _watchdog.remove_alert_hook(got.append)
    assert len(got) == 1
    t = got[0]
    assert t["rule"] == "hook_t" and t["event"] == "alert"
    assert t["value"] == 1.0 and t["trigger"] == 0.5


def test_alert_hook_exception_never_kills_the_tick(tel):
    def bad(_t):
        raise RuntimeError("hook crash")

    _watchdog.add_alert_hook(bad)
    try:
        wd = _watchdog.Watchdog(
            rules=[_watchdog.Rule("hook_bad", lambda: 1.0, 0.5)]
        )
        trans = wd.evaluate()
    finally:
        _watchdog.remove_alert_hook(bad)
    assert [t["rule"] for t in trans] == ["hook_bad"]


def test_flight_disabled_by_default_off_path(tel, monkeypatch):
    """Without SPARSE_TPU_FLIGHT and without an explicit recorder, an
    alert transition must not create a singleton, a directory, or any
    file — the off path is one settings check."""
    monkeypatch.setattr(settings, "flight", "")
    _flight.stop_flight()
    default_root = _flight._DEFAULT_ROOT
    before = (
        sorted(os.listdir(default_root))
        if os.path.isdir(default_root) else None
    )
    out = _flight.on_alert_transition(
        {"rule": "slo_miss_rate", "severity": "page", "value": 1.0}
    )
    assert out is None
    assert _flight.current() is None
    after = (
        sorted(os.listdir(default_root))
        if os.path.isdir(default_root) else None
    )
    assert after == before
    st = _flight.state()
    assert st["enabled"] is False and st["captures"] == 0


def test_flight_env_enables_lazy_singleton(tel, monkeypatch, tmp_path):
    root = str(tmp_path / "incidents")
    monkeypatch.setattr(settings, "flight", root)
    _flight.stop_flight()
    try:
        out = _flight.on_alert_transition(
            {"rule": "queue_depth", "severity": "warn", "value": 600.0,
             "trigger": 512.0}
        )
        assert out is not None and out.startswith(root)
        assert _flight.current() is not None
        assert os.path.isfile(os.path.join(out, "incident.json"))
    finally:
        _flight.stop_flight()


# -- capture semantics: rate limit, bound, contents ---------------------------


def test_capture_rate_limit(tel, tmp_path):
    fr = _flight.FlightRecorder(
        root=str(tmp_path / "inc"), min_interval_s=120.0,
    )
    base = _flight._SUPPRESSED.value
    b1 = fr.capture(reason="alert", rule="r1")
    assert b1 is not None
    assert fr.capture(reason="alert", rule="r1") is None
    assert fr.capture(reason="manual") is None  # manual limited too
    assert fr.suppressed == 2
    assert _flight._SUPPRESSED.value == base + 2
    names = os.listdir(str(tmp_path / "inc"))
    assert len(names) == 1


def test_capture_bound_prunes_oldest(tel, tmp_path):
    root = str(tmp_path / "inc")
    fr = _flight.FlightRecorder(root=root, max_bundles=2,
                                min_interval_s=0.0)
    dirs = [fr.capture(reason="alert", rule=f"r{i}") for i in range(4)]
    assert all(dirs)
    kept = sorted(os.listdir(root))
    assert len(kept) == 2
    # the two NEWEST survive (names sort chronologically)
    assert kept == sorted(os.path.basename(d) for d in dirs[-2:])


def test_bundle_contents_and_event(tel, tmp_path):
    telemetry.record("fault.injected", site="dispatch", fault="delay",
                     ms=150)
    fr = _flight.FlightRecorder(root=str(tmp_path / "inc"),
                                min_interval_s=0.0)
    b = fr.capture(
        reason="alert", rule="slo_miss_rate",
        transition={"rule": "slo_miss_rate", "severity": "page",
                    "value": 0.9, "trigger": 0.5},
    )
    assert sorted(os.listdir(b)) == [
        "incident.json", "metrics.json", "ring.jsonl", "trace.json",
    ]
    man = json.load(open(os.path.join(b, "incident.json")))
    assert man["rule"] == "slo_miss_rate"
    assert man["transition"]["value"] == 0.9
    assert man["process"]["pid"] == os.getpid()
    assert "watchdog" in man and "fingerprint" in man
    assert man["fingerprint"]["config"]["telemetry"] is True
    ring = [json.loads(ln) for ln in open(os.path.join(b, "ring.jsonl"))]
    assert ring[0]["kind"] == "session.start"
    assert any(ev["kind"] == "fault.injected" for ev in ring)
    mets = json.load(open(os.path.join(b, "metrics.json")))
    assert "plan_cache" in mets and "metrics" in mets
    trace = json.load(open(os.path.join(b, "trace.json")))
    assert "traceEvents" in trace
    # the capture is itself an event + an always-on counter
    evs = telemetry.events("flight.capture")
    assert evs and evs[-1]["rule"] == "slo_miss_rate"
    assert _metrics.counter(
        "flight.captures", rule="slo_miss_rate"
    ).value >= 1
    # the /incidents listing sees it
    st = fr.state()
    assert st["captures"] == 1
    assert st["bundles"][0]["rule"] == "slo_miss_rate"


def test_bundle_carries_split_sink_identity(tel, tmp_path, monkeypatch):
    """Multi-process sink split (ISSUE 12 satellite): the bundle a
    simulated process 1 captures must carry THAT process's identity
    block (pi=1, split sink path) and its own ring tail."""
    monkeypatch.setenv("SPARSE_TPU_PROCESS_COUNT", "2")
    monkeypatch.setenv("SPARSE_TPU_PROCESS_INDEX", "1")
    _recorder.reset_identity()
    telemetry.configure(str(tmp_path / "records.jsonl"))
    try:
        telemetry.record("span", name="p1.work", dur_s=0.01)
        assert telemetry.sink_path().endswith(
            f"records.{os.getpid()}.jsonl"
        )
        fr = _flight.FlightRecorder(root=str(tmp_path / "inc"),
                                    min_interval_s=0.0)
        b = fr.capture(reason="alert", rule="anomaly_rate")
        man = json.load(open(os.path.join(b, "incident.json")))
        assert man["process"]["pi"] == 1
        assert man["process"]["procs"] == 2
        ring = [
            json.loads(ln) for ln in open(os.path.join(b, "ring.jsonl"))
        ]
        # identity block first, stamped with the split-process identity
        assert ring[0]["kind"] == "session.start" and ring[0]["pi"] == 1
        spans = [ev for ev in ring if ev.get("kind") == "span"]
        assert any(ev.get("name") == "p1.work" for ev in spans)
        assert all(ev["pi"] == 1 for ev in spans)
    finally:
        _recorder.reset_identity()


def test_watchdog_alert_auto_captures_once(tel, tmp_path):
    """The full hook chain: a firing rule writes exactly one bundle
    through the singleton; the clear does not capture."""
    _flight.stop_flight()
    _flight.flight(root=str(tmp_path / "inc"), min_interval_s=0.0)
    level = {"v": 1.0}
    try:
        wd = _watchdog.Watchdog(rules=[
            _watchdog.Rule("auto_t", lambda: level["v"], 0.5, clear=0.2)
        ])
        wd.evaluate()
        names = os.listdir(str(tmp_path / "inc"))
        assert len(names) == 1 and names[0].endswith("-auto_t")
        level["v"] = 0.0
        wd.evaluate()  # clears; must not capture a second bundle
        assert len(os.listdir(str(tmp_path / "inc"))) == 1
    finally:
        _flight.stop_flight()


# -- sampled device-time profiling -------------------------------------------


def _mats(n=48, B=3):
    mats = [_tridiag(n, seed=i) for i in range(B)]
    rhs = np.random.default_rng(5).standard_normal((B, n))
    return mats, rhs


def test_profile_sampling_records_device_split(tel):
    mats, rhs = _mats()
    ses = SolveSession("cg", profile_every=1)
    ses.solve_many(mats, rhs, tol=1e-8)
    ev = telemetry.events("batch.dispatch")[-1]
    assert "device_ms" in ev and "host_ms" in ev
    assert ev["device_ms"] >= 0.0 and ev["host_ms"] >= 0.0
    # the split tiles the solve wall (within rounding)
    assert ev["device_ms"] + ev["host_ms"] <= ev["solve_ms"] + 0.1
    fam = _metrics.family("batch.program_device_ms")
    assert any(m.count >= 1 for m in fam)
    from sparse_tpu.telemetry import _cost

    progs = _cost.programs()
    key = str(ev["program"])
    assert progs[key]["device_samples"] >= 1
    assert progs[key]["device_ms_mean"] >= 0.0


def test_profile_every_n_samples_every_nth(tel):
    mats, rhs = _mats()
    ses = SolveSession("cg", profile_every=2)
    for _ in range(4):  # 4 dispatches -> exactly 2 sampled
        for A, b in zip(mats, rhs):
            ses.submit(A, b, tol=1e-8)
        ses.flush()
    evs = telemetry.events("batch.dispatch")
    sampled = [e for e in evs if "device_ms" in e]
    assert len(evs) == 4 and len(sampled) == 2


def test_profile_off_is_byte_identical(tel):
    """The acceptance pin: sampling OFF (default) leaves the dispatch
    programs (jaxpr), plan-cache keys, host-sync counts and event
    fields exactly as they were — and ON changes only host-side
    timing, never the compiled program."""
    import jax

    mats, rhs = _mats()
    ses_off = SolveSession("cg")
    assert ses_off.profile_every == 0  # the default env
    ses_on = SolveSession("cg", profile_every=1)
    pat_off = ses_off.pattern_of(mats[0])
    pat_on = ses_on.pattern_of(mats[0])
    dt = np.dtype(np.result_type(mats[0].data.dtype, rhs.dtype))
    prog_off = ses_off._build_program(pat_off, 4, dt)
    prog_on = ses_on._build_program(pat_on, 4, dt)
    args = (
        np.zeros((4, pat_off.nnz), dt), np.zeros((4, 48), dt),
        np.zeros((4, 48), dt), np.zeros(4), 10,
    )
    def jaxpr_of(prog):
        # two sessions hold distinct (but functionally identical) pack
        # closures; volatile object addresses in the repr are not
        # program structure
        import re

        return re.sub(r"0x[0-9a-f]+", "0x", str(jax.make_jaxpr(prog)(*args)))

    assert jaxpr_of(prog_off) == jaxpr_of(prog_on)

    def syncs_of(ses):
        base = _metrics.counter(
            "telemetry.counts", name="host_sync.int"
        ).value
        ses.solve_many(mats, rhs, tol=1e-8)
        return _metrics.counter(
            "telemetry.counts", name="host_sync.int"
        ).value - base

    assert syncs_of(ses_off) == syncs_of(ses_on)
    off_evs = [
        e for e in telemetry.events("batch.dispatch")
        if "device_ms" not in e
    ]
    assert off_evs  # the off path emitted, without the sampled fields
    assert all("host_ms" not in e for e in off_evs)


def test_profiler_capture_trace(tel, tmp_path):
    res = telemetry.profile_capture(str(tmp_path / "prof"), seconds=0.01)
    assert res["ok"] is True
    assert res["files"]  # xplane/trace artifacts landed
    evs = telemetry.events("profile.capture")
    assert evs and evs[-1]["ok"] is True


def test_debug_capture_bundle_includes_profile(tel, tmp_path):
    _flight.stop_flight()
    _flight.flight(root=str(tmp_path / "inc"), min_interval_s=0.0)
    try:
        b = _flight.capture_now(reason="manual", profile=True,
                                profile_seconds=0.01)
        assert b is not None
        man = json.load(open(os.path.join(b, "incident.json")))
        assert man["reason"] == "manual"
        assert man["profile"]["ok"] is True
        assert os.path.isdir(os.path.join(b, "profile"))
    finally:
        _flight.stop_flight()


# -- serve endpoints ----------------------------------------------------------


def test_serve_incidents_and_capture_endpoints(tel, tmp_path):
    import urllib.request

    _flight.stop_flight()
    _flight.flight(root=str(tmp_path / "inc"), min_interval_s=0.0)
    try:
        with telemetry.serve(port=0) as srv:
            inc = json.loads(
                urllib.request.urlopen(
                    f"{srv.url}/incidents", timeout=10
                ).read()
            )
            assert inc["enabled"] is True and inc["captures"] == 0
            cap = json.loads(
                urllib.request.urlopen(
                    f"{srv.url}/debug/capture", timeout=30
                ).read()
            )
            assert cap["ok"] is True and cap["bundle"]
            inc2 = json.loads(
                urllib.request.urlopen(
                    f"{srv.url}/incidents", timeout=10
                ).read()
            )
            assert inc2["captures"] == 1
            assert inc2["bundles"][0]["name"] == cap["bundle"]
            hz = json.loads(
                urllib.request.urlopen(
                    f"{srv.url}/healthz", timeout=10
                ).read()
            )
            assert hz["incidents"]["enabled"] is True
            assert hz["incidents"]["captures"] == 1
            assert "span_sync_errors" in hz
    finally:
        _flight.stop_flight()


# -- the doctor ---------------------------------------------------------------


def _bundle_with(tmp_path, rule, events, latches=None, faults_cfg=None):
    b = tmp_path / "inc" / f"20260101T000000.001-{rule}"
    os.makedirs(b, exist_ok=True)
    man = {
        "schema": 1, "reason": "alert", "rule": rule,
        "ts": 1700000000.0, "iso": "2026-01-01T00:00:00Z",
        "transition": {"rule": rule, "severity": "page", "value": 1.0,
                       "trigger": 0.5},
        "process": {"pi": 0, "pid": 1234},
        "failover_latches": latches or {},
        "faults": faults_cfg or {},
    }
    with open(b / "incident.json", "w") as f:
        json.dump(man, f)
    with open(b / "ring.jsonl", "w") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")
    return str(b)


def test_doctor_names_injected_delay(tmp_path):
    doctor = _load("axon_doctor")
    b = _bundle_with(
        tmp_path, "slo_miss_rate",
        [{"kind": "fault.injected", "ts": 1.0, "site": "dispatch",
          "fault": "delay", "ms": 150},
         {"kind": "batch.dispatch", "ts": 2.0, "solver": "cg",
          "batch": 4, "bucket": 4}],
        faults_cfg={"active": True, "spec": "delay:dispatch:ms=150"},
    )
    man, evs = doctor.load_bundle(b)
    diag = doctor.diagnose(man, evs)
    assert diag["cause"] == "injected-dispatch-delay"
    assert "dispatch delay" in diag["probable_cause"]
    assert diag["rule"] == "slo_miss_rate"


def test_doctor_names_failover_and_vault(tmp_path):
    doctor = _load("axon_doctor")
    b = _bundle_with(
        tmp_path, "failover_latched",
        [{"kind": "kernel.failover", "ts": 1.0, "kernel": "sell_spmv",
          "error": "boom"}],
        latches={"sell_spmv": 1},
    )
    man, evs = doctor.load_bundle(b)
    assert doctor.diagnose(man, evs)["cause"] == "pallas-failover"
    b2 = _bundle_with(
        tmp_path, "vault_quarantine",
        [{"kind": "vault.quarantine", "ts": 1.0,
          "artifact": "sell_pattern", "reason": "checksum"}],
    )
    man2, evs2 = doctor.load_bundle(b2)
    d2 = doctor.diagnose(man2, evs2)
    assert d2["cause"] == "vault-corruption"


def test_doctor_compile_tax_and_unknown(tmp_path):
    doctor = _load("axon_doctor")
    b = _bundle_with(
        tmp_path, "slo_miss_rate",
        [{"kind": "plan_cache.compile", "ts": 1.0,
          "program": "batch.cg.B8.<f8"}],
    )
    man, evs = doctor.load_bundle(b)
    assert doctor.diagnose(man, evs)["cause"] == "compile-tax"
    b2 = _bundle_with(tmp_path, "", [{"kind": "span", "ts": 1.0,
                                      "name": "x", "dur_s": 0.1}])
    man2, evs2 = doctor.load_bundle(b2)
    assert doctor.diagnose(man2, evs2)["cause"] == "unknown"


def test_doctor_cli_resolves_newest_and_exits_clean(tel, tmp_path,
                                                    capsys):
    doctor = _load("axon_doctor")
    _bundle_with(
        tmp_path, "anomaly_rate",
        [{"kind": "solver.anomaly", "ts": 1.0, "solver": "cg",
          "reason": "stagnation"}],
    )
    root = str(tmp_path / "inc")
    assert doctor.main([root, "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["cause"] == "solver-anomalies"
    assert doctor.main([str(tmp_path / "nope")]) == 2


# -- satellites ---------------------------------------------------------------


def test_span_sync_errors_counted(tel, monkeypatch):
    from sparse_tpu.telemetry import _spans

    base = _spans._SYNC_ERRORS.value

    class Boom:
        pass

    def bad_block(x):
        raise RuntimeError("device gone")

    import jax

    monkeypatch.setattr(jax, "block_until_ready", bad_block)
    with telemetry.span("t.sync", sync=Boom()):
        pass
    telemetry.device_sync(Boom())
    assert _spans._SYNC_ERRORS.value == base + 2


def test_trim_incidents_keeps_newest(tmp_path):
    trim = _load("trim_records")
    root = str(tmp_path / "incidents")
    for i in range(6):
        d = os.path.join(root, f"20260101T00000{i}.001-r{i}")
        os.makedirs(d)
        with open(os.path.join(d, "incident.json"), "w") as f:
            json.dump({"rule": f"r{i}"}, f)
    # a manifest-less dir is not a bundle: never touched
    os.makedirs(os.path.join(root, "not-a-bundle"))
    removed = trim.trim_incidents(root=root, keep=2)
    assert removed == 4
    kept = sorted(os.listdir(root))
    assert "not-a-bundle" in kept
    bundles = [n for n in kept if n != "not-a-bundle"]
    assert bundles == ["20260101T000004.001-r4", "20260101T000005.001-r5"]
    assert trim.trim_incidents(root=root, keep=2, dry_run=True) == 0


def test_report_trend_joins_bench_rounds(tmp_path):
    report = _load("axon_report")
    rows = [
        (1, 500.0, None), (2, 550.0, 120.5), (3, 600.0, 140.25),
    ]
    for n, iters, rps in rows:
        tail = ""
        if rps is not None:
            tail = json.dumps({
                "metric": f"cg_iters_per_s_pde512_cpu", "value": iters,
                "sustained_cg": {"achieved_rps": rps, "p95_ms": 20.0,
                                 "slo_miss_rate": 0.0},
                "cold_start": {"cold_s": 1.5, "warm_s": 0.1},
            }) + "\n"
        with open(tmp_path / f"BENCH_r0{n}.json", "w") as f:
            json.dump({
                "n": n, "rc": 0, "tail": tail,
                "parsed": {"metric": "cg_iters_per_s_pde512_cpu",
                           "value": iters, "unit": "iters/s"},
            }, f)
    trend = report.build_trend(
        sorted(str(tmp_path / f"BENCH_r0{n}.json") for n, _, _ in rows)
    )
    assert len(trend["rounds"]) == 3
    assert trend["series"]["cg_iters_per_s"] == [
        ["BENCH_r01.json", 500.0], ["BENCH_r02.json", 550.0],
        ["BENCH_r03.json", 600.0],
    ]
    assert trend["series"]["sustained_cg.achieved_rps"] == [
        ["BENCH_r02.json", 120.5], ["BENCH_r03.json", 140.25],
    ]
    assert trend["rounds"][1]["cold_start"]["warm_s"] == 0.1
    # the CLI path over the committed rounds always succeeds
    assert report.main(["--trend", "--quiet"]) == 0


def test_report_programs_table_gains_device_column(tmp_path):
    report = _load("axon_report")
    path = str(tmp_path / "r.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({
            "kind": "plan_cache.compile", "ts": 1.0,
            "program": "batch.cg.B4.<f8", "flops": 1e9, "bytes": 1e8,
            "compile_s": 0.5,
        }) + "\n")
        for i, (dev, host) in enumerate([(2.0, 1.0), (4.0, 3.0)]):
            f.write(json.dumps({
                "kind": "batch.dispatch", "ts": 2.0 + i, "solver": "cg",
                "batch": 4, "bucket": 4, "program": "batch.cg.B4.<f8",
                "solve_ms": dev + host + 1.0, "device_ms": dev,
                "host_ms": host,
            }) + "\n")
        f.write(json.dumps({
            "kind": "batch.dispatch", "ts": 9.0, "solver": "cg",
            "batch": 4, "bucket": 4, "program": "batch.cg.B4.<f8",
            "solve_ms": 5.0,
        }) + "\n")
    rep = report.build_report(path)
    p = rep["programs"]["batch.cg.B4.<f8"]
    assert p["solves"] == 3
    assert p["device_samples"] == 2
    assert p["device_ms_mean"] == 3.0
    assert p["host_ms_mean"] == 2.0
    # device-clock achieved rate: 1e9 flops * 2 samples / 6ms
    assert p["achieved_gflops_dev"] == pytest.approx(
        1e9 * 2 / 6e-3 / 1e9, rel=1e-6
    )
    assert rep["metrics"]["program.batch.cg.B4.<f8.device_ms_mean"] == {
        "v": 3.0, "hib": False,
    }


def test_schema_covers_new_kinds(tel):
    from sparse_tpu.telemetry import schema

    assert not schema.validate({
        "kind": "flight.capture", "ts": 1.0, "reason": "alert",
        "rule": "slo_miss_rate", "dir": "x",
    })
    assert not schema.validate({
        "kind": "profile.capture", "ts": 1.0, "ok": True, "dir": "x",
    })
    assert schema.validate({"kind": "flight.capture", "ts": 1.0})
