"""Stacked-real complex transfer shims (VERDICT r3 #5).

On transfer-restricted backends (the axon tunnel) complex arrays cannot
cross the host<->device boundary; ``utils.asjnp`` ships them as stacked
real planes recombined compiled, and ``utils.tohost`` does the inverse.
These tests force the restricted path on the CPU mesh (monkeypatching the
memoized predicate) and pin it to the unrestricted results; the on-
hardware lane is ``scripts/tpu_complex_check.py`` (opt-in test below).

Reference analog: the {c64, c128} accelerator dispatch lanes of
``src/sparse/util/dispatch.h:53-75``.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import sparse_tpu as sparse
import sparse_tpu.linalg as linalg
from sparse_tpu import integrate, utils

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def restricted(monkeypatch):
    monkeypatch.setattr(utils, "_TRANSFER_RESTRICTED", True)
    yield
    # monkeypatch restores the memo automatically


def test_asjnp_tohost_roundtrip(restricted):
    z = (np.arange(6) + 1j * np.arange(6)[::-1]).astype(np.complex128)
    d = utils.asjnp(z)
    assert np.iscomplexobj(d)
    np.testing.assert_allclose(utils.tohost(d), z)
    # real arrays are untouched by the shims
    r = np.arange(4.0)
    np.testing.assert_allclose(utils.tohost(utils.asjnp(r)), r)


def test_complex_spmv_through_stacked_path(restricted):
    n = 32
    rng = np.random.default_rng(1)
    hop = rng.random(n - 1) + 1j * rng.random(n - 1)
    H = sparse.diags([np.conj(hop), np.full(n, 2.0 + 0j), hop], [-1, 0, 1]).tocsr()
    x = rng.random(n) + 1j * rng.random(n)
    import scipy.sparse as sp

    Hs = sp.diags([np.conj(hop), np.full(n, 2.0 + 0j), hop], [-1, 0, 1]).tocsr()
    np.testing.assert_allclose(
        utils.tohost(H @ utils.asjnp(x)), Hs @ x, rtol=1e-10
    )


def test_complex_cg_through_stacked_path(restricted):
    n = 64
    rng = np.random.default_rng(2)
    hop = rng.random(n - 1) + 1j * rng.random(n - 1)
    A = sparse.diags(
        [np.conj(hop), np.full(n, 6.0 + 0j), hop], [-1, 0, 1]
    ).tocsr()
    b = rng.random(n) + 1j * rng.random(n)
    x, iters = linalg.cg(A, b, tol=1e-10, maxiter=500)
    import scipy.sparse as sp

    As = sp.diags([np.conj(hop), np.full(n, 6.0 + 0j), hop], [-1, 0, 1]).tocsr()
    resid = np.linalg.norm(As @ utils.tohost(x) - b)
    assert resid < 1e-7, resid


def test_complex_solve_ivp_through_stacked_path(restricted):
    n = 16
    rng = np.random.default_rng(3)
    hop = rng.random(n - 1) + 1j * rng.random(n - 1)
    H = sparse.diags([np.conj(hop), np.full(n, 1.0 + 0j), hop], [-1, 0, 1]).tocsr()
    psi0 = np.zeros(n, dtype=complex)
    psi0[n // 2] = 1.0
    out = integrate.solve_ivp(
        lambda t, p: -1j * (H @ p), (0.0, 0.4), psi0, rtol=1e-9, atol=1e-11
    )
    psiT = utils.tohost(out.y)[:, -1]
    assert abs(np.linalg.norm(psiT) - 1.0) < 1e-6
    import scipy.integrate as si
    import scipy.sparse as sp

    Hs = sp.diags([np.conj(hop), np.full(n, 1.0 + 0j), hop], [-1, 0, 1]).tocsr()
    ref = si.solve_ivp(
        lambda t, p: -1j * (Hs @ p), (0.0, 0.4), psi0, rtol=1e-9, atol=1e-11
    )
    np.testing.assert_allclose(psiT, ref.y[:, -1], rtol=1e-5, atol=1e-7)


def test_complex_lane_script_cpu():
    """The hardware lane script passes on the CPU backend too (same code
    path minus the restriction — keeps the script itself green)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "tpu_complex_check.py")],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["ok"]


@pytest.mark.skipif(
    not os.environ.get("RUN_TPU_HW"),
    reason="opt-in hardware lane (RUN_TPU_HW=1, needs the live tunnel)",
)
def test_complex_lane_script_tpu_hw():
    """The c64 lane on the REAL accelerator: restores the tunnel trigger
    the conftest parked and runs the script on the default backend."""
    env = dict(os.environ)
    saved = env.pop("_SAVED_PALLAS_AXON_POOL_IPS", None)
    if saved:
        env["PALLAS_AXON_POOL_IPS"] = saved
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "tpu_complex_check.py")],
        capture_output=True, text=True, timeout=900, cwd=REPO, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["ok"] and rec["transfer_restricted"]
