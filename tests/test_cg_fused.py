"""Fused two-pass CG (kernels/cg_dia.py) vs the plain step-loop oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparse_tpu.kernels.cg_dia import cg_dia_fused
from sparse_tpu.models.poisson import (
    cg_dia,
    laplacian_2d_dia,
    make_cg_step_dia,
)


@pytest.mark.parametrize("n,iters", [(16, 50), (40, 30)])
def test_cg_fused_matches_step_loop(n, iters):
    N = n * n
    planes, offsets = laplacian_2d_dia(n)
    b = jax.random.normal(jax.random.PRNGKey(0), (N,), dtype=jnp.float32)
    x0 = jnp.zeros((N,), jnp.float32)

    step = make_cg_step_dia(offsets, n, use_pallas=False)
    state = (planes, x0, b, jnp.zeros((N,), jnp.float32), jnp.zeros((), jnp.float32))
    x_ref = np.asarray(cg_dia(step, *state, iters=iters)[0])

    x_f, r_f, rho = cg_dia_fused(
        planes, offsets, b, x0, N, iters=iters, interpret=True
    )
    assert np.allclose(np.asarray(x_f), x_ref, atol=1e-4)
    assert float(rho) >= 0.0


def test_cg_fused_nonzero_x0():
    n = 16
    N = n * n
    planes, offsets = laplacian_2d_dia(n)
    key = jax.random.PRNGKey(1)
    b = jax.random.normal(key, (N,), dtype=jnp.float32)
    x0 = jax.random.normal(jax.random.PRNGKey(2), (N,), dtype=jnp.float32)

    step = make_cg_step_dia(offsets, n, use_pallas=False)
    from sparse_tpu.ops.dia_spmv import dia_spmv_xla

    r0 = b - dia_spmv_xla(planes, offsets, x0, (N, N))
    state = (planes, x0, r0, jnp.zeros((N,), jnp.float32), jnp.zeros((), jnp.float32))
    x_ref = np.asarray(cg_dia(step, *state, iters=40)[0])

    x_f = cg_dia_fused(planes, offsets, b, x0, N, iters=40, interpret=True)[0]
    assert np.allclose(np.asarray(x_f), x_ref, atol=1e-4)
