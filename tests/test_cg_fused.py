"""Fused two-pass CG (kernels/cg_dia.py) vs the plain step-loop oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparse_tpu.kernels.cg_dia import cg_dia_fused
from sparse_tpu.models.poisson import (
    cg_dia,
    laplacian_2d_dia,
    make_cg_step_dia,
)


@pytest.mark.parametrize("n,iters", [(16, 50), (40, 30)])
def test_cg_fused_matches_step_loop(n, iters):
    N = n * n
    planes, offsets = laplacian_2d_dia(n)
    b = jax.random.normal(jax.random.PRNGKey(0), (N,), dtype=jnp.float32)
    x0 = jnp.zeros((N,), jnp.float32)

    step = make_cg_step_dia(offsets, n, use_pallas=False)
    state = (planes, x0, b, jnp.zeros((N,), jnp.float32), jnp.zeros((), jnp.float32))
    x_ref = np.asarray(cg_dia(step, *state, iters=iters)[0])

    x_f, r_f, rho = cg_dia_fused(
        planes, offsets, b, x0, N, iters=iters, interpret=True
    )
    assert np.allclose(np.asarray(x_f), x_ref, atol=1e-4)
    assert float(rho) >= 0.0


def test_cg_fused_nonzero_x0():
    n = 16
    N = n * n
    planes, offsets = laplacian_2d_dia(n)
    key = jax.random.PRNGKey(1)
    b = jax.random.normal(key, (N,), dtype=jnp.float32)
    x0 = jax.random.normal(jax.random.PRNGKey(2), (N,), dtype=jnp.float32)

    step = make_cg_step_dia(offsets, n, use_pallas=False)
    from sparse_tpu.ops.dia_spmv import dia_spmv_xla

    r0 = b - dia_spmv_xla(planes, offsets, x0, (N, N))
    state = (planes, x0, r0, jnp.zeros((N,), jnp.float32), jnp.zeros((), jnp.float32))
    x_ref = np.asarray(cg_dia(step, *state, iters=40)[0])

    x_f = cg_dia_fused(planes, offsets, b, x0, N, iters=40, interpret=True)[0]
    assert np.allclose(np.asarray(x_f), x_ref, atol=1e-4)


def test_cg_fused_junk_dia_tail_slots():
    """scipy-ignored out-of-band DIA slots must not leak into the solve.

    Dense-random planes are a legal sp.dia_matrix input whose slots for
    nonexistent rows hold junk; the packing must mask them or padded rows
    of q contaminate r/rho (regression: residual was ~1e5 before the
    row-mask in dia_pack).
    """
    import scipy.sparse as sp

    m, offsets = 600, (-1, 0, 1)
    rng = np.random.default_rng(3)
    off = rng.uniform(0.5, 1.0, m).astype(np.float32)  # A[j+1, j] = off[j]
    data = np.zeros((3, m), dtype=np.float32)
    data[0, :] = off                      # o=-1: data[0][j] = A[j+1, j]
    data[1, :] = 4.0
    data[2, 1:] = off[:-1]                # o=+1: data[2][j] = A[j-1, j] (symmetric)
    data[0, m - 1] = 1e6                  # scipy-ignored slots: junk
    data[2, 0] = -1e6
    A = sp.dia_matrix((data, offsets), shape=(m, m)).tocsr()
    b = rng.standard_normal(m).astype(np.float32)

    x = np.asarray(
        cg_dia_fused(jnp.asarray(data), offsets, jnp.asarray(b), None, m,
                     iters=80, tile=1024, interpret=True)[0]
    )
    assert np.linalg.norm(A @ x - b) < 1e-2


def test_cg_fused_multi_tile():
    """G > 1 exercises the double-buffered plane/window DMA machinery."""
    import scipy.sparse as sp

    m = 2500  # three 1024-tiles
    offsets = (-50, -1, 0, 1, 50)
    rng = np.random.default_rng(5)
    A = sp.diags(
        [np.full(m - 50, -1.0), np.full(m - 1, -1.0), np.full(m, 4.2),
         np.full(m - 1, -1.0), np.full(m - 50, -1.0)],
        offsets, shape=(m, m), format="dia",
    )
    data = A.data.astype(np.float32)
    b = rng.standard_normal(m).astype(np.float32)
    x = np.asarray(
        cg_dia_fused(jnp.asarray(data), offsets, jnp.asarray(b), None, m,
                     iters=120, tile=1024, interpret=True)[0]
    )
    assert np.linalg.norm(A.tocsr() @ x - b) < 1e-2


@pytest.mark.parametrize("n,iters", [(16, 150), (40, 120)])
def test_cg_onepass_matches_twopass(n, iters):
    """Chronopoulos-Gear one-pass CG converges like the two-pass kernel."""
    from sparse_tpu.kernels.cg_dia import cg_dia_fused_onepass
    from sparse_tpu.ops.dia_spmv import dia_spmv_xla

    N = n * n
    planes, offsets = laplacian_2d_dia(n)
    b = np.asarray(
        jax.random.normal(jax.random.PRNGKey(3), (N,), jnp.float32)
    )
    x2 = cg_dia_fused(planes, offsets, jnp.asarray(b), None, N,
                      iters=iters, tile=1024, interpret=True)[0]
    x1 = cg_dia_fused_onepass(planes, offsets, jnp.asarray(b), None, N,
                              iters=iters, tile=1024, interpret=True)[0]
    r2 = np.linalg.norm(np.asarray(dia_spmv_xla(planes, offsets, x2, (N, N))) - b)
    r1 = np.linalg.norm(np.asarray(dia_spmv_xla(planes, offsets, x1, (N, N))) - b)
    assert r1 < max(4 * r2, 1e-3)


def test_cg_onepass_multi_tile_and_x0():
    from sparse_tpu.kernels.cg_dia import cg_dia_fused_onepass
    from sparse_tpu.ops.dia_spmv import dia_spmv_xla

    n = 50  # 2500 rows -> G=3 at tile=1024
    N = n * n
    planes, offsets = laplacian_2d_dia(n)
    b = np.asarray(jax.random.normal(jax.random.PRNGKey(4), (N,), jnp.float32))
    x0 = np.asarray(jax.random.normal(jax.random.PRNGKey(5), (N,), jnp.float32))
    x1 = cg_dia_fused_onepass(planes, offsets, jnp.asarray(b), jnp.asarray(x0),
                              N, iters=150, tile=1024, interpret=True)[0]
    r1 = np.linalg.norm(np.asarray(dia_spmv_xla(planes, offsets, x1, (N, N))) - b)
    assert r1 < 1e-2


def test_cg_fused_bf16_planes_exact():
    """bf16 plane streaming with exactly-representable stencil values
    reproduces the f32 result bit-for-bit at the solver level.

    Geometry matters: TM must be a 2048 multiple or the alignment guard
    silently falls back to f32 and the test stops testing anything —
    n=48 (N=2304 -> TM=2048 at tile=2048) keeps the bf16 path live; the
    planes dtype reaching the kernel is asserted via the packing helper.
    """
    from sparse_tpu.kernels.dia_spmv import plane_stream_dtype

    n = 48
    N = n * n
    planes, offsets = laplacian_2d_dia(n)
    assert bool(jnp.all(planes == planes.astype(jnp.bfloat16).astype(planes.dtype)))
    # the guard must RESOLVE to bf16 for this geometry (TM=2048)
    assert plane_stream_dtype(jnp.bfloat16, jnp.float32, 2048) == jnp.dtype(jnp.bfloat16)
    b = np.asarray(jax.random.normal(jax.random.PRNGKey(6), (N,), jnp.float32))
    x32 = cg_dia_fused(planes, offsets, jnp.asarray(b), None, N,
                       iters=100, tile=2048, interpret=True)[0]
    xbf = cg_dia_fused(planes, offsets, jnp.asarray(b), None, N,
                       iters=100, tile=2048, plane_dtype=jnp.bfloat16,
                       interpret=True)[0]
    np.testing.assert_allclose(np.asarray(x32), np.asarray(xbf), rtol=0, atol=0)


def test_plane_stream_dtype_alignment_guard():
    from sparse_tpu.kernels.dia_spmv import plane_stream_dtype

    f32 = jnp.dtype(jnp.float32)
    assert plane_stream_dtype(None, jnp.float32, 1024) == f32
    assert plane_stream_dtype(jnp.bfloat16, jnp.float32, 1024) == f32  # odd-1024
    assert plane_stream_dtype(jnp.bfloat16, jnp.float32, 4096) == jnp.dtype(jnp.bfloat16)


def test_linalg_cg_fused_fast_path_matches_loop():
    """linalg.cg's fused fast path (forced into interpret mode off-TPU)
    must produce the same solution and iteration count as the plain
    device loop — identical iterates, same absolute-||r|| stopping rule."""
    import numpy as np

    import sparse_tpu
    from sparse_tpu import linalg
    from sparse_tpu.config import settings

    n = 24
    diag_a = np.full(n * n - 1, -1.0, np.float32)
    diag_a[n - 1 :: n] = 0.0
    diag_g = np.full(n * (n - 1), -1.0, np.float32)
    diag_c = np.full(n * n, 4.0, np.float32)
    A = sparse_tpu.diags(
        [diag_g, diag_a, diag_c, diag_a, diag_g], [-n, -1, 0, 1, n],
        dtype=np.float32,
    )
    b = np.random.default_rng(0).random(n * n).astype(np.float32)

    old = settings.fused_cg
    try:
        settings.fused_cg = False
        x_loop, it_loop = linalg.cg(A, b, tol=1e-4, maxiter=400)
        settings.fused_cg = "force"
        x_fused, it_fused = linalg.cg(A, b, tol=1e-4, maxiter=400)
    finally:
        settings.fused_cg = old
    assert it_fused == it_loop
    np.testing.assert_allclose(
        np.asarray(x_fused), np.asarray(x_loop), rtol=2e-4, atol=2e-4
    )
    # and the answer actually solves the system
    resid = np.linalg.norm(np.asarray(A @ x_fused) - b)
    assert resid < 1e-3


def test_linalg_cg_fused_respects_x0_and_maxiter():
    import numpy as np

    import sparse_tpu
    from sparse_tpu import linalg
    from sparse_tpu.config import settings

    n = 16
    diag_a = np.full(n * n - 1, -1.0, np.float32)
    diag_a[n - 1 :: n] = 0.0
    diag_g = np.full(n * (n - 1), -1.0, np.float32)
    diag_c = np.full(n * n, 4.0, np.float32)
    A = sparse_tpu.diags(
        [diag_g, diag_a, diag_c, diag_a, diag_g], [-n, -1, 0, 1, n],
        dtype=np.float32,
    )
    rng = np.random.default_rng(1)
    xtrue = rng.random(n * n).astype(np.float32)
    b = np.asarray(A @ xtrue)
    old = settings.fused_cg
    try:
        settings.fused_cg = "force"
        # warm start very close to the solution: should converge immediately
        x, iters = linalg.cg(
            A, b, x0=xtrue + 1e-6, tol=1e-3, maxiter=400, conv_test_iters=5
        )
        assert iters <= 5
        # maxiter cap respected
        x2, iters2 = linalg.cg(A, b, tol=1e-30, maxiter=7)
        assert iters2 == 7
    finally:
        settings.fused_cg = old
