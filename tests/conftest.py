"""Test harness configuration.

Mirrors the reference's distributed-testing strategy (SURVEY §4): the same
correctness tests run under multiple resource shapes. Here: a virtual 8-device
CPU mesh via --xla_force_host_platform_device_count, with x64 enabled so
scipy-oracle comparisons are exact-dtype.

Must run before jax initializes a backend, hence the env mutation at import.
"""

import os

# The harness pre-sets JAX_PLATFORMS (e.g. to the axon TPU tunnel); tests must
# run on the virtual CPU mesh, so override rather than setdefault.
os.environ["JAX_PLATFORMS"] = "cpu"
# The axon sitecustomize hook dials the TPU tunnel from EVERY python process
# whose env carries PALLAS_AXON_POOL_IPS — including the subprocesses that
# example smoke tests spawn. When the tunnel is wedged that registration
# blocks for minutes before giving up, so drop the trigger for this process
# tree; CPU-mesh tests never need the tunnel. The value is parked under a
# saved key so the opt-in hardware lane (RUN_TPU_HW=1) can restore it for
# its subprocess.
_tunnel = os.environ.pop("PALLAS_AXON_POOL_IPS", None)
if _tunnel is not None:
    os.environ.setdefault("_SAVED_PALLAS_AXON_POOL_IPS", _tunnel)

# THIS repo's CI runs the Pallas failover strict: a pattern-matched
# ValueError from the DIA kernel re-raises instead of silently degrading
# to the XLA path (kernels/dia_spmv.py). Repo-scoped by design — downstream
# suites that don't set the flag keep the production failover.
os.environ.setdefault("SPARSE_TPU_STRICT_PALLAS", "1")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
# NOTE: do NOT be tempted by --xla_backend_optimization_level=0 to cut the
# suite's compile time: it breaks real numerics (bf16 widening in the fused
# CG, the f64-oracle IR table, fleet precond parity), and level 1 compiles
# no faster than the default.
os.environ["XLA_FLAGS"] = _flags
# Persistent compilation cache: identical programs recompile constantly across
# test processes (the suite spawns example/nox64/regression subprocesses) and
# across repeated runs. Repo-local and gitignored; the env var — not
# jax.config — so child processes inherit it. First run warms, reruns are
# ~2x faster end to end.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(__file__), "..", ".jax_cache"),
)
# Persist every compile, however small: the suite's compile mass is
# thousands of sub-second programs (measured ~17k entries, ~80MB), so the
# default 1s threshold caches almost nothing. The write tax on a cold run
# is noise; a warm rerun is ~2x faster end to end.
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")

import gc  # noqa: E402

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


@pytest.fixture(autouse=True, scope="module")
def _bound_gc_scan_cost():
    """Keep full-suite runs O(1) per test instead of O(live objects).

    One pytest process accumulates every module's compiled executables and
    jaxprs in jax's in-memory caches — millions of long-lived containers that
    CPython's automatic gen-2 collections rescan over and over, so the suite
    gets measurably slower the longer the process lives. Collect once per
    module, then freeze the survivors into the permanent generation: caches
    stay warm, the collector stops traversing them."""
    yield
    gc.collect()
    gc.freeze()

# -- quick lane (`-m quick`, ~3-4 min) --------------------------------------
# Builder-iteration subset: one fast, broad-coverage module per subsystem
# (formats, ops, kernels, solvers, distribution, examples' building blocks).
# The full suite (~25-30 min on the 8-device virtual mesh) stays the green
# evidence; this is the inner-loop check. Chosen from measured per-module
# wall times (r4 durations run) to stay under ~4 minutes total.
_QUICK_FILES = {
    "test_autopilot.py",
    "test_axon_report.py",
    "test_batch.py",
    "test_bench_evidence.py",
    "test_bsr.py",
    "test_checkpoint.py",
    "test_comm_measured.py",
    "test_coo.py",
    "test_csr_conversion.py",
    "test_csr_dot.py",
    "test_csr_elemwise.py",
    "test_csr_misc.py",
    "test_csr_sddmm.py",
    "test_csr_spmm.py",
    "test_dia.py",
    "test_dia_spmv.py",
    "test_dist.py",
    "test_elastic.py",
    "test_fleet.py",
    "test_flight.py",
    "test_grid2d.py",
    "test_history.py",
    "test_ingest.py",
    "test_io.py",
    "test_loadgen.py",
    "test_mixed.py",
    "test_multigrid.py",
    "test_pipeline.py",
    "test_plan_cache.py",
    "test_precond.py",
    "test_quantum.py",
    "test_quick_lane.py",
    "test_resilience.py",
    "test_sell_spmv.py",
    "test_shard_perf.py",
    "test_spatial.py",
    "test_telemetry.py",
    "test_tropical.py",
    "test_vault.py",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if os.path.basename(str(item.fspath)) in _QUICK_FILES:
            item.add_marker(pytest.mark.quick)
