"""Coverage-layer construction helpers vs scipy oracles.

The scipy.sparse surface beyond the reference's core: find/tril/triu,
block assembly (bmat/vstack/hstack/block_diag), kronsum, npz round trips,
and the array-API-era aliases — closing the ``coverage_report()`` gaps.
"""

import numpy as np
import pytest
import scipy.sparse as scpy

import sparse_tpu as sparse
from .utils.sample import sample_csr


def test_find():
    s = sample_csr(9, 11, density=0.3, seed=130).tocsr()
    r, c, v = sparse.find(sparse.csr_array(s))
    rs, cs, vs = scpy.find(s)
    assert np.array_equal(r, rs) and np.array_equal(c, cs)
    assert np.allclose(v, vs)


@pytest.mark.parametrize("k", [-2, 0, 1])
@pytest.mark.parametrize("fn", ["tril", "triu"])
def test_tril_triu(k, fn):
    s = sample_csr(8, 10, density=0.4, seed=131).tocsr()
    got = getattr(sparse, fn)(sparse.csr_array(s), k=k, format="csr")
    exp = getattr(scpy, fn)(s, k=k)
    assert np.allclose(np.asarray(got.todense()), exp.todense())


def test_bmat_and_stacks():
    a = sample_csr(3, 4, density=0.5, seed=132).tocsr()
    b = sample_csr(3, 2, density=0.5, seed=133).tocsr()
    c = sample_csr(5, 4, density=0.5, seed=134).tocsr()
    got = sparse.bmat(
        [[sparse.csr_array(a), sparse.csr_array(b)], [sparse.csr_array(c), None]],
        format="csr",
    )
    exp = scpy.bmat([[a, b], [c, None]], format="csr")
    assert np.allclose(np.asarray(got.todense()), exp.todense())

    gv = sparse.vstack([sparse.csr_array(a), sparse.csr_array(c)])
    ev = scpy.vstack([a, c])
    assert np.allclose(np.asarray(gv.todense()), ev.todense())

    gh = sparse.hstack([sparse.csr_array(a), sparse.csr_array(b)])
    eh = scpy.hstack([a, b])
    assert np.allclose(np.asarray(gh.todense()), eh.todense())

    gd = sparse.block_diag([sparse.csr_array(a), sparse.csr_array(b)])
    ed = scpy.block_diag([a, b])
    assert np.allclose(np.asarray(gd.todense()), ed.todense())


def test_bmat_shape_mismatch_raises():
    a = sparse.csr_array(sample_csr(3, 4, seed=135))
    b = sparse.csr_array(sample_csr(2, 2, seed=136))
    with pytest.raises(ValueError):
        sparse.bmat([[a, b]])


def test_kronsum():
    a = sample_csr(4, 4, density=0.5, seed=137).tocsr()
    b = sample_csr(3, 3, density=0.5, seed=138).tocsr()
    got = sparse.kronsum(sparse.csr_array(a), sparse.csr_array(b))
    exp = scpy.kronsum(a, b)
    assert np.allclose(np.asarray(got.todense()), exp.todense())


@pytest.mark.parametrize("fmt", ["csr", "csc", "coo"])
def test_npz_roundtrip_scipy_interop(tmp_path, fmt):
    s = sample_csr(7, 9, density=0.3, seed=139).asformat(fmt)
    ours = getattr(sparse, f"{fmt}_array")(s)
    path = tmp_path / f"m_{fmt}.npz"
    sparse.save_npz(str(path), ours)
    # scipy can read what we wrote
    back_scipy = scpy.load_npz(str(path))
    assert np.allclose(back_scipy.toarray(), s.toarray())
    # and we can read what scipy wrote
    path2 = tmp_path / f"s_{fmt}.npz"
    scpy.save_npz(str(path2), s)
    back_ours = sparse.load_npz(str(path2))
    assert back_ours.format == fmt
    assert np.allclose(np.asarray(back_ours.todense()), s.toarray())


def test_aliases_and_warnings():
    assert sparse.eye_array is sparse.eye
    assert issubclass(sparse.SparseEfficiencyWarning, sparse.SparseWarning)
    assert isinstance(sparse.csr_array(sample_csr(3, 3, seed=140)), sparse.sparray)
    a = sparse.random_array((6, 5), density=0.4, rng=3, format="csr")
    assert a.shape == (6, 5) and a.format == "csr"
    assert sparse.get_index_dtype(maxval=10) == np.int32
    assert sparse.get_index_dtype(maxval=2**40) == np.int64


def test_coverage_report_shrinks():
    rep = sparse.coverage_report()
    for name in ["bmat", "vstack", "hstack", "tril", "triu", "find",
                 "kronsum", "save_npz", "load_npz", "block_diag", "sparray"]:
        assert name in rep["implemented"], name


def test_find_coalesces_duplicates():
    """Cancelling duplicate COO entries must not appear (r2 review)."""
    a = sparse.coo_array(
        (np.array([1.0, -1.0]), (np.array([0, 0]), np.array([1, 1]))),
        shape=(2, 2),
    )
    r, c, v = sparse.find(a)
    assert r.size == 0 and c.size == 0 and v.size == 0


def test_random_array_keyword_sampler():
    """scipy-1.12-style samplers take size as a KEYWORD (r2 review)."""
    sampler = lambda *, size: np.ones(size)
    a = sparse.random_array((6, 6), density=0.5, rng=1, data_sampler=sampler)
    dense = np.asarray(a.todense())
    assert set(np.unique(dense)) <= {0.0, 1.0}
    assert np.count_nonzero(dense) == 18


def test_swapaxes_permute_dims():
    import numpy as np

    import sparse_tpu

    A = sparse_tpu.random(5, 6, 0.4, random_state=0, format="csr")
    d = np.asarray(A.todense())
    np.testing.assert_allclose(
        np.asarray(sparse_tpu.swapaxes(A, 0, 1).todense()), d.T
    )
    np.testing.assert_allclose(
        np.asarray(sparse_tpu.swapaxes(A, 0, 0).todense()), d
    )
    np.testing.assert_allclose(
        np.asarray(sparse_tpu.permute_dims(A).todense()), d.T
    )
    np.testing.assert_allclose(
        np.asarray(sparse_tpu.permute_dims(A, (0, 1)).todense()), d
    )


def test_safely_cast_index_arrays():
    import numpy as np
    import pytest

    import sparse_tpu

    A = sparse_tpu.random(5, 6, 0.4, random_state=0, format="csr")
    ix, ip = sparse_tpu.safely_cast_index_arrays(A, np.int32)
    assert ix.dtype == np.int32 and ip.dtype == np.int32
    ix8, _ = sparse_tpu.safely_cast_index_arrays(A, np.int8)
    assert ix8.dtype == np.int8
    with pytest.raises(NotImplementedError):
        sparse_tpu.expand_dims(A, 0)


def test_coverage_surface_complete():
    """Module + class surfaces report zero gaps (round 3)."""
    rep = sparse.coverage_report()
    assert rep["missing"] == []
    for cls, sub in rep["classes"].items():
        assert sub["missing"] == [], (cls, sub["missing"])


def test_isspmatrix_format_predicates():
    a = sparse.coo_array((np.array([1.0]), (np.array([0]), np.array([0]))), shape=(2, 2))
    assert sparse.isspmatrix_dok(sparse.dok_array((2, 2)))
    assert sparse.isspmatrix_lil(sparse.lil_array((2, 2)))
    assert sparse.isspmatrix_bsr(a.tocsr().tobsr(blocksize=(1, 1)))
    assert not sparse.isspmatrix_bsr(a)
    assert not sparse.isspmatrix_dok(a)
    assert not sparse.isspmatrix_lil(a)


def test_coo_tensordot_vs_numpy():
    rng = np.random.default_rng(7)
    A = scpy.random(6, 5, 0.4, random_state=rng, format="coo")
    B = scpy.random(5, 7, 0.5, random_state=rng, format="coo")
    C = scpy.random(6, 5, 0.5, random_state=rng, format="coo")
    a = sparse.coo_array((A.data, (A.row, A.col)), shape=A.shape)
    b = sparse.coo_array((B.data, (B.row, B.col)), shape=B.shape)
    c = sparse.coo_array((C.data, (C.row, C.col)), shape=C.shape)
    Ad, Bd, Cd = A.toarray(), B.toarray(), C.toarray()

    def arr(x):
        return np.asarray(x.toarray() if hasattr(x, "toarray") else x)

    np.testing.assert_allclose(arr(a.tensordot(b, axes=1)),
                               np.tensordot(Ad, Bd, axes=1), rtol=1e-6)
    np.testing.assert_allclose(arr(a.tensordot(b, axes=([1], [0]))),
                               np.tensordot(Ad, Bd, axes=([1], [0])), rtol=1e-6)
    np.testing.assert_allclose(arr(a.tensordot(c.T, axes=([0], [1]))),
                               np.tensordot(Ad, Cd.T, axes=([0], [1])), rtol=1e-6)
    np.testing.assert_allclose(float(a.tensordot(c, axes=2)),
                               np.tensordot(Ad, Cd, axes=2), rtol=1e-6)
    np.testing.assert_allclose(
        float(a.tensordot(c.T, axes=([0, 1], [1, 0]))),
        np.tensordot(Ad, Cd.T, axes=([0, 1], [1, 0])), rtol=1e-6)
    v = np.arange(5.0)
    np.testing.assert_allclose(arr(a.tensordot(v, axes=1)),
                               np.tensordot(Ad, v, axes=1), rtol=1e-6)
    with pytest.raises(ValueError):
        a.tensordot(b, axes=([0, 1], [0]))


def test_coo_tensordot_full_contraction_rejects_broadcast():
    a = sparse.coo_array(
        (np.array([1.0, 2.0]), (np.array([0, 1]), np.array([1, 0]))),
        shape=(6, 5),
    )
    with pytest.raises(ValueError):
        a.tensordot(np.ones((1, 5)), axes=2)


def test_linalg_star_import_exports_round3_surface():
    import sparse_tpu.linalg as linalg

    ns = {}
    exec("from sparse_tpu.linalg import *", ns)
    for name in ["minres", "lsmr", "tfqmr", "qmr", "splu", "spilu",
                 "factorized", "inv", "expm", "spsolve_triangular",
                 "is_sptriangular", "spbandwidth", "eigs", "lobpcg",
                 "SuperLU"]:
        assert name in ns, name
        assert name in linalg.__all__, name
