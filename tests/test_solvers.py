"""Krylov solver oracle tests vs scipy-solved systems.

Reference analogs: ``tests/integration/test_cg_solve.py``,
``test_cgs_solve.py``, ``test_bicg_solve.py`` — SPD systems built from a
seeded random sparse matrix, solved and checked by residual (the reference
asserts ``A @ x_pred ~= y``).
"""

import numpy as np
import pytest
import scipy.sparse as sp

import sparse_tpu as sparse
import sparse_tpu.linalg as linalg
from .utils.common import real_types, types
from .utils.sample import sample_csr, sample_vec


def _spd(n, dtype=np.float64, seed=0, density=0.1):
    """SPD (hermitian for complex) CSR: 0.5(S + S^H) + n*I."""
    s = sample_csr(n, n, density=density, dtype=dtype, seed=seed)
    a = 0.5 * (s + s.conjugate().T) + n * sp.identity(n, dtype=dtype)
    return a.tocsr()


@pytest.mark.parametrize("dtype", types)
def test_cg_solve(dtype):
    n = 100
    s = _spd(n, dtype=dtype)
    A = sparse.csr_array(s)
    x = sample_vec(n, dtype=dtype, seed=7)
    y = np.asarray(s @ x)
    x_pred, iters = linalg.cg(A, y, tol=1e-8)
    assert iters > 0
    assert np.allclose(np.asarray(A @ x_pred), y, atol=1e-5)


def test_cg_solve_with_callback():
    n = 64
    s = _spd(n, seed=3)
    A = sparse.csr_array(s)
    y = np.asarray(s @ sample_vec(n, seed=8))
    seen = []
    x_pred, iters = linalg.cg(A, y, tol=1e-8, callback=lambda xk: seen.append(np.asarray(xk)))
    assert len(seen) == iters
    assert np.allclose(np.asarray(A @ x_pred), y, atol=1e-6)


def test_cg_solve_with_identity_preconditioner():
    n = 64
    s = _spd(n, seed=4)
    A = sparse.csr_array(s)
    y = np.asarray(s @ sample_vec(n, seed=9))
    M = linalg.IdentityOperator((n, n), dtype=np.float64)
    x_pred, _ = linalg.cg(A, y, tol=1e-8, M=M)
    assert np.allclose(np.asarray(A @ x_pred), y, atol=1e-6)


def test_cg_solve_with_jacobi_preconditioner():
    """A real (non-identity) preconditioner must not change the answer."""
    n = 64
    s = _spd(n, seed=5)
    A = sparse.csr_array(s)
    y = np.asarray(s @ sample_vec(n, seed=10))
    dinv = 1.0 / s.diagonal()
    M = linalg.LinearOperator((n, n), matvec=lambda r: dinv * r, dtype=np.float64)
    x_pred, _ = linalg.cg(A, y, tol=1e-10, M=M)
    assert np.allclose(np.asarray(A @ x_pred), y, atol=1e-6)


def test_cg_solve_with_linear_operator():
    """Matrix-free operator (reference test_cg_solve.py:79)."""
    n = 64
    s = _spd(n, seed=6)
    y = np.asarray(s @ sample_vec(n, seed=11))
    sj = sparse.csr_array(s)
    op = linalg.LinearOperator((n, n), matvec=lambda x: sj @ x, dtype=np.float64)
    x_pred, _ = linalg.cg(op, y, tol=1e-8)
    assert np.allclose(np.asarray(sj @ x_pred), y, atol=1e-6)


def test_spsolve():
    n = 48
    s = _spd(n, seed=12)
    A = sparse.csr_array(s)
    y = np.asarray(s @ sample_vec(n, seed=13))
    x_pred = linalg.spsolve(A, y, tol=1e-10)
    assert np.allclose(np.asarray(A @ x_pred), y, atol=1e-6)


@pytest.mark.parametrize("dtype", real_types)
def test_cgs_solve(dtype):
    n = 80
    s = _spd(n, dtype=dtype, seed=14)
    A = sparse.csr_array(s)
    y = np.asarray(s @ sample_vec(n, dtype=dtype, seed=15))
    x_pred, _ = linalg.cgs(A, y, tol=1e-8)
    assert np.allclose(np.asarray(A @ x_pred), y, atol=1e-4)


@pytest.mark.parametrize("dtype", real_types)
def test_bicg_solve(dtype):
    """BiCG on a NONsymmetric diagonally-dominant system
    (reference test_bicg_solve.py:23 uses an unsymmetrized sample)."""
    n = 80
    s = sample_csr(n, n, density=0.1, dtype=dtype, seed=16)
    s = (s + n * sp.identity(n, dtype=dtype)).tocsr()
    A = sparse.csr_array(s)
    y = np.asarray(s @ sample_vec(n, dtype=dtype, seed=17))
    x_pred, _ = linalg.bicg(A, y, tol=1e-8)
    assert np.allclose(np.asarray(A @ x_pred), y, atol=1e-4)


@pytest.mark.parametrize("dtype", real_types)
def test_bicgstab_solve(dtype):
    n = 80
    s = sample_csr(n, n, density=0.1, dtype=dtype, seed=18)
    s = (s + n * sp.identity(n, dtype=dtype)).tocsr()
    A = sparse.csr_array(s)
    y = np.asarray(s @ sample_vec(n, dtype=dtype, seed=19))
    x_pred, _ = linalg.bicgstab(A, y, tol=1e-8)
    assert np.allclose(np.asarray(A @ x_pred), y, atol=1e-4)


def test_cg_x0_and_maxiter():
    """x0 is honored; maxiter caps the iteration count."""
    n = 64
    s = _spd(n, seed=20)
    A = sparse.csr_array(s)
    xstar = sample_vec(n, seed=21)
    y = np.asarray(s @ xstar)
    x_pred, iters = linalg.cg(A, y, x0=xstar, tol=1e-6, conv_test_iters=1)
    assert iters <= 1
    assert np.allclose(np.asarray(x_pred), xstar, atol=1e-6)
    _, iters = linalg.cg(A, y, maxiter=3, conv_test_iters=100)
    assert iters <= 3
