"""Fleet serving tier (ISSUE 10): mesh-sharded SolveSession.

The load-bearing contracts:

* **Parity** — batch-sharded dispatches produce the SAME per-lane
  iterates as the single-device programs (machine eps; lanes never
  exchange data, only the all-converged exit crosses the mesh), for all
  three solvers.
* **mesh=1 ≡ classic** — a one-device mesh selects the single-device
  strategy and builds a jaxpr-identical program under the same
  plan-cache key (fleet can never perturb the non-fleet path).
* **Compile economics** — exactly one plan-cache miss per
  (bucket, mesh); a second mesh is a second program.
* **Mesh-keyed warm restart** — manifest entries carry the mesh
  fingerprint; a same-topology restart replays to a zero-miss serving
  window, a different topology (or fleet off) cold-starts cleanly.
* **Resilience** — an injected dispatch drop on a sharded bucket rides
  the ordinary retry/requeue machinery to recovery.

Runs on the conftest-forced 8-device virtual CPU mesh
(``--xla_force_host_platform_device_count=8``).
"""

import numpy as np
import pytest
import scipy.sparse as sp

import jax

import sparse_tpu
from sparse_tpu import fleet, linalg, plan_cache, telemetry, vault
from sparse_tpu.batch import SolveSession
from sparse_tpu.batch import bucket as bucketing
from sparse_tpu.batch.operator import SparsityPattern
from sparse_tpu.config import settings
from sparse_tpu.parallel.mesh import mesh_fingerprint
from sparse_tpu.resilience import faults

SOLVERS = ("cg", "bicgstab", "gmres")


@pytest.fixture(autouse=True)
def _clean_state(tmp_path):
    """Scratch telemetry sink, no faults, vault off, cold plan cache."""
    faults.clear()
    old_vault = settings.vault
    old_tel = settings.telemetry
    settings.vault = ""
    telemetry.configure(str(tmp_path / "records.jsonl"))
    telemetry.reset()
    plan_cache.clear()
    yield
    faults.clear()
    settings.vault = old_vault
    settings.telemetry = old_tel
    telemetry.configure(None)
    telemetry.reset()
    plan_cache.clear()


def _traffic(B=32, n=96, seed=0, dtype=np.float64):
    rng = np.random.default_rng(seed)
    e = np.ones(n)
    mats = []
    for _ in range(B):
        A = sp.diags(
            [-e[:-1], 3.0 * e, -e[:-1]], [-1, 0, 1], format="csr"
        ).astype(dtype)
        A.setdiag((3.0 + rng.random(n)).astype(dtype))
        A.sort_indices()
        mats.append(A.tocsr())
    rhs = rng.standard_normal((B, n)).astype(dtype)
    return mats, rhs


def _mesh(S):
    return fleet.fleet_mesh(S)


# ---------------------------------------------------------------------------
# parity: sharded ≡ single-device at machine eps
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("solver", SOLVERS)
def test_sharded_parity_machine_eps(solver):
    mats, rhs = _traffic(B=32)
    s0 = SolveSession(solver, batch_max=32, fleet=False)
    X0, it0, r0 = s0.solve_many(mats, rhs, tol=1e-10)
    s1 = SolveSession(
        solver, batch_max=32, fleet="auto", fleet_mesh=_mesh(8),
        fleet_min_b=4,
    )
    X1, it1, r1 = s1.solve_many(mats, rhs, tol=1e-10)
    assert np.max(np.abs(X1 - X0)) < 1e-13
    assert np.array_equal(it0, it1)
    assert np.max(np.abs(r1 - r0)) < 1e-20
    # the solve really converged (not a trivially-equal failure)
    for A, x, b in zip(mats, X1, rhs):
        assert np.linalg.norm(A @ x - b) < 1e-8


def test_sharded_parity_f32():
    mats, rhs = _traffic(B=16, dtype=np.float32)
    s0 = SolveSession("cg", batch_max=16, fleet=False)
    X0, _, _ = s0.solve_many(mats, rhs, tol=1e-5)
    s1 = SolveSession(
        "cg", batch_max=16, fleet="auto", fleet_mesh=_mesh(8),
        fleet_min_b=4,
    )
    X1, _, _ = s1.solve_many(mats, rhs, tol=1e-5)
    assert np.max(np.abs(X1 - X0)) < 1e-6


# ---------------------------------------------------------------------------
# mesh=1 ≡ the classic single-device path
# ---------------------------------------------------------------------------
def test_mesh1_selects_single_and_jaxpr_identical():
    mats, _ = _traffic(B=1, n=64)
    pat = SparsityPattern.from_csr(mats[0])
    pol = fleet.FleetPolicy("auto", mesh=_mesh(1), min_b=2)
    assert not pol.enabled
    plan = pol.decide(pat, 8, "cg")
    assert plan.strategy == "single"
    assert plan.key_suffix == ""

    s0 = SolveSession("cg", fleet=False)
    s1 = SolveSession("cg", fleet="auto", fleet_mesh=_mesh(1), fleet_min_b=2)
    B, n = 8, pat.shape[0]
    args = (
        np.zeros((B, pat.nnz)), np.zeros((B, n)), np.zeros((B, n)),
        np.zeros(B), 100,
    )
    j0 = jax.make_jaxpr(s0._build_program(pat, B, np.dtype(np.float64)))(
        *args
    )
    j1 = jax.make_jaxpr(
        s1._build_program(pat, B, np.dtype(np.float64), plan=plan)
    )(*args)
    assert str(j0) == str(j1)


def test_fleet_off_env_default_is_single():
    ses = SolveSession("cg")
    assert not ses.fleet.enabled
    st = ses.session_stats()
    assert st["mesh"] == {"enabled": False, "devices": 1}


# ---------------------------------------------------------------------------
# compile economics: one miss per (bucket, mesh)
# ---------------------------------------------------------------------------
def test_one_plan_cache_miss_per_bucket_and_mesh():
    mats, rhs = _traffic(B=16)
    pat = SparsityPattern.from_csr(mats[0])
    pat.sell_pack()  # warm the pattern pack outside the window
    vals = [np.asarray(A.data) for A in mats]

    def serve(ses):
        tickets = [
            ses.submit(v, b, tol=1e-10, pattern=pat)
            for v, b in zip(vals, rhs)
        ]
        ses.flush()
        return [t.result() for t in tickets]

    s8 = SolveSession(
        "cg", batch_max=16, fleet="auto", fleet_mesh=_mesh(8), fleet_min_b=4
    )
    snap = plan_cache.snapshot()
    serve(s8)
    d1 = plan_cache.delta(snap)
    assert d1["misses"] == 1  # exactly the bucket program
    snap = plan_cache.snapshot()
    serve(s8)
    assert plan_cache.delta(snap)["misses"] == 0  # warm re-dispatch

    # a DIFFERENT mesh is a different program: one more miss, once
    s4 = SolveSession(
        "cg", batch_max=16, fleet="auto", fleet_mesh=_mesh(4), fleet_min_b=4
    )
    snap = plan_cache.snapshot()
    serve(s4)
    assert plan_cache.delta(snap)["misses"] == 1
    snap = plan_cache.snapshot()
    serve(s4)
    assert plan_cache.delta(snap)["misses"] == 0


# ---------------------------------------------------------------------------
# bucketing: mesh-multiple rounding + pad accounting (satellite bugfix)
# ---------------------------------------------------------------------------
def test_bucket_batch_mesh_multiple():
    assert bucketing.bucket_batch(5, "pow2", 64, multiple_of=8) == 8
    assert bucketing.bucket_batch(9, "pow2", 64, multiple_of=8) == 16
    assert bucketing.bucket_batch(5, "exact", 64, multiple_of=8) == 8
    assert bucketing.bucket_batch(12, "exact", 64, multiple_of=8) == 16
    # a cap below the mesh size rounds UP (never an unshardable bucket)
    assert bucketing.bucket_batch(3, "pow2", 4, multiple_of=8) == 8
    # no constraint = unchanged classic behavior
    assert bucketing.bucket_batch(5, "pow2", 64) == 8
    assert bucketing.bucket_batch(5, "exact", 64) == 5


def test_mesh_pad_lanes_instant_converge_and_occupancy():
    mats, rhs = _traffic(B=5)  # pow2 would say 8; mesh multiple keeps 8
    settings.telemetry = True
    ses = SolveSession(
        "cg", batch_max=64, fleet="auto", fleet_mesh=_mesh(8),
        fleet_min_b=4, conv_test_iters=5,
    )
    X, iters, _ = ses.solve_many(mats, rhs, tol=1e-10)
    assert X.shape == (5, rhs.shape[1])
    ev = [e for e in telemetry.events() if e["kind"] == "batch.dispatch"][-1]
    assert ev["bucket"] == 8 and ev["batch"] == 5 and ev["pad_waste"] == 3
    fd = [e for e in telemetry.events() if e["kind"] == "fleet.dispatch"][-1]
    # pad lanes are excluded from the device occupancy surface
    assert fd["device_lanes"] == [1, 1, 1, 1, 1, 0, 0, 0]
    occ = ses.session_stats()["device_occupancy"]
    assert occ == [1.0, 1.0, 1.0, 1.0, 1.0, 0.0, 0.0, 0.0]
    # pad lanes froze at the first conv test, never at maxiter
    shards = [e for e in telemetry.events() if e["kind"] == "fleet.shard"]
    assert len(shards) >= 8
    for A, x, b in zip(mats, X, rhs):
        assert np.linalg.norm(A @ x - b) < 1e-8


def test_session_stats_mesh_dimension():
    ses = SolveSession(
        "cg", fleet="auto", fleet_mesh=_mesh(8), fleet_min_b=4
    )
    st = ses.session_stats()
    assert st["mesh"]["devices"] == 8
    assert st["mesh"]["fingerprint"] == mesh_fingerprint(_mesh(8))
    assert st["device_occupancy"] == []  # nothing dispatched yet
    assert "device_occupancy" in st and "mesh" in st


# ---------------------------------------------------------------------------
# comm accounting: measured psum bytes reconcile with the model
# ---------------------------------------------------------------------------
def test_sharded_comm_measured_within_tolerance():
    mats, rhs = _traffic(B=16)
    settings.telemetry = True
    ses = SolveSession(
        "cg", batch_max=16, fleet="auto", fleet_mesh=_mesh(8),
        fleet_min_b=4, conv_test_iters=5,
    )
    ses.solve_many(mats, rhs, tol=1e-10)
    evs = [
        e for e in telemetry.events()
        if e["kind"] == "comm.measured" and e.get("site") == "fleet.batch"
    ]
    assert evs, "sharded dispatch emitted no comm.measured event"
    ev = evs[-1]
    assert ev["S"] == 8 and ev["exact"]
    assert abs(ev["divergence_pct"]) <= 10.0


# ---------------------------------------------------------------------------
# warm restart: mesh fingerprint gates replay
# ---------------------------------------------------------------------------
def test_warm_restart_matching_vs_mismatched_mesh(tmp_path):
    settings.vault = str(tmp_path / "vault")
    mats, rhs = _traffic(B=16)
    s1 = SolveSession(
        "cg", batch_max=16, fleet="auto", fleet_mesh=_mesh(8), fleet_min_b=4
    )
    s1.solve_many(mats, rhs, tol=1e-10)
    ents = vault.manifest_entries()
    assert [e.get("mesh") for e in ents] == [mesh_fingerprint(_mesh(8))]
    assert ents[0].get("strategy") == "batch"

    # same topology: replay -> zero-miss serving window
    plan_cache.clear()
    s2 = SolveSession(
        "cg", batch_max=16, fleet="auto", fleet_mesh=_mesh(8),
        fleet_min_b=4, warm_start=True,
    )
    assert s2.warm_replayed == 1
    snap = plan_cache.snapshot()
    X2, _, _ = s2.solve_many(mats, rhs, tol=1e-10)
    assert plan_cache.delta(snap)["misses"] == 0

    # different topology: entry skipped, clean cold start
    plan_cache.clear()
    s3 = SolveSession(
        "cg", batch_max=16, fleet="auto", fleet_mesh=_mesh(4),
        fleet_min_b=4, warm_start=True,
    )
    assert s3.warm_replayed == 0
    X3, _, _ = s3.solve_many(mats, rhs, tol=1e-10)
    assert np.max(np.abs(X3 - X2)) < 1e-13

    # fleet off entirely: mesh-keyed entry also skipped
    plan_cache.clear()
    s4 = SolveSession("cg", batch_max=16, fleet=False, warm_start=True)
    assert s4.warm_replayed == 0


# ---------------------------------------------------------------------------
# resilience: injected dispatch drop on a sharded bucket
# ---------------------------------------------------------------------------
def test_injected_dispatch_drop_recovers():
    mats, rhs = _traffic(B=16)
    settings.telemetry = True
    ses = SolveSession(
        "cg", batch_max=16, fleet="auto", fleet_mesh=_mesh(8),
        fleet_min_b=4, dispatch_attempts=2,
    )
    faults.configure("drop:dispatch:p=1,n=1")
    try:
        X, iters, r2 = ses.solve_many(mats, rhs, tol=1e-10)
    finally:
        faults.clear()
    for A, x, b in zip(mats, X, rhs):
        assert np.linalg.norm(A @ x - b) < 1e-8
    kinds = {e["kind"] for e in telemetry.events()}
    assert "fault.injected" in kinds
    assert "fleet.dispatch" in kinds  # the retry still sharded


# ---------------------------------------------------------------------------
# row-sharded strategy: oversized single systems
# ---------------------------------------------------------------------------
def test_row_sharded_submission_parity():
    n = 1024
    e = np.ones(n)
    A = sp.diags([-e[:-1], 3.0 * e, -e[:-1]], [-1, 0, 1], format="csr")
    rng = np.random.default_rng(3)
    A.setdiag(3.0 + rng.random(n))
    A.sort_indices()
    A = A.tocsr()
    b = rng.standard_normal(n)
    settings.telemetry = True
    ses = SolveSession(
        "cg", fleet="auto", fleet_mesh=_mesh(8), row_shard_min_n=512
    )
    t = ses.submit(A, b, tol=1e-9)
    x, iters, resid2 = t.result()
    assert t.converged and t.solver == "cg"
    assert np.linalg.norm(A @ x - b) < 1e-8
    x0, _ = linalg.cg(sparse_tpu.csr_array(A), b, tol=1e-9, maxiter=n * 10)
    assert np.max(np.abs(x - np.asarray(x0))) < 1e-10
    fd = [e for e in telemetry.events() if e["kind"] == "fleet.dispatch"]
    assert fd and fd[-1]["strategy"] == "row" and fd[-1]["S"] == 8
    # a row-sharded system spans every device
    assert ses.session_stats()["device_occupancy"] == [1.0] * 8


def test_row_threshold_not_met_stays_single():
    n = 64
    e = np.ones(n)
    A = sp.diags([-e[:-1], 3.0 * e, -e[:-1]], [-1, 0, 1], format="csr").tocsr()
    b = np.ones(n)
    settings.telemetry = True
    ses = SolveSession(
        "cg", fleet="auto", fleet_mesh=_mesh(8), row_shard_min_n=4096
    )
    t = ses.submit(A, b, tol=1e-9)
    x, _, _ = t.result()
    assert np.linalg.norm(A @ x - b) < 1e-8
    assert not [
        e for e in telemetry.events() if e["kind"] == "fleet.dispatch"
    ]


# ---------------------------------------------------------------------------
# policy plumbing
# ---------------------------------------------------------------------------
def test_policy_modes_and_resolve():
    assert fleet.FleetPolicy("").mode == ""
    assert fleet.FleetPolicy("off").mode == ""
    for sp_ in ("1", "on", "true", "auto"):
        assert fleet.FleetPolicy(sp_, mesh=_mesh(2)).mode == "auto"
    assert fleet.FleetPolicy("batch", mesh=_mesh(2)).mode == "batch"
    with pytest.raises(ValueError):
        fleet.FleetPolicy("bogus", mesh=_mesh(2))
    pol = fleet.FleetPolicy.resolve(True, mesh=_mesh(8), min_b=3)
    assert pol.enabled and pol.min_b == 3
    assert fleet.FleetPolicy.resolve(pol) is pol
    assert not fleet.FleetPolicy.resolve(False).enabled


def test_policy_mode_restriction():
    mats, _ = _traffic(B=1, n=64)
    pat = SparsityPattern.from_csr(mats[0])
    row_only = fleet.FleetPolicy("row", mesh=_mesh(8), min_b=2, row_min_n=32)
    assert row_only.decide(pat, 16, "cg").strategy == "single"
    assert row_only.decide(pat, 1, "cg").strategy == "row"
    assert row_only.bucket_multiple() == 1
    batch_only = fleet.FleetPolicy(
        "batch", mesh=_mesh(8), min_b=2, row_min_n=32
    )
    assert batch_only.decide(pat, 16, "cg").strategy == "batch"
    assert batch_only.decide(pat, 1, "cg").strategy == "single"
    assert batch_only.bucket_multiple() == 8
    # row never triggers for non-cg primaries (dist only carries cg)
    auto = fleet.FleetPolicy("auto", mesh=_mesh(8), min_b=2, row_min_n=32)
    assert auto.decide(pat, 1, "gmres").strategy == "single"


def test_device_lane_counts():
    assert fleet.device_lane_counts(5, 8, 8) == [1, 1, 1, 1, 1, 0, 0, 0]
    assert fleet.device_lane_counts(32, 32, 8) == [4] * 8
    assert fleet.device_lane_counts(9, 16, 4) == [4, 4, 1, 0]
    assert fleet.device_lane_counts(1, 1, 1) == [1]


def test_mesh_fingerprint_stability():
    fp8 = mesh_fingerprint(_mesh(8))
    assert fp8 == mesh_fingerprint(_mesh(8))
    assert fp8 != mesh_fingerprint(_mesh(4))
    assert fp8 == "cpu:8:lanes"
