"""Generic Krylov solvers over mesh-sharded operators.

``DistCSR.as_operator()`` exposes the padded SpMV as a LinearOperator, so
``linalg.cg``/``cgs``/``bicgstab``/``gmres`` trace their whole solve over
sharded arrays — GSPMD inserts the psum for every reduction. This is the
framework's "every solver is distributed" property (the reference gets it
from Legion's implicit partitioning).
"""

import numpy as np
import pytest

import sparse_tpu as sparse
import sparse_tpu.linalg as linalg
from sparse_tpu.models.poisson import laplacian_2d_csr_host
from sparse_tpu.parallel.dist import shard_csr
from sparse_tpu.parallel.mesh import get_mesh


def _setup(num_shards, n=24):
    A = laplacian_2d_csr_host(n, dtype=np.float64)
    # SPD and diagonally dominant after a shift
    mesh = get_mesh(num_shards)
    D = shard_csr(A, mesh=mesh, balanced=True)
    rng = np.random.default_rng(0)
    x_true = rng.standard_normal(A.shape[0])
    b = np.asarray(A @ x_true)
    return A, D, x_true, b


@pytest.mark.parametrize("num_shards", [2, 8])
@pytest.mark.parametrize("solver", ["cg", "cgs", "bicgstab", "gmres"])
def test_generic_solver_on_mesh_operator(num_shards, solver):
    A, D, x_true, b = _setup(num_shards)
    op = D.as_operator()
    bp = D.pad_out_vector(b)
    fn = getattr(linalg, solver)
    xp = np.asarray(fn(op, bp, tol=1e-10)[0])
    x = D.unpad_vector(xp)
    assert np.allclose(x, x_true, atol=1e-5)


@pytest.mark.parametrize("num_shards", [2, 8])
def test_bicg_lsqr_on_mesh_operator(num_shards):
    """Adjoint-needing solvers via the transpose layout (with_rmatvec)."""
    A, D, x_true, b = _setup(num_shards)
    op = D.as_operator(with_rmatvec=True, source=A)
    bp = D.pad_out_vector(b)
    xp = np.asarray(linalg.bicg(op, bp, tol=1e-10)[0])
    assert np.allclose(D.unpad_vector(xp), x_true, atol=1e-5)
    xl = np.asarray(linalg.lsqr(op, bp, atol=1e-12, btol=1e-12)[0])
    assert np.allclose(D.unpad_vector(xl), x_true, atol=1e-4)


def test_operator_requires_square():
    import scipy.sparse as sp

    rect = sparse.csr_array(sp.random(10, 6, density=0.5, random_state=0, format="csr"))
    D = shard_csr(rect, mesh=get_mesh(2))
    with pytest.raises(ValueError):
        D.as_operator()


@pytest.mark.parametrize("num_shards", [2, 8])
@pytest.mark.parametrize("solver", ["minres", "tfqmr", "lgmres", "gcrotmk"])
def test_round3_solvers_on_mesh_operator(num_shards, solver):
    """The round-3 solver additions inherit the same "every solver is
    distributed" property: they only see a LinearOperator, so the mesh
    SpMV + GSPMD psums carry them unchanged."""
    A, D, x_true, b = _setup(num_shards)
    op = D.as_operator()
    bp = D.pad_out_vector(b)
    xp = np.asarray(getattr(linalg, solver)(op, bp, tol=1e-10)[0])
    assert np.allclose(D.unpad_vector(xp), x_true, atol=1e-4)


@pytest.mark.parametrize("num_shards", [2])
def test_qmr_lsmr_on_mesh_operator(num_shards):
    A, D, x_true, b = _setup(num_shards)
    op = D.as_operator(with_rmatvec=True, source=A)
    bp = D.pad_out_vector(b)
    xq = np.asarray(linalg.qmr(op, bp, tol=1e-10)[0])
    assert np.allclose(D.unpad_vector(xq), x_true, atol=1e-4)
    xl = np.asarray(linalg.lsmr(op, bp, atol=1e-12, btol=1e-12)[0])
    assert np.allclose(D.unpad_vector(xl), x_true, atol=1e-4)
