"""parallel.multigrid: mesh-sharded V-cycle machinery (unit level).

The examples exercise the full AMG/GMG drivers; these tests pin the shared
component directly — hierarchy sharding shapes, V-cycle as a dist_cg
preconditioner, and that the preconditioner actually helps.
"""

import numpy as np
import pytest
import scipy.sparse as sp

import sparse_tpu as sparse
from sparse_tpu.parallel.dist import dist_cg
from sparse_tpu.parallel.mesh import get_mesh
from sparse_tpu.parallel.multigrid import make_dist_vcycle, shard_hierarchy


def _poisson1d(n, dtype=np.float64):
    return sparse.csr_array(
        sp.diags([-1.0, 2.0, -1.0], [-1, 0, 1], shape=(n, n), format="csr").astype(dtype)
    )


def _injection(nf):
    nc = nf // 2
    cols = (np.arange(nc) * 2).astype(np.int64)
    R = sparse.csr_array.from_parts(
        np.ones(nc), cols, np.arange(nc + 1, dtype=np.int64), (nc, nf)
    )
    return R


def _linear_rp(nf):
    """Standard 1-D linear interpolation P (1/2, 1, 1/2) and R = P^T / 2."""
    nc = nf // 2
    i = np.arange(nc)
    rows = np.concatenate([2 * i, np.maximum(2 * i - 1, 0), np.minimum(2 * i + 1, nf - 1)])
    cols = np.concatenate([i, i, i])
    vals = np.concatenate([np.ones(nc), np.full(nc, 0.5), np.full(nc, 0.5)])
    Ps = sp.coo_matrix((vals, (rows, cols)), shape=(nf, nc)).tocsr()
    P = sparse.csr_array(Ps)
    R = sparse.csr_array(Ps.T.tocsr() * 0.5)
    return R, P


@pytest.mark.parametrize("S", [2, 8])
def test_shard_hierarchy_shapes(S):
    mesh = get_mesh(S)
    nf = 64
    A0 = _poisson1d(nf)
    R = _injection(nf)
    P = R.T.tocsr()
    A1 = R @ A0 @ P
    ops, splits = shard_hierarchy([A0, A1], [(R, P)], mesh)
    assert len(ops) == 2 and len(splits) == 2
    Ad0, Rd, Pd = ops[0]
    assert Ad0.m_pad % S == 0
    assert Rd.m_pad == ops[1][0].m_pad  # R lands in the coarse layout
    assert ops[1][1] is None and ops[1][2] is None


def test_vcycle_preconditions_dist_cg():
    mesh = get_mesh(8)
    nf = 128
    A0 = _poisson1d(nf)
    R, P = _linear_rp(nf)
    A1 = R @ A0 @ P
    ops, _ = shard_hierarchy([A0, A1], [(R, P)], mesh)
    weights = []
    for Ad, lvA in ((ops[0][0], A0), (ops[1][0], A1)):
        D = np.asarray(lvA.diagonal())
        weights.append((2.0 / 3.0) / (Ad.pad_out_vector(D - 1.0) + 1.0))
    M = make_dist_vcycle(ops, weights, coarse_apply=lambda rp: weights[-1] * rp)

    b = np.ones(nf)
    A0d = ops[0][0]
    _, it_plain, conv_plain = dist_cg(A0d, b, tol=1e-8, maxiter=400,
                                      conv_test_iters=5)
    xp, it_pre, conv_pre = dist_cg(A0d, b, tol=1e-8, maxiter=400,
                                   conv_test_iters=5, M=M)
    assert conv_plain and conv_pre
    x = A0d.unpad_vector(xp)
    resid = np.linalg.norm(np.asarray(A0 @ x) - b)
    assert resid < 1e-5
    assert it_pre < it_plain  # the V-cycle must actually help


def test_vcycle_padded_slots_stay_zero():
    mesh = get_mesh(8)
    nf = 100  # not divisible by 8 -> real padding
    A0 = _poisson1d(nf)
    R = _injection(nf)
    P = R.T.tocsr()
    A1 = R @ A0 @ P
    ops, _ = shard_hierarchy([A0, A1], [(R, P)], mesh)
    weights = []
    for Ad, lvA in ((ops[0][0], A0), (ops[1][0], A1)):
        D = np.asarray(lvA.diagonal())
        weights.append((2.0 / 3.0) / (Ad.pad_out_vector(D - 1.0) + 1.0))
    M = make_dist_vcycle(ops, weights, coarse_apply=lambda rp: weights[-1] * rp)
    A0d = ops[0][0]
    rp = A0d.pad_out_vector(np.random.default_rng(0).standard_normal(nf))
    out = np.asarray(M(rp))
    # zero out the real slots; anything left is pad contamination
    mask = np.asarray(A0d.pad_out_vector(np.ones(nf)))
    assert np.allclose(out * (1 - mask), 0.0)


def test_replicated_tail_matches_sharded_cycle():
    """The dense replicated coarse tail (zero per-level collectives — the
    fix for the reference's coarse-level weak-scaling collapse, SURVEY §6)
    computes the same V-cycle as the fully-sharded construction."""
    from sparse_tpu.parallel.multigrid import make_replicated_tail

    mesh = get_mesh(8)
    nf = 256
    A0 = _poisson1d(nf)
    R0, P0 = _linear_rp(nf)
    A1 = R0 @ A0 @ P0
    R1, P1 = _linear_rp(nf // 2)
    A2 = R1 @ A1 @ P1
    As, RPs = [A0, A1, A2], [(R0, P0), (R1, P1)]

    def w_host(A):
        return (2.0 / 3.0) / np.asarray(A.diagonal())

    # fully sharded: all 3 levels DistCSR, bottom = smoother application
    ops_f, _ = shard_hierarchy(As, RPs, mesh)
    wf = [
        (2.0 / 3.0) / (ops_f[i][0].pad_out_vector(np.asarray(As[i].diagonal()) - 1.0) + 1.0)
        for i in range(3)
    ]
    M_full = make_dist_vcycle(ops_f, wf, coarse_apply=lambda rp: wf[-1] * rp)

    # replicated tail from level 1 down, same math
    ops_t, spl_t = shard_hierarchy(As[:2], RPs[:1], mesh)
    tail = make_replicated_tail(
        As[1:], RPs[1:], [w_host(A1)], spl_t[-1], ops_t[-1][0].R,
        bottom="smooth", bottom_weight=w_host(A2),
    )
    M_tail = make_dist_vcycle(ops_t, [wf[0], None], tail)

    rp = ops_f[0][0].pad_out_vector(
        np.sin(np.arange(nf) * 0.1).astype(np.float64)
    )
    out_full = ops_f[0][0].unpad_vector(np.asarray(M_full(rp)))
    out_tail = ops_t[0][0].unpad_vector(np.asarray(M_tail(rp)))
    np.testing.assert_allclose(out_tail, out_full, rtol=1e-10, atol=1e-12)


def test_replicated_tail_solve_bottom():
    """bottom='solve' (LU direct) tail preconditions dist_cg to fewer
    iterations than the plain solve."""
    from sparse_tpu.parallel.multigrid import make_replicated_tail

    mesh = get_mesh(8)
    nf = 128
    A0 = _poisson1d(nf)
    R, P = _linear_rp(nf)
    A1 = R @ A0 @ P
    ops, spl = shard_hierarchy([A0, A1], [(R, P)], mesh)
    w0 = (2.0 / 3.0) / (
        ops[0][0].pad_out_vector(np.asarray(A0.diagonal()) - 1.0) + 1.0
    )
    tail = make_replicated_tail(
        [A1], [], [], spl[-1], ops[-1][0].R, bottom="solve"
    )
    M = make_dist_vcycle(ops, [w0, None], tail)
    b = np.ones(nf)
    _, it_plain, _ = dist_cg(ops[0][0], b, tol=1e-8, maxiter=400,
                             conv_test_iters=5)
    xp, it_pre, conv = dist_cg(ops[0][0], b, tol=1e-8, maxiter=400,
                               conv_test_iters=5, M=M)
    assert conv
    x = ops[0][0].unpad_vector(xp)
    assert np.linalg.norm(np.asarray(A0 @ x) - b) < 1e-5
    assert it_pre < it_plain
