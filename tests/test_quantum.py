"""Quantum MIS Hamiltonian tests against brute-force oracles.

Reference analog: the quantum workload (SURVEY §3.5). The oracle here is a
direct itertools enumeration of independent sets.
"""

from itertools import combinations

import networkx as nx
import numpy as np
import pytest

from sparse_tpu import quantum


def brute_independent_sets(graph, k):
    nodes = list(graph.nodes)
    out = []
    for comb in combinations(nodes, k):
        if not any(graph.has_edge(u, v) for u, v in combinations(comb, 2)):
            out.append(frozenset(comb))
    return set(out)


def sets_to_frozensets(sets, n):
    B = quantum._bits_to_bool(sets, n)
    return [frozenset(np.nonzero(row)[0].tolist()) for row in B]


GRAPHS = [
    nx.cycle_graph(6),
    nx.path_graph(7),
    nx.complete_graph(5),
    nx.erdos_renyi_graph(10, 0.4, seed=3),
    nx.empty_graph(4),
]


@pytest.mark.parametrize("graph", GRAPHS)
def test_enumeration_matches_bruteforce(graph):
    n = graph.number_of_nodes()
    sets = queues = None
    for k in range(1, n + 1):
        sets, queues = quantum.enumerate_independent_sets(graph, k, sets, queues)
        expect = brute_independent_sets(graph, k)
        got = sets_to_frozensets(sets, n)
        assert len(got) == len(set(got)), "duplicate sets"
        assert set(got) == expect, f"k={k}"
        if sets.shape[0] == 0 or quantum.popcount(queues).sum() == 0:
            break


@pytest.mark.parametrize("graph", GRAPHS)
def test_independence_polynomial(graph):
    n = graph.number_of_nodes()
    ip = quantum.independence_polynomial(graph)
    expect = [1]
    for k in range(1, n + 1):
        cnt = len(brute_independent_sets(graph, k))
        if cnt == 0:
            break
        expect.append(cnt)
    assert ip == expect


def test_driver_hamiltonian_structure():
    g = nx.cycle_graph(5)
    drv = quantum.HamiltonianDriver(graph=g)
    H = drv.hamiltonian
    nstates = drv.nstates
    assert H.shape == (nstates, nstates)
    Hd = np.asarray(H.toarray())
    # symmetric 0/1 matrix
    np.testing.assert_array_equal(Hd, Hd.T)
    assert set(np.unique(Hd.real)) <= {0.0, 1.0}
    # every size-k set connects to exactly k subsets + supersets:
    # row degree of a state of size k is k + #extensions; check total edge
    # count = 2 * sum_k k * ip[k]
    expected_edges = 2 * sum(k * c for k, c in enumerate(drv.ip))
    assert H.nnz == expected_edges
    # no diagonal entries
    assert np.all(Hd.diagonal() == 0)


def test_mis_hamiltonian_diagonal():
    g = nx.cycle_graph(5)
    drv = quantum.HamiltonianDriver(graph=g)
    mis = quantum.HamiltonianMIS(graph=g, poly=drv.ip)
    assert mis.nstates == drv.nstates
    d = np.asarray(mis.hamiltonian.toarray()).real
    np.testing.assert_array_equal(d, np.diag(d.diagonal()))
    # C5 has MIS size 2
    assert mis.optimum == 2.0
    assert mis.minimum_energy == 0.0
    # last state is the null state (level 0)
    assert d[-1, -1] == 0.0


def test_driver_levels_consistent_with_mis_ordering():
    """The flipped state ordering must agree between driver and MIS diag:
    states connected by the driver differ by exactly one in MIS cost."""
    g = nx.erdos_renyi_graph(8, 0.35, seed=1)
    drv = quantum.HamiltonianDriver(graph=g)
    mis = quantum.HamiltonianMIS(graph=g, poly=drv.ip)
    C = np.asarray(mis.hamiltonian.toarray()).real.diagonal()
    H = drv.hamiltonian.tocoo()
    rows, cols = np.asarray(H.row), np.asarray(H.col)
    assert np.all(np.abs(C[rows] - C[cols]) == 1)


def test_evolution_preserves_norm():
    """-i H evolution through solve_ivp keeps the state normalized."""
    from sparse_tpu import integrate

    g = nx.cycle_graph(6)
    drv = quantum.HamiltonianDriver(graph=g, dtype=np.complex128)
    H = drv.hamiltonian
    y0 = np.zeros(drv.nstates, dtype=np.complex128)
    y0[-1] = 1.0  # start in the null state
    out = integrate.solve_ivp(
        lambda t, y: -1j * (H @ y), (0, 1.0), y0, method="DOP853",
        rtol=1e-9, atol=1e-11,
    )
    assert out.success
    norms = np.linalg.norm(np.asarray(out.y), axis=0)
    np.testing.assert_allclose(norms, 1.0, rtol=1e-7)
