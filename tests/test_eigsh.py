"""eigsh oracle tests vs numpy's dense symmetric eigensolver.

Reference analog: ``tests/integration/test_eigsh.py:24`` (Lanczos extremal
eigenvalues of a random symmetric matrix vs the dense oracle).
"""

import numpy as np
import pytest
import scipy.sparse as sp

import sparse_tpu as sparse
import sparse_tpu.linalg as linalg
from .utils.sample import sample_csr


def _sym(n, seed=0, density=0.15):
    s = sample_csr(n, n, density=density, seed=seed)
    return (0.5 * (s + s.T)).tocsr()


@pytest.mark.parametrize("which", ["LM", "SM", "LA", "SA"])
def test_eigsh_extremal(which):
    n, k = 60, 4
    s = _sym(n, seed=40)
    dense_w = np.linalg.eigvalsh(s.toarray())
    w_ret, v = linalg.eigsh(sparse.csr_array(s), k=k, which=which, tol=1e-9)
    w_ret = np.asarray(w_ret)
    w = np.sort(w_ret)
    if which == "LM":
        exp = np.sort(dense_w[np.argsort(np.abs(dense_w))[-k:]])
    elif which == "SM":
        exp = np.sort(dense_w[np.argsort(np.abs(dense_w))[:k]])
    elif which == "LA":
        exp = dense_w[-k:]
    else:
        exp = dense_w[:k]
    assert np.allclose(w, exp, atol=1e-5)
    # eigenvector residuals (order as returned)
    A = s.toarray()
    Vr = np.asarray(v)
    for i in range(k):
        ri = A @ Vr[:, i] - float(w_ret[i]) * Vr[:, i]
        assert np.linalg.norm(ri) < 1e-4 * max(1.0, abs(float(w_ret[i])))


def test_eigsh_no_vectors():
    n = 40
    s = _sym(n, seed=41)
    w = linalg.eigsh(sparse.csr_array(s), k=3, return_eigenvectors=False, tol=1e-9)
    dense_w = np.linalg.eigvalsh(s.toarray())
    exp = np.sort(dense_w[np.argsort(np.abs(dense_w))[-3:]])
    assert np.allclose(np.sort(np.asarray(w)), exp, atol=1e-5)


def test_eigsh_laplacian_smallest():
    """The 1-D Laplacian's extreme eigenvalues are known analytically."""
    n = 32
    L = sp.diags([-1.0, 2.0, -1.0], [-1, 0, 1], shape=(n, n)).tocsr()
    w = linalg.eigsh(sparse.csr_array(L), k=1, which="LA", return_eigenvectors=False, tol=1e-10)
    exact = 2 - 2 * np.cos(np.pi * n / (n + 1))
    assert np.allclose(np.asarray(w), [exact], atol=1e-6)


@pytest.mark.parametrize("mtx", ["banded.mtx", "graph.mtx"])
def test_eigsh_matvec_parity_with_scipy(mtx):
    """VERDICT r2 #9: thick restart keeps the locked Ritz block across
    cycles, so the matvec count stays within 2x of scipy's ARPACK on the
    testdata matrices at k=6 (a single-vector restart needs many times
    more)."""
    import os

    import scipy.io
    import scipy.sparse.linalg as sla

    path = os.path.join(os.path.dirname(__file__), "..", "testdata", mtx)
    s = scipy.io.mmread(path).tocsr().astype(np.float64)
    s = (0.5 * (s + s.T)).tocsr()
    n = s.shape[0]
    k = min(6, n - 2)

    counts = {"ours": 0, "scipy": 0}

    def make_op(key):
        def mv(x):
            counts[key] += 1
            return s @ np.asarray(x)

        return sla.LinearOperator(s.shape, matvec=mv, dtype=s.dtype)

    w_sp = sla.eigsh(make_op("scipy"), k=k, which="LM",
                     return_eigenvectors=False)

    # np.asarray(x) is untraceable, forcing eigsh onto its host-loop path —
    # so the counter sees EVERY operator application (on the jitted device
    # path a Python matvec runs only at trace time and counts compiles,
    # not matvecs; the cycle structure being measured is identical)
    def mv_ours(x):
        counts["ours"] += 1
        return s @ np.asarray(x)

    ours = linalg.LinearOperator(s.shape, matvec=mv_ours, dtype=s.dtype)
    w_us = linalg.eigsh(ours, k=k, which="LM", tol=1e-8,
                        return_eigenvectors=False)
    assert np.allclose(np.sort(np.asarray(w_us)), np.sort(w_sp), rtol=1e-6,
                       atol=1e-9)
    assert counts["ours"] <= 2 * max(counts["scipy"], 1), counts


def test_eigsh_complex_hermitian():
    """Review r3: a complex Hermitian operator needs a complex Lanczos
    basis (real-basis projection onto Re(A) returns wrong eigenvalues)."""
    n = 50
    rng = np.random.default_rng(54)
    M = rng.normal(size=(n, n)) + 1j * rng.normal(size=(n, n))
    H = (M + M.conj().T) / 2
    Hs = sp.csr_array(np.where(np.abs(H) > 1.2, H, 0))
    Hs = ((Hs + Hs.conj().T) / 2).tocsr()
    dense_w = np.linalg.eigvalsh(Hs.toarray())
    w, V = linalg.eigsh(sparse.csr_array(Hs), k=4, which="LA", tol=1e-9)
    np.testing.assert_allclose(np.sort(np.asarray(w)), dense_w[-4:],
                               rtol=1e-6, atol=1e-8)
    # residual check confirms the eigenVECTORS are complex and correct
    Vr = np.asarray(V)
    for i in range(4):
        r = Hs @ Vr[:, i] - np.asarray(w)[i] * Vr[:, i]
        assert np.linalg.norm(r) < 1e-5
