"""2-D processor-grid algorithms on the virtual 8-device mesh.

Reference analogs: ``sparse/spatial.py:48-84`` (cdist launch grid) and
``sparse/quantum.py:86-107`` (CREATE_HAMILTONIANS 2-D replication).
The virtual mesh is 4x2 (factor_int(8)).
"""

import numpy as np
import pytest

import sparse_tpu.spatial as spatial
from sparse_tpu.parallel import cdist_2d, get_mesh_2d, lookup_2d
from .utils.sample import sample_dense


@pytest.mark.parametrize("m,n,k", [(37, 29, 5), (8, 8, 3), (65, 3, 7)])
def test_cdist_2d_matches_single_device(m, n, k):
    XA = sample_dense(m, k, seed=120)
    XB = sample_dense(n, k, seed=121)
    got = cdist_2d(XA, XB)
    exp = np.asarray(spatial.cdist(XA, XB))
    assert got.shape == (m, n)
    assert np.allclose(got, exp, atol=1e-10)


def test_cdist_mesh_kwarg():
    XA = sample_dense(19, 4, seed=122)
    XB = sample_dense(23, 4, seed=123)
    mesh = get_mesh_2d()
    got = spatial.cdist(XA, XB, mesh=mesh)
    exp = np.asarray(spatial.cdist(XA, XB))
    assert np.allclose(np.asarray(got), exp, atol=1e-10)


def test_cdist_2d_sqeuclidean():
    XA = sample_dense(11, 3, seed=124)
    XB = sample_dense(14, 3, seed=125)
    got = cdist_2d(XA, XB, metric="sqeuclidean")
    exp = np.asarray(spatial.cdist(XA, XB, metric="sqeuclidean"))
    assert np.allclose(got, exp, atol=1e-10)


@pytest.mark.parametrize("W", [1, 2])
def test_lookup_2d_matches_host(W):
    rng = np.random.default_rng(126)
    S = 100
    # unique random bitset rows, lex-sorted
    sets = rng.integers(0, 2**50, size=(S * 2, W)).astype(np.uint64)
    sets = np.unique(sets.view([("", np.uint64)] * W)).view(np.uint64).reshape(-1, W)[:S]
    queries = sets[rng.integers(0, sets.shape[0], size=57)]
    got = lookup_2d(sets, queries)
    from sparse_tpu.quantum import _lookup

    exp = _lookup(sets, queries)
    assert np.array_equal(got, exp)


def test_lookup_2d_missing_raises():
    sets = np.array([[1], [5], [9]], dtype=np.uint64)
    queries = np.array([[4]], dtype=np.uint64)
    with pytest.raises(RuntimeError):
        lookup_2d(sets, queries)


def test_hamiltonian_driver_mesh_matches_host():
    """The 2-D-grid Hamiltonian build must equal the host build exactly."""
    import networkx as nx

    from sparse_tpu.quantum import HamiltonianDriver

    g = nx.cycle_graph(8)
    host = HamiltonianDriver(graph=g)
    dist = HamiltonianDriver(graph=g, mesh=get_mesh_2d())
    assert host.nstates == dist.nstates
    H0 = np.asarray(host.hamiltonian.todense())
    H1 = np.asarray(dist.hamiltonian.todense())
    assert np.array_equal(H0, H1)
