"""Direct-solver surface tests: spsolve_triangular, splu/spilu/factorized,
inv, expm, is_sptriangular, spbandwidth — scipy oracles.

Beyond the reference (its spsolve is CG, linalg.py:88); scipy.sparse.linalg
drop-in completeness.
"""

import numpy as np
import pytest
import scipy.linalg
import scipy.sparse as sp
import scipy.sparse.linalg as sla

import sparse_tpu as sparse
import sparse_tpu.linalg as linalg
from .utils.sample import sample_vec


def _tri(n, lower=True, seed=0, unit=False):
    rng = np.random.default_rng(seed)
    M = sp.random(n, n, 0.15, random_state=rng).toarray()
    M = np.tril(M, -1) if lower else np.triu(M, 1)
    d = np.ones(n) if unit else rng.uniform(1.0, 2.0, n)
    return sp.csr_matrix(M + np.diag(d))


def test_spbandwidth_and_is_sptriangular():
    n = 20
    L = _tri(n, lower=True)
    U = _tri(n, lower=False)
    A = sparse.csr_array(L)
    B = sparse.csr_array(U)
    lo, hi = linalg.spbandwidth(A)
    assert hi == 0 and lo > 0
    assert linalg.is_sptriangular(A) == (True, False)
    assert linalg.is_sptriangular(B) == (False, True)
    D = sparse.eye(5)
    assert linalg.is_sptriangular(D) == (True, True)
    assert linalg.spbandwidth(D) == (0, 0)


@pytest.mark.parametrize("lower", [True, False])
@pytest.mark.parametrize("nrhs", [0, 3])
def test_spsolve_triangular(lower, nrhs):
    n = 300  # > one block: exercises the scan chain
    T = _tri(n, lower=lower, seed=1)
    A = sparse.csr_array(T)
    b = (
        sample_vec(n, seed=2)
        if nrhs == 0
        else np.stack([sample_vec(n, seed=2 + i) for i in range(nrhs)], axis=1)
    )
    x = np.asarray(linalg.spsolve_triangular(A, b, lower=lower, block=64))
    x_sci = sla.spsolve_triangular(T.tocsr(), b, lower=lower)
    np.testing.assert_allclose(x, x_sci, rtol=2e-4, atol=2e-5)


def test_spsolve_triangular_unit_diagonal():
    n = 120
    T = _tri(n, lower=True, seed=3, unit=True)
    A = sparse.csr_array(T)
    b = sample_vec(n, seed=4)
    x = np.asarray(
        linalg.spsolve_triangular(A, b, lower=True, unit_diagonal=True, block=50)
    )
    x_sci = sla.spsolve_triangular(T.tocsr(), b, lower=True, unit_diagonal=True)
    np.testing.assert_allclose(x, x_sci, rtol=2e-4, atol=2e-5)


def test_spsolve_triangular_rejects_wrong_shape_and_singular():
    n = 10
    T = _tri(n, lower=True, seed=5).toarray()
    T[3, 3] = 0.0
    A = sparse.csr_array(sp.csr_matrix(T))
    with pytest.raises(np.linalg.LinAlgError):
        linalg.spsolve_triangular(A, np.ones(n), lower=True)
    full = sparse.csr_array(sp.csr_matrix(np.ones((4, 4))))
    with pytest.raises(ValueError):
        linalg.spsolve_triangular(full, np.ones(4), lower=True)


def _gen(n, seed=6):
    rng = np.random.default_rng(seed)
    return (sp.random(n, n, 0.2, random_state=rng) + n * sp.identity(n)).tocsr()


def test_splu_solve_and_factors():
    n = 60
    S = _gen(n)
    A = sparse.csr_array(S)
    lu = linalg.splu(A)
    assert lu.shape == (n, n) and lu.nnz == S.nnz
    b = sample_vec(n, seed=7)
    x = np.asarray(lu.solve(b))
    np.testing.assert_allclose(x, sla.spsolve(S.tocsc(), b), rtol=1e-4, atol=1e-5)
    # transpose solve
    xt = np.asarray(lu.solve(b, trans="T"))
    np.testing.assert_allclose(
        xt, sla.spsolve(S.T.tocsc(), b), rtol=1e-4, atol=1e-5
    )
    # scipy SuperLU convention: Pr @ A @ Pc == L @ U with
    # Pr[perm_r[i], i] = 1, i.e. (L @ U)[perm_r] == A
    L = np.asarray(lu.L.todense())
    U = np.asarray(lu.U.todense())
    np.testing.assert_allclose(
        (L @ U)[lu.perm_r], S.toarray(), rtol=1e-4, atol=1e-4
    )
    Pr = sp.csc_matrix(
        (np.ones(n), (lu.perm_r, np.arange(n))), shape=(n, n)
    )
    np.testing.assert_allclose(
        (Pr @ S).toarray(), L @ U, rtol=1e-4, atol=1e-4
    )


def test_spilu_preconditions_cg():
    n = 80
    rng = np.random.default_rng(8)
    S = sp.random(n, n, 0.1, random_state=rng)
    S = (S + S.T) * 0.5 + sp.diags(np.linspace(1, 3, n))
    S = S.tocsr()
    A = sparse.csr_array(S)
    ilu = linalg.spilu(A)  # real ILU(0) now (r4) — approximate by design
    b = sample_vec(n, seed=9)
    x = np.asarray(ilu.solve(b))
    # one apply contracts the residual (random-pattern ILU(0) is a weak
    # but real preconditioner; the Poisson iteration-count test below is
    # the strength assertion)
    assert np.linalg.norm(np.asarray(S @ x) - b) < np.linalg.norm(b)
    assert np.all(np.isfinite(x))
    # and it is exactly U^-1 L^-1 b for its OWN factors
    ref = sla.spsolve_triangular(
        sp.csr_matrix(ilu.U.toarray()),
        sla.spsolve_triangular(sp.csr_matrix(ilu.L.toarray()), b, lower=True),
        lower=False,
    )
    np.testing.assert_allclose(x, ref, rtol=1e-6, atol=1e-8)


def test_factorized_closure():
    n = 40
    S = _gen(n, seed=10)
    solve = linalg.factorized(sparse.csr_array(S))
    b = sample_vec(n, seed=11)
    np.testing.assert_allclose(
        np.asarray(solve(b)), sla.spsolve(S.tocsc(), b), rtol=1e-4, atol=1e-5
    )


def test_inv():
    n = 30
    S = _gen(n, seed=12)
    Ainv = linalg.inv(sparse.csr_array(S))
    assert Ainv.format == "csr"
    np.testing.assert_allclose(
        np.asarray(Ainv.todense()), np.linalg.inv(S.toarray()),
        rtol=1e-3, atol=1e-4,
    )


def test_expm():
    n = 25
    rng = np.random.default_rng(13)
    S = sp.random(n, n, 0.2, random_state=rng).tocsr() * 0.5
    E = linalg.expm(sparse.csr_array(S))
    assert E.format == "csr"
    np.testing.assert_allclose(
        np.asarray(E.todense()), scipy.linalg.expm(S.toarray()),
        rtol=1e-4, atol=1e-5,
    )


def test_splu_above_ceiling_uses_sparse_mode():
    from sparse_tpu import native

    if native.lib() is None:
        # the no-native behavior has its own dedicated test below; this
        # one must not pass vacuously (ADVICE r5)
        pytest.skip("native library unavailable")
    big = sparse.eye(9000)
    # beyond the dense ceiling the native sparse LU takes over (VERDICT
    # r4 weak #5): the factorization WORKS instead of raising
    lu = linalg.splu(big)
    assert lu._mode == "sparse"
    b = np.arange(9000, dtype=np.float64)
    np.testing.assert_allclose(np.asarray(lu.solve(b)), b, atol=1e-5)


def test_inv_above_dense_ceiling_raises():
    # splu succeeds above the ceiling in sparse mode, but inv() must still
    # refuse: the inverse is dense (ADVICE r5)
    with pytest.raises(ValueError, match="dense ceiling"):
        linalg.inv(sparse.eye(9000))


def test_splu_size_ceiling_raises_without_native(monkeypatch):
    from sparse_tpu import native

    monkeypatch.setattr(native, "splu_host", lambda *a, **k: None)
    with pytest.raises(ValueError, match="ceiling"):
        linalg.splu(sparse.eye(9000))


def test_splu_complex_rhs_on_real_factor():
    n = 30
    S = _gen(n, seed=30)
    lu = linalg.splu(sparse.csr_array(S))
    rng = np.random.default_rng(31)
    b = rng.standard_normal(n) + 1j * rng.standard_normal(n)
    x = np.asarray(lu.solve(b))
    x_sci = sla.spsolve(S.tocsc().astype(np.complex128), b)
    np.testing.assert_allclose(x, x_sci, rtol=1e-4, atol=1e-5)


# -- real sparse ILU(0) / IC(0) (VERDICT r3 #6) ------------------------------

def _dense_ilu0(S):
    """Pattern-restricted Gaussian elimination — the ILU(0) definition."""
    A = S.toarray().copy()
    pattern = S.toarray() != 0
    n = A.shape[0]
    for i in range(1, n):
        for k in range(i):
            if pattern[i, k]:
                A[i, k] /= A[k, k]
                for j in range(k + 1, n):
                    if pattern[i, j]:
                        A[i, j] -= A[i, k] * A[k, j]
    L = np.tril(A, -1) * pattern + np.eye(n)
    U = np.triu(A) * pattern
    return L, U


def test_ilu0_matches_dense_reference():
    n = 60
    S = _gen(n, seed=21)
    ilu = linalg.spilu(sparse.csr_array(S))
    Lref, Uref = _dense_ilu0(S)
    np.testing.assert_allclose(ilu.L.toarray(), Lref, rtol=1e-10, atol=1e-12)
    np.testing.assert_allclose(ilu.U.toarray(), Uref, rtol=1e-10, atol=1e-12)
    # the ILU(0) residual property: (L@U)[i,j] == A[i,j] on A's pattern
    prod = Lref @ Uref
    pat = S.toarray() != 0
    np.testing.assert_allclose(prod[pat], S.toarray()[pat], rtol=1e-9, atol=1e-11)


def test_ilu0_solve_is_two_triangular_solves():
    n = 50
    S = _gen(n, seed=22)
    ilu = linalg.spilu(sparse.csr_array(S))
    b = sample_vec(n, seed=23)
    x = np.asarray(ilu.solve(b))
    Lref, Uref = _dense_ilu0(S)
    ref = np.linalg.solve(Uref, np.linalg.solve(Lref, b))
    np.testing.assert_allclose(x, ref, rtol=1e-6, atol=1e-8)


def test_spilu_preconditions_cg_fewer_iterations():
    """ILU(0) as M must cut CG iteration counts vs unpreconditioned on a
    2-D Poisson — the preconditioner-family behavior the dense shim
    could not provide at scale."""
    import scipy.sparse as sp

    n = 48
    g = sp.eye(n) * 0 + sp.diags([np.full(n - 1, -1.0), np.full(n, 2.0),
                                  np.full(n - 1, -1.0)], [-1, 0, 1])
    S = (sp.kron(sp.identity(n), g) + sp.kron(g, sp.identity(n))).tocsr()
    A = sparse.csr_array(S)
    b = sample_vec(n * n, seed=5)
    _, iters_plain = linalg.cg(A, b, tol=1e-8, maxiter=2000)
    ilu = linalg.spilu(A)
    M = linalg.LinearOperator(A.shape, matvec=ilu.solve, dtype=np.float64)
    x, iters_pre = linalg.cg(A, b, tol=1e-8, maxiter=2000, M=M)
    assert iters_pre < iters_plain / 2, (iters_pre, iters_plain)
    np.testing.assert_allclose(
        np.asarray(A @ x), b, rtol=1e-5, atol=1e-6
    )


def test_spilu_drop_tol_thins_factors():
    n = 80
    S = _gen(n, seed=25)
    full = linalg.spilu(sparse.csr_array(S))
    dropped = linalg.spilu(sparse.csr_array(S), drop_tol=0.2)
    assert dropped.L.nnz + dropped.U.nnz < full.L.nnz + full.U.nnz
    # still a usable preconditioner apply
    b = sample_vec(n, seed=26)
    assert np.all(np.isfinite(np.asarray(dropped.solve(b))))


def test_ic0_matches_dense_reference():
    import scipy.sparse as sp

    n = 40
    S = _gen(n, seed=27)  # _gen returns SPD-ish; symmetrize hard
    S = ((S + S.T) * 0.5 + sp.identity(n) * 5).tocsr()
    icf = linalg.ic0(sparse.csr_array(S))
    # dense pattern-restricted Cholesky
    A = S.toarray()
    pat = np.tril(A != 0)
    n_ = n
    L = np.zeros_like(A)
    for i in range(n_):
        for j in range(i + 1):
            if not pat[i, j]:
                continue
            s = A[i, j] - L[i, :j] @ L[j, :j]
            L[i, j] = np.sqrt(s) if i == j else s / L[j, j]
    np.testing.assert_allclose(icf.L.toarray(), L, rtol=1e-8, atol=1e-10)
    b = sample_vec(n, seed=28)
    ref = np.linalg.solve(L @ L.T, b)
    np.testing.assert_allclose(np.asarray(icf.solve(b)), ref, rtol=1e-6, atol=1e-8)


@pytest.mark.slow
def test_spilu_million_row_laplacian_onnz_memory():
    """The VERDICT r3 #6 acceptance: spilu on a 1e6-row matrix must
    factor and solve in O(nnz) memory (the dense shim implied 8 TB)."""
    import scipy.sparse as sp

    n = 1_000_000
    S = sp.diags([np.full(n - 1, -1.0), np.full(n, 4.0),
                  np.full(n - 1, -1.0)], [-1, 0, 1], format="csr")
    ilu = linalg.spilu(sparse.csr_array(S))
    b = np.ones(n)
    x = np.asarray(ilu.solve(b))
    assert x.shape == (n,) and np.all(np.isfinite(x))
    # tridiagonal ILU(0) == exact LU: the solve IS the solution
    np.testing.assert_allclose(
        np.asarray(S @ x), b, rtol=1e-4, atol=1e-4
    )


# ---------------------------------------------------------------------------
# Sparse LU (native Gilbert-Peierls; VERDICT r4 weak #5 — no dense ceiling)
# ---------------------------------------------------------------------------


def _gp_matrix(n, seed=5, density=0.12):
    rng = np.random.default_rng(seed)
    M = sp.random(n, n, density, random_state=rng).toarray()
    np.fill_diagonal(M, rng.uniform(3.0, 5.0, n))
    return sp.csr_matrix(M)


@pytest.fixture
def sparse_lu_forced(monkeypatch):
    """Force the sparse branch for small matrices by shrinking the dense
    ceiling (the production crossover stays 8192)."""
    from sparse_tpu import _direct

    monkeypatch.setattr(_direct, "DENSE_DIRECT_MAX_N", 50)
    from sparse_tpu import native

    if native.lib() is None:
        pytest.skip("native library unavailable")
    return _direct


def test_splu_sparse_mode_matches_scipy(sparse_lu_forced):
    S = _gp_matrix(144)
    A = sparse.csr_array(S)
    lu = linalg.splu(A)
    assert lu._mode == "sparse"
    b = np.random.default_rng(0).standard_normal(144)
    x = np.asarray(lu.solve(b))
    np.testing.assert_allclose(x, sla.spsolve(S.tocsc(), b), rtol=1e-8,
                               atol=1e-10)
    # trans solves
    xt = np.asarray(lu.solve(b, trans="T"))
    np.testing.assert_allclose(S.T @ xt, b, rtol=1e-8, atol=1e-8)
    xh = np.asarray(lu.solve(b, trans="H"))
    np.testing.assert_allclose(xt, xh)
    # multi-rhs
    B = np.random.default_rng(1).standard_normal((144, 3))
    X = np.asarray(lu.solve(B))
    np.testing.assert_allclose(S @ X, B, rtol=1e-8, atol=1e-8)


def test_splu_sparse_factors_and_perm_convention(sparse_lu_forced):
    S = _gp_matrix(90, seed=7)
    lu = linalg.splu(sparse.csr_array(S))
    assert lu._mode == "sparse"
    L = np.asarray(lu.L.toarray())
    U = np.asarray(lu.U.toarray())
    assert np.allclose(np.triu(L, 1), 0) and np.allclose(np.diag(L), 1)
    assert np.allclose(np.tril(U, -1), 0)
    # scipy convention: (L @ U)[perm_r] == A (with perm_c identity here)
    np.testing.assert_allclose((L @ U)[lu.perm_r], S.toarray(), atol=1e-10)


def test_splu_sparse_complex_rhs_and_singular(sparse_lu_forced):
    S = _gp_matrix(80, seed=9)
    lu = linalg.splu(sparse.csr_array(S))
    assert lu._mode == "sparse"
    rng = np.random.default_rng(2)
    bz = rng.standard_normal(80) + 1j * rng.standard_normal(80)
    xz = np.asarray(lu.solve(bz))
    np.testing.assert_allclose(S @ xz, bz, rtol=1e-8, atol=1e-8)
    # structurally singular: zero column
    Sd = S.toarray()
    Sd[:, 17] = 0.0
    with pytest.raises(RuntimeError, match="singular"):
        linalg.splu(sparse.csr_array(sp.csr_matrix(Sd)))


def test_splu_no_native_lib_keeps_ceiling_error(sparse_lu_forced, monkeypatch):
    from sparse_tpu import native

    monkeypatch.setattr(native, "splu_host", lambda *a, **k: None)
    with pytest.raises(ValueError, match="ceiling"):
        linalg.splu(sparse.csr_array(_gp_matrix(60)))


def test_splu_complex_matrix_stays_dense_under_ceiling():
    n = 40
    rng = np.random.default_rng(3)
    M = (sp.random(n, n, 0.2, random_state=rng)
         + sp.random(n, n, 0.2, random_state=rng) * 1j).toarray()
    np.fill_diagonal(M, 4.0 + 1j)
    S = sp.csr_matrix(M)
    lu = linalg.splu(sparse.csr_array(S))
    b = rng.standard_normal(n) + 1j * rng.standard_normal(n)
    np.testing.assert_allclose(S @ np.asarray(lu.solve(b)), b, rtol=1e-5,
                               atol=1e-6)


def test_splu_rcm_ordering_cuts_fill(sparse_lu_forced):
    """permc_spec='RCM': symmetric reverse-Cuthill-McKee pre-permutation.
    On a scrambled banded matrix the band order is recoverable, so fill
    drops by a large factor while solves stay transparent (plain Ax=b)."""
    rng = np.random.default_rng(4)
    n = 400
    offs = (-12, -5, 0, 5, 12)
    band = sp.diags([rng.standard_normal(n - abs(k)) for k in offs], offs)
    band = (band + sp.eye(n) * 6).tocsr()
    p = rng.permutation(n)
    S = band[p][:, p].tocsr()
    A = sparse.csr_array(S)
    lu_nat = linalg.splu(A)
    lu_rcm = linalg.splu(A, permc_spec="RCM")
    fill = lambda lu: lu._Lcsc[2].size + lu._Ucsc[2].size
    assert fill(lu_rcm) < fill(lu_nat) / 2
    b = rng.standard_normal(n)
    for lu in (lu_nat, lu_rcm):
        np.testing.assert_allclose(S @ np.asarray(lu.solve(b)), b, atol=1e-8)
        np.testing.assert_allclose(
            S.T @ np.asarray(lu.solve(b, trans="T")), b, atol=1e-8
        )
        # scipy attr convention: (L @ U)[perm_r] == A[:, perm_c]
        LU = np.asarray((lu.L @ lu.U).toarray())
        np.testing.assert_allclose(
            LU[lu.perm_r], S.toarray()[:, lu.perm_c], atol=1e-10
        )


def test_spilu_fill_factor_runs_true_ilut(sparse_lu_forced):
    """fill_factor given -> scipy's actual ILUT algorithm (threshold drop
    + per-column fill cap on the Gilbert-Peierls core), not ILU(0)."""
    m = 20
    n = m * m
    ex = np.ones(m)
    T1 = sp.diags([-ex[:-1], 2 * ex, -ex[:-1]], [-1, 0, 1])
    T2 = sp.diags([-100 * ex[:-1], 200 * ex, -100 * ex[:-1]], [-1, 0, 1])
    S = (sp.kron(sp.identity(m), T1) + sp.kron(T2, sp.identity(m))).tocsr()
    A = sparse.csr_array(S)
    ilut = linalg.spilu(A, drop_tol=1e-3, fill_factor=10)
    assert type(ilut).__name__ == "SuperLU" and ilut._mode == "sparse"
    # fill bound: per column each half keeps <= ceil(ff * avg / 2), plus
    # the U diagonals
    avg = S.nnz / n
    lfil = int(np.ceil(10 * avg / 2.0))
    lnnz = ilut._Lcsc[2].size
    unnz = ilut._Ucsc[2].size
    assert lnnz <= lfil * n and unnz <= (lfil + 1) * n
    # preconditions CG at least as well as ILU(0), far better than plain
    b = np.random.default_rng(3).standard_normal(n)
    def iters(M=None):
        kw = {}
        if M is not None:
            kw["M"] = linalg.LinearOperator((n, n), dtype=np.float64,
                                            matvec=M.solve)
        _, it = linalg.cg(A, b, tol=1e-10, maxiter=2000, **kw)
        return it
    it_p, it_0, it_t = iters(), iters(linalg.spilu(A)), iters(ilut)
    assert it_t <= it_0 < it_p
    # the fill cap genuinely caps: with NO threshold drop, fill_factor=1
    # must stay within its per-half-column bound and well under ff=20
    tight = linalg.spilu(A, drop_tol=0.0, fill_factor=1)
    loose = linalg.spilu(A, drop_tol=0.0, fill_factor=20)
    lfil1 = int(np.ceil(avg / 2.0))
    tnnz = tight._Lcsc[2].size + tight._Ucsc[2].size
    assert tnnz <= 2 * lfil1 * n + n
    assert tnnz < loose._Lcsc[2].size + loose._Ucsc[2].size
    # no-native fallback: fill_factor silently degrades to ILU(0)
    from sparse_tpu import native
    from unittest import mock
    with mock.patch.object(native, "ilut_host", lambda *a, **k: None):
        obj = linalg.spilu(A, fill_factor=10)
        assert type(obj).__name__ == "SpILU"
