"""Direct-solver surface tests: spsolve_triangular, splu/spilu/factorized,
inv, expm, is_sptriangular, spbandwidth — scipy oracles.

Beyond the reference (its spsolve is CG, linalg.py:88); scipy.sparse.linalg
drop-in completeness.
"""

import numpy as np
import pytest
import scipy.linalg
import scipy.sparse as sp
import scipy.sparse.linalg as sla

import sparse_tpu as sparse
import sparse_tpu.linalg as linalg
from .utils.sample import sample_vec


def _tri(n, lower=True, seed=0, unit=False):
    rng = np.random.default_rng(seed)
    M = sp.random(n, n, 0.15, random_state=rng).toarray()
    M = np.tril(M, -1) if lower else np.triu(M, 1)
    d = np.ones(n) if unit else rng.uniform(1.0, 2.0, n)
    return sp.csr_matrix(M + np.diag(d))


def test_spbandwidth_and_is_sptriangular():
    n = 20
    L = _tri(n, lower=True)
    U = _tri(n, lower=False)
    A = sparse.csr_array(L)
    B = sparse.csr_array(U)
    lo, hi = linalg.spbandwidth(A)
    assert hi == 0 and lo > 0
    assert linalg.is_sptriangular(A) == (True, False)
    assert linalg.is_sptriangular(B) == (False, True)
    D = sparse.eye(5)
    assert linalg.is_sptriangular(D) == (True, True)
    assert linalg.spbandwidth(D) == (0, 0)


@pytest.mark.parametrize("lower", [True, False])
@pytest.mark.parametrize("nrhs", [0, 3])
def test_spsolve_triangular(lower, nrhs):
    n = 300  # > one block: exercises the scan chain
    T = _tri(n, lower=lower, seed=1)
    A = sparse.csr_array(T)
    b = (
        sample_vec(n, seed=2)
        if nrhs == 0
        else np.stack([sample_vec(n, seed=2 + i) for i in range(nrhs)], axis=1)
    )
    x = np.asarray(linalg.spsolve_triangular(A, b, lower=lower, block=64))
    x_sci = sla.spsolve_triangular(T.tocsr(), b, lower=lower)
    np.testing.assert_allclose(x, x_sci, rtol=2e-4, atol=2e-5)


def test_spsolve_triangular_unit_diagonal():
    n = 120
    T = _tri(n, lower=True, seed=3, unit=True)
    A = sparse.csr_array(T)
    b = sample_vec(n, seed=4)
    x = np.asarray(
        linalg.spsolve_triangular(A, b, lower=True, unit_diagonal=True, block=50)
    )
    x_sci = sla.spsolve_triangular(T.tocsr(), b, lower=True, unit_diagonal=True)
    np.testing.assert_allclose(x, x_sci, rtol=2e-4, atol=2e-5)


def test_spsolve_triangular_rejects_wrong_shape_and_singular():
    n = 10
    T = _tri(n, lower=True, seed=5).toarray()
    T[3, 3] = 0.0
    A = sparse.csr_array(sp.csr_matrix(T))
    with pytest.raises(np.linalg.LinAlgError):
        linalg.spsolve_triangular(A, np.ones(n), lower=True)
    full = sparse.csr_array(sp.csr_matrix(np.ones((4, 4))))
    with pytest.raises(ValueError):
        linalg.spsolve_triangular(full, np.ones(4), lower=True)


def _gen(n, seed=6):
    rng = np.random.default_rng(seed)
    return (sp.random(n, n, 0.2, random_state=rng) + n * sp.identity(n)).tocsr()


def test_splu_solve_and_factors():
    n = 60
    S = _gen(n)
    A = sparse.csr_array(S)
    lu = linalg.splu(A)
    assert lu.shape == (n, n) and lu.nnz == S.nnz
    b = sample_vec(n, seed=7)
    x = np.asarray(lu.solve(b))
    np.testing.assert_allclose(x, sla.spsolve(S.tocsc(), b), rtol=1e-4, atol=1e-5)
    # transpose solve
    xt = np.asarray(lu.solve(b, trans="T"))
    np.testing.assert_allclose(
        xt, sla.spsolve(S.T.tocsc(), b), rtol=1e-4, atol=1e-5
    )
    # scipy SuperLU convention: Pr @ A @ Pc == L @ U with
    # Pr[perm_r[i], i] = 1, i.e. (L @ U)[perm_r] == A
    L = np.asarray(lu.L.todense())
    U = np.asarray(lu.U.todense())
    np.testing.assert_allclose(
        (L @ U)[lu.perm_r], S.toarray(), rtol=1e-4, atol=1e-4
    )
    Pr = sp.csc_matrix(
        (np.ones(n), (lu.perm_r, np.arange(n))), shape=(n, n)
    )
    np.testing.assert_allclose(
        (Pr @ S).toarray(), L @ U, rtol=1e-4, atol=1e-4
    )


def test_spilu_preconditions_cg():
    n = 80
    rng = np.random.default_rng(8)
    S = sp.random(n, n, 0.1, random_state=rng)
    S = (S + S.T) * 0.5 + sp.diags(np.linspace(1, 3, n))
    S = S.tocsr()
    A = sparse.csr_array(S)
    ilu = linalg.spilu(A)
    b = sample_vec(n, seed=9)
    # the exact-LU "incomplete" factorization solves in one apply
    x = np.asarray(ilu.solve(b))
    np.testing.assert_allclose(
        x, sla.spsolve(S.tocsc(), b), rtol=1e-4, atol=1e-5
    )


def test_factorized_closure():
    n = 40
    S = _gen(n, seed=10)
    solve = linalg.factorized(sparse.csr_array(S))
    b = sample_vec(n, seed=11)
    np.testing.assert_allclose(
        np.asarray(solve(b)), sla.spsolve(S.tocsc(), b), rtol=1e-4, atol=1e-5
    )


def test_inv():
    n = 30
    S = _gen(n, seed=12)
    Ainv = linalg.inv(sparse.csr_array(S))
    assert Ainv.format == "csr"
    np.testing.assert_allclose(
        np.asarray(Ainv.todense()), np.linalg.inv(S.toarray()),
        rtol=1e-3, atol=1e-4,
    )


def test_expm():
    n = 25
    rng = np.random.default_rng(13)
    S = sp.random(n, n, 0.2, random_state=rng).tocsr() * 0.5
    E = linalg.expm(sparse.csr_array(S))
    assert E.format == "csr"
    np.testing.assert_allclose(
        np.asarray(E.todense()), scipy.linalg.expm(S.toarray()),
        rtol=1e-4, atol=1e-5,
    )


def test_splu_size_ceiling_raises():
    big = sparse.eye(9000)
    with pytest.raises(ValueError):
        linalg.splu(big)


def test_splu_complex_rhs_on_real_factor():
    n = 30
    S = _gen(n, seed=30)
    lu = linalg.splu(sparse.csr_array(S))
    rng = np.random.default_rng(31)
    b = rng.standard_normal(n) + 1j * rng.standard_normal(n)
    x = np.asarray(lu.solve(b))
    x_sci = sla.spsolve(S.tocsc().astype(np.complex128), b)
    np.testing.assert_allclose(x, x_sci, rtol=1e-4, atol=1e-5)
