"""Streaming dispatch (ISSUE 13): the SolveSession pipeline.

Pins the pipeline contract pillars: (a) `SPARSE_TPU_INFLIGHT=1`
reproduces the classic synchronous path bit-identically (numeric AND
jaxpr parity — the window changes host scheduling, never programs);
(b) the deferred-readback future API (`ready` / `result(timeout=)` /
`poll()` / `drain()`) resolves interleaved patterns in any await order;
(c) per-ticket deadlines are re-checked at readback — a lane gone stale
in flight keeps its result instead of spending a requeue past its
deadline, while a lane expired before dispatch still fails; (d)
admission control blocks or rejects at `max_queue_depth` with
`batch.admission` evidence; (e) the async `_prebuild` warm replay races
a first `submit` to a zero-serving-build window; (f) the
`batch.queue_depth` gauge decrements per ticket at finalize — no drift
through failures, deadlines or requeues (`queue_depth_drift == 0`).
"""

import numpy as np
import pytest
import scipy.sparse as sp

import jax

from sparse_tpu import plan_cache, telemetry
from sparse_tpu.batch import (
    AdmissionError,
    SolveSession,
    TicketDeadlineError,
    TicketTimeoutError,
    bucket_batch,
    pad_lanes,
    stage_lanes,
)
from sparse_tpu.batch.service import _InFlight
from sparse_tpu.config import settings
from sparse_tpu.resilience import faults
from sparse_tpu.telemetry import _metrics


@pytest.fixture
def tel(tmp_path, monkeypatch):
    telemetry.reset()
    monkeypatch.setattr(settings, "telemetry", True)
    telemetry.configure(str(tmp_path / "records.jsonl"))
    yield tmp_path / "records.jsonl"
    telemetry.configure(None)
    telemetry.reset()


def _tridiag(n, seed=0):
    rng = np.random.default_rng(seed)
    e = np.ones(n)
    A = sp.diags([-e[:-1], 3.0 * e, -e[:-1]], [-1, 0, 1], format="csr")
    A = A.copy()
    A.setdiag(3.0 + rng.random(n))
    A.sort_indices()
    return A


def _systems(B=6, n=48, seed=7):
    rng = np.random.default_rng(seed)
    mats = [_tridiag(n, seed=s) for s in range(B)]
    rhs = rng.standard_normal((B, n))
    return mats, rhs


# ---------------------------------------------------------------------------
# (a) parity: the window changes scheduling, never results or programs
# ---------------------------------------------------------------------------
def test_inflight1_numeric_parity_with_pipelined():
    mats, rhs = _systems()
    s_sync = SolveSession("cg", inflight=1, warm_start=False)
    X0, it0, r0 = s_sync.solve_many(mats, rhs, tol=1e-10)

    s_pipe = SolveSession("cg", inflight=3, warm_start=False)
    tickets = [
        s_pipe.submit(A, b, tol=1e-10) for A, b in zip(mats, rhs)
    ]
    s_pipe.flush(wait=False)
    outs = [t.result() for t in tickets]
    X1 = np.stack([o[0] for o in outs])
    it1 = np.asarray([o[1] for o in outs])
    r1 = np.asarray([o[2] for o in outs])
    # bit-identical, not merely close: same program, same inputs
    assert np.array_equal(X0, X1)
    assert np.array_equal(it0, it1)
    assert np.array_equal(r0, r1)


def test_inflight_never_enters_program_jaxpr_or_keys():
    mats, _ = _systems(B=2)
    s1 = SolveSession("cg", inflight=1, warm_start=False)
    s2 = SolveSession("cg", inflight=4, warm_start=False)
    pat1 = s1.pattern_of(mats[0])
    pat2 = s2.pattern_of(mats[0])
    B, dt = 2, np.dtype(np.float64)
    j1 = jax.make_jaxpr(s1._build_program(pat1, B, dt))(
        np.zeros((B, pat1.nnz)), np.zeros((B, 48)), np.zeros((B, 48)),
        np.zeros(B), 10,
    )
    j2 = jax.make_jaxpr(s2._build_program(pat2, B, dt))(
        np.zeros((B, pat2.nnz)), np.zeros((B, 48)), np.zeros((B, 48)),
        np.zeros(B), 10,
    )
    assert str(j1) == str(j2)


def test_stage_lanes_matches_pad_lanes():
    rng = np.random.default_rng(3)
    values = rng.standard_normal((3, 10))
    rhs = rng.standard_normal((3, 5))
    tols = np.array([1e-8, 1e-6, 1e-4])
    ref = pad_lanes(values, rhs, tols, 4)
    dev = stage_lanes(values, rhs, tols, 4)
    assert ref[4] == dev[4] == 3
    for a, b in zip(ref[:4], dev[:4]):
        assert np.array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# (b) deferred readback: future API, interleaved patterns, poll/drain
# ---------------------------------------------------------------------------
def test_deferred_readback_interleaved_patterns_any_order():
    n = 40
    mats_a = [_tridiag(n, seed=s) for s in range(3)]
    mats_b = [_tridiag(n + 8, seed=10 + s) for s in range(3)]
    rng = np.random.default_rng(11)
    ses = SolveSession("cg", inflight=4, batch_max=2, warm_start=False)
    tickets = []
    oracle = []
    for A in [mats_a[0], mats_b[0], mats_a[1], mats_b[1], mats_a[2],
              mats_b[2]]:
        b = rng.standard_normal(A.shape[0])
        tickets.append(ses.submit(A, b, tol=1e-10))
        oracle.append((A, b))
    ses.flush(wait=False)
    # await in reverse order: retirement is FIFO underneath, the
    # future API hides it
    for t, (A, b) in reversed(list(zip(tickets, oracle))):
        x, _iters, _r2 = t.result()
        assert np.linalg.norm(A @ x - b) < 1e-8
    assert ses.session_stats()["tickets"]["queue_depth_drift"] == 0


def test_ready_flag_and_poll_and_drain_counts():
    mats, rhs = _systems(B=4)
    ses = SolveSession("cg", inflight=8, batch_max=2, warm_start=False)
    ts = [ses.submit(A, b, tol=1e-10) for A, b in zip(mats, rhs)]
    assert not any(t.ready for t in ts)  # still queued
    dispatched = ses.flush(wait=False)
    assert dispatched == 2
    retired = ses.poll() + ses.drain()
    assert retired <= 2
    assert all(t.ready for t in ts)
    assert all(t.done for t in ts)
    st = ses.session_stats()
    assert st["pipeline"]["depth"] == 0
    assert st["tickets"]["queue_depth_drift"] == 0


def test_result_timeout_leaves_ticket_pending(monkeypatch):
    mats, rhs = _systems(B=1)
    ses = SolveSession("cg", inflight=2, warm_start=False)
    t = ses.submit(mats[0], rhs[0], tol=1e-12)
    # deterministic timeout: pretend the device never finishes
    monkeypatch.setattr(_InFlight, "is_ready", lambda self: False)
    with pytest.raises(TicketTimeoutError):
        t.result(timeout=0.01)
    assert not t.done  # a timeout never loses work
    monkeypatch.undo()
    x, _iters, _r2 = t.result()
    assert np.linalg.norm(mats[0] @ x - rhs[0]) < 1e-8


# ---------------------------------------------------------------------------
# (c) deadlines: still fail at dispatch; re-checked at readback
# ---------------------------------------------------------------------------
def test_deadline_expired_before_dispatch_still_fails():
    mats, rhs = _systems(B=1)
    ses = SolveSession("cg", inflight=2, warm_start=False)
    t = ses.submit(mats[0], rhs[0], tol=1e-10, deadline_s=0.0)
    ses.flush(wait=False)
    with pytest.raises(TicketDeadlineError):
        t.result()
    assert ses.session_stats()["tickets"]["queue_depth_drift"] == 0


def test_deadline_at_readback_skips_requeue(tel):
    mats, rhs = _systems(B=2)
    before = _metrics.counter("batch.stale_requeues").value
    ses = SolveSession("cg", inflight=4, requeue=True, warm_start=False)
    # maxiter=1 cannot converge -> the lanes would requeue. Hold the
    # bucket in flight (is_ready False keeps poll() from retiring it),
    # then lapse the deadlines WHILE in flight: readback must keep the
    # unconverged results instead of spending a fallback solve
    ts = [
        ses.submit(A, b, tol=1e-14, maxiter=1, deadline_s=60.0)
        for A, b in zip(mats, rhs)
    ]
    orig_ready = _InFlight.is_ready
    _InFlight.is_ready = lambda self: False
    try:
        ses.flush(wait=False)
        assert ses.session_stats()["pipeline"]["depth"] == 1
        for t in ts:
            t.deadline_s = 1e-9  # in-flight wait outlived the budget
    finally:
        _InFlight.is_ready = orig_ready
    ses.drain()
    for t in ts:
        assert t.done and not t.converged
        assert not t.requeued
    assert _metrics.counter("batch.stale_requeues").value >= before + 2
    evs = [
        e for e in telemetry.events()
        if e["kind"] == "batch.deadline" and e.get("stage") == "readback"
    ]
    assert evs and evs[0]["lanes"] == 2
    assert ses.session_stats()["tickets"]["queue_depth_drift"] == 0


def test_unexpired_unconverged_lane_still_requeues():
    mats, rhs = _systems(B=1)
    ses = SolveSession("cg", inflight=4, requeue=True, warm_start=False)
    t = ses.submit(mats[0], rhs[0], tol=1e-10, maxiter=1)
    ses.flush(wait=False)
    x, _iters, _r2 = t.result()
    assert t.requeued  # no deadline -> the fallback ran
    assert np.linalg.norm(mats[0] @ x - rhs[0]) < 1e-6


# ---------------------------------------------------------------------------
# (d) admission control
# ---------------------------------------------------------------------------
def test_admission_reject_mode(tel):
    mats, rhs = _systems(B=3)
    ses = SolveSession("cg", inflight=2, max_queue_depth=2,
                       admission="reject", warm_start=False)
    ses.submit(mats[0], rhs[0], tol=1e-10)
    ses.submit(mats[1], rhs[1], tol=1e-10)
    with pytest.raises(AdmissionError):
        ses.submit(mats[2], rhs[2], tol=1e-10)
    evs = [e for e in telemetry.events() if e["kind"] == "batch.admission"]
    assert evs and evs[0]["mode"] == "reject" and evs[0]["depth"] == 2
    ses.drain()
    # rejected work never entered: the admitted two still solve
    assert ses.session_stats()["tickets"]["done"] == 2
    assert ses.session_stats()["tickets"]["queue_depth_drift"] == 0


def test_admission_block_mode_drives_pipeline(tel):
    mats, rhs = _systems(B=6)
    ses = SolveSession("cg", inflight=2, max_queue_depth=3,
                       admission="block", warm_start=False)
    ts = [ses.submit(A, b, tol=1e-10) for A, b in zip(mats, rhs)]
    assert ses._unfinalized < 3 + 1  # backpressure held the line
    ses.drain()
    assert all(t.done for t in ts)
    evs = [e for e in telemetry.events() if e["kind"] == "batch.admission"]
    assert evs and all(e["mode"] == "block" for e in evs)
    assert "waited_ms" in evs[0]
    assert ses.session_stats()["tickets"]["queue_depth_drift"] == 0


# ---------------------------------------------------------------------------
# (e) async warm replay races the first submit
# ---------------------------------------------------------------------------
def test_async_prebuild_races_first_submit(tmp_path, monkeypatch):
    monkeypatch.setattr(settings, "vault", str(tmp_path / "vault"))
    mats, rhs = _systems(B=4)
    seed_ses = SolveSession("cg", warm_start=False)
    X0, _, _ = seed_ses.solve_many(mats, rhs, tol=1e-10)
    plan_cache.clear()  # "the process died"
    ses = SolveSession("cg", inflight=2, warm_start=True)  # async replay
    # submit IMMEDIATELY — the race the pipeline must win: dispatch
    # waits for the replay's program instead of rebuilding it
    ts = [ses.submit(A, b, tol=1e-10) for A, b in zip(mats, rhs)]
    ses.flush(wait=False)
    X1 = np.stack([t.result()[0] for t in ts])
    assert ses.warm_replayed >= 1
    assert ses.session_stats()["pipeline"]["serving_builds"] == 0
    np.testing.assert_allclose(X0, X1, atol=1e-12)


def test_warm_async_false_replays_synchronously(tmp_path, monkeypatch):
    monkeypatch.setattr(settings, "vault", str(tmp_path / "vault"))
    mats, rhs = _systems(B=4)
    SolveSession("cg", warm_start=False).solve_many(mats, rhs, tol=1e-10)
    plan_cache.clear()
    ses = SolveSession("cg", warm_start=True, warm_async=False)
    assert ses._warm is None  # no thread; replay already done
    assert ses.warm_replayed >= 1


# ---------------------------------------------------------------------------
# (f) queue-depth gauge accounting
# ---------------------------------------------------------------------------
def test_queue_depth_gauge_no_drift_on_bucket_failure():
    mats, rhs = _systems(B=4)
    g = _metrics.gauge("batch.queue_depth")
    base = g.value
    ses = SolveSession("cg", inflight=1, dispatch_attempts=1,
                       warm_start=False)
    ts = [ses.submit(A, b, tol=1e-10) for A, b in zip(mats, rhs)]
    assert g.value == base + 4
    faults.configure("drop:dispatch:p=1")  # every dispatch drops
    try:
        ses.flush()
    finally:
        faults.clear()
    assert all(t.failed for t in ts)
    # per-ticket decrement at finalize: failures fully drain the gauge
    assert g.value == base
    assert ses.session_stats()["tickets"]["queue_depth_drift"] == 0


def test_queue_depth_gauge_no_drift_through_requeue_and_deadline():
    mats, rhs = _systems(B=3)
    g = _metrics.gauge("batch.queue_depth")
    base = g.value
    ses = SolveSession("cg", inflight=2, warm_start=False)
    ses.submit(mats[0], rhs[0], tol=1e-10)              # clean
    ses.submit(mats[1], rhs[1], tol=1e-10, maxiter=1)   # will requeue
    t3 = ses.submit(mats[2], rhs[2], tol=1e-10, deadline_s=0.0)  # expires
    ses.flush()
    assert t3.failed
    assert g.value == base
    assert ses.session_stats()["tickets"]["queue_depth_drift"] == 0


def test_inflight_event_and_gauge(tel):
    mats, rhs = _systems(B=4)
    ses = SolveSession("cg", inflight=8, batch_max=2, warm_start=False)
    for A, b in zip(mats, rhs):
        ses.submit(A, b, tol=1e-10)
    ses.flush(wait=False)
    ses.drain()
    evs = [e for e in telemetry.events() if e["kind"] == "batch.inflight"]
    assert len(evs) == 2  # one per dispatched bucket
    assert all(e["capacity"] == 8 for e in evs)
    assert max(e["depth"] for e in evs) >= 1
    assert _metrics.gauge("batch.inflight").value == 0  # drained


# ---------------------------------------------------------------------------
# loadgen rides the future API
# ---------------------------------------------------------------------------
def test_loadgen_closed_loop_records_inflight_depth():
    from sparse_tpu import loadgen

    mats, rhs = _systems(B=4)
    ses = SolveSession("cg", inflight=4, batch_max=4, warm_start=False)
    trace = loadgen.ArrivalTrace.parse("closed:requests=12,concurrency=4")
    # keep buckets "unready" so opportunistic poll() can't retire them
    # before the await point — the depth the runner records is then the
    # genuinely outstanding window, deterministic on any machine
    orig_ready = _InFlight.is_ready
    _InFlight.is_ready = lambda self: False
    try:
        rep = loadgen.run_load(ses, trace, list(zip(mats, rhs)),
                               tol=1e-10)
    finally:
        _InFlight.is_ready = orig_ready
    assert rep.completed == 12
    assert rep.inflight_depth  # recorded
    assert rep.inflight_depth["max"] >= 4  # concurrency honestly held
    assert rep.inflight_depth["pipelined"] is True
    assert rep.as_dict()["inflight_depth"] == rep.inflight_depth


def test_bucket_batch_unchanged_by_pipeline():
    # the pipeline must not perturb bucketing: same pow2 quantization
    assert bucket_batch(5, policy="pow2", batch_max=64) == 8
    assert bucket_batch(5, policy="exact", batch_max=64) == 5
