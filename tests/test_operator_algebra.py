"""LinearOperator algebra (+, -, scalar *, @ composition, **) and
funm_multiply_krylov oracle tests (scipy.sparse.linalg drop-in)."""

import numpy as np
import pytest
import scipy.linalg
import scipy.sparse as sp
import scipy.sparse.linalg as sla

import sparse_tpu as sparse
import sparse_tpu.linalg as linalg
from .utils.sample import sample_vec


def _ops(n=30, seed=0):
    rng = np.random.default_rng(seed)
    Ad = rng.standard_normal((n, n))
    Bd = rng.standard_normal((n, n))
    return (linalg.aslinearoperator(Ad), linalg.aslinearoperator(Bd),
            Ad, Bd)


def test_operator_sum_scale_compose():
    A, B, Ad, Bd = _ops()
    v = sample_vec(30, seed=1)
    np.testing.assert_allclose(
        np.asarray((A + B).matvec(v)), (Ad + Bd) @ v, rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray((A - B).matvec(v)), (Ad - Bd) @ v, rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray((2.5 * A).matvec(v)), 2.5 * (Ad @ v), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray((-A).matvec(v)), -(Ad @ v), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray((A @ B).matvec(v)), Ad @ (Bd @ v), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray((A * B).matvec(v)), Ad @ (Bd @ v), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray((A ** 2).matvec(v)), Ad @ (Ad @ v), rtol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray((A ** 0).matvec(v)), v, rtol=1e-6
    )
    # rmatvec of compositions (adjoint order flips)
    np.testing.assert_allclose(
        np.asarray((A @ B).rmatvec(v)), Bd.T @ (Ad.T @ v), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray((A + B).rmatvec(v)), (Ad + Bd).T @ v, rtol=1e-5
    )
    # matmat block path
    X = np.stack([sample_vec(30, seed=s) for s in (2, 3)], axis=1)
    np.testing.assert_allclose(
        np.asarray((A + 2.0 * B).matmat(X)), (Ad + 2 * Bd) @ X, rtol=1e-5
    )


def test_operator_algebra_shape_validation():
    A = linalg.aslinearoperator(np.ones((3, 4)))
    B = linalg.aslinearoperator(np.ones((4, 4)))
    with pytest.raises(ValueError):
        A + B
    with pytest.raises(ValueError):
        B @ A  # (4,4) @ (3,4) mismatch
    with pytest.raises(ValueError):
        A ** 2  # non-square
    with pytest.raises(ValueError):
        B ** -1


def test_operator_algebra_in_solver():
    """Composed operators must flow through the device solvers."""
    n = 50
    rng = np.random.default_rng(4)
    S = (sp.random(n, n, 0.2, random_state=rng) + n * sp.identity(n)).tocsr()
    A = linalg.aslinearoperator(sparse.csr_array(S))
    shifted = A + (-2.0) * linalg.IdentityOperator((n, n))
    b = sample_vec(n, seed=5)
    x, _ = linalg.gmres(shifted, b, tol=1e-9)
    ref = sla.spsolve((S - 2.0 * sp.identity(n)).tocsc(), b)
    np.testing.assert_allclose(np.asarray(x), ref, atol=1e-4)


@pytest.mark.parametrize("assume_a", ["general", "hermitian"])
def test_funm_multiply_krylov_expm(assume_a):
    n = 60
    rng = np.random.default_rng(6)
    S = sp.random(n, n, 0.1, random_state=rng) * 0.5
    if assume_a == "hermitian":
        S = (S + S.T) * 0.5
    S = (S - sp.identity(n)).tocsr()
    A = sparse.csr_array(S)
    b = sample_vec(n, seed=7)
    y = np.asarray(linalg.funm_multiply_krylov(
        scipy.linalg.expm, A, b, assume_a=assume_a, t=0.7,
        restart_every_m=12,
    ))
    ref = scipy.linalg.expm(0.7 * S.toarray()) @ b
    np.testing.assert_allclose(y, ref, rtol=5e-4, atol=5e-5)


def test_funm_multiply_krylov_inv_sqrt():
    """A genuinely non-exponential f: A^{-1/2} b on an SPD matrix."""
    n = 50
    rng = np.random.default_rng(8)
    Q = sp.random(n, n, 0.2, random_state=rng)
    S = (Q @ Q.T + n * sp.identity(n)).tocsr()
    A = sparse.csr_array(S)
    b = sample_vec(n, seed=9)

    def inv_sqrt(M):
        # this scipy build's sqrtm upcasts to longdouble complex, which
        # np.linalg.inv rejects; the oracle only needs complex128
        return np.linalg.inv(scipy.linalg.sqrtm(M).astype(np.complex128))

    y = np.asarray(linalg.funm_multiply_krylov(
        inv_sqrt, A, b, assume_a="her", restart_every_m=25,
        max_restarts=8,
    ))
    w, V = np.linalg.eigh(S.toarray())
    ref = V @ ((V.T @ b) / np.sqrt(w))
    np.testing.assert_allclose(y, ref, rtol=1e-3, atol=1e-4)


def test_funm_multiply_krylov_validates_and_zero_b():
    A = sparse.csr_array(sp.identity(4).tocsr())
    with pytest.raises(ValueError):
        linalg.funm_multiply_krylov(scipy.linalg.expm, A, np.ones(4),
                                    assume_a="banana")
    y = linalg.funm_multiply_krylov(scipy.linalg.expm, A, np.zeros(4))
    assert np.allclose(np.asarray(y), 0)


def test_pow_large_exponent_no_recursion():
    A = linalg.aslinearoperator(np.eye(8) * 0.999)
    v = np.ones(8)
    out = np.asarray((A ** 2000).matvec(v))
    np.testing.assert_allclose(out, 0.999 ** 2000 * v, rtol=1e-3)


def test_matmul_scalar_raises_but_dot_and_mul_follow_scipy():
    A = linalg.aslinearoperator(np.eye(3) * 3.0)
    with pytest.raises(ValueError, match="Scalar operands"):
        A @ 2.0
    # scipy: dot(scalar) scales; A * v applies
    scaled = A.dot(2.0)
    v = np.ones(3)
    np.testing.assert_allclose(np.asarray(scaled.matvec(v)), 6.0 * v)
    np.testing.assert_allclose(np.asarray(A * v), 3.0 * v)


def test_funm_multiply_krylov_large_norm_b():
    """The breakdown test must scale with the H column, not ||b|| (r3
    review: b with huge norm falsely declared an invariant subspace)."""
    n = 40
    rng = np.random.default_rng(10)
    S = (sp.random(n, n, 0.2, random_state=rng) * 0.4 - sp.identity(n)).tocsr()
    A = sparse.csr_array(S)
    b = (rng.standard_normal(n) * 1e16).astype(np.float32)
    y = np.asarray(linalg.funm_multiply_krylov(
        scipy.linalg.expm, A, b, restart_every_m=15
    ))
    ref = scipy.linalg.expm(S.toarray()) @ b
    np.testing.assert_allclose(y, ref, rtol=1e-3)


def test_eigs_raises_arpack_no_convergence_with_partials():
    n = 60
    rng = np.random.default_rng(11)
    S = sp.random(n, n, 0.15, random_state=rng).tocsr()
    A = sparse.csr_array(S)
    with pytest.raises(linalg.ArpackNoConvergence) as ei:
        linalg.eigs(A, k=5, which="SM", maxiter=1, tol=1e-14)
    assert hasattr(ei.value, "eigenvalues")
    assert isinstance(ei.value, linalg.ArpackError)
