"""sparse_tpu-backed implementations of pyamg's smoothed-aggregation core.

Reference analog: ``examples/pyamg_to_legate/wrapper.py`` — the same six
entry points pyamg dispatches through (strength of connection, aggregation,
tentative prolongator, prolongation smoother, Jacobi relaxation, stencil
gallery), each re-routed to the TPU-native library. The heavy lifting lives
in ``examples/amg.py`` (tropical-semiring MIS aggregation, SpGEMM Galerkin
products); this module adapts pyamg's calling conventions and numpy interop,
and ``patch(pyamg)`` swaps them in everywhere pyamg already imported the
originals.
"""

from __future__ import annotations

import os
import sys

import numpy as np

_EXAMPLES = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _EXAMPLES not in sys.path:
    sys.path.insert(0, _EXAMPLES)

import amg as _amg  # examples/amg.py: the sparse_tpu AMG building blocks
import sparse_tpu as sparse


def symmetric_strength_of_connection(A, theta=0.0):
    """pyamg.strength.symmetric_strength_of_connection analog."""
    return _amg.strength(sparse.csr_array(A.tocsr()), theta=theta)


def standard_aggregation(C, **kwargs):
    """pyamg.aggregation.standard_aggregation analog: MIS(2) aggregation
    driven by the tropical-semiring SpMV tournament (reference
    wrapper.py:118-139 PMIS)."""
    AggOp, mis = _amg.mis_aggregate(sparse.csr_array(C.tocsr()))
    return AggOp, np.asarray(mis)


def fit_candidates(AggOp, B):
    """pyamg.aggregation.fit_candidates analog."""
    if not isinstance(AggOp, sparse.SparseArray):
        AggOp = sparse.csr_array(AggOp.tocsr())
    return _amg.fit_candidates(AggOp, np.asarray(B))


def jacobi_prolongation_smoother(S, T, C, B, omega=4.0 / 3.0, degree=1, **kwargs):
    """pyamg.aggregation.jacobi_prolongation_smoother analog:
    P = (I - (omega/rho) D^-1 S)^degree T."""
    Ss = S if isinstance(S, sparse.SparseArray) else sparse.csr_array(S.tocsr())
    Ts = T if isinstance(T, sparse.SparseArray) else sparse.csr_array(T.tocsr())
    P, rho = _amg.smooth_prolongator(Ss, Ts, k=degree, omega=omega)
    S.rho_D_inv = rho  # cached like the reference (wrapper.py:76)
    return P


def jacobi(A, x, b, iterations=1, omega=1.0):
    """pyamg.relaxation.relaxation.jacobi analog (in-place on x)."""
    D = np.asarray(A.diagonal())
    rho = getattr(A, "rho_D_inv", None)
    if rho is None:
        Dinv_A = A.multiply((1.0 / D)[:, None])
        rho = _amg.estimate_spectral_radius(Dinv_A)
        A.rho_D_inv = rho
    for _ in range(iterations):
        y = np.asarray(A @ x)
        x += (omega / rho) * (np.asarray(b) - y) / D


def stencil_grid(S, grid, dtype=None, format=None):
    """pyamg.gallery.stencil_grid analog (vectorized assembly)."""
    A = _amg.stencil_grid(np.asarray(S), tuple(grid))
    A = sparse.csr_array(A.tocsr()) if not isinstance(A, sparse.SparseArray) else A
    if dtype is not None:
        A = A.astype(dtype)
    return A.asformat(format) if format else A


def patch(pyamg):
    """Swap the sparse_tpu implementations into every alias pyamg's loaded
    modules hold (reference wrapper.py:200-248)."""
    _HERE = os.path.dirname(os.path.abspath(__file__))
    if _HERE not in sys.path:
        sys.path.insert(0, _HERE)
    from patcher import patch_symbol_everywhere

    pairs = [
        (
            pyamg.strength.symmetric_strength_of_connection,
            symmetric_strength_of_connection,
        ),
        (pyamg.aggregation.standard_aggregation, standard_aggregation),
        (pyamg.aggregation.fit_candidates, fit_candidates),
        (
            pyamg.aggregation.jacobi_prolongation_smoother,
            jacobi_prolongation_smoother,
        ),
        (pyamg.relaxation.relaxation.jacobi, jacobi),
        (pyamg.gallery.stencil_grid, stencil_grid),
    ]
    patchers = []
    for target, repl in pairs:
        patchers.extend(patch_symbol_everywhere(target, repl))
    return patchers
