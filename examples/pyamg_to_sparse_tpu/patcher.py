"""Patch every imported alias of a symbol across loaded modules.

Reference analog: ``examples/pyamg_to_legate/patcher.py`` (itself the
standard unittest.mock recipe for replacing a function everywhere it has
already been imported, including ``from x import y as z`` aliases).
"""

from __future__ import annotations

import sys
import unittest.mock as mock


def patch_symbol_everywhere(target, replacement, match_prefix=None, skip_substring="test"):
    """Start a mock patcher for every module-level binding of ``target``.

    Walks ``sys.modules``, finds names bound to ``target`` (however they
    were imported), and patches each to call ``replacement``. Returns the
    list of active patchers; call ``.stop()`` on each to undo.
    """
    patchers = []
    for module in list(sys.modules.values()):
        name = getattr(module, "__name__", "")
        if match_prefix is not None and not name.startswith(match_prefix):
            continue
        if skip_substring is not None and skip_substring in name:
            continue
        for local_name, local in list(getattr(module, "__dict__", {}).items()):
            if local is target:
                p = mock.patch(f"{name}.{local_name}", autospec=True)
                m = p.start()
                m.side_effect = replacement
                patchers.append(p)
    return patchers
