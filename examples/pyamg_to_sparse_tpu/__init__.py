"""pyamg -> sparse_tpu external-ecosystem adapter.

Reference analog: ``/root/reference/examples/pyamg_to_legate/`` — route
pyamg's smoothed-aggregation building blocks through the accelerated sparse
library by patching every imported alias of the target symbols.
"""
