"""Geometric multigrid (V-cycle) preconditioned CG on the 2-D Poisson problem.

Reference analog: ``examples/gmg.py`` (541 LoC; the BASELINE.md "GMG" row —
4500^2/GPU, 37.2 iters/s @1 V100). Same algorithm: weighted-Jacobi smoothing,
Galerkin coarse operators A_c = R A P via SpGEMM, V-cycle used as the CG
preconditioner.

TPU-first redesigns vs the reference:
  * restriction operators are assembled **vectorized** (9-point stencil masks
    over the whole coarse grid at once) instead of the reference's Python
    loop over coarse points (gmg.py:303-380);
  * the weighted-Jacobi omega uses the pyamg formula omega/rho(D^-1 A);
  * machine-subset scoping for coarse levels (gmg.py:196-224) maps to the
    planned subset-mesh execution; single-chip here.

Run:  python examples/gmg.py -n 128 -levels 4 -maxiter 200
"""

import argparse

import numpy as np

from benchmark import get_phase_procs, parse_common_args

parser = argparse.ArgumentParser()
parser.add_argument("-n", type=int, default=128)
parser.add_argument("-levels", type=int, default=3)
parser.add_argument("-maxiter", type=int, default=200)
parser.add_argument("-tol", type=float, default=1e-8)
parser.add_argument("-gridop", default="linear", choices=["injection", "linear"])
parser.add_argument("-verbose", action="store_true")
parser.add_argument(
    "-dist",
    action="store_true",
    help="build Galerkin coarse operators with mesh-distributed SpGEMM and "
    "solve with a distributed V-cycle-preconditioned CG over the mesh",
)
parser.add_argument(
    "--no-grid",
    action="store_true",
    help="disable the structured-grid stencil pipeline (models/gmg_grid.py) "
    "and use the generic sparse-matrix hierarchy on TPU too",
)
args, _ = parser.parse_known_args()
common, timer, _np, sparse, linalg, use_tpu = parse_common_args()


def _spgemm(X, Y):
    """Galerkin sparse @ sparse (mesh-distributed under -dist; shared
    switch in benchmark.galerkin_spgemm)."""
    from benchmark import galerkin_spgemm

    return galerkin_spgemm(X, Y, args.dist and use_tpu)


def poisson2D(N):
    """5-point Poisson on an N x N grid via the DIA->CSC->T->CSR path."""
    first = np.full(N - 1, -1.0)
    diag_a = np.full(N * N - 1, -1.0)
    diag_a[N - 1 :: N] = 0.0
    diag_g = -1.0 * np.ones(N * (N - 1))
    diag_c = 4.0 * np.ones(N * N)
    diagonals = [diag_g, diag_a, diag_c, diag_a, diag_g]
    offsets = [-N, -1, 0, 1, N]
    return sparse.diags(diagonals, offsets, dtype=np.float64).tocsc().T


def injection_operator(fine_dim):
    """R picking every second fine point (gmg.py:287) — vectorized."""
    fine_n = int(np.sqrt(fine_dim))
    coarse_n = fine_n // 2
    coarse_dim = coarse_n * coarse_n
    ij = np.arange(coarse_dim, dtype=np.int64)
    ci, cj = ij // coarse_n, ij % coarse_n
    cols = 2 * ci * fine_n + 2 * cj
    indptr = np.arange(coarse_dim + 1, dtype=np.int64)
    R = sparse.csr_matrix(
        (np.ones(coarse_dim), cols, indptr), shape=(coarse_dim, fine_dim)
    )
    return R, coarse_dim


def linear_operator(fine_dim):
    """Full-weighting 9-point restriction (gmg.py:303) — vectorized assembly:
    for each of the 9 stencil offsets, one masked COO slab over the whole
    coarse grid; duplicates/order resolved by the sort-based COO->CSR."""
    fine_n = int(np.sqrt(fine_dim))
    coarse_n = fine_n // 2
    coarse_dim = coarse_n * coarse_n
    ij = np.arange(coarse_dim, dtype=np.int64)
    ci, cj = ij // coarse_n, ij % coarse_n
    rows_l, cols_l, vals_l = [], [], []
    weights = {(-1, -1): 1, (-1, 0): 2, (-1, 1): 1,
               (0, -1): 2, (0, 0): 4, (0, 1): 2,
               (1, -1): 1, (1, 0): 2, (1, 1): 1}
    for (di, dj), w in weights.items():
        fi = 2 * ci + di
        fj = 2 * cj + dj
        ok = (fi >= 0) & (fi < fine_n) & (fj >= 0) & (fj < fine_n)
        rows_l.append(ij[ok])
        cols_l.append((fi * fine_n + fj)[ok])
        vals_l.append(np.full(int(ok.sum()), w / 16.0))
    rows = np.concatenate(rows_l)
    cols = np.concatenate(cols_l)
    vals = np.concatenate(vals_l)
    if use_tpu:
        R = sparse.coo_array((vals, (rows, cols)), shape=(coarse_dim, fine_dim)).tocsr()
    else:
        R = sparse.coo_matrix((vals, (rows, cols)), shape=(coarse_dim, fine_dim)).tocsr()
    return R, coarse_dim


def max_eigenvalue(matvec, n, iters=15, seed=0):
    """Power iteration + Rayleigh quotient (gmg.py:134) on a matvec
    closure — lets callers estimate rho(D^-1 A) without materializing
    the scaled matrix (a full SpGEMM+sort per level in the old form)."""
    rng = np.random.default_rng(seed)
    x1 = rng.random(n)
    for _ in range(iters):
        x1 = np.asarray(matvec(x1))
        x1 = x1 / np.linalg.norm(x1)
    return float(np.dot(x1, np.asarray(matvec(x1))))


class WeightedJacobi:
    def __init__(self, omega=4.0 / 3.0):
        self.level_params = []
        self._init_omega = omega

    def init_level_params(self, A, level):
        D_inv = 1.0 / np.asarray(A.diagonal())
        # pyamg-style: omega / rho(D^-1 A); the scaled operator is applied
        # as matvec closures (row scale after SpMV) — no materialized
        # D^-1 A product, no per-level SpGEMM sort
        Di = self._as_backend(D_inv, D_inv)
        Ac = A.tocsr()
        spectral_radius = max_eigenvalue(
            lambda x: Di * (Ac @ x), A.shape[1]
        )
        omega = self._init_omega / spectral_radius
        self.level_params.append((omega, D_inv))
        assert len(self.level_params) - 1 == level

    def pre(self, A, r, x, level):
        omega, D_inv = self.level_params[level]
        return omega * r * self._as_backend(D_inv, r)

    def post(self, A, r, x, level):
        omega, D_inv = self.level_params[level]
        return x + omega * (r - A @ x) * self._as_backend(D_inv, r)

    def coarse(self, A, r, x, level):
        return self.pre(A, r, x, level)

    @staticmethod
    def _as_backend(D_inv, like):
        # keep the smoother traceable: jnp arrays stay jnp (the whole V-cycle
        # then fuses into CG's while_loop); scipy path stays numpy
        if use_tpu:
            import jax.numpy as jnp

            return jnp.asarray(D_inv)
        return D_inv


def _restrict_stencil(r, fine_n, coarse_n, gridop):
    """Apply the restriction R as a separable strided stencil on the 2-D
    grid — TPU-first: three strided slices + weighted add per axis (pure
    VPU elementwise, exact f32) instead of a rectangular gather SpMV.
    A 1-channel XLA conv was tried first: 15x slower on v5e (MXU-shaped
    op at channel count 1) and bf16-rounded. Exactly the linear map of
    injection_operator/linear_operator (oracle-tested)."""
    import jax.numpy as jnp

    cn = coarse_n
    X = r.reshape(fine_n, fine_n)
    if gridop == "injection":
        return X[0 : 2 * cn : 2, 0 : 2 * cn : 2].reshape(-1)

    def r1(Y):  # [1,2,1]/4 at stride 2 along axis 0 of a 1-padded array
        return (
            Y[0 : 2 * cn : 2, :] + 2.0 * Y[1 : 2 * cn + 1 : 2, :]
            + Y[2 : 2 * cn + 2 : 2, :]
        ) * jnp.asarray(0.25, Y.dtype)

    Xp = jnp.pad(X, 1)
    return r1(r1(Xp).T).T.reshape(-1)


def _prolong_stencil(xc, fine_n, coarse_n, gridop):
    """Apply P = R.T as the transposed separable stencil: strided
    scatter-adds of the coarse values onto the fine grid."""
    import jax.numpy as jnp

    cn = coarse_n
    Z = xc.reshape(cn, cn)
    if gridop == "injection":
        out = jnp.zeros((fine_n, fine_n), dtype=Z.dtype)
        return out.at[0 : 2 * cn : 2, 0 : 2 * cn : 2].set(Z).reshape(-1)

    def p1(Y):  # transpose of r1 along axis 0: coarse rows -> fine rows
        half = jnp.asarray(0.5, Y.dtype)
        quarter = jnp.asarray(0.25, Y.dtype)
        out = jnp.zeros((fine_n, Y.shape[1]), Y.dtype)
        out = out.at[0 : 2 * cn : 2, :].add(half * Y)          # f = 2c
        out = out.at[1 : 2 * cn + 1 : 2, :].add(quarter * Y)   # f = 2c+1
        out = out.at[1 : 2 * cn - 2 : 2, :].add(quarter * Y[1:, :])  # f = 2c-1
        return out

    return p1(p1(Z).T).T.reshape(-1)


class GMG:
    """V-cycle preconditioner (gmg.py:148)."""

    def __init__(self, A, shape, levels, gridop):
        self.A = A
        self.shape = shape
        self.N = int(np.prod(shape))
        self.levels = levels
        self.gridop = gridop
        self.restriction_op = {
            "injection": injection_operator,
            "linear": linear_operator,
        }[gridop]
        self.smoother = WeightedJacobi()
        self.grid_dims = []  # per level: (fine_n, coarse_n)
        self.operators = self.compute_operators(A)

    def compute_operators(self, A):
        operators = []
        dim = self.N
        self.smoother.init_level_params(A, 0)
        for level in range(self.levels - 1):
            fine_n = int(np.sqrt(dim))
            R, dim = self.restriction_op(dim)
            self.grid_dims.append((fine_n, int(np.sqrt(dim))))
            P = R.T.tocsr()
            A = _spgemm(_spgemm(R, A), P).tocsr()  # Galerkin: two SpGEMMs
            self.smoother.init_level_params(A, level + 1)
            operators.append((R, A, P))
        return operators

    def cycle(self, r):
        # fully traceable (sparse ops + elementwise): under the sparse_tpu
        # package the entire V-cycle inlines into CG's compiled while_loop
        return self._cycle(self.A, r, 0)

    def _cycle(self, A, r, level):
        if level == self.levels - 1:
            return self.smoother.coarse(A, r, None, level=level)
        R, coarse_A, P = self.operators[level]
        x = self.smoother.pre(A, r, None, level=level)
        fine_r = r - A @ x
        if use_tpu:
            # stencil (conv) form of R/P: the rectangular transfer
            # operators are the one part of the cycle with no banded
            # (DIA) fast path, and the gather SpMV is the V-cycle's
            # bottleneck on TPU — the conv form is exact and XLA-native
            fn, cn = self.grid_dims[level]
            coarse_r = _restrict_stencil(fine_r, fn, cn, self.gridop)
            coarse_x = self._cycle(coarse_A, coarse_r, level + 1)
            x_corrected = x + _prolong_stencil(coarse_x, fn, cn, self.gridop)
        else:
            coarse_r = R @ fine_r
            coarse_x = self._cycle(coarse_A, coarse_r, level + 1)
            x_corrected = x + P @ coarse_x
        return self.smoother.post(A, r, x_corrected, level=level)

    def linear_operator(self):
        if use_tpu:
            return linalg.LinearOperator(
                self.A.shape, dtype=np.float64, matvec=lambda r: self.cycle(r)
            )
        import scipy.sparse.linalg as sla

        return sla.LinearOperator(
            self.A.shape, dtype=np.float64, matvec=lambda r: self.cycle(r)
        )


def build_dist_cycle(mg, mesh, replicate_below: int = 2048):
    """Mesh-sharded weighted-Jacobi V-cycle over the geometric hierarchy
    (shared machinery: ``sparse_tpu.parallel.multigrid``). The coarsest
    level applies the smoother, as in GMG._cycle — no dense solve.

    Levels at or below ``replicate_below`` rows run as a dense REPLICATED
    tail (one gather in, one scatter out, zero per-level collectives) —
    the fix for the reference's coarse-level weak-scaling collapse
    (SURVEY §6: 4% efficiency at 192 GPUs).
    """
    from sparse_tpu.parallel.multigrid import (
        make_dist_vcycle,
        make_replicated_tail,
        shard_hierarchy,
        tail_crossover,
    )

    As = [mg.A] + [op[1] for op in mg.operators]
    RPs = [(op[0], op[2]) for op in mg.operators]
    L = len(As)
    # no bottom_always: a smoother bottom never NEEDS replication, so a
    # hierarchy whose coarsest level is still large stays fully sharded
    # (densifying it would be an O(n^2) replicated allocation)
    c = tail_crossover([A.shape[0] for A in As], replicate_below)

    def pad_w(i, Ad):
        omega, D_inv = mg.smoother.level_params[i]
        # pad slots get omega*1.0 — inert (padded inputs are exactly zero)
        return float(omega) * (
            Ad.pad_out_vector(np.asarray(D_inv) - 1.0) + 1.0
        )

    if c >= L:  # fully sharded, smoother bottom
        ops, _ = shard_hierarchy(As, RPs, mesh)
        weights = [pad_w(i, ops[i][0]) for i in range(L)]
        return ops[0][0], make_dist_vcycle(
            ops, weights, coarse_apply=lambda rp: weights[-1] * rp
        )

    ops, spl_list = shard_hierarchy(As[: c + 1], RPs[:c], mesh)
    weights = [pad_w(i, ops[i][0]) for i in range(c)]
    weights.append(None)  # level c enters the replicated tail

    def host_w(i):
        omega, D_inv = mg.smoother.level_params[i]
        return float(omega) * np.asarray(D_inv)

    coarse_apply = make_replicated_tail(
        As[c:],
        RPs[c:],
        [host_w(i) for i in range(c, L - 1)],
        spl_list[-1],
        ops[-1][0].R,
        bottom="smooth",
        bottom_weight=host_w(L - 1),
    )
    return ops[0][0], make_dist_vcycle(ops, weights, coarse_apply)


def main_grid():
    """Structured-grid pipeline (sparse_tpu/models/gmg_grid.py): stencil
    hierarchy via comb-probed Galerkin products, grid-space V-cycle, the
    whole PCG one compiled while_loop. Numerically the same hierarchy as
    the generic path (oracle-pinned in tests/test_gmg_grid.py); replaces
    its two dominant costs — host COO sorts + eager power iteration in
    init (~52 s at n=4000 measured r3) and CSR/gather ops in the cycle."""
    import jax
    import jax.numpy as jnp

    from sparse_tpu.models import gmg_grid as gg

    N = args.n
    dtype = jnp.float64 if common.precision == "f64" else jnp.float32
    build, solve = get_phase_procs(use_tpu)
    timer.start()
    with build:
        rng = np.random.default_rng(0)
        b = jnp.asarray(rng.random(N * N), dtype=dtype)
    print(f"Data creation time: {timer.stop():.1f} ms")

    timer.start()
    with build:
        hier = gg.build_hierarchy(N, args.levels, args.gridop, dtype=dtype)
    print(f"GMG init time: {timer.stop():.1f} ms")

    with solve:
        if args.dist:
            # GSPMD distribution: row-shard every level's planes and the
            # vectors; the SAME vcycle/cg code below then compiles into a
            # multi-device program with XLA-inserted halo collectives
            # (oracle-pinned vs single-device in tests/test_gmg_grid.py)
            from sparse_tpu.parallel.mesh import get_mesh

            hier, vec_sharding = gg.shard_hierarchy_grid(hier, get_mesh())
            b = jax.device_put(b, vec_sharding)
        else:
            # commit the stencil planes (built CPU-side) to the
            # accelerator: jit ARGUMENTS that stay host-resident would
            # re-cross the device link every call (kernels/cg_dia.py
            # residency note). Arrays only — the per-level grid size n is
            # a PYTHON int feeding static_argnums and must not become a
            # jax Array.
            from sparse_tpu.utils import commit_to_exec_device

            hier = [
                (
                    dict(
                        zip(st.keys(), commit_to_exec_device(tuple(st.values())))
                    ),
                    commit_to_exec_device((w,))[0],
                    n,
                )
                for (st, w, n) in hier
            ]
            b = commit_to_exec_device((b,))[0]
        st0 = hier[0][0]
        vc = gg.make_vcycle(hier, args.gridop)
        mv = jax.jit(
            lambda v: gg.stencil_apply(st0, v.reshape(N, N)).reshape(-1)
        )
        npdt = np.float64 if common.precision == "f64" else np.float32
        A_op = linalg.LinearOperator((N * N, N * N), dtype=npdt, matvec=mv)
        M = linalg.LinearOperator((N * N, N * N), dtype=npdt, matvec=vc)

        from benchmark import solve_timed_best_of_2

        x, iters, total_ms = solve_timed_best_of_2(
            lambda: linalg.cg(A_op, b, tol=args.tol, maxiter=args.maxiter, M=M),
            timer,
        )

    resid = float(np.linalg.norm(np.asarray(mv(x)) - np.asarray(b)))
    print(f"Iterations: {iters}  residual: {resid:.3e}")
    print(f"Solve time: {total_ms:.1f} ms")
    print(f"Iterations / sec: {iters / (total_ms / 1000.0):.3f}")


def main():
    N = args.n
    build, solve = get_phase_procs(use_tpu)
    timer.start()
    with build:
        A = poisson2D(N).tocsr()
        rng = np.random.default_rng(0)
        b = rng.random(N * N)
    print(f"Data creation time: {timer.stop():.1f} ms")

    timer.start()
    with build:
        mg = GMG(A=A, shape=(N, N), levels=args.levels, gridop=args.gridop)
        M = mg.linear_operator()
    print(f"GMG init time: {timer.stop():.1f} ms")

    callback = None
    if args.verbose:
        def callback(x):
            print(f"Residual: {np.linalg.norm(b - np.asarray(A @ x)):.3e}")

    with solve:
        if use_tpu and args.dist:
            from benchmark import solve_dist_cg_timed
            from sparse_tpu.parallel.mesh import get_mesh

            A0d, cycle = build_dist_cycle(mg, get_mesh())
            x, iters, total_ms = solve_dist_cg_timed(
                A0d, cycle, b, timer, tol=args.tol, maxiter=args.maxiter
            )
            resid = float(np.linalg.norm(np.asarray(A @ x) - b))
            print(f"Iterations: {iters}  residual: {resid:.3e}")
            print(f"Solve time: {total_ms:.1f} ms")
            print(f"Iterations / sec: {iters / (total_ms / 1000.0):.3f}")
            return
        _ = float(np.linalg.norm(np.asarray(A @ np.zeros(A.shape[1]))))  # warm up
        if use_tpu and callback is None:
            import os as _os

            if _os.environ.get("SPARSE_TPU_SPMV_MODE") is None:
                # banded level operators: Mosaic DIA kernel beats the XLA
                # shift-add form (+17% measured on v5e at n=1000); safe —
                # cached_prepared_spmv falls back off-TPU
                from sparse_tpu.config import settings

                settings.spmv_mode = "pallas"
            from benchmark import solve_timed_best_of_2

            x, iters, total_ms = solve_timed_best_of_2(
                lambda: linalg.cg(A, b, tol=args.tol, maxiter=args.maxiter, M=M),
                timer,
            )
        else:
            timer.start()
            if use_tpu:
                x, iters = linalg.cg(
                    A, b, tol=args.tol, maxiter=args.maxiter, M=M,
                    callback=callback,
                )
            else:
                it = [0]

                def count(xk):
                    it[0] += 1

                x, _ = linalg.cg(
                    A, b, rtol=args.tol, maxiter=args.maxiter, M=M,
                    callback=count,
                )
                iters = it[0]
            total_ms = timer.stop(fence=x)

    resid = float(np.linalg.norm(np.asarray(A @ x) - b))
    print(f"Iterations: {iters}  residual: {resid:.3e}")
    print(f"Solve time: {total_ms:.1f} ms")
    print(f"Iterations / sec: {iters / (total_ms / 1000.0):.3f}")


if __name__ == "__main__":
    # grid pipeline is the default on the sparse_tpu package (single-
    # device AND -dist, where it distributes via sharding annotations);
    # --no-grid keeps the generic sparse-matrix machinery exercised,
    # including the explicit DistCSR/replicated-tail -dist path.
    if use_tpu and not args.no_grid:
        main_grid()
    else:
        main()
