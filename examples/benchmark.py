"""Shared benchmark harness for the examples.

Reference analog: ``examples/benchmark.py`` — Timer protocol (LegateTimer uses
time futures so timing doesn't synchronize, benchmark.py:18-31), per-phase
machine scoping (benchmark.py:92-117), and the ``--package legate|cupy|scipy``
switch (benchmark.py:120-140).

TPU translation:
  * the future-based timer becomes a fetch-fence timer: ``stop(fence=arr)``
    pulls one scalar from the last result, which orders the host clock after
    all device work (jax dispatch is async; plain block_until_ready is not a
    reliable fence through remote-tunnel platforms);
  * machine phase scoping becomes ``jax.default_device`` scoping: build
    phases can run on CPU while solve phases run on the TPU chip;
  * ``--package sparse_tpu|scipy`` keeps the scipy oracle runnable from every
    example for comparison runs.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

# allow running the examples straight from the repo checkout
_repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _repo_root not in sys.path:
    sys.path.insert(0, _repo_root)


class Timer:
    def start(self):
        self._t0 = time.perf_counter()

    def stop(self, fence=None) -> float:
        """Milliseconds since start(). ``fence`` orders the clock after device
        work by fetching one scalar from the given array."""
        if fence is not None:
            _fetch_scalar(fence)
        return (time.perf_counter() - self._t0) * 1000.0


def _fetch_scalar(arr):
    import numpy as np

    a = arr
    while getattr(a, "ndim", 0) > 0:
        a = a[tuple(0 for _ in range(a.ndim))]
    return float(np.real(np.asarray(a)))


def parse_common_args(extra=None):
    """Returns (args, timer, np_like, sparse, linalg, use_tpu_package)."""
    parser = argparse.ArgumentParser(add_help=False)
    parser.add_argument(
        "--package", default="sparse_tpu", choices=["sparse_tpu", "scipy"]
    )
    parser.add_argument(
        "--precision", default="f64", choices=["f32", "f64"],
        help="f64 enables x64 (emulated on TPU); f32 is TPU-native",
    )
    parser.add_argument("--build-on-cpu", action="store_true",
                        help="run construction phases on the host CPU device")
    args, _ = parser.parse_known_args()

    if args.package == "sparse_tpu":
        import jax

        # honor JAX_PLATFORMS=cpu even when a platform plugin tries to
        # override it (same pattern as tests/conftest.py)
        if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
            jax.config.update("jax_platforms", "cpu")
        if args.precision == "f64":
            jax.config.update("jax_enable_x64", True)
        from sparse_tpu.utils import enable_compilation_cache

        enable_compilation_cache()  # remote-tunnel compiles are 20-40 s each
        import numpy as np

        import sparse_tpu as sparse
        from sparse_tpu import linalg

        return args, Timer(), np, sparse, linalg, True
    else:
        import numpy as np
        import scipy.sparse as sparse
        import scipy.sparse.linalg as linalg

        return args, Timer(), np, sparse, linalg, False


def get_phase_procs(use_tpu: bool):
    """(build_scope, solve_scope) context managers — the machine-scoping
    analog (benchmark.py:92-117). On TPU: device placement scopes."""
    import contextlib

    if not use_tpu:
        return contextlib.nullcontext(), contextlib.nullcontext()
    import jax

    # jax.devices() lists only the DEFAULT platform — under a TPU plugin
    # the CPU backend never appears there, which silently routed the whole
    # build phase through the accelerator (every constructor op a tunnel
    # round trip; GMG init at n=2000 alone blew the bench window). Ask for
    # the cpu backend explicitly; it coexists with the accelerator client.
    try:
        cpus = jax.devices("cpu")
    except RuntimeError:
        cpus = None
    accel = jax.devices()[0]
    build = jax.default_device(cpus[0]) if cpus and accel.platform != "cpu" else contextlib.nullcontext()
    solve = jax.default_device(accel)
    return build, solve


def solve_timed_best_of_2(solve, timer):
    """Shared estimator block for the single-device benchmark examples:
    one warm-up solve outside the clock (the reference's CUDA tasks are
    prebuilt), two timed solves, and BOTH estimators disclosed — min-of-2
    approximates machine capability under shared-tunnel throughput swings
    (up to 4x run-to-run), mean-of-2 is the comparable-estimator number
    (the reference baselines are means over dedicated-node runs).

    ``solve`` is a zero-arg callable returning (x, iters) with identical
    arguments each call, so the timed calls reuse the compiled while_loop.
    Prints the disclosure lines (bench.py parses "Iterations / sec
    (mean)") and returns (x, iters, min_ms).
    """
    _ = solve()
    timer.start()
    x, iters = solve()
    first_ms = timer.stop(fence=x)
    timer.start()
    x, iters = solve()
    second_ms = timer.stop(fence=x)
    mean_ms = (first_ms + second_ms) / 2.0
    min_ms = min(first_ms, second_ms)
    print(f"Timing: 2 timed solves, min {min_ms:.1f} ms / mean {mean_ms:.1f} ms")
    print(f"Iterations / sec (mean): {iters / (mean_ms / 1000.0):.3f}")
    return x, iters, min_ms


def solve_dist_cg_timed(A0d, cycle, b, timer, tol, maxiter, conv_test_iters=5):
    """Shared -dist solve block for the multigrid examples: compile the
    distributed preconditioned CG outside the timing, fence on a host
    scalar read, and fetch the full solution only after the clock stops.
    Returns (x, iters, total_ms)."""
    import jax.numpy as jnp

    from sparse_tpu.parallel.dist import make_dist_cg

    solver = make_dist_cg(
        A0d, tol=tol, maxiter=maxiter, M=cycle, conv_test_iters=conv_test_iters
    )
    bp = A0d.pad_out_vector(b)
    x0p = jnp.zeros_like(bp)
    solver(bp, x0p)[0].block_until_ready()  # compile outside timing
    timer.start()
    xp, iters, _ = solver(bp, x0p)
    iters = int(iters)  # completion fence (host scalar read)
    total_ms = timer.stop(fence=xp)
    x = A0d.unpad_vector(xp)  # full-vector fetch outside the timing
    return x, iters, total_ms


def galerkin_spgemm(X, Y, dist: bool):
    """Sparse @ sparse for hierarchy setup, routed through the
    mesh-distributed row-gather SpGEMM (parallel.spgemm.dist_spgemm;
    reference csr.py:1390-1490) when ``dist`` — shared by the -dist modes
    of the multigrid examples."""
    if dist:
        from sparse_tpu.parallel import dist_spgemm

        return dist_spgemm(X.tocsr(), Y.tocsr())
    return X @ Y
