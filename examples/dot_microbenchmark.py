"""SpMV/SpMM microbenchmark on a banded matrix.

Reference analog: ``examples/dot_microbenchmark.py`` (the BASELINE.md "CSR
SpMV" row: 10M rows/GPU, 11 nnz/row, f64, iterations/sec).

Run:  python examples/dot_microbenchmark.py -n 10000000 -i 25 --precision f32
"""

import argparse

from benchmark import get_phase_procs, parse_common_args

parser = argparse.ArgumentParser()
parser.add_argument("-n", type=int, default=100)
parser.add_argument("-i", type=int, default=25)
parser.add_argument("-nnz-per-row", type=int, default=11)
parser.add_argument("-op", choices=["spmv", "spmm"], default="spmv")
parser.add_argument("-k", type=int, default=32)
args, _ = parser.parse_known_args()
common, timer, np, sparse, _, use_tpu = parse_common_args()
n, iters, nnz_per_row = args.n, args.i, args.nnz_per_row

init_procs, bench_procs = get_phase_procs(use_tpu)

dtype = np.float32 if (use_tpu and common.precision == "f32") else np.float64

with init_procs:
    A = sparse.diags(
        [1] * nnz_per_row,
        [x - (nnz_per_row // 2) for x in range(nnz_per_row)],
        shape=(n, n),
        format="csr",
        dtype=dtype,
    )

with bench_procs:
    if args.op == "spmv":
        x = np.ones((n,), dtype=dtype)
    else:
        x = np.ones((n, args.k), dtype=dtype)

    y = A.dot(x)  # warm up / compile
    timer.start()
    for _ in range(iters):
        y = A.dot(x)
    total = timer.stop(fence=y) / 1000.0 if use_tpu else timer.stop() / 1000.0

flops = 2 * A.nnz * (1 if args.op == "spmv" else args.k)
print(f"Iterations / sec: {iters / total:.3f}")
print(f"GFLOP/s: {flops * iters / total / 1e9:.2f}")
