"""Weak-scaling harness: constant per-chip problem size over a growing mesh.

Reference analog: the Summit sweep scripts (``scripts/summit/run_legate_pde.sh``
— grid side scales as n*sqrt(g)) behind every BASELINE.md scaling row. On a
real TPU pod this measures ICI-scaling of the distributed CG (halo ppermute +
GSPMD psums); on the virtual CPU mesh it validates the harness itself.

Run:  python examples/weak_scaling.py -n 512 -shards 1,2,4,8 -iters 100
"""

import argparse
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-n", type=int, default=512, help="grid side per chip")
    parser.add_argument("-shards", default="1,2,4,8")
    parser.add_argument("-iters", type=int, default=100)
    args, _ = parser.parse_known_args()

    import jax

    if os.environ.get("JAX_PLATFORMS", "") in ("", "cpu"):
        # the axon TPU-tunnel plugin overrides the env var; pin the knob
        jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS") or None)

    import numpy as np

    from sparse_tpu.models.poisson import laplacian_2d_csr_host
    from sparse_tpu.parallel.dist import make_dist_cg, shard_csr
    from sparse_tpu.parallel.mesh import get_mesh

    shards = [int(s) for s in args.shards.split(",")]
    results = []
    base_rate = None
    for S in shards:
        side = int(round(args.n * math.sqrt(S)))
        A = laplacian_2d_csr_host(side, dtype=np.float32)
        mesh = get_mesh(S)
        D = shard_csr(A, mesh=mesh, balanced=True)
        b = np.random.default_rng(0).standard_normal(A.shape[0]).astype(np.float32)
        bp = D.pad_out_vector(b)
        run = make_dist_cg(D, tol=0.0, maxiter=args.iters, conv_test_iters=args.iters)
        import jax.numpy as jnp

        xp, iters, _ = run(bp, jnp.zeros_like(bp))
        int(iters)  # compile + warm
        best = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            xp, iters, _ = run(bp, jnp.zeros_like(bp))
            int(iters)
            best = max(best, args.iters / (time.perf_counter() - t0))
        if base_rate is None:
            base_rate = best
        eff = best / base_rate
        from sparse_tpu.parallel.dist import comm_stats

        st = comm_stats(D, conv_test_iters=args.iters)
        results.append(
            {"shards": S, "rows": A.shape[0], "iters_per_s": round(best, 2),
             "efficiency": round(eff, 3),
             "halo_entries": st["halo_entries_per_spmv"],
             "collective_bytes_per_iter":
                 st["cg_iter_collective_bytes_per_shard"],
             "mode": st["mode"]}
        )
        print(
            f"S={S:3d}  rows={A.shape[0]:>10,}  {best:8.2f} iters/s  "
            f"efficiency {eff:6.1%}  halo {st['halo_entries_per_spmv']}  "
            f"{st['cg_iter_collective_bytes_per_shard']} B/iter"
        )
    print(json.dumps({"weak_scaling": results}))


def comm_models(args):
    """Predicted alltoallv traffic vs S for the shuffle-shaped components
    (no devices needed — the models are exact and structural): samplesort
    at constant L keys/shard, and the 2-D SpGEMM on a growing grid with a
    constant per-device Laplacian block. The signal mirrors the CG
    harness's comm columns: per-shard exchange bytes must track the
    per-shard WORKLOAD, never the mesh size."""
    # this path truly needs no devices: pin CPU unconditionally (the
    # harness presets JAX_PLATFORMS=axon and the plugin overrides the env
    # var, so the host SpGEMM inside the model would otherwise wedge in
    # remote backend init)
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from sparse_tpu.models.poisson import laplacian_2d_csr_host
    from sparse_tpu.parallel.sort import sort_comm_stats
    from sparse_tpu.parallel.spgemm import spgemm2d_comm_stats
    from sparse_tpu.utils import factor_int

    rng = np.random.default_rng(0)
    shards = [int(s) for s in args.shards.split(",")]
    sort_rows, spg_rows = [], []
    for S in shards:
        keys = rng.integers(0, 1 << 24, args.n * S).astype(np.int64)
        st = sort_comm_stats(keys, S, payloads=(np.ones(args.n * S, np.float32),))
        sort_rows.append(
            {"shards": S, "keys": args.n * S,
             "exchange_bytes_per_shard": st["exchange_bytes_per_shard_max"],
             "sample_bytes_per_shard": st["sample_allgather_bytes_per_shard"],
             "fallback": st["fallback_odd_even"]}
        )
        side = int(round(math.sqrt(args.n * S)))
        import sparse_tpu

        A = sparse_tpu.csr_array(laplacian_2d_csr_host(side, dtype=np.float32))
        gx, gy = factor_int(S)
        sg = spgemm2d_comm_stats(A, A, (gx, gy))
        spg_rows.append(
            {"shards": S, "grid": sg["grid"], "c_nnz": sg["c_nnz"],
             "replicate_bytes_per_device": sg["replicate_bytes_per_device"],
             "shuffle_bytes_per_device": sg["shuffle_bytes_per_device_max"]}
        )
        print(f"S={S:3d}  sort {st['exchange_bytes_per_shard_max']:>9,} B/shard"
              f"  spgemm2d grid={gx}x{gy} repl"
              f" {sg['replicate_bytes_per_device']:>10,} B"
              f" shuffle {sg['shuffle_bytes_per_device_max']:>9,} B")
    print(json.dumps({"sort_model": sort_rows, "spgemm2d_model": spg_rows}))


if __name__ == "__main__":
    import argparse as _ap

    _p = _ap.ArgumentParser(add_help=False)
    _p.add_argument("-models", action="store_true",
                    help="print predicted comm bytes vs S (no devices)")
    _p.add_argument("-n", type=int, default=512)
    _p.add_argument("-shards", default="1,2,4,8")
    _args, _ = _p.parse_known_args()
    if _args.models:
        comm_models(_args)
    else:
        main()
