"""Weak-scaling harness: constant per-chip problem size over a growing mesh.

Reference analog: the Summit sweep scripts (``scripts/summit/run_legate_pde.sh``
— grid side scales as n*sqrt(g)) behind every BASELINE.md scaling row. On a
real TPU pod this measures ICI-scaling of the distributed CG (halo ppermute +
GSPMD psums); on the virtual CPU mesh it validates the harness itself.

Run:  python examples/weak_scaling.py -n 512 -shards 1,2,4,8 -iters 100
"""

import argparse
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-n", type=int, default=512, help="grid side per chip")
    parser.add_argument("-shards", default="1,2,4,8")
    parser.add_argument("-iters", type=int, default=100)
    args, _ = parser.parse_known_args()

    import jax

    if os.environ.get("JAX_PLATFORMS", "") in ("", "cpu"):
        # the axon TPU-tunnel plugin overrides the env var; pin the knob
        jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS") or None)

    import numpy as np

    from sparse_tpu.models.poisson import laplacian_2d_csr_host
    from sparse_tpu.parallel.dist import make_dist_cg, shard_csr
    from sparse_tpu.parallel.mesh import get_mesh

    shards = [int(s) for s in args.shards.split(",")]
    results = []
    base_rate = None
    for S in shards:
        side = int(round(args.n * math.sqrt(S)))
        A = laplacian_2d_csr_host(side, dtype=np.float32)
        mesh = get_mesh(S)
        D = shard_csr(A, mesh=mesh, balanced=True)
        b = np.random.default_rng(0).standard_normal(A.shape[0]).astype(np.float32)
        bp = D.pad_out_vector(b)
        run = make_dist_cg(D, tol=0.0, maxiter=args.iters, conv_test_iters=args.iters)
        import jax.numpy as jnp

        xp, iters, _ = run(bp, jnp.zeros_like(bp))
        int(iters)  # compile + warm
        best = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            xp, iters, _ = run(bp, jnp.zeros_like(bp))
            int(iters)
            best = max(best, args.iters / (time.perf_counter() - t0))
        if base_rate is None:
            base_rate = best
        eff = best / base_rate
        from sparse_tpu.parallel.dist import comm_stats

        st = comm_stats(D, conv_test_iters=args.iters)
        results.append(
            {"shards": S, "rows": A.shape[0], "iters_per_s": round(best, 2),
             "efficiency": round(eff, 3),
             "halo_entries": st["halo_entries_per_spmv"],
             "collective_bytes_per_iter":
                 st["cg_iter_collective_bytes_per_shard"],
             "mode": st["mode"]}
        )
        print(
            f"S={S:3d}  rows={A.shape[0]:>10,}  {best:8.2f} iters/s  "
            f"efficiency {eff:6.1%}  halo {st['halo_entries_per_spmv']}  "
            f"{st['cg_iter_collective_bytes_per_shard']} B/iter"
        )
    print(json.dumps({"weak_scaling": results}))


if __name__ == "__main__":
    main()
