"""pyamg integration driver: patch pyamg with sparse_tpu and solve Poisson.

Reference analog: ``examples/pyamg_legate_test.py`` — build a pyamg
smoothed-aggregation solver whose inner kernels (strength, aggregation,
prolongation smoothing, relaxation, gallery) run on the TPU-native library,
then solve a Poisson problem and report residual + timing.

pyamg is an optional external dependency; without it this driver exercises
the adapter functions standalone on the library's own AMG pipeline so the
integration surface stays covered in this environment.
"""

import argparse
import sys
import time

import numpy as np


def run_with_pyamg(n):
    import pyamg

    sys.path.insert(0, "examples/pyamg_to_sparse_tpu")
    from wrapper import patch

    patch(pyamg)
    A = pyamg.gallery.poisson((n, n), format="csr")
    ml = pyamg.smoothed_aggregation_solver(A)
    b = np.random.default_rng(0).random(A.shape[0])
    t0 = time.perf_counter()
    x = ml.solve(b, tol=1e-8)
    dt = time.perf_counter() - t0
    r = np.linalg.norm(b - A @ x)
    print(f"pyamg+sparse_tpu: n={n} residual={r:.3e} solve={dt*1e3:.1f} ms")


def run_standalone(n):
    """No pyamg installed: drive the adapter functions directly."""
    sys.path.insert(0, "examples/pyamg_to_sparse_tpu")
    import wrapper

    A = wrapper.stencil_grid(
        np.array([[0, -1, 0], [-1, 4, -1], [0, -1, 0]], dtype=float), (n, n)
    ).tocsr()
    C = wrapper.symmetric_strength_of_connection(A, theta=0.0)
    AggOp, mis = wrapper.standard_aggregation(C)
    B = np.ones((A.shape[0], 1))
    T, R = wrapper.fit_candidates(AggOp, B)
    P = wrapper.jacobi_prolongation_smoother(A, T, C, B)
    x = np.zeros(A.shape[0])
    b = np.random.default_rng(0).random(A.shape[0])
    wrapper.jacobi(A, x, b, iterations=3)
    r = np.linalg.norm(b - np.asarray(A @ x))
    print(
        f"standalone adapter: n={n} aggregates={AggOp.shape[1]} "
        f"P nnz={P.nnz} jacobi(3) residual={r:.3e}"
    )


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("-n", "--num-nodes", type=int, default=32)
    args, _ = parser.parse_known_args()
    try:
        import pyamg  # noqa: F401

        run_with_pyamg(args.num_nodes)
    except ImportError:
        print("pyamg not installed; running the adapter standalone")
        run_standalone(args.num_nodes)
