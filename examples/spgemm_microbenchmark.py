"""SpGEMM microbenchmark: banded A @ A.

Reference analog: ``examples/spgemm_microbenchmark.py``.

Run:  python examples/spgemm_microbenchmark.py -n 100000 -i 10
"""

import argparse

from benchmark import parse_common_args

parser = argparse.ArgumentParser()
parser.add_argument("-n", type=int, default=100)
parser.add_argument("-i", type=int, default=25)
parser.add_argument("-nnz-per-row", type=int, default=11)
args, _ = parser.parse_known_args()
common, timer, np, sparse, _, use_tpu = parse_common_args()
n, iters, nnz_per_row = args.n, args.i, args.nnz_per_row

A = sparse.diags(
    [1] * nnz_per_row,
    [x - (nnz_per_row // 2) for x in range(nnz_per_row)],
    shape=(n, n),
    format="csr",
    dtype=np.float64,
)
B = A.copy()

C = A @ B  # warm up
timer.start()
for _ in range(iters):
    C = A @ B
total = (timer.stop(fence=C.data) if use_tpu else timer.stop()) / 1000.0

print(f"Iterations / sec: {iters / total:.3f}")
