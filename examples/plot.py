"""Visualization harness: draw a grid graph with its MIS/aggregates.

Reference analog: ``examples/plot.py`` — trimesh + draw_graph + plot_mis
over a structured mesh, coloring MIS nodes. Headless-friendly: figures save
to PNG (``-o``) instead of requiring a display.

Run:  python examples/plot.py -n 8 -o mis.png
"""

import argparse
import math

import numpy as np


def trimesh(vertices, indices, ax):
    from matplotlib import collections

    vertices, indices = np.asarray(vertices), np.asarray(indices)
    triangles = vertices[indices.ravel(), :].reshape(
        (indices.shape[0], indices.shape[1], 2)
    )
    col = collections.PolyCollection(
        triangles, lw=1, edgecolor="black", facecolor="gray", alpha=0.5
    )
    ax.add_collection(col, autolim=True)
    ax.axis("off")
    ax.autoscale_view()


def draw_graph(mesh, P, out=None, labels=True):
    """mesh: COO adjacency over an N*N grid; P: 0/1 per-node coloring."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    N = int(math.sqrt(mesh.shape[0]))
    grid = np.meshgrid(range(N), range(N))
    V = np.vstack(list(map(np.ravel, grid))).T
    E = np.vstack((np.asarray(mesh.row), np.asarray(mesh.col))).T
    c = ["red" if p == 0 else "green" for p in P]

    fig = plt.figure()
    ax = plt.gca()
    trimesh(V, E, ax)
    ax.scatter(V[:, 0], V[:, 1], marker="o", s=400, c=c)
    if labels:
        for i in range(V.shape[0]):
            ax.annotate(str(i), (V[i, 0], V[i, 1]), ha="center", va="center")
    if out:
        fig.savefig(out, dpi=120, bbox_inches="tight")
        print(f"wrote {out}")
    else:
        plt.show()
    plt.close(fig)


def plot_mis(A, out=None):
    from amg import maximal_independent_set

    mis = maximal_independent_set(A.tocsr())
    P = np.zeros(A.shape[0])
    P[np.asarray(mis)] = 1
    draw_graph(A.tocoo(), P, out=out)
    return mis


if __name__ == "__main__":
    import sys

    sys.path.insert(0, "examples")
    parser = argparse.ArgumentParser()
    parser.add_argument("-n", type=int, default=8)
    parser.add_argument("-o", "--out", default="mis.png")
    args, _ = parser.parse_known_args()

    from amg import poisson2D

    A = poisson2D(args.n)
    mis = plot_mis(A, out=args.out)
    print(f"MIS size {len(mis)} of {A.shape[0]} nodes")
