"""Implicit heat-equation integration: BDF + sparse Laplacian Jacobian.

u_t = alpha * Lap(u) on an n x n grid (Dirichlet), semidiscretized to the
stiff linear ODE y' = alpha * L y with L this library's 5-point Laplacian.
The explicit RK methods need h ~ 1/||L|| steps (CFL); BDF takes steps
bounded only by accuracy, with each Newton solve an MXU-tiled LU apply —
the workload the reference's explicit-only integrate.py cannot run at
this stiffness. Usage:

    python examples/heat_implicit.py -n 24 -alpha 1.0 -t 0.1 [-explicit]
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

# honor JAX_PLATFORMS=cpu even when a platform plugin tries to override
# it (same workaround as examples/benchmark.py:70-75)
if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)  # stiff Newton wants f64

from sparse_tpu import csr_array  # noqa: E402
from sparse_tpu.integrate import solve_ivp  # noqa: E402
from sparse_tpu.models.poisson import laplacian_2d_csr_host  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", type=int, default=24)
    ap.add_argument("-alpha", type=float, default=1.0)
    ap.add_argument("-t", type=float, default=0.5)
    ap.add_argument("-rtol", type=float, default=1e-6)
    ap.add_argument("-explicit", action="store_true",
                    help="also time RK45 for the stiffness comparison")
    args = ap.parse_args()

    n = args.n
    A = laplacian_2d_csr_host(n)  # positive-definite 5-point stencil
    scale = args.alpha * (n + 1) ** 2  # 1/h^2: the true discrete Laplacian
    L = csr_array((-scale) * A.tocsr())  # y' = -alpha/h^2 A y (decay)
    N = n * n
    # interior Dirichlet nodes i/(n+1): sin(pi x)sin(pi y) sampled here
    # IS the discrete mode-1 eigenvector, so the decay check is exact
    x = np.linspace(0, 1, n + 2)[1:-1]
    X, Y = np.meshgrid(x, x, indexing="ij")
    y0 = (np.sin(np.pi * X) * np.sin(np.pi * Y)).ravel()

    def rhs(t, y):
        return L @ y

    t0 = time.perf_counter()
    sol = solve_ivp(rhs, (0.0, args.t), y0, method="BDF", jac=L,
                    rtol=args.rtol, atol=1e-9)
    dt_bdf = time.perf_counter() - t0
    print(f"BDF:  status={sol.status} steps={len(sol.t) - 1} "
          f"nfev={sol.nfev} nlu={sol.nlu} wall={dt_bdf:.2f}s")

    # the lowest Laplacian mode decays as exp(-lam1*t); compare
    lam1 = 4 * scale * (1 - np.cos(np.pi / (n + 1)))
    u_T = np.asarray(sol.y)[:, -1]
    decay = float(u_T @ y0 / (y0 @ y0))
    print(f"mode-1 decay: measured {decay:.6f} vs exp(-lam1*t) "
          f"{np.exp(-lam1 * args.t):.6f}")

    if args.explicit:
        t0 = time.perf_counter()
        rk = solve_ivp(rhs, (0.0, args.t), y0, method="RK45",
                       rtol=args.rtol, atol=1e-9)
        dt_rk = time.perf_counter() - t0
        print(f"RK45: status={rk.status} steps={len(rk.t) - 1} "
              f"nfev={rk.nfev} wall={dt_rk:.2f}s "
              f"(stiffness ratio nfev: {rk.nfev / max(sol.nfev, 1):.1f}x)")


if __name__ == "__main__":
    main()
