"""Algebraic multigrid (smoothed aggregation) preconditioned CG.

Reference analog: ``examples/amg.py`` (569 LoC; the BASELINE.md north-star
workload — 4096^2 Poisson at >=80% weak-scaling efficiency). Same algorithm:
strength-filtered MIS(2) aggregation computed with the tropical-semiring SpMV
(amg.py:199-283), tentative prolongator from near-nullspace candidates
(fit_candidates), Jacobi-smoothed prolongator, Galerkin coarse operators via
SpGEMM, V-cycle preconditioned CG.

TPU-first redesigns:
  * the MIS tournament runs on int32 tuples (index tie-break makes the order
    strict regardless of random-value collisions, so int64 randomness is not
    required — TPU-native lane width);
  * the V-cycle is fully traceable: smoothers are jnp elementwise ops and the
    coarse solve is a jnp dense solve, so CG + preconditioner compile into
    one XLA program;
  * per-level workspace caching (amg.py:284-331) is unnecessary — XLA owns
    buffers.

Run:  python examples/amg.py -n 128 -maxiter 200
"""

import argparse

import numpy as np

from benchmark import get_phase_procs, parse_common_args

parser = argparse.ArgumentParser()
parser.add_argument("-n", type=int, default=64)
parser.add_argument("-data", default="poisson", choices=["poisson", "diffusion"])
parser.add_argument("-theta", type=float, default=0.0)
parser.add_argument("-max_coarse", type=int, default=10)
parser.add_argument("-maxiter", type=int, default=None)
parser.add_argument("-tol", type=float, default=1e-8)
parser.add_argument("-verbose", action="store_true")
parser.add_argument(
    "-dist",
    action="store_true",
    help="build the hierarchy with mesh-distributed SpGEMM (Galerkin R@A@P) "
    "and solve with a distributed V-cycle-preconditioned CG over the mesh",
)
args, _ = parser.parse_known_args()
common, timer, _np, sparse, linalg, use_tpu = parse_common_args()

if use_tpu:
    import jax.numpy as jnp
else:
    jnp = np


def spg(X, Y):
    """Galerkin sparse @ sparse (mesh-distributed under -dist; shared
    switch in benchmark.galerkin_spgemm)."""
    from benchmark import galerkin_spgemm

    return galerkin_spgemm(X, Y, args.dist and use_tpu)


# ---------------------------------------------------------------------------
# Problem construction (amg.py:48-132) — vectorized stencil_grid
# ---------------------------------------------------------------------------
def stencil_grid(S, grid):
    """Sparse operator from a stencil S over an N-d grid: one COO slab per
    stencil offset with boundary masking (vectorized; the reference zeroes
    boundary connections diagonal-by-diagonal, amg.py:48-103)."""
    S = np.asarray(S, dtype=np.float64)
    grid = tuple(grid)
    N_v = int(np.prod(grid))
    idx = np.arange(N_v, dtype=np.int64)
    coords = np.unravel_index(idx, grid)
    center = tuple(s // 2 for s in S.shape)
    rows_l, cols_l, vals_l = [], [], []
    for off in np.ndindex(S.shape):
        w = S[off]
        if w == 0:
            continue
        d = tuple(o - c for o, c in zip(off, center))
        nbr = [coords[k] + d[k] for k in range(len(grid))]
        ok = np.ones(N_v, dtype=bool)
        for k in range(len(grid)):
            ok &= (nbr[k] >= 0) & (nbr[k] < grid[k])
        cols = np.ravel_multi_index([n[ok] for n in nbr], grid)
        rows_l.append(idx[ok])
        cols_l.append(cols)
        vals_l.append(np.full(int(ok.sum()), w))
    rows = np.concatenate(rows_l)
    cols = np.concatenate(cols_l)
    vals = np.concatenate(vals_l)
    if use_tpu:
        return sparse.coo_array((vals, (rows, cols)), shape=(N_v, N_v)).tocsr()
    return sparse.coo_matrix((vals, (rows, cols)), shape=(N_v, N_v)).tocsr()


def poisson2D(N):
    M = 2
    stencil = np.zeros((3,) * M)
    for i in range(M):
        stencil[(1,) * i + (0,) + (1,) * (M - i - 1)] = -1
        stencil[(1,) * i + (2,) + (1,) * (M - i - 1)] = -1
    stencil[(1,) * M] = 2 * M
    return stencil_grid(stencil, (N, N))


def diffusion2D(N, epsilon=1.0, theta=0.0):
    eps, th = float(epsilon), float(theta)
    C, S = np.cos(th), np.sin(th)
    CS, CC, SS = C * S, C**2, S**2
    a = (-1 * eps - 1) * CC + (-1 * eps - 1) * SS + (3 * eps - 3) * CS
    b = (2 * eps - 4) * CC + (-4 * eps + 2) * SS
    c = (-1 * eps - 1) * CC + (-1 * eps - 1) * SS + (-3 * eps + 3) * CS
    d = (-4 * eps + 2) * CC + (2 * eps - 4) * SS
    e = (8 * eps + 8) * CC + (8 * eps + 8) * SS
    stencil = np.array([[a, b, c], [d, e, d], [c, b, a]]) / 6.0
    return stencil_grid(stencil, (N, N))


# ---------------------------------------------------------------------------
# Smoothed-aggregation setup (amg.py:134-283)
# ---------------------------------------------------------------------------
def strength(A, theta=0.0):
    """Symmetric strength-of-connection filter (amg.py:134)."""
    if theta == 0:
        return A
    B = abs(A.copy()).tocoo()
    D = np.asarray(A.diagonal())
    data = np.asarray(B.data)
    row, col = np.asarray(B.row), np.asarray(B.col)
    keep = data >= theta * np.sqrt(np.abs(D[row] * D[col]))
    data = np.where(keep, data, 0.0)
    # column-wise normalization by the max entry
    colmax = np.zeros(A.shape[1])
    np.maximum.at(colmax, col, data)
    data = data / np.where(colmax[col] == 0, 1.0, colmax[col])
    nz = data != 0
    if use_tpu:
        return sparse.coo_array(
            (data[nz], (row[nz], col[nz])), shape=A.shape
        ).tocsr()
    return sparse.coo_matrix((data[nz], (row[nz], col[nz])), shape=A.shape).tocsr()


def fit_candidates(AggOp, B):
    """Tentative prolongator from near-nullspace candidates (amg.py:148)."""
    Q = AggOp.tocoo()
    Bsq = np.asarray(B).ravel() ** 2
    data = Bsq[np.asarray(Q.row)] * np.asarray(Q.data)
    colsum = np.zeros(AggOp.shape[1])
    np.add.at(colsum, np.asarray(Q.col), data)
    R = np.sqrt(colsum)
    data = data / np.where(R[np.asarray(Q.col)] == 0, 1.0, R[np.asarray(Q.col)])
    # data entries are B[row] * B[row] / R[col]; the tentative prolongator
    # has value B[row] / R[col] per (row, aggregate) pair
    vals = np.asarray(B).ravel()[np.asarray(Q.row)] / np.where(
        R[np.asarray(Q.col)] == 0, 1.0, R[np.asarray(Q.col)]
    )
    if use_tpu:
        T = sparse.coo_array(
            (vals, (np.asarray(Q.row), np.asarray(Q.col))), shape=AggOp.shape
        ).tocsr()
    else:
        T = sparse.coo_matrix(
            (vals, (np.asarray(Q.row), np.asarray(Q.col))), shape=AggOp.shape
        ).tocsr()
    return T, R.reshape(-1, 1)


def estimate_spectral_radius(A, maxiter=15, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.random(A.shape[0])
    y = x
    for _ in range(maxiter):
        x = x / np.linalg.norm(x)
        y = np.asarray(A @ x)
        x, y = y, x
    return float(np.dot(x, y) / np.linalg.norm(y))


def smooth_prolongator(A, T, k=1, omega=4.0 / 3.0, D=None):
    """P = (I - (omega/rho) D^-1 A) T (amg.py:171)."""
    if D is None:
        D = np.asarray(A.diagonal())
    D_inv = 1.0 / D
    D_inv_S = A.multiply(D_inv[:, None])
    rho = estimate_spectral_radius(D_inv_S)
    D_inv_S = D_inv_S * (omega / rho)
    P = T.tocsr()
    for _ in range(k):
        P = P - spg(D_inv_S, P)
    return P, rho


def maximal_independent_set(C, k=1, invalid=None, seed=0):
    """MIS(k) by tropical-semiring tournament (amg.py:199).

    On the sparse_tpu path the WHOLE round loop runs on device as one
    compiled ``lax.while_loop`` (``csr_array.mis_tropical``) — one host
    sync for the final flags instead of a device->host fetch per hop.
    The host loop remains as the generic fallback.
    """
    assert C.shape[0] == C.shape[1]
    N = C.shape[0]
    C = C.tocsr()
    if hasattr(C, "mis_tropical"):
        flags = np.asarray(C.mis_tropical(k=k, invalid=invalid, seed=seed))
        return np.nonzero(flags == 2)[0]
    rng = np.random.default_rng(seed)
    # int32 tuples: the index component breaks ties, so the lexicographic
    # order stays strict even under random-value collisions
    random_values = rng.integers(0, np.iinfo(np.int32).max, size=N, dtype=np.int32)
    x = np.stack(
        [np.ones(N, np.int32), random_values, np.arange(N, dtype=np.int32)], axis=1
    )
    active = N
    if invalid is not None:
        x[invalid, 0] = -1
        active -= int(invalid.sum())
    while True:
        z = np.array(C.tropical_spmv(x))
        for _ in range(1, k):
            z = np.array(C.tropical_spmv(z))
        mis_node = np.nonzero((x[:, 0] == 1) & (z[:, 2] == np.arange(N)))[0]
        x[mis_node, 0] = 2
        non_mis = np.nonzero((x[:, 0] == 1) & (z[:, 0] == 2))[0]
        x[non_mis, 0] = 0
        active -= len(mis_node) + len(non_mis)
        if active == 0:
            break
        assert 0 < active < N
    return np.nonzero(x[:, 0] == 2)[0]


def mis_aggregate(C):
    """Aggregates = nearest MIS(2) root, found by two tropical hops (amg.py:259)."""
    C = C.tocsr()
    N_fine = C.shape[0]
    if hasattr(C, "mis_tropical"):
        # device composition: MIS while_loop + the two routing hops run
        # compiled; the host fetches flags and columns once each
        flags = C.mis_tropical(k=2)
        col_dev, n_coarse = C.mis_aggregate_cols(flags)
        mis = np.nonzero(np.asarray(flags) == 2)[0]
        col = np.asarray(col_dev)
        N_coarse = int(n_coarse)
    else:
        mis = maximal_independent_set(C, 2)
        N_coarse = mis.size
        x = np.zeros((N_fine, 2), dtype=np.int32)
        x[mis, 0] = 2
        x[mis, 1] = np.arange(N_coarse, dtype=np.int32)
        y = np.array(C.tropical_spmv(x))
        y[:, 0] += x[:, 0]
        z = np.array(C.tropical_spmv(y))
        col = z[:, 1]
    data = np.ones(N_fine)
    row = np.arange(N_fine)
    if use_tpu:
        agg = sparse.coo_array((data, (row, col)), shape=(N_fine, N_coarse))
    else:
        agg = sparse.coo_matrix((data, (row, col)), shape=(N_fine, N_coarse))
    return agg.tocsr(), mis


# ---------------------------------------------------------------------------
# Hierarchy + V-cycle (amg.py:284-427)
# ---------------------------------------------------------------------------
class Level:
    def __init__(self, R=None, A=None, P=None, D=None, B=None, rho_DinvA=None):
        self.R, self.A, self.P, self.D, self.B = R, A, P, D, B
        self.rho_DinvA = rho_DinvA
        self.dense_A = None

    def presmoother(self, x, b, omega=4.0 / 3.0):
        return (omega / self.rho_DinvA) * b / self._D()

    def postsmoother(self, x, b, omega=4.0 / 3.0):
        return x + (omega / self.rho_DinvA) * (b - self.A @ x) / self._D()

    def _D(self):
        return jnp.asarray(self.D) if use_tpu else self.D


def build_hierarchy(A, B, theta=0.0, max_coarse=10):
    levels = [Level(A=A, B=B)]
    while levels[-1].A.shape[0] > max_coarse:
        A = levels[-1].A
        B = levels[-1].B
        D = np.asarray(A.diagonal())
        C = strength(A, theta=theta)
        AggOp, roots = mis_aggregate(C)
        T, B_coarse = fit_candidates(AggOp, B)
        P, rho = smooth_prolongator(A, T, k=1, D=D)
        R = P.T.tocsr()
        levels[-1] = Level(R, A, P, D, B, rho)
        A_coarse = spg(spg(R, A), P).tocsr()
        levels.append(Level(A=A_coarse, B=B_coarse))
    levels[-1].dense_A = np.asarray(levels[-1].A.toarray())
    return levels


def cycle(levels, lvl, b):
    """Traceable V-cycle: returns x (jnp under sparse_tpu)."""
    level = levels[lvl]
    x = level.presmoother(None, b)
    residual = b - level.A @ x
    coarse_b = level.R @ residual
    if lvl == len(levels) - 2:
        dense = levels[-1].dense_A
        coarse_x = (
            jnp.linalg.solve(jnp.asarray(dense), coarse_b)
            if use_tpu
            else np.linalg.solve(dense, coarse_b)
        )
    else:
        coarse_x = cycle(levels, lvl + 1, coarse_b)
    x = x + level.P @ coarse_x
    return level.postsmoother(x, b)


def build_dist_cycle(levels, mesh, replicate_below: int = 2048):
    """Wrap the hierarchy in mesh-sharded operators and return (A0_dist, M).

    Levels ABOVE ``replicate_below`` rows become ``DistCSR`` shards with
    PINNED equal row splits (padded vector spaces line up across levels, no
    repacking between restriction and prolongation); levels at or below it
    — where the reference's weak scaling collapses because per-level
    collectives dwarf the compute (SURVEY §6: GMG at 4% on 192 GPUs) — run
    as a dense REPLICATED tail (``make_replicated_tail``): one gather in,
    one scatter out, zero collectives for the whole coarse cascade, dense
    MXU matvecs + an LU-factored bottom solve inside the compiled program.
    """
    from sparse_tpu.parallel.multigrid import (
        make_dist_vcycle,
        make_replicated_tail,
        shard_hierarchy,
        tail_crossover,
    )

    omega = 4.0 / 3.0
    L = len(levels)
    # crossover: first level small enough to replicate; the bottom level is
    # ALWAYS replicated (it was already a replicated dense solve, and AMG
    # coarsening bounds it by max_coarse)
    c = tail_crossover(
        [lv.A.shape[0] for lv in levels], replicate_below, bottom_always=True
    )
    As = [lv.A for lv in levels[: c + 1]]
    RPs = [(lv.R, lv.P) for lv in levels[:c]]
    ops, spl_list = shard_hierarchy(As, RPs, mesh)
    print(f"dist tail crossover: level {c} of {L}")
    weights = []
    for i, lv in enumerate(levels[:c]):
        Ad = ops[i][0]
        Dp = Ad.pad_out_vector(np.asarray(lv.D) - 1.0) + 1.0
        weights.append((omega / lv.rho_DinvA) / Dp)
    weights.append(None)  # level c enters the replicated tail

    coarse_apply = make_replicated_tail(
        [lv.A for lv in levels[c:]],
        [(lv.R, lv.P) for lv in levels[c:-1]],
        [
            (omega / lv.rho_DinvA) / np.asarray(lv.D)
            for lv in levels[c:-1]
        ],
        spl_list[-1],
        ops[-1][0].R,
        bottom="solve",
    )
    return ops[0][0], make_dist_vcycle(ops, weights, coarse_apply)


def operator_complexity(levels):
    return sum(level.A.nnz for level in levels) / levels[0].A.nnz


def grid_complexity(levels):
    return sum(level.A.shape[0] for level in levels) / levels[0].A.shape[0]


def main():
    N = args.n
    build, solve = get_phase_procs(use_tpu)
    timer.start()
    with build:
        A = poisson2D(N) if args.data == "poisson" else diffusion2D(N)
        B = np.ones((A.shape[0], 1))
    print(f"Data creation time: {timer.stop():.1f} ms")

    timer.start()
    with build:
        levels = build_hierarchy(A, B, theta=args.theta, max_coarse=args.max_coarse)
    print(f"AMG setup time: {timer.stop():.1f} ms")
    print(f"levels: {len(levels)}  sizes: {[lv.A.shape[0] for lv in levels]}")
    print(f"operator complexity: {operator_complexity(levels):.2f}")
    print(f"grid complexity: {grid_complexity(levels):.2f}")

    b = np.ones(A.shape[0])
    with solve:
        if use_tpu and args.dist:
            import json as _json

            from benchmark import solve_dist_cg_timed
            from sparse_tpu.parallel.dist import comm_stats
            from sparse_tpu.parallel.mesh import get_mesh

            A0d, M = build_dist_cycle(levels, get_mesh())
            print(
                "dist comm stats: "
                f"{_json.dumps(comm_stats(A0d, conv_test_iters=5))}"
            )
            x, iters, total_ms = solve_dist_cg_timed(
                A0d, M, b, timer, tol=args.tol, maxiter=args.maxiter or 200
            )
        elif use_tpu:
            M = linalg.LinearOperator(
                A.shape, matvec=lambda r: cycle(levels, 0, r), dtype=np.float64
            )
            _ = float(np.linalg.norm(np.asarray(A @ np.zeros(A.shape[1]))))
            from benchmark import solve_timed_best_of_2

            x, iters, total_ms = solve_timed_best_of_2(
                lambda: linalg.cg(
                    A, b, tol=args.tol, maxiter=args.maxiter, M=M,
                    conv_test_iters=5,
                ),
                timer,
            )
        else:
            import scipy.sparse.linalg as sla

            M = sla.LinearOperator(
                A.shape, matvec=lambda r: cycle(levels, 0, r), dtype=np.float64
            )
            it = [0]
            timer.start()
            x, _ = linalg.cg(A, b, rtol=args.tol, maxiter=args.maxiter, M=M,
                             callback=lambda xk: it.__setitem__(0, it[0] + 1))
            iters = it[0]
            total_ms = timer.stop()

    resid = float(np.linalg.norm(np.asarray(A @ x) - b))
    print(f"Iterations: {iters}  residual: {resid:.3e}")
    print(f"Iterations / sec: {iters / (total_ms / 1000.0):.3f}")


if __name__ == "__main__":
    main()
