"""Spectral norm estimation via the power method.

Reference analog: ``examples/spectral_norm.py`` (derived from
github.com/pericycle/normest): dense vs CSR power iteration must agree.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")

from sparse_tpu import csr_array


def normest(M, tol=1e-4):
    """2-norm of M (PSD) by power iteration."""
    max_it = 10
    res = 1.0
    it_count = 0
    rng = np.random.default_rng(15210)
    x = rng.random((M.shape[1], 1))
    y = np.asarray(M.dot(x))
    pnorm = np.sqrt(np.sum(y**2))
    x = y / pnorm
    while (res > tol) and (it_count < max_it):
        y = np.asarray(M.dot(x))
        ynorm = np.sqrt(np.sum(y**2))
        res = abs(pnorm - ynorm)
        pnorm = ynorm.copy()
        x = y / ynorm
        it_count += 1
    v = np.asarray(M.dot(x))
    return np.sqrt(np.sum(v**2))


if __name__ == "__main__":
    rng = np.random.default_rng(15210)
    M = rng.random((100, 100))
    A = csr_array(M)
    dense_est = normest(M)
    sparse_est = normest(A)
    print(f"dense normest:  {dense_est:.6f}")
    print(f"sparse normest: {sparse_est:.6f}")
    assert np.isclose(sparse_est, dense_est), (sparse_est, dense_est)
    print("OK")
