"""PDE benchmark: CG solve of the 2-D 5-point Poisson operator.

Reference analog: ``examples/pde.py`` (the BASELINE.md "PDE" row — 6000^2
unknowns/GPU, 300 iterations, `-throughput` mode). Same matrix-construction
path as the reference (diags -> CSC -> transpose -> CSR, pde.py:d2_mat_
dirichlet_2d) so conversion machinery is exercised; `-throughput -max_iter N`
runs the fixed-iteration solve.

Run:  python examples/pde.py -nx 101 -ny 101
      python examples/pde.py -throughput -max_iter 300 -nx 2000 -ny 2000
"""

import argparse
import sys

from benchmark import get_phase_procs, parse_common_args

parser = argparse.ArgumentParser()
parser.add_argument("-nx", type=int, default=101)
parser.add_argument("-ny", type=int, default=101)
parser.add_argument("-throughput", action="store_true")
parser.add_argument("-max_iter", type=int, default=None)
parser.add_argument("-tol", type=float, default=1e-10)
args, _ = parser.parse_known_args()
common, timer, np, sparse, linalg, use_tpu = parse_common_args()

if args.throughput and args.max_iter is None:
    print("Must provide -max_iter when using -throughput.")
    sys.exit(1)

nx, ny = args.nx, args.ny
xmin, xmax = 0.0, 1.0
ymin, ymax = -0.5, 0.5
dx = (xmax - xmin) / (nx - 1)
dy = (ymax - ymin) / (ny - 1)

build, solve = get_phase_procs(use_tpu)


def d2_mat_dirichlet_2d(nx, ny, dx, dy):
    """Centered second-order 2-D Laplacian with Dirichlet BCs (pde.py analog),
    assembled from diagonals. (nx-2)(ny-2) unknowns."""
    a = 1.0 / dx**2
    g = 1.0 / dy**2
    c = -2.0 * a - 2.0 * g
    nxs, nys = nx - 2, ny - 2
    n = nxs * nys
    # x-neighbor diagonal: break at row boundaries
    diag_a = np.full(n - 1, a)
    diag_a[nxs - 1 :: nxs] = 0.0
    diag_g = np.full(n - nxs, g)
    diag_c = np.full(n, c)
    diagonals = [diag_g, diag_a, diag_c, diag_a, diag_g]
    offsets = [-nxs, -1, 0, 1, nxs]
    # same conversion path as the reference: DIA -> CSC -> T -> CSR
    return sparse.diags(diagonals, offsets, shape=(n, n)).tocsc().T


with build:
    x = np.linspace(xmin, xmax, nx)
    y = np.linspace(ymin, ymax, ny)
    X, Y = np.meshgrid(x, y, indexing="ij")
    b = np.sin(np.pi * X) * np.cos(np.pi * Y) + np.sin(
        5.0 * np.pi * X
    ) * np.cos(5.0 * np.pi * Y)
    if args.throughput:
        n = b.shape[0] - 2
        bflat = np.ones((n * (b.shape[1] - 2),))
    else:
        bflat = np.asarray(b)[1:-1, 1:-1].flatten("F")
    timer.start()
    A = d2_mat_dirichlet_2d(nx, ny, dx, dy)
    A = A.tocsr() if hasattr(A, "tocsr") else A
    print(f"Matrix construction time: {timer.stop():.1f} ms")

with solve:
    maxiter = args.max_iter if args.throughput else nx * ny
    # warm up (compile) outside the timed region
    _ = A @ (bflat * 0.0)
    if use_tpu and args.throughput:
        # compile the WHOLE solve outside the clock (the reference's CUDA
        # tasks are prebuilt; a ~30 s tunnel compile inside the clock was
        # the r3 public-API number's entire gap), then best-of-2 + mean
        from benchmark import solve_timed_best_of_2

        p_sol, iters, total_ms = solve_timed_best_of_2(
            lambda: linalg.cg(
                A, bflat, tol=args.tol, maxiter=maxiter,
                conv_test_iters=10**9,
            ),
            timer,
        )
    elif use_tpu:
        timer.start()
        p_sol, iters = linalg.cg(
            A, bflat, tol=args.tol, maxiter=maxiter, conv_test_iters=25,
        )
        total_ms = timer.stop(fence=p_sol)
    else:
        timer.start()
        it = [0]
        p_sol, _info = linalg.cg(
            A, bflat, rtol=args.tol, maxiter=maxiter,
            callback=lambda xk: it.__setitem__(0, it[0] + 1),
        )
        iters = it[0]
        total_ms = timer.stop(fence=p_sol)

resid = float(np.linalg.norm(np.asarray(A @ p_sol) - bflat))
print(f"Iterations: {iters}  residual: {resid:.3e}")
print(f"Iterations / sec: {iters / (total_ms / 1000.0):.3f}")
