"""Quantum MIS benchmark: Hamiltonian build + RK time evolution.

Reference analog: the BASELINE.md "Quantum" row (MIS Hamiltonian build + RK
evolution, 1.85 iters/s @1 V100; driven by the quantum demo script). The
state evolves under H(t) = a(t) H_MIS + b(t) H_driver — an adiabatic-style
sweep from the driver toward the cost Hamiltonian — integrated with DOP853
in complex arithmetic; every RHS evaluation is one sparse SpMV (§3.5).

Run:  python examples/quantum_evolution.py -nodes 16 -t 1.0
"""

import argparse
import time

import networkx as nx
import numpy as np

from benchmark import get_phase_procs, parse_common_args

parser = argparse.ArgumentParser()
parser.add_argument("-nodes", type=int, default=14)
parser.add_argument("-prob", type=float, default=0.35)
parser.add_argument("-t", type=float, default=1.0)
parser.add_argument("-seed", type=int, default=0)
parser.add_argument(
    "-graph", choices=("er", "cycle"), default="er",
    help="cycle: C_n ring (L_n independent sets — '-graph cycle -nodes 25' "
    "is the >=1e5-state scale shape of VERDICT r2 #10)",
)
parser.add_argument(
    "-dist_shards", type=int, default=0,
    help="route the build's group sorts + COO->CSR through the mesh "
    "samplesort with this many shards (0 = single-host build)",
)
args, _ = parser.parse_known_args()
common, timer, _np, sparse, linalg, use_tpu = parse_common_args()

from sparse_tpu import integrate, quantum  # noqa: E402

if args.graph == "cycle":
    graph = nx.cycle_graph(args.nodes)
else:
    graph = nx.erdos_renyi_graph(args.nodes, args.prob, seed=args.seed)

build_scope, solve_scope = get_phase_procs(use_tpu)

# --precision f32 (TPU-native) evolves in complex64 with f32-scaled
# tolerances; f64/complex128 matches the reference's dtype (emulated,
# slow on TPU — documented deviation, same stance as the PDE/GMG rows)
if use_tpu and common.precision == "f32":
    cdtype = np.complex64
    rtol, atol = 1e-5, 1e-7
else:
    cdtype = np.complex128
    rtol, atol = 1e-8, 1e-10

timer.start()
with build_scope:
    # construction stays on the host CPU backend (the reference's
    # build-on-CPU/solve-on-GPU machine scoping): eagerly dispatching
    # the build's sorts through a remote accelerator is round-trip-bound
    driver = quantum.HamiltonianDriver(
        graph=graph, dtype=cdtype,
        dist_shards=args.dist_shards or None,
    )
    mis = quantum.HamiltonianMIS(graph=graph, poly=driver.ip, dtype=cdtype)
    H_driver = driver.hamiltonian
    H_cost = mis.hamiltonian
print(f"Hamiltonian build: {timer.stop():.1f} ms  "
      f"(nstates={driver.nstates}, nnz={H_driver.nnz})")

T = args.t


nst = driver.nstates

if cdtype == np.complex64:
    # TPU-native form: both Hamiltonians are REAL (bit-flip couplings and
    # diagonal costs), so i dy/dt = H y splits into the stacked real
    # system (dyr, dyi) = (H yi, -H yr) — f32 end to end, no complex
    # arrays on the device (the tunnel backend cannot transfer them),
    # and the SpMVs ride the real f32 fast path.
    import jax.numpy as jnp

    with build_scope:
        Hc = H_cost.astype(np.float32).tocsr()
        Hd = H_driver.astype(np.float32).tocsr()

    def rhs(t, y):
        a = t / T
        b = 1.0 - t / T
        yr, yi = y[:nst], y[nst:]
        Hyr = a * (Hc @ yr) + b * (Hd @ yr)
        Hyi = a * (Hc @ yi) + b * (Hd @ yi)
        return jnp.concatenate([Hyi, -Hyr])

    y0 = np.zeros(2 * nst, dtype=np.float32)
    y0[nst - 1] = 1.0  # start in the empty-set state (real part)
else:
    def rhs(t, y):
        a = t / T          # ramp the cost Hamiltonian up
        b = 1.0 - t / T    # ...and the driver down
        return -1j * (a * (H_cost @ y) + b * (H_driver @ y))

    y0 = np.zeros(nst, dtype=cdtype)
    y0[-1] = 1.0  # start in the empty-set state

with build_scope:
    # one eager RHS call primes the operators' layout caches ON THE CPU
    # backend — experimental accelerator backends (the axon tunnel) only
    # reliably run COMPILED programs, so every eager op belongs here
    np.asarray(rhs(0.0, y0))
with solve_scope:
    # compile outside the clock (the reference's CUDA tasks are prebuilt;
    # a tunnel compile inside the clock would swamp the 13-step run)
    integrate.solve_ivp(
        rhs, (0, T * 1e-6), y0, method="DOP853", rtol=rtol, atol=atol
    )
    t0 = time.perf_counter()
    out = integrate.solve_ivp(
        rhs, (0, T), y0, method="DOP853", rtol=rtol, atol=atol
    )
    wall = time.perf_counter() - t0

final = np.asarray(out.y)[:, -1]
if cdtype == np.complex64:
    final = final[:nst] + 1j * final[nst:]
print(f"steps: {len(out.t) - 1}  nfev: {out.nfev}  wall: {wall:.2f} s")
print(f"norm drift: {abs(np.linalg.norm(final) - 1.0):.2e}")
print(f"MIS size: {int(mis.optimum)}  "
      f"optimum overlap: {mis.optimum_overlap(final):.4f}  "
      f"cost: {mis.cost_function(final):.4f}")
print(f"Iterations / sec: {(len(out.t) - 1) / wall:.3f}")
