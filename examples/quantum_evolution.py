"""Quantum MIS benchmark: Hamiltonian build + RK time evolution.

Reference analog: the BASELINE.md "Quantum" row (MIS Hamiltonian build + RK
evolution, 1.85 iters/s @1 V100; driven by the quantum demo script). The
state evolves under H(t) = a(t) H_MIS + b(t) H_driver — an adiabatic-style
sweep from the driver toward the cost Hamiltonian — integrated with DOP853
in complex arithmetic; every RHS evaluation is one sparse SpMV (§3.5).

Run:  python examples/quantum_evolution.py -nodes 16 -t 1.0
"""

import argparse
import time

import networkx as nx
import numpy as np

from benchmark import parse_common_args

parser = argparse.ArgumentParser()
parser.add_argument("-nodes", type=int, default=14)
parser.add_argument("-prob", type=float, default=0.35)
parser.add_argument("-t", type=float, default=1.0)
parser.add_argument("-seed", type=int, default=0)
parser.add_argument(
    "-graph", choices=("er", "cycle"), default="er",
    help="cycle: C_n ring (L_n independent sets — '-graph cycle -nodes 25' "
    "is the >=1e5-state scale shape of VERDICT r2 #10)",
)
parser.add_argument(
    "-dist_shards", type=int, default=0,
    help="route the build's group sorts + COO->CSR through the mesh "
    "samplesort with this many shards (0 = single-host build)",
)
args, _ = parser.parse_known_args()
common, timer, _np, sparse, linalg, use_tpu = parse_common_args()

from sparse_tpu import integrate, quantum  # noqa: E402

if args.graph == "cycle":
    graph = nx.cycle_graph(args.nodes)
else:
    graph = nx.erdos_renyi_graph(args.nodes, args.prob, seed=args.seed)

timer.start()
driver = quantum.HamiltonianDriver(
    graph=graph, dtype=np.complex128,
    dist_shards=args.dist_shards or None,
)
mis = quantum.HamiltonianMIS(graph=graph, poly=driver.ip, dtype=np.complex128)
H_driver = driver.hamiltonian
H_cost = mis.hamiltonian
print(f"Hamiltonian build: {timer.stop():.1f} ms  "
      f"(nstates={driver.nstates}, nnz={H_driver.nnz})")

T = args.t


def rhs(t, y):
    a = t / T          # ramp the cost Hamiltonian up
    b = 1.0 - t / T    # ...and the driver down
    return -1j * (a * (H_cost @ y) + b * (H_driver @ y))


y0 = np.zeros(driver.nstates, dtype=np.complex128)
y0[-1] = 1.0  # start in the empty-set state

t0 = time.perf_counter()
out = integrate.solve_ivp(rhs, (0, T), y0, method="DOP853", rtol=1e-8, atol=1e-10)
wall = time.perf_counter() - t0

final = np.asarray(out.y)[:, -1]
print(f"steps: {len(out.t) - 1}  nfev: {out.nfev}  wall: {wall:.2f} s")
print(f"norm drift: {abs(np.linalg.norm(final) - 1.0):.2e}")
print(f"MIS size: {int(mis.optimum)}  "
      f"optimum overlap: {mis.optimum_overlap(final):.4f}  "
      f"cost: {mis.cost_function(final):.4f}")
print(f"Iterations / sec: {(len(out.t) - 1) / wall:.3f}")
