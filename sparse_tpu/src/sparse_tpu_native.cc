// Native runtime kernels for sparse_tpu (host-side work that sits outside
// the XLA compute path).
//
// Reference analogs:
//   * independent-set BFS expansion: src/quantum/quantum.cc:27-112
//     (EnumerateIndependentSets) — the IntSet<N,T> template loops become
//     plain word-parallel bitset code over caller-provided buffers;
//   * MatrixMarket body parsing: src/sparse/io/mtx_to_coo.cc:44-145
//     (READ_MTX_TO_COO) — a single-pass tokenizer, ~20x faster than
//     numpy.loadtxt for large files. Header parsing / symmetry expansion
//     stay in Python (sparse_tpu/io.py), matching where the reference
//     blocks on scalar futures.
//
// Build: see sparse_tpu/native.py (auto-compiled with g++ -O3 on first use).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------------
// Independent-set BFS expansion
// ---------------------------------------------------------------------------

// Total number of size-(k+1) sets generated from this level:
// sum of popcounts of the extension queues.
int64_t ind_sets_count(const uint64_t* queues, int64_t S, int64_t W) {
  int64_t total = 0;
  for (int64_t i = 0; i < S * W; i++) {
    total += __builtin_popcountll(queues[i]);
  }
  return total;
}

// Expand one BFS level. new_sets/new_queues must hold ind_sets_count rows.
// Order matches the reference: parent-major, then extension node ascending
// (quantum.cc:89-108).
void ind_sets_expand(const uint64_t* sets, const uint64_t* queues,
                     const uint64_t* comp_gt,  // [n, W] candidate masks
                     int64_t S, int64_t W, int64_t n, uint64_t* new_sets,
                     uint64_t* new_queues) {
  int64_t out = 0;
  for (int64_t i = 0; i < S; i++) {
    const uint64_t* q = queues + i * W;
    const uint64_t* s = sets + i * W;
    for (int64_t w = 0; w < W; w++) {
      uint64_t bits = q[w];
      while (bits) {
        int b = __builtin_ctzll(bits);
        bits &= bits - 1;
        int64_t u = w * 64 + b;
        uint64_t* ns = new_sets + out * W;
        uint64_t* nq = new_queues + out * W;
        const uint64_t* cg = comp_gt + u * W;
        for (int64_t ww = 0; ww < W; ww++) {
          ns[ww] = s[ww];
          nq[ww] = q[ww] & cg[ww];
        }
        ns[w] |= (uint64_t(1) << b);
        out++;
      }
    }
  }
}

// ---------------------------------------------------------------------------
// MatrixMarket coordinate-body parser
// ---------------------------------------------------------------------------

// Parse `nnz` coordinate lines starting at `body` (after header/size line).
// kind: 0 = pattern (no value), 1 = real/integer (1 value), 2 = complex.
// Returns the number of entries parsed (== nnz on success, < nnz on error).
int64_t mtx_parse_body(const char* body, int64_t body_len, int64_t nnz,
                       int32_t kind, int64_t* rows, int64_t* cols,
                       double* vals_re, double* vals_im) {
  const char* p = body;
  const char* end = body + body_len;
  int64_t i = 0;
  while (i < nnz && p < end) {
    // skip whitespace/newlines and comment lines
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) {
      p++;
    }
    if (p < end && *p == '%') {
      while (p < end && *p != '\n') p++;
      continue;
    }
    if (p >= end) break;
    char* next;
    long long r = strtoll(p, &next, 10);
    if (next == p) break;
    p = next;
    long long c = strtoll(p, &next, 10);
    if (next == p) break;
    p = next;
    rows[i] = r - 1;  // MatrixMarket is 1-based
    cols[i] = c - 1;
    if (kind == 0) {
      vals_re[i] = 1.0;
    } else {
      double re = strtod(p, &next);
      if (next == p) break;
      p = next;
      vals_re[i] = re;
      if (kind == 2) {
        double im = strtod(p, &next);
        if (next == p) break;
        p = next;
        vals_im[i] = im;
      }
    }
    i++;
  }
  return i;
}

// Parse a whitespace-separated array of doubles (MatrixMarket "array" body).
int64_t mtx_parse_dense(const char* body, int64_t body_len, int64_t count,
                        double* out) {
  const char* p = body;
  const char* end = body + body_len;
  int64_t i = 0;
  while (i < count && p < end) {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) {
      p++;
    }
    if (p < end && *p == '%') {
      while (p < end && *p != '\n') p++;
      continue;
    }
    if (p >= end) break;
    char* next;
    double v = strtod(p, &next);
    if (next == p) break;
    p = next;
    out[i++] = v;
  }
  return i;
}

}  // extern "C"
