// Native runtime kernels for sparse_tpu (host-side work that sits outside
// the XLA compute path).
//
// Reference analogs:
//   * independent-set BFS expansion: src/quantum/quantum.cc:27-112
//     (EnumerateIndependentSets) — the IntSet<N,T> template loops become
//     plain word-parallel bitset code over caller-provided buffers;
//   * MatrixMarket body parsing: src/sparse/io/mtx_to_coo.cc:44-145
//     (READ_MTX_TO_COO) — a single-pass tokenizer, ~20x faster than
//     numpy.loadtxt for large files. Header parsing / symmetry expansion
//     stay in Python (sparse_tpu/io.py), matching where the reference
//     blocks on scalar futures.
//
// Build: see sparse_tpu/native.py (auto-compiled with g++ -O3 on first use).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Host Gustavson SpGEMM (construction-phase C = A @ B, CSR x CSR -> CSR)
//
// Reference analog: the CPU/OMP SpGEMM task pair
// src/sparse/array/csr/spgemm_csr_csr_csr.cc (2-pass: NNZ count then fill).
// The TPU build keeps its device-side ESC formulation for sharded/compiled
// paths; this native kernel serves EAGER host-resident calls — multigrid
// hierarchy Galerkin products and other setup-phase SpGEMMs, where the
// XLA sort-based form pays ~2 orders of magnitude in constant factors.
// ---------------------------------------------------------------------------

// Pass 1: per-row nnz of C via a row-stamped dense mask. Returns total nnz.
int64_t spgemm_count(int64_t m, int64_t n,
                     const int64_t* Ap, const int64_t* Aj,
                     const int64_t* Bp, const int64_t* Bj,
                     int64_t* Cp) {
  std::vector<int64_t> mask(static_cast<size_t>(n), -1);
  Cp[0] = 0;
  int64_t nnz = 0;
  for (int64_t i = 0; i < m; ++i) {
    int64_t row_nnz = 0;
    for (int64_t jj = Ap[i]; jj < Ap[i + 1]; ++jj) {
      const int64_t j = Aj[jj];
      for (int64_t kk = Bp[j]; kk < Bp[j + 1]; ++kk) {
        const int64_t k = Bj[kk];
        if (mask[static_cast<size_t>(k)] != i) {
          mask[static_cast<size_t>(k)] = i;
          ++row_nnz;
        }
      }
    }
    nnz += row_nnz;
    Cp[i + 1] = nnz;
  }
  return nnz;
}

// Pass 2: fill values with a linked-list accumulator, then sort each row's
// (column, value) pairs so the output is canonical CSR.
void spgemm_fill(int64_t m, int64_t n,
                 const int64_t* Ap, const int64_t* Aj, const double* Ax,
                 const int64_t* Bp, const int64_t* Bj, const double* Bx,
                 const int64_t* Cp, int64_t* Cj, double* Cx) {
  std::vector<int64_t> next(static_cast<size_t>(n), -1);
  std::vector<double> sums(static_cast<size_t>(n), 0.0);
  std::vector<std::pair<int64_t, double>> row;
  for (int64_t i = 0; i < m; ++i) {
    int64_t head = -2;
    int64_t length = 0;
    for (int64_t jj = Ap[i]; jj < Ap[i + 1]; ++jj) {
      const int64_t j = Aj[jj];
      const double v = Ax[jj];
      for (int64_t kk = Bp[j]; kk < Bp[j + 1]; ++kk) {
        const int64_t k = Bj[kk];
        sums[static_cast<size_t>(k)] += v * Bx[kk];
        if (next[static_cast<size_t>(k)] == -1) {
          next[static_cast<size_t>(k)] = head;
          head = k;
          ++length;
        }
      }
    }
    row.clear();
    row.reserve(static_cast<size_t>(length));
    for (int64_t cnt = 0; cnt < length; ++cnt) {
      row.emplace_back(head, sums[static_cast<size_t>(head)]);
      const int64_t tmp = head;
      head = next[static_cast<size_t>(head)];
      next[static_cast<size_t>(tmp)] = -1;
      sums[static_cast<size_t>(tmp)] = 0.0;
    }
    std::sort(row.begin(), row.end());
    int64_t out = Cp[i];
    for (const auto& cv : row) {
      Cj[out] = cv.first;
      Cx[out] = cv.second;
      ++out;
    }
  }
}

// ---------------------------------------------------------------------------
// Independent-set BFS expansion
// ---------------------------------------------------------------------------

// Total number of size-(k+1) sets generated from this level:
// sum of popcounts of the extension queues.
int64_t ind_sets_count(const uint64_t* queues, int64_t S, int64_t W) {
  int64_t total = 0;
  for (int64_t i = 0; i < S * W; i++) {
    total += __builtin_popcountll(queues[i]);
  }
  return total;
}

// Expand one BFS level. new_sets/new_queues must hold ind_sets_count rows.
// Order matches the reference: parent-major, then extension node ascending
// (quantum.cc:89-108).
void ind_sets_expand(const uint64_t* sets, const uint64_t* queues,
                     const uint64_t* comp_gt,  // [n, W] candidate masks
                     int64_t S, int64_t W, int64_t n, uint64_t* new_sets,
                     uint64_t* new_queues) {
  int64_t out = 0;
  for (int64_t i = 0; i < S; i++) {
    const uint64_t* q = queues + i * W;
    const uint64_t* s = sets + i * W;
    for (int64_t w = 0; w < W; w++) {
      uint64_t bits = q[w];
      while (bits) {
        int b = __builtin_ctzll(bits);
        bits &= bits - 1;
        int64_t u = w * 64 + b;
        uint64_t* ns = new_sets + out * W;
        uint64_t* nq = new_queues + out * W;
        const uint64_t* cg = comp_gt + u * W;
        for (int64_t ww = 0; ww < W; ww++) {
          ns[ww] = s[ww];
          nq[ww] = q[ww] & cg[ww];
        }
        ns[w] |= (uint64_t(1) << b);
        out++;
      }
    }
  }
}

// ---------------------------------------------------------------------------
// MatrixMarket coordinate-body parser
// ---------------------------------------------------------------------------

// Parse `nnz` coordinate lines starting at `body` (after header/size line).
// kind: 0 = pattern (no value), 1 = real/integer (1 value), 2 = complex.
// Returns the number of entries parsed (== nnz on success, < nnz on error).
int64_t mtx_parse_body(const char* body, int64_t body_len, int64_t nnz,
                       int32_t kind, int64_t* rows, int64_t* cols,
                       double* vals_re, double* vals_im) {
  const char* p = body;
  const char* end = body + body_len;
  int64_t i = 0;
  while (i < nnz && p < end) {
    // skip whitespace/newlines and comment lines
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) {
      p++;
    }
    if (p < end && *p == '%') {
      while (p < end && *p != '\n') p++;
      continue;
    }
    if (p >= end) break;
    char* next;
    long long r = strtoll(p, &next, 10);
    if (next == p) break;
    p = next;
    long long c = strtoll(p, &next, 10);
    if (next == p) break;
    p = next;
    rows[i] = r - 1;  // MatrixMarket is 1-based
    cols[i] = c - 1;
    if (kind == 0) {
      vals_re[i] = 1.0;
    } else {
      double re = strtod(p, &next);
      if (next == p) break;
      p = next;
      vals_re[i] = re;
      if (kind == 2) {
        double im = strtod(p, &next);
        if (next == p) break;
        p = next;
        vals_im[i] = im;
      }
    }
    i++;
  }
  return i;
}

// Parse a whitespace-separated array of doubles (MatrixMarket "array" body).
int64_t mtx_parse_dense(const char* body, int64_t body_len, int64_t count,
                        double* out) {
  const char* p = body;
  const char* end = body + body_len;
  int64_t i = 0;
  while (i < count && p < end) {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) {
      p++;
    }
    if (p < end && *p == '%') {
      while (p < end && *p != '\n') p++;
      continue;
    }
    if (p >= end) break;
    char* next;
    double v = strtod(p, &next);
    if (next == p) break;
    p = next;
    out[i++] = v;
  }
  return i;
}

// ---------------------------------------------------------------------------
// ILU(0) / IC(0) numeric factorizations (construction-phase, in place)
//
// The reference has no direct/incomplete solvers (its linalg.py spsolve IS
// cg); these back the beyond-reference scipy.sparse.linalg spilu surface.
// Factorization is inherently row-sequential, so it runs here on the host
// as a setup-phase kernel (like the Gustavson SpGEMM above); the per-
// iteration triangular SOLVES run on device via the blocked lax.scan in
// sparse_tpu/_direct.py.
// ---------------------------------------------------------------------------

// In-place ILU(0), IKJ form, on a canonical (sorted, deduplicated) CSR.
// After return data holds L (strict lower, unit diagonal implicit) and U
// (upper incl. diagonal) on A's sparsity pattern. Returns 0, or -(i+1) if
// row i has no structural diagonal / a zero pivot.
int64_t ilu0_csr(int64_t n, const int64_t* indptr, const int64_t* indices,
                 double* data) {
  std::vector<int64_t> pos(n, -1);
  std::vector<int64_t> diag(n, -1);
  for (int64_t i = 0; i < n; ++i) {
    for (int64_t p = indptr[i]; p < indptr[i + 1]; ++p) {
      if (indices[p] == i) {
        diag[i] = p;
        break;
      }
    }
    if (diag[i] < 0) return -(i + 1);
  }
  for (int64_t i = 0; i < n; ++i) {
    for (int64_t p = indptr[i]; p < indptr[i + 1]; ++p) pos[indices[p]] = p;
    for (int64_t p = indptr[i]; p < indptr[i + 1]; ++p) {
      int64_t k = indices[p];
      if (k >= i) break;
      double ukk = data[diag[k]];
      if (ukk == 0.0) return -(k + 1);
      double lik = data[p] / ukk;
      data[p] = lik;
      for (int64_t q = diag[k] + 1; q < indptr[k + 1]; ++q) {
        int64_t pj = pos[indices[q]];
        if (pj >= 0) data[pj] -= lik * data[q];
      }
    }
    for (int64_t p = indptr[i]; p < indptr[i + 1]; ++p) pos[indices[p]] = -1;
    if (data[diag[i]] == 0.0) return -(i + 1);
  }
  return 0;
}

// In-place IC(0) on the LOWER-triangular part of an SPD matrix in canonical
// CSR (each row's diagonal entry is its last). After return data holds L
// with A ~= L L^T on the lower pattern. Returns 0, or -(i+1) on a missing
// diagonal / non-positive pivot (matrix not SPD enough for IC(0)).
int64_t ic0_csr(int64_t n, const int64_t* indptr, const int64_t* indices,
                double* data) {
  for (int64_t i = 0; i < n; ++i) {
    int64_t pi0 = indptr[i], pi1 = indptr[i + 1];
    if (pi1 <= pi0 || indices[pi1 - 1] != i) return -(i + 1);
    for (int64_t p = pi0; p < pi1; ++p) {
      int64_t j = indices[p];
      // dot of L rows i and j over columns < j (two-pointer, sorted CSR)
      double s = 0.0;
      int64_t a = pi0;
      int64_t b = indptr[j], b1 = indptr[j + 1] - 1;  // exclude row j's diag
      while (a < p && b < b1) {
        int64_t ca = indices[a], cb = indices[b];
        if (ca == cb) {
          s += data[a] * data[b];
          ++a;
          ++b;
        } else if (ca < cb) {
          ++a;
        } else {
          ++b;
        }
      }
      if (j < i) {
        double ljj = data[indptr[j + 1] - 1];
        if (ljj == 0.0) return -(j + 1);
        data[p] = (data[p] - s) / ljj;
      } else {
        double v = data[p] - s;
        if (v <= 0.0) return -(i + 1);
        data[p] = std::sqrt(v);
      }
    }
  }
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Sparse LU with partial pivoting (Gilbert-Peierls, left-looking): P A = L U.
//
// Reference analog: the reference leans on vendor/scipy factorizations for
// its direct solves; this kernel is the native setup-phase factorization
// that lifts sparse_tpu's dense-LU size ceiling (VERDICT r4 weak #5). The
// symbolic step per column is the classic CSparse reach (DFS through the
// pivoted L columns, reverse postorder = topological elimination order), so
// total work is O(flops(L,U)), not O(n * nnz). Natural (no COLAMD) column
// order; fill is whatever the ordering gives — callers with huge fill
// should precondition + iterate instead.
//
// L is unit-lower (diagonal implicit), U upper, both CSC over PIVOT row
// ids; perm[k] = original row chosen as pivot k (PA = LU reads
// (PA)[k, :] = A[perm[k], :]).
// ---------------------------------------------------------------------------

namespace {

struct SpluHandle {
  int64_t n = 0;
  std::vector<int64_t> Lp, Li, Up, Ui;
  std::vector<double> Lx, Ux;
  std::vector<int64_t> perm;
};

}  // namespace

// Shared Gilbert-Peierls core. droptol == 0 && lfil == 0 -> exact LU;
// otherwise ILUT(p, tau): entries with |x| < droptol * ||A(:,j)||_2 are
// dropped (pivot always kept) and at most lfil largest-|value| entries
// are kept per column in EACH of L and U-off-diagonal (lfil == 0 means
// unlimited). Dropping shrinks downstream reach, which is the point.
static SpluHandle* lu_factor_core(int64_t n, const int64_t* Ap,
                                  const int64_t* Ai, const double* Ax,
                                  double droptol, int64_t lfil,
                                  int64_t* info) {
  auto* h = new SpluHandle();
  h->n = n;
  h->Lp.assign(1, 0);
  h->Up.assign(1, 0);
  h->perm.assign(n, -1);
  std::vector<int64_t> pinv(n, -1);   // original row -> pivot position
  std::vector<double> x(n, 0.0);
  std::vector<unsigned char> mark(n, 0);
  std::vector<int64_t> topo, stack, pstack;
  std::vector<std::pair<int64_t, double>> ucol, lcol;
  topo.reserve(64);
  *info = 0;

  for (int64_t j = 0; j < n; ++j) {
    // symbolic: reach of pattern(A(:, j)) through the pivoted L columns
    topo.clear();
    for (int64_t p = Ap[j]; p < Ap[j + 1]; ++p) {
      int64_t root = Ai[p];
      if (mark[root]) continue;
      mark[root] = 1;
      stack.assign(1, root);
      pstack.assign(1, pinv[root] >= 0 ? h->Lp[pinv[root]] : -1);
      while (!stack.empty()) {
        int64_t node = stack.back();
        int64_t k = pinv[node];
        bool descended = false;
        if (k >= 0) {
          int64_t end = h->Lp[k + 1];
          int64_t& pp = pstack.back();
          if (pp < 0) pp = h->Lp[k];
          while (pp < end) {
            int64_t child = h->Li[pp++];
            if (!mark[child]) {
              mark[child] = 1;
              stack.push_back(child);
              pstack.push_back(pinv[child] >= 0 ? h->Lp[pinv[child]] : -1);
              descended = true;
              break;
            }
          }
        }
        if (!descended) {  // postorder emit; reverse gives topo order
          topo.push_back(node);
          stack.pop_back();
          pstack.pop_back();
        }
      }
    }
    // numeric: scatter A(:, j), eliminate in reverse postorder
    double cn2 = 0.0;
    for (int64_t p = Ap[j]; p < Ap[j + 1]; ++p) {
      x[Ai[p]] = Ax[p];
      cn2 += Ax[p] * Ax[p];
    }
    const double tau = droptol > 0.0 ? droptol * std::sqrt(cn2) : 0.0;
    for (int64_t t = (int64_t)topo.size() - 1; t >= 0; --t) {
      int64_t i = topo[t];
      int64_t k = pinv[i];
      if (k < 0) continue;
      double xi = x[i];
      if (xi == 0.0) continue;
      for (int64_t p = h->Lp[k]; p < h->Lp[k + 1]; ++p)
        x[h->Li[p]] -= h->Lx[p] * xi;
    }
    // partial pivot: largest |x| among unpivoted reached rows
    int64_t piv = -1;
    double pmax = 0.0;
    for (int64_t i : topo) {
      if (pinv[i] < 0) {
        double a = std::fabs(x[i]);
        if (a > pmax) {
          pmax = a;
          piv = i;
        }
      }
    }
    if (piv < 0 || pmax == 0.0) {
      *info = -(j + 1);
      delete h;
      return nullptr;
    }
    double d = x[piv];
    pinv[piv] = j;
    h->perm[j] = piv;
    // emit: pivoted rows -> U(:, j) (incl. the new diagonal), unpivoted
    // rows -> L(:, j) scaled by the pivot; then keep the lfil largest per
    // half; clear the workspace. ILUT drop rules (SuperLU/Saad, ADVICE
    // r5): U drops on the raw value |x| < tau = droptol * ||A(:,j)||2,
    // L drops on the SCALED multiplier |x/d| < droptol — the pivot is
    // picked first, so a large pivot no longer keeps entries that are
    // tiny as L multipliers (nor a tiny pivot drop large ones). The
    // U diagonal is never dropped.
    ucol.clear();
    lcol.clear();
    for (int64_t i : topo) {
      if (pinv[i] >= 0) {
        if (pinv[i] == j || std::fabs(x[i]) >= tau)
          ucol.emplace_back(pinv[i], x[i]);
      } else if (x[i] != 0.0 &&
                 (droptol <= 0.0 || std::fabs(x[i] / d) >= droptol)) {
        lcol.emplace_back(i, x[i] / d);  // ORIGINAL row id; remapped later
      }
      x[i] = 0.0;
      mark[i] = 0;
    }
    if (lfil > 0) {
      auto by_mag = [](const std::pair<int64_t, double>& a,
                       const std::pair<int64_t, double>& b) {
        return std::fabs(a.second) > std::fabs(b.second);
      };
      if ((int64_t)lcol.size() > lfil) {
        std::nth_element(lcol.begin(), lcol.begin() + lfil, lcol.end(),
                         by_mag);
        lcol.resize(lfil);
      }
      // U keeps its diagonal unconditionally + the lfil largest others
      if ((int64_t)ucol.size() > lfil + 1) {
        auto diag_it = std::find_if(
            ucol.begin(), ucol.end(),
            [j](const std::pair<int64_t, double>& e) { return e.first == j; });
        std::swap(*diag_it, ucol.back());
        auto dent = ucol.back();
        ucol.pop_back();
        std::nth_element(ucol.begin(), ucol.begin() + lfil, ucol.end(),
                         by_mag);
        ucol.resize(lfil);
        ucol.push_back(dent);
      }
    }
    std::sort(ucol.begin(), ucol.end());
    for (auto& e : ucol) {
      h->Ui.push_back(e.first);
      h->Ux.push_back(e.second);
    }
    for (auto& e : lcol) {
      h->Li.push_back(e.first);
      h->Lx.push_back(e.second);
    }
    h->Lp.push_back((int64_t)h->Li.size());
    h->Up.push_back((int64_t)h->Ui.size());
  }
  // L row ids -> pivot space (every row is pivoted by now)
  for (auto& i : h->Li) i = pinv[i];
  return h;
}

extern "C" {

// Exact factorization of the n x n CSC matrix (Ap, Ai, Ax). Returns an
// opaque handle (or nullptr on failure) and sets *info to 0, or -(j+1)
// when column j has no usable pivot.
void* splu_factor(int64_t n, const int64_t* Ap, const int64_t* Ai,
                  const double* Ax, int64_t* info) {
  return lu_factor_core(n, Ap, Ai, Ax, 0.0, 0, info);
}

// ILUT(p, tau) incomplete factorization — same handle/getter protocol.
void* ilut_factor(int64_t n, const int64_t* Ap, const int64_t* Ai,
                  const double* Ax, double droptol, int64_t lfil,
                  int64_t* info) {
  return lu_factor_core(n, Ap, Ai, Ax, droptol, lfil, info);
}

int64_t splu_lnnz(void* vh) { return (int64_t)((SpluHandle*)vh)->Li.size(); }
int64_t splu_unnz(void* vh) { return (int64_t)((SpluHandle*)vh)->Ui.size(); }

void splu_get(void* vh, int64_t* Lp, int64_t* Li, double* Lx, int64_t* Up,
              int64_t* Ui, double* Ux, int64_t* perm) {
  auto* h = (SpluHandle*)vh;
  // empty-vector data() may be null (diagonal matrices have empty L);
  // memcpy from null is UB even at size 0
  auto cp = [](void* dst, const void* src, size_t bytes) {
    if (bytes) std::memcpy(dst, src, bytes);
  };
  cp(Lp, h->Lp.data(), h->Lp.size() * sizeof(int64_t));
  cp(Li, h->Li.data(), h->Li.size() * sizeof(int64_t));
  cp(Lx, h->Lx.data(), h->Lx.size() * sizeof(double));
  cp(Up, h->Up.data(), h->Up.size() * sizeof(int64_t));
  cp(Ui, h->Ui.data(), h->Ui.size() * sizeof(int64_t));
  cp(Ux, h->Ux.data(), h->Ux.size() * sizeof(double));
  cp(perm, h->perm.data(), h->perm.size() * sizeof(int64_t));
}

void splu_free(void* vh) { delete (SpluHandle*)vh; }

}  // extern "C"
