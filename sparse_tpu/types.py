"""Canonical dtypes for sparse index/value data.

Reference analog: ``sparse/types.py:18-25`` (coord=int64, nnz=uint64). On TPU we
default to int32 coordinates (native lane width; int64 requires x64 emulation) and
promote to int64 only when a dimension or nnz count demands it.
"""

from __future__ import annotations

import numpy as np

# Default coordinate (row/col index) dtype. int32 covers dims < 2**31.
coord_ty = np.int32
# Dtype used for nnz counters / indptr offsets.
nnz_ty = np.int32
# Wide variants, used when shapes/nnz exceed int32 range.
coord_ty_wide = np.int64
nnz_ty_wide = np.int64

_INT32_MAX = np.iinfo(np.int32).max


def index_dtype_for(shape, nnz: int):
    """Pick an index dtype large enough for ``shape`` and ``nnz``."""
    m = max([int(nnz), *[int(s) for s in shape]] or [0])
    return coord_ty_wide if m > _INT32_MAX else coord_ty
