"""BSR (block sparse row) format — the MXU-native sparse layout.

Beyond the reference's class surface (its coverage layer lists tobsr as a
gap): scipy's BSR stores dense [R, C] blocks at block-sparse positions.
On TPU this is the one sparse format whose SpMV is a BATCHED DENSE MATMUL
(``einsum('brc,bc->br')`` over the gathered x blocks) — the MXU runs the
blocks at dense-matmul throughput instead of the VPU gather path, so
matrices with natural block structure (multi-dof PDE discretizations,
graph nets with feature blocks) should prefer BSR.

Layout: ``indptr`` [Mb+1], ``indices`` [nnzb] block-column ids, ``data``
[nnzb, R, C] dense blocks. Stored zeros inside blocks are kept (scipy
semantics): ``nnz`` counts stored values, ``count_nonzero`` the true
nonzeros.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .base import SparseArray
from .utils import asjnp


class bsr_array(SparseArray):
    format = "bsr"
    ndim = 2

    def __init__(self, arg1, shape=None, dtype=None, blocksize=None):
        if isinstance(arg1, tuple) and len(arg1) == 3:
            data, indices, indptr = arg1
            data = asjnp(data, dtype=dtype)
            if data.ndim != 3:
                raise ValueError("bsr data must be [nnzb, R, C]")
            if blocksize is not None and tuple(map(int, blocksize)) != (
                int(data.shape[1]),
                int(data.shape[2]),
            ):
                raise ValueError(
                    f"blocksize {tuple(blocksize)} does not match data "
                    f"blocks {tuple(data.shape[1:])}"
                )
            self.data = data
            self.indices = asjnp(indices)
            self.indptr = asjnp(indptr)
            R, C = int(data.shape[1]), int(data.shape[2])
            Mb = int(self.indptr.shape[0]) - 1
            if shape is None:
                nb = int(jnp.max(self.indices)) + 1 if data.shape[0] else 1
                shape = (Mb * R, nb * C)
            self._shape = tuple(int(s) for s in shape)
            if self._shape[0] % R or self._shape[1] % C:
                raise ValueError(
                    f"shape {self._shape} not divisible by blocksize {(R, C)}"
                )
            self._dtype = np.dtype(self.data.dtype)
            return
        if isinstance(arg1, SparseArray):
            src = arg1.tocsr()
        else:
            from .csr import csr_array

            dense = np.asarray(arg1)
            if dense.ndim != 2:
                raise ValueError("bsr_array expects a 2-D input")
            src = csr_array(dense)
        B = src.tobsr(blocksize=blocksize)
        self.data, self.indices, self.indptr = B.data, B.indices, B.indptr
        self._shape = B.shape
        self._dtype = B.dtype

    # ---- basic surface ---------------------------------------------------
    @property
    def blocksize(self):
        return (int(self.data.shape[1]), int(self.data.shape[2]))

    @property
    def nnz(self) -> int:
        # scipy: stored values (whole blocks), not true nonzeros
        return int(self.data.size)

    def _data_array(self):
        return self.data

    def _with_data(self, data):
        return bsr_array(
            (data, self.indices, self.indptr), shape=self.shape
        )

    # ---- conversions -----------------------------------------------------
    def tocoo(self):
        """Host-side conversion (pure numpy index arithmetic — the result
        feeds a host constructor anyway, so no device round trips)."""
        from .coo import coo_array

        R, C = self.blocksize
        nnzb = int(self.data.shape[0])
        indptr = np.asarray(self.indptr, dtype=np.int64)
        brow = np.repeat(np.arange(len(indptr) - 1, dtype=np.int64), np.diff(indptr))
        bcol = np.asarray(self.indices, dtype=np.int64)
        r_in = np.arange(R, dtype=np.int64)
        c_in = np.arange(C, dtype=np.int64)
        rows = np.broadcast_to(
            (brow[:, None, None] * R + r_in[None, :, None]), (nnzb, R, C)
        ).reshape(-1)
        cols = np.broadcast_to(
            (bcol[:, None, None] * C + c_in[None, None, :]), (nnzb, R, C)
        ).reshape(-1)
        vals = np.asarray(self.data).reshape(-1)
        # drop stored zeros at the conversion boundary (canonical COO)
        keep = vals != 0
        return coo_array(
            (vals[keep], (rows[keep], cols[keep])), shape=self.shape
        )

    def tocsr(self):
        return self.tocoo().tocsr()

    def tocsc(self):
        return self.tocoo().tocsc()

    def todia(self):
        return self.tocoo().todia()

    def tobsr(self, blocksize=None):
        if blocksize is None or tuple(blocksize) == self.blocksize:
            return self
        return self.tocsr().tobsr(blocksize=blocksize)

    def toarray(self):
        from .ops.coords import expand_rows

        R, C = self.blocksize
        m, n = self.shape
        Mb, Nb = m // R, n // C
        nnzb = int(self.data.shape[0])
        out = jnp.zeros((Mb, Nb, R, C), dtype=self.dtype)
        if nnzb:
            brow = expand_rows(self.indptr, nnzb)
            out = out.at[brow, self.indices].add(self.data)
        return np.asarray(out.transpose(0, 2, 1, 3).reshape(m, n))

    def transpose(self):
        from .ops.coords import expand_rows

        R, C = self.blocksize
        nnzb = int(self.data.shape[0])
        brow = np.asarray(expand_rows(self.indptr, nnzb))
        bcol = np.asarray(self.indices)
        order = np.lexsort((brow, bcol))
        new_indptr = np.zeros(self.shape[1] // C + 1, dtype=np.int64)
        np.add.at(new_indptr, bcol + 1, 1)
        new_indptr = np.cumsum(new_indptr)
        return bsr_array(
            (
                jnp.swapaxes(self.data[jnp.asarray(order)], 1, 2),
                brow[order],
                new_indptr,
            ),
            shape=(self.shape[1], self.shape[0]),
        )

    @property
    def T(self):
        return self.transpose()

    # ---- compute: batched dense blocks on the MXU ------------------------
    def _spmv(self, x):
        from .ops.coords import expand_rows

        R, C = self.blocksize
        m, n = self.shape
        nnzb = int(self.data.shape[0])
        if nnzb == 0:
            return jnp.zeros((m,), dtype=jnp.result_type(self.dtype, x.dtype))
        xb = x.reshape(n // C, C)
        gath = xb[self.indices]  # [nnzb, C]
        prod = jnp.einsum("brc,bc->br", self.data, gath)  # MXU batch matmul
        brow = expand_rows(self.indptr, nnzb)
        y = jax.ops.segment_sum(
            prod, brow, num_segments=m // R, indices_are_sorted=True
        )
        return y.reshape(m)

    def _spmm(self, Bm):
        from .ops.coords import expand_rows

        R, C = self.blocksize
        m, n = self.shape
        k = Bm.shape[1]
        nnzb = int(self.data.shape[0])
        if nnzb == 0:
            return jnp.zeros((m, k), dtype=jnp.result_type(self.dtype, Bm.dtype))
        xb = Bm.reshape(n // C, C, k)
        gath = xb[self.indices]  # [nnzb, C, k]
        prod = jnp.einsum("brc,bck->brk", self.data, gath)
        brow = expand_rows(self.indptr, nnzb)
        y = jax.ops.segment_sum(
            prod, brow, num_segments=m // R, indices_are_sorted=True
        )
        return y.reshape(m, k)

    def dot(self, other):
        other_arr = asjnp(other) if not isinstance(other, SparseArray) else other
        if isinstance(other_arr, SparseArray):
            return self.tocsr() @ other_arr
        if other_arr.ndim == 1:
            if other_arr.shape[0] != self.shape[1]:
                raise ValueError(
                    f"dimension mismatch: {self.shape} @ {other_arr.shape}"
                )
            return self._spmv(other_arr.astype(jnp.result_type(self.dtype, other_arr.dtype)))
        if other_arr.ndim == 2:
            if other_arr.shape[0] != self.shape[1]:
                raise ValueError(
                    f"dimension mismatch: {self.shape} @ {other_arr.shape}"
                )
            return self._spmm(other_arr.astype(jnp.result_type(self.dtype, other_arr.dtype)))
        raise ValueError("bsr dot expects a vector or matrix")

    def __matmul__(self, other):
        return self.dot(other)

    def __add__(self, other):
        other = other.tocsr() if isinstance(other, bsr_array) else other
        return self.tocsr() + other

    def multiply(self, other):
        other = other.tocsr() if isinstance(other, bsr_array) else other
        return self.tocsr().multiply(other)

    def sum(self, axis=None):
        return self.tocsr().sum(axis=axis)

    def __repr__(self):
        return (
            f"<{self.shape[0]}x{self.shape[1]} BSR array, blocksize="
            f"{self.blocksize}, nnzb={int(self.data.shape[0])},"
            f" dtype={self.dtype}>"
        )

    __str__ = __repr__
