"""Recovery policy engine: turn solver-health verdicts into bounded action.

``telemetry/_health.py`` *detects* (nonfinite, divergence, stagnation,
breakdown); nothing in the stack acted on a detection before this module
— a NaN'd 10k-iteration solve simply returned garbage. The engine runs a
solve through a bounded retry ladder (in the spirit of
interpolation-restart resilience for Krylov methods):

==============  =========================================================
verdict         action
==============  =========================================================
stagnation      restart the same solver from the current (best) iterate;
                a second stagnation first DROPS the preconditioner when
                one is wired (the cheap rung — a bad M is a far more
                common stall than a solver mismatch), then escalates
                down the solver ladder (cg -> bicgstab -> gmres)
breakdown       BiCGStab rho/omega breakdown (detected by the health
                monitor's breakdown tap; silently ``where``-guarded in
                the recurrence itself): escalate straight to GMRES
nonfinite       with a preconditioner wired, probe M on a pristine
                finite vector first: M producing nonfinites is
                classified DISTINCTLY (``nonfinite_m``, ISSUE 14) and
                the ladder drops M before anything else — corruption
                inside the preconditioner apply must not cost a solver
                escalation. Otherwise roll back to the last
                ``CheckpointManager`` state when one is wired, else
                clean re-solve from zero
preempt         injected/real preemption at a chunk boundary: resume
                from checkpoint/best iterate
device          a topology failure (``faults.is_topology_error`` — a
                lost slice, a replaced device, an injected mesh fault):
                the ``remesh`` rung runs AHEAD of solver escalation —
                the wired ``on_remesh`` hook re-plans the mesh
                (``SolveSession._do_remesh`` when a session drives the
                ladder), the next attempt resumes from the best
                iterate, and no solver escalation is spent on a
                failure that was never numeric (ISSUE 20,
                docs/resilience.md "Elastic topology")
==============  =========================================================

Every retry emits a ``solver.retry`` event (+ ``resilience.retries``
metrics counter); a solve that converges after >= 1 retry emits
``solver.recovered``; an exhausted attempt/deadline budget emits
``solver.giveup``. Those chains (``fault.injected -> solver.retry ->
solver.recovered``) are what ``scripts/chaos_check.py`` and the
acceptance test assert through ``axon_report``.

Residual verification runs under :func:`faults.suspended` so the check
itself is pristine even when the operator is fault-wrapped, and uses the
same convergence convention as the underlying solver (absolute ``||r|| <
tol`` for CG/BiCGStab, ``max(tol * ||b||, atol)`` for GMRES).
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field

import numpy as np

from ..config import settings
from ..telemetry import _metrics
from . import faults

__all__ = [
    "RecoveryInfo",
    "RecoveryPolicy",
    "deadline_remaining_s",
    "solve_with_recovery",
]

_RETRIES = _metrics.counter("resilience.retries")
_RECOVERED = _metrics.counter("resilience.recovered")
_GIVEUPS = _metrics.counter("resilience.giveups")

#: escalation ladder: where a solver goes when restarting stops helping
ESCALATION = {"cg": "bicgstab", "bicgstab": "gmres", "gmres": "gmres"}


def deadline_remaining_s(t_start: float, deadline_s,
                         now: float | None = None) -> float:
    """Seconds left in a wall-clock budget measured from ``t_start``
    (``time.monotonic`` base); ``inf`` when ``deadline_s`` is ``None``.

    The shared deadline arithmetic of the resilience surfaces: the
    recovery ladder's between-attempt gate here, and the batch
    pipeline's per-ticket checks (``batch/service.py``) — which, with
    streaming dispatch (ISSUE 13), re-evaluate the SAME budget at
    *readback* as well as at dispatch, so a lane that went stale while
    its bucket was in flight never spends a requeue's compute past its
    deadline."""
    if deadline_s is None:
        return math.inf
    now = time.monotonic() if now is None else now
    return float(deadline_s) - (now - float(t_start))


@dataclass
class RecoveryPolicy:
    """Attempt/deadline budgets and ladder knobs for one recovered solve.

    ``max_attempts`` counts solve attempts including the first;
    ``deadline_s`` is wall-clock for the whole ladder (checked between
    attempts — a running attempt is never interrupted). ``escalate``
    overrides the solver ladder; ``restart_first`` is how many
    non-improving same-solver restarts a stagnating solve gets before
    escalating (an attempt that *improved* the best residual always
    restarts for free — progress is never punished with an escalation).
    ``segment_iters``: once a nonfinite/preempt verdict appears, later
    attempts advance in verified segments of this many iterations from
    the best iterate, so a corruption mid-solve costs one segment of
    progress instead of the whole solve (interpolation-restart style);
    corrupted segments HALVE the segment (floor 8) — under heavy
    corruption shorter segments are exponentially more likely to
    complete clean — and each clean segment doubles it back toward the
    full length (AIMD, so the cadence tracks the corruption rate).
    ``verify_factor`` relaxes the pristine residual check (the solvers
    test their *recurrence* residual; the true residual can sit slightly
    above it in low precision).
    ``on_remesh`` is the elastic-mesh hook (ISSUE 20): a no-arg
    callable the ``remesh`` rung invokes when an attempt died of a
    topology error — ``SolveSession._do_remesh`` when a session drives
    the ladder, anything that re-plans placement otherwise. ``None``
    (the default) keeps the rung a plain best-iterate resume."""

    max_attempts: int = 4
    deadline_s: float | None = None
    escalate: dict = field(default_factory=lambda: dict(ESCALATION))
    restart_first: int = 1
    segment_iters: int | None = 50
    verify_factor: float = 1.0
    on_remesh: object = None

    def next_solver(self, solver: str) -> str:
        return self.escalate.get(solver, "gmres")


@dataclass
class RecoveryInfo:
    """Outcome of :func:`solve_with_recovery`."""

    converged: bool
    attempts: int
    iters_total: int
    resid: float
    solver: str  # the solver that produced the returned iterate
    recovered: bool  # converged after at least one retry
    gave_up_reason: str | None = None
    history: list = field(default_factory=list)  # per-attempt dicts


def _finite(x) -> bool:
    return bool(np.isfinite(np.asarray(x)).all())


def _verify(op, b_np, x, target: float):
    """Pristine residual check: ``(rnorm, finite, converged)``. One
    matvec under ``faults.suspended()``."""
    with faults.suspended():
        xa = np.asarray(x)
        if not np.isfinite(xa).all():
            return math.inf, False, False
        r = b_np - np.asarray(op.matvec(x))
    finite = bool(np.isfinite(r).all())
    rnorm = float(np.linalg.norm(r)) if finite else math.inf
    return rnorm, finite, rnorm <= target


def _m_nonfinite(M, b_np) -> bool:
    """Probe whether the preconditioner ITSELF emits nonfinites on a
    pristine finite input (faults stay ACTIVE — an injected
    ``nonfinite:precond`` clause should show here). The distinct
    nonfinite-in-M classifier of the drop-preconditioner rung."""
    from .. import linalg
    from ..utils import asjnp

    try:
        out = np.asarray(linalg.make_linear_operator(M).matvec(asjnp(b_np)))
        return not bool(np.isfinite(out).all())
    except Exception:  # noqa: BLE001 - an M that raises is also bad
        return True


def _health_reasons() -> set:
    """Anomaly reasons of the most recent solve (empty when telemetry is
    off — the engine then falls back to residual-only classification)."""
    if not settings.telemetry:
        return set()
    from .. import telemetry

    rep = telemetry.last_solve_report()
    if not rep:
        return set()
    return {a.get("reason") for a in rep.get("anomalies", ())}


def _run_attempt(solver: str, A, b, x0, tol, target, maxiter, restart, M):
    """Dispatch one attempt through the public linalg solvers. Returns
    ``(x, iters)``; lets :class:`faults.Preempted` propagate."""
    from .. import linalg

    if solver == "cg":
        return linalg.cg(A, b, x0=x0, tol=tol, maxiter=maxiter, M=M)
    if solver == "bicgstab":
        return linalg.bicgstab(A, b, x0=x0, tol=tol, maxiter=maxiter)
    if solver == "gmres":
        # drive GMRES to the ladder's ABSOLUTE target via atol so an
        # escalated attempt meets the original solver's stopping rule
        return linalg.gmres(
            A, b, x0=x0, tol=0.0, atol=target, restart=restart, M=M
        )
    raise ValueError(f"unknown solver {solver!r}")


def solve_with_recovery(
    A,
    b,
    solver: str = "cg",
    tol: float = 1e-8,
    maxiter=None,
    x0=None,
    M=None,
    restart=None,
    policy: RecoveryPolicy | None = None,
    checkpoint=None,
    ticket: str | None = None,
):
    """Solve ``A x = b`` with bounded, observable recovery.

    ``checkpoint`` is an optional :class:`~sparse_tpu.checkpoint.
    CheckpointManager` (or path): finite improving iterates are persisted
    between attempts and a nonfinite/preempted attempt rolls back to the
    last saved state instead of restarting from zero. Returns
    ``(x, RecoveryInfo)``; never raises on solver failure — an exhausted
    budget returns the best iterate with ``info.converged=False`` and a
    ``solver.giveup`` event.

    ``ticket`` threads a request-scoped trace id (``telemetry.
    new_ticket_id()`` / a ``SolveTicket.id``) through the whole ladder:
    every event any attempt emits — ``solver.retry``, a deep
    ``kernel.failover``, the terminal ``solver.recovered``/``giveup`` —
    then carries it (``telemetry.ticket_scope``), so a recovered solve
    reads as one request in the ticket-aware Axon tooling.
    """
    from .. import linalg, telemetry
    from ..utils import asjnp

    if ticket is not None:
        with telemetry.ticket_scope(ticket):
            return solve_with_recovery(
                A, b, solver=solver, tol=tol, maxiter=maxiter, x0=x0,
                M=M, restart=restart, policy=policy,
                checkpoint=checkpoint, ticket=None,
            )

    pol = policy or RecoveryPolicy()
    if checkpoint is not None and not hasattr(checkpoint, "load"):
        from ..checkpoint import CheckpointManager

        checkpoint = CheckpointManager(checkpoint)
    op = linalg.make_linear_operator(A)
    b_np = np.asarray(b)
    n = b_np.shape[0]
    if maxiter is None:
        maxiter = 10 * n
    # the underlying solvers test absolute ||r|| < tol (gmres: relative,
    # floored by atol) — verify against the matching target
    bnorm = float(np.linalg.norm(b_np))
    target = float(tol) * max(bnorm, 1.0) if solver == "gmres" else float(tol)

    verify_target = target * max(float(pol.verify_factor), 1.0)
    t0 = time.monotonic()
    cur_solver = solver
    cur_x0 = x0
    cur_M = M  # dropped (set None) by the drop-preconditioner rung
    attempt_maxiter = maxiter
    seg = None  # None until the first nonfinite/preempt verdict
    restarts_used = 0
    iters_total = 0
    history: list = []
    best_x = None
    best_rnorm = math.inf

    for attempt in range(1, max(int(pol.max_attempts), 1) + 1):
        reason = None
        x = None
        iters = 0
        prev_best = best_rnorm
        try:
            x, iters = _run_attempt(
                cur_solver, A, asjnp(b), cur_x0, tol, target,
                attempt_maxiter, restart, cur_M,
            )
            iters_total += int(iters)
            rnorm, finite, ok = _verify(op, b_np, x, verify_target)
        except faults.Preempted as e:
            reason, rnorm, finite, ok = "preempt", math.inf, False, False
            history.append(
                {"attempt": attempt, "solver": cur_solver,
                 "reason": "preempt", "error": str(e)}
            )
        except Exception as e:  # noqa: BLE001 - topology-only; re-raised
            if not faults.is_topology_error(e):
                raise
            # a device/topology failure, not a numeric one (ISSUE 20):
            # classified distinctly so the ladder can re-plan placement
            # instead of burning a solver escalation
            reason, rnorm, finite, ok = "device", math.inf, False, False
            history.append(
                {"attempt": attempt, "solver": cur_solver,
                 "reason": "device", "error": str(e)}
            )
        if reason is None:
            history.append(
                {"attempt": attempt, "solver": cur_solver,
                 "iters": int(iters), "rnorm": rnorm}
            )
            if finite and rnorm < best_rnorm:
                best_x, best_rnorm = x, rnorm
                if checkpoint is not None:
                    checkpoint.save(attempt, x=np.asarray(x))
            if ok:
                recovered = attempt > 1
                if recovered:
                    _RECOVERED.inc()
                    telemetry.record(
                        "solver.recovered", solver=cur_solver,
                        attempts=attempt, iters_total=iters_total,
                        resid=rnorm, requested=solver,
                    )
                return x, RecoveryInfo(
                    converged=True, attempts=attempt,
                    iters_total=iters_total, resid=rnorm,
                    solver=cur_solver, recovered=recovered,
                    history=history,
                )
            # classify the failure (health verdicts refine the residual
            # view: breakdown is only visible through the monitor's tap)
            verdicts = _health_reasons()
            if not finite:
                # nonfinite-in-M is classified DISTINCTLY (ISSUE 14):
                # probe the preconditioner on a pristine finite vector
                # (faults stay active — an injected precond clause shows
                # here) so the ladder can drop M instead of burning a
                # rollback + solver escalation on corruption the
                # operator never produced
                if cur_M is not None and _m_nonfinite(cur_M, b_np):
                    reason = "nonfinite_m"
                else:
                    reason = "nonfinite"
            elif "breakdown" in verdicts:
                reason = "breakdown"
            else:
                reason = "stagnation"

        # -- budget gates ---------------------------------------------------
        gave_up = None
        if attempt >= pol.max_attempts:
            gave_up = "attempts"
        elif deadline_remaining_s(t0, pol.deadline_s) <= 0:
            gave_up = "deadline"
        if gave_up:
            _GIVEUPS.inc()
            telemetry.record(
                "solver.giveup", solver=cur_solver, attempts=attempt,
                reason=gave_up, last_verdict=reason, resid=best_rnorm,
                requested=solver,
            )
            x_out = best_x if best_x is not None else (
                x if x is not None else asjnp(np.zeros_like(b_np))
            )
            return x_out, RecoveryInfo(
                converged=False, attempts=attempt,
                iters_total=iters_total, resid=best_rnorm,
                solver=cur_solver, recovered=False,
                gave_up_reason=gave_up, history=history,
            )

        # -- ladder ---------------------------------------------------------
        improved = (
            reason not in ("nonfinite", "nonfinite_m", "preempt", "device")
            and math.isfinite(best_rnorm)
            and best_rnorm < prev_best * (1.0 - 1e-3)
        )
        if reason == "device":
            # the remesh rung (ISSUE 20): the attempt died of topology,
            # not numerics — re-plan placement (the wired hook) and
            # resume from the best finite iterate; neither a solver
            # escalation nor the restart budget is spent
            action = "remesh"
            if pol.on_remesh is not None:
                try:
                    pol.on_remesh()
                except Exception:  # noqa: BLE001 - re-plan best-effort
                    pass
            cur_x0 = best_x  # None => clean re-solve from zero
        elif reason == "nonfinite_m":
            # the drop-preconditioner rung (ISSUE 14): the corruption
            # came from M's apply, so dropping it IS the fix — resume
            # from the best finite iterate, no solver escalation, no
            # segmented advance
            action = "drop_precond"
            cur_M = None
            cur_x0 = best_x  # None => clean re-solve from zero
        elif reason == "breakdown":
            action = "escalate"
            cur_solver = "gmres"
            cur_x0 = best_x
        elif reason in ("nonfinite", "preempt"):
            state = None
            if checkpoint is not None:
                _, state = checkpoint.load()
            if state is not None and "x" in state:
                action = "rollback"
                cur_x0 = asjnp(state["x"]).astype(b_np.dtype)
            elif best_x is not None:
                action = "rollback"
                cur_x0 = best_x
            else:
                action = "clean"
                cur_x0 = None
            if pol.segment_iters:
                # advance in verified segments from here on: a repeat
                # corruption costs one segment, not the whole solve.
                # AIMD on the segment length: halve per corrupted
                # segment (shorter segments are exponentially likelier
                # to complete clean), double back per clean one below.
                seg = max(
                    (seg if seg is not None
                     else 2 * int(pol.segment_iters)) // 2, 8,
                )
                attempt_maxiter = seg
        else:  # stagnation
            if seg is not None:
                # last segment completed clean: grow back toward full
                seg = min(seg * 2, max(int(pol.segment_iters), 1))
                attempt_maxiter = seg
            if improved:
                # the attempt made real progress (short maxiter budget,
                # verified segment): keep going from the best iterate —
                # progress never consumes the restart budget
                action = "restart"
            elif restarts_used < pol.restart_first:
                action = "restart"
                restarts_used += 1
            elif cur_M is not None:
                # drop-preconditioner rung BEFORE solver escalation
                # (ISSUE 14): a stalling preconditioned solve sheds M
                # first — cheaper than a solver change, and a bad M is
                # the likelier stall — with a fresh restart budget for
                # the unpreconditioned configuration
                action = "drop_precond"
                cur_M = None
                restarts_used = 0
            else:
                action = "escalate"
                cur_solver = pol.next_solver(cur_solver)
                restarts_used = 0
            cur_x0 = best_x if best_x is not None else x
        _RETRIES.inc()
        _metrics.counter("resilience.retries.by_reason", reason=reason).inc()
        telemetry.record(
            "solver.retry", solver=cur_solver, attempt=attempt,
            reason=reason, action=action, requested=solver,
            resid=best_rnorm if math.isfinite(best_rnorm) else None,
        )
