"""Unified Pallas->XLA failover registry.

Before this module, three call sites each carried their own one-time
failover latch: ``kernels/sell_spmv.PreparedCSR`` (a ``_pallas_ok``
attribute), ``kernels/dia_spmv.cached_prepared_spmv`` (a plan-cache
sentinel) and ``batch/operator.BatchedCSR`` (another ``_pallas_ok``) —
three copies of the classification logic, three slightly different
event shapes, and no way to *undo* a failover when the backend heals
(e.g. a tunnel TPU that was briefly mid-restart). This registry is the
one place failover state lives:

* ``failed(kernel, obj)`` — is the Pallas path latched off for this
  (kernel, operator) pair? Checked at dispatch, one dict probe.
* ``handle(kernel, obj, e)`` — the shared failure ladder: classify the
  error (vocabulary match for DIA's backend-aware rules, any
  ``ValueError``/``NotImplementedError`` for the SELL sites), honor
  ``SPARSE_TPU_STRICT_PALLAS``, warn once, emit a consistent
  ``kernel.failover`` event + ``kernel.failovers`` metrics counter, and
  latch. Returns when the caller should take the XLA path; re-raises
  genuine caller errors.
* ``maybe_inject(kernel)`` — the fault-injection hook: raises
  :class:`InjectedPallasFailure` when a ``fail:pallas`` clause fires
  (:mod:`.faults`), which then rides the exact production failover path.
* ``probe(kernel, obj, fn)`` — the reinstate hook: run a real kernel
  attempt; on success the latch clears and a ``kernel.reinstate`` event
  records the recovery, so a transiently-broken backend doesn't pay the
  XLA slow path for the rest of the process lifetime.

Entries keyed by an operator object are weak-ref finalized (same
discipline as ``sparse_tpu.plan_cache``) so the registry cannot leak or
resurrect state across object lifetimes.
"""

from __future__ import annotations

import os
import threading
import weakref

from ..telemetry import _metrics
from . import faults

__all__ = [
    "InjectedPallasFailure",
    "classify_unavailable",
    "clear",
    "failed",
    "handle",
    "latches",
    "mark_failed",
    "maybe_inject",
    "probe",
    "reinstate",
    "snapshot",
    "strict",
]

_LOCK = threading.RLock()
# (kernel, id(obj) or 0) -> error repr
_FAILED: dict = {}
_FINALIZERS: dict = {}

_FAILOVERS = _metrics.counter("kernel.failovers")
_REINSTATES = _metrics.counter("kernel.reinstates")


class InjectedPallasFailure(NotImplementedError):
    """A forced Pallas launch failure from the fault injector. Subclasses
    ``NotImplementedError`` so every existing failover handler treats it
    as the canonical lowering-unavailable signal (strict mode included —
    an injected failure must exercise the production failover, not the
    strict re-raise)."""


def _key(kernel: str, obj) -> tuple:
    return (kernel, 0 if obj is None else id(obj))


def _finalize_obj(oid: int) -> None:
    with _LOCK:
        for k in [k for k in _FAILED if k[1] == oid]:
            del _FAILED[k]
        _FINALIZERS.pop(oid, None)


def strict() -> bool:
    """``SPARSE_TPU_STRICT_PALLAS``: pattern-matched ``ValueError``s
    re-raise instead of failing over (this repo's CI default — see
    tests/conftest.py)."""
    return bool(os.environ.get("SPARSE_TPU_STRICT_PALLAS"))


def failed(kernel: str, obj=None) -> bool:
    """True when the Pallas path is latched off for ``(kernel, obj)``
    (or kernel-wide with ``obj=None``)."""
    with _LOCK:
        return _key(kernel, obj) in _FAILED or (kernel, 0) in _FAILED


def mark_failed(kernel: str, obj=None, error: str = "") -> None:
    """Latch the Pallas path off and record the consistent failover
    telemetry (``kernel.failover`` event + ``kernel.failovers`` metrics
    counter). Idempotent per (kernel, obj)."""
    import jax

    key = _key(kernel, obj)
    with _LOCK:
        fresh = key not in _FAILED
        _FAILED[key] = error
        if obj is not None and id(obj) not in _FINALIZERS:
            try:
                _FINALIZERS[id(obj)] = weakref.finalize(
                    obj, _finalize_obj, id(obj)
                )
            except TypeError:
                pass  # un-weakref-able key: entry lives for the process
    if not fresh:
        return
    _FAILOVERS.inc()
    _metrics.counter("kernel.failovers.by_kernel", kernel=kernel).inc()
    from ..config import settings

    if settings.telemetry:
        from .. import telemetry

        telemetry.record(
            "kernel.failover", kernel=kernel, error=error[:200],
            backend=jax.default_backend(),
        )


def reinstate(kernel: str, obj=None) -> bool:
    """Clear the latch (the probe hook's success path); returns whether
    anything was latched. Emits ``kernel.reinstate``."""
    with _LOCK:
        had = _FAILED.pop(_key(kernel, obj), None) is not None
        # an obj-level reinstate also clears a kernel-wide latch: the
        # probe proved the kernel lowers on this backend again
        if obj is not None:
            had = (_FAILED.pop((kernel, 0), None) is not None) or had
    if had:
        _REINSTATES.inc()
        from ..config import settings

        if settings.telemetry:
            from .. import telemetry

            telemetry.record("kernel.reinstate", kernel=kernel)
    return had


def probe(kernel: str, obj, probe_fn) -> bool:
    """Probe-based reinstate: run one real kernel attempt (``probe_fn``,
    zero-arg). Success clears the latch and returns True; any exception
    leaves the latch in place and returns False (the probe is the safe
    place to fail)."""
    try:
        probe_fn()
    except Exception:
        return False
    reinstate(kernel, obj)
    return True


def maybe_inject(kernel: str) -> None:
    """Raise :class:`InjectedPallasFailure` when a ``fail:pallas`` fault
    clause fires for ``kernel`` (no-op otherwise; one boolean read when
    injection is inactive)."""
    if faults.ACTIVE and faults.should_fail_pallas(kernel):
        raise InjectedPallasFailure(
            f"injected Pallas launch failure for kernel {kernel!r}"
        )


def classify_unavailable(e: Exception) -> bool:
    """Backend-aware classification of a Pallas error as
    lowering-unavailable (failover-eligible) vs a genuine caller/kernel
    bug (must re-raise). The DIA site's rules, shared: on real TPU only
    the historical interpret-mode message is benign; off-TPU any
    lowering-availability wording (or a bare ``NotImplementedError``)
    qualifies."""
    import jax

    if isinstance(e, InjectedPallasFailure):
        return True
    msg = str(e).lower()
    if jax.default_backend() == "tpu":
        return "interpret mode" in msg
    return isinstance(e, NotImplementedError) or any(
        s in msg
        for s in (
            "interpret mode",
            "lowering",
            "not implemented",
            "unsupported backend",
            "unimplemented",
            "mosaic",
        )
    )


def handle(kernel: str, obj, e: Exception, vocab: bool = False) -> None:
    """The shared failover ladder for a caught Pallas error.

    ``vocab=True`` applies :func:`classify_unavailable` first (the DIA
    site's stricter contract); the SELL sites fail over on any caught
    ``ValueError``/``NotImplementedError``. Strict mode re-raises
    pattern-matched ``ValueError``s in both regimes; a bare
    ``NotImplementedError`` (including injected failures) always takes
    the failover. On return the caller takes the XLA path; otherwise
    this re-raises ``e``.
    """
    if vocab and not classify_unavailable(e):
        raise e
    if strict() and not isinstance(e, NotImplementedError):
        raise e
    from ..utils import user_warning

    user_warning(
        f"Pallas kernel {kernel!r} unavailable; failing over to the XLA "
        f"formulation for this operator: {e!r}"
    )
    mark_failed(kernel, obj, error=repr(e))


def snapshot() -> dict:
    """Current latches: ``{(kernel, keyed): error}`` with ``keyed`` the
    object id (0 = kernel-wide) — introspection/debugging surface."""
    with _LOCK:
        return {f"{k}[{oid or '*'}]": err for (k, oid), err in _FAILED.items()}


def latches() -> dict:
    """JSON-friendly per-kernel latch view for serving surfaces
    (``/healthz``): ``{kernel: {"scoped": n per-operator latches,
    "kernel_wide": bool, "error": the most recent latch's error}}`` —
    operator ids stay internal (they are meaningless across processes
    and would churn every scrape)."""
    with _LOCK:
        items = list(_FAILED.items())
    out: dict = {}
    for (kernel, oid), err in items:
        st = out.setdefault(
            kernel, {"scoped": 0, "kernel_wide": False, "error": ""}
        )
        if oid == 0:
            st["kernel_wide"] = True
        else:
            st["scoped"] += 1
        st["error"] = str(err)[:200]
    return out


def clear() -> None:
    """Drop every latch (tests)."""
    with _LOCK:
        _FAILED.clear()
        for f in _FINALIZERS.values():
            try:
                f.detach()
            except Exception:
                pass
        _FINALIZERS.clear()
