"""sparse_tpu.resilience — fault injection + bounded, observable recovery.

The detect-only observability stack (``sparse_tpu.telemetry``) gets an
*acting* counterpart:

* :mod:`.faults` — seeded, spec-driven fault injector gated by
  ``SPARSE_TPU_FAULTS`` (matvec corruption, forced Pallas failure,
  dispatch drop/delay, chunk-boundary preemption). Strictly zero
  overhead and zero code-path change when unset.
* :mod:`.failover` — the one registry behind every Pallas->XLA failover
  (SELL, DIA, batched SELL): consistent ``kernel.failover`` events,
  strict-mode rules in one place, and a probe-based reinstate hook.
* :mod:`.policy` — the recovery engine: health verdicts -> bounded retry
  ladder (restart from iterate, BiCGStab-breakdown -> GMRES escalation,
  nonfinite -> checkpoint rollback / clean re-solve) with per-solve
  attempt + deadline budgets, emitting ``solver.retry`` /
  ``solver.recovered`` / ``solver.giveup``.

The resilient :class:`~sparse_tpu.batch.service.SolveSession` (ticket
deadlines, failed-lane requeue, degraded mode) builds on the same
pieces. docs/resilience.md is the human-facing guide.
"""

from __future__ import annotations

from . import failover, faults  # noqa: F401
from .failover import InjectedPallasFailure  # noqa: F401
from .faults import FaultSpecError, Preempted  # noqa: F401

__all__ = [
    "FaultSpecError",
    "InjectedPallasFailure",
    "Preempted",
    "RecoveryInfo",
    "RecoveryPolicy",
    "deadline_remaining_s",
    "failover",
    "faults",
    "policy",
    "solve_with_recovery",
]


def __getattr__(name):
    # policy imports linalg (lazily at call time, but keep the package
    # import light and cycle-proof anyway): resolve on first touch
    if name in ("policy", "RecoveryPolicy", "RecoveryInfo",
                "solve_with_recovery", "deadline_remaining_s"):
        import importlib

        _policy = importlib.import_module(".policy", __name__)

        globals()["policy"] = _policy
        globals()["RecoveryPolicy"] = _policy.RecoveryPolicy
        globals()["RecoveryInfo"] = _policy.RecoveryInfo
        globals()["solve_with_recovery"] = _policy.solve_with_recovery
        globals()["deadline_remaining_s"] = _policy.deadline_remaining_s
        return globals()[name]
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
