"""Seeded, spec-driven fault injection for chaos-testing the solve stack.

The ROADMAP north star is production traffic; production solves meet
NaN-producing operator data, kernels whose backend lowering vanishes,
stragglers, and preemption. This module makes every one of those failure
modes *reproducible on demand* so the recovery machinery
(:mod:`.policy`, :mod:`.failover`, the resilient
:class:`~sparse_tpu.batch.service.SolveSession`) can be exercised in CI
instead of discovered in an incident.

Faults are described by ``SPARSE_TPU_FAULTS`` (``settings.faults``), a
semicolon-separated list of clauses::

    fault:site[:key=value[,key=value...]]

    nonfinite:matvec:p=0.01,seed=7     # NaN-poison matvec outputs
    inf:matvec:p=0.005                 # Inf instead of NaN
    bitflip:matvec:p=0.01,scale=1e18   # scale one element (bitflip-like)
    fail:pallas                        # force Pallas launch failure
    fail:pallas:kernel=sell_spmv,n=1   # ...for one kernel, first try only
    drop:dispatch:p=0.5                # SolveSession dispatch failure
    delay:dispatch:ms=25               # dispatch latency injection
    preempt:chunk:p=0.1,seed=3         # preemption at chunk boundaries
    shrink:mesh:to=4                   # serving mesh forged down to 4
    swap:mesh                          # same-size mesh, devices replaced
    flap:mesh:n=6                      # topology toggles per disruption
    truncate:io:p=0.5                  # vault write survives torn/half
    bitflip:io:p=0.1,seed=5            # flip one byte on artifact read
    stale:io                           # write with an outdated format
    enospc:io:n=1                      # artifact write hits ENOSPC

Each clause fires with probability ``p`` (default 1) from its own seeded
``numpy`` Generator (``seed``, default 0) so a chaos run is bit-for-bit
repeatable; ``n=`` bounds the total number of fires. Every fire bumps
the always-on ``faults.injected`` metrics counter and (telemetry
enabled) emits a ``fault.injected`` event — the head of the
``fault.injected -> solver.retry -> solver.recovered`` chains
``scripts/chaos_check.py`` asserts.

**Zero overhead / zero code-path change when unset.** Every hook in the
library is gated on the module-level :data:`ACTIVE` boolean (a single
attribute read, host-side only); the matvec corruption wrapper is only
*installed* when a matvec clause is active, so with the env unset the
traced solver programs are byte-identical to a build without this
module (``tests/test_resilience.py`` pins jaxpr equality and the
host-sync count).
"""

from __future__ import annotations

import contextlib
import threading
from dataclasses import dataclass, field

import numpy as np

from ..config import settings
from ..telemetry import _metrics

__all__ = [
    "ACTIVE",
    "FaultClause",
    "FaultSpecError",
    "InjectedMeshFailure",
    "Preempted",
    "TopologyError",
    "active",
    "check_preempt",
    "clear",
    "configure",
    "corrupt_array",
    "corrupt_traced",
    "dispatch_actions",
    "io_actions",
    "is_topology_error",
    "mesh_disrupt",
    "mesh_view",
    "parse_spec",
    "reload_from_env",
    "should_fail_pallas",
    "stats",
    "suspended",
    "targets",
    "wrap_batched_matvec",
    "wrap_precond",
]

#: site -> admissible faults (the grammar's type table)
SITES = {
    "matvec": ("nonfinite", "inf", "bitflip"),
    # preconditioner application (sparse_tpu.precond): same corruption
    # grammar as matvec, but the wrapper installs INSIDE the M apply —
    # so the chaos drills can corrupt the preconditioner while the
    # operator stays pristine (the recovery ladder's drop-preconditioner
    # rung, docs/resilience.md)
    "precond": ("nonfinite", "inf", "bitflip"),
    "pallas": ("fail",),
    "dispatch": ("drop", "delay"),
    "chunk": ("preempt",),
    # persistent plan-cache tier (sparse_tpu.vault): disk failure modes.
    # Write path: truncate (torn write left on disk), stale (artifact
    # from an outdated format), enospc (OSError at write). Read path:
    # bitflip (one corrupted byte). Every one must quarantine + rebuild,
    # never crash or mis-serve (docs/resilience.md).
    "io": ("truncate", "bitflip", "stale", "enospc"),
    # serving-mesh topology (sparse_tpu.fleet.elastic): forge a
    # deterministic topology change on the forced CPU mesh so the
    # elastic-mesh path (detect -> quiesce -> migrate -> re-plan) is
    # drillable in CI. ``shrink:mesh:to=4`` — the forged world lost
    # devices (default: half the mesh); ``swap:mesh`` — same count,
    # different physical devices (a slice replacement); ``flap:mesh`` —
    # the topology toggles between shrunk and original on every
    # disruption, the flap-guard drill (docs/resilience.md "Elastic
    # topology").
    "mesh": ("shrink", "swap", "flap"),
}

#: which io faults apply on which half of the artifact IO path
_IO_WRITE_FAULTS = ("truncate", "stale", "enospc")
_IO_READ_FAULTS = ("bitflip",)

_INJECTED = _metrics.counter("faults.injected")

#: module-level hot-path gate: True iff an injector is configured.
#: Library hooks read this one attribute and do nothing else when False.
ACTIVE = False

_LOCK = threading.RLock()
_INJECTOR = None
_SUSPEND = 0  # >0: injection temporarily disabled (policy verification)


class FaultSpecError(ValueError):
    """A ``SPARSE_TPU_FAULTS`` clause that does not parse/validate."""


class Preempted(RuntimeError):
    """Raised by :func:`check_preempt` at a chunk boundary — the injected
    analog of the process being preempted mid-solve. Recovery drivers
    (``resilience.policy``) catch it and resume from the last
    checkpoint/iterate."""


class TopologyError(RuntimeError):
    """A failure attributable to the device topology itself — a lost
    slice, a replaced device, a mesh the program was compiled for that
    no longer exists. The classification the elastic-mesh machinery
    (``fleet/elastic.py``, the recovery ladder's ``remesh`` rung) keys
    off, as distinct from numeric failures."""


class InjectedMeshFailure(TopologyError):
    """A ``mesh``-site fault clause fired (:func:`mesh_disrupt`) — the
    injected stand-in for a dispatch lost to a topology change."""


#: substrings that mark a backend error as topology-caused; deliberately
#: narrow — a mis-classified numeric failure would spend a remesh where
#: a solver escalation was owed
_TOPOLOGY_MARKERS = (
    "topology changed", "slice lost", "device unavailable",
    "device failure", "data_loss", "mesh mismatch",
)


def is_topology_error(exc) -> bool:
    """Classify an exception as a device/topology failure (vs numeric):
    the :class:`TopologyError` family, or a backend ``RuntimeError``/
    ``OSError`` carrying one of the known topology markers. The gate
    ahead of the recovery ladder's ``remesh`` rung and the session's
    dispatch-error revalidation."""
    if isinstance(exc, TopologyError):
        return True
    if isinstance(exc, (RuntimeError, OSError)):
        msg = str(exc).lower()
        return any(m in msg for m in _TOPOLOGY_MARKERS)
    return False


@dataclass(frozen=True)
class FaultClause:
    """One parsed clause of the fault spec."""

    fault: str
    site: str
    p: float = 1.0
    seed: int = 0
    kernel: str | None = None  # pallas clauses: restrict to one kernel name
    scale: float = 1e18  # bitflip multiplier
    ms: float = 10.0  # delay duration
    n: int | None = None  # max total fires (None = unbounded)
    extras: tuple = field(default_factory=tuple)

    def describe(self) -> str:
        opts = [f"p={self.p:g}", f"seed={self.seed}"]
        if self.kernel:
            opts.append(f"kernel={self.kernel}")
        if self.n is not None:
            opts.append(f"n={self.n}")
        return f"{self.fault}:{self.site}:" + ",".join(opts)


def parse_spec(spec: str) -> tuple:
    """Parse a ``SPARSE_TPU_FAULTS`` string into clauses (see module doc).

    Raises :class:`FaultSpecError` on unknown sites/faults, site/fault
    mismatches, or malformed options — a chaos run with a typo'd spec
    must fail loudly, not silently inject nothing.
    """
    clauses = []
    for raw in str(spec).split(";"):
        raw = raw.strip()
        if not raw:
            continue
        parts = raw.split(":", 2)
        if len(parts) < 2:
            raise FaultSpecError(
                f"clause {raw!r}: expected fault:site[:options]"
            )
        fault, site = parts[0].strip().lower(), parts[1].strip().lower()
        if site not in SITES:
            raise FaultSpecError(
                f"clause {raw!r}: unknown site {site!r} "
                f"(one of {sorted(SITES)})"
            )
        if fault not in SITES[site]:
            raise FaultSpecError(
                f"clause {raw!r}: fault {fault!r} not valid for site "
                f"{site!r} (one of {SITES[site]})"
            )
        kw: dict = {}
        extras = []
        if len(parts) == 3 and parts[2].strip():
            for opt in parts[2].split(","):
                opt = opt.strip()
                if not opt:
                    continue
                if "=" not in opt:
                    raise FaultSpecError(
                        f"clause {raw!r}: option {opt!r} is not key=value"
                    )
                k, v = (s.strip() for s in opt.split("=", 1))
                try:
                    if k == "p":
                        kw["p"] = float(v)
                    elif k == "seed":
                        kw["seed"] = int(v)
                    elif k == "kernel":
                        kw["kernel"] = v
                    elif k == "scale":
                        kw["scale"] = float(v)
                    elif k == "ms":
                        kw["ms"] = float(v)
                    elif k == "n":
                        kw["n"] = int(v)
                    else:
                        extras.append((k, v))
                except ValueError as e:
                    raise FaultSpecError(
                        f"clause {raw!r}: bad value for {k!r}: {v!r}"
                    ) from e
        p = kw.get("p", 1.0)
        if not (0.0 <= p <= 1.0):
            raise FaultSpecError(f"clause {raw!r}: p={p} outside [0, 1]")
        clauses.append(
            FaultClause(fault=fault, site=site, extras=tuple(extras), **kw)
        )
    return tuple(clauses)


class _Injector:
    """Clause set + per-clause seeded RNGs and fire budgets."""

    def __init__(self, clauses):
        self.clauses = tuple(clauses)
        self._rngs = [np.random.default_rng(c.seed) for c in clauses]
        self._fires = [0] * len(clauses)
        self.by_site: dict = {}
        for i, c in enumerate(clauses):
            self.by_site.setdefault(c.site, []).append(i)

    def _draw(self, i: int) -> bool:
        """One Bernoulli draw for clause ``i`` honoring its fire budget.
        The RNG always advances (determinism does not depend on budget
        state), the budget only gates whether the fire takes effect."""
        c = self.clauses[i]
        hit = bool(self._rngs[i].random() < c.p)
        if not hit:
            return False
        if c.n is not None and self._fires[i] >= c.n:
            return False
        self._fires[i] += 1
        return True

    def stats(self) -> dict:
        return {
            c.describe(): f for c, f in zip(self.clauses, self._fires)
        }


def _record_fire(clause: FaultClause, **extra) -> None:
    _INJECTED.inc()
    _metrics.counter(
        "faults.injected.by_site", site=clause.site, fault=clause.fault
    ).inc()
    if settings.telemetry:
        from .. import telemetry

        telemetry.record(
            "fault.injected", site=clause.site, fault=clause.fault, **extra
        )


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------
def configure(spec: str | None) -> None:
    """Install an injector from a spec string (tests / chaos drivers).
    ``None``/empty clears injection entirely."""
    global _INJECTOR, ACTIVE
    with _LOCK:
        if not spec:
            _INJECTOR = None
            ACTIVE = False
            return
        _INJECTOR = _Injector(parse_spec(spec))
        ACTIVE = True


def clear() -> None:
    """Remove all fault injection (hooks go back to their one-boolean
    disabled path)."""
    configure(None)


def reload_from_env() -> None:
    """Re-read ``SPARSE_TPU_FAULTS`` from the environment (the settings
    object caches env at import; tests monkeypatching the env call this)."""
    import os

    configure(os.environ.get("SPARSE_TPU_FAULTS", ""))


def active() -> bool:
    return ACTIVE


def targets(site: str) -> bool:
    """True when a clause targets ``site`` — the hook-installation gate
    (e.g. the matvec wrapper only exists when ``targets('matvec')``)."""
    inj = _INJECTOR
    return bool(inj and site in inj.by_site)


@contextlib.contextmanager
def suspended():
    """Temporarily disable every injection (depth-counted). The recovery
    policy verifies residuals under this guard so a verification matvec
    through a fault-wrapped operator is pristine."""
    global _SUSPEND
    with _LOCK:
        _SUSPEND += 1
    try:
        yield
    finally:
        with _LOCK:
            _SUSPEND -= 1


def stats() -> dict:
    """Per-clause fire counts (``{clause-description: fires}``)."""
    inj = _INJECTOR
    return inj.stats() if inj else {}


# ---------------------------------------------------------------------------
# injection points
# ---------------------------------------------------------------------------
def corrupt_array(a: np.ndarray, site: str = "matvec") -> np.ndarray:
    """Host-side corruption of one array per the active matvec clauses
    (NaN / Inf / scale-one-element). Returns the (possibly copied) array;
    the input is never mutated in place."""
    inj = _INJECTOR
    if inj is None or _SUSPEND > 0:
        return a
    out = a
    for i in inj.by_site.get(site, ()):
        c = inj.clauses[i]
        with _LOCK:
            fire = inj._draw(i)
            if not fire:
                continue
            idx = int(inj._rngs[i].integers(max(out.size, 1)))
        if out is a:
            out = np.array(a, copy=True)
        if out.size == 0:
            continue
        if c.fault == "nonfinite":
            out.flat[idx] = np.nan
        elif c.fault == "inf":
            out.flat[idx] = np.inf
        elif c.fault == "bitflip":
            out.flat[idx] = out.flat[idx] * c.scale
        _record_fire(c, index=idx, size=int(out.size))
    return out


def corrupt_traced(y, site: str = "matvec"):
    """Trace-safe corruption of a device array: routes through
    ``jax.pure_callback`` so the seeded host RNG decides per *execution*
    (works inside ``lax.while_loop`` bodies on the CPU backend, where
    chaos runs live). Only ever called from wrappers that are installed
    when a matvec clause is active — never present in clean traces."""
    import jax

    def _cb(a):
        return corrupt_array(np.asarray(a), site=site)

    return jax.pure_callback(
        _cb, jax.ShapeDtypeStruct(y.shape, y.dtype), y
    )


def wrap_batched_matvec(mv):
    """Wrap a batched ``(B, n) -> (B, m)`` matvec with output corruption
    (the hook :mod:`sparse_tpu.batch.krylov` installs when active)."""

    def faulty_mv(X):
        return corrupt_traced(mv(X), site="matvec")

    faulty_mv._fault_wrapped = True
    return faulty_mv


def wrap_precond(mvec):
    """Wrap a preconditioner apply (batched ``(B, n) -> (B, n)``, or
    unbatched ``(n,) -> (n,)``) with output corruption — the hook
    :mod:`sparse_tpu.precond` installs when a ``precond`` clause is
    active. Distinct from the matvec site so a drill can poison M while
    A stays pristine."""

    def faulty_apply(R):
        return corrupt_traced(mvec(R), site="precond")

    faulty_apply._fault_wrapped = True
    return faulty_apply


def should_fail_pallas(kernel: str) -> bool:
    """Draw the forced-Pallas-failure clauses for ``kernel``; a fire is
    recorded here (the failover site raises and emits the matching
    ``kernel.failover``)."""
    inj = _INJECTOR
    if inj is None or _SUSPEND > 0:
        return False
    for i in inj.by_site.get("pallas", ()):
        c = inj.clauses[i]
        if c.kernel is not None and c.kernel != kernel:
            continue
        with _LOCK:
            fire = inj._draw(i)
        if fire:
            _record_fire(c, kernel=kernel)
            return True
    return False


def dispatch_actions() -> list:
    """Actions for one SolveSession dispatch: ``[("drop",)]`` and/or
    ``[("delay", ms)]`` per the active dispatch clauses (a fired drop is
    recorded here; the session raises its injected dispatch failure)."""
    inj = _INJECTOR
    if inj is None or _SUSPEND > 0:
        return []
    acts = []
    for i in inj.by_site.get("dispatch", ()):
        c = inj.clauses[i]
        with _LOCK:
            fire = inj._draw(i)
        if not fire:
            continue
        if c.fault == "drop":
            _record_fire(c)
            acts.append(("drop",))
        elif c.fault == "delay":
            _record_fire(c, ms=c.ms)
            acts.append(("delay", c.ms))
    return acts


def _mesh_to(c: FaultClause) -> int | None:
    """The ``to=`` option of a mesh clause (rides the extras path —
    ``to`` is grammar only this site understands). ``None`` = the
    consumer's default (half the current mesh)."""
    for k, v in c.extras:
        if k == "to":
            try:
                return int(v)
            except ValueError as e:
                raise FaultSpecError(
                    f"mesh clause: bad value for 'to': {v!r}"
                ) from e
    return None


def mesh_view():
    """The forged topology the active mesh clause currently presents,
    WITHOUT consuming a fire: ``None`` when no mesh clause is live, else
    ``(kind, to)`` — ``('shrink', n)`` for a world that lost devices,
    ``('swap', None)`` for same-count replaced devices, ``('none',
    None)`` for a flap clause currently back on the original topology.
    Deterministic and idempotent: the session's :class:`~sparse_tpu.
    fleet.elastic.MeshMonitor` polls this to decide whether the forged
    world differs from the mesh it is serving on; only when it does is
    a fire consumed (:func:`mesh_disrupt`). A flap clause alternates
    its view on the clause's fire parity — each consumed disruption
    toggles the forged world, so remeshes ping-pong until the flap
    guard latches."""
    inj = _INJECTOR
    if inj is None or _SUSPEND > 0:
        return None
    for i in inj.by_site.get("mesh", ()):
        c = inj.clauses[i]
        if c.n is not None and inj._fires[i] >= c.n:
            continue  # budget spent: the forged world is gone
        if c.fault == "shrink":
            return ("shrink", _mesh_to(c))
        if c.fault == "swap":
            return ("swap", None)
        if c.fault == "flap":
            return (
                ("shrink", _mesh_to(c)) if inj._fires[i] % 2 == 0
                else ("none", None)
            )
    return None


def mesh_disrupt():
    """Consume one mesh-site fire: the budget-counted draw behind a
    topology disruption (the session raises its
    :class:`InjectedMeshFailure` / migrates on a fired draw). Returns
    the clause's ``(kind, to)`` directive or ``None``. Call only after
    :func:`mesh_view` said the forged world differs from the serving
    mesh — a remeshed session whose mesh already matches the forged
    topology draws nothing, so fire counts equal actual disruptions."""
    inj = _INJECTOR
    if inj is None or _SUSPEND > 0:
        return None
    for i in inj.by_site.get("mesh", ()):
        c = inj.clauses[i]
        with _LOCK:
            fire = inj._draw(i)
        if not fire:
            continue
        to = _mesh_to(c)
        _record_fire(c, **({"to": to} if to is not None else {}))
        if c.fault == "flap":
            # the fire just consumed toggled the forged world; report
            # the view the session must now migrate TO
            return (
                ("shrink", to) if (inj._fires[i] - 1) % 2 == 0
                else ("none", None)
            )
        return (c.fault, to)
    return None


def io_actions(op: str) -> list:
    """Fired ``io``-site actions for one vault operation; ``op`` is
    ``'write'`` or ``'read'``. Returns ``[(fault, frac), ...]`` where
    ``frac`` (bitflip only) positions the flipped byte as a fraction of
    the blob length — drawn from the clause's seeded RNG so a chaos run
    corrupts the same byte every time."""
    inj = _INJECTOR
    if inj is None or _SUSPEND > 0:
        return []
    admissible = _IO_WRITE_FAULTS if op == "write" else _IO_READ_FAULTS
    acts = []
    for i in inj.by_site.get("io", ()):
        c = inj.clauses[i]
        if c.fault not in admissible:
            continue
        with _LOCK:
            fire = inj._draw(i)
            frac = (
                float(inj._rngs[i].random()) if fire and c.fault == "bitflip"
                else 0.0
            )
        if not fire:
            continue
        _record_fire(c, op=op)
        acts.append((c.fault, frac))
    return acts


def check_preempt(where: str) -> None:
    """Raise :class:`Preempted` when a chunk-boundary preemption clause
    fires (called from the host chunk loops: ``checkpointed_cg``,
    ``linalg._try_fused_cg``)."""
    inj = _INJECTOR
    if inj is None or _SUSPEND > 0:
        return
    for i in inj.by_site.get("chunk", ()):
        c = inj.clauses[i]
        with _LOCK:
            fire = inj._draw(i)
        if fire:
            _record_fire(c, where=where)
            raise Preempted(f"injected preemption at {where}")


# env-configured at import so `SPARSE_TPU_FAULTS=... python app.py` needs
# no code changes anywhere
if settings.faults:
    configure(settings.faults)
