"""BDF — variable-order (1-5) implicit multistep method for stiff ODEs
(scipy.integrate.BDF semantics, NDF-modified constants).

Beyond the reference: its integrate.py carries only the explicit RK
family (RK23/RK45/DOP853, integrate.py:750-1050), so stiff systems —
heat-equation semidiscretizations, chemical kinetics — are out of reach
there. TPU design: the Newton iteration's linear solves are dense LU on
the device (``jax.scipy.linalg.lu_factor``; one MXU-tiled factorization
per Jacobian/step-size change, cheap ``lu_solve`` triangular applies per
iteration), and the Jacobian of a sparse-matrix-driven RHS can be handed
in directly as a sparse array (the linear-ODE case y' = A y that this
library's PDE/quantum workloads produce).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .base import SparseArray
from .utils import asjnp

MAX_ORDER = 5
NEWTON_MAXITER = 4
MIN_FACTOR = 0.2
MAX_FACTOR = 10.0


def _norm_rms(x, scale):
    return float(np.linalg.norm(np.asarray(x) / np.asarray(scale))
                 / np.sqrt(x.shape[0]))


def _compute_R(order, factor):
    """Pascal-like matrix relating difference arrays at step ratios
    (Shampine & Reichelt, ode15s)."""
    I = np.arange(1, order + 1)[:, None]
    J = np.arange(1, order + 1)[None, :]
    M = np.zeros((order + 1, order + 1))
    M[1:, 1:] = (I - 1 - factor * J) / I
    M[0] = 1
    return np.cumprod(M, axis=0)


def _change_D(D, order, factor):
    R = _compute_R(order, factor)
    U = _compute_R(order, 1)
    RU = R.dot(U)
    D[: order + 1] = RU.T @ D[: order + 1]


class BDF:
    """Implicit multistep BDF/NDF solver (registered as
    ``solve_ivp(..., method='BDF')``; constructed by integrate.py)."""

    def __init__(self, fun, t0, y0, t_bound, max_step=np.inf, rtol=1e-3,
                 atol=1e-6, jac=None, jac_sparsity=None, vectorized=False,
                 first_step=None, **extraneous):
        from .integrate import (
            OdeSolver, select_initial_step, validate_max_step, validate_tol,
        )

        OdeSolver.__init__(self, fun, t0, y0, t_bound, vectorized,
                           support_complex=True)
        self.max_step = validate_max_step(max_step)
        self.rtol, self.atol = validate_tol(rtol, atol, self.n)
        f = self.fun(self.t, self.y)
        self.nfev += 1
        if first_step is None:
            self.h_abs = select_initial_step(
                self.fun, self.t, self.y, f, self.direction, 1,
                self.rtol, self.atol,
            )
        else:
            self.h_abs = float(first_step)
        self.h_abs_old = None
        self.error_norm_old = None

        # from the VALIDATED rtol: validate_tol may clamp a too-small
        # request, and the Newton tests must see the effective tolerance
        self.newton_tol = max(
            10 * np.finfo(np.float64).eps / self.rtol,
            min(0.03, self.rtol ** 0.5),
        )
        self._jac_arg = jac
        self.jac_factor = None
        self.J = self._validate_jac(self.t, self.y, f)
        self.LU = None
        self.current_jac = True

        kappa = np.array([0, -0.1850, -1 / 9, -0.0823, -0.0415, 0])
        self.gamma = np.hstack((0, np.cumsum(1 / np.arange(1, MAX_ORDER + 1))))
        self.alpha = (1 - kappa) * self.gamma
        self.error_const = kappa * self.gamma + 1 / np.arange(1, MAX_ORDER + 2)

        D = np.empty((MAX_ORDER + 3, self.n),
                     dtype=np.asarray(self.y).dtype)
        D[0] = np.asarray(self.y)
        D[1] = np.asarray(f) * self.h_abs * self.direction
        self.D = D
        self.order = 1
        self.n_equal_steps = 0

    # the OdeSolver surface is inherited dynamically: integrate.py builds
    # a subclass binding this class with OdeSolver as a mixin base.

    # -- jacobian ---------------------------------------------------------
    def _validate_jac(self, t, y, f):
        jac = self._jac_arg
        if jac is None:
            self._jac_callable = None
            return self._num_jac(t, y, f)
        if callable(jac):
            self._jac_callable = jac
            J = jac(t, y)
            self.njev += 1
            return self._as_dense(J)
        self._jac_callable = None
        self._jac_const = self._as_dense(jac)
        return self._jac_const

    @staticmethod
    def _as_dense(J):
        if isinstance(J, SparseArray):
            return np.asarray(J.todense())
        if hasattr(J, "toarray"):
            return np.asarray(J.toarray())
        return np.asarray(J)

    def _num_jac(self, t, y, f):
        """Dense forward-difference Jacobian (n extra RHS evaluations;
        supply ``jac`` for large systems)."""
        y_np = np.asarray(y)
        f_np = np.asarray(f)
        n = self.n
        J = np.empty((n, n), dtype=f_np.dtype)
        eps = np.finfo(
            y_np.real.dtype if np.iscomplexobj(y_np) else y_np.dtype
        ).eps
        h = eps ** 0.5 * np.maximum(np.abs(y_np), 1e-5)
        for i in range(n):
            yp = y_np.copy()
            yp[i] += h[i]
            J[:, i] = (np.asarray(self.fun(t, asjnp(yp))) - f_np) / h[i]
        self.nfev += n
        self.njev += 1
        return J

    def _refresh_jac(self, t, y, f):
        if self._jac_callable is not None:
            self.njev += 1
            return self._as_dense(self._jac_callable(t, y))
        if self._jac_arg is not None:
            return self._jac_const
        return self._num_jac(t, y, f)

    # -- linear algebra ---------------------------------------------------
    def _lu(self, c):
        from jax.scipy.linalg import lu_factor

        self.nlu += 1
        M = jnp.eye(self.n, dtype=jnp.asarray(self.J).dtype) - c * jnp.asarray(
            self.J
        )
        return lu_factor(M)

    def _solve_lu(self, LU, b):
        from jax.scipy.linalg import lu_solve

        return np.asarray(lu_solve(LU, jnp.asarray(b)))

    # -- newton -----------------------------------------------------------
    def _solve_bdf_system(self, t_new, y_predict, c, psi, LU, scale):
        d = np.zeros_like(y_predict)
        y = y_predict.copy()
        dy_norm_old = None
        converged = False
        for k in range(NEWTON_MAXITER):
            f = np.asarray(self.fun(t_new, asjnp(y)))
            self.nfev += 1
            if not np.all(np.isfinite(f)):
                break
            dy = self._solve_lu(LU, c * f - psi - d)
            dy_norm = _norm_rms(dy, scale)
            rate = None if dy_norm_old is None else dy_norm / dy_norm_old
            if rate is not None and (
                rate >= 1
                or rate ** (NEWTON_MAXITER - k) / (1 - rate) * dy_norm
                > self.newton_tol
            ):
                break
            y = y + dy
            d = d + dy
            if dy_norm == 0 or (
                rate is not None
                and rate / (1 - rate) * dy_norm < self.newton_tol
            ):
                converged = True
                break
            dy_norm_old = dy_norm
        return converged, k + 1, y, d

    # -- stepping ---------------------------------------------------------
    def _step_impl(self):
        t = self.t
        D = self.D
        max_step = self.max_step
        min_step = 10 * np.abs(np.nextafter(t, self.direction * np.inf) - t)
        if self.h_abs > max_step:
            h_abs = max_step
            _change_D(D, self.order, max_step / self.h_abs)
            self.n_equal_steps = 0
        elif self.h_abs < min_step:
            h_abs = min_step
            _change_D(D, self.order, min_step / self.h_abs)
            self.n_equal_steps = 0
        else:
            h_abs = self.h_abs

        order = self.order
        alpha = self.alpha
        gamma = self.gamma
        error_const = self.error_const
        atol, rtol = self.atol, self.rtol

        step_accepted = False
        while not step_accepted:
            if h_abs < min_step:
                return False, self.TOO_SMALL_STEP
            h = h_abs * self.direction
            t_new = t + h
            if self.direction * (t_new - self.t_bound) > 0:
                t_new = self.t_bound
                _change_D(D, order, np.abs(t_new - t) / h_abs)
                self.n_equal_steps = 0
                self.LU = None
            h = t_new - t
            h_abs = np.abs(h)

            y_predict = np.sum(D[: order + 1], axis=0)
            scale = atol + rtol * np.abs(y_predict)
            psi = np.dot(D[1: order + 1].T, gamma[1: order + 1]) / alpha[order]

            converged = False
            c = h / alpha[order]
            while not converged:
                if self.LU is None:
                    self.LU = self._lu(c)
                converged, n_iter, y_new, d = self._solve_bdf_system(
                    t_new, y_predict, c, psi, self.LU, scale
                )
                if not converged:
                    if self.current_jac:
                        break
                    self.J = self._refresh_jac(
                        t_new, asjnp(y_predict),
                        asjnp(np.asarray(self.fun(t_new, asjnp(y_predict)))),
                    )
                    self.current_jac = True
                    self.LU = None
            if not converged:
                factor = 0.5
                h_abs *= factor
                _change_D(D, order, factor)
                self.n_equal_steps = 0
                self.LU = None
                continue

            safety = 0.9 * (2 * NEWTON_MAXITER + 1) / (
                2 * NEWTON_MAXITER + n_iter
            )
            scale = atol + rtol * np.abs(y_new)
            error = error_const[order] * d
            error_norm = _norm_rms(error, scale)
            if error_norm > 1:
                factor = max(MIN_FACTOR,
                             safety * error_norm ** (-1 / (order + 1)))
                h_abs *= factor
                _change_D(D, order, factor)
                self.n_equal_steps = 0
                continue
            step_accepted = True

        self.n_equal_steps += 1
        self.t = t_new
        self.y = asjnp(y_new)
        self.h_abs = h_abs
        self.h_abs_old = h_abs
        self.error_norm_old = error_norm
        # the Jacobian is now stale at the advanced (t, y): a Newton
        # failure on the NEXT step must refresh it before conceding a
        # step halving. Constant Jacobians never go stale.
        if self._jac_callable is not None or self._jac_arg is None:
            self.current_jac = False

        # update differences
        D[order + 2] = d - D[order + 1]
        D[order + 1] = d
        for i in reversed(range(order + 1)):
            D[i] += D[i + 1]

        if self.n_equal_steps < order + 1:
            return True, None

        # consider order change once enough equal steps accumulated
        if order > 1:
            error_m = error_const[order - 1] * D[order]
            error_m_norm = _norm_rms(error_m, scale)
        else:
            error_m_norm = np.inf
        if order < MAX_ORDER:
            error_p = error_const[order + 1] * D[order + 2]
            error_p_norm = _norm_rms(error_p, scale)
        else:
            error_p_norm = np.inf
        error_norms = np.array([error_m_norm, error_norm, error_p_norm])
        with np.errstate(divide="ignore"):
            factors = error_norms ** (-1 / np.arange(order, order + 3))
        delta_order = int(np.argmax(factors)) - 1
        order += delta_order
        self.order = order
        factor = min(MAX_FACTOR, safety * np.max(factors))
        self.h_abs *= factor
        _change_D(D, order, factor)
        self.n_equal_steps = 0
        self.LU = None
        self.current_jac = False
        return True, None

    def _dense_output_impl(self):
        from .integrate import DenseOutput

        class BdfDenseOutput(DenseOutput):
            def __init__(s, t_old, t, h, order, D):
                super().__init__(t_old, t)
                s.order = order
                s.t_shift = s.t - h * np.arange(s.order)
                s.denom = h * (1 + np.arange(s.order))
                s.D = D[: order + 1]

            def _call_impl(s, t):
                t = np.asarray(t)
                if t.ndim == 0:
                    x = (t - s.t_shift) / s.denom
                    p = np.cumprod(x)
                else:
                    x = (t[None, :] - s.t_shift[:, None]) / s.denom[:, None]
                    p = np.cumprod(x, axis=0)
                y = np.dot(s.D[1:].T, p)
                if y.ndim == 1:
                    y += s.D[0]
                else:
                    y += s.D[0][:, None]
                return asjnp(y)

        return BdfDenseOutput(
            self.t_old, self.t, self.h_abs * self.direction, self.order,
            self.D.copy(),
        )
