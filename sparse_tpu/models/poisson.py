"""5-point Poisson/Laplacian workload — the PDE benchmark's compute core.

Reference analog: ``examples/pde.py`` builds the 2-D 5-point Laplacian with
``sparse.diags`` and solves it with ``linalg.cg`` (the BASELINE.md "PDE"
row: 6000^2 unknowns/GPU, 300 CG iterations). TPU-first redesign: the matrix
is *generated on device* directly in the padded-row (ELL) layout with pure
jnp ops — a 36M-row operator materializes in HBM in milliseconds with no host
round-trip — and the CG loop is one compiled ``lax.fori_loop``/``while_loop``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnums=(0,), static_argnames=("dtype",))
def laplacian_2d_ell(n: int, dtype=jnp.float32):
    """The n*n-point 2-D 5-point Laplacian as ELL planes ([N, 5] idx/val).

    Stencil per grid point (i, j): 4 on the diagonal, -1 to each in-grid
    neighbor. Out-of-grid slots point at column 0 with value 0.
    """
    N = n * n
    ids = jnp.arange(N, dtype=jnp.int32)
    i = ids // n
    j = ids % n
    # neighbor columns: W, S, center, N, E (sorted by column id)
    cols = jnp.stack([ids - n, ids - 1, ids, ids + 1, ids + n], axis=1)
    valid = jnp.stack(
        [i > 0, j > 0, jnp.ones_like(ids, dtype=bool), j < n - 1, i < n - 1],
        axis=1,
    )
    vals = jnp.where(
        valid,
        jnp.where(jnp.arange(5) == 2, jnp.asarray(4.0, dtype), jnp.asarray(-1.0, dtype)),
        jnp.asarray(0.0, dtype),
    )
    cols = jnp.where(valid, cols, 0).astype(jnp.int32)
    return cols, vals


def laplacian_2d_csr(n: int, dtype=np.float64):
    """Small-scale CSR construction via the library's own diags/kron path."""
    import sparse_tpu as st

    l1 = st.diags([-1.0, 2.0, -1.0], [-1, 0, 1], shape=(n, n), dtype=dtype)
    eye = st.identity(n, dtype=dtype)
    return (st.kron(l1, eye) + st.kron(eye, l1)).tocsr()


def laplacian_2d_csr_host(n: int, dtype=np.float64):
    """Large-scale CSR construction fully on host (pure numpy assembly).

    Million-row layout-construction inputs (shard_csr timing, dryrun) need
    the matrix itself built in O(nnz) host time with no device round-trips;
    this assembles the 5-point stencil rows directly in CSR order.
    """
    import sparse_tpu as st

    N = n * n
    ids = np.arange(N, dtype=np.int64)
    i, j = ids // n, ids % n
    # per-row neighbor columns in sorted order: W(-n), S(-1), C, N(+1), E(+n)
    cols = np.stack([ids - n, ids - 1, ids, ids + 1, ids + n], axis=1)
    valid = np.stack(
        [i > 0, j > 0, np.ones(N, dtype=bool), j < n - 1, i < n - 1], axis=1
    )
    vals = np.where(np.arange(5) == 2, 4.0, -1.0).astype(dtype)
    vals = np.broadcast_to(vals, (N, 5))[valid]
    indices = cols[valid].astype(np.int64)
    indptr = np.zeros(N + 1, dtype=np.int64)
    np.cumsum(valid.sum(axis=1), out=indptr[1:])
    return st.csr_array.from_parts(vals, indices, indptr, (N, N))


from ..ops.spmv import csr_spmv_ell as _spmv_ell


def laplacian_2d_dia(n: int, dtype=jnp.float32):
    """The n*n 2-D 5-point Laplacian as DIA planes ([5, N] data).

    scipy DIA convention: data[k, j] = A[j - o_k, j], so the mask for
    offset o is "row j - o is a grid neighbor of column j". The diagonal
    layout makes SpMV zero-gather (ops.dia_spmv) — the flagship bench
    formulation. Returns (planes, offsets) with offsets a static tuple.
    """
    return _laplacian_2d_dia_planes(n, dtype=dtype), (-n, -1, 0, 1, n)


@partial(jax.jit, static_argnums=(0,), static_argnames=("dtype",))
def _laplacian_2d_dia_planes(n: int, dtype=jnp.float32):
    N = n * n
    j = jnp.arange(N, dtype=jnp.int32)
    col_in_row = j % n
    neg = jnp.asarray(-1.0, dtype)
    zero = jnp.asarray(0.0, dtype)
    planes = jnp.stack(
        [
            jnp.where(j + n < N, neg, zero),  # o=-n: vertical edge (j+n, j)
            jnp.where(col_in_row < n - 1, neg, zero),  # o=-1: edge (j+1, j)
            jnp.full((N,), 4.0, dtype),  # o=0
            jnp.where(col_in_row > 0, neg, zero),  # o=+1: edge (j-1, j)
            jnp.where(j - n >= 0, neg, zero),  # o=+n: edge (j-n, j)
        ]
    )
    return planes


def cg_step_ell(ell_idx, ell_val, x, r, p, rho):
    """One CG iteration on an ELL matrix — the flagship jittable step.

    The AXPBY fusion of the reference (linalg.py:479-496) is implicit: under
    jit XLA fuses every elementwise update into the SpMV epilogue.
    """
    rho_new = jnp.vdot(r, r)
    beta = rho_new / jnp.where(rho == 0, 1, rho)
    p = jnp.where(rho == 0, r, r + beta * p)
    q = _spmv_ell(ell_idx, ell_val, p)
    alpha = rho_new / jnp.vdot(p, q)
    x = x + alpha * p
    r = r - alpha * q
    return x, r, p, rho_new


def poisson_cg_state(n: int, dtype=jnp.float32, seed: int = 0):
    """Build (ell_idx, ell_val, x0, r0, p0, rho0) for an n*n Poisson solve."""
    ell_idx, ell_val = laplacian_2d_ell(n, dtype=dtype)
    N = n * n
    key = jax.random.PRNGKey(seed)
    xtrue = jax.random.normal(key, (N,), dtype=dtype)
    b = _spmv_ell(ell_idx, ell_val, xtrue)
    x0 = jnp.zeros((N,), dtype=dtype)
    r0 = b  # r = b - A @ 0
    p0 = jnp.zeros((N,), dtype=dtype)
    rho0 = jnp.zeros((), dtype=dtype)
    return ell_idx, ell_val, x0, r0, p0, rho0


@partial(jax.jit, static_argnames=("iters",))
def cg_ell(ell_idx, ell_val, x, r, p, rho, iters: int = 300):
    """Fixed-iteration CG (throughput mode, like `pde.py -throughput`)."""

    def body(_, state):
        return cg_step_ell(ell_idx, ell_val, *state)

    return jax.lax.fori_loop(0, iters, body, (x, r, p, rho))


# ---------------------------------------------------------------------------
# DIA (zero-gather) flagship variant — see ops.dia_spmv
# ---------------------------------------------------------------------------
def make_cg_step_dia(offsets: tuple, n: int, use_pallas: bool | None = None):
    """One CG iteration with the diagonal-layout SpMV; offsets are static
    structure, closed over so the returned fn is jittable on arrays alone.

    On TPU the SpMV is the Pallas VMEM-windowed kernel (1.4-1.9x the XLA
    formulation on a v5e: 88 vs 62 CG iters/s at 6000^2, vs the reference's
    75.9 on a V100 — BASELINE.md); elsewhere the XLA zero-gather path.
    XLA hoists the kernel's loop-invariant plane padding out of the CG
    ``fori_loop``, so the padding copy is one-time, not per-iteration.
    """
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        from ..kernels.dia_spmv import dia_spmv_pallas as _spmv_dia
    else:
        from ..ops.dia_spmv import dia_spmv_xla as _spmv_dia

    N = n * n

    def cg_step_dia(planes, x, r, p, rho):
        rho_new = jnp.vdot(r, r)
        beta = rho_new / jnp.where(rho == 0, 1, rho)
        p = jnp.where(rho == 0, r, r + beta * p)
        q = _spmv_dia(planes, offsets, p, (N, N))
        alpha = rho_new / jnp.vdot(p, q)
        return x + alpha * p, r - alpha * q, p, rho_new

    return cg_step_dia


def poisson_cg_state_dia(n: int, dtype=jnp.float32, seed: int = 0):
    """(planes, x0, r0, p0, rho0) + the step fn for an n*n Poisson solve."""
    from ..ops.dia_spmv import dia_spmv_xla

    planes, offsets = laplacian_2d_dia(n, dtype=dtype)
    N = n * n
    key = jax.random.PRNGKey(seed)
    xtrue = jax.random.normal(key, (N,), dtype=dtype)
    b = dia_spmv_xla(planes, offsets, xtrue, (N, N))
    x0 = jnp.zeros((N,), dtype=dtype)
    state = (planes, x0, b, jnp.zeros((N,), dtype=dtype), jnp.zeros((), dtype=dtype))
    return state, make_cg_step_dia(offsets, n)


_cg_dia_compiled = {}


def cg_dia(step_fn, planes, x, r, p, rho, iters: int = 300):
    """Fixed-iteration DIA-CG, one compiled loop.

    The jitted runner is cached per step_fn so repeated calls (benchmark
    timing loops) hit the compilation cache instead of retracing."""
    run = _cg_dia_compiled.get(step_fn)
    if run is None:

        @partial(jax.jit, static_argnames=("iters",))
        def run(planes, x, r, p, rho, iters):
            def body(_, state):
                return step_fn(planes, *state)

            return jax.lax.fori_loop(0, iters, body, (x, r, p, rho))

        _cg_dia_compiled[step_fn] = run
    return run(planes, x, r, p, rho, iters=iters)
