"""Benchmark/application model builders (the examples' compute cores).

Reference analog: the workload-construction halves of ``examples/pde.py``,
``examples/gmg.py``, ``examples/amg.py`` — kept importable here so the driver
entrypoint (``__graft_entry__.py``), ``bench.py``, and the example scripts all
share one implementation.
"""

from .poisson import (  # noqa: F401
    cg_dia,
    cg_ell,
    cg_step_ell,
    laplacian_2d_csr,
    laplacian_2d_dia,
    laplacian_2d_ell,
    make_cg_step_dia,
    poisson_cg_state,
    poisson_cg_state_dia,
)
