"""Structured-grid geometric multigrid, entirely in 2-D grid space.

Reference analog: ``examples/gmg.py`` (the BASELINE.md "GMG" row — V-cycle
weighted-Jacobi preconditioned CG, Galerkin coarse operators A_c = R A P
computed with general SpGEMM tasks, gmg.py:289-381).

TPU-first redesign: on a structured grid every operator in the hierarchy is
a <=9-point stencil, so nothing needs a general sparse format at all —

* each level operator is a dict ``{(di, dj): [n, n] coefficient plane}``;
  applying it is pad + 9 shifted multiply-adds, pure VPU work that XLA
  fuses into one pass (no gather, no CSR indices, no Pallas pad/trim);
* the Galerkin product R A P is computed EXACTLY by probing the composed
  operator with period-3 comb vectors — 9 grid applies per level instead
  of two SpGEMMs + sorts (the r3-measured init was 52 s at n=4000, almost
  all COO sorts and eager power iteration);
* restriction/prolongation are separable strided stencils; prolongation
  uses interleave-reshape (stack + reshape) rather than scatter-add —
  TPU has no fast scatter;
* the weighted-Jacobi omega power iteration is one jitted ``fori_loop``.

The whole V-cycle is traceable, so ``linalg.cg(A, b, M=vcycle)`` inlines
hierarchy application into the compiled while_loop — one XLA program per
solve, one host sync per convergence test, zero host round-trips per
iteration.

Exactness: ``galerkin_stencil`` equals the explicit R @ A @ P product and
``prolong_grid``/``restrict_grid`` equal the explicit P/R SpMVs
(oracle-tested against scipy in tests/test_gmg_grid.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "poisson_stencil",
    "stencil_apply",
    "restrict_grid",
    "prolong_grid",
    "galerkin_stencil",
    "build_hierarchy",
    "make_vcycle",
    "shard_hierarchy_grid",
]


def poisson_stencil(n: int, dtype=jnp.float32) -> dict:
    """5-point Poisson stencil on an n x n grid, as SCALAR coefficients.

    Matches examples/gmg.py:poisson2D (4 on the diagonal, -1 to the four
    neighbors; couplings across the grid edge vanish via zero-padding at
    apply time). Scalars, not [n, n] planes: the coefficients are
    uniform, and the fine level dominates the V-cycle's HBM traffic — a
    plane-form apply reads 5 extra N-sized arrays per application.
    ``stencil_apply`` broadcasts either form.
    """
    del n  # the stencil is resolution-independent; kept for the API shape
    return {
        (0, 0): jnp.asarray(4.0, dtype),
        (-1, 0): jnp.asarray(-1.0, dtype),
        (1, 0): jnp.asarray(-1.0, dtype),
        (0, -1): jnp.asarray(-1.0, dtype),
        (0, 1): jnp.asarray(-1.0, dtype),
    }


@jax.jit
def stencil_apply(planes: dict, X):
    """y = A @ x with A in stencil form: (A x)[i,j] = sum_d C_d[i,j] *
    x[i+di, j+dj], x zero-padded at the boundary.

    Jitted (as are all public entry points here): the module's op mix
    triggers an XLA CPU *eager-mode* heap corruption on jax 0.9.0 at odd
    grid sizes; compiled execution is correct, and under an outer trace
    (the CG while_loop) the inner jit simply inlines."""
    n = X.shape[0]
    Xp = jnp.pad(X, 1)
    out = None
    for (di, dj), C in planes.items():
        term = C * jax.lax.slice(Xp, (1 + di, 1 + dj), (1 + di + n, 1 + dj + n))
        out = term if out is None else out + term
    return out


@partial(jax.jit, static_argnums=(1, 2))
def restrict_grid(X, cn: int, gridop: str):
    """R @ r on the grid: full-weighting [1,2,1]/4 per axis at stride 2
    (or even-point injection). Equal to the explicit restriction matrix
    of examples/gmg.py:linear_operator / injection_operator."""
    if gridop == "injection":
        return X[0 : 2 * cn : 2, 0 : 2 * cn : 2]

    def r1(Y):
        return (
            Y[0 : 2 * cn : 2, :]
            + 2.0 * Y[1 : 2 * cn + 1 : 2, :]
            + Y[2 : 2 * cn + 2 : 2, :]
        ) * jnp.asarray(0.25, Y.dtype)

    Xp = jnp.pad(X, 1)
    return r1(r1(Xp).T).T


def _p1_interleave(Y, fn: int, cn: int):
    """1-D transposed full-weighting along axis 0, scatter-free.

    Fine row 2c gets 0.5*Y[c]; fine row 2c+1 gets 0.25*(Y[c] + Y[c+1])
    (Y[cn] treated as 0) — assembled by interleaving the even/odd row
    planes with stack+reshape instead of at[...].add scatters.
    """
    half = jnp.asarray(0.5, Y.dtype)
    quarter = jnp.asarray(0.25, Y.dtype)
    evens = half * Y
    odds = quarter * (Y + jnp.pad(Y[1:, :], ((0, 1), (0, 0))))
    inter = jnp.stack([evens, odds], axis=1).reshape(2 * cn, Y.shape[1])
    return jnp.pad(inter, ((0, fn - 2 * cn), (0, 0)))


@partial(jax.jit, static_argnums=(1, 2, 3))
def prolong_grid(Z, fn: int, cn: int, gridop: str):
    """P @ xc = R.T @ xc on the grid (transposed separable stencil)."""
    if gridop == "injection":
        out = jnp.zeros((fn, fn), dtype=Z.dtype)
        return out.at[0 : 2 * cn : 2, 0 : 2 * cn : 2].set(Z)
    return _p1_interleave(_p1_interleave(Z, fn, cn).T, fn, cn).T


@partial(jax.jit, static_argnums=(1, 2, 3))
def galerkin_stencil(planes: dict, fn: int, cn: int, gridop: str) -> dict:
    """Coarse Galerkin stencil A_c = R A P by comb probing.

    A_c has reach <= 1 in coarse units for both grid operators, so probing
    the composed map T = R \\circ A \\circ P with the 9 period-3 comb
    vectors separates every coefficient exactly:
        A_c[d][i, j] = (T comb_{a,b})[i, j]  where (a, b) = (i+di, j+dj) mod 3.
    Equal to the explicit R @ A @ P SpGEMM product (oracle-tested); costs
    9 grid applies instead of two unstructured SpGEMMs + sorts.
    """
    ii, jj = np.meshgrid(np.arange(cn), np.arange(cn), indexing="ij")
    dtype = next(iter(planes.values())).dtype

    def T(comb):
        return restrict_grid(
            stencil_apply(planes, prolong_grid(comb, fn, cn, gridop)), cn, gridop
        )

    probes = {}
    for a in range(3):
        for b in range(3):
            comb = ((ii % 3 == a) & (jj % 3 == b)).astype(dtype)
            probes[(a, b)] = T(jnp.asarray(comb))

    out = {}
    for di in (-1, 0, 1):
        for dj in (-1, 0, 1):
            # plane[i,j] = probes[(i+di)%3, (j+dj)%3][i,j]
            sel = jnp.stack(
                [probes[(a, b)] for a in range(3) for b in range(3)]
            ).reshape(3, 3, cn, cn)
            plane = sel[(ii + di) % 3, (jj + dj) % 3, ii, jj]
            if gridop == "injection" and (di, dj) != (0, 0):
                # injection Galerkin on a <=1-reach fine stencil couples
                # only even fine points two apart — identically zero
                # off-diagonal; drop the planes rather than carry zeros
                continue
            out[(di, dj)] = plane
    return out


@partial(jax.jit, static_argnames=("offsets", "iters"))
def _power_rho(planes_tuple, offsets, D_inv, x0, iters: int):
    """rho(D^-1 A) by power iteration + Rayleigh quotient, one compiled
    fori_loop (the r3 host-loop form was ~38 s at n=2000 on CPU)."""
    planes = dict(zip(offsets, planes_tuple))

    def mv(v):
        return D_inv * stencil_apply(planes, v)

    def body(_, v):
        w = mv(v)
        return w / jnp.linalg.norm(w)

    v = jax.lax.fori_loop(0, iters, body, x0)
    return jnp.vdot(v, mv(v))


def _rho(planes: dict, D_inv, n: int, seed=0, iters=15):
    rng = np.random.default_rng(seed)
    x0 = jnp.asarray(rng.random((n, n)), dtype=jnp.asarray(D_inv).dtype)
    offsets = tuple(planes.keys())
    return float(
        _power_rho(tuple(planes.values()), offsets, D_inv, x0, iters)
    )


def build_hierarchy(
    n: int, levels: int, gridop: str = "linear", omega: float = 4.0 / 3.0,
    dtype=jnp.float32, planes: dict | None = None,
):
    """[(stencil planes, omega*D^-1 plane, grid size)] per level.

    The smoother weight follows the pyamg formula omega / rho(D^-1 A)
    (examples/gmg.py:WeightedJacobi), with rho from the jitted power
    iteration. ``planes`` overrides the level-0 operator (default:
    5-point Poisson).
    """
    st = poisson_stencil(n, dtype) if planes is None else planes
    out = []
    for lvl in range(levels):
        D_inv = 1.0 / st[(0, 0)]
        w = jnp.asarray(omega / _rho(st, D_inv, n), dtype) * D_inv
        out.append((st, w, n))
        if lvl < levels - 1:
            cn = n // 2
            st = galerkin_stencil(st, n, cn, gridop)
            n = cn
    return out


def shard_hierarchy_grid(hierarchy, mesh, axis: str = "shards",
                         replicate_below: int = 1024):
    """Lay a grid hierarchy out over a device mesh, GSPMD style.

    The TPU-first distributed form of this multigrid is NOT hand-written
    collectives: every level's [n, n] planes (and the solve vectors) get
    a row sharding ``P(axis, None)``, and XLA/GSPMD inserts the stencil
    halo exchanges (collective-permutes for the pad/slice patterns) and
    transfer-operator communication itself — the scaling-book recipe
    (annotate shardings, let the compiler place collectives). Levels
    with fewer than ``replicate_below`` total grid points (``n * n``,
    the flat vector length — so the default 1024 still shards a 64x64
    level) are fully REPLICATED:
    the same zero-collective coarse tail that fixes the reference's
    weak-scaling collapse (SURVEY §6, parallel/multigrid.py), expressed
    as a sharding annotation instead of a gather/scatter pair.

    Returns ``(hierarchy, vec_sharding)``: a new hierarchy with
    identically-shaped, device-committed arrays, plus the sharding to
    apply to flat [n0*n0] solve vectors (row-block layout matching level
    0 — replicated when level 0 itself could not shard). Use with
    :func:`make_vcycle` / ``linalg.cg`` unchanged — computation follows
    data placement.

    A level row-shards only when its n divides the mesh size (GSPMD
    device_put rejects ragged dimension splits); everything else is
    replicated, which is also the intended coarse-tail layout.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    S = int(mesh.devices.size)
    row_sharded = NamedSharding(mesh, P(axis, None))
    replicated = NamedSharding(mesh, P())

    out = []
    vec_sharding = NamedSharding(mesh, P())
    for lvl, (st, w, n) in enumerate(hierarchy):
        shardable = n % S == 0 and n * n >= replicate_below
        sh = row_sharded if shardable else replicated
        if lvl == 0 and shardable:
            vec_sharding = NamedSharding(mesh, P(axis))
        st_s = {
            d: jax.device_put(p, sh if getattr(p, "ndim", 0) == 2 else replicated)
            for d, p in st.items()
        }
        w_s = jax.device_put(w, sh if getattr(w, "ndim", 0) == 2 else replicated)
        out.append((st_s, w_s, n))
    return out, vec_sharding


def make_vcycle(hierarchy, gridop: str = "linear"):
    """One V-cycle as a traceable [N] -> [N] map (flat vectors, the
    LinearOperator/M contract of ``linalg.cg``): pre-smooth, restrict the
    residual, recurse, prolong-correct, post-smooth; the coarsest level
    applies the smoother once (examples/gmg.py:GMG._cycle)."""

    def cycle_2d(r, lvl):
        st, w, n = hierarchy[lvl]
        if lvl == len(hierarchy) - 1:
            return w * r
        x = w * r
        fine_r = r - stencil_apply(st, x)
        cn = hierarchy[lvl + 1][2]
        coarse_x = cycle_2d(restrict_grid(fine_r, cn, gridop), lvl + 1)
        x = x + prolong_grid(coarse_x, n, cn, gridop)
        return x + w * (r - stencil_apply(st, x))

    n0 = hierarchy[0][2]

    def cycle(r_flat):
        return cycle_2d(r_flat.reshape(n0, n0), 0).reshape(-1)

    return cycle
