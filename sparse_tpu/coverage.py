"""API coverage + provenance layer.

Reference analog: ``sparse/coverage.py`` (clone_module at coverage.py:59,
clone_scipy_arr_kind at coverage.py:89) — the machinery that clones
``scipy.sparse``'s module/class surface and wraps every public entry point
with provenance tracking so task launches are attributed to user code.

TPU-native redesign: there is no task stream to attribute, but XLA profiles
have the same problem — HLO op names say nothing about which library call
produced them. ``track_provenance`` wraps public ops in ``jax.named_scope``
so traced computations carry ``sparse_tpu.<op>`` scopes into the profiler
(the ``track_provenance`` analog, coverage.py:50-57). ``coverage_report``
is the measurable drop-in check: it walks ``scipy.sparse``'s public surface
and reports what this package implements vs what is missing.
"""

from __future__ import annotations

import functools
import inspect

import jax

from .config import settings


def track_provenance(fn):
    """Wrap a public op so its trace carries a ``sparse_tpu.<name>`` scope.

    Profiles (``jax.profiler``) then attribute fused HLO back to the
    user-level library call — the named_scope mapping of SURVEY §5.

    The provenance scopes double as telemetry event sources: with
    ``settings.telemetry`` on, every public entry is counted under its
    scope name (``telemetry.summary()["counts"]``), so a session log says
    which library calls a workload actually exercised — the task-launch
    attribution the reference gets from Legion provenance, without it.
    """
    scope = f"sparse_tpu.{fn.__qualname__}"

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if settings.telemetry:
            from . import telemetry

            telemetry.count(scope)
        with jax.named_scope(scope):
            return fn(*args, **kwargs)

    return wrapper


# scipy.sparse names that are deliberately out of scope (deprecated in scipy,
# or matrix-creation aliases scipy itself discourages).
_EXCLUDED = {
    "matrix_power",  # scipy: dense-ish utility
    "spmatrix",
    "sparsetools",
    "test",
}


def _scipy_surface():
    """Public callables/classes of scipy.sparse (module level)."""
    import scipy.sparse as sp

    out = {}
    for name in dir(sp):
        if name.startswith("_") or name in _EXCLUDED:
            continue
        obj = getattr(sp, name)
        if inspect.ismodule(obj):
            continue
        if callable(obj) or inspect.isclass(obj):
            out[name] = obj
    return out


def _class_surface(cls):
    return {
        n
        for n in dir(cls)
        if not n.startswith("_") and callable(getattr(cls, n, None))
    }


def coverage_report(verbose: bool = False):
    """Compare this package's surface against scipy.sparse.

    Returns ``{"implemented": [...], "missing": [...], "classes": {...}}``;
    with ``verbose`` prints a table. The drop-in parity check the reference
    gets from clone_module (coverage.py:226-276) — here a measurement
    instead of a blind clone, so the gap is always visible.
    """
    import sparse_tpu

    surface = _scipy_surface()
    implemented, missing = [], []
    for name in sorted(surface):
        if hasattr(sparse_tpu, name):
            implemented.append(name)
        else:
            missing.append(name)

    classes = {}
    import scipy.sparse as sp

    for sc_name, our_name in [
        ("csr_array", "csr_array"),
        ("csc_array", "csc_array"),
        ("coo_array", "coo_array"),
        ("dia_array", "dia_array"),
    ]:
        sc_cls = getattr(sp, sc_name)
        our_cls = getattr(sparse_tpu, our_name)
        sc_methods = _class_surface(sc_cls)
        our_methods = _class_surface(our_cls)
        classes[sc_name] = {
            "implemented": sorted(sc_methods & our_methods),
            "missing": sorted(sc_methods - our_methods),
        }

    report = {
        "implemented": implemented,
        "missing": missing,
        "classes": classes,
    }
    if verbose:
        n_tot = len(implemented) + len(missing)
        print(
            f"scipy.sparse module surface: {len(implemented)}/{n_tot} "
            "implemented"
        )
        print("missing:", ", ".join(missing) or "(none)")
        for cname, c in classes.items():
            n_tot = len(c["implemented"]) + len(c["missing"])
            print(f"{cname}: {len(c['implemented'])}/{n_tot} methods")
            if c["missing"]:
                print("  missing:", ", ".join(c["missing"]))
    return report
