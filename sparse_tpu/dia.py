"""DIA (diagonal) sparse array.

Reference analog: ``sparse/dia.py`` (class at dia.py:65; vectorized DIA->CSC
conversion dia.py:222-249; transpose dia.py:178). Layout matches scipy:
``data[k, j]`` holds ``A[j - offsets[k], j]`` (column-indexed diagonals).

TPU note: DIA -> other formats is a fully dense-shaped masked gather (one
[n_diags, L] plane) followed by one compaction — no per-diagonal loop.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .base import SparseArray
from .utils import asjnp, host_int


@jax.tree_util.register_pytree_node_class
class dia_array(SparseArray):
    format = "dia"

    def __init__(self, arg, shape=None, dtype=None, copy=False):
        if isinstance(arg, dia_array):
            data, offsets, shape = arg.data, arg.offsets, arg.shape
        elif isinstance(arg, tuple) and len(arg) == 2 and not np.isscalar(arg[0]):
            data, offsets = arg
            data = asjnp(data)
            offsets = np.atleast_1d(np.asarray(offsets, dtype=np.int64))
            if shape is None:
                raise ValueError("dia_array((data, offsets)) requires shape=")
        elif isinstance(arg, SparseArray) or hasattr(arg, "tocoo"):
            c = arg.tocoo()
            data, offsets, shape = _coo_to_dia(c)
        else:
            d = asjnp(arg)
            from .coo import coo_array

            c = coo_array(d)
            data, offsets, shape = _coo_to_dia(c)
        if dtype is not None:
            data = data.astype(dtype)
        self.data = asjnp(data)
        # offsets stay on host: they define static structure (like shapes)
        self.offsets = np.asarray(offsets, dtype=np.int64)
        self._shape = (int(shape[0]), int(shape[1]))
        self._dtype = np.dtype(self.data.dtype)

    # -- pytree protocol ---------------------------------------------------
    def tree_flatten(self):
        return (self.data,), (tuple(self.offsets.tolist()), self._shape)

    @classmethod
    def tree_unflatten(cls, aux, children):
        offsets, shape = aux
        obj = object.__new__(cls)
        obj.data = children[0]
        obj.offsets = np.asarray(offsets, dtype=np.int64)
        obj._shape = shape
        obj._dtype = np.dtype(obj.data.dtype)
        return obj

    # ----------------------------------------------------------------------
    @property
    def nnz(self) -> int:
        """Count of stored entries that fall inside the matrix bounds."""
        m, n = self.shape
        L = self.data.shape[1]
        total = 0
        for off in self.offsets:
            lo = max(0, off)
            hi = min(n, m + off, L)
            total += max(0, int(hi - lo))
        return total

    def _data_array(self):
        return self.data

    def _with_data(self, data):
        return dia_array((data, self.offsets), shape=self.shape)

    # -- conversions -------------------------------------------------------
    def tocoo(self):
        from .coo import coo_array

        m, n = self.shape
        nd, L = self.data.shape
        cols = jnp.arange(L, dtype=jnp.int32)[None, :].repeat(nd, axis=0)
        rows = cols - jnp.asarray(self.offsets, dtype=jnp.int32)[:, None]
        valid = (rows >= 0) & (rows < m) & (cols < n) & (self.data != 0)
        cnt = host_int(valid.sum())
        take = jnp.nonzero(valid.ravel(), size=cnt)[0]
        out = coo_array(
            (
                self.data.ravel()[take],
                (rows.ravel()[take], cols.ravel()[take]),
            ),
            shape=self.shape,
        )
        # one slot per (diagonal, column): duplicate-free by construction
        # (diagonal-major order though — not scipy-canonical)
        out._duplicate_free = True
        return out

    def _direct_parts(self, by_row: bool):
        """Sort-FREE host conversion to CSR (by_row) or CSC parts.

        DIA is already ordered: within a row, entries at ascending
        offsets have ascending columns (col = row + offset); within a
        column, entries at DESCENDING offsets have ascending rows
        (row = col - offset). So both compressed forms fall out of a
        masked transpose — no 20M-entry sort (the COO route cost 35 s
        at 2000^2 on the CPU backend; this is milliseconds). Matches
        the reference's vectorized conversion (dia.py:222-249) in
        spirit, minus its sort. Returns (indptr, indices, data) numpy.
        """
        from .types import index_dtype_for

        m, n = self.shape
        data = np.asarray(self.data)
        offsets = np.asarray(self.offsets)
        nd, L = data.shape
        if by_row:
            order = np.argsort(offsets, kind="stable")
            d = offsets[order][:, None]                  # [D, 1]
            i = np.arange(m)[None, :]                    # [1, m]
            pos = i + d                                  # columns; also the
            lines = m                                    # data column index
        else:
            order = np.argsort(-offsets, kind="stable")
            d = offsets[order][:, None]
            j = np.arange(n)[None, :]
            pos = j - d                                  # rows
            lines = n
        # value source: data[k, column]; column is pos (by_row) or j (csc)
        src = pos if by_row else np.broadcast_to(
            np.arange(n)[None, :], pos.shape
        )
        valid = (pos >= 0) & (pos < (n if by_row else m)) & (src < L)
        gathered = np.take_along_axis(
            data[order], np.clip(src, 0, max(L - 1, 0)), axis=1
        )
        valid &= gathered != 0
        validT = valid.T                                 # [lines, D]
        indices = pos.T[validT]
        vals = gathered.T[validT]
        idt = index_dtype_for(self.shape, len(vals))
        counts = valid.sum(axis=0)  # one count per line (row/column)
        indptr = np.zeros(lines + 1, dtype=idt)
        indptr[1:] = np.cumsum(counts).astype(idt)
        return indptr, indices.astype(idt), vals

    def tocsr(self):
        from .utils import in_trace

        if in_trace():
            return self.tocoo().tocsr()
        from .csr import csr_array

        indptr, indices, vals = self._direct_parts(by_row=True)
        return csr_array.from_parts(vals, indices, indptr, self.shape)

    def tocsc(self):
        """Reference fast path dia.py:222-249 — here fully sort-free."""
        from .utils import in_trace

        if in_trace():
            return self.tocoo().tocsc()
        from .csc import csc_array

        indptr, indices, vals = self._direct_parts(by_row=False)
        return csc_array.from_parts(vals, indices, indptr, self.shape)

    def todia(self):
        return self

    def toarray(self):
        return self.tocoo().toarray()

    def transpose(self, axes=None):
        """offsets -> -offsets with a per-diagonal shift (dia.py:178)."""
        if axes is not None:
            raise ValueError("transpose with axes != None is unsupported")
        m, n = self.shape
        L = self.data.shape[1]
        Lt = max(m, L)
        nd = self.data.shape[0]
        # dataT[k, j] = data[k, j + offsets[k]] on the transposed shape (n, m)
        j = jnp.arange(Lt, dtype=jnp.int32)[None, :]
        src = j + jnp.asarray(self.offsets, dtype=jnp.int32)[:, None]
        ok = (src >= 0) & (src < L)
        src_c = jnp.clip(src, 0, L - 1)
        gathered = self.data[jnp.arange(nd)[:, None], src_c]
        dataT = jnp.where(ok, gathered, jnp.zeros((), dtype=self.data.dtype))
        return dia_array((dataT, -self.offsets), shape=(n, m))

    @property
    def T(self):
        return self.transpose()

    # -- arithmetic --------------------------------------------------------
    def dot(self, other):
        """SpMV stays in DIA: the diagonal layout needs no gathers at all
        (ops.dia_spmv — shifted vector adds). Everything else routes
        through CSR."""
        x = other
        if not isinstance(x, SparseArray):
            x = asjnp(x)
            # fast path requires scipy-width data planes (data.shape[1] == n);
            # transpose of a non-square matrix can leave wider planes
            if (
                x.ndim == 1
                and x.shape[0] == self.shape[1]
                and self.data.shape[1] == self.shape[1]
            ):
                from .config import settings

                offs = tuple(int(o) for o in self.offsets)
                if settings.spmv_mode == "pallas":
                    from .kernels.dia_spmv import cached_prepared_spmv

                    y = cached_prepared_spmv(
                        self, "_prepared", self.data, offs, self.shape, x
                    )
                    if y is not None:  # None: band too wide for VMEM
                        return y
                from .ops.dia_spmv import dia_spmv_xla

                return dia_spmv_xla(self.data, offs, x, self.shape)
        return self.tocsr().dot(other)

    def _rdot(self, other):
        return self.tocsr()._rdot(other)

    def __add__(self, other):
        return self.tocsr() + other

    def __mul__(self, other):
        if np.isscalar(other) or getattr(other, "ndim", 1) == 0:
            return self._with_data(self.data * other)
        return self.tocsr().multiply(other)

    def multiply(self, other):
        return self.__mul__(other)

    def sum(self, axis=None):
        return self.tocsr().sum(axis=axis)

    def diagonal(self, k=0):
        m, n = self.shape
        out_len = min(m + min(k, 0), n - max(k, 0))
        if out_len <= 0:
            return jnp.zeros((0,), dtype=self.dtype)
        hits = np.nonzero(self.offsets == k)[0]
        if hits.size == 0:
            return jnp.zeros((out_len,), dtype=self.dtype)
        row = self.data[int(hits[0])]
        lo = max(0, k)
        seg = row[lo : lo + out_len]
        if seg.shape[0] < out_len:
            seg = jnp.pad(seg, (0, out_len - seg.shape[0]))
        return seg

    def __str__(self):
        return (
            f"<{self.shape[0]}x{self.shape[1]} DIA array,"
            f" ndiags={self.data.shape[0]}, dtype={self.dtype}>"
        )

    __repr__ = __str__


def _coo_to_dia(c):
    """COO -> (data, offsets, shape). Host-syncs the distinct-offset set."""
    m, n = c.shape
    # offsets lie in [-m, n]: int32-exact for any dims that fit int32
    # (an int64 request under no-x64 warns and truncates anyway)
    odt = jnp.int64 if max(m, n) > 2**31 - 1 else jnp.int32
    offs_dev = c.col.astype(odt) - c.row.astype(odt)
    offsets = np.unique(np.asarray(offs_dev))
    L = n
    nd = int(offsets.shape[0])
    data = jnp.zeros((max(nd, 1), L), dtype=c.data.dtype)
    if c.nnz:
        k = jnp.searchsorted(jnp.asarray(offsets), offs_dev)
        data = data.at[k, c.col].add(c.data)
    if nd == 0:
        offsets = np.zeros((1,), dtype=np.int64)
    return data, offsets, (m, n)
