"""COO sparse array.

Reference analog: ``sparse/coo.py`` (class at coo.py:72; distributed sort-based
tocsr/tocsc at coo.py:233-349 using SORT_BY_KEY + NCCL/CPU communicators). On TPU
the conversion is one fused device sort (``ops.coords.sort_coo``); the sharded
samplesort over a mesh lives in ``sparse_tpu.parallel.sort``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .base import SparseArray, _resolve_shape
from .ops import conv
from .types import index_dtype_for
from .utils import asjnp, common_dtype


@jax.tree_util.register_pytree_node_class
class coo_array(SparseArray):
    format = "coo"

    def __init__(self, arg, shape=None, dtype=None, copy=False):
        if isinstance(arg, coo_array):
            row, col, data, shape = arg.row, arg.col, arg.data, arg.shape
        elif isinstance(arg, SparseArray):
            c = arg.tocoo()
            row, col, data, shape = c.row, c.col, c.data, c.shape
        elif isinstance(arg, tuple) and len(arg) == 2 and isinstance(arg[1], tuple):
            data, (row, col) = arg
            data, row, col = asjnp(data), asjnp(row), asjnp(col)
            shape = _resolve_shape(shape, row, col)
        elif isinstance(arg, tuple) and len(arg) == 2 and all(
            isinstance(s, (int, np.integer)) for s in arg
        ):
            shape = (int(arg[0]), int(arg[1]))
            row = col = jnp.zeros((0,), dtype=np.int32)
            data = jnp.zeros((0,), dtype=dtype or np.float32)
        elif hasattr(arg, "tocoo"):  # scipy sparse
            c = arg.tocoo()
            row, col, data = asjnp(c.row), asjnp(c.col), asjnp(c.data)
            shape = c.shape
        else:  # dense
            d = asjnp(arg)
            if d.ndim != 2:
                raise ValueError("COO arrays must be 2-D")
            indptr, cols, vals, _ = conv.dense_to_csr(d)
            from .ops.coords import expand_rows

            row = expand_rows(indptr, vals.shape[0])
            col, data, shape = cols, vals, d.shape
        if dtype is not None:
            data = data.astype(dtype)
        idt = index_dtype_for(shape, data.shape[0])
        self.row = asjnp(row, idt)
        self.col = asjnp(col, idt)
        self.data = asjnp(data)
        self._shape = (int(shape[0]), int(shape[1]))
        self._dtype = np.dtype(self.data.dtype)

    # -- pytree protocol ---------------------------------------------------
    def tree_flatten(self):
        return (self.data, self.row, self.col), self._shape

    @classmethod
    def tree_unflatten(cls, shape, children):
        data, row, col = children
        obj = object.__new__(cls)
        obj.data, obj.row, obj.col = data, row, col
        obj._shape = shape
        obj._dtype = np.dtype(data.dtype)
        return obj

    # ----------------------------------------------------------------------
    @property
    def nnz(self) -> int:
        return int(self.data.shape[0])

    def _data_array(self):
        return self.data

    def _with_data(self, data):
        return coo_array((data, (self.row, self.col)), shape=self.shape)

    def tocoo(self):
        return self

    # raw COO may hold unsorted/duplicate triples until converted
    has_sorted_indices = False
    has_canonical_format = False

    def sum_duplicates(self):
        """Canonicalize IN PLACE: lex-sort triples, sum duplicate (row, col)
        pairs (scipy coo.sum_duplicates)."""
        from .ops.coords import dedup_sorted, sort_coo

        srows, scols, svals = sort_coo(
            self.row, self.col, self.data, self.shape, by="row"
        )
        urows, ucols, uvals, _ = dedup_sorted(srows, scols, svals)
        self.row, self.col, self.data = urows, ucols, uvals
        self.has_sorted_indices = True
        self.has_canonical_format = True

    def tocsr(self):
        from .csr import csr_array

        indptr, indices, data = conv.coo_to_csr(
            self.row, self.col, self.data, self.shape
        )
        return csr_array.from_parts(data, indices, indptr, self.shape)

    def tocsc(self):
        from .csc import csc_array

        indptr, indices, data = conv.coo_to_csc(
            self.row, self.col, self.data, self.shape
        )
        return csc_array.from_parts(data, indices, indptr, self.shape)

    def todia(self):
        return self.tocsc().todia()

    def toarray(self):
        return conv.coo_to_dense(self.row, self.col, self.data, self.shape)

    def transpose(self, axes=None):
        if axes is not None:
            raise ValueError("transpose with axes != None is unsupported")
        return coo_array(
            (self.data, (self.col, self.row)),
            shape=(self.shape[1], self.shape[0]),
        )

    @property
    def T(self):
        return self.transpose()

    def dot(self, other):
        return self.tocsr().dot(other)

    def tensordot(self, other, axes=2):
        """np.tensordot semantics restricted to 2-D operands.

        scipy.sparse's n-D coo_array grew ``tensordot``; this package is
        2-D-only (like the reference), so the supported contractions are
        the 2-D ones: one shared axis (a transposed matmul) or both axes
        (a full contraction to a scalar).
        """
        ndim_b = getattr(other, "ndim", np.ndim(other))
        if isinstance(axes, (int, np.integer)):
            k = int(axes)
            a_axes = tuple(range(self.ndim - k, self.ndim))
            b_axes = tuple(range(k))
        else:
            a_axes, b_axes = axes
            if isinstance(a_axes, (int, np.integer)):
                a_axes = (int(a_axes),)
            if isinstance(b_axes, (int, np.integer)):
                b_axes = (int(b_axes),)
            for ax, nd, side in (
                *((ax, self.ndim, "a") for ax in a_axes),
                *((ax, ndim_b, "b") for ax in b_axes),
            ):
                if not -nd <= int(ax) < nd:
                    raise ValueError(
                        f"axes value {ax} out of range for {side} "
                        f"(ndim {nd})"
                    )
            a_axes = tuple(int(ax) % self.ndim for ax in a_axes)
            b_axes = tuple(int(ax) % ndim_b for ax in b_axes)
        if len(a_axes) != len(b_axes):
            raise ValueError("axes lists must have the same length")
        if len(a_axes) == 1:
            a = self if a_axes[0] == self.ndim - 1 else self.transpose()
            b = other
            if ndim_b == 2 and b_axes[0] == 1:
                b = other.transpose() if isinstance(other, SparseArray) else np.asarray(other).T
            return a.dot(b)
        if len(a_axes) == 2 and ndim_b == 2:
            # full contraction: sum_ij A[i,j] * B'[i,j]
            b = other
            if a_axes[0] != b_axes[0]:  # pairing crosses: align via transpose
                b = other.transpose() if isinstance(other, SparseArray) else np.asarray(other).T
            if isinstance(b, SparseArray):
                b = b.toarray()
            b = np.asarray(b)
            if tuple(b.shape) != tuple(self.shape):
                # multiply() broadcasts; tensordot must not (numpy raises)
                raise ValueError(
                    f"shape mismatch in tensordot: {self.shape} vs {b.shape}"
                )
            return self.multiply(b).sum()
        raise NotImplementedError(
            "tensordot on 2-D sparse arrays supports 1- or 2-axis contractions"
        )

    def _rdot(self, other):
        return self.tocsr()._rdot(other)

    def __add__(self, other):
        return self.tocsr() + other

    def __mul__(self, other):
        if np.isscalar(other) or getattr(other, "ndim", 1) == 0:
            return self._with_data(self.data * other)
        return self.tocsr() * other

    def multiply(self, other):
        return self.tocsr().multiply(other)

    def sum(self, axis=None):
        if axis is None:
            return self.data.sum()
        return self.tocsr().sum(axis=axis)

    def diagonal(self, k=0):
        return self.tocsr().diagonal(k=k)

    def __str__(self):
        return (
            f"<{self.shape[0]}x{self.shape[1]} COO array, nnz={self.nnz},"
            f" dtype={self.dtype}>"
        )

    __repr__ = __str__
