"""sparse_tpu: a TPU-native distributed sparse linear algebra framework.

A drop-in ``scipy.sparse``-style library with the capabilities of
nv-legate/legate.sparse, built on JAX/XLA/Pallas. See SURVEY.md at the repo
root for the reference layer map this package mirrors:

  L1 task library      -> sparse_tpu.ops + sparse_tpu.kernels (Pallas)
  L2 runtime glue      -> sparse_tpu.config / sparse_tpu.parallel.mesh
  L3 partitioning      -> sparse_tpu.parallel
  L4 formats & ops     -> csr/csc/coo/dia + module constructors + io
  L5 algorithms        -> linalg / integrate / spatial / quantum
"""

from ._version import __version__  # noqa: F401
from .base import SparseArray  # noqa: F401
from .coo import coo_array  # noqa: F401
from .csc import csc_array  # noqa: F401
from .csr import csr_array  # noqa: F401
from .dia import dia_array  # noqa: F401
from .bsr import bsr_array  # noqa: F401
from .dok import dok_array  # noqa: F401
from .lil import lil_array  # noqa: F401
from .module import (  # noqa: F401
    SparseEfficiencyWarning,
    SparseWarning,
    block_array,
    block_diag,
    bmat,
    diags,
    diags_array,
    expand_dims,
    eye,
    eye_array,
    find,
    get_index_dtype,
    hstack,
    identity,
    is_sparse_matrix,
    issparse,
    isspmatrix,
    isspmatrix_bsr,
    isspmatrix_coo,
    isspmatrix_csc,
    isspmatrix_csr,
    isspmatrix_dia,
    isspmatrix_dok,
    isspmatrix_lil,
    kron,
    kronsum,
    load_npz,
    rand,
    random,
    permute_dims,
    random_array,
    safely_cast_index_arrays,
    save_npz,
    spdiags,
    swapaxes,
    tril,
    triu,
    vstack,
)

sparray = SparseArray  # scipy's abstract base alias

# scipy.sparse.*_matrix aliases (coverage layer parity, coverage.py:226-276)
csr_matrix = csr_array
csc_matrix = csc_array
coo_matrix = coo_array
dia_matrix = dia_array
dok_matrix = dok_array
bsr_matrix = bsr_array
lil_matrix = lil_array

from . import batch, csgraph, ingest, integrate, io, linalg, mixed, plan_cache, quantum, resilience, spatial, telemetry  # noqa: F401,E402

from .coverage import coverage_report, track_provenance  # noqa: F401,E402
