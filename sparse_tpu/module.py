"""Top-level constructors: spdiags/diags/eye/identity/kron/random/rand + predicates.

Reference analog: ``sparse/module.py:59-510``.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .base import SparseArray
from .coo import coo_array
from .csc import csc_array
from .csr import csr_array
from .dia import dia_array
from .utils import asjnp


def _as_format(A, format):
    if format is None:
        return A
    return A.asformat(format)


def diags(diagonals, offsets=0, shape=None, format=None, dtype=None):
    """scipy.sparse.diags-compatible constructor (reference module.py:96)."""
    if np.isscalar(offsets):
        offsets = [offsets]
        if np.isscalar(diagonals) or (
            hasattr(diagonals, "ndim") and getattr(diagonals, "ndim", 1) == 1
        ) or (
            isinstance(diagonals, (list, tuple))
            and diagonals
            and np.isscalar(diagonals[0])
        ):
            diagonals = [np.asarray(diagonals)]
    diagonals = [np.atleast_1d(np.asarray(d)) for d in diagonals]
    offsets = np.atleast_1d(np.asarray(offsets, dtype=np.int64))
    if len(diagonals) != len(offsets):
        raise ValueError("number of diagonals does not match number of offsets")
    if shape is None:
        m = max(len(d) + abs(int(o)) for d, o in zip(diagonals, offsets))
        shape = (m, m)
    m, n = int(shape[0]), int(shape[1])
    if dtype is None:
        dtype = np.result_type(*[d.dtype for d in diagonals])
    L = n
    data = np.zeros((len(offsets), L), dtype=dtype)
    for k, (d, off) in enumerate(zip(diagonals, offsets)):
        off = int(off)
        length = min(m + min(off, 0), n - max(off, 0))
        if length < 0:
            raise ValueError(f"offset {off} out of bounds for shape {shape}")
        lo = max(off, 0)
        if d.size == 1 and length > 1:
            d = np.full((length,), d[0])
        if d.size < length:
            raise ValueError(
                f"diagonal {k} has wrong length {d.size}, needs {length}"
            )
        data[k, lo : lo + length] = d[:length]
    A = dia_array((asjnp(data), offsets), shape=(m, n))
    return _as_format(A, format)


def spdiags(data, diags_offsets, m=None, n=None, format=None):
    """scipy.sparse.spdiags-compatible (reference module.py:59)."""
    if m is None and n is None:
        raise ValueError("spdiags requires m, n")
    if n is None:
        m, n = m
    A = dia_array((asjnp(np.atleast_2d(np.asarray(data))),
                   np.atleast_1d(np.asarray(diags_offsets, dtype=np.int64))),
                  shape=(int(m), int(n)))
    return _as_format(A, format)


def eye(m, n=None, k=0, dtype=np.float64, format="csr"):
    """Identity-like matrix (reference module.py:221)."""
    if n is None:
        n = m
    m, n = int(m), int(n)
    length = min(m + min(k, 0), n - max(k, 0))
    if length <= 0:
        A = csr_array((m, n), dtype=dtype)
        return _as_format(A, format)
    d = np.ones((length,), dtype=dtype)
    return diags([d], [k], shape=(m, n), format=format, dtype=dtype)


def identity(n, dtype=np.float64, format=None):
    return eye(n, dtype=dtype, format=format or "csr")


def kron(A, B, format=None):
    """Kronecker product of sparse matrices (reference module.py:253).

    COO outer-product expansion: nnz(A) x nnz(B) triples in one vectorized
    broadcast — no loops, one fused sort in the CSR conversion.
    """
    A = coo_array(A) if not isinstance(A, SparseArray) else A.tocoo()
    B = coo_array(B) if not isinstance(B, SparseArray) else B.tocoo()
    ma, na = A.shape
    mb, nb = B.shape
    out_shape = (ma * mb, na * nb)
    if A.nnz == 0 or B.nnz == 0:
        return _as_format(csr_array(out_shape), format)
    from .ops.coords import require_x64_index

    # per-DIMENSION escalation only: the sort/dedup machinery works on
    # (row, col) pairs, so huge m*n products never need int64 — only an
    # output dimension itself overflowing int32 does
    rdt = jnp.int64 if require_x64_index(ma * mb) else jnp.int32
    cdt = jnp.int64 if require_x64_index(na * nb) else jnp.int32
    rows = (A.row.astype(rdt)[:, None] * jnp.asarray(mb, rdt) + B.row.astype(rdt)[None, :]).ravel()
    cols = (A.col.astype(cdt)[:, None] * jnp.asarray(nb, cdt) + B.col.astype(cdt)[None, :]).ravel()
    vals = (A.data[:, None] * B.data[None, :]).ravel()
    out = coo_array((vals, (rows, cols)), shape=out_shape)
    if format in (None, "coo"):
        return out
    return out.asformat(format)


def random(
    m,
    n,
    density=0.01,
    format="coo",
    dtype=None,
    random_state=None,
    data_rvs=None,
):
    """Sparse random matrix (reference module.py:360)."""
    m, n = int(m), int(n)
    if density < 0 or density > 1:
        raise ValueError("density expected in [0, 1]")
    mn = m * n
    k = int(round(density * mn))
    if random_state is None:
        rng = np.random.default_rng()
    elif isinstance(random_state, (int, np.integer)):
        rng = np.random.default_rng(int(random_state))
    else:
        rng = random_state
    if mn > 0 and k > 0:
        if mn < (1 << 26):
            flat = rng.choice(mn, size=k, replace=False)
        else:  # sample-and-dedup for huge index spaces
            uniq = np.unique(rng.integers(0, mn, size=int(k * 1.2) + 16))
            while uniq.shape[0] < k:  # top up until k distinct positions
                more = rng.integers(0, mn, size=int(k * 0.4) + 16)
                uniq = np.unique(np.concatenate([uniq, more]))
            # subsample uniformly — truncating the sorted uniques would bias
            # every draw toward low row indices
            flat = rng.choice(uniq, size=k, replace=False)
    else:
        flat = np.zeros((0,), dtype=np.int64)
        k = 0
    rows = (flat // n).astype(np.int64)
    cols = (flat % n).astype(np.int64)
    if data_rvs is not None:
        vals = np.asarray(data_rvs(k))
    else:
        vals = rng.random(k)
    if dtype is not None:
        vals = vals.astype(dtype)
    out = coo_array((asjnp(vals), (rows, cols)), shape=(m, n))
    return _as_format(out, format)


def rand(m, n, density=0.01, format="coo", dtype=None, random_state=None):
    return random(m, n, density, format, dtype, random_state)


def issparse(o) -> bool:
    return isinstance(o, SparseArray)


def is_sparse_matrix(o) -> bool:
    return isinstance(o, SparseArray)


def isspmatrix(o) -> bool:
    return isinstance(o, SparseArray)


def isspmatrix_csr(o) -> bool:
    return isinstance(o, csr_array)


def isspmatrix_csc(o) -> bool:
    return isinstance(o, csc_array)


def isspmatrix_coo(o) -> bool:
    return isinstance(o, coo_array)


def isspmatrix_dia(o) -> bool:
    return isinstance(o, dia_array)


def isspmatrix_bsr(o) -> bool:
    from .bsr import bsr_array

    return isinstance(o, bsr_array)


def isspmatrix_dok(o) -> bool:
    from .dok import dok_array

    return isinstance(o, dok_array)


def isspmatrix_lil(o) -> bool:
    from .lil import lil_array

    return isinstance(o, lil_array)


# ---------------------------------------------------------------------------
# Block assembly / triangles / nonzero surface (coverage.py parity layer) —
# the scipy.sparse construction helpers the reference's drop-in story
# implies. All are coordinate-space assemblies over the COO machinery.
# ---------------------------------------------------------------------------
class SparseWarning(Warning):
    pass


class SparseEfficiencyWarning(SparseWarning):
    pass


def find(A):
    """(rows, cols, values) of the nonzero entries (scipy.sparse.find)."""
    # round-trip through CSR first: scipy sums duplicate COO entries before
    # selecting nonzeros (cancelling duplicates must not appear)
    c = (A if issparse(A) else coo_array(np.asarray(A))).tocsr().tocoo()
    vals = np.asarray(c.data)
    rows = np.asarray(c.row)
    cols = np.asarray(c.col)
    nz = vals != 0
    order = np.lexsort((cols[nz], rows[nz]))  # scipy returns row-major order
    return rows[nz][order], cols[nz][order], vals[nz][order]


def _coo_parts(A):
    c = A.tocoo() if issparse(A) else coo_array(np.asarray(A))
    return np.asarray(c.row), np.asarray(c.col), np.asarray(c.data), c.shape


def tril(A, k=0, format=None):
    """Lower triangle (entries with col - row <= k)."""
    r, c, v, shape = _coo_parts(A)
    keep = (c - r) <= k
    out = coo_array((asjnp(v[keep]), (r[keep], c[keep])), shape=shape)
    return _as_format(out, format)


def triu(A, k=0, format=None):
    """Upper triangle (entries with col - row >= k)."""
    r, c, v, shape = _coo_parts(A)
    keep = (c - r) >= k
    out = coo_array((asjnp(v[keep]), (r[keep], c[keep])), shape=shape)
    return _as_format(out, format)


def bmat(blocks, format=None, dtype=None):
    """Assemble a sparse matrix from a 2-D grid of blocks (None = zero)."""
    blocks = [list(row) for row in blocks]
    R = len(blocks)
    C = len(blocks[0]) if R else 0
    row_h = [None] * R
    col_w = [None] * C
    for i in range(R):
        if len(blocks[i]) != C:
            raise ValueError("blocks must be a rectangular 2-D grid")
        for j in range(C):
            b = blocks[i][j]
            if b is None:
                continue
            m, n = b.shape
            if row_h[i] is None:
                row_h[i] = m
            elif row_h[i] != m:
                raise ValueError(f"block row {i} has incompatible heights")
            if col_w[j] is None:
                col_w[j] = n
            elif col_w[j] != n:
                raise ValueError(f"block column {j} has incompatible widths")
    if any(h is None for h in row_h) or any(w is None for w in col_w):
        raise ValueError("every block row/column needs at least one block")
    r_off = np.concatenate([[0], np.cumsum(row_h)])
    c_off = np.concatenate([[0], np.cumsum(col_w)])
    rows_all, cols_all, vals_all = [], [], []
    for i in range(R):
        for j in range(C):
            b = blocks[i][j]
            if b is None:
                continue
            r, c, v, _ = _coo_parts(b)
            rows_all.append(r + r_off[i])
            cols_all.append(c + c_off[j])
            vals_all.append(v)
    if vals_all:
        rows = np.concatenate(rows_all)
        cols = np.concatenate(cols_all)
        vals = np.concatenate(vals_all)
    else:
        rows = cols = np.zeros(0, dtype=np.int64)
        vals = np.zeros(0)
    if dtype is not None:
        vals = vals.astype(dtype)
    out = coo_array(
        (asjnp(vals), (rows, cols)), shape=(int(r_off[-1]), int(c_off[-1]))
    )
    return _as_format(out, format)


block_array = bmat


def vstack(blocks, format=None, dtype=None):
    return bmat([[b] for b in blocks], format=format, dtype=dtype)


def hstack(blocks, format=None, dtype=None):
    return bmat([list(blocks)], format=format, dtype=dtype)


def block_diag(mats, format=None, dtype=None):
    grid = [
        [m if i == j else None for j in range(len(mats))]
        for i, m in enumerate(mats)
    ]
    return bmat(grid, format=format, dtype=dtype)


def kronsum(A, B, format=None):
    """kron(I_n, A) + kron(B, I_m) for square A [m, m], B [n, n]."""
    m, m2 = A.shape
    n, n2 = B.shape
    if m != m2 or n != n2:
        raise ValueError("kronsum needs square operands")
    out = kron(identity(n, dtype=A.dtype), A) + kron(B, identity(m, dtype=B.dtype))
    return _as_format(out.tocoo(), format) if format else out


def save_npz(file, matrix, compressed=True):
    """scipy-compatible .npz writer (csr/csc/coo; scipy can load these)."""
    fmt = matrix.format
    fields = {"shape": np.asarray(matrix.shape), "format": fmt.encode("ascii")}
    if fmt in ("csr", "csc"):
        fields["data"] = np.asarray(matrix.data)
        fields["indices"] = np.asarray(matrix.indices)
        fields["indptr"] = np.asarray(matrix.indptr)
    elif fmt == "coo":
        fields["data"] = np.asarray(matrix.data)
        fields["row"] = np.asarray(matrix.row)
        fields["col"] = np.asarray(matrix.col)
    else:
        return save_npz(file, matrix.tocoo(), compressed)
    (np.savez_compressed if compressed else np.savez)(file, **fields)


def load_npz(file):
    """scipy-compatible .npz reader."""
    from .csc import csc_array as _csc
    from .csr import csr_array as _csr

    with np.load(file) as f:
        fmt = f["format"].item()
        if isinstance(fmt, bytes):
            fmt = fmt.decode("ascii")
        shape = tuple(int(v) for v in f["shape"])
        if fmt in ("csr", "csc"):
            cls = _csr if fmt == "csr" else _csc
            return cls.from_parts(f["data"], f["indices"], f["indptr"], shape)
        if fmt == "coo":
            return coo_array((asjnp(f["data"]), (f["row"], f["col"])), shape=shape)
    raise ValueError(f"unsupported sparse npz format {fmt!r}")


def get_index_dtype(arrays=(), maxval=None, check_contents=False):
    """scipy semantics: int32 only when safe.

    An array whose dtype cannot cast to int32 forces int64 unless
    ``check_contents`` verifies its values (max AND min) actually fit.
    """
    i32 = np.iinfo(np.int32)
    if maxval is not None and maxval > i32.max:
        return np.int64
    for a in arrays:
        a = np.asarray(a)
        if np.can_cast(a.dtype, np.int32):
            continue
        if check_contents and np.issubdtype(a.dtype, np.integer):
            if a.size == 0:
                continue
            if int(a.min()) >= i32.min and int(a.max()) <= i32.max:
                continue
        return np.int64
    return np.int32


# array-API-era aliases
eye_array = eye
diags_array = diags


def random_array(shape, *, density=0.01, format="coo", dtype=None,
                 random_state=None, rng=None, data_sampler=None):
    """scipy>=1.12 random_array surface (shape tuple, keyword-only)."""
    m, n = shape
    state = rng if rng is not None else random_state
    # scipy calls data_sampler with the size KEYWORD; random() passes its
    # sampler a positional count
    rvs = None if data_sampler is None else (lambda k: data_sampler(size=k))
    return random(m, n, density, format, dtype, state, data_rvs=rvs)


def _check_axis(a) -> int:
    if a not in (-2, -1, 0, 1):
        raise ValueError(f"axis {a} out of bounds for a 2-D sparse array")
    return a % 2


def swapaxes(A, axis1, axis2):
    """2-D sparse swapaxes: identity for (0,0)/(1,1), transpose for (0,1).

    scipy.sparse.swapaxes analog (the n-D generalization collapses to the
    transpose in the 2-D world both we and the reference live in).
    Out-of-range axes raise, as in numpy/scipy."""
    ax = {_check_axis(axis1), _check_axis(axis2)}
    if ax == {0} or ax == {1}:
        return A.copy()
    return A.T


def permute_dims(A, axes=None):
    """scipy.sparse.permute_dims for 2-D: (0, 1) identity, (1, 0) transpose."""
    if axes is None:
        axes = (1, 0)
    axes = tuple(_check_axis(a) for a in axes)
    if axes == (0, 1):
        return A.copy()
    if axes == (1, 0):
        return A.T
    raise ValueError(f"invalid axes permutation {axes}")


def expand_dims(A, axis):
    """Unsupported: sparse arrays here are 2-D only (as in the reference).
    Raises rather than silently mis-shaping."""
    raise NotImplementedError(
        "expand_dims needs n-D sparse arrays; sparse_tpu (like the "
        "reference) is 2-D only"
    )


def safely_cast_index_arrays(A, idx_dtype=np.int32, msg=""):
    """scipy.sparse.safely_cast_index_arrays analog: return (indices,
    indptr)-style index arrays cast to ``idx_dtype``, raising when values
    don't fit."""
    info = np.iinfo(idx_dtype)

    def cast(arr):
        a = np.asarray(arr)
        if a.size and (a.max() > info.max or a.min() < info.min):
            raise ValueError(f"index values too large for {idx_dtype} {msg}")
        return a.astype(idx_dtype)

    if hasattr(A, "indptr"):
        return cast(A.indices), cast(A.indptr)
    if hasattr(A, "offsets"):  # DIA carries only the offsets vector
        return cast(A.offsets)
    return cast(A.row), cast(A.col)
