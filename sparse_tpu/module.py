"""Top-level constructors: spdiags/diags/eye/identity/kron/random/rand + predicates.

Reference analog: ``sparse/module.py:59-510``.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .base import SparseArray
from .coo import coo_array
from .csc import csc_array
from .csr import csr_array
from .dia import dia_array
from .utils import asjnp


def _as_format(A, format):
    if format is None:
        return A
    return A.asformat(format)


def diags(diagonals, offsets=0, shape=None, format=None, dtype=None):
    """scipy.sparse.diags-compatible constructor (reference module.py:96)."""
    if np.isscalar(offsets):
        offsets = [offsets]
        if np.isscalar(diagonals) or (
            hasattr(diagonals, "ndim") and getattr(diagonals, "ndim", 1) == 1
        ) or (
            isinstance(diagonals, (list, tuple))
            and diagonals
            and np.isscalar(diagonals[0])
        ):
            diagonals = [np.asarray(diagonals)]
    diagonals = [np.atleast_1d(np.asarray(d)) for d in diagonals]
    offsets = np.atleast_1d(np.asarray(offsets, dtype=np.int64))
    if len(diagonals) != len(offsets):
        raise ValueError("number of diagonals does not match number of offsets")
    if shape is None:
        m = max(len(d) + abs(int(o)) for d, o in zip(diagonals, offsets))
        shape = (m, m)
    m, n = int(shape[0]), int(shape[1])
    if dtype is None:
        dtype = np.result_type(*[d.dtype for d in diagonals])
    L = n
    data = np.zeros((len(offsets), L), dtype=dtype)
    for k, (d, off) in enumerate(zip(diagonals, offsets)):
        off = int(off)
        length = min(m + min(off, 0), n - max(off, 0))
        if length < 0:
            raise ValueError(f"offset {off} out of bounds for shape {shape}")
        lo = max(off, 0)
        if d.size == 1 and length > 1:
            d = np.full((length,), d[0])
        if d.size < length:
            raise ValueError(
                f"diagonal {k} has wrong length {d.size}, needs {length}"
            )
        data[k, lo : lo + length] = d[:length]
    A = dia_array((asjnp(data), offsets), shape=(m, n))
    return _as_format(A, format)


def spdiags(data, diags_offsets, m=None, n=None, format=None):
    """scipy.sparse.spdiags-compatible (reference module.py:59)."""
    if m is None and n is None:
        raise ValueError("spdiags requires m, n")
    if n is None:
        m, n = m
    A = dia_array((asjnp(np.atleast_2d(np.asarray(data))),
                   np.atleast_1d(np.asarray(diags_offsets, dtype=np.int64))),
                  shape=(int(m), int(n)))
    return _as_format(A, format)


def eye(m, n=None, k=0, dtype=np.float64, format="csr"):
    """Identity-like matrix (reference module.py:221)."""
    if n is None:
        n = m
    m, n = int(m), int(n)
    length = min(m + min(k, 0), n - max(k, 0))
    if length <= 0:
        A = csr_array((m, n), dtype=dtype)
        return _as_format(A, format)
    d = np.ones((length,), dtype=dtype)
    return diags([d], [k], shape=(m, n), format=format, dtype=dtype)


def identity(n, dtype=np.float64, format=None):
    return eye(n, dtype=dtype, format=format or "csr")


def kron(A, B, format=None):
    """Kronecker product of sparse matrices (reference module.py:253).

    COO outer-product expansion: nnz(A) x nnz(B) triples in one vectorized
    broadcast — no loops, one fused sort in the CSR conversion.
    """
    A = coo_array(A) if not isinstance(A, SparseArray) else A.tocoo()
    B = coo_array(B) if not isinstance(B, SparseArray) else B.tocoo()
    ma, na = A.shape
    mb, nb = B.shape
    out_shape = (ma * mb, na * nb)
    if A.nnz == 0 or B.nnz == 0:
        return _as_format(csr_array(out_shape), format)
    from .ops.coords import require_x64_keys

    require_x64_keys(out_shape)  # loud error instead of silent int32 wrap
    rows = (A.row.astype(jnp.int64)[:, None] * mb + B.row.astype(jnp.int64)[None, :]).ravel()
    cols = (A.col.astype(jnp.int64)[:, None] * nb + B.col.astype(jnp.int64)[None, :]).ravel()
    vals = (A.data[:, None] * B.data[None, :]).ravel()
    out = coo_array((vals, (rows, cols)), shape=out_shape)
    if format in (None, "coo"):
        return out
    return out.asformat(format)


def random(
    m,
    n,
    density=0.01,
    format="coo",
    dtype=None,
    random_state=None,
    data_rvs=None,
):
    """Sparse random matrix (reference module.py:360)."""
    m, n = int(m), int(n)
    if density < 0 or density > 1:
        raise ValueError("density expected in [0, 1]")
    mn = m * n
    k = int(round(density * mn))
    if random_state is None:
        rng = np.random.default_rng()
    elif isinstance(random_state, (int, np.integer)):
        rng = np.random.default_rng(int(random_state))
    else:
        rng = random_state
    if mn > 0 and k > 0:
        if mn < (1 << 26):
            flat = rng.choice(mn, size=k, replace=False)
        else:  # sample-and-dedup for huge index spaces
            uniq = np.unique(rng.integers(0, mn, size=int(k * 1.2) + 16))
            while uniq.shape[0] < k:  # top up until k distinct positions
                more = rng.integers(0, mn, size=int(k * 0.4) + 16)
                uniq = np.unique(np.concatenate([uniq, more]))
            # subsample uniformly — truncating the sorted uniques would bias
            # every draw toward low row indices
            flat = rng.choice(uniq, size=k, replace=False)
    else:
        flat = np.zeros((0,), dtype=np.int64)
        k = 0
    rows = (flat // n).astype(np.int64)
    cols = (flat % n).astype(np.int64)
    if data_rvs is not None:
        vals = np.asarray(data_rvs(k))
    else:
        vals = rng.random(k)
    if dtype is not None:
        vals = vals.astype(dtype)
    out = coo_array((asjnp(vals), (rows, cols)), shape=(m, n))
    return _as_format(out, format)


def rand(m, n, density=0.01, format="coo", dtype=None, random_state=None):
    return random(m, n, density, format, dtype, random_state)


def issparse(o) -> bool:
    return isinstance(o, SparseArray)


def is_sparse_matrix(o) -> bool:
    return isinstance(o, SparseArray)


def isspmatrix(o) -> bool:
    return isinstance(o, SparseArray)


def isspmatrix_csr(o) -> bool:
    return isinstance(o, csr_array)


def isspmatrix_csc(o) -> bool:
    return isinstance(o, csc_array)


def isspmatrix_coo(o) -> bool:
    return isinstance(o, coo_array)


def isspmatrix_dia(o) -> bool:
    return isinstance(o, dia_array)
