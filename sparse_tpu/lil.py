"""LIL (list-of-lists) format — row-wise incremental host-side construction.

Beyond the reference's class surface (its coverage layer lists tolil as a
gap too): per-row sorted column/value lists with cheap row assignment —
scipy's recommended format for building row by row, converted once
(``tocsr``) for device compute.
"""

from __future__ import annotations

import numpy as np

from .base import SparseArray


class lil_array(SparseArray):
    format = "lil"
    ndim = 2

    def __init__(self, arg1, shape=None, dtype=None):
        if isinstance(arg1, tuple) and len(arg1) == 2 and all(
            isinstance(s, (int, np.integer)) for s in arg1
        ):
            self._shape = (int(arg1[0]), int(arg1[1]))
            self._dtype = np.dtype(dtype or np.float64)
            self.rows = [[] for _ in range(self.shape[0])]
            self.data = [[] for _ in range(self.shape[0])]
            return
        if isinstance(arg1, SparseArray):
            C = arg1.tocsr()
            indptr = np.asarray(C.indptr)
            indices = np.asarray(C.indices)
            vals = np.asarray(C.data)
            self._shape = C.shape
        else:
            dense = np.asarray(arg1)
            if dense.ndim != 2:
                raise ValueError("lil_array expects a 2-D input")
            self._shape = dense.shape
            r, c = np.nonzero(dense)
            vals = dense[r, c]
            indptr = np.searchsorted(r, np.arange(self.shape[0] + 1))
            indices = c
        if shape is not None:
            shape = tuple(int(s) for s in shape)
            if self._shape[0] > shape[0] or (
                len(indices) and int(np.max(indices)) >= shape[1]
            ):
                raise ValueError(
                    f"shape {shape} cannot hold entries of shape {self._shape}"
                )
            old_m = self._shape[0]
            self._shape = shape
        else:
            old_m = self.shape[0]
        self._dtype = np.dtype(dtype or vals.dtype)
        self.rows = [
            list(map(int, indices[indptr[i] : indptr[i + 1]]))
            if i < old_m
            else []
            for i in range(self.shape[0])
        ]
        self.data = [
            [self.dtype.type(v) for v in vals[indptr[i] : indptr[i + 1]]]
            if i < old_m
            else []
            for i in range(self.shape[0])
        ]

    @property
    def nnz(self) -> int:
        return sum(len(r) for r in self.rows)

    def _check(self, i, axis):
        ext = self.shape[axis]
        i = int(i)
        if i < 0:
            i += ext
        if not 0 <= i < ext:
            raise IndexError(f"index {i} out of range for axis {axis}")
        return i

    def __getitem__(self, key):
        import bisect

        if isinstance(key, tuple) and len(key) == 2:
            i = self._check(key[0], 0)
            j = self._check(key[1], 1)
            pos = bisect.bisect_left(self.rows[i], j)
            if pos < len(self.rows[i]) and self.rows[i][pos] == j:
                return self.data[i][pos]
            return self.dtype.type(0)
        # whole-row read -> dense 1-D (scipy returns a sparse row; the
        # dense vector is this library's documented axis-result deviation)
        i = self._check(key, 0)
        out = np.zeros(self.shape[1], dtype=self.dtype)
        out[self.rows[i]] = self.data[i]
        return out

    def __setitem__(self, key, value):
        import bisect

        if isinstance(key, tuple) and len(key) == 2:
            i = self._check(key[0], 0)
            j = self._check(key[1], 1)
            pos = bisect.bisect_left(self.rows[i], j)
            present = pos < len(self.rows[i]) and self.rows[i][pos] == j
            if value == 0:
                if present:
                    del self.rows[i][pos]
                    del self.data[i][pos]
            elif present:
                self.data[i][pos] = self.dtype.type(value)
            else:
                self.rows[i].insert(pos, j)
                self.data[i].insert(pos, self.dtype.type(value))
            return
        # whole-row assignment from a dense vector
        i = self._check(key, 0)
        row = np.asarray(value)
        if row.shape != (self.shape[1],):
            raise ValueError(
                f"row assignment expects shape ({self.shape[1]},), got {row.shape}"
            )
        nz = np.nonzero(row)[0]
        self.rows[i] = list(map(int, nz))
        self.data[i] = [self.dtype.type(v) for v in row[nz]]

    # ---- conversions -----------------------------------------------------
    def tocsr(self):
        from .csr import csr_array

        indptr = np.zeros(self.shape[0] + 1, dtype=np.int64)
        np.cumsum([len(r) for r in self.rows], out=indptr[1:])
        indices = np.array(
            [j for r in self.rows for j in r], dtype=np.int64
        )
        vals = np.array(
            [v for d in self.data for v in d], dtype=self.dtype
        )
        return csr_array.from_parts(vals, indices, indptr, self.shape)

    def tocoo(self):
        return self.tocsr().tocoo()

    def tocsc(self):
        return self.tocsr().tocsc()

    def todia(self):
        return self.tocsr().todia()

    def tolil(self):
        return self

    def toarray(self):
        out = np.zeros(self.shape, dtype=self.dtype)
        for i, (r, d) in enumerate(zip(self.rows, self.data)):
            out[i, r] = d
        return out

    def copy(self):
        new = lil_array(self.shape, dtype=self.dtype)
        new.rows = [list(r) for r in self.rows]
        new.data = [list(d) for d in self.data]
        return new

    # SparseArray's generic hooks (neg/abs/astype/conj run through these)
    def _data_array(self):
        return np.array(
            [v for d in self.data for v in d], dtype=self.dtype
        )

    def _with_data(self, data):
        data = np.asarray(data)
        new = lil_array(self.shape, dtype=data.dtype)
        new.rows = [list(r) for r in self.rows]
        it = iter(data)
        new.data = [
            [data.dtype.type(next(it)) for _ in d] for d in self.data
        ]
        return new

    def transpose(self):
        return self.tocsr().T.tolil()

    @property
    def T(self):
        return self.transpose()

    # ---- math delegates to CSR -------------------------------------------
    def _delegate(self):
        return self.tocsr()

    def __matmul__(self, other):
        return self._delegate() @ other

    def dot(self, other):
        return self._delegate().dot(other)

    def __add__(self, other):
        other = other._delegate() if isinstance(other, lil_array) else other
        return self._delegate() + other

    def __mul__(self, other):
        return self._delegate() * other

    def multiply(self, other):
        other = other._delegate() if isinstance(other, lil_array) else other
        return self._delegate().multiply(other)

    def sum(self, axis=None):
        return self._delegate().sum(axis=axis)

    def __repr__(self):
        return (
            f"<{self.shape[0]}x{self.shape[1]} LIL array, nnz={self.nnz},"
            f" dtype={self.dtype}>"
        )

    __str__ = __repr__
