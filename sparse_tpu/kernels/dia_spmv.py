"""Pallas TPU kernel: DIA SpMV with explicit VMEM windowing.

The XLA formulation (``ops.dia_spmv``) already avoids gathers; this kernel
additionally controls the memory schedule: the x vector stays in HBM, each
grid step DMAs exactly the [TM + 2B] window its row tile needs into VMEM,
and the D diagonal contributions are accumulated as statically-shifted VMEM
slices on the VPU. One x load + one data load + one y store per element —
the HBM-bandwidth lower bound for banded SpMV.

Reference analog: the cuSPARSE-backed CSR SpMV task
(``src/sparse/array/csr/spmv.cu:42-116``) with the shifted-pointer trick;
here the "shifted pointer" is a static slice offset into the VMEM window.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _round_up(v: int, m: int) -> int:
    return (v + m - 1) // m * m


@partial(jax.jit, static_argnames=("offsets", "shape", "tile", "interpret"))
def dia_spmv_pallas(
    data, offsets: tuple, x, shape: tuple, tile: int = 16384, interpret: bool = False
):
    """y = A @ x, A in DIA layout (scipy convention), banded offsets.

    ``tile`` rows per grid step (multiple of 128). The per-tile x window is
    [tile + 2B] where B is the bandwidth; windows of neighboring tiles
    overlap by 2B — the halo. DMA'd from HBM per step.
    """
    m, n = shape
    D = len(offsets)
    B = _round_up(max(max((abs(int(o)) for o in offsets), default=0), 1), 128)
    TM = min(tile, _round_up(max(m, 128), 128))
    G = (m + TM - 1) // TM
    m_pad = G * TM

    # prod[k, j] = data[k, j] * x[j]; shifted windows of prod are summed.
    prod = data * x[None, :n]  # [D, n]
    # pad so that window [g*TM, g*TM + TM + 2B) is always in range after a
    # left shift of B: padded index j' = j + B (right pad clamped for wide
    # matrices where n > m_pad)
    prod = jnp.pad(prod, ((0, 0), (B, max(m_pad - n, 0) + B)))
    prod = prod[:, : m_pad + 2 * B]

    win = TM + 2 * B

    def kernel(prod_hbm, y_ref, xwin, sem):
        g = pl.program_id(0)
        dma = pltpu.make_async_copy(
            prod_hbm.at[:, pl.ds(g * TM, win)], xwin, sem
        )
        dma.start()
        dma.wait()
        acc = jnp.zeros((TM,), dtype=y_ref.dtype)
        for k, o in enumerate(offsets):
            lo = B + int(o)
            acc = acc + xwin[k, lo : lo + TM]
        y_ref[:] = acc

    y = pl.pallas_call(
        kernel,
        grid=(G,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((TM,), lambda g: (g,), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m_pad,), prod.dtype),
        scratch_shapes=[
            pltpu.VMEM((D, win), prod.dtype),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(prod)
    return y[:m]
