"""Pallas TPU kernel: DIA SpMV with explicit VMEM windowing.

The XLA formulation (``ops.dia_spmv``) already avoids gathers; this kernel
additionally controls the memory schedule: data and x stay in HBM, each grid
step DMAs the [D, TM + 2B] data tile and the [TM + 2B] x window its row tile
needs into VMEM, and the diagonal contributions — **including the data*x
multiply** — are computed in VMEM as statically-shifted slices on the VPU.
Per element that is one data load + one (windowed) x load + one y store,
plus a one-time [D, 2B]-per-row-tile halo pad of the data planes — no
full-size intermediate product array ever exists in HBM.

Reference analog: the cuSPARSE-backed CSR SpMV task
(``src/sparse/array/csr/spmv.cu:42-116``) with the shifted-pointer trick;
here the "shifted pointer" is a static slice offset into the VMEM window.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _round_up(v: int, m: int) -> int:
    return (v + m - 1) // m * m


def dia_spmv_pallas(data, offsets, x, shape, tile=16384, interpret=None):
    """See ``_dia_spmv_pallas``; ``interpret=None`` auto-selects interpret
    mode off-TPU (Pallas TPU kernels only compile natively on tpu)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _dia_spmv_pallas(
        data, tuple(offsets), x, tuple(shape), tile=tile, interpret=interpret
    )


@partial(jax.jit, static_argnames=("offsets", "shape", "tile", "interpret"))
def _dia_spmv_pallas(
    data, offsets: tuple, x, shape: tuple, tile: int = 16384, interpret: bool = False
):
    """y = A @ x, A in DIA layout (scipy convention), banded offsets.

    ``tile`` rows per grid step (multiple of 128). The per-tile x/data window
    is [tile + 2B] where B is the bandwidth; windows of neighboring tiles
    overlap by 2B — the halo. Both are DMA'd from HBM per step and multiplied
    in VMEM (contribution of diagonal o to row i is data[k, i+o] * x[i+o]).
    """
    m, n = shape
    D = len(offsets)
    # Mosaic DMA alignment: 1-D HBM memrefs carry a (1024,) tiling, so the
    # row tile TM rounds to 1024 and the halo B to 512 — then the window
    # win = TM + 2B, every window start g*TM, and each plane's base k*L in
    # the flattened plane array are all multiples of 1024.
    B = _round_up(max(max((abs(int(o)) for o in offsets), default=0), 1), 512)
    TM = min(_round_up(tile, 1024), _round_up(max(m, 1024), 1024))
    G = (m + TM - 1) // TM
    m_pad = G * TM
    win = TM + 2 * B
    L = m_pad + 2 * B  # padded plane length (multiple of 1024)

    # Halo-pad data planes and x into a shared padded coordinate system
    # (index j' = j + B); a copy of the inputs, NOT a product intermediate.
    # The plane count pads to a sublane multiple of 8 (zero planes) so each
    # window is one aligned [Dp, win] DMA.
    Dp = _round_up(D, 8)
    pad_hi = max(m_pad - n, 0) + B
    data_p = jnp.pad(data, ((0, Dp - D), (B, pad_hi)))[:, :L]
    x_p = jnp.pad(x, (B, pad_hi))[:L]
    out_dt = jnp.result_type(data.dtype, x.dtype)

    def kernel(data_hbm, x_hbm, y_ref, dwinA, dwinB, xwinA, xwinB, semA, semB):
        # Cross-step double buffering: step g waits on the DMAs it (or the
        # warm-up) issued into its slot's buffers and prefetches step g+1
        # into the other slot's, overlapping HBM reads with VPU compute —
        # scratch and semaphores persist across the sequential TPU grid.
        # The two slots are unrolled statically (Mosaic cannot scalar-index
        # the tiled dims of a VMEM ref, so buffer choice must be static).
        g = pl.program_id(0)
        G_ = pl.num_programs(0)

        def issue(dwin, xwin, sem, gg):
            pltpu.make_async_copy(
                data_hbm.at[:, pl.ds(gg * TM, win)], dwin, sem.at[0]
            ).start()
            pltpu.make_async_copy(
                x_hbm.at[pl.ds(gg * TM, win)], xwin, sem.at[1]
            ).start()

        def wait(dwin, xwin, sem, gg):
            pltpu.make_async_copy(
                data_hbm.at[:, pl.ds(gg * TM, win)], dwin, sem.at[0]
            ).wait()
            pltpu.make_async_copy(
                x_hbm.at[pl.ds(gg * TM, win)], xwin, sem.at[1]
            ).wait()

        def step(dwin, xwin, sem, dwin_n, xwin_n, sem_n):
            @pl.when(g == 0)
            def _():
                issue(dwin, xwin, sem, g)

            @pl.when(g + 1 < G_)
            def _():
                issue(dwin_n, xwin_n, sem_n, g + 1)

            wait(dwin, xwin, sem, g)
            acc = jnp.zeros((TM,), dtype=y_ref.dtype)
            for k, o in enumerate(offsets):
                lo = B + int(o)
                acc = acc + dwin[k, lo : lo + TM] * xwin[lo : lo + TM]
            y_ref[:] = acc

        @pl.when(g % 2 == 0)
        def _():
            step(dwinA, xwinA, semA, dwinB, xwinB, semB)

        @pl.when(g % 2 == 1)
        def _():
            step(dwinB, xwinB, semB, dwinA, xwinA, semA)

    y = pl.pallas_call(
        kernel,
        grid=(G,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((TM,), lambda g: (g,), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m_pad,), out_dt),
        scratch_shapes=[
            pltpu.VMEM((Dp, win), data.dtype),
            pltpu.VMEM((Dp, win), data.dtype),
            pltpu.VMEM((win,), x.dtype),
            pltpu.VMEM((win,), x.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(data_p, x_p)
    return y[:m]
