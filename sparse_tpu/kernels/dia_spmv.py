"""Pallas TPU kernel: DIA SpMV with explicit VMEM windowing.

The XLA formulation (``ops.dia_spmv``) already avoids gathers; this kernel
additionally controls the memory schedule: data and x stay in HBM, each grid
step DMAs the [D, TM + 2B] data tile and the [TM + 2B] x window its row tile
needs into VMEM, and the diagonal contributions — **including the data*x
multiply** — are computed in VMEM as statically-shifted slices on the VPU.
Per element that is one data load + one (windowed) x load + one y store,
plus a one-time [D, 2B]-per-row-tile halo pad of the data planes — no
full-size intermediate product array ever exists in HBM.

Reference analog: the cuSPARSE-backed CSR SpMV task
(``src/sparse/array/csr/spmv.cu:42-116``) with the shifted-pointer trick;
here the "shifted pointer" is a static slice offset into the VMEM window.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _round_up(v: int, m: int) -> int:
    return (v + m - 1) // m * m


def dia_spmv_pallas(data, offsets, x, shape, tile=16384, interpret=None):
    """See ``_dia_spmv_pallas``; ``interpret=None`` auto-selects interpret
    mode off-TPU (Pallas TPU kernels only compile natively on tpu)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _dia_spmv_pallas(
        data, tuple(offsets), x, tuple(shape), tile=tile, interpret=interpret
    )


@partial(jax.jit, static_argnames=("offsets", "shape", "tile", "interpret"))
def _dia_spmv_pallas(
    data, offsets: tuple, x, shape: tuple, tile: int = 16384, interpret: bool = False
):
    """y = A @ x, A in DIA layout (scipy convention), banded offsets.

    ``tile`` rows per grid step (multiple of 128). The per-tile x/data window
    is [tile + 2B] where B is the bandwidth; windows of neighboring tiles
    overlap by 2B — the halo. Both are DMA'd from HBM per step and multiplied
    in VMEM (contribution of diagonal o to row i is data[k, i+o] * x[i+o]).
    """
    m, n = shape
    D = len(offsets)
    # Mosaic DMA alignment: 2-D slices align to the (8, 128) tile, and 1-D
    # HBM memrefs carry a (1024,) tiling — so the plane count pads to a
    # multiple of 8 (zero planes, skipped in the compute loop), the row tile
    # TM to 1024, and the halo B to 512 (making win = TM + 2B and every
    # slice start g*TM multiples of 1024).
    Dp = _round_up(D, 8)
    B = _round_up(max(max((abs(int(o)) for o in offsets), default=0), 1), 512)
    TM = min(_round_up(tile, 1024), _round_up(max(m, 1024), 1024))
    G = (m + TM - 1) // TM
    m_pad = G * TM
    win = TM + 2 * B

    # Halo-pad data planes and x into a shared padded coordinate system
    # (index j' = j + B); a copy of the inputs, NOT a product intermediate.
    pad_hi = max(m_pad - n, 0) + B
    data_p = jnp.pad(data, ((0, Dp - D), (B, pad_hi)))[:, : m_pad + 2 * B]
    x_p = jnp.pad(x, (B, pad_hi))[: m_pad + 2 * B]
    out_dt = jnp.result_type(data.dtype, x.dtype)

    def kernel(data_hbm, x_hbm, y_ref, dwin, xwin, sems):
        g = pl.program_id(0)
        d_dma = pltpu.make_async_copy(
            data_hbm.at[:, pl.ds(g * TM, win)], dwin, sems.at[0]
        )
        x_dma = pltpu.make_async_copy(
            x_hbm.at[pl.ds(g * TM, win)], xwin, sems.at[1]
        )
        d_dma.start()
        x_dma.start()
        d_dma.wait()
        x_dma.wait()
        acc = jnp.zeros((TM,), dtype=y_ref.dtype)
        for k, o in enumerate(offsets):
            lo = B + int(o)
            acc = acc + dwin[k, lo : lo + TM] * xwin[lo : lo + TM]
        y_ref[:] = acc

    y = pl.pallas_call(
        kernel,
        grid=(G,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((TM,), lambda g: (g,), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m_pad,), out_dt),
        scratch_shapes=[
            pltpu.VMEM((Dp, win), data.dtype),
            pltpu.VMEM((win,), x.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(data_p, x_p)
    return y[:m]
