"""Pallas TPU kernel: DIA SpMV with explicit VMEM windowing.

The XLA formulation (``ops.dia_spmv``) already avoids gathers; this kernel
additionally controls the memory schedule: data and x stay in HBM, each grid
step DMAs the [D, TM + 2B] data tile and the [TM + 2B] x window its row tile
needs into VMEM, and the diagonal contributions — **including the data*x
multiply** — are computed in VMEM as statically-shifted slices on the VPU.
Per element that is one data load + one (windowed) x load + one y store,
plus a one-time [D, 2B]-per-row-tile halo pad of the data planes — no
full-size intermediate product array ever exists in HBM.

Reference analog: the cuSPARSE-backed CSR SpMV task
(``src/sparse/array/csr/spmv.cu:42-116``) with the shifted-pointer trick;
here the "shifted pointer" is a static slice offset into the VMEM window.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _round_up(v: int, m: int) -> int:
    return (v + m - 1) // m * m


# ---------------------------------------------------------------------------
# Prepared-layout variant: row-indexed planes, packed once, reused per SpMV.
#
# The original kernel below re-pads the scipy-layout planes on every call —
# an extra read+write of the whole matrix per SpMV — and DMAs Dp = ceil8(D)
# column-indexed planes with a 2B halo each. Preparing a row-indexed flat
# plane array once removes both: plane k's coefficient for row i is
# pr[k, i] = data[k, i + o_k], so each grid step needs exactly [D, TM]
# plane elements (no halo, no pad planes) fetched as D aligned 1-D DMAs
# from the flattened [D * m_pad] buffer. Only the x window keeps the 2B
# halo. Per-element traffic drops from ~Dp(TM+2B)/D·TM to 1 plane load +
# ~1 x load + 1 y store — the true bandwidth floor for DIA SpMV.
# ---------------------------------------------------------------------------


def plane_stream_dtype(requested, default, TM: int):
    """Resolve the plane stream dtype against the DMA alignment rule:
    2-byte elements need 2048-element-aligned starts, so an odd-1024 TM
    forces the default (4-byte) stream. Single source for every caller
    (PreparedDia, dia_spmv_packed, the fused CG kernels)."""
    if requested is None:
        return jnp.dtype(default)
    rdt = jnp.dtype(requested)
    if rdt.itemsize == 2 and TM % 2048:
        return jnp.dtype(default)
    return rdt


class DiaPlan:
    """Static geometry of a prepared DIA operator (hashable => jit-static)."""

    __slots__ = ("offsets", "m", "n", "TM", "B", "G", "D")

    def __init__(self, offsets, m, n, TM, B, G):
        self.offsets = tuple(int(o) for o in offsets)
        self.m, self.n, self.TM, self.B, self.G = m, n, TM, B, G
        self.D = len(self.offsets)

    def _key(self):
        return (self.offsets, self.m, self.n, self.TM, self.B, self.G)

    def __hash__(self):
        return hash(self._key())

    def __eq__(self, other):
        return isinstance(other, DiaPlan) and self._key() == other._key()


def dia_plan(offsets, shape, tile: int = 65536) -> DiaPlan:
    m, n = shape
    B = _round_up(max(max((abs(int(o)) for o in offsets), default=0), 1), 512)
    TM = min(_round_up(tile, 1024), _round_up(max(m, 1024), 1024))
    G = (m + TM - 1) // TM
    return DiaPlan(offsets, m, n, TM, B, G)


@partial(jax.jit, static_argnames=("plan",))
def dia_pack(data, plan: DiaPlan):
    """scipy-layout [D, n] planes -> flat row-indexed [D * m_pad] buffer.

    Columns beyond m_pad + B - 1 can never be touched (row i reads column
    i + o <= m_pad - 1 + B), so wide matrices are truncated to that bound —
    without it, dynamic_update_slice would CLAMP the start when the operand
    overruns the buffer and silently shift every coefficient.
    """
    m_pad = plan.G * plan.TM
    B = plan.B
    ncap = min(plan.n, m_pad + B)
    buf = jnp.zeros((plan.D, m_pad + 2 * B), dtype=data.dtype)
    buf = jax.lax.dynamic_update_slice(buf, data[:, :ncap], (0, B))
    # Row mask: scipy ignores DIA slots whose row j - o falls outside the
    # matrix, but the arrays may hold junk there. Those slots land in
    # pr rows i >= m; zeroing them keeps padded rows exactly zero — vital
    # for cg_dia_fused, where nonzero padded q would leak into r and rho.
    valid = jnp.arange(m_pad) < plan.m
    rows = [
        jnp.where(valid, jax.lax.dynamic_slice(buf[k], (B + o,), (m_pad,)), 0)
        for k, o in enumerate(plan.offsets)
    ]
    return jnp.concatenate(rows)  # [D * m_pad]


@partial(jax.jit, static_argnames=("plan",))
def dia_pad_x(x, plan: DiaPlan):
    """[n] -> [m_pad + 2B] with x at offset B (zeros elsewhere).

    Same wide-matrix truncation as :func:`dia_pack`: entries past
    m_pad + B - 1 are unreachable by any in-band diagonal.
    """
    m_pad = plan.G * plan.TM
    ncap = min(x.shape[0], m_pad + plan.B)
    out = jnp.zeros((m_pad + 2 * plan.B,), dtype=x.dtype)
    return jax.lax.dynamic_update_slice(out, x[:ncap], (plan.B,))


@partial(jax.jit, static_argnames=("plan", "interpret", "acc_dtype"))
def dia_spmv_packed(planes_flat, x_padded, plan: DiaPlan, interpret: bool = False,
                    acc_dtype=None):
    """y = A @ x from the prepared layout; returns the [m_pad] padded y.

    ``planes_flat`` from :func:`dia_pack`, ``x_padded`` from
    :func:`dia_pad_x` — keep both resident across calls (solvers keep their
    vectors in padded coordinates and never repack).

    The plane stream already supports reduced-width storage
    (:func:`plane_stream_dtype` — bf16 planes halve matrix traffic and
    widen at the accumulate); ``acc_dtype`` additionally pins the
    accumulator/output dtype ABOVE the natural result type (ISSUE 15:
    bf16 planes + bf16 x still reduce in f32). ``None`` = historic
    result-type behavior, byte-identical.
    """
    TM, B, G, D = plan.TM, plan.B, plan.G, plan.D
    win = TM + 2 * B
    m_pad = G * TM
    out_dt = acc_dtype or jnp.result_type(planes_flat.dtype, x_padded.dtype)
    # direct callers may hand us 2-byte planes with a misaligned TM; the
    # pack-time guard in PreparedDia avoids this per-call cast on hot paths
    safe_dt = plane_stream_dtype(planes_flat.dtype, out_dt, TM)
    if safe_dt != planes_flat.dtype:
        planes_flat = planes_flat.astype(safe_dt)

    # Each plane gets its OWN 1-D (TM,) VMEM buffer: Mosaic rejects DMA into
    # a single row of a 2-D (8,128)-tiled scratch ("slice along dim 0 must
    # be aligned to tiling (8)"), while 1-D destinations are unrestricted —
    # and D separate buffers keep the stream at exactly D planes (no ceil8
    # padding traffic, the point of the packed layout).
    def kernel(planes_hbm, x_hbm, y_ref, *scr):
        dwinsA, dwinsB = scr[:D], scr[D : 2 * D]
        xwinA, xwinB, semA, semB = scr[2 * D :]
        g = pl.program_id(0)
        G_ = pl.num_programs(0)

        def copies(dwins, xwin, sem, gg):
            for k in range(D):
                yield pltpu.make_async_copy(
                    planes_hbm.at[pl.ds(k * m_pad + gg * TM, TM)],
                    dwins[k],
                    sem.at[k],
                )
            yield pltpu.make_async_copy(
                x_hbm.at[pl.ds(gg * TM, win)], xwin, sem.at[D]
            )

        def issue(dwins, xwin, sem, gg):
            for c in copies(dwins, xwin, sem, gg):
                c.start()

        def wait(dwins, xwin, sem, gg):
            for c in copies(dwins, xwin, sem, gg):
                c.wait()

        def step(dwins, xwin, sem, dwins_n, xwin_n, sem_n):
            @pl.when(g == 0)
            def _():
                issue(dwins, xwin, sem, g)

            @pl.when(g + 1 < G_)
            def _():
                issue(dwins_n, xwin_n, sem_n, g + 1)

            wait(dwins, xwin, sem, g)
            acc = jnp.zeros((TM,), dtype=y_ref.dtype)
            for k, o in enumerate(plan.offsets):
                lo = B + o
                acc = acc + dwins[k][:].astype(acc.dtype) * xwin[lo : lo + TM]
            y_ref[:] = acc

        @pl.when(g % 2 == 0)
        def _():
            step(dwinsA, xwinA, semA, dwinsB, xwinB, semB)

        @pl.when(g % 2 == 1)
        def _():
            step(dwinsB, xwinB, semB, dwinsA, xwinA, semA)

    return pl.pallas_call(
        kernel,
        grid=(G,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((TM,), lambda g: (g,), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m_pad,), out_dt),
        scratch_shapes=[pltpu.VMEM((TM,), planes_flat.dtype)] * (2 * D)
        + [
            pltpu.VMEM((win,), x_padded.dtype),
            pltpu.VMEM((win,), x_padded.dtype),
            pltpu.SemaphoreType.DMA((D + 1,)),
            pltpu.SemaphoreType.DMA((D + 1,)),
        ],
        interpret=interpret,
    )(planes_flat, x_padded)


def dia_spmv_pallas_v2(data, offsets, x, shape, tile=65536, interpret=None):
    """One-shot wrapper over the prepared path (packs per call — for tests
    and drop-in use; hot loops should pack once via PreparedDia)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    plan = dia_plan(tuple(offsets), tuple(shape), tile=tile)
    y = dia_spmv_packed(
        dia_pack(data, plan), dia_pad_x(x, plan), plan, interpret=interpret
    )
    return y[: plan.m]


@partial(jax.jit, static_argnames=("plan", "iters", "interpret"))
def _spmv_chain(planes_flat, x_padded, plan: DiaPlan, iters: int,
                interpret: bool = False):
    """``iters`` dependent SpMVs compiled as ONE dispatch (y feeds the next
    x window), for wall-clock timing that a shared-tunnel's per-dispatch
    latency cannot contaminate — the best-of-chain measurement discipline
    behind the autotuner and the bench's packed-DIA row."""

    def body(_, xp):
        y = dia_spmv_packed(planes_flat, xp, plan, interpret=interpret)
        return jax.lax.dynamic_update_slice(xp, y.astype(xp.dtype), (plan.B,))

    return jax.lax.fori_loop(0, iters, body, x_padded)


_TILE_CACHE: dict = {}
# Process-wide retirement of the compiled fori_loop chain clock: loop-
# wrapped kernels are a known worker-fault class on the tunnel backend, and
# repeated faulting attempts are the main tunnel-wedge trigger — so after
# the FIRST failure anywhere (any geometry, any call) the compiled clock is
# never attempted again this process (same one-time-latch pattern as the
# resilience.failover registry, but autotune-local).
_CHAIN_RETIRED = [False]


@partial(jax.jit, static_argnames=("plan",))
def _chain_step(planes_flat, x_padded, plan: DiaPlan):
    """One SpMV + x-window update as a single COMPILED step — the host-
    chained clock dispatches K of these (data dependence serializes on
    device) with no eager ops ever touching the accelerator (eager slices
    are an UNIMPLEMENTED class on the tunnel backend)."""
    y = dia_spmv_packed(planes_flat, x_padded, plan)
    return jax.lax.dynamic_update_slice(
        x_padded, y.astype(x_padded.dtype), (plan.B,)
    )


def autotune_dia_tile(
    data,
    offsets,
    shape,
    candidates=(65536, 131072),
    chain: int = 16,
    reps: int = 3,
    budget_s: float = 30.0,
):
    """Pick the fastest row-tile for this geometry on the CURRENT backend.

    Times a ``chain``-long compiled SpMV chain per candidate (best of
    ``reps``) and memoizes the winner per (offsets, shape, dtype) for the
    session — the runtime analog of the reference's one-time partition
    analysis, sized so the probe costs ~1 s of device time once compiles
    are cached. Returns ``(best_tile, {tile: seconds_per_spmv})``.
    Off-TPU (interpret mode) timings are meaningless: returns the default
    without probing.

    Cold-compile guard: each candidate can cost a fresh Mosaic compile
    (~20-40 s through a remote tunnel), so the default candidate list is
    just the two tiles that have ever won a session sweep, the first
    candidate is the always-safe 65536 default, and probing stops once
    ``budget_s`` of wall clock is spent — best-so-far wins, later
    sessions with a warm compile cache probe the full list.
    """
    import time

    from .. import telemetry
    from ..config import settings

    offsets = tuple(int(o) for o in offsets)
    shape = tuple(int(s) for s in shape)
    key = (offsets, shape, str(np.dtype(data.dtype)))
    if key in _TILE_CACHE:
        telemetry.count("autotune.cache_hit")
        return _TILE_CACHE[key]
    # the off-switch (SPARSE_TPU_PALLAS_AUTOTUNE=0) gates EVERY probe
    # path, incl. bench's direct calls — it exists so an operator can
    # forbid the extra cold Mosaic compiles on a fragile tunnel.
    # The gate result is NOT memoized (ADVICE r5): caching it under the
    # geometry key would make a later same-session flip of the setting
    # (or a backend change) return the gate default as if a probe ran.
    if not settings.pallas_autotune or jax.default_backend() != "tpu":
        reason = (
            "autotune-disabled" if not settings.pallas_autotune
            else "backend-not-tpu"
        )
        telemetry.record(
            "autotune.result", tile=65536, probed=False, reason=reason,
            shape=list(shape), diags=len(offsets),
            dtype=str(np.dtype(data.dtype)),
        )
        return (65536, {})

    # Two clocks, never mixed in one race. Preferred: the compiled
    # fori_loop chain (one dispatch per timing) — but loop-wrapped kernels
    # are a known worker-fault class on the tunnel backend, so it gets
    # exactly ONE lifetime attempt process-wide (_CHAIN_RETIRED); any
    # failure retires it and the race RESTARTS on the host-chained clock:
    # K jitted single steps (data dependence serializes on device, no
    # eager accelerator ops), fenced by a host scalar fetch — the fetch is
    # the only fence the tunnel honors (block_until_ready is not, see
    # bench._time_kernel). The fence cost is a constant per timing shared
    # by every candidate, so the RANKING is unaffected; band values in a
    # host-clock race carry ~1/chain of one round-trip each.
    def run_compiled(pf, xp, plan):
        try:
            t0 = time.perf_counter()
            out = _spmv_chain(pf, xp, plan, chain)
            float(jnp.asarray(out)[-1])  # host-scalar fence
            return (time.perf_counter() - t0) / chain
        except Exception:  # pragma: no cover - backend-dependent
            _CHAIN_RETIRED[0] = True
            return None

    def run_host(pf, xp, plan):
        t0 = time.perf_counter()
        x_cur = xp
        for _ in range(chain):
            x_cur = _chain_step(pf, x_cur, plan)
        float(jnp.asarray(x_cur)[-1])  # host-scalar fence
        return (time.perf_counter() - t0) / chain

    def time_candidate(pf, xp, plan):
        # per-PLAN warm run outside the clock: both clocks' jits are keyed
        # on the static plan, so every candidate's first call compiles
        # (~20-40 s through a remote tunnel) — that must never land in a
        # timed rep. Only the ACTIVE clock is warmed (finding: a spare
        # compile per candidate can eat the whole probe budget). Returns
        # (best_secs, used_compiled_clock).
        if not _CHAIN_RETIRED[0]:
            run_compiled(pf, xp, plan)  # warm; may retire the clock
        if _CHAIN_RETIRED[0]:
            float(jnp.asarray(_chain_step(pf, xp, plan))[-1])  # warm host
        best = float("inf")
        used_compiled = False
        for _ in range(reps):
            s = run_compiled(pf, xp, plan) if not _CHAIN_RETIRED[0] else None
            if s is None:
                s = run_host(pf, xp, plan)
            else:
                used_compiled = True
            best = min(best, s)
        return best, used_compiled

    timings: dict[int, float] = {}
    for _race in range(2):
        t_begin = time.perf_counter()  # each race gets the full budget
        retired_at_start = _CHAIN_RETIRED[0]
        timings = {}
        any_compiled = False
        for tile in candidates:
            if timings and time.perf_counter() - t_begin > budget_s:
                break  # out of probe budget: best-so-far wins
            plan = dia_plan(offsets, shape, tile=tile)
            if plan.G == 1 and timings:
                continue  # a single-grid-step plan is tile-size invariant
            try:
                pf = dia_pack(data, plan)
                xp = dia_pad_x(
                    jnp.ones(
                        (shape[1],),
                        dtype=jnp.result_type(data.dtype, jnp.float32),
                    ),
                    plan,
                )
                timings[tile], used = time_candidate(pf, xp, plan)
                any_compiled = any_compiled or used
            except Exception:  # pragma: no cover - backend-dependent
                continue  # an unlowerable candidate drops out of the race
        if _CHAIN_RETIRED[0] == retired_at_start or not any_compiled:
            # no mid-race clock flip — or the flip happened before any
            # compiled timing landed, so everything recorded is already
            # pure host-clock: keep it, no re-race (extra device probes
            # are wedge exposure)
            break
        # the compiled clock died mid-race WITH compiled timings on the
        # board: cross-clock offsets differ by ~a tunnel round-trip, so
        # discard and re-race everything on the host clock (retirement is
        # process-wide, so this happens at most once)
    if not timings:
        result = (65536, {})
    else:
        result = (min(timings, key=timings.get), timings)
    _TILE_CACHE[key] = result
    telemetry.record(
        "autotune.probe", tile=result[0], shape=list(shape),
        diags=len(offsets), dtype=str(np.dtype(data.dtype)),
        timings_us={str(t): round(s * 1e6, 1) for t, s in result[1].items()},
        clock="host" if _CHAIN_RETIRED[0] else "compiled",
    )
    return result


class PreparedDia:
    """A DIA operator packed once into the kernel-native layout.

    Holds the flat row-indexed plane buffer on device; each call pads x
    into window coordinates, runs :func:`dia_spmv_packed`, and trims the
    result. Format classes cache one of these per matrix so solver loops
    never repack (the reference likewise keeps its CSR stores resident
    across task launches rather than re-materializing per SpMV).

    ``tile=None`` autotunes on real TPUs when ``settings.pallas_autotune``
    is on (one ~1 s chained probe per geometry per session) and otherwise
    uses the 65536 default.
    """

    __slots__ = ("plan", "planes")

    def __init__(self, data, offsets, shape, tile: int | None = None):
        if tile is None:
            # autotune_dia_tile itself gates on settings.pallas_autotune
            # and the backend; off / off-TPU it returns the 65536 default
            tile, _ = autotune_dia_tile(data, offsets, shape)
        self.plan = dia_plan(tuple(int(o) for o in offsets), tuple(shape), tile=tile)
        sdt = plane_stream_dtype(data.dtype, jnp.float32, self.plan.TM)
        if sdt != jnp.dtype(data.dtype):
            data = data.astype(sdt)  # misaligned TM: stream at f32
        self.planes = dia_pack(data, self.plan)
        from .. import telemetry

        telemetry.count("kernel.dia_pack")

    @classmethod
    def from_parts(cls, plan: DiaPlan, planes) -> "PreparedDia":
        """Reassemble from an already-packed plane buffer — the vault
        codec's constructor. The stored :class:`DiaPlan` carries the
        session that wrote it's autotuned row tile, so a disk hit also
        skips the autotune probe."""
        prep = object.__new__(cls)
        prep.plan = plan
        prep.planes = planes
        return prep

    def __call__(self, x, interpret=None):
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        from .. import telemetry

        # dispatch counter (counts trace entries once when called under
        # jit — kernel dispatch counts, not device executions)
        telemetry.count("kernel.dia_spmv_packed")
        y = dia_spmv_packed(
            self.planes, dia_pad_x(x, self.plan), self.plan, interpret=interpret
        )
        return y[: self.plan.m]


#: failover-registry kernel name (resilience/failover.py)
DIA_KERNEL = "dia_spmv"


def _vault_codecs():
    from ..vault import _codecs

    return _codecs


def cached_prepared_spmv(obj, attr: str, data, offsets, shape, x):
    """Shared band-gated PreparedDia dispatch for the format classes.

    Returns ``None`` when the band exceeds ``settings.pallas_max_band``
    (caller falls back to the XLA formulation); otherwise obtains a
    :class:`PreparedDia` for ``obj`` from the library-wide
    ``sparse_tpu.plan_cache`` (weak-ref keyed under ``attr``) and applies
    it. Fresh objects from ``_with_data``/constructors are new cache keys,
    so mutation invalidates the plan for free.

    Failure handling lives in the shared failover registry
    (``sparse_tpu.resilience.failover``): this site classifies with the
    strict lowering-unavailability vocabulary (``vocab=True`` — on a
    real TPU only the historical interpret-mode message is benign, a
    genuine Mosaic compile regression stays LOUD; off-TPU any
    lowering-availability wording qualifies), honors
    ``SPARSE_TPU_STRICT_PALLAS``, emits the consistent
    ``kernel.failover`` event, and latches per matrix object — a latch
    :func:`~sparse_tpu.resilience.failover.probe` can clear again when
    the backend heals.
    """
    from .. import plan_cache
    from ..config import settings
    from ..resilience import failover

    band = max((abs(int(o)) for o in offsets), default=0)
    if band > settings.pallas_max_band:
        return None
    if failover.failed(DIA_KERNEL, obj):
        return None
    prepared = plan_cache.get(
        obj, attr, lambda: PreparedDia(data, offsets, shape),
        # persistent tier (sparse_tpu.vault): the packed plane buffer +
        # autotuned tile persist across processes, content-keyed on the
        # exact planes/offsets/shape (dtype rides the array hash)
        vault_kind="prepared_dia",
        vault_key=lambda: _vault_codecs().prepared_dia_key(
            data, offsets, shape
        ),
    )
    try:
        # forced-failure injection point, then the real kernel attempt
        failover.maybe_inject(DIA_KERNEL)
        return prepared(x)
    except (ValueError, NotImplementedError) as e:
        failover.handle(DIA_KERNEL, obj, e, vocab=True)
        return None


def dia_spmv_pallas(data, offsets, x, shape, tile=16384, interpret=None):
    """See ``_dia_spmv_pallas``; ``interpret=None`` auto-selects interpret
    mode off-TPU (Pallas TPU kernels only compile natively on tpu)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _dia_spmv_pallas(
        data, tuple(offsets), x, tuple(shape), tile=tile, interpret=interpret
    )


@partial(jax.jit, static_argnames=("offsets", "shape", "tile", "interpret"))
def _dia_spmv_pallas(
    data, offsets: tuple, x, shape: tuple, tile: int = 16384, interpret: bool = False
):
    """y = A @ x, A in DIA layout (scipy convention), banded offsets.

    ``tile`` rows per grid step (multiple of 128). The per-tile x/data window
    is [tile + 2B] where B is the bandwidth; windows of neighboring tiles
    overlap by 2B — the halo. Both are DMA'd from HBM per step and multiplied
    in VMEM (contribution of diagonal o to row i is data[k, i+o] * x[i+o]).
    """
    m, n = shape
    D = len(offsets)
    # Mosaic DMA alignment: 1-D HBM memrefs carry a (1024,) tiling, so the
    # row tile TM rounds to 1024 and the halo B to 512 — then the window
    # win = TM + 2B, every window start g*TM, and each plane's base k*L in
    # the flattened plane array are all multiples of 1024. (Geometry shared
    # with the prepared path via dia_plan — single source.)
    _p = dia_plan(offsets, shape, tile=tile)
    B, TM, G = _p.B, _p.TM, _p.G
    m_pad = G * TM
    win = TM + 2 * B
    L = m_pad + 2 * B  # padded plane length (multiple of 1024)

    # Halo-pad data planes and x into a shared padded coordinate system
    # (index j' = j + B); a copy of the inputs, NOT a product intermediate.
    # The plane count pads to a sublane multiple of 8 (zero planes) so each
    # window is one aligned [Dp, win] DMA.
    Dp = _round_up(D, 8)
    pad_hi = max(m_pad - n, 0) + B
    data_p = jnp.pad(data, ((0, Dp - D), (B, pad_hi)))[:, :L]
    x_p = jnp.pad(x, (B, pad_hi))[:L]
    out_dt = jnp.result_type(data.dtype, x.dtype)

    def kernel(data_hbm, x_hbm, y_ref, dwinA, dwinB, xwinA, xwinB, semA, semB):
        # Cross-step double buffering: step g waits on the DMAs it (or the
        # warm-up) issued into its slot's buffers and prefetches step g+1
        # into the other slot's, overlapping HBM reads with VPU compute —
        # scratch and semaphores persist across the sequential TPU grid.
        # The two slots are unrolled statically (Mosaic cannot scalar-index
        # the tiled dims of a VMEM ref, so buffer choice must be static).
        g = pl.program_id(0)
        G_ = pl.num_programs(0)

        def issue(dwin, xwin, sem, gg):
            pltpu.make_async_copy(
                data_hbm.at[:, pl.ds(gg * TM, win)], dwin, sem.at[0]
            ).start()
            pltpu.make_async_copy(
                x_hbm.at[pl.ds(gg * TM, win)], xwin, sem.at[1]
            ).start()

        def wait(dwin, xwin, sem, gg):
            pltpu.make_async_copy(
                data_hbm.at[:, pl.ds(gg * TM, win)], dwin, sem.at[0]
            ).wait()
            pltpu.make_async_copy(
                x_hbm.at[pl.ds(gg * TM, win)], xwin, sem.at[1]
            ).wait()

        def step(dwin, xwin, sem, dwin_n, xwin_n, sem_n):
            @pl.when(g == 0)
            def _():
                issue(dwin, xwin, sem, g)

            @pl.when(g + 1 < G_)
            def _():
                issue(dwin_n, xwin_n, sem_n, g + 1)

            wait(dwin, xwin, sem, g)
            acc = jnp.zeros((TM,), dtype=y_ref.dtype)
            for k, o in enumerate(offsets):
                lo = B + int(o)
                acc = acc + dwin[k, lo : lo + TM] * xwin[lo : lo + TM]
            y_ref[:] = acc

        @pl.when(g % 2 == 0)
        def _():
            step(dwinA, xwinA, semA, dwinB, xwinB, semB)

        @pl.when(g % 2 == 1)
        def _():
            step(dwinB, xwinB, semB, dwinA, xwinA, semA)

    y = pl.pallas_call(
        kernel,
        grid=(G,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((TM,), lambda g: (g,), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m_pad,), out_dt),
        scratch_shapes=[
            pltpu.VMEM((Dp, win), data.dtype),
            pltpu.VMEM((Dp, win), data.dtype),
            pltpu.VMEM((win,), x.dtype),
            pltpu.VMEM((win,), x.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(data_p, x_p)
    return y[:m]
